"""Benchmark: PHOLD events/sec on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no performance numbers (BASELINE.md); the
recorded value is raw engine throughput (events/sec/chip) on the PHOLD
DES stress workload, and vs_baseline reports the simulated-seconds per
wallclock-second ratio (the north-star metric per BASELINE.json).
"""

import json
import sys
import time


def main():
    num_hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    stop_s = int(sys.argv[2]) if len(sys.argv) > 2 else 10

    import jax
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.engine.state import EngineConfig

    topo = """
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">102400</data><data key="d4">102400</data></node>
    <edge source="poi" target="poi"><data key="d7">25.0</data>
      <data key="d9">0.0</data></edge>
  </graph>
</graphml>
"""
    scen = Scenario(
        stop_time=stop_s * 10**9,
        topology_graphml=topo,
        hosts=[HostSpec(id="node", quantity=num_hosts, processes=[
            ProcessSpec(plugin="phold", start_time=10**9,
                        arguments="port=9000 mean=500ms size=64 init=1")])],
    )

    cfg = EngineConfig(num_hosts=num_hosts, qcap=16, scap=4, obcap=8,
                       incap=16, chunk_windows=512)

    # Warm-up run at identical array shapes but a tiny stop time:
    # stop_time is a dynamic scalar, so this compiles the full window
    # program without recompiling for the measured run below.
    import copy
    warm_scen = copy.deepcopy(scen)
    warm_scen.stop_time = int(1.2 * 10**9)
    Simulation(warm_scen, engine_cfg=cfg).run()

    report = Simulation(scen, engine_cfg=cfg).run()
    s = report.summary()

    print(json.dumps({
        "metric": f"phold-{num_hosts} events/sec/chip",
        "value": round(s["events_per_sec"], 1),
        "unit": "events/s",
        "vs_baseline": round(s["speedup"], 3),
    }))


if __name__ == "__main__":
    main()
