"""Benchmark matrix: events/sec on one chip vs a measured baseline.

Prints one JSON line PER config:
  {"metric": ..., "value": N, "unit": "events/s", "vs_baseline": N,
   "realtime_x": N, "baseline": {...}}

- `value`: compiled-engine event throughput on this platform.
- `realtime_x`: simulated-seconds per wallclock-second.
- `vs_baseline`: value / the measured baseline events/sec. The
  reference publishes no numbers and cannot be built here (no
  GLib/igraph in the image — BASELINE.md), so the denominator is the
  pure-Python reference engine (engine.pyengine, the differential
  oracle) timed on the same workload at a scale it can complete; its
  config and throughput are recorded in `baseline` so the ratio is
  auditable. Per-event cost in a heap-loop DES is roughly
  scale-independent, which is what makes the small-scale denominator
  meaningful.

Configs (one line each, MOST IMPORTANT FIRST: round 2's run timed out
before the last config printed, so the flagship TCP lines now emit
before anything else and every line flushes the moment its config
finishes):
  tgen-1k-tcp     BASELINE #2 shape: 1k-host tgen web+bulk over TCP
  socks10k        BASELINE #3 shape: 10k-host SOCKS chains (the
                  flagship TCP tier — captured every round instead of
                  only in ad-hoc baseline_configs runs, VERDICT r5)
  phold-4096      UDP DES stress (scheduler/queue hot loop)
  gossip-100k     BASELINE #5 shape: 100k-host block gossip

Every emitted line also appends one perf-ledger entry
(shadow_tpu/obs/ledger.py, default perf/ledger.jsonl;
SHADOW_TPU_LEDGER=off disables) so the round-over-round trajectory is
machine-checkable by tools/perf_regress.py instead of living only in
BENCH_r{N}.json artifacts nobody diffs.

A persistent XLA compile cache (.jax_cache/, gitignored) makes repeat
runs skip the three cold compiles that dominated round 2's ~35 min
matrix. Independently, SHADOW_TPU_AOT_CACHE=DIR enables the serving
layer's executable cache (shadow_tpu/serving/aotcache.py) — and
either way every line now says `compile_cache: hit|miss` plus the
per-line `jitcache` counter deltas, so a "cold_wall" label is
mechanically honest about whether cold included a real XLA build or
opened warm from a cache (docs/serving.md).

Legacy single-config mode (used by smoke tests):
  python bench.py 512 5     -> phold-512, 5 sim-seconds, one line
"""

import copy
import json
import os
import sys
import time


def _enable_compile_cache():
    """Persistent XLA compile cache next to this file — accelerator
    backends only: this build's XLA:CPU AOT loader mismatches its own
    cache entries (see tests/conftest.py), so CPU runs stay
    uncached."""
    import jax

    try:
        if jax.default_backend() == "cpu":
            return
        cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass  # older jax without the knobs: run uncached


def _phold_scenario(num_hosts, stop_s):
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario

    topo = """
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">102400</data><data key="d4">102400</data></node>
    <edge source="poi" target="poi"><data key="d7">25.0</data>
      <data key="d9">0.0</data></edge>
  </graph>
</graphml>
"""
    return Scenario(
        stop_time=stop_s * 10**9,
        topology_graphml=topo,
        hosts=[HostSpec(id="node", quantity=num_hosts, processes=[
            ProcessSpec(plugin="phold", start_time=10**9,
                        arguments="port=9000 mean=500ms size=64 init=1")])],
    )


def _phold_cfg(num_hosts):
    from shadow_tpu.engine.state import EngineConfig
    return EngineConfig(num_hosts=num_hosts, qcap=16, scap=4, obcap=8,
                        incap=16, chunk_windows=512)


def _netscope_cfg(cfg):
    """SHADOW_TPU_NETSCOPE=1 runs every compiled config with the
    network observatory histograms on (obs.netscope) — the bench line
    and its ledger entry then carry rtt_p50_us/rtt_p99_us/
    completion_p99_s, so the trajectory gates tail behavior next to
    the rate. Applied to the ledger fingerprint too: the knob changes
    the compiled shape, so it starts its own trajectory."""
    if os.environ.get("SHADOW_TPU_NETSCOPE", "") not in ("", "0"):
        import dataclasses
        return dataclasses.replace(cfg, netscope=True)
    return cfg


def _run_compiled(scen, cfg, warm_stop_ns=int(1.2 * 10**9), reps=1,
                  runahead_ms=0):
    """Warm-up at identical shapes (tiny stop; stop_time is a dynamic
    scalar so no recompile for the measured run), then measure `reps`
    times. Returns the MEDIAN-throughput rep's summary, annotated with
    the per-rep spread (round-3 verdict: headline ratios should not
    rest on single unrepeated runs; reps are cheap once compiled).
    runahead_ms > 0 overrides the lookahead window — the reference's
    --runahead knob (tools.baseline_configs.apply_runahead, the one
    shared definition)."""
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.serving import aotcache as _AC
    from tools.baseline_configs import apply_runahead

    cfg = _netscope_cfg(cfg)

    def build(s):
        return apply_runahead(Simulation(s, engine_cfg=cfg),
                              runahead_ms)

    # jitcache tallies over this line's warmup+reps: did "cold"
    # include a real XLA build (compile_cache=miss), or did the line
    # open warm from the in-memory/disk executable tier (hit)?
    jc0 = dict(_AC.STATS)
    warm = copy.deepcopy(scen)
    warm.stop_time = warm_stop_ns
    build(warm).run()
    outs = []
    for _ in range(max(reps, 1)):
        report = build(scen).run()
        s = report.summary()
        s["cost"] = report.cost_model()
        # cold/warm split (VERDICT weak #5: single warm-median numbers
        # make cross-round deltas uninterpretable): cold_wall is the
        # compile + first chunk, warm_wall the rest of the run (None
        # on single-chunk runs, where the split does not exist)
        warm = report.cost.get("warm_wall")
        s["warm_wall"] = round(warm, 3) if warm else None
        s["cold_wall"] = round(report.wall_seconds - (warm or 0), 3)
        outs.append(s)
    outs.sort(key=lambda s: s["events_per_sec"])
    med = outs[len(outs) // 2]
    if len(outs) > 1:
        rates = [round(s["events_per_sec"], 1) for s in outs]
        med["rep_rates"] = rates
        med["rep_spread"] = round(rates[-1] - rates[0], 1)
    delta = {k: round(_AC.STATS[k] - jc0[k], 3)
             for k in jc0 if _AC.STATS[k] != jc0[k]}
    med["compile_cache"] = "miss" if delta.get("compiles") else "hit"
    med["jitcache"] = delta
    return med


def _run_pyengine(scen, cfg, runahead_ms=0):
    """The measured baseline: the pure-Python engine on the same
    workload shape, timed end to end (same runahead as the compiled
    run so the ratio compares identical protocols).

    Pinned to the CPU backend: the heap engine's per-event eager jnp
    calls (RNG/float mirrors) would otherwise each round-trip to the
    accelerator when bench runs on a real chip, understating the
    baseline by ~500x."""
    import contextlib
    import jax
    from shadow_tpu.engine.pyengine import PyEngine
    from shadow_tpu.engine.sim import Simulation

    try:
        ctx = jax.default_device(jax.devices("cpu")[0])
    except Exception:
        ctx = contextlib.nullcontext()
    with ctx:
        from tools.baseline_configs import apply_runahead
        sim = apply_runahead(Simulation(scen, engine_cfg=cfg),
                             runahead_ms)
        eng = PyEngine(sim)
        t0 = time.perf_counter()
        stats = eng.run()
        wall = time.perf_counter() - t0
    from shadow_tpu.engine import defs
    events = int(stats[:, defs.ST_EVENTS].sum())
    return {"events": events, "wall_seconds": round(wall, 2),
            "events_per_sec": round(events / max(wall, 1e-9), 1)}


def _run_minides(n, stop_s, mean_ms=500.0, lat_ms=25.0):
    """Compiled-C denominator: tools/minides.c, a dependency-free
    binary-heap DES on the same PHOLD shape (the reference C engine is
    unbuildable here — BASELINE.md). It does LESS per-event work than
    any full engine (no NIC/socket/window machinery), so its
    events/sec UPPER-bounds compiled-C DES throughput and the
    resulting vs ratio is conservative. Returns None if cc fails."""
    import subprocess
    import tempfile

    src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "tools", "minides.c")
    exe = os.path.join(tempfile.mkdtemp(prefix="minides."), "minides")
    try:
        subprocess.run(["cc", "-O2", "-o", exe, src, "-lm"], check=True,
                       capture_output=True)
        out = subprocess.run(
            [exe, str(n), str(stop_s), str(mean_ms), str(lat_ms)],
            check=True, capture_output=True, text=True).stdout
        kv = dict(p.split("=") for p in out.split())
        return {"engine": "minides (compiled-C heap DES, phold shape; "
                          "upper-bounds compiled DES throughput — "
                          "tools/minides.c)",
                "config": f"phold-{n}, {stop_s} sim-s",
                "events": int(kv["events"]),
                "wall_seconds": float(kv["wall_s"]),
                "events_per_sec": float(kv["events_per_sec"])}
    except Exception:
        return None


def _emit(metric, summary, baseline, baseline_cfg, baseline_c=None,
          ledger_cfg=None, ledger_extra=None):
    import jax

    vs = (summary["events_per_sec"] / baseline["events_per_sec"]
          if baseline and baseline["events_per_sec"] else None)
    cost = summary.get("cost") or {}
    line = {
        "metric": metric,
        "value": round(summary["events_per_sec"], 1),
        "unit": "events/s",
        # the platform stamp keeps CPU-container numbers from ever
        # being compared against accelerator rounds
        "platform": jax.default_backend(),
        "vs_baseline": round(vs, 2) if vs else None,
        "realtime_x": round(summary["speedup"], 3),
        "events": summary["events"],
        "cold_wall": summary.get("cold_wall"),
        "warm_wall": summary.get("warm_wall"),
        # what "cold" actually included, mechanically: miss = this
        # line's warmup+reps paid >=1 real XLA compile; hit = every
        # executable came from the jitcache memory/disk tier
        # (serving.aotcache; jitcache holds the counter deltas)
        "compile_cache": summary.get("compile_cache"),
        "jitcache": summary.get("jitcache"),
        # cost-model digest (SimReport.cost_model): where the wall
        # goes, auditable per line
        "passes_per_window": round(cost.get("passes_per_window", 0), 2),
        "roofline_frac": round(cost.get("roofline_frac", 0), 4),
        # memory observatory (obs.memscope): the rep's device-buffer
        # watermark (allocator peak on device backends, process RSS on
        # CPU — mem_source says which) and the per-host state census,
        # so the matrix carries a byte trajectory next to the rate one
        "mem_peak_bytes": summary.get("mem_peak_bytes"),
        "mem_source": summary.get("mem_source"),
        "state_bytes_per_host": summary.get("state_bytes_per_host"),
        "baseline": ({"engine": "pyengine (pure-Python reference "
                      "engine; C reference unbuildable here — see "
                      "BASELINE.md)",
                      "config": baseline_cfg, **baseline}
                     if baseline else None),
    }
    if "rep_rates" in summary:
        line["rep_rates"] = summary["rep_rates"]
        line["rep_spread"] = summary["rep_spread"]
    if "rtt_p50_us" in summary:
        # network observatory tails (obs.netscope, SHADOW_TPU_NETSCOPE
        # runs): exact percentile read-outs beside the rate
        line["rtt_p50_us"] = summary["rtt_p50_us"]
        line["rtt_p99_us"] = summary["rtt_p99_us"]
        line["completion_p99_s"] = summary.get("completion_p99_s")
    if "waste_frac" in summary:
        # lockstep occupancy (obs.passcope): the wasted-lane fraction
        # beside the rate — and, on --passcope runs, which pass the
        # device time concentrated in
        line["waste_frac"] = summary["waste_frac"]
        if "top_pass" in summary:
            line["top_pass"] = summary["top_pass"]
            line["top_pass_frac"] = summary["top_pass_frac"]
    if baseline_c:
        line["baseline_c"] = baseline_c
        if baseline_c.get("events_per_sec"):
            line["vs_compiled_c"] = round(
                summary["events_per_sec"] / baseline_c["events_per_sec"],
                4)
    print(json.dumps(line), flush=True)
    if ledger_cfg is not None:
        # durable trajectory: one perf-ledger line per bench line,
        # keyed scenario x config-fingerprint x platform so
        # tools/perf_regress.py can gate the next round against this
        # one (SHADOW_TPU_LEDGER=off disables)
        try:
            from shadow_tpu.obs import ledger as LG
            ledger_cfg = _netscope_cfg(ledger_cfg)
            entry = LG.make_entry(
                scenario=metric.split(" ")[0],
                fingerprint=LG.fingerprint_of(ledger_cfg,
                                              **(ledger_extra or {})),
                platform=line["platform"], summary=summary,
                cost=cost,
                rep_rates=summary.get("rep_rates"),
                rep_spread=summary.get("rep_spread"),
                cold_wall=summary.get("cold_wall"),
                warm_wall=summary.get("warm_wall"),
                cfg=ledger_cfg,
                note=(f"compile_cache={summary['compile_cache']}"
                      if summary.get("compile_cache") else None))
            LG.append(entry)
        except Exception as e:  # pragma: no cover — never fail a line
            print(json.dumps({"ledger_error": repr(e)}), flush=True)


def bench_phold():
    base = _run_pyengine(_phold_scenario(512, 4), _phold_cfg(512))
    base_c = _run_minides(4096, 10)
    s = _run_compiled(_phold_scenario(4096, 10), _phold_cfg(4096),
                      reps=3)
    _emit("phold-4096 events/sec/chip", s, base, "phold-512, 4 sim-s",
          baseline_c=base_c, ledger_cfg=_phold_cfg(4096),
          ledger_extra={"stop": 10})


def bench_gossip():
    from shadow_tpu.core.config import load_xml
    from shadow_tpu.engine.state import EngineConfig

    # lean caps per the example's own recipe (gossip traffic is sparse
    # per host; auto-sizing from bandwidth balloons at 100k hosts)
    def caps(n):
        return EngineConfig(num_hosts=n, qcap=16, scap=2, obcap=16,
                            incap=32, chunk_windows=256)

    scen = load_xml("examples/gossip-100k.xml")

    base_scen = load_xml("examples/gossip-100k.xml")
    base_scen.hosts[1].quantity = 999     # miner + 999 nodes
    # gossip peer draws target ids [0, n); shrink n with the host count
    for h in base_scen.hosts:
        for p in h.processes:
            p.arguments += " n=1000"
    base = _run_pyengine(base_scen, caps(1000))
    s = _run_compiled(scen, caps(100_000), reps=3)
    _emit("gossip-100k events/sec/chip", s, base,
          "gossip-1000, 30 sim-s", ledger_cfg=caps(100_000))


def bench_tgen_tcp():
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.baseline_configs import build_bulk_1k, socks_caps

    # 10 sim-s (round 4; was 30): the realtime ratio is duration-
    # independent, and the driver's wall budget has to cover ALL three
    # matrix lines — two rc=124 rounds proved a 30 sim-s TCP config
    # does not fit it cold (round-3 verdict item 3).
    # runahead 10ms (round 4): the reference's --runahead knob, the
    # same protocol as the at-scale socks/tor measurements (its
    # no-topology default window is this same 10ms, shd-master.c:123);
    # plab's 1ms minimum edge otherwise forces 10x the windows and the
    # per-window fixed costs dominate the line
    base = _run_pyengine(build_bulk_1k(20, stop=10),
                         socks_caps(20, scap=32), runahead_ms=10)
    s = _run_compiled(build_bulk_1k(1000, stop=10),
                      socks_caps(1000, scap=32),
                      warm_stop_ns=int(2.2 * 10**9), runahead_ms=10)
    _emit("tgen-1k-tcp events/sec/chip", s, base,
          "tgen-20, 10 sim-s (both runahead 10ms)",
          ledger_cfg=socks_caps(1000, scap=32),
          ledger_extra={"stop": 10, "runahead": 10})


def bench_socks():
    """The flagship TCP tier (BASELINE #3, socks10k) in the every-round
    matrix: VERDICT r5 weak #2/#4 — the tier the perf items gate on
    went unmeasured whenever nobody hand-ran baseline_configs. Same
    protocol as the at-scale chip rounds (runahead 10ms, PlanetLab
    topology); 10 sim-s (the realtime ratio is duration-independent
    and the matrix wall budget must cover four lines). No pyengine
    denominator: at this shape the heap engine alone would dominate
    the matrix wall, and the socks trajectory is tracked by the
    ledger, not by a vs-python ratio."""
    from tools.baseline_configs import build_socks, socks_caps

    s = _run_compiled(build_socks(10_000, hops=1, stop=10, count=0,
                                  pause="5s"),
                      socks_caps(10_000, scap=96),
                      warm_stop_ns=int(2.4 * 10**9), reps=3,
                      runahead_ms=10)
    _emit("socks10k events/sec/chip", s, None, None,
          ledger_cfg=socks_caps(10_000, scap=96),
          ledger_extra={"stop": 10, "runahead": 10})


def main():
    _enable_compile_cache()
    # optional observability (shadow_tpu/obs/README.md): installed
    # process-wide so ALL configs/reps share one timeline/registry —
    # Simulation.run() sees the recorders already enabled and leaves
    # their lifecycle to us. The registry's `sim.*` section then holds
    # the LAST run's summary (the same dict each _emit line reads).
    from shadow_tpu.obs import metrics as _MT
    from shadow_tpu.obs import trace as _TR
    trace_path = os.environ.get("SHADOW_TPU_TRACE")
    metrics_path = os.environ.get("SHADOW_TPU_METRICS")
    if trace_path:
        _TR.install(trace_path)
    if metrics_path:
        _MT.install(metrics_path,
                    jsonl_path=metrics_path + ".chunks.jsonl")
    import atexit
    if trace_path:
        atexit.register(_TR.finish)
    if metrics_path:
        atexit.register(_MT.finish)
    if len(sys.argv) > 1 and sys.argv[1].isdigit():
        # legacy single-config mode: phold-N [stop_s]
        n = int(sys.argv[1])
        stop_s = int(sys.argv[2]) if len(sys.argv) > 2 else 10
        if metrics_path:
            _MT.REGISTRY.label = f"phold-{n}"
        base = _run_pyengine(_phold_scenario(min(n, 512), 4),
                             _phold_cfg(min(n, 512)))
        s = _run_compiled(_phold_scenario(n, stop_s), _phold_cfg(n))
        _emit(f"phold-{n} events/sec/chip", s, base,
              f"phold-{min(n, 512)}, 4 sim-s")
        return

    # full matrix, most important first (a timeout then costs the least
    # important line, not the flagship): the TCP tiers (tgen, then the
    # flagship socks10k), then the 100k UDP config (the line nearest
    # the north star — it never printed in rounds 2-3), then phold. Configs are isolated so one failure
    # doesn't hide the rest, and the trailing "complete" line makes a
    # driver timeout self-evident in the artifact.
    t0 = time.perf_counter()
    for fn in (bench_tgen_tcp, bench_socks, bench_gossip, bench_phold):
        try:
            if metrics_path:
                # label the registry's chunk lines so N configs x R
                # reps interleaved in one chunks.jsonl stay
                # partitionable by run
                _MT.REGISTRY.label = fn.__name__
            fn()
        except Exception as e:  # pragma: no cover
            print(json.dumps({"metric": fn.__name__, "error": repr(e)}),
                  flush=True)
    from shadow_tpu.serving import aotcache as _AC
    print(json.dumps({"matrix": "complete",
                      "wall_seconds": round(time.perf_counter() - t0, 1),
                      "jitcache": {k: round(v, 3)
                                   for k, v in _AC.STATS.items()}}),
          flush=True)


if __name__ == "__main__":
    main()
