"""Persistent AOT executable cache: the disk tier under AotJit.

``core.jitcache.AotJit`` already memoizes one Compiled object per
argument signature — but only in process memory, so every fresh
process pays the full XLA build (312 s on a cold tor50k CPU run,
10-15 min per config shape on chip — BASELINE.md). This module adds
the disk tier: executables serialized via
``jax.experimental.serialize_executable`` and reloaded by any later
process that asks for the same program.

Cache key anatomy (docs/serving.md) — an entry may load ONLY when all
of these match, so a stale executable is structurally unreachable:

- the AotJit's ``cache_scope``: a stable program identity carrying
  the config fingerprint (``obs.ledger.fingerprint_of(cfg)``) and the
  chunk size — e.g. ``run_windows.c64.<fp16>``;
- the argument signature (``AotJit._sig``: pytree structure, leaf
  shapes/dtypes/weak-types, shardings);
- jax/jaxlib versions and the backend's own platform_version (XLA);
- the platform: backend name, device kind, device count;
- a source digest over every traced module
  (``shadow_tpu/{core,engine,net,apps,parallel,hosting}``): editing
  device code invalidates every entry mechanically, no version bump
  to forget.

Donation policy: cached programs compile, store and execute their
DONATION-FREE twin (``AotJit.undonated_jit``). A serialize round trip
of a donated executable is unsound on the XLA:CPU client — the loaded
executable's outputs alias the donated input buffers, whose memory
the runtime frees, a use-after-free that silently corrupts results
once the allocator reuses the block. Undonated execution computes
identical values (digest chains stay byte-identical, proven in
tests/test_serving.py) at a transient 2x peak for the donated
operands during each call; runs without an active cache keep
donation untouched.

Storage is crash-safe in the PR 5 checkpoint-store shape: sidecars
(``.sha256`` content hash, ``.meta.json`` key anatomy) publish before
the payload's atomic tmp+fsync+os.replace, loads verify the hash and
fall back LOUDLY to recompile on any torn/corrupt/alien entry, and
retention bounds the directory. Serialization support is probed once
per process (``serialize_support``); backends without it degrade to
the in-memory tier with a warning, never an error.

Observability: every disk hit / miss / store / reject counts in
:data:`STATS` (always) and ``jitcache.*`` metrics (when obs.metrics
is enabled), and the compile/load walls record as ``jitcache.compile``
/ ``jitcache.load`` spans — which obs.perf attributes to the
``compile-miss`` / ``compile-hit`` phases, so a phase map says
mechanically whether "cold" included a real XLA build.

Enable with ``--aot-cache DIR`` (CLI), ``fleet run --aot-cache DIR``,
or ``SHADOW_TPU_AOT_CACHE=DIR``.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import sys
import time

FORMAT = "shadow_tpu.serving.aotcache"
VERSION = 1

# entries retained per cache dir (oldest-mtime pruned past this);
# SHADOW_TPU_AOT_CACHE_KEEP overrides
DEFAULT_KEEP = 64

# process-wide tallies, kept unconditionally (bench.py labels each
# line compile_cache=hit|miss from the `compiles` delta; the metrics
# registry mirrors them when enabled)
STATS = {"compiles": 0, "disk_hits": 0, "disk_misses": 0,
         "disk_stores": 0, "rejected": 0,
         "compile_wall_s": 0.0, "load_wall_s": 0.0}

ACTIVE = None
_ENV_CHECKED = False

_REPO_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the packages whose source the compiled programs trace — the window/
# exchange/app programs (core/engine/net/apps/parallel) AND the hosted
# op-replay program (hosting.bridge apply_ops, cache_scope
# "apply_ops"); editing any of them invalidates every cache entry
SOURCE_SCOPE = ("core", "engine", "net", "apps", "parallel", "hosting")


def _warn(msg: str):
    sys.stderr.write(f"shadow_tpu: aot-cache: {msg}\n")


def install(root: str, keep: int = None) -> "DiskCache":
    """Enable the disk tier process-wide (the obs.install contract:
    the installer owns the lifecycle; AotJit just consults active())."""
    global ACTIVE
    ACTIVE = DiskCache(root, keep=keep)
    return ACTIVE


def uninstall():
    global ACTIVE, _ENV_CHECKED
    ACTIVE = None
    _ENV_CHECKED = True      # tests: do not fall back to the env var


def active() -> "DiskCache | None":
    """The installed cache, resolving SHADOW_TPU_AOT_CACHE once per
    process so fleet children enable the tier without CLI plumbing."""
    global ACTIVE, _ENV_CHECKED
    if ACTIVE is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        env = os.environ.get("SHADOW_TPU_AOT_CACHE")
        if env:
            install(env)
    return ACTIVE


# --- capability probe ------------------------------------------------------

_SERIALIZE_OK = None


def serialize_support() -> bool:
    """Once per process: can this backend serialize AND reload a
    compiled executable? Probed on a trivial program; a backend
    without support (or a jax without the API) degrades the cache to
    in-memory-only with a loud warning — never an error."""
    global _SERIALIZE_OK
    if _SERIALIZE_OK is None:
        try:
            import jax
            import jax.numpy as jnp
            from jax.experimental import serialize_executable as se
            c = jax.jit(lambda x: x + 1).lower(jnp.int32(0)).compile()
            payload, in_tree, out_tree = se.serialize(c)
            se.deserialize_and_load(payload, in_tree, out_tree)
            _SERIALIZE_OK = True
        except Exception as e:
            _SERIALIZE_OK = False
            _warn("this backend cannot serialize executables "
                  f"({type(e).__name__}: {e}); the AOT cache is "
                  "in-memory only for this process — fresh processes "
                  "will recompile")
    return _SERIALIZE_OK


# --- key components --------------------------------------------------------

_SOURCE_DIGEST = None


def source_digest() -> str:
    """sha256 over every .py under the traced packages (sorted
    relpaths, name + content), computed once per process. Any device-
    code edit changes it, so no stale executable can survive a source
    change."""
    global _SOURCE_DIGEST
    if _SOURCE_DIGEST is None:
        h = hashlib.sha256()
        files = []
        for pkg in SOURCE_SCOPE:
            root = os.path.join(_REPO_PKG, pkg)
            for dirpath, _, names in os.walk(root):
                for n in names:
                    if n.endswith(".py"):
                        p = os.path.join(dirpath, n)
                        files.append((os.path.relpath(p, _REPO_PKG), p))
        for rel, p in sorted(files):
            h.update(rel.encode())
            with open(p, "rb") as f:
                h.update(f.read())
        _SOURCE_DIGEST = h.hexdigest()[:16]
    return _SOURCE_DIGEST


def platform_key() -> dict:
    """The environment components of the key: an executable compiled
    by a different jax/jaxlib/XLA, backend, device kind or device
    count must MISS (stale-rejection is structural — the key differs,
    so the entry is unreachable, never loaded-and-wrong)."""
    import jax
    import jaxlib
    dev = jax.devices()[0]
    try:
        xla = dev.client.platform_version
    except Exception:
        xla = "?"
    return {"backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", "?"),
            "n_devices": jax.device_count(),
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "xla": xla}


def _sig_text(sig) -> str:
    """Stable textual form of an AotJit._sig value (the in-memory key
    may hold live sharding objects whose repr is process-stable but
    whose hash is not portable; the disk key needs text)."""
    treedef, leaves = sig
    return json.dumps([str(treedef),
                       [[list(shape), dtype, bool(weak), str(sh)]
                        for shape, dtype, weak, sh in leaves]])


def entry_key(scope: str, sig) -> str:
    """One disk-entry key from all five components; the .meta.json
    sidecar records the anatomy for post-mortems. ``donated: False``
    records that stored executables are always the donation-free
    twin (load_or_compile) — a future donated artifact would be a
    different key, never a silent swap."""
    blob = json.dumps({"format": FORMAT, "version": VERSION,
                       "scope": scope, "sig": _sig_text(sig),
                       "platform": platform_key(),
                       "source": source_digest(),
                       "donated": False}, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def key_meta(scope: str, sig) -> dict:
    return {"format": FORMAT, "version": VERSION, "scope": scope,
            "sig": _sig_text(sig), "platform": platform_key(),
            "source": source_digest(), "donated": False}


# --- the disk tier ---------------------------------------------------------

def _write_atomic(path: str, data: bytes):
    """tmp + fsync + os.replace (the checkpoint-store write shape): a
    crash mid-write can never publish a torn file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class DiskCache:
    """One cache directory of serialized executables."""

    def __init__(self, root: str, keep: int = None):
        self.root = root
        if keep is None:
            keep = int(os.environ.get("SHADOW_TPU_AOT_CACHE_KEEP",
                                      str(DEFAULT_KEEP)))
        self.keep = max(int(keep), 1)

    def exec_path(self, key: str) -> str:
        return os.path.join(self.root, key + ".exec")

    def meta_path(self, key: str) -> str:
        return os.path.join(self.root, key + ".meta.json")

    def has(self, key: str) -> bool:
        return os.path.exists(self.exec_path(key))

    def entries(self) -> list:
        """Cached keys, oldest mtime first."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        paths = [os.path.join(self.root, n) for n in names
                 if n.endswith(".exec")]

        def mtime(p):
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0
        return [os.path.basename(p)[:-len(".exec")]
                for p in sorted(paths, key=lambda p: (mtime(p), p))]

    def load(self, key: str):
        """-> a loaded Compiled, or None (miss). EVERY failure mode —
        missing entry, missing/mismatched hash sidecar, unpicklable
        payload, a backend that refuses the executable — is a miss
        that falls back to recompile; corrupt entries warn and are
        removed so they cannot re-fail every run."""
        if not serialize_support():
            return None
        p = self.exec_path(key)
        try:
            with open(p, "rb") as f:
                blob = f.read()
        except OSError:
            STATS["disk_misses"] += 1
            return None
        try:
            with open(p + ".sha256") as f:
                want = f.read().strip()
        except OSError:
            want = None
        if want is None or hashlib.sha256(blob).hexdigest() != want:
            self._reject(key, "content hash missing or mismatched "
                         "(torn write / bit rot)")
            return None
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = pickle.loads(blob)
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            self._reject(key, f"deserialize failed "
                         f"({type(e).__name__}: {e})")
            return None

    def _reject(self, key: str, why: str):
        STATS["rejected"] += 1
        _warn(f"entry {key}: {why} — falling back to recompile and "
              "dropping the entry")
        self.remove(key)

    def remove(self, key: str):
        for p in (self.exec_path(key), self.exec_path(key) + ".sha256",
                  self.meta_path(key)):
            try:
                os.unlink(p)
            except OSError:
                pass

    # a publisher holding the per-key lock longer than this is
    # presumed dead (SIGKILL mid-store) and its lock is broken
    LOCK_STALE_S = 600.0

    def _publish_lock(self, key: str):
        """O_EXCL per-key writer lock -> fd, or None (another LIVE
        writer is publishing this key — first writer wins; the loser
        keeps its in-memory executable). Concurrent same-key stores
        are real under `fleet run --aot-cache` WITHOUT --prewarm
        (every child finishes the same compile at ~the same time),
        and unserialized sidecar/payload interleavings would look
        like corruption to every reader — which then DELETES the
        half-published entry."""
        lock = self.exec_path(key) + ".lock"
        for _ in range(2):
            try:
                return os.open(lock, os.O_CREAT | os.O_EXCL
                               | os.O_WRONLY), lock
            except FileExistsError:
                try:
                    if (time.time() - os.path.getmtime(lock)
                            < self.LOCK_STALE_S):
                        return None
                    os.unlink(lock)        # stale: dead writer
                except OSError:
                    return None
        return None

    def store(self, key: str, compiled, meta: dict = None) -> str | None:
        """Serialize + publish one executable. Sidecars (hash, meta)
        publish BEFORE the payload's atomic replace, so a visible
        .exec always has its verification hash (the PR 5 ordering —
        a kill between the two writes leaves an invisible entry, not
        a complete-looking unverifiable one). Publishing is
        first-writer-wins: a complete entry is never overwritten, and
        a per-key lock serializes racing writers (fleet children
        compiling the same shape), since interleaved sidecar/payload
        writes from two processes would read as corruption."""
        if not serialize_support():
            return None
        os.makedirs(self.root, exist_ok=True)
        if self.has(key):
            return None           # someone already published it whole
        got = self._publish_lock(key)
        if got is None:
            return None
        fd, lock = got
        try:
            if self.has(key):
                return None
            try:
                from jax.experimental import serialize_executable as se
                payload, in_tree, out_tree = se.serialize(compiled)
                blob = pickle.dumps((payload, in_tree, out_tree))
            except Exception as e:
                _warn(f"serialize failed ({type(e).__name__}: {e}); "
                      "entry not persisted (this process keeps its "
                      "in-memory executable)")
                return None
            p = self.exec_path(key)
            _write_atomic(p + ".sha256",
                          (hashlib.sha256(blob).hexdigest()
                           + "\n").encode())
            m = dict(meta or {})
            m["payload_bytes"] = len(blob)
            _write_atomic(self.meta_path(key),
                          (json.dumps(m, indent=1, sort_keys=True)
                           + "\n").encode())
            _write_atomic(p, blob)
            STATS["disk_stores"] += 1
            self._retain()
            return p
        finally:
            os.close(fd)
            try:
                os.unlink(lock)
            except OSError:
                pass

    def _retain(self):
        keys = self.entries()
        for key in keys[:max(len(keys) - self.keep, 0)]:
            self.remove(key)


# --- the AotJit miss path --------------------------------------------------

def load_or_compile(jitted, scope, sig, args, undonated=None):
    """Resolve one AotJit signature miss: disk-load where a cache is
    active and the program has a stable scope, else compile (and
    persist). The observability contract lives here so every AotJit
    user gets it for free: ``jitcache.load`` / ``jitcache.compile``
    spans (-> obs.perf ``compile-hit`` / ``compile-miss`` phases),
    ``jitcache.*`` metrics, and the unconditional :data:`STATS`.

    `undonated` is a zero-arg callable returning the donation-free
    twin of `jitted` (None when the program donates nothing). When
    the disk tier is in play, the UNDONATED program is what compiles,
    stores and loads: a serialize round trip of a donated executable
    is unsound on the XLA:CPU client — the loaded executable's
    outputs alias the donated input buffers, whose memory the runtime
    frees, a use-after-free that silently corrupts results once the
    allocator reuses the block (reproduced on the window chunk
    program: event-queue bytes mutate after unrelated allocations).
    Undonated execution computes identical values — cold-through-
    cache and warm chains stay byte-identical to the donated no-cache
    run (tests/test_serving.py) — at a transient 2x peak for the
    donated operands during each call. Without an active cache (or
    without a scope) the donated program runs untouched."""
    from ..obs import metrics as MT
    from ..obs import trace as TR

    cache = active()
    key = None
    if cache is not None and scope is not None and serialize_support():
        # the swap only buys anything when executables actually
        # round-trip through disk; a backend that cannot serialize
        # keeps donation (and its memory savings) untouched
        if undonated is not None:
            u = undonated()
            if u is not None:
                jitted = u
        key = entry_key(scope, sig)
        t0 = TR.TRACER.now() if TR.ENABLED else None
        w0 = time.perf_counter()
        fn = cache.load(key)
        if fn is not None:
            wall = time.perf_counter() - w0
            STATS["disk_hits"] += 1
            STATS["load_wall_s"] += wall
            if TR.ENABLED:
                TR.TRACER.complete("jitcache.load", t0,
                                   args={"key": key, "scope": scope})
            if MT.ENABLED:
                reg = MT.REGISTRY
                reg.counter("jitcache.disk_hits").inc()
                g = reg.gauge("jitcache.load_wall_s")
                g.set(g.v + wall)
            return fn
    t0 = TR.TRACER.now() if TR.ENABLED else None
    w0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    wall = time.perf_counter() - w0
    STATS["compiles"] += 1
    STATS["compile_wall_s"] += wall
    if TR.ENABLED:
        TR.TRACER.complete("jitcache.compile", t0,
                           args={"scope": scope or "?",
                                 "cached": key is not None})
    if MT.ENABLED:
        reg = MT.REGISTRY
        reg.counter("jitcache.compiles").inc()
        g = reg.gauge("jitcache.compile_wall_s")
        g.set(g.v + wall)
    if key is not None:
        cache.store(key, compiled, meta=key_meta(scope, sig))
    return compiled
