"""Vmapped scenario batching: one compile, N cheap executions.

A parameter sweep's scenarios usually share one compiled shape —
identical :class:`engine.state.EngineConfig`, differing only in seed
and Shared scalars (stop time, RNG root, latency tables). Running
them one process each pays the XLA compile N times (or, with the
aotcache disk tier, one compile + N loads + N process startups).
This module runs them as ONE program: the scenarios' (Hosts,
HostParams, Shared) pytrees stack on a leading axis and the window
chunk program runs under ``jax.vmap``
(``engine.window.run_windows_batch_aot``), so an N-point sweep pays
one compile and N lanes of cheap execution per pass.

Determinism is untouched, and provably so: jax's while_loop batching
rule freezes a finished lane's carry, so each lane's window
trajectory — chunk boundaries, window counts, state bytes — is
exactly its individual run's. Every lane emits its OWN digest chain
(an :class:`obs.digest.DigestRecorder` per scenario, cadence records
on the same window boundaries a single run produces) and its own
perf-ledger entry, and ``tools/divergence.py`` exits 0 against the
same scenario run individually (tests/test_serving.py — the
acceptance proof).

Batch runs are deliberately plainer than ``Simulation.run``: no
hosted apps, no fault schedules, no pcap, no mesh, no
checkpoint/resume (a crashed batch re-runs from scratch — the fleet
treats a batch group like a ``cmd`` run). What they keep is the part
a sweep needs: digest chains, summaries, ledger entries, the fleet
liveness heartbeat.

CLI (dispatched from ``python -m shadow_tpu batch ...``)::

  python -m shadow_tpu batch a.xml b.xml c.xml [--digest-dir D]
  python -m shadow_tpu batch sweep.xml --seeds 1,2,3,4 [--stop-time 10s]

``fleet submit --batch`` enqueues the same thing as one slot with
per-member journal states (docs/serving.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


class BatchShapeError(ValueError):
    """The scenarios do not share one compiled shape (EngineConfig or
    array shapes differ) — run them individually, or align their
    configs."""


def _stack_trees(trees):
    import jax
    import jax.numpy as jnp
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def check_same_shape(sims) -> None:
    """Every member must compile to the SAME program: identical
    EngineConfig (static shapes/knobs) and identical array shapes
    (topology size, app tables). Differing Shared *values* (seed,
    stop time, latency tables) are exactly what batching is for."""
    import jax

    cfg0 = sims[0].cfg
    for i, s in enumerate(sims[1:], 1):
        if s.cfg != cfg0:
            raise BatchShapeError(
                f"member {i} resolves a different EngineConfig than "
                f"member 0 — not one compiled shape:\n  0: {cfg0}\n  "
                f"{i}: {s.cfg}")
    shapes0 = jax.tree.map(lambda a: a.shape, (sims[0].hosts,
                                               sims[0].hp, sims[0].sh))
    for i, s in enumerate(sims[1:], 1):
        shapes = jax.tree.map(lambda a: a.shape, (s.hosts, s.hp, s.sh))
        if shapes != shapes0:
            raise BatchShapeError(
                f"member {i}'s state arrays differ in shape from "
                "member 0's (different topology/app tables?) — "
                "members must share one compiled shape")
    for i, s in enumerate(sims):
        if s.hosting is not None:
            raise BatchShapeError(
                f"member {i} hosts real processes; batching covers "
                "modeled scenarios only")
        if s.injector is not None:
            raise BatchShapeError(
                f"member {i} schedules faults; batching covers plain "
                "runs only (fault surgery needs per-run host state)")
        if s.cfg.tracecap:
            raise BatchShapeError(
                f"member {i} enables pcap tracing; batching covers "
                "plain runs only")


def run_batch(sims, names=None, digest_paths=None, digest_every=0,
              netscope_paths=None, verbose=False):
    """Run N same-shape Simulations as one vmapped program.

    `digest_paths` (optional, len N) gives each lane its own digest
    chain + manifest, recorded at `digest_every` (default
    obs.digest.DEFAULT_EVERY) — cadence and final records land on
    exactly the window boundaries the same scenario produces
    individually, so the chains are byte-comparable with
    tools/divergence.py. Returns a list of SimReport, one per lane
    (wall_seconds is the SHARED batch wall — ledger entries say so).

    When the shared config carries ``netscope``, each lane gets its
    own :class:`obs.netscope.NetScope` recorder sampled on its own
    chunk boundaries (frozen lanes stop sampling, like a single run
    stopping) and its SimReport carries a per-lane ``network`` report
    from its slice of the stacked [lanes, H, K, B] accumulator —
    byte-equal to the same scenario's individual run.
    `netscope_paths` (optional, len N) streams each lane's records to
    its own JSONL file.
    """
    import jax
    import jax.numpy as jnp

    from ..core.simtime import SIMTIME_MAX
    from ..engine import defs
    from ..engine.sim import SimReport
    from ..engine.state import hot_fields
    from ..engine.window import (pass_labels, run_windows_batch_aot,
                                 sparse_batch)
    from ..obs import digest as DG
    from ..obs import netscope as NSC
    from ..obs import passcope as PCOPE

    B = len(sims)
    assert B >= 1
    check_same_shape(sims)
    cfg = sims[0].cfg
    for s in sims:
        assert not s._ran, "Simulation objects are single-use"
        s._ran = True
    names = list(names or [f"member{i}" for i in range(B)])

    nsrecs = None
    if cfg.netscope:
        if netscope_paths is not None:
            assert len(netscope_paths) == B
        nsrecs = [NSC.NetScope(netscope_paths[i]
                               if netscope_paths is not None else None)
                  for i in range(B)]
    elif netscope_paths is not None:
        raise BatchShapeError(
            "netscope_paths given but the members' EngineConfig has "
            "netscope off — the device histograms are a compiled "
            "shape, so enable it on every member")

    recorders = None
    if digest_paths is not None:
        assert len(digest_paths) == B
        every = digest_every or DG.DEFAULT_EVERY
        recorders = [DG.DigestRecorder(p, every=every)
                     for p in digest_paths]
        for s, dg in zip(sims, recorders):
            dg.write_manifest(DG.build_manifest(
                s.scenario, s.cfg, s.seed, s.sh, s.host_names, dg))

    # records must land on exact window boundaries (the engine.sim
    # contract): the shared chunk rule (hosted members are refused
    # above, so this is cfg.chunk_windows shrunk to the cadence)
    chunk = sims[0].effective_chunk(
        recorders[0].every if recorders is not None else 0)
    fn = run_windows_batch_aot(cfg, chunk, B)

    hosts = _stack_trees([s.hosts for s in sims])
    hp = _stack_trees([s.hp for s in sims])
    sh = _stack_trees([s.sh for s in sims])
    ws = jnp.stack([jnp.min(s.hosts.eq_next) for s in sims])
    we = jnp.where(ws == SIMTIME_MAX, ws, ws + sh.min_jump)

    H = cfg.num_hosts
    stops = np.array([int(s.sh.stop_time) for s in sims],
                     dtype=np.int64)
    total_windows = np.zeros(B, dtype=np.int64)
    done = np.zeros(B, dtype=bool)
    _pl = pass_labels(cfg, H)
    pass_acc = np.zeros((B, len(_pl)), dtype=np.int64)
    _hot = hot_fields(cfg)
    row_bytes = sum(
        int(np.prod(getattr(sims[0].hosts, f).shape[1:]))
        * getattr(sims[0].hosts, f).dtype.itemsize for f in _hot)

    # fleet liveness heartbeat (docs/fleet.md): the scheduler's
    # watchdog needs a wall-paced progress signal from batch children
    # exactly like single runs (engine.sim's per-chunk touch). The
    # per-loop write below paces it while chunks retire — but the
    # FIRST fn() call blocks through the whole vmapped XLA compile
    # (10-15+ min on chip, vs the 900 s default hang timeout), so a
    # background beater keeps the mtime moving during it; otherwise
    # the watchdog would SIGKILL a healthy compiling group into
    # retry -> the identical compile -> quarantine.
    hb_dir = os.environ.get("SHADOW_TPU_FLEET_RUN_DIR")
    hb_path = os.path.join(hb_dir, "heartbeat") if hb_dir else None
    hb_stop = None
    if hb_path is not None:
        import threading

        hb_ws = {"ws": 0}

        def _beat(stop):
            while not stop.wait(15.0):
                try:
                    with open(hb_path, "w") as f:
                        f.write(f"{hb_ws['ws']}\n")
                except OSError:
                    pass

        hb_stop = threading.Event()
        threading.Thread(target=_beat, args=(hb_stop,),
                         daemon=True).start()

    def lane(tree, i):
        return jax.tree.map(lambda a: a[i], tree)

    def record(i, kind):
        w = int(np.asarray(ws)[i])
        sim_ns = (min(w, int(stops[i])) if w < SIMTIME_MAX
                  else int(stops[i]))
        recorders[i].record(lane(hosts, i), H,
                            int(total_windows[i]), sim_ns, kind)

    wall0 = time.perf_counter()
    first_chunk_wall = None
    while True:
        if hb_path is not None:
            hb_ws["ws"] = int(np.asarray(ws).min())
            try:
                with open(hb_path, "w") as f:
                    f.write(f"{hb_ws['ws']}\n")
            except OSError:
                pass
        hosts, ws, we, n, pc = fn(hosts, hp, sh, ws, we)
        n_np = np.asarray(n)
        total_windows += n_np
        pass_acc += np.asarray(pc)
        if first_chunk_wall is None:
            first_chunk_wall = time.perf_counter() - wall0
        w_np = np.asarray(ws)
        if nsrecs is not None:
            # per-lane network samples from the stacked accumulator:
            # one record per chunk a lane was ACTIVE in (a frozen
            # lane's carry no longer moves — sampling it would add
            # records a single run never emits)
            ns_b = np.asarray(hosts.ns_hist)
            st_b = np.asarray(hosts.stats)
            sk_b = np.asarray(hosts.sk_used)
        for i in range(B):
            if done[i]:
                continue
            if nsrecs is not None:
                nsrecs[i].sample(
                    int(total_windows[i]),
                    min(int(w_np[i]), int(stops[i])),
                    ns_b[i], st_b[i], conns=int(sk_b[i].sum()))
            # the single-run record order, per lane: cadence when due
            # after the chunk, then the final record when the lane
            # completes — so chains byte-match individual runs
            if (recorders is not None
                    and recorders[i].due(int(total_windows[i]))):
                record(i, "cadence")
            if w_np[i] >= stops[i] or w_np[i] >= SIMTIME_MAX:
                if recorders is not None:
                    record(i, "final")
                done[i] = True
        if verbose:
            print(f"  batch: {int(done.sum())}/{B} done, windows="
                  f"{total_windows.tolist()}")
        if done.all():
            break
    if hb_stop is not None:
        hb_stop.set()
    wall = time.perf_counter() - wall0
    if recorders is not None:
        for dg in recorders:
            dg.close()

    warm = (wall - first_chunk_wall
            if first_chunk_wall is not None
            and wall > first_chunk_wall * 1.05 else None)
    stats_b = np.asarray(hosts.stats)
    peaks_b = np.asarray(hosts.cap_peaks)
    ns_final = (np.asarray(hosts.ns_hist)
                if nsrecs is not None else None)
    reports = []
    for i in range(B):
        w = int(np.asarray(ws)[i])
        sim_ns = (min(int(stops[i]), w) if w < SIMTIME_MAX
                  else int(stops[i]))
        peaks = peaks_b[i].max(axis=0)
        capacity = {"rows": [
            ("event_queue", cfg.qcap, int(peaks[0])),
            ("socket_table", cfg.scap, int(peaks[1])),
            ("outbox", cfg.obcap, int(peaks[2])),
            ("nic_txq", cfg.txqcap, int(peaks[3])),
        ]}
        cost = {
            "row_bytes": row_bytes,
            "hot_columns": len(_hot),
            "pass_mix": {lbl: (size, int(nn)) for (lbl, size), nn in
                         zip(_pl, pass_acc[i])},
            "batch": sparse_batch(cfg),
            "per_chip_hosts": H,
            "shards": 1,
            "warm_wall": warm,
            "hbm_peak_gbps": float(os.environ.get(
                "SHADOW_TPU_HBM_GBPS", "819")),
        }
        network = {}
        if nsrecs is not None:
            # per-lane network report from this lane's slice of the
            # FINAL device histogram (not the last sample — the exact
            # construction engine.sim uses)
            network = NSC.report(ns_final[i])
            network["records"] = len(nsrecs[i].records)
            if nsrecs[i].path:
                network["path"] = nsrecs[i].path
            nsrecs[i].close()
        # per-lane lockstep occupancy (obs.passcope): each lane's own
        # pass mix against its own executed events — a skewed lane
        # shows its waste here, not averaged into the batch
        occ = PCOPE.occupancy(
            cost["pass_mix"],
            int(stats_b[i][:, defs.ST_EVENTS].sum()),
            cost["batch"])
        reports.append(SimReport(
            stats=stats_b[i], host_names=sims[i].host_names,
            sim_time_ns=sim_ns, wall_seconds=wall,
            windows=int(total_windows[i]), capacity=capacity,
            cost=cost, network=network, occupancy=occ))
    return reports


# --- CLI -------------------------------------------------------------------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="shadow_tpu batch",
        description="run N same-shape scenarios as one vmapped "
                    "program: one compile, N executions "
                    "(docs/serving.md)")
    p.add_argument("configs", nargs="+",
                   help="scenario XML path(s); with --seeds, exactly "
                        "one, replicated per seed")
    p.add_argument("--seeds", default=None, metavar="S1,S2,...",
                   help="replicate the single config across these "
                        "seeds (member ids <stem>-s<seed>)")
    p.add_argument("--stop-time", default=None, metavar="TIME",
                   help="override every member's stop time")
    p.add_argument("--runahead", default=None, metavar="TIME",
                   help="override every member's lookahead window")
    p.add_argument("--digest-dir", default=None, metavar="DIR",
                   help="per-member digest chains: DIR/<member>."
                        "digest.jsonl (+ manifests)")
    p.add_argument("--digest-paths", default=None, metavar="P1,P2,...",
                   help="explicit per-member digest chain paths "
                        "(comma-separated, member order; the fleet "
                        "worker points these at each member's run "
                        "directory)")
    p.add_argument("--digest-every", type=int, default=0,
                   metavar="WINDOWS")
    p.add_argument("--netscope", action="store_true",
                   help="network observatory (obs.netscope): device "
                        "histograms per lane, a per-lane network "
                        "report in each member's summary, and one "
                        "cross-lane ensemble JSON line (pooled + "
                        "per-lane percentiles)")
    p.add_argument("--netscope-dir", default=None, metavar="DIR",
                   help="per-member netscope time-series streams: "
                        "DIR/<member>.netscope.jsonl (implies "
                        "--netscope)")
    p.add_argument("--netscope-paths", default=None,
                   metavar="P1,P2,...",
                   help="explicit per-member netscope stream paths "
                        "(comma-separated, member order; the fleet "
                        "worker points these at each member's run "
                        "directory — implies --netscope)")
    p.add_argument("--aot-cache", default=None, metavar="DIR",
                   help="persistent AOT executable cache "
                        "(docs/serving.md)")
    p.add_argument("--perf", nargs="?", const="", default=None,
                   metavar="LEDGER",
                   help="append one perf-ledger entry PER MEMBER "
                        "(events are the member's; the wall is the "
                        "shared batch wall, noted in the entry)")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--summary-json", action="store_true",
                   help="print one summary JSON line per member")
    args = p.parse_args(argv)

    if args.aot_cache:
        from . import aotcache as AC
        AC.install(args.aot_cache)

    from ..core.config import load_xml
    from ..core.simtime import parse_time

    if args.seeds:
        if len(args.configs) != 1:
            p.error("--seeds takes exactly one config XML")
        try:
            seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
        except ValueError:
            p.error(f"--seeds {args.seeds!r}: not integers")
        if not seeds:
            p.error("--seeds names no seeds")
        if len(set(seeds)) != len(seeds):
            p.error(f"--seeds {args.seeds!r} lists duplicates — "
                    "member ids (and their digest chains) are named "
                    "by seed, so duplicate lanes would interleave "
                    "into one chain file")
        stem = os.path.splitext(os.path.basename(args.configs[0]))[0]
        members = [(f"{stem}-s{s}", args.configs[0], s) for s in seeds]
    else:
        members = []
        for path in args.configs:
            members.append((os.path.splitext(
                os.path.basename(path))[0], path, None))
        if len({m[0] for m in members}) != len(members):
            p.error("duplicate member stems; give distinct config "
                    "basenames (per-member outputs are named by stem)")

    sims = []
    names = []
    for name, path, seed in members:
        try:
            scen = load_xml(path)
        except (OSError, ValueError) as e:
            p.error(f"{path}: {e}")
        if args.stop_time:
            scen.stop_time = parse_time(args.stop_time,
                                        default_unit="s")
        if seed is not None:
            scen.seed = seed
        from ..engine.sim import Simulation
        sim = Simulation(scen)
        if args.netscope or args.netscope_dir or args.netscope_paths:
            # the device histograms are part of the compiled shape, so
            # the knob must be set before Hosts allocation — rebuild
            # with the auto config flipped (topology is reused)
            import dataclasses
            sim = Simulation(scen, topology=sim.topo,
                             engine_cfg=dataclasses.replace(
                                 sim.cfg, netscope=True))
        if args.runahead:
            import jax.numpy as jnp
            ra = parse_time(args.runahead, default_unit="ms")
            sim.sh = sim.sh.replace(min_jump=jnp.int64(max(ra, 1)))
        sims.append(sim)
        names.append(name)

    digest_paths = None
    if args.digest_paths:
        digest_paths = [s for s in args.digest_paths.split(",") if s]
        if len(digest_paths) != len(sims):
            p.error(f"--digest-paths names {len(digest_paths)} paths "
                    f"for {len(sims)} members")
    elif args.digest_dir:
        os.makedirs(args.digest_dir, exist_ok=True)
        digest_paths = [os.path.join(args.digest_dir,
                                     f"{n}.digest.jsonl")
                        for n in names]

    netscope_paths = None
    if args.netscope_paths:
        netscope_paths = [s for s in args.netscope_paths.split(",")
                          if s]
        if len(netscope_paths) != len(sims):
            p.error(f"--netscope-paths names {len(netscope_paths)} "
                    f"paths for {len(sims)} members")
    elif args.netscope_dir:
        os.makedirs(args.netscope_dir, exist_ok=True)
        netscope_paths = [os.path.join(args.netscope_dir,
                                       f"{n}.netscope.jsonl")
                          for n in names]

    try:
        reports = run_batch(sims, names=names,
                            digest_paths=digest_paths,
                            digest_every=args.digest_every,
                            netscope_paths=netscope_paths,
                            verbose=args.verbose)
    except BatchShapeError as e:
        p.error(str(e))

    from . import aotcache as AC
    compile_cache = "miss" if AC.STATS["compiles"] else "hit"
    B = len(reports)
    for name, rep in zip(names, reports):
        s = rep.summary()
        line = {"member": name, "events": s["events"],
                "windows": s["windows"],
                "sim_seconds": s["sim_seconds"],
                "batch_wall_seconds": round(rep.wall_seconds, 3),
                "batch": B, "compile_cache": compile_cache}
        print(json.dumps(line), flush=True)
        if args.summary_json:
            print(json.dumps(s), flush=True)

    if args.netscope or args.netscope_dir or args.netscope_paths:
        # cross-lane percentile curves: pooled distribution + per-lane
        # tails per kind, from the lanes' final device histograms
        from ..obs import netscope as NSC
        ens = NSC.ensemble([
            [r.network["kinds"][n]["buckets"] for n in NSC.KIND_NAMES]
            for r in reports if r.network.get("kinds")])
        print(json.dumps({"netscope_ensemble": {
            "runs": ens.get("runs", 0),
            "kinds": {name: {f: k[f] for f in
                             ("count", "p50_us", "p90_us", "p99_us",
                              "lane_p50_us", "lane_p99_us")}
                      for name, k in ens.get("kinds", {}).items()},
        }}), flush=True)

    if args.perf is not None:
        import jax

        from ..obs import ledger as LG
        for name, rep, sim in zip(names, reports, sims):
            entry = LG.make_entry(
                scenario=name,
                fingerprint=LG.fingerprint_of(
                    sim.cfg, seed=sim.scenario.seed,
                    stop_ns=int(sim.scenario.stop_time),
                    batch=B),
                platform=jax.default_backend(),
                summary=rep.summary(), cost=rep.cost_model(),
                warm_wall=(round(rep.cost["warm_wall"], 3)
                           if rep.cost.get("warm_wall") else None),
                cold_wall=round(rep.wall_seconds
                                - (rep.cost.get("warm_wall") or 0), 3),
                note=(f"vmapped batch member ({B} lanes, one "
                      f"compile_cache={compile_cache} program; wall "
                      "is the SHARED batch wall, so the rate reads "
                      "as this member's share)"),
                cfg=sim.cfg)
            lpath = LG.append(entry, args.perf or None)
            if lpath:
                sys.stderr.write(
                    f"shadow_tpu: batch: perf ledger += {lpath} "
                    f"({name})\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
