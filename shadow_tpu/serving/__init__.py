"""Serving layer: compile once, execute N times.

The reference simulator amortizes nothing across runs — and this
port's dominant fixed cost per run is the XLA compile (312 s of a
714 s cold tor50k wall on CPU; 10-15 min per config shape on chip —
BASELINE.md). This package is the fleet's answer (ROADMAP item 3),
three parts:

- :mod:`aotcache` — a persistent disk tier under ``core.jitcache
  .AotJit``: executables serialized via
  ``jax.experimental.serialize_executable`` (capability-probed; loud
  in-memory-only fallback), keyed config-fingerprint x arg-signature
  x jax/XLA versions x platform x source digest, stored crash-safely
  (tmp+fsync+os.replace + sha256 sidecars — the PR 5 checkpoint-store
  pattern). A process-fresh run of a known shape loads in seconds
  instead of compiling in minutes.
- :mod:`prewarm` — the fleet scheduler fingerprints each queued run's
  config shape headlessly, dedups shapes across the sweep, and
  compiles each distinct shape ONCE in a pre-warm slot before
  admission, so workers open on a warm cache (``fleet run --prewarm``).
- :mod:`batch` — same-shape scenarios (identical EngineConfig,
  differing seed/scalar params) execute as ONE vmapped program over a
  leading scenario axis: one compile, N cheap executions, while still
  emitting per-scenario digest chains and ledger entries, proven
  byte-identical to N individual runs (``fleet submit --batch``,
  ``python -m shadow_tpu batch``).

Everything here is host-side orchestration: digest chains of cached,
pre-warmed or batched runs are byte-identical to cold individual runs
(tests/test_serving.py; docs/serving.md).
"""
