"""Fleet pre-warm: compile each distinct config shape once, up front.

A sweep's runs mostly share a handful of compiled shapes — and
without this module each worker child discovers that the expensive
way, by compiling (10-15 min per shape on chip). With ``fleet run
--prewarm --aot-cache DIR`` the scheduler instead:

1. **fingerprints** each queued config run's compiled shape
   headlessly (a cheap ``--shape-fingerprint`` child per run: builds
   the Simulation, prints ``obs.ledger.fingerprint_of(cfg)``, never
   compiles);
2. **dedups** shapes across the sweep;
3. **compiles each distinct shape once** in a pre-warm slot (a
   ``--prewarm --aot-cache DIR`` child that populates the persistent
   executable cache and exits), before — or concurrently with —
   admission: a run is admitted only once its shape is warmed (or
   its warm FAILED, in which case it runs anyway and pays its own
   compile — pre-warm is an optimization, never a gate that can
   wedge a sweep).

Every transition journals into the queue
(``{"op": "prewarm", ...}``), so ``fleet status`` reports shapes
warmed vs pending offline, and a restarted scheduler re-probes
cheaply (warm children that find their shape already cached exit in
seconds).

The probe/warm child command builders are injectable so the
scheduler machinery tests stay jax-free (tests/test_serving.py).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def probe_argv(python: str, spec: dict) -> list:
    """The shape-fingerprint child for one config run spec: the same
    config + extra args a worker attempt would run (anything that
    changes the compiled shape — --engine-caps, --seed, the digest
    cadence that shrinks the chunk — must be in both), ending in
    --shape-fingerprint."""
    argv = ([python or sys.executable, "-m", "shadow_tpu",
             os.path.abspath(spec["config"])]
            + list(spec.get("args") or []))
    if spec.get("digest", True):
        # the worker child runs with --digest, whose cadence sets the
        # compiled chunk; the probe must report THAT program's shape
        argv += ["--digest", "unused.probe.jsonl"]
        if spec.get("digest_every"):
            argv += ["--digest-every", str(spec["digest_every"])]
    return argv + ["--shape-fingerprint"]


def warm_argv(python: str, spec: dict, cache_dir: str) -> list:
    """The pre-warm compile child for one shape, built from a
    representative member spec. Digest settings ride along because
    the worker child will run with --digest, and the digest cadence
    sets the chunk size the program compiles for (engine.sim); the
    chain file itself is never written in --prewarm mode."""
    argv = ([python or sys.executable, "-m", "shadow_tpu",
             os.path.abspath(spec["config"])]
            + list(spec.get("args") or [])
            + ["--aot-cache", os.path.abspath(cache_dir), "--prewarm"])
    if spec.get("digest", True):
        argv += ["--digest", "unused.prewarm.jsonl"]
        if spec.get("digest_every"):
            argv += ["--digest-every", str(spec["digest_every"])]
    return argv


class Prewarmer:
    """Owns the probe → dedup → warm pipeline for one scheduler run.

    Non-blocking: the scheduler calls :meth:`tick` once per drain
    loop; :meth:`ready` gates admission. `journal` is a callback
    (op fields -> None) appending ``prewarm`` records to the queue
    journal; `probe_fn`/`warm_fn` build child argvs (injectable for
    jax-free tests)."""

    def __init__(self, specs: list, cache_dir: str, python: str = None,
                 jobs: int = 1, log=None, journal=None,
                 probe_fn=probe_argv, warm_fn=warm_argv,
                 probe_timeout_s: float = 600.0,
                 warm_timeout_s: float = 3600.0):
        self.cache_dir = cache_dir
        self.python = python
        self.jobs = max(int(jobs), 1)
        # a hung probe/warm child must never wedge the sweep (the
        # scheduler-watchdog contract, one level down): past its
        # deadline it is SIGKILLed and counted failed — its runs
        # then admit and pay their own compile
        self.probe_timeout_s = float(probe_timeout_s)
        self.warm_timeout_s = float(warm_timeout_s)
        self._deadline = {}      # id(proc) -> wall deadline
        self.log = log or (lambda m: sys.stderr.write(
            f"shadow_tpu: prewarm: {m}\n"))
        self.journal = journal or (lambda **kw: None)
        self.probe_fn = probe_fn
        self.warm_fn = warm_fn
        # config-mode specs only; everything else is ready by
        # definition (cmd runs own their whole argv)
        self._specs = {s["id"]: s for s in specs if s.get("config")}
        self._to_probe = list(self._specs)
        self._probes = {}        # run_id -> Popen
        self._shape_of = {}      # run_id -> fingerprint or "" (failed)
        self._spec_of_shape = {}  # fingerprint -> representative spec
        self._to_warm = []       # fingerprints awaiting a warm slot
        self._warming = {}       # fingerprint -> Popen
        self._state = {}         # fingerprint -> warming|warmed|failed

    @staticmethod
    def _child_env(spec: dict) -> dict:
        """Probe/warm children run under the run's OWN environment
        overrides (``fleet submit --env``, e.g. a platform pin) — the
        worker attempt applies them (fleet.worker.Slot), so a
        probe/warm under the scheduler's environment could
        fingerprint and compile a DIFFERENT backend's program, paying
        a useless warm plus the run's own compile."""
        env = dict(os.environ)
        env.update(spec.get("env") or {})
        return env

    # --- queries ----------------------------------------------------
    def ready(self, run_id: str) -> bool:
        """Admission gate: True once the run's shape is warmed — or
        its probe/warm FAILED (the run then pays its own compile; a
        broken pre-warm must never starve the queue)."""
        if run_id not in self._specs:
            return True
        fp = self._shape_of.get(run_id)
        if fp is None:
            return False                  # probe still pending
        if fp == "":
            return True                   # probe failed: run anyway
        return self._state.get(fp) in ("warmed", "failed")

    def done(self) -> bool:
        return (not self._to_probe and not self._probes
                and not self._to_warm and not self._warming)

    def counts(self) -> dict:
        pending = sum(1 for fp, st in self._state.items()
                      if st == "warming") + len(self._to_warm)
        return {"warmed": sum(1 for s in self._state.values()
                              if s == "warmed"),
                "failed": sum(1 for s in self._state.values()
                              if s == "failed"),
                "warming": pending,
                "probing": len(self._to_probe) + len(self._probes)}

    # --- the pipeline -----------------------------------------------
    def tick(self):
        """Advance the pipeline without blocking: reap finished
        probe/warm children, launch new ones up to `jobs` each."""
        self._reap_probes()
        self._reap_warms()
        while self._to_probe and len(self._probes) < self.jobs:
            rid = self._to_probe.pop(0)
            spec = self._specs[rid]
            try:
                proc = subprocess.Popen(
                    self.probe_fn(self.python, spec),
                    stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL,
                    env=self._child_env(spec))
            except OSError as e:
                self._probe_done(rid, "", f"spawn failed: {e}")
                continue
            self._probes[rid] = proc
            self._deadline[id(proc)] = (time.monotonic()
                                        + self.probe_timeout_s)
        while self._to_warm and len(self._warming) < self.jobs:
            fp = self._to_warm.pop(0)
            spec = self._spec_of_shape[fp]
            try:
                proc = subprocess.Popen(
                    self.warm_fn(self.python, spec, self.cache_dir),
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                    env=self._child_env(spec))
            except OSError as e:
                self._warm_done(fp, None, f"spawn failed: {e}")
                continue
            self._warming[fp] = proc
            self._deadline[id(proc)] = (time.monotonic()
                                        + self.warm_timeout_s)
            self._state[fp] = "warming"
            self.journal(shape=fp, state="warming",
                         run=spec["id"])
            self.log(f"shape {fp}: warming (via {spec['id']})")

    def _expired(self, proc) -> bool:
        dl = self._deadline.get(id(proc))
        if dl is None or time.monotonic() < dl:
            return False
        try:
            proc.kill()
        except OSError:
            pass
        return True

    def _reap_probes(self):
        for rid, proc in list(self._probes.items()):
            rc = proc.poll()
            if rc is None:
                if not self._expired(proc):
                    continue
                rc = proc.wait()
            del self._probes[rid]
            self._deadline.pop(id(proc), None)
            out = proc.stdout.read() if proc.stdout else b""
            if proc.stdout:
                proc.stdout.close()
            fp = ""
            if rc == 0:
                # the probe prints exactly one JSON line; scan for it
                # so a warning-spewing child still parses. `shape`
                # (chunk-qualified, c<chunk>.<fp>) is the dedup key —
                # two runs sharing a config fingerprint but chunking
                # differently compile different programs; bare
                # `shape_fingerprint` is the pre-chunk fallback
                for line in out.decode(errors="replace").splitlines():
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict) and rec.get(
                            "shape_fingerprint"):
                        fp = (rec.get("shape")
                              or rec["shape_fingerprint"])
                        break
            self._probe_done(
                rid, fp,
                None if fp else f"probe rc={rc}, no fingerprint")

    def _probe_done(self, rid: str, fp: str, err: str = None):
        self._shape_of[rid] = fp
        if not fp:
            self.log(f"run {rid}: shape probe failed ({err}); the "
                     "run will compile for itself")
            self.journal(shape="", state="probe-failed", run=rid)
            return
        self.journal(shape=fp, state="resolved", run=rid)
        if fp in self._state or fp in self._to_warm:
            return                         # deduped: already handled
        self._spec_of_shape[fp] = self._specs[rid]
        self._to_warm.append(fp)

    def _reap_warms(self):
        for fp, proc in list(self._warming.items()):
            rc = proc.poll()
            if rc is None:
                if not self._expired(proc):
                    continue
                rc = proc.wait()
            del self._warming[fp]
            self._deadline.pop(id(proc), None)
            self._warm_done(fp, rc)

    def shutdown(self):
        """Kill outstanding probe/warm children (scheduler exit or
        preemption): pre-warm is pure optimization, nothing durable
        is lost — a restarted scheduler re-probes, and warm children
        finding their shape already cached exit in seconds."""
        for proc in list(self._probes.values()) + list(
                self._warming.values()):
            try:
                proc.kill()
                proc.wait(timeout=5)
            except Exception:
                pass
        self._probes.clear()
        self._warming.clear()
        self._to_probe.clear()
        self._to_warm.clear()

    def _warm_done(self, fp: str, rc, err: str = None):
        ok = rc == 0
        self._state[fp] = "warmed" if ok else "failed"
        self.journal(shape=fp, state=self._state[fp])
        if ok:
            self.log(f"shape {fp}: warmed")
        else:
            self.log(f"shape {fp}: pre-warm FAILED "
                     f"({err or f'rc={rc}'}); its runs will compile "
                     "for themselves")
