"""``shadow_tpu fleet`` — submit / run / status for sweep queues.

  python -m shadow_tpu fleet submit Q config.xml [opts] [-- child args]
  python -m shadow_tpu fleet submit Q --cmd [opts] -- prog arg...
  python -m shadow_tpu fleet run Q [--workers N] [--metrics FILE] ...
  python -m shadow_tpu fleet status Q [--json]

``submit`` durably enqueues one run (the XML is copied into the
queue, so temp files are fine); ``--batch GROUP [--seeds 1,2,..]``
enqueues vmapped-batch members that execute as lanes of ONE compiled
program (serving.batch). ``run`` drains the queue — restart it after
any crash or preemption and the sweep completes as if never
interrupted (docs/fleet.md); ``--aot-cache DIR`` shares a persistent
executable cache across children and ``--prewarm`` compiles each
distinct config shape once before its runs admit (docs/serving.md).
``status`` folds the journal into a table, including shapes warmed
vs pending.

Exit codes of ``run``: 0 queue drained, every run done; 3 drained but
some runs quarantined (their crash-cause journals are named in the
status output); 75 preempted (SIGTERM — children checkpointed and
were requeued; run again to resume); 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
from xml.etree import ElementTree


def _count_hosts(xml_path: str) -> int:
    """Admission weight from the scenario XML: total expanded hosts.
    A light direct parse — submit must not pay an engine import."""
    try:
        root = ElementTree.parse(xml_path).getroot()
    except (OSError, ElementTree.ParseError):
        return 1
    return max(sum(int(el.attrib.get("quantity", 1) or 1)
                   for el in root if el.tag in ("host", "node")), 1)


def _split_rest(argv: list) -> tuple:
    """Split the fleet argv at the first ``--``: argparse sees the
    head, the tail goes verbatim to the child (argparse.REMAINDER is
    famously greedy around optionals, so the split is manual)."""
    if "--" in argv:
        i = argv.index("--")
        return list(argv[:i]), list(argv[i + 1:])
    return list(argv), []


def _rss_weight(args, hosts: int) -> int:
    """The admission RSS weight (MiB) of one member: an explicit
    --rss-mb always wins; otherwise --mem-bytes-per-host (measured
    per-host state bytes from the memscope census) x the member's
    host count, rounded up — so admission bounds concurrent footprint
    by what a run MEASURES, not by a static host-count proxy."""
    if args.rss_mb or not args.mem_bytes_per_host:
        return args.rss_mb
    return -(-hosts * args.mem_bytes_per_host // (1 << 20))


def _auto_id(queue, stem: str) -> str:
    taken = set(queue.fold()) if queue.exists() else set()
    if stem not in taken:
        return stem
    i = 2
    while f"{stem}-{i}" in taken:
        i += 1
    return f"{stem}-{i}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="shadow_tpu fleet",
        description="crash-safe sweep scheduler (docs/fleet.md)")
    sub = p.add_subparsers(dest="cmd_name", required=True)

    ps = sub.add_parser("submit", help="durably enqueue one run")
    ps.add_argument("queue", help="queue directory")
    ps.add_argument("config", nargs="?",
                    help="scenario XML (omit with --cmd)")
    ps.add_argument("--id", default=None,
                    help="run id (default: config basename, "
                         "deduplicated)")
    ps.add_argument("--cmd", action="store_true",
                    help="raw command mode: everything after -- is "
                         "the child argv (retries re-run from "
                         "scratch; no managed checkpoint/digest)")
    ps.add_argument("--hosts", type=int, default=0,
                    help="admission weight (default: parsed from the "
                         "XML; 1 for --cmd)")
    ps.add_argument("--rss-mb", type=int, default=0,
                    help="declared peak RSS for admission control")
    ps.add_argument("--mem-bytes-per-host", type=int, default=0,
                    metavar="BYTES",
                    help="measured per-host state bytes (the memscope "
                         "census — tools/capacity_plan.py or a "
                         "--perf run's state_bytes_per_host): the "
                         "admission RSS weight becomes hosts x this, "
                         "so the scheduler bounds concurrent runs by "
                         "MEASURED footprint instead of raw host "
                         "counts. Explicit --rss-mb wins")
    ps.add_argument("--max-retries", type=int, default=3,
                    help="crashes before quarantine (default 3)")
    ps.add_argument("--checkpoint-every", type=float, default=10.0,
                    metavar="SEC",
                    help="child checkpoint cadence (default 10)")
    ps.add_argument("--no-digest", action="store_true",
                    help="skip the per-run determinism digest chain")
    ps.add_argument("--digest-every", type=int, default=0,
                    metavar="WINDOWS")
    ps.add_argument("--perf", nargs="?", const="", default=None,
                    metavar="LEDGER",
                    help="append a per-run perf-ledger entry on "
                         "completion (child --perf; default ledger "
                         "path unless LEDGER given). Resumed "
                         "attempts skip the append, as documented in "
                         "docs/performance.md")
    ps.add_argument("--netscope", action="store_true",
                    help="network observatory (obs.netscope): the "
                         "child streams its per-window network "
                         "time-series into the run directory "
                         "(<run>/netscope.jsonl); `fleet status "
                         "--ensemble` folds the streams into "
                         "cross-run percentile curves")
    ps.add_argument("--batch", default=None, metavar="GROUP",
                    help="vmapped-batch group (serving.batch): every "
                         "member submitted under GROUP executes in "
                         "ONE child as lanes of one compiled program "
                         "— one compile, N executions — while keeping "
                         "its own journal state and digest chain. "
                         "Members must share one compiled shape "
                         "(identical EngineConfig); batch retries "
                         "re-run the whole group from scratch (no "
                         "managed checkpoint). docs/serving.md")
    ps.add_argument("--seeds", default=None, metavar="S1,S2,...",
                    help="with --batch: submit one member per seed "
                         "from this one XML (ids <id>-s<seed>)")
    ps.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="child environment override "
                                        "(repeatable)")
    ps.epilog = ("everything after `--` goes verbatim to the child: "
                 "extra CLI args in config mode (--seed, --fault, "
                 "--engine-caps ...), the command itself in --cmd "
                 "mode")

    pr = sub.add_parser("run", help="drain the queue (restartable)")
    pr.add_argument("queue")
    pr.add_argument("--workers", type=int, default=2)
    pr.add_argument("--max-hosts", type=int, default=0,
                    help="admission cap on CONCURRENT simulated "
                         "hosts (0 = unbounded)")
    pr.add_argument("--max-rss-mb", type=int, default=0)
    pr.add_argument("--hang-timeout", type=float, default=900.0,
                    metavar="SEC",
                    help="watchdog: SIGKILL a run with no progress "
                         "signals for this long (default 900 — must "
                         "exceed the cold XLA compile)")
    pr.add_argument("--backoff", type=float, default=1.0, metavar="SEC")
    pr.add_argument("--backoff-cap", type=float, default=60.0,
                    metavar="SEC")
    pr.add_argument("--grace", type=float, default=60.0, metavar="SEC",
                    help="preemption: wall given to children to "
                         "checkpoint after SIGTERM before SIGKILL")
    pr.add_argument("--metrics", default=None, metavar="FILE",
                    help="write fleet.* metrics (obs.metrics) to FILE")
    pr.add_argument("--python", default=None,
                    help="interpreter for child runs")
    pr.add_argument("--aot-cache", default=None, metavar="DIR",
                    help="persistent AOT executable cache shared by "
                         "every child (serving.aotcache): a sweep's "
                         "repeated shapes compile once and load in "
                         "seconds afterwards (docs/serving.md)")
    pr.add_argument("--prewarm", action="store_true",
                    help="with --aot-cache: fingerprint each queued "
                         "config run's compiled shape headlessly, "
                         "dedup shapes across the sweep, and compile "
                         "each distinct shape ONCE before its runs "
                         "admit — workers open on a warm cache "
                         "(serving.prewarm; docs/serving.md)")
    pr.add_argument("--prewarm-jobs", type=int, default=1,
                    metavar="N",
                    help="concurrent shape probe/compile children "
                         "(default 1)")

    pt = sub.add_parser("status", help="fold the journal into a table")
    pt.add_argument("queue")
    pt.add_argument("--json", action="store_true")
    pt.add_argument("--ensemble", action="store_true",
                    help="fold the runs' netscope streams "
                         "(<run>/netscope.jsonl — submit --netscope) "
                         "into cross-run percentile curves: pooled "
                         "p50/p90/p99 + per-run tails per kind "
                         "(obs.netscope.ensemble)")

    head, rest = _split_rest(list(argv) if argv is not None
                             else sys.argv[1:])
    args = p.parse_args(head)
    if rest and args.cmd_name != "submit":
        p.error(f"`{args.cmd_name}` takes no `--` tail")
    from .queue import Queue, make_spec
    from .worker import _cfg_bytes

    if args.cmd_name == "submit":
        q = Queue(args.queue)
        env = {}
        for kv in args.env:
            k, eq, v = kv.partition("=")
            if not eq:
                p.error(f"--env {kv!r} is not K=V")
            env[k] = v
        if args.seeds and not args.batch:
            p.error("--seeds expands a vmapped-batch group; give the "
                    "group a name with --batch GROUP")
        if args.cmd:
            if not rest:
                p.error("--cmd needs a command after --")
            if args.batch:
                p.error("--batch members are config runs (the batch "
                        "child stacks their engine state on one "
                        "vmapped axis; an arbitrary command has no "
                        "such state)")
            # durability/perf args are managed for CONFIG runs only;
            # silently accepting them here would e.g. drop the user's
            # expected ledger entries without a trace
            if (args.checkpoint_every != 10.0 or args.no_digest
                    or args.digest_every or args.perf is not None
                    or args.netscope):
                p.error("--cmd runs execute the command verbatim: "
                        "--checkpoint-every/--no-digest/--digest-every"
                        "/--perf/--netscope apply to config runs only "
                        "(put the equivalent flags in the command "
                        "itself)")
            if args.config:
                rest = [args.config] + rest
            rid = args.id or _auto_id(q, "cmd")
            spec = make_spec(rid, cmd=rest, env=env,
                             hosts=args.hosts or 1,
                             rss_mb=_rss_weight(args, args.hosts or 1),
                             max_retries=args.max_retries)
        else:
            if not args.config:
                p.error("submit needs a scenario XML (or --cmd)")
            # the worker appends the MANAGED durability args after the
            # tail; argparse last-wins would silently discard any the
            # user put there — refuse instead
            managed = {"--checkpoint", "--checkpoint-every",
                       "--checkpoint-keep", "--digest",
                       "--digest-every", "--resume", "--perf",
                       "--until-complete", "--max-retries",
                       "--retry-backoff"}
            clash = [a for a in rest
                     if a in managed
                     or a.split("=", 1)[0] in managed]
            if clash:
                p.error(f"{' '.join(sorted(set(clash)))} in the `--` "
                        "tail: the fleet manages checkpoint/digest/"
                        "resume/perf for config runs — use the submit "
                        "options (--checkpoint-every, --digest-every, "
                        "--no-digest, --perf) instead")
            stem = os.path.splitext(os.path.basename(args.config))[0]
            rid = args.id or _auto_id(q, stem)
            if args.batch:
                # batch children run the group's configs verbatim
                # (serving.batch takes no per-member extra args) — a
                # `--` tail would be silently dropped; refuse instead
                if rest:
                    p.error("--batch members take no `--` tail (the "
                            "batch child runs the XMLs verbatim; "
                            "vary members by --seeds or by config)")
                if args.checkpoint_every != 10.0:
                    p.error("--checkpoint-every with --batch: batch "
                            "children carry no checkpoint store — a "
                            "crashed group re-runs from scratch "
                            "(docs/serving.md)")
                seeds = [None]
                if args.seeds:
                    try:
                        seeds = [int(s) for s in args.seeds.split(",")
                                 if s.strip()]
                    except ValueError:
                        p.error(f"--seeds {args.seeds!r}: not "
                                "integers")
                    if not seeds:
                        p.error("--seeds names no seeds")
                # group consistency: ONE batch child runs the whole
                # group, in exactly one of two forms (worker.
                # build_batch_argv) — one XML x N seeds, or one XML
                # per member. Submissions into an existing group must
                # keep its form, and the seeded form must keep its
                # one XML (by content; the queue copies per member)
                if q.exists():
                    prior = [st.spec for st in q.fold().values()
                             if st.spec.get("batch") == args.batch]
                    if prior:
                        seeded = args.seeds is not None
                        was = prior[0].get("batch_seed") is not None
                        if seeded != was:
                            form = "seeded" if was else "per-XML"
                            p.error(
                                f"batch group {args.batch!r} already "
                                f"holds {form} members; a group "
                                "mixes no forms (one child, one argv "
                                "shape — docs/serving.md)")
                        if not seeded:
                            # the batch child names per-member
                            # outputs by config stem (serving.batch);
                            # a colliding stem would only fail at RUN
                            # time as a usage-error quarantine of the
                            # whole group — refuse it here instead
                            stems = {os.path.splitext(
                                os.path.basename(s["config"]))[0]
                                for s in prior}
                            if stem in stems:
                                p.error(
                                    f"batch group {args.batch!r} "
                                    f"already holds a member whose "
                                    f"config is named {stem!r} — the "
                                    "batch child names per-member "
                                    "outputs by config basename, so "
                                    "stems must be distinct "
                                    "(docs/serving.md)")
                        if seeded and _cfg_bytes(
                                prior[0]["config"]) not in (
                                None, _cfg_bytes(args.config)):
                            p.error(
                                f"batch group {args.batch!r} is the "
                                "one-XML-many-seeds form and this XML "
                                "differs from the group's — seeded "
                                "members all run ONE config "
                                "(docs/serving.md)")
                        # the ONE batch child runs with the group's
                        # digest/perf/env settings; silently running
                        # a member at another member's settings would
                        # drop its expected ledger entry / cadence
                        # without a trace (the PR 7 submit-gate
                        # principle) — refuse instead
                        group_knobs = {
                            "digest": not args.no_digest,
                            "digest_every": int(args.digest_every),
                            "perf": args.perf,
                            "netscope": bool(args.netscope),
                            "env": env}
                        prior_knobs = {
                            # bool-normalized: a pre-netscope journal
                            # spec has no key at all (None == off)
                            k: (bool(prior[0].get(k))
                                if k == "netscope"
                                else prior[0].get(k))
                            for k in group_knobs}
                        if prior_knobs != group_knobs:
                            diff = [k for k in group_knobs
                                    if group_knobs[k]
                                    != prior_knobs[k]]
                            p.error(
                                f"batch group {args.batch!r}: "
                                f"{', '.join(diff)} differ(s) from "
                                "the group's — one child runs the "
                                "whole group, so digest/perf/env "
                                "settings are group-wide "
                                "(docs/serving.md)")
                rids = []
                n_hosts = args.hosts or _count_hosts(args.config)
                for seed in seeds:
                    mid = rid if seed is None else f"{rid}-s{seed}"
                    spec = make_spec(
                        mid, config=args.config, env=env,
                        hosts=n_hosts,
                        rss_mb=_rss_weight(args, n_hosts),
                        max_retries=args.max_retries,
                        digest=not args.no_digest,
                        digest_every=args.digest_every,
                        perf=args.perf, netscope=args.netscope,
                        batch=args.batch, batch_seed=seed)
                    try:
                        q.submit(spec)
                    except (ValueError, OSError) as e:
                        p.error(str(e))
                    rids.append(mid)
                print(f"submitted {' '.join(rids)} -> {args.queue} "
                      f"(batch group {args.batch})")
                return 0
            n_hosts = args.hosts or _count_hosts(args.config)
            spec = make_spec(
                rid, config=args.config, args=rest, env=env,
                hosts=n_hosts,
                rss_mb=_rss_weight(args, n_hosts),
                max_retries=args.max_retries,
                checkpoint_every=args.checkpoint_every,
                digest=not args.no_digest,
                digest_every=args.digest_every, perf=args.perf,
                netscope=args.netscope)
        try:
            q.submit(spec)
        except (ValueError, OSError) as e:
            p.error(str(e))
        print(f"submitted {rid} -> {args.queue}")
        return 0

    if args.cmd_name == "run":
        from ..obs import metrics as MT
        from .scheduler import Scheduler, SchedulerLockError
        q = Queue(args.queue)
        if not q.exists():
            p.error(f"{args.queue!r} holds no queue journal — submit "
                    "runs first")
        if args.prewarm and not args.aot_cache:
            p.error("--prewarm compiles shapes INTO the persistent "
                    "executable cache; give it one with "
                    "--aot-cache DIR")
        sched = Scheduler(
            q, workers=args.workers, max_hosts=args.max_hosts,
            max_rss_mb=args.max_rss_mb,
            hang_timeout_s=args.hang_timeout, backoff_s=args.backoff,
            backoff_cap_s=args.backoff_cap, grace_s=args.grace,
            python=args.python, aot_cache=args.aot_cache,
            prewarm=args.prewarm, prewarm_jobs=args.prewarm_jobs)
        # SIGTERM/SIGINT = preempt: children checkpoint + requeue,
        # we exit 75; the next `fleet run` resumes the sweep
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda s, f: sched.request_preempt())
        own_mt = False
        if args.metrics and not MT.ENABLED:
            MT.install(args.metrics)
            own_mt = True
        try:
            return sched.run()
        except SchedulerLockError as e:
            sys.stderr.write(f"shadow_tpu: fleet: {e}\n")
            return 1
        finally:
            if own_mt:
                MT.finish()

    # status
    q = Queue(args.queue)
    states = q.fold()
    pw = q.prewarm_fold()
    ens = None
    if args.ensemble:
        # fold every run's netscope stream (its last record carries
        # the run's cumulative histogram) into cross-run curves —
        # runs without a stream (not submitted --netscope, or not
        # started yet) are skipped, and named
        from ..obs import netscope as NSC
        tables, members, missing = [], [], []
        for rid in states:
            path = q.netscope_path(rid)
            _, recs = (NSC.read_stream(path)
                       if os.path.exists(path) else ({}, []))
            if recs:
                tables.append(recs[-1]["hist"])
                members.append(rid)
            else:
                missing.append(rid)
        ens = NSC.ensemble(tables)
        if ens:
            ens["members"] = members
        if missing:
            ens = ens or {}
            ens["missing"] = missing
    if args.json:
        out = {rid: {**st.spec, "state": st.state,
                     "started": st.started, "crashes": st.crashes,
                     "preemptions": st.preemptions,
                     "reclaims": st.reclaims,
                     "last_rc": st.last_rc,
                     "last_cause": st.last_cause,
                     "quarantine_cause": st.quarantine_cause}
               for rid, st in states.items()}
        if pw["shapes"]:
            # shapes warmed vs pending (serving.prewarm journal
            # records); "_shapes" cannot collide with a run id — the
            # table is keyed by path-safe ids the submitter chose
            out["_shapes"] = pw
        if ens is not None:
            out["_ensemble"] = ens
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    if not states:
        print(f"{args.queue}: empty queue")
        return 0
    wid = max(len(r) for r in states) + 2
    print(f"{'run':<{wid}}{'state':<13}{'starts':<8}{'crashes':<9}"
          "cause")
    for rid, st in states.items():
        cause = st.quarantine_cause or st.last_cause or ""
        batch = st.spec.get("batch")
        if batch:
            cause = (f"[batch {batch}] {cause}" if cause
                     else f"[batch {batch}]")
        print(f"{rid:<{wid}}{st.state:<13}{st.started:<8}"
              f"{st.crashes:<9}{cause}")
    counts = {}
    for st in states.values():
        counts[st.state] = counts.get(st.state, 0) + 1
    print("total: " + ", ".join(f"{v} {k}"
                                for k, v in sorted(counts.items())))
    if pw["shapes"]:
        sc = {}
        for st in pw["shapes"].values():
            sc[st] = sc.get(st, 0) + 1
        print("shapes: " + ", ".join(
            f"{v} {k}" for k, v in sorted(sc.items())))
        for fp, st in sorted(pw["shapes"].items()):
            members = sorted(r for r, f in pw["runs"].items()
                             if f == fp)
            print(f"  {fp}  {st:<10} "
                  + (" ".join(members[:6])
                     + (f" +{len(members) - 6}" if len(members) > 6
                        else "")))
    if ens is not None:
        if ens.get("kinds"):
            print(f"ensemble: {ens['runs']} runs "
                  f"({' '.join(ens['members'])})")
            for name, k in ens["kinds"].items():
                lanes = " ".join(str(v) for v in k["lane_p99_us"])
                print(f"  {name:<12}n={k['count']:<9}"
                      f"p50={k['p50_us']}us p90={k['p90_us']}us "
                      f"p99={k['p99_us']}us  per-run p99: {lanes}")
        else:
            print("ensemble: no netscope streams (submit with "
                  "--netscope)")
        if ens.get("missing"):
            print("  no stream: " + " ".join(ens["missing"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
