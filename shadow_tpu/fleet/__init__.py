"""Fleet: a crash-safe sweep scheduler over the durable-run substrate.

The reference's Master/Slave split exists so one controller keeps many
workers making progress through failures (shd-master.c / shd-slave.c);
PR 5 built the inverse half here — ONE run that survives any crash
(engine.supervisor + engine.checkpoint + the digest rewind). This
package generalizes that from one run to a fleet: a durable on-disk
run queue (queue), a worker slot that executes each run as a
supervised child process (worker), and a scheduler that drains the
queue through crashes of the runs AND of itself (scheduler).

Guarantees (docs/fleet.md, proven by tests/test_fleet.py):

- **durable**: every queue transition is one fsync'd JSONL journal
  line (torn-line tolerant — obs.ledger); claims are O_EXCL files;
  SIGKILLing workers and the scheduler at arbitrary instants loses no
  run and duplicates no result;
- **equivalent**: a sweep interrupted anywhere completes on restart
  with every run's digest chain byte-identical to an uninterrupted
  reference sweep (the PR 5 claim, lifted to fleets) — and the chains
  are independent of worker count and scheduling order;
- **isolated**: a deterministic crasher is retried with exponential
  backoff and then QUARANTINED with its crash-cause journal, while
  the rest of the queue keeps draining;
- **bounded**: admission control caps concurrent simulated hosts /
  declared RSS, so an oversized scenario waits as "queued" instead of
  OOMing the box (it runs alone once the box is free);
- **preemptible**: SIGTERM makes workers checkpoint at the next chunk
  boundary (engine.sim.Preempted, exit 75) and requeues their runs as
  resumable — scheduler restart ≡ uninterrupted sweep.

CLI: ``shadow_tpu fleet submit|run|status`` (fleet.cli).
"""

from .queue import Queue, RunState  # noqa: F401
from .scheduler import Scheduler    # noqa: F401
