"""Worker slot: one run attempt as a supervised child process.

The single-run supervisor (engine.supervisor) re-execs ONE command
line until it completes; a fleet slot is the same idea held by the
scheduler: build the child CLI (managed durability args for config
runs — per-run checkpoint store, digest chain, ``--resume latest`` on
re-dispatch), spawn it in its own session (so a takeover can kill the
whole process group of an orphaned run), stream its stdout to the
run's log, and watch its wall-clock PROGRESS — checkpoint-pointer /
digest-chain / log mtimes — so a hung run is diagnosed and SIGKILLed
instead of wedging the slot (the shim watchdog contract, one level
up). Every exit is classified (engine.supervisor.classify_exit) and
appended to the run's crash-cause journal.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from ..engine.supervisor import EXIT_PREEMPTED, CrashLog, classify_exit
from .queue import Queue


# claim-gate wrapper: the spawned process execs the real child ONLY
# once the claim file names its own pid — so a scheduler SIGKILLed
# inside the spawn→claim window leaves a gate that times out and
# exits 75 on its own, never an untracked live orphan racing a
# re-dispatched attempt over the same run directory. (A SIGSTOP
# handshake cannot do this: stopping before exec deadlocks Popen's
# exec-errpipe read in the parent.)
_CLAIM_GATE = """\
import json, os, sys, time
claim, me, end = sys.argv[1], str(os.getpid()), time.time() + 30
ok = False
while not ok and time.time() < end:
    try:
        ok = str(json.load(open(claim)).get("pid")) == me
    except Exception:
        ok = False
    if not ok:
        time.sleep(0.01)
if not ok:
    sys.exit(75)
os.execvp(sys.argv[2], sys.argv[2:])
"""


def build_child_argv(queue: Queue, spec: dict, resume: bool,
                     python: str = None) -> list:
    """The child command line for one attempt. Config runs get the
    managed durability args; cmd runs are verbatim (their retries
    re-run from scratch — the spec chose that mode)."""
    if spec.get("cmd"):
        return list(spec["cmd"])
    rid = spec["id"]
    argv = ([python or sys.executable, "-m", "shadow_tpu",
             os.path.abspath(spec["config"])]
            + list(spec.get("args") or [])
            + ["--checkpoint", os.path.abspath(queue.store_base(rid)),
               "--checkpoint-every", str(spec["checkpoint_every"])])
    if spec.get("digest", True):
        argv += ["--digest", os.path.abspath(queue.digest_path(rid))]
        if spec.get("digest_every"):
            argv += ["--digest-every", str(spec["digest_every"])]
    if spec.get("perf") is not None:
        argv += (["--perf", spec["perf"]] if spec["perf"]
                 else ["--perf"])
    if resume:
        argv += ["--resume", "latest"]
    return argv


class Slot:
    """One executing attempt. The scheduler polls it; it owns the
    child process, the claim's pid refresh, and the exit record."""

    def __init__(self, queue: Queue, state, python: str = None,
                 log=None):
        self.queue = queue
        self.spec = state.spec
        self.run_id = state.spec["id"]
        self.attempt = state.started + 1
        self.resume = bool(state.resume and state.spec.get("config"))
        self.log = log or (lambda m: sys.stderr.write(
            f"shadow_tpu: fleet: {m}\n"))
        self.hung = False           # watchdog SIGKILLed it
        self.preempting = False     # we SIGTERMed it (scheduler preempt)
        self.preempt_killed = False  # grace expired -> SIGKILL
        self.crash_log = CrashLog(queue.crash_log_path(self.run_id))

        rd = queue.run_dir(self.run_id)
        os.makedirs(rd, exist_ok=True)
        argv = build_child_argv(queue, self.spec, self.resume, python)
        env = dict(os.environ)
        env.update(self.spec.get("env") or {})
        env["SHADOW_TPU_FLEET_RUN_DIR"] = os.path.abspath(rd)
        self._stdout = open(queue.log_path(self.run_id), "ab")
        self.t0 = time.time()
        self.last_progress = self.t0
        # own session (killpg-able takeover), gated behind the claim:
        # the wrapper execs the real argv only once the claim names
        # its pid (start() publishes it); exec failures of a bad
        # executable surface as a crash exit in run.log
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-c", _CLAIM_GATE,
                 os.path.abspath(queue.claim_path(self.run_id))] + argv,
                stdout=self._stdout, stderr=subprocess.STDOUT,
                env=env, start_new_session=True)
        except OSError:
            self._stdout.close()       # no slot survives to close it
            raise
        self.argv = argv          # the REAL child argv (claims,
        #   crash records, recovery cmdline match — post-exec the
        #   process's /proc cmdline equals exactly this)

    def start(self):
        """Open the claim gate: publish the claim with the child pid.
        If the claim cannot be written, kill the gate — it would time
        out and exit 75 on its own anyway."""
        try:
            self.refresh_claim()
        except OSError:
            self.kill()
            raise

    # --- claim pid refresh (recovery needs the CHILD pid) ---
    def claim_meta(self) -> dict:
        return {"scheduler_pid": os.getpid(), "pid": self.proc.pid,
                "attempt": self.attempt, "argv": self.argv}

    def refresh_claim(self):
        """Re-publish the claim with the child pid (the claim was
        taken before the pid existed): atomic replace, so a reader
        always sees a complete claim."""
        import json
        path = self.queue.claim_path(self.run_id)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"t": round(time.time(), 3),
                       **self.claim_meta()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # --- progress / watchdog ---
    def progress_paths(self) -> list:
        q, rid = self.queue, self.run_id
        # heartbeat FIRST: checkpoints/digests are sim-paced (a slow
        # box legitimately goes long wall stretches without either),
        # but engine.sim touches <run_dir>/heartbeat once per chunk
        # whenever SHADOW_TPU_FLEET_RUN_DIR is set — the wall-paced
        # liveness signal the watchdog actually needs
        return [os.path.join(q.run_dir(rid), "heartbeat"),
                q.store_base(rid) + ".latest", q.digest_path(rid),
                q.log_path(rid)]

    def check_progress(self) -> float:
        """Newest progress timestamp: spawn time or the latest mtime
        of the run's checkpoint pointer / digest chain / stdout log —
        the signals a LIVE run refreshes and a hung one cannot."""
        for p in self.progress_paths():
            try:
                m = os.path.getmtime(p)
            except OSError:
                continue
            if m > self.last_progress:
                self.last_progress = m
        return self.last_progress

    # --- signals ---
    def preempt(self):
        """Cooperative preemption: SIGTERM — a config run checkpoints
        at its next chunk boundary and exits 75 (engine.sim.Preempted);
        a cmd run dies and is simply re-run later."""
        if not self.preempting:
            self.preempting = True
            try:
                os.kill(self.proc.pid, signal.SIGTERM)
            except OSError:
                pass

    def kill(self):
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except OSError:
            try:
                self.proc.kill()
            except OSError:
                pass

    # --- exit ---
    def classify(self, rc: int) -> tuple:
        """(kind, cause): kind in done|preempt|crash. Any nonzero
        exit while WE were preempting is a preemption, not a crash —
        the scheduler asked for it."""
        if rc == 0:
            return "done", "completed"
        if rc == EXIT_PREEMPTED:
            return "preempt", "preempted (snapshot saved)"
        if self.preempting:
            return "preempt", ("preempted (grace expired; SIGKILLed)"
                               if self.preempt_killed else
                               f"preempted ({classify_exit(rc)})")
        if self.hung:
            return "crash", ("hung (no progress; SIGKILLed by "
                             "watchdog)")
        return "crash", classify_exit(rc)

    def record_exit(self, rc: int, kind: str, cause: str):
        """Per-attempt crash-cause record (the engine.supervisor
        journal shape, one per attempt, fsync'd + torn-tolerant)."""
        self.crash_log.append({
            "attempt": self.attempt, "exit_status": rc,
            "kind": kind, "cause": cause,
            "wall_s": round(time.time() - self.t0, 3),
            "resumed": self.resume,
            # drop only a leading interpreter path (config runs); a
            # cmd run's argv[0] IS the program — the post-mortem
            # needs it
            "argv": (self.argv[1:] if self.spec.get("config")
                     else self.argv),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        })

    def close(self):
        try:
            self._stdout.close()
        except OSError:
            pass
