"""Worker slot: one run attempt as a supervised child process.

The single-run supervisor (engine.supervisor) re-execs ONE command
line until it completes; a fleet slot is the same idea held by the
scheduler: build the child CLI (managed durability args for config
runs — per-run checkpoint store, digest chain, ``--resume latest`` on
re-dispatch), spawn it in its own session (so a takeover can kill the
whole process group of an orphaned run), stream its stdout to the
run's log, and watch its wall-clock PROGRESS — checkpoint-pointer /
digest-chain / log mtimes — so a hung run is diagnosed and SIGKILLed
instead of wedging the slot (the shim watchdog contract, one level
up). Every exit is classified (engine.supervisor.classify_exit) and
appended to the run's crash-cause journal.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from ..engine.supervisor import EXIT_PREEMPTED, CrashLog, classify_exit
from .queue import Queue


# claim-gate wrapper: the spawned process execs the real child ONLY
# once the claim file names its own pid — so a scheduler SIGKILLed
# inside the spawn→claim window leaves a gate that times out and
# exits 75 on its own, never an untracked live orphan racing a
# re-dispatched attempt over the same run directory. (A SIGSTOP
# handshake cannot do this: stopping before exec deadlocks Popen's
# exec-errpipe read in the parent.)
_CLAIM_GATE = """\
import json, os, sys, time
claim, me, end = sys.argv[1], str(os.getpid()), time.time() + 30
ok = False
while not ok and time.time() < end:
    try:
        ok = str(json.load(open(claim)).get("pid")) == me
    except Exception:
        ok = False
    if not ok:
        time.sleep(0.01)
if not ok:
    sys.exit(75)
os.execvp(sys.argv[2], sys.argv[2:])
"""


def build_child_argv(queue: Queue, spec: dict, resume: bool,
                     python: str = None, aot_cache: str = None) -> list:
    """The child command line for one attempt. Config runs get the
    managed durability args (plus ``--aot-cache`` when the scheduler
    serves one — serving.aotcache); cmd runs are verbatim (their
    retries re-run from scratch — the spec chose that mode; they get
    the cache via SHADOW_TPU_AOT_CACHE in their environment)."""
    if spec.get("cmd"):
        return list(spec["cmd"])
    rid = spec["id"]
    argv = ([python or sys.executable, "-m", "shadow_tpu",
             os.path.abspath(spec["config"])]
            + list(spec.get("args") or [])
            + ["--checkpoint", os.path.abspath(queue.store_base(rid)),
               "--checkpoint-every", str(spec["checkpoint_every"])])
    if spec.get("digest", True):
        argv += ["--digest", os.path.abspath(queue.digest_path(rid))]
        if spec.get("digest_every"):
            argv += ["--digest-every", str(spec["digest_every"])]
    if spec.get("perf") is not None:
        argv += (["--perf", spec["perf"]] if spec["perf"]
                 else ["--perf"])
    if spec.get("netscope"):
        argv += ["--netscope",
                 os.path.abspath(queue.netscope_path(rid))]
    if aot_cache:
        argv += ["--aot-cache", os.path.abspath(aot_cache)]
    if resume:
        argv += ["--resume", "latest"]
    return argv


def _cfg_bytes(path: str) -> bytes | None:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def build_batch_argv(queue: Queue, specs: list, python: str = None,
                     aot_cache: str = None) -> list:
    """The ONE child command line executing a whole vmapped-batch
    group (``python -m shadow_tpu batch`` — serving.batch):
    per-member digest chains land in each member's run directory via
    --digest-paths, exactly where an individual run's would. Two
    forms, decided by the specs: every member carrying a batch_seed
    = one XML x N seeds; otherwise one XML per member."""
    py = python or sys.executable
    seeds = [s.get("batch_seed") for s in specs]
    seeded = [sd is not None for sd in seeds]
    # backstop for the submit-time group-consistency gate (fleet.cli):
    # a malformed group must refuse here — the scheduler records the
    # OSError as a per-member spawn failure — never silently run the
    # wrong XML or drop seeds. OSError because that is the spawn-
    # failure contract the scheduler already handles.
    if any(seeded) and not all(seeded):
        raise OSError(
            "batch group mixes seeded and unseeded members — one "
            "child runs one argv shape (docs/serving.md); resubmit "
            "the group in one form")
    if all(seeded):
        if len(specs) > 1:
            blobs = {_cfg_bytes(s["config"]) for s in specs}
            blobs.discard(None)
            if len(blobs) > 1:
                raise OSError(
                    "batch group is the one-XML-many-seeds form but "
                    "its members' XMLs differ — seeded members all "
                    "run ONE config (docs/serving.md)")
        argv = [py, "-m", "shadow_tpu", "batch",
                os.path.abspath(specs[0]["config"]),
                "--seeds", ",".join(str(sd) for sd in seeds)]
    else:
        argv = ([py, "-m", "shadow_tpu", "batch"]
                + [os.path.abspath(s["config"]) for s in specs])
    if specs[0].get("digest", True):
        argv += ["--digest-paths",
                 ",".join(os.path.abspath(queue.digest_path(s["id"]))
                          for s in specs)]
        if specs[0].get("digest_every"):
            argv += ["--digest-every",
                     str(specs[0]["digest_every"])]
    if specs[0].get("perf") is not None:
        argv += (["--perf", specs[0]["perf"]] if specs[0]["perf"]
                 else ["--perf"])
    if specs[0].get("netscope"):
        # per-lane time-series land in each member's run directory,
        # exactly where an individual run's would (like the digest
        # chains above)
        argv += ["--netscope-paths",
                 ",".join(os.path.abspath(
                     queue.netscope_path(s["id"])) for s in specs)]
    if aot_cache:
        argv += ["--aot-cache", os.path.abspath(aot_cache)]
    return argv


class Slot:
    """One executing attempt. The scheduler polls it; it owns the
    child process, the claim's pid refresh, and the exit record."""

    def __init__(self, queue: Queue, state, python: str = None,
                 log=None, aot_cache: str = None):
        self.queue = queue
        self.spec = state.spec
        self.run_id = state.spec["id"]
        self.attempt = state.started + 1
        self.resume = bool(state.resume and state.spec.get("config"))
        self.log = log or (lambda m: sys.stderr.write(
            f"shadow_tpu: fleet: {m}\n"))
        self.hung = False           # watchdog SIGKILLed it
        self.preempting = False     # we SIGTERMed it (scheduler preempt)
        self.preempt_killed = False  # grace expired -> SIGKILL
        self.crash_log = CrashLog(queue.crash_log_path(self.run_id))

        rd = queue.run_dir(self.run_id)
        os.makedirs(rd, exist_ok=True)
        argv = build_child_argv(queue, self.spec, self.resume, python,
                                aot_cache=aot_cache)
        env = dict(os.environ)
        env.update(self.spec.get("env") or {})
        env["SHADOW_TPU_FLEET_RUN_DIR"] = os.path.abspath(rd)
        if aot_cache:
            # cmd runs (arbitrary argv — bench lines, tools) pick the
            # persistent executable cache up from the environment
            # (serving.aotcache.active); config runs also get the
            # explicit --aot-cache flag above
            env["SHADOW_TPU_AOT_CACHE"] = os.path.abspath(aot_cache)
        self._stdout = open(queue.log_path(self.run_id), "ab")
        self.t0 = time.time()
        self.last_progress = self.t0
        # own session (killpg-able takeover), gated behind the claim:
        # the wrapper execs the real argv only once the claim names
        # its pid (start() publishes it); exec failures of a bad
        # executable surface as a crash exit in run.log
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-c", _CLAIM_GATE,
                 os.path.abspath(queue.claim_path(self.run_id))] + argv,
                stdout=self._stdout, stderr=subprocess.STDOUT,
                env=env, start_new_session=True)
        except OSError:
            self._stdout.close()       # no slot survives to close it
            raise
        self.argv = argv          # the REAL child argv (claims,
        #   crash records, recovery cmdline match — post-exec the
        #   process's /proc cmdline equals exactly this)

    def start(self):
        """Open the claim gate: publish the claim with the child pid.
        If the claim cannot be written, kill the gate — it would time
        out and exit 75 on its own anyway."""
        try:
            self.refresh_claim()
        except OSError:
            self.kill()
            raise

    # --- claim pid refresh (recovery needs the CHILD pid) ---
    def claim_meta(self) -> dict:
        return {"scheduler_pid": os.getpid(), "pid": self.proc.pid,
                "attempt": self.attempt, "argv": self.argv}

    def refresh_claim(self):
        """Re-publish the claim with the child pid (the claim was
        taken before the pid existed): atomic replace, so a reader
        always sees a complete claim."""
        import json
        path = self.queue.claim_path(self.run_id)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"t": round(time.time(), 3),
                       **self.claim_meta()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # --- progress / watchdog ---
    def progress_paths(self) -> list:
        q, rid = self.queue, self.run_id
        # heartbeat FIRST: checkpoints/digests are sim-paced (a slow
        # box legitimately goes long wall stretches without either),
        # but engine.sim touches <run_dir>/heartbeat once per chunk
        # whenever SHADOW_TPU_FLEET_RUN_DIR is set — the wall-paced
        # liveness signal the watchdog actually needs
        return [os.path.join(q.run_dir(rid), "heartbeat"),
                q.store_base(rid) + ".latest", q.digest_path(rid),
                q.log_path(rid)]

    def check_progress(self) -> float:
        """Newest progress timestamp: spawn time or the latest mtime
        of the run's checkpoint pointer / digest chain / stdout log —
        the signals a LIVE run refreshes and a hung one cannot."""
        for p in self.progress_paths():
            try:
                m = os.path.getmtime(p)
            except OSError:
                continue
            if m > self.last_progress:
                self.last_progress = m
        return self.last_progress

    # --- signals ---
    def preempt(self):
        """Cooperative preemption: SIGTERM — a config run checkpoints
        at its next chunk boundary and exits 75 (engine.sim.Preempted);
        a cmd run dies and is simply re-run later."""
        if not self.preempting:
            self.preempting = True
            try:
                os.kill(self.proc.pid, signal.SIGTERM)
            except OSError:
                pass

    def kill(self):
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except OSError:
            try:
                self.proc.kill()
            except OSError:
                pass

    # --- exit ---
    def classify(self, rc: int) -> tuple:
        """(kind, cause): kind in done|preempt|crash. Any nonzero
        exit while WE were preempting is a preemption, not a crash —
        the scheduler asked for it."""
        if rc == 0:
            return "done", "completed"
        if rc == EXIT_PREEMPTED:
            return "preempt", "preempted (snapshot saved)"
        if self.preempting:
            return "preempt", ("preempted (grace expired; SIGKILLed)"
                               if self.preempt_killed else
                               f"preempted ({classify_exit(rc)})")
        if self.hung:
            return "crash", ("hung (no progress; SIGKILLed by "
                             "watchdog)")
        return "crash", classify_exit(rc)

    def record_exit(self, rc: int, kind: str, cause: str):
        """Per-attempt crash-cause record (the engine.supervisor
        journal shape, one per attempt, fsync'd + torn-tolerant)."""
        self.crash_log.append({
            "attempt": self.attempt, "exit_status": rc,
            "kind": kind, "cause": cause,
            "wall_s": round(time.time() - self.t0, 3),
            "resumed": self.resume,
            # drop only a leading interpreter path (config runs); a
            # cmd run's argv[0] IS the program — the post-mortem
            # needs it
            "argv": (self.argv[1:] if self.spec.get("config")
                     else self.argv),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        })

    def close(self):
        try:
            self._stdout.close()
        except OSError:
            pass


class BatchSlot(Slot):
    """One executing vmapped-batch GROUP (serving.batch): a single
    child process covering N member runs, each keeping its own
    journal state. The scheduler claims every member before spawning;
    the claim gate rides the FIRST member's claim file (one child,
    one gate). Batch children carry no checkpoint store — a crashed
    group re-runs from scratch, like a cmd run — so ``resume`` is
    always False and the watchdog's progress signals are the group
    heartbeat plus every member's digest chain."""

    def __init__(self, queue: Queue, states: list, python: str = None,
                 log=None, aot_cache: str = None):
        assert states, "a batch group needs at least one member"
        self.queue = queue
        self.states = list(states)
        self.specs = [st.spec for st in self.states]
        self.member_ids = [st.id for st in self.states]
        self.spec = dict(self.specs[0])
        # the group's admission weight is the members' sum (they run
        # concurrently as lanes of one program)
        self.spec["hosts"] = sum(s.get("hosts", 1) for s in self.specs)
        self.spec["rss_mb"] = sum(s.get("rss_mb", 0)
                                  for s in self.specs)
        self.run_id = self.member_ids[0]
        self.group = self.specs[0].get("batch")
        self.attempt = self.states[0].started + 1
        self.resume = False
        self.log = log or (lambda m: sys.stderr.write(
            f"shadow_tpu: fleet: {m}\n"))
        self.hung = False
        self.preempting = False
        self.preempt_killed = False
        self.crash_log = CrashLog(queue.crash_log_path(self.run_id))

        for rid in self.member_ids:
            os.makedirs(queue.run_dir(rid), exist_ok=True)
        argv = build_batch_argv(queue, self.specs, python,
                                aot_cache=aot_cache)
        env = dict(os.environ)
        env.update(self.specs[0].get("env") or {})
        env["SHADOW_TPU_FLEET_RUN_DIR"] = os.path.abspath(
            queue.run_dir(self.run_id))
        if aot_cache:
            env["SHADOW_TPU_AOT_CACHE"] = os.path.abspath(aot_cache)
        self._stdout = open(queue.log_path(self.run_id), "ab")
        self.t0 = time.time()
        self.last_progress = self.t0
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-c", _CLAIM_GATE,
                 os.path.abspath(queue.claim_path(self.run_id))]
                + argv,
                stdout=self._stdout, stderr=subprocess.STDOUT,
                env=env, start_new_session=True)
        except OSError:
            self._stdout.close()
            raise
        self.argv = argv

    def progress_paths(self) -> list:
        q = self.queue
        paths = [os.path.join(q.run_dir(self.run_id), "heartbeat"),
                 q.log_path(self.run_id)]
        for rid in self.member_ids:
            paths.append(q.digest_path(rid))
        return paths

    def record_exit(self, rc: int, kind: str, cause: str):
        self.crash_log.append({
            "attempt": self.attempt, "exit_status": rc,
            "kind": kind, "cause": cause,
            "wall_s": round(time.time() - self.t0, 3),
            "resumed": False,
            "batch": self.group, "members": self.member_ids,
            "argv": self.argv[1:],
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        })
