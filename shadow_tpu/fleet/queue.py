"""Durable on-disk run queue: fsync'd JSONL journal + atomic claims.

Layout under one queue directory ``Q``:

- ``Q/queue.jsonl`` — the journal: every transition (submit, start,
  exit, requeue, quarantine, reclaim) is ONE fsync'd JSON line
  (obs.ledger.jsonl_append), so a SIGKILL can lose nothing and tear
  at most the line in flight — which reads skip (torn-line tolerant,
  the same crash shape as the perf ledger and the digest chain). The
  queue's current state is a pure FOLD over the journal (fold()):
  there is no mutable state file to corrupt.
- ``Q/claims/<id>.claim`` — atomic claim file (O_EXCL) naming the
  scheduler + child pid executing a run; prevents double execution
  and lets a restarted scheduler find in-flight runs of a dead one.
- ``Q/runs/<id>/`` — the run's working directory: its checkpoint
  store (``ck.*`` — engine.checkpoint.run_store_base namespacing),
  digest chain (``digest.jsonl``), child stdout (``run.log``),
  crash-cause journal (``crash.jsonl``), and a private copy of the
  scenario XML (``config.xml`` — the queue is self-contained; the
  submitted path may be a temp file).
- ``Q/scheduler.lock`` — single-scheduler mutual exclusion
  (fleet.scheduler).

Run specs carry two execution modes: ``config`` runs a scenario XML
through the ``python -m shadow_tpu`` CLI with MANAGED durability args
(checkpoint store, digest chain, ``--resume latest`` on re-dispatch —
fleet.worker), while ``cmd`` runs an arbitrary argv (bench lines,
tests) that is simply re-run from scratch on retry.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import sys
import time

from ..engine.checkpoint import run_store_base, valid_run_id
from ..obs.ledger import jsonl_append, jsonl_read

JOURNAL = "queue.jsonl"

# terminal states; everything else keeps the scheduler loop alive
TERMINAL = ("done", "quarantined")


def make_spec(run_id: str, config: str = None, cmd: list = None,
              args: list = None, env: dict = None, hosts: int = 1,
              rss_mb: int = 0, max_retries: int = 3,
              checkpoint_every: float = 10.0, digest: bool = True,
              digest_every: int = 0, perf: str = None,
              netscope: bool = False, batch: str = None,
              batch_seed: int = None) -> dict:
    """One run spec (a journal ``submit`` payload). Exactly one of
    `config` (scenario XML path — managed durability) and `cmd`
    (arbitrary argv — rerun-from-scratch retries) must be set.
    `hosts`/`rss_mb` are the admission-control weights; `args` extra
    CLI arguments for config runs (seed, faults, engine caps...);
    `perf` non-None appends a per-run perf-ledger entry on completion
    ("" = the default ledger path); `netscope` streams the child's
    network observatory time-series into the run directory
    (obs.netscope — ``fleet status --ensemble`` folds them). `batch` names a vmapped-batch
    group (serving.batch): every member of the group executes in ONE
    child (``python -m shadow_tpu batch``) while keeping its own
    journal state; `batch_seed` is the member's seed in the
    one-XML-many-seeds form. Batch members are config runs WITHOUT
    managed checkpoints (a crashed batch re-runs from scratch, like
    a cmd run)."""
    if not valid_run_id(run_id):
        raise ValueError(
            f"run id {run_id!r} is not path-safe (letters/digits/._- "
            "only, starting with an alphanumeric)")
    if bool(config) == bool(cmd):
        raise ValueError("a run spec needs exactly one of config=XML "
                         "or cmd=[argv]")
    if batch is not None and not config:
        raise ValueError("batch members are config runs")
    return {
        "id": run_id,
        "config": config,
        "cmd": list(cmd) if cmd else None,
        "args": list(args or []),
        "env": dict(env or {}),
        "hosts": int(hosts),
        "rss_mb": int(rss_mb),
        "max_retries": int(max_retries),
        "checkpoint_every": float(checkpoint_every),
        "digest": bool(digest),
        "digest_every": int(digest_every),
        "perf": perf,
        "netscope": bool(netscope),
        "batch": batch,
        "batch_seed": batch_seed,
    }


@dataclasses.dataclass
class RunState:
    """One run's folded state. `crashes` counts crash-kind exits (the
    retry/quarantine counter); `started` counts dispatches — any run
    started at least once is re-dispatched with ``--resume latest``
    (the CLI starts fresh, with a warning, when the crash predated
    the first snapshot)."""
    spec: dict
    state: str = "queued"     # queued | running | done | quarantined
    started: int = 0
    crashes: int = 0
    preemptions: int = 0
    reclaims: int = 0
    pid: int = None
    last_rc: int = None
    last_cause: str = None
    quarantine_cause: str = None

    @property
    def id(self) -> str:
        return self.spec["id"]

    @property
    def resume(self) -> bool:
        return self.started > 0


class Queue:
    """Owns one queue directory; every mutation is a journal append."""

    def __init__(self, root: str):
        self.root = root
        self.journal = os.path.join(root, JOURNAL)
        self.claims_dir = os.path.join(root, "claims")
        self.runs_dir = os.path.join(root, "runs")

    def ensure(self):
        os.makedirs(self.claims_dir, exist_ok=True)
        os.makedirs(self.runs_dir, exist_ok=True)
        return self

    def exists(self) -> bool:
        return os.path.exists(self.journal)

    # --- journal ---
    def append(self, op: str, **fields):
        """One fsync'd journal line; the crash-safety of the whole
        queue reduces to this call's durability."""
        rec = {"op": op, "t": round(time.time(), 3), **fields}
        jsonl_append(self.journal, rec, fsync=True, sort_keys=True)
        return rec

    def entries(self) -> list:
        return jsonl_read(self.journal, label="fleet queue")

    def submit(self, spec: dict) -> str:
        """Durably enqueue one run: copy its scenario XML into the
        run directory (self-contained queue), then journal the
        submit. Duplicate ids are refused — a resubmitted id would
        make the fold ambiguous."""
        self.ensure()
        if spec["id"] in self.fold():
            raise ValueError(f"run id {spec['id']!r} already queued "
                             f"in {self.root}")
        if spec.get("config"):
            rd = self.run_dir(spec["id"])
            os.makedirs(rd, exist_ok=True)
            # keep the original basename: the perf ledger labels a
            # --perf run's scenario from it (obs.ledger trajectories).
            # Stored ABSOLUTE: a later `fleet run` may start from a
            # different cwd than this submit, and a cwd-relative path
            # would resolve to nothing there (rc=2 → instant
            # quarantine of the whole sweep)
            dst = os.path.abspath(
                os.path.join(rd, os.path.basename(spec["config"])))
            shutil.copyfile(spec["config"], dst)
            spec = dict(spec, config=dst)
        self.append("submit", run=spec)
        return spec["id"]

    def fold(self) -> dict:
        """Journal -> {run_id: RunState}, submission-ordered (dicts
        preserve insertion order — the scheduler's FIFO). Unknown ops
        and records for unknown runs are skipped with a warning, so a
        newer journal never crashes an older reader."""
        states: dict = {}
        for rec in self.entries():
            op = rec.get("op")
            if op == "prewarm":
                continue          # shape records fold separately
                #   (prewarm_fold); they carry no run transition
            if op == "submit":
                spec = rec.get("run") or {}
                rid = spec.get("id")
                if not rid or rid in states:
                    sys.stderr.write(
                        f"fleet queue: {self.journal}: skipping "
                        f"duplicate/invalid submit {rid!r}\n")
                    continue
                states[rid] = RunState(spec=spec)
                continue
            st = states.get(rec.get("id"))
            if st is None:
                sys.stderr.write(
                    f"fleet queue: {self.journal}: {op} record for "
                    f"unknown run {rec.get('id')!r} — skipped\n")
                continue
            if op == "start":
                st.state = "running"
                st.started += 1
                st.pid = rec.get("pid")
            elif op == "exit":
                st.last_rc = rec.get("rc")
                st.last_cause = rec.get("cause")
                st.pid = None
                kind = rec.get("kind")
                if kind == "done":
                    st.state = "done"
                elif kind == "preempt":
                    st.preemptions += 1
                    st.state = "queued"
                else:                    # crash (incl. watchdog kills)
                    st.crashes += 1
                    st.state = "queued"
            elif op == "reclaim":
                # a dead scheduler's in-flight run, found via its
                # stale claim: requeued as resumable, NOT counted as
                # a crash (the run did nothing wrong)
                st.reclaims += 1
                st.pid = None
                if st.state == "running":
                    st.state = "queued"
            elif op == "quarantine":
                st.state = "quarantined"
                st.quarantine_cause = rec.get("cause")
            else:
                sys.stderr.write(
                    f"fleet queue: {self.journal}: unknown op "
                    f"{op!r} — skipped\n")
        return states

    def prewarm_fold(self) -> dict:
        """The serving-layer shape records (``op: prewarm`` — written
        by the scheduler's Prewarmer): {"shapes": {fingerprint: last
        state}, "runs": {run_id: fingerprint}} — what ``fleet
        status`` reports as shapes warmed vs pending."""
        shapes: dict = {}
        runs: dict = {}
        for rec in self.entries():
            if rec.get("op") != "prewarm":
                continue
            fp = rec.get("shape")
            state = rec.get("state")
            rid = rec.get("run")
            if fp:
                shapes[fp] = state if state != "resolved" else (
                    shapes.get(fp) or "pending")
            if rid and fp:
                runs[rid] = fp
        return {"shapes": shapes, "runs": runs}

    # --- per-run paths ---
    def run_dir(self, run_id: str) -> str:
        return os.path.join(self.runs_dir, run_id)

    def store_base(self, run_id: str) -> str:
        """The run's checkpoint-store base (engine.checkpoint
        namespacing: rotation, ``latest`` pointer, crash log and
        hosted sidecars all live under the run's own directory)."""
        return run_store_base(self.runs_dir, run_id)

    def digest_path(self, run_id: str) -> str:
        return os.path.join(self.run_dir(run_id), "digest.jsonl")

    def netscope_path(self, run_id: str) -> str:
        return os.path.join(self.run_dir(run_id), "netscope.jsonl")

    def log_path(self, run_id: str) -> str:
        return os.path.join(self.run_dir(run_id), "run.log")

    def crash_log_path(self, run_id: str) -> str:
        return os.path.join(self.run_dir(run_id), "crash.jsonl")

    # --- claims ---
    def claim_path(self, run_id: str) -> str:
        return os.path.join(self.claims_dir, run_id + ".claim")

    def claim(self, run_id: str, meta: dict) -> bool:
        """Atomically claim a run (O_EXCL): exactly one worker slot
        can hold it. False = someone else holds it."""
        self.ensure()
        try:
            fd = os.open(self.claim_path(run_id),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            json.dump({"t": round(time.time(), 3), **meta}, f)
            f.flush()
            os.fsync(f.fileno())
        return True

    def read_claim(self, run_id: str) -> dict | None:
        try:
            with open(self.claim_path(run_id)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError):
            # torn claim (killed mid-write): holder unknowable —
            # report it as an empty claim so recovery reclaims it
            return {}

    def release(self, run_id: str):
        try:
            os.unlink(self.claim_path(run_id))
        except OSError:
            pass

    def claimed_ids(self) -> list:
        try:
            names = os.listdir(self.claims_dir)
        except OSError:
            return []
        return sorted(n[:-len(".claim")] for n in names
                      if n.endswith(".claim"))
