"""Crash-safe sweep scheduler: drain the queue through any failure.

One scheduler process owns one queue (``scheduler.lock``; a stale
lock — dead pid — is taken over). The loop holds up to ``workers``
slots (fleet.worker), each a supervised child process, and per tick:

1. **reaps** finished slots — journals the exit, releases the claim,
   and classifies it: done; preempt (exit 75 → requeued resumable);
   crash (retry with exponential backoff — engine.supervisor's one
   rule — escalating to QUARANTINE past the run's max_retries, so a
   deterministic crasher parks with its crash-cause journal while
   the queue keeps draining; deterministic usage errors, rc=2,
   quarantine immediately);
2. runs the **watchdog** — a slot whose progress signals (checkpoint
   pointer / digest / log mtimes) are older than ``hang_timeout_s``
   is diagnosed hung and SIGKILLed, never wedging the slot;
3. honors **preemption** — SIGTERM to the scheduler forwards SIGTERM
   to every child (config runs checkpoint at their next chunk
   boundary and exit 75 — engine.sim.Preempted), SIGKILLs stragglers
   after a grace period, journals everything and exits 75 itself;
   restarting ``fleet run`` completes the sweep byte-identically;
4. **admits** queued runs FIFO under the admission budget: concurrent
   simulated hosts (and declared RSS) are bounded, so an oversized
   scenario waits as "queued" — and runs ALONE once the box is free —
   instead of OOMing the box;
5. publishes ``fleet.*`` **metrics** when a registry is installed.

Crash-safety of the scheduler itself: all state is the journal fold +
claim files. On startup, recovery kills any orphaned child of a dead
scheduler (its claim names the pid/process-group), journals a
``reclaim`` (NOT a crash — the run did nothing wrong) and requeues
the run as resumable.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

from ..engine.supervisor import EXIT_PREEMPTED, backoff_delay
from .queue import TERMINAL, Queue
from .worker import BatchSlot, Slot

LOCK = "scheduler.lock"

# scheduler exit codes (fleet.cli documents them)
EXIT_DRAINED = 0          # every run done
EXIT_QUARANTINED = 3      # drained, but some runs are quarantined


def _pid_alive(pid) -> bool:
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError):
        return False
    return True


class SchedulerLockError(RuntimeError):
    pass


class Scheduler:
    def __init__(self, queue: Queue, workers: int = 2,
                 max_hosts: int = 0, max_rss_mb: int = 0,
                 hang_timeout_s: float = 900.0, backoff_s: float = 1.0,
                 backoff_cap_s: float = 60.0, grace_s: float = 60.0,
                 poll_s: float = 0.2, python: str = None, log=None,
                 max_spont_preempts: int = 20, aot_cache: str = None,
                 prewarm: bool = False, prewarm_jobs: int = 1):
        self.queue = queue
        self.workers = max(int(workers), 1)
        self.max_hosts = int(max_hosts)
        self.max_rss_mb = int(max_rss_mb)
        self.hang_timeout_s = float(hang_timeout_s)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.python = python
        self.log = log or (lambda m: sys.stderr.write(
            f"shadow_tpu: fleet: {m}\n"))
        # serving layer (docs/serving.md): children share a
        # persistent AOT executable cache, and with prewarm=True each
        # distinct config shape compiles ONCE before its runs admit
        self.aot_cache = aot_cache
        self.prewarm = bool(prewarm) and bool(aot_cache)
        self.prewarm_jobs = max(int(prewarm_jobs), 1)
        self._prewarmer = None
        # spontaneous exit-75s (nobody preempted): bounded so a child
        # that always exits 75 cannot livelock the drain loop
        self.max_spont_preempts = int(max_spont_preempts)
        self.slots = []
        self._eligible_at = {}      # run_id -> wall time (backoff)
        self._spont_preempts = {}   # run_id -> spontaneous 75 count
        self._preempt = threading.Event()
        self._counters = {"starts": 0, "retries": 0, "preemptions": 0,
                          "watchdog_kills": 0, "reclaims": 0,
                          "quarantines": 0}

    # --- preemption (SIGTERM handler calls this) ---
    def request_preempt(self):
        self._preempt.set()

    # --- single-scheduler lock ---
    def lock_path(self) -> str:
        return os.path.join(self.queue.root, LOCK)

    def _acquire_lock(self):
        self.queue.ensure()
        # the lock must be COMPLETE when it becomes visible: write a
        # private tmp first and publish with os.link (which fails
        # EEXIST like O_EXCL) — a contender reading a half-written
        # lock would misjudge a live scheduler as stale garbage
        tmp = f"{self.lock_path()}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(),
                       "t": round(time.time(), 3)}, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            self._acquire_lock_from(tmp)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _acquire_lock_from(self, tmp: str):
        for _ in range(3):
            try:
                os.link(tmp, self.lock_path())
                return
            except FileExistsError:
                try:
                    with open(self.lock_path()) as f:
                        holder = json.load(f)
                except FileNotFoundError:
                    continue           # raced a takeover; re-examine
                except (OSError, json.JSONDecodeError):
                    # locks are published complete (link-from-tmp), so
                    # an unparsable one is pre-publication garbage
                    # from an older writer — treat as stale
                    holder = {}
                if _pid_alive(holder.get("pid")):
                    raise SchedulerLockError(
                        f"another scheduler (pid {holder.get('pid')}) "
                        f"holds {self.lock_path()}; one scheduler per "
                        "queue")
                # takeover must be ATOMIC: renaming the stale lock
                # aside succeeds for exactly ONE contender (a plain
                # unlink-and-retry lets a second concurrent starter
                # unlink the winner's FRESH lock — two schedulers on
                # one queue). The loser's rename raises ENOENT and it
                # re-examines whatever lock now exists.
                stale = f"{self.lock_path()}.stale.{os.getpid()}"
                try:
                    os.rename(self.lock_path(), stale)
                except OSError:
                    continue           # lost the takeover race
                self.log(f"taking over stale scheduler lock "
                         f"(dead pid {holder.get('pid')})")
                try:
                    os.unlink(stale)
                except OSError:
                    pass
        raise SchedulerLockError(
            f"could not acquire {self.lock_path()}")

    def _release_lock(self):
        try:
            os.unlink(self.lock_path())
        except OSError:
            pass

    # --- recovery: a dead scheduler's in-flight runs ---
    @staticmethod
    def _looks_like_claimed_child(pid, argv, claim_path) -> bool:
        """Pid-reuse guard before a recovery SIGKILL: the live
        process must still be the claimed child — its /proc cmdline
        matches the claim's recorded argv (post-exec), or carries the
        run's unique claim path (the pre-exec claim-gate wrapper
        names it in its own argv). An unreadable /proc or any other
        command line means the pid was recycled by an unrelated
        process — reclaim the run but do NOT kill."""
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                parts = [p.decode(errors="replace")
                         for p in f.read().split(b"\0") if p]
        except OSError:
            return False
        if argv and parts == list(argv):
            return True
        ab = os.path.abspath(claim_path)
        return any(p == ab for p in parts)

    def _recover(self, states: dict):
        for rid in self.queue.claimed_ids():
            claim = self.queue.read_claim(rid) or {}
            # only the CHILD pid is killable; a claim holding just the
            # dead scheduler's pid means the child never got published
            # — with the stopped-spawn handshake it never ran either
            pid = claim.get("pid")
            if _pid_alive(pid):
                if self._looks_like_claimed_child(
                        pid, claim.get("argv"),
                        self.queue.claim_path(rid)):
                    # orphan of a dead scheduler (we hold the lock, so
                    # no live scheduler owns it): kill its whole
                    # process group; the run resumes from its newest
                    # snapshot
                    self.log(f"run {rid}: killing orphaned child "
                             f"(pid {pid}) of a dead scheduler")
                    try:
                        os.killpg(int(pid), signal.SIGKILL)
                    except OSError:
                        try:
                            os.kill(int(pid), signal.SIGKILL)
                        except OSError:
                            pass
                else:
                    self.log(
                        f"run {rid}: claimed pid {pid} is alive but "
                        "no longer matches the claim (pid reuse?) — "
                        "reclaiming without killing")
            st = states.get(rid)
            if st is not None and st.state == "running":
                self.queue.append("reclaim", id=rid, pid=pid)
                st.reclaims += 1
                st.state = "queued"
                self._counters["reclaims"] += 1
            self.queue.release(rid)

    # --- admission control ---
    def admissible(self, spec: dict) -> bool:
        """Bound CONCURRENT totals. A run whose weight alone exceeds
        the budget is not starved: it is admitted when nothing else
        runs (alone it cannot stack with anything, which is the OOM
        the bound exists to prevent)."""
        if not self.slots:
            return True
        if self.max_hosts:
            used = sum(s.spec.get("hosts", 1) for s in self.slots)
            if used + spec.get("hosts", 1) > self.max_hosts:
                return False
        if self.max_rss_mb:
            used = sum(s.spec.get("rss_mb", 0) for s in self.slots)
            if used + spec.get("rss_mb", 0) > self.max_rss_mb:
                return False
        return True

    # --- one reaped exit ---
    def _handle_exit(self, slot: Slot, rc: int, states: dict):
        if isinstance(slot, BatchSlot):
            return self._handle_batch_exit(slot, rc, states)
        st = states[slot.run_id]
        kind, cause = slot.classify(rc)
        slot.record_exit(rc, kind, cause)
        self.queue.append("exit", id=slot.run_id, attempt=slot.attempt,
                          rc=rc, kind=kind, cause=cause,
                          wall_s=round(time.time() - slot.t0, 3))
        self.queue.release(slot.run_id)
        slot.close()
        st.last_rc, st.last_cause, st.pid = rc, cause, None
        if kind == "done":
            st.state = "done"
            self.log(f"run {slot.run_id}: completed "
                     f"(attempt {slot.attempt})")
            return
        if kind == "preempt":
            st.preemptions += 1
            st.state = "queued"
            if not slot.preempting:
                # a 75 nobody asked for (the child preempted itself,
                # or something external SIGTERMs it every attempt):
                # resumable, but backed off and CAPPED — an
                # always-75 child must not livelock the drain loop
                n = self._spont_preempts.get(slot.run_id, 0) + 1
                self._spont_preempts[slot.run_id] = n
                if n > self.max_spont_preempts:
                    self._quarantine(
                        st, f"preempted {n} times without a "
                        "scheduler preemption (exit-75 livelock); "
                        f"last: {cause}")
                    return
                self._eligible_at[slot.run_id] = (
                    time.time() + backoff_delay(self.backoff_s, n,
                                                self.backoff_cap_s))
            self.log(f"run {slot.run_id}: {cause}; requeued resumable")
            return
        self._register_crash(st, rc, cause)

    def _handle_batch_exit(self, slot: BatchSlot, rc: int,
                           states: dict):
        """One batch child's exit fans out to every member's journal
        state: done marks all members done; preempt requeues them
        (re-run from scratch — batch children carry no checkpoint);
        a crash escalates EACH member's own retry→quarantine count,
        so a poisoned group parks member by member while the rest of
        the queue drains."""
        kind, cause = slot.classify(rc)
        slot.record_exit(rc, kind, cause)
        wall = round(time.time() - slot.t0, 3)
        for rid in slot.member_ids:
            self.queue.append("exit", id=rid, attempt=slot.attempt,
                              rc=rc, kind=kind, cause=cause,
                              wall_s=wall, batch=slot.group)
            self.queue.release(rid)
        slot.close()
        for rid in slot.member_ids:
            st = states[rid]
            st.last_rc, st.last_cause, st.pid = rc, cause, None
            if kind == "done":
                st.state = "done"
            elif kind == "preempt":
                st.preemptions += 1
                st.state = "queued"
            else:
                self._register_crash(st, rc, cause)
        if kind == "done":
            self.log(f"batch {slot.group}: completed "
                     f"({len(slot.member_ids)} members, attempt "
                     f"{slot.attempt})")
        elif kind == "preempt":
            if not slot.preempting:
                n = self._spont_preempts.get(slot.run_id, 0) + 1
                self._spont_preempts[slot.run_id] = n
                if n > self.max_spont_preempts:
                    for rid in slot.member_ids:
                        self._quarantine(
                            states[rid],
                            f"batch preempted {n} times without a "
                            "scheduler preemption (exit-75 "
                            f"livelock); last: {cause}")
                    return
                delay = backoff_delay(self.backoff_s, n,
                                      self.backoff_cap_s)
                for rid in slot.member_ids:
                    self._eligible_at[rid] = time.time() + delay
            self.log(f"batch {slot.group}: {cause}; members requeued "
                     "(batch retries re-run from scratch)")

    def _quarantine(self, st, why: str):
        self.queue.append("quarantine", id=st.id, cause=why,
                          crashes=st.crashes,
                          crash_log=self.queue.crash_log_path(st.id))
        st.state = "quarantined"
        st.quarantine_cause = why
        self._counters["quarantines"] += 1
        self.log(f"run {st.id}: QUARANTINED — {why} (crash causes: "
                 f"{self.queue.crash_log_path(st.id)})")

    def _register_crash(self, st, rc, cause: str):
        """The crash-exit escalation, shared by reaped exits and
        spawn failures: retry with backoff, quarantine past the
        run's max_retries (usage errors immediately)."""
        st.crashes += 1
        st.state = "queued"
        max_retries = int(st.spec.get("max_retries", 3))
        if rc == 2 or st.crashes > max_retries:
            self._quarantine(
                st, ("deterministic usage error (rc=2); not retried"
                     if rc == 2 else
                     f"{st.crashes} crashes (> {max_retries} "
                     f"retries); last: {cause}"))
            return
        delay = backoff_delay(self.backoff_s, st.crashes,
                              self.backoff_cap_s)
        self._eligible_at[st.id] = time.time() + delay
        self._counters["retries"] += 1
        self.log(f"run {st.id}: {cause}; retry "
                 f"{st.crashes}/{max_retries} in {delay:.1f}s"
                 + (" (resume latest)" if st.spec.get("config")
                    else ""))

    def _handle_spawn_failure(self, st, err: OSError):
        """The child never existed (bad executable, claim write
        failure): journal + crash-log the attempt and ride the normal
        crash escalation — the scheduler itself never dies of it."""
        from ..engine.supervisor import CrashLog
        cause = f"spawn failed: {err}"
        attempt = st.started + 1
        CrashLog(self.queue.crash_log_path(st.id),
                 log=self.log).append({
            "attempt": attempt, "exit_status": None, "kind": "crash",
            "cause": cause, "wall_s": 0.0, "resumed": st.resume,
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        })
        self.queue.append("exit", id=st.id, attempt=attempt, rc=None,
                          kind="crash", cause=cause, wall_s=0.0)
        self.queue.release(st.id)
        st.last_rc, st.last_cause = None, cause
        self._register_crash(st, None, cause)

    # --- metrics ---
    def _publish(self, states: dict):
        from ..obs import metrics as MT
        if not MT.ENABLED:
            return
        reg = MT.REGISTRY
        by_state = {"queued": 0, "running": 0, "done": 0,
                    "quarantined": 0}
        for st in states.values():
            by_state[st.state] = by_state.get(st.state, 0) + 1
        for k, v in by_state.items():
            reg.gauge(f"fleet.{k}").set(v)
        reg.gauge("fleet.slots_busy").set(len(self.slots))
        for k, v in self._counters.items():
            c = reg.counter(f"fleet.{k}")
            c.n = v                       # absolute, scheduler-owned

    def _claim(self, run_id: str) -> bool:
        """Claim one run, reclaiming a dead holder's stale claim."""
        if self.queue.claim(run_id, {"scheduler_pid": os.getpid()}):
            return True
        claim = self.queue.read_claim(run_id) or {}
        if _pid_alive(claim.get("pid")):
            return False          # genuinely held (shouldn't happen
            #   under the lock) — skip
        self.queue.release(run_id)
        return self.queue.claim(run_id,
                                {"scheduler_pid": os.getpid()})

    def _admit_batch(self, st, states: dict, now: float,
                     slotted: set) -> bool:
        """Try to admit the whole vmapped-batch group `st` belongs to
        as ONE BatchSlot. All non-terminal members must be queued,
        past their backoff and (under --prewarm) shape-warm; the
        group's admission weight is the members' sum. Returns True
        when a slot started."""
        gid = st.spec.get("batch")
        group = [s for s in states.values()
                 if s.spec.get("batch") == gid
                 and s.state not in TERMINAL]
        if not group:
            return False
        for m in group:
            if (m.state != "queued" or m.id in slotted
                    or now < self._eligible_at.get(m.id, 0)
                    or (self._prewarmer is not None
                        and not self._prewarmer.ready(m.id))):
                return False
        weight = {"hosts": sum(m.spec.get("hosts", 1) for m in group),
                  "rss_mb": sum(m.spec.get("rss_mb", 0)
                                for m in group)}
        if not self.admissible(weight):
            return False
        claimed = []
        for m in group:
            if not self._claim(m.id):
                for rid in claimed:
                    self.queue.release(rid)
                return False
            claimed.append(m.id)
        try:
            slot = BatchSlot(self.queue, group, python=self.python,
                             log=self.log, aot_cache=self.aot_cache)
        except OSError as e:
            for m in group:
                self._handle_spawn_failure(m, e)
            return False
        try:
            slot.start()
        except OSError as e:
            slot.close()
            for m in group:
                self._handle_spawn_failure(m, e)
            return False
        for m in group:
            m.state = "running"
            m.started += 1
            m.pid = slot.proc.pid
            self.queue.append("start", id=m.id, attempt=slot.attempt,
                              pid=slot.proc.pid, resume=False,
                              batch=gid)
        self.slots.append(slot)
        self._counters["starts"] += 1
        self.log(f"batch {gid}: started attempt {slot.attempt} "
                 f"({len(group)} members, pid {slot.proc.pid})")
        return True

    def _slotted_ids(self) -> set:
        """Every run id currently covered by a slot — a BatchSlot
        covers all its members, not just its leading run_id."""
        ids = set()
        for s in self.slots:
            if isinstance(s, BatchSlot):
                ids.update(s.member_ids)
            else:
                ids.add(s.run_id)
        return ids

    # --- the drain loop ---
    def run(self) -> int:
        self.queue.ensure()
        self._acquire_lock()
        try:
            states = self.queue.fold()
            if not states:
                self.log("queue is empty; nothing to do")
                return EXIT_DRAINED
            self._recover(states)
            if self.prewarm:
                # serving.prewarm: fingerprint each queued config
                # run's shape, dedup across the sweep, compile each
                # distinct shape once into the shared cache; runs
                # admit once their shape is warmed (docs/serving.md)
                from ..serving.prewarm import Prewarmer
                self._prewarmer = Prewarmer(
                    [st.spec for st in states.values()
                     if st.state not in TERMINAL
                     and not st.spec.get("batch")],
                    # batch groups are excluded: they compile their
                    # own vmapped b<N> program (one compile for the
                    # whole group by construction), which the
                    # single-run warm would not serve — gating them
                    # on it would pay two compiles (docs/serving.md)
                    self.aot_cache, python=self.python,
                    jobs=self.prewarm_jobs, log=self.log,
                    journal=lambda **kw: self.queue.append(
                        "prewarm", **kw))
            n_all = len(states)
            self.log(f"draining {n_all} runs "
                     f"({sum(1 for s in states.values() if s.state in TERMINAL)} "
                     f"already terminal) with {self.workers} workers")
            while True:
                # 0. pre-warm pipeline (non-blocking)
                if self._prewarmer is not None:
                    self._prewarmer.tick()
                # 1. reap
                for slot in list(self.slots):
                    rc = slot.proc.poll()
                    if rc is None:
                        continue
                    self.slots.remove(slot)
                    self._handle_exit(slot, rc, states)
                # 2. watchdog
                now = time.time()
                for slot in self.slots:
                    if slot.hung or slot.preempting:
                        continue
                    if (now - slot.check_progress()
                            > self.hang_timeout_s):
                        slot.hung = True
                        self._counters["watchdog_kills"] += 1
                        self.log(
                            f"run {slot.run_id}: no progress for "
                            f"{self.hang_timeout_s:.0f}s — diagnosing "
                            "hung; SIGKILL")
                        slot.kill()
                # 3. preemption
                if self._preempt.is_set():
                    return self._drain_preempt(states)
                # 4. admit
                slotted = self._slotted_ids()
                for st in states.values():
                    if len(self.slots) >= self.workers:
                        break
                    if st.state != "queued":
                        continue
                    if st.id in slotted:
                        continue
                    if now < self._eligible_at.get(st.id, 0):
                        continue
                    if (self._prewarmer is not None
                            and not self._prewarmer.ready(st.id)):
                        continue      # shape still probing/compiling
                    if st.spec.get("batch"):
                        if self._admit_batch(st, states, now,
                                             slotted):
                            slotted = self._slotted_ids()
                        continue
                    if not self.admissible(st.spec):
                        continue
                    if not self._claim(st.id):
                        continue
                    try:
                        slot = Slot(self.queue, st, python=self.python,
                                    log=self.log,
                                    aot_cache=self.aot_cache)
                    except OSError as e:
                        self._handle_spawn_failure(st, e)
                        continue
                    try:
                        slot.start()
                    except OSError as e:
                        slot.close()
                        # an unspawnable child (bad executable, claim
                        # write failure) is a CRASH of that run, never
                        # of the scheduler — it rides the normal
                        # retry→quarantine escalation while the rest
                        # of the queue keeps draining
                        self._handle_spawn_failure(st, e)
                        continue
                    st.state = "running"
                    st.started += 1
                    st.pid = slot.proc.pid
                    self.slots.append(slot)
                    slotted.add(st.id)
                    self._counters["starts"] += 1
                    self.queue.append(
                        "start", id=st.id, attempt=slot.attempt,
                        pid=slot.proc.pid, resume=slot.resume)
                    self.log(f"run {st.id}: started attempt "
                             f"{slot.attempt} (pid {slot.proc.pid}"
                             f"{', resume latest' if slot.resume else ''})")
                # 5. metrics
                self._publish(states)
                # 6. done? (a queued run always starts eventually —
                # backoff expires, and admission admits any run alone
                # — so "drained" means everything is terminal)
                if not self.slots and all(
                        st.state in TERMINAL
                        for st in states.values()):
                    break
                time.sleep(self.poll_s)
            quarantined = [st.id for st in states.values()
                           if st.state == "quarantined"]
            done = sum(1 for st in states.values()
                       if st.state == "done")
            self.log(f"queue drained: {done}/{n_all} done"
                     + (f", {len(quarantined)} quarantined "
                        f"({', '.join(quarantined)})"
                        if quarantined else ""))
            return EXIT_QUARANTINED if quarantined else EXIT_DRAINED
        finally:
            if self._prewarmer is not None:
                self._prewarmer.shutdown()
            self._release_lock()

    def _drain_preempt(self, states: dict) -> int:
        """SIGTERM every child, give them `grace_s` to checkpoint and
        exit 75, SIGKILL stragglers, journal + requeue everything,
        then exit 75 ourselves: the next ``fleet run`` resumes the
        sweep exactly where it stopped."""
        self.log(f"preempted: signalling {len(self.slots)} running "
                 f"child(ren); grace {self.grace_s:.0f}s")
        for slot in self.slots:
            slot.preempt()
        deadline = time.time() + self.grace_s
        while self.slots and time.time() < deadline:
            for slot in list(self.slots):
                rc = slot.proc.poll()
                if rc is not None:
                    self.slots.remove(slot)
                    self._handle_exit(slot, rc, states)
            time.sleep(min(self.poll_s, 0.1))
        for slot in list(self.slots):
            slot.preempt_killed = True
            slot.kill()
            rc = slot.proc.wait()
            self.slots.remove(slot)
            self._handle_exit(slot, rc, states)
        self._counters["preemptions"] += 1
        self._publish(states)
        self.log("preemption complete; restart `fleet run` to resume "
                 "the sweep")
        return EXIT_PREEMPTED
