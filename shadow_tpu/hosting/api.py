"""CPU-side hosted-application API.

This is what replaces writing a C plugin against libc + LD_PRELOAD in
the reference (SURVEY §2.4/2.5): a hosted app is real Python code
driven by the same wake reasons on-device apps get, issuing syscalls
against a per-host :class:`HostOS` handle. Syscalls are batched and
applied to device state between lookahead windows (hosting.bridge), so
apps see the engine's real TCP/UDP stack.

Determinism: apps must derive randomness from ``os.random()`` (seeded
per host from the scenario seed) and time from ``os.now()`` (simulated
nanoseconds) — mirroring how the reference virtualizes /dev/random and
clock_gettime for plugins (shd-process.c:4329-4650).
"""

from __future__ import annotations

from dataclasses import dataclass, field


class Sock:
    """Handle for one socket INCARNATION: a (device slot, generation)
    pair. Slots are recycled after close; the generation (stamped on
    every wake by the engine) keeps a handle bound to exactly the
    connection that created it. Resolves after the op batch that
    created it is applied; hosted apps only dereference it in later
    callbacks, by which time it is bound."""

    __slots__ = ("slot", "gen")

    def __init__(self):
        self.slot = None
        self.gen = None

    def __index__(self):
        if self.slot is None:
            raise RuntimeError("Sock used before its open op applied")
        return self.slot

    def __repr__(self):
        return f"Sock({self.slot}@{self.gen})"


@dataclass
class _PendingOp:
    code: int
    a: int = 0
    b: int = 0
    c: int = 0
    d: int = 0
    t: int = 0          # sim time the op was issued (its wake's time)
    out: Sock = None


class HostOS:
    """Per-host syscall surface handed to hosted app callbacks.

    The call set mirrors the host_* syscall backend of the reference
    (shd-host.c:598-1556) at the same granularity the on-device apps
    use: byte-counted streams and tagged datagrams.
    """

    def __init__(self, host_id: int, name: str, rng, dns, clock):
        self.host_id = host_id
        self.name = name
        self._rng = rng
        self._dns = dns
        self._clock = clock          # callable -> current sim time ns
        self._ops: list = []
        self._socks: dict = {}       # (slot, gen) -> Sock (live handles;
        #   entries are dropped at close so the map is bounded by
        #   concurrently-open sockets)

    # --- environment ---
    def now(self) -> int:
        """Simulated time, nanoseconds."""
        return self._clock()

    def random(self) -> float:
        """Deterministic per-host uniform [0, 1)."""
        return float(self._rng.random())

    def random_bytes(self, n: int) -> bytes:
        """n deterministic entropy bytes from the per-host PRNG — the
        backing store for hosted getrandom/getentropy//dev/u?random
        (reference: the host random source serves /dev/random reads,
        shd-host.c:574, which is what makes entropy-drawing binaries
        run identically across runs, shd-test-determinism.c:15-60)."""
        return self._rng.bytes(int(n))

    def resolve(self, name: str) -> int:
        """Virtual DNS lookup -> host id."""
        return self._dns.resolve(name)

    # --- sockets ---
    def udp_open(self, port: int = 0) -> Sock:
        return self._push_open(1, a=port)

    def tcp_listen(self, port: int) -> Sock:
        return self._push_open(2, a=port)

    def tcp_connect(self, dst, port: int, tag: int = 0) -> Sock:
        dst = self.resolve(dst) if isinstance(dst, str) else int(dst)
        return self._push_open(3, a=dst, b=port, c=tag)

    def write(self, sock, nbytes: int):
        self._push(_PendingOp(4, a=self._slot(sock), b=int(nbytes)))

    def sendto(self, sock, dst, port: int, nbytes: int, aux: int = 0):
        dst = self.resolve(dst) if isinstance(dst, str) else int(dst)
        self._push(_PendingOp(
            5, a=self._slot(sock), b=dst,
            c=(int(port) << 32) | (int(aux) & 0xFFFFFFFF), d=int(nbytes)))

    def close(self, sock):
        self._push(_PendingOp(6, a=self._slot(sock)))
        # retire the incarnation's handle so _socks stays bounded by
        # open sockets, not by connections ever opened; a late wake for
        # the closed incarnation just materializes a fresh handle
        if isinstance(sock, Sock) and sock.slot is not None:
            self._socks.pop((sock.slot, sock.gen), None)

    def abort(self, sock):
        """Abortive close (net.tcp.tcp_abort_call): an established TCP
        connection sends RST toward the peer instead of draining a FIN;
        anything else frees immediately. The teardown a supervisor
        issues for a dead hosted process's leftover sockets — the peer
        sees a reset, as it would from a real kernel reaping a killed
        process."""
        self._push(_PendingOp(9, a=self._slot(sock)))
        if isinstance(sock, Sock) and sock.slot is not None:
            self._socks.pop((sock.slot, sock.gen), None)

    def timer(self, delay_ns: int, tag: int = 0):
        self._push(_PendingOp(7, a=self.now() + int(delay_ns),
                              b=int(tag)))

    def pipe(self):
        """A linked pair of pipe halves (the reference's Channel,
        shd-channel.c): write on one half wakes the other with the
        byte count; close delivers EOF. Returns (Sock, Sock). The
        handles resolve at the next wake — use them in a LATER
        callback, not the one that created them (same-batch Sock
        references cannot name one half of a pair)."""
        sa, sb = Sock(), Sock()
        self._push(_PendingOp(8, out=(sa, sb)))
        return sa, sb

    # --- internals ---
    def _push(self, op: _PendingOp):
        op.t = self.now()
        self._ops.append(op)

    def _push_open(self, code, a=0, b=0, c=0) -> Sock:
        s = Sock()
        self._push(_PendingOp(code, a=a, b=b, c=c, out=s))
        return s

    def _slot(self, sock):
        """A slot operand: an int, a resolved Sock, or an unresolved
        Sock created earlier in this same batch (the runtime encodes
        the latter as a same-batch result reference, resolved on
        device — so `sock = os.udp_open(); os.sendto(sock, ...)` works
        within one callback)."""
        if isinstance(sock, Sock):
            return sock if sock.slot is None else sock.slot
        return int(sock)

    def sock_for(self, slot: int, gen: int = 0) -> Sock:
        """Sock handle for a wake's (slot, generation) — the SAME
        object for every wake of one connection incarnation
        (server-accepted children get their first handle here)."""
        s = self._socks.get((slot, gen))
        if s is None:
            s = Sock()
            s.slot = slot
            s.gen = gen
            self._socks[(slot, gen)] = s
        return s

    def _bind(self, sock: Sock, packed: int):
        """Bind an open's result: packed = (generation << 16) | slot,
        or -1 on failure."""
        if packed < 0:
            sock.slot = -1
            sock.gen = -1
            return
        sock.slot = packed & 0xFFFF
        sock.gen = (packed >> 16) & 0x7FFF
        self._socks[(sock.slot, sock.gen)] = sock

    def _bind_pipe(self, sa: Sock, sb: Sock, packed: int):
        """Bind a pipe open's packed pair:
        gen_a(7) | slot_a(8) | gen_b(7) | slot_b(8)."""
        if packed < 0:
            for s in (sa, sb):
                s.slot = -1
                s.gen = -1
            return
        sa.slot = (packed >> 15) & 0xFF
        sa.gen = (packed >> 23) & 0x7F
        sb.slot = packed & 0xFF
        sb.gen = (packed >> 8) & 0x7F
        self._socks[(sa.slot, sa.gen)] = sa
        self._socks[(sb.slot, sb.gen)] = sb


class PayloadBroker:
    """Host-side per-connection byte streams for hosted apps.

    The engine models byte COUNTS (a DES does not move payloads across
    the device); when BOTH endpoints of a TCP connection are hosted
    processes in this simulator, the real bytes can ride host-side: the
    sender's app appends what it wrote, the receiver pops exactly the
    count the engine delivered. Delivered counts are in-order stream
    advances bounded by what was sent, so a FIFO per (connection,
    direction) reproduces the exact bytes a real network would have
    delivered. Streams a hosted endpoint writes toward a MODELED peer
    have no reader; they are capped (and dropped on overflow) so a
    long run cannot accumulate unbounded buffers — readers of such
    connections see zero-fill, same as before payloads existed.

    Keys: (cli_host, cli_port, srv_host, srv_port, direction) with
    direction 0 = client->server, 1 = server->client. Both endpoints
    derive the same tuple — the server from its accept wake's peer
    identity, the client from its connected wake's local port (the
    SYN|ACK's DPORT). A 4-tuple reused by a LATER connection (ephemeral
    wrap + TIME_WAIT recycling) could alias a stream whose endpoints
    never closed; closes drop each side's stream so this needs both
    processes to leak the socket — accepted and documented here.
    """

    CAP = 64 << 20  # in-flight bound for READER-LESS streams (a hosted
    #   endpoint writing toward a modeled process: no one ever pops)

    def __init__(self):
        self._streams: dict = {}   # key -> bytearray (None = overflowed)
        # keys whose actual READER registered (subscribe()): these
        # streams are never capped (the reader drains them at modeled
        # delivery pace) and survive the writer's close until the
        # reader closes. Reader-less keys — the peer process is a
        # modeled app, even one sharing a host with a hosted app — are
        # capped and dropped at the writer's close.
        self._subs: set = set()

    def open(self, key):
        """Idempotent create: both endpoints open both directions at
        connection establishment, so a writer's first push always finds
        the stream (the accept wake precedes the connected wake in sim
        time; create-only keeps the later open from clearing bytes the
        earlier side already pushed). An overflow-dead marker (None) is
        revived: it belongs to a previous connection incarnation."""
        if self._streams.get(key) is None:   # absent OR overflow-dead
            self._streams[key] = bytearray()

    def subscribe(self, key):
        """Register as the READER of `key` (each endpoint subscribes
        its inbound direction at establishment)."""
        self.open(key)
        self._subs.add(key)

    def subscribed(self, key) -> bool:
        return key in self._subs

    def push(self, key, data: bytes):
        buf = self._streams.get(key)
        if buf is None:
            return                      # no stream (modeled peer never
        #                                 opened it) or overflowed
        if key not in self._subs and len(buf) + len(data) > self.CAP:
            self._streams[key] = None   # cap blown on a reader-less
            #   stream (modeled peer); stop buffering — a subscribed
            #   stream is never capped, its reader drains it
            return
        buf += data

    def pop(self, key, n: int):
        """Exactly n bytes off the stream front, or None when the
        stream cannot cover the request — absent, overflow-dead, or
        shorter than n. A live writer always stays ahead of delivered
        counts (bytes are pushed at send time, delivery follows by the
        modeled latency), so a short stream means no real writer backs
        it (modeled peer: perpetually empty) or a degraded one
        (crashed peer / reused key); the caller zero-fills locally and
        no padding bytes cross the control channel.

        A SHORT stream (nonempty but < n) is marked overflow-dead: one
        uncovered read has already zero-filled, so later covered pops
        would return real bytes at the wrong stream offset — shifted
        partial replay is worse than degrading to consistent zero-fill
        (round-4 advisor)."""
        buf = self._streams.get(key)
        if buf is None:
            return None
        if len(buf) < n:
            if len(buf) > 0:
                self._streams[key] = None
            return None
        out = bytes(buf[:n])
        del buf[:n]
        return out

    def drop(self, key):
        self._streams.pop(key, None)
        self._subs.discard(key)


class HostedApp:
    """Base class for hosted applications. Override the callbacks you
    need; each receives the HostOS handle first."""

    def on_start(self, os: HostOS):
        pass

    def on_timer(self, os: HostOS, tag: int):
        pass

    def on_connected(self, os: HostOS, sock: Sock, lport: int = 0,
                     peer: tuple = (0, 0)):
        """`lport` is the connection's local (ephemeral) port and
        `peer` = (virtual host id, port) of the server — both off the
        SYN|ACK that completed the handshake, mirroring on_accept's
        identity args on the passive side."""
        pass

    def on_accept(self, os: HostOS, sock: Sock, tag: int, dport: int = 0,
                  peer: tuple = (0, 0)):
        """`sock` is the accepted CHILD connection; `dport` the local
        port it arrived on (identifies the listener when the app has
        several); `peer` = (virtual host id, port) of the connecting
        client."""
        pass

    def on_eof(self, os: HostOS, sock: Sock):
        pass

    def on_sent(self, os: HostOS, sock: Sock):
        pass

    def on_dgram(self, os: HostOS, sock: Sock, src: int, sport: int,
                 nbytes: int, aux: int):
        pass


# --- hosted-plugin registry (the analogue of <plugin id path>) ---

_REGISTRY: dict = {}


def register(name: str, factory):
    """Register a hosted app factory: factory(args_str) -> HostedApp."""
    _REGISTRY[name] = factory


def lookup(name: str):
    if name not in _REGISTRY:
        # built-in hosted apps register at import; pull them in before
        # giving up (the LD_PRELOAD shim bridge lives in .shim)
        from . import shim  # noqa: F401
    if name not in _REGISTRY:
        raise ValueError(
            f"no hosted app {name!r} registered "
            f"(have: {sorted(_REGISTRY)}); call hosting.register first")
    return _REGISTRY[name]
