"""Device side of the app-hosting bridge.

The reference hosts real, unmodified applications by interposing libc
and re-entering blocked green threads from the epoll notify task
(/root/reference/src/main/host/shd-process.c,
src/preload/shd-interposer.c; reentry shd-epoll.c:597-658). The TPU
redesign keeps the same seam — apps outside the engine, the entire
virtual network stack inside — but inverts the mechanics:

- every wake that would re-enter a hosted process is appended to a
  device-resident **wake ring** (Hosts.hw_*), drained to the CPU at
  window boundaries;
- every syscall the hosted app makes in response is encoded as a fixed
  op word and applied to device state by :func:`apply_ops` — one
  compiled program that replays the batch through the same row-level
  socket/TCP/UDP calls the on-device apps use.

So hosted apps get the real transport stack (handshakes, cwnd, RTO,
loss) with CPU-side application logic; the cost is one host round trip
per lookahead window, which is the price the reference also pays at its
process boundary (context switches into pth threads per event). See
hosting.runtime for the CPU half.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rget, rset, rset_where
from ..engine import equeue
from ..engine.defs import EV_APP, WAKE_TIMER, ST_EQ_FULL_LOCAL
from ..net import nic
from ..net import packet as P
from ..net.tcp import tcp_connect, tcp_listen, tcp_write, tcp_close_call
from ..net.udp import udp_sendto

_I32 = jnp.int32
_I64 = jnp.int64

# --- op encoding (int64 words) ---
# [host, opcode, a, b, c, d, t, proc]  (t = sim time the app issued the
# op, i.e. its wake's event time — ops apply at app time, not window
# time; proc = the hosted process's slot on its host, so sockets the
# replay allocates carry the right sk_proc and wake back to the hosted
# process even when modeled processes share the host — the reference's
# canonical tor+tgen host shape, shd-configuration.h:36-95)
OP_WORDS = 8
OP_NOP = 0
OP_UDP_OPEN = 1      # a=port (0 = ephemeral)           -> slot
OP_TCP_LISTEN = 2    # a=port                           -> slot
OP_TCP_CONNECT = 3   # a=dst host, b=dst port, c=tag    -> slot
OP_TCP_WRITE = 4     # a=slot, b=nbytes
OP_UDP_SENDTO = 5    # a=slot, b=dst host, c=(port<<32)|aux, d=nbytes
OP_CLOSE = 6         # a=slot (tcp/udp or pipe half; proto-dispatched)
OP_TIMER = 7         # a=deadline ns (absolute), b=tag
OP_PIPE_OPEN = 8     # -> packed pair (see _pipe_result)
OP_ABORT = 9         # a=slot: abortive close (RST when established)


def hosted_wake(row, hp, sh, now, pkt):
    """EV_APP handler for hosted hosts: record the wake for the CPU
    tier instead of running an on-device state machine."""
    cnt = row.hw_cnt
    cap = row.hw_time.shape[0]
    ok = cnt < cap
    at = jnp.clip(cnt, 0, cap - 1)
    return row.replace(
        hw_time=rset_where(row.hw_time, at, ok, now),
        hw_pkt=rset_where(row.hw_pkt, at, ok, pkt),
        hw_cnt=cnt + jnp.where(ok, 1, 0),
        hw_drop=row.hw_drop + jnp.where(ok, 0, 1),
    )


def _apply_one(hosts, hp, sh, op, results):
    """Apply one op word to the addressed host row at the op's own
    timestamp. Returns (hosts, result). Operands < -1 are same-batch
    result references (-(k+2) = result of op k), letting an app use a
    socket in the same callback that opened it."""
    h = jnp.clip(op[0].astype(_I32), 0, hp.hid.shape[0] - 1)
    code = op[1].astype(_I32)
    now = op[6]
    row = jax.tree.map(lambda a: a[h], hosts)
    hrow = jax.tree.map(lambda a: a[h], hp)
    # run the replay in the hosted process's dispatch context: sockets
    # it opens stamp sk_proc = app_proc (net.socket.sock_alloc), so
    # their wakes route back to the hosted slot, not process 0
    PP = row.app_node.shape[0]
    row = row.replace(app_proc=jnp.clip(op[7], 0, PP - 1).astype(_I32))

    K = results.shape[0]

    def deref(x):
        """Resolve a possibly-referencing operand to a concrete slot
        (results pack (generation << 16) | slot for opens)."""
        j = jnp.clip(-x - 2, 0, K - 1).astype(_I32)
        rj = results[j]
        slot_j = jnp.where(rj >= 0, rj & 0xFFFF, -1).astype(jnp.int64)
        return jnp.where(x >= -1, x, slot_j)

    # Only SOCKET-SLOT operands may be same-batch references; derefing
    # every word would corrupt legitimate negative scalars (e.g. an
    # app-chosen negative timer tag). Slot operands by opcode: word 2
    # for WRITE/SENDTO/CLOSE — opens return slots, they never take them.
    slot_op = (code == OP_TCP_WRITE) | (code == OP_UDP_SENDTO) | \
              (code == OP_CLOSE) | (code == OP_ABORT)
    # NOTE: pipe handles resolve host-side (pipe opens bind both
    # halves from one packed result), so OP_PIPE_OPEN takes no slot
    # operands and pipe writes/closes arrive as ordinary slot ints
    op = jnp.stack([op[0], op[1],
                    jnp.where(slot_op, deref(op[2]), op[2]),
                    op[3], op[4], op[5], op[6]])

    def op_nop(r):
        return r, _I32(-1)

    def _slot_result(r, slot, ok):
        # pack (generation << 16) | slot so the host side can bind the
        # handle to this exact socket incarnation (slots are recycled)
        from ..core.rowops import rget as _rget
        gen = _rget(r.sk_timer_gen, slot) & 0x7FFF
        return jnp.where(ok, (gen << 16) | slot, -1).astype(_I32)

    def op_udp_open(r):
        r, slot, ok = _udp_open_bridge(r, op[2].astype(_I32))
        return r, _slot_result(r, slot, ok)

    def op_listen(r):
        r, slot, ok = tcp_listen(r, op[2].astype(_I32))
        return r, _slot_result(r, slot, ok)

    def op_connect(r):
        r, slot, ok = tcp_connect(r, hrow, sh, now,
                                  dst_host=op[2].astype(_I32),
                                  dst_port=op[3].astype(_I32),
                                  tag=op[4].astype(_I32))
        return r, _slot_result(r, slot, ok)

    def op_write(r):
        # pipes share the write/close verbs (descriptor-uniform, like
        # the reference's transport vtable); dispatch on the proto
        from ..net.channel import PROTO_PIPE, pipe_write
        slot = op[2].astype(_I32)
        is_pipe = rget(r.sk_proto, slot) == PROTO_PIPE
        r = jax.lax.cond(
            is_pipe,
            lambda r2: pipe_write(r2, now, slot, op[3]),
            lambda r2: tcp_write(r2, now, slot, op[3]), r)
        return r, _I32(0)

    def op_sendto(r):
        r = udp_sendto(r, hrow, now, op[2].astype(_I32),
                       dst_host=op[3].astype(_I32),
                       dst_port=(op[4] >> 32).astype(_I32),
                       nbytes=op[5],
                       aux=(op[4] & 0xFFFFFFFF).astype(_I32))
        return r, _I32(0)

    def op_close(r):
        from ..net.channel import PROTO_PIPE, pipe_close
        slot = op[2].astype(_I32)
        is_pipe = rget(r.sk_proto, slot) == PROTO_PIPE
        r = jax.lax.cond(
            is_pipe,
            lambda r2: pipe_close(r2, now, slot),
            lambda r2: tcp_close_call(r2, now, slot), r)
        return r, _I32(0)

    def op_timer(r):
        # slotless wake: P.SRC carries the process slot (the same
        # convention modeled apps use, apps.base.schedule_wake) so the
        # timer returns to the hosted process on a multi-process host
        wake = rset(rset(rset(rset(jnp.zeros((P.PKT_WORDS,), _I32),
                                   P.ACK, _I32(WAKE_TIMER)),
                              P.SEQ, _I32(-1)),
                         P.AUX, op[3].astype(_I32)),
                    P.SRC, r.app_proc)
        r = equeue.q_push(r, op[2], EV_APP, wake)
        return r, _I32(0)

    def op_pipe_open(r):
        from ..core.rowops import rget as _rget
        from ..net.channel import pipe_open
        r, a, b, ok = pipe_open(r)
        # pack BOTH halves with their generations:
        # gen_a(7) | slot_a(8) | gen_b(7) | slot_b(8) — 30 bits.
        # The 8-bit slot fields require scap <= 256 (validated at
        # Simulation build for hosted scenarios); the 7-bit gen
        # window means a slot recycled >127 times between an open
        # and its close could alias — acceptable for pipe lifetimes,
        # which are bounded by one hosted process's run
        gen_a = _rget(r.sk_timer_gen, a) & 0x7F
        gen_b = _rget(r.sk_timer_gen, b) & 0x7F
        packed = ((gen_a << 23) | ((a & 0xFF) << 15) |
                  (gen_b << 8) | (b & 0xFF))
        return r, jnp.where(ok, packed, -1).astype(_I32)

    def op_abort(r):
        # abortive teardown (supervisor path): pipes just close; TCP
        # resets an established peer, frees anything else
        from ..net.channel import PROTO_PIPE, pipe_close
        from ..net.tcp import tcp_abort_call
        slot = op[2].astype(_I32)
        is_pipe = rget(r.sk_proto, slot) == PROTO_PIPE
        r = jax.lax.cond(
            is_pipe,
            lambda r2: pipe_close(r2, now, slot),
            lambda r2: tcp_abort_call(r2, now, slot), r)
        return r, _I32(0)

    row, result = jax.lax.switch(
        jnp.clip(code, 0, 9),
        [op_nop, op_udp_open, op_listen, op_connect, op_write, op_sendto,
         op_close, op_timer, op_pipe_open, op_abort], row)
    # restore the between-dispatches invariant (app_proc == 0)
    row = row.replace(app_proc=_I32(0))
    hosts = jax.tree.map(lambda a, v: a.at[h].set(v), hosts, row)
    return hosts, result


def _udp_open_bridge(row, port):
    """udp_open with a traced port scalar (0 = pick ephemeral) — the
    net.udp version branches on a Python-level `port=None` instead."""
    from ..net.socket import sock_alloc, alloc_eport
    row, slot, ok = sock_alloc(row, P.PROTO_UDP)
    row, ep = alloc_eport(row)
    p = jnp.where(port > 0, port, ep)
    row = row.replace(sk_lport=rset_where(row.sk_lport, slot, ok, p))
    return row, slot, ok


def apply_ops(hosts, hp, sh, ops):
    """Apply a padded [K, OP_WORDS] int64 op batch sequentially (ops on
    the same host must compose), then clear the wake rings. Returns
    (hosts, results[K] int32)."""
    # op replay is the second state-mutation boundary beside the drain
    # (engine.window.step_one_host): decode the narrow at-rest layout
    # once for the whole batch, replay against wide rows (sock_alloc
    # and the tcp/udp calls write wide dtypes), re-encode on return.
    # Static-dtype keyed, so wide-state runs trace zero conversions.
    from ..engine.state import narrow_state, widen_state
    hosts, was_narrow = widen_state(hosts)

    def body(i, carry):
        hosts, results = carry
        hosts, res = _apply_one(hosts, hp, sh, ops[i], results)
        return hosts, results.at[i].set(res)

    K = ops.shape[0]
    results = jnp.full((K,), -1, _I32)
    hosts, results = jax.lax.fori_loop(0, K, body, (hosts, results))
    hosts = hosts.replace(hw_cnt=jnp.zeros_like(hosts.hw_cnt))
    if was_narrow:
        hosts = narrow_state(hosts)
    return hosts, results


from ..core.jitcache import AotJit  # noqa: E402  (see jitcache docstring)

apply_ops_jit = AotJit(apply_ops, donate_argnums=(0,),
                       cache_scope="apply_ops")
