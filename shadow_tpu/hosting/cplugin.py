"""Native (C ABI) hosted plugins.

The reference hosts unmodified ELF binaries by interposing libc and
loading each instance into its own linker namespace (SURVEY §2.4/2.5:
elf-loader + rpth + libshadow-interpose). The TPU-native equivalent
keeps the same boundary with explicit mechanics: a plugin is a shared
object exporting event callbacks against a syscall vtable — the same
HostedApp surface Python apps use, crossing into C via ctypes. Every
host instance gets its own opaque state pointer, so one .so serves
thousands of isolated instances (the role dlmopen namespaces played).

C ABI (see examples/plugins/cping.c):

    typedef struct {
        long long (*now)(void* os);          // sim time ns
        double    (*rnd)(void* os);          // deterministic uniform
        int  (*udp_open)(void* os, int port);     // -> pending sock id
        int  (*tcp_connect)(void* os, int dst_host, int port, int tag);
        int  (*tcp_listen)(void* os, int port);
        void (*send_to)(void* os, int sock, int dst_host, int port,
                        long long nbytes, int aux);
        void (*write_sk)(void* os, int sock, long long nbytes);
        void (*close_sk)(void* os, int sock);
        void (*timer)(void* os, long long delay_ns, int tag);
        int  (*resolve)(void* os, const char* name);
    } shadow_os_api;

    void* plugin_create(const char* args);
    void  plugin_destroy(void* st);
    // reasons mirror engine.defs WAKE_*; a/b/c carry slot/src/len|tag
    void  plugin_on_wake(void* st, void* os, const shadow_os_api* api,
                         int reason, int a, int b, long long c);

Socket ids on the C side are the HostOS pending handles resolved after
the batch applies (the same deferred-binding Python apps get).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

from .api import HostedApp, register

_API_FIELDS = [
    ("now", ctypes.CFUNCTYPE(ctypes.c_longlong, ctypes.c_void_p)),
    ("rnd", ctypes.CFUNCTYPE(ctypes.c_double, ctypes.c_void_p)),
    ("udp_open", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                  ctypes.c_int)),
    ("tcp_connect", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                     ctypes.c_int, ctypes.c_int,
                                     ctypes.c_int)),
    ("tcp_listen", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                    ctypes.c_int)),
    ("send_to", ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int,
                                 ctypes.c_int, ctypes.c_int,
                                 ctypes.c_longlong, ctypes.c_int)),
    ("write_sk", ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_longlong)),
    ("close_sk", ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int)),
    ("timer", ctypes.CFUNCTYPE(None, ctypes.c_void_p,
                               ctypes.c_longlong, ctypes.c_int)),
    ("resolve", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                 ctypes.c_char_p)),
]


class _OsApi(ctypes.Structure):
    _fields_ = _API_FIELDS


_loaded = {}


def _load(so_path: str):
    lib = _loaded.get(so_path)
    if lib is None:
        lib = ctypes.CDLL(so_path)
        lib.plugin_create.restype = ctypes.c_void_p
        lib.plugin_create.argtypes = [ctypes.c_char_p]
        lib.plugin_destroy.argtypes = [ctypes.c_void_p]
        lib.plugin_on_wake.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(_OsApi),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_longlong]
        _loaded[so_path] = lib
    return lib


def build_plugin(c_path: str, so_path: str = None) -> str:
    """Compile a plugin source with g++ (once; mtime-checked)."""
    so_path = so_path or os.path.splitext(c_path)[0] + ".so"
    if (not os.path.exists(so_path)
            or os.path.getmtime(so_path) < os.path.getmtime(c_path)):
        subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o", so_path,
                        c_path], check=True, capture_output=True)
    return so_path


class CPluginApp(HostedApp):
    """Bridges one native plugin instance into the HostedApp callbacks.

    Socket identity: the C side works with small integer handles that
    index this instance's Sock table (pending HostOS handles); wakes
    translate device slots back to those handles.
    """

    def __init__(self, so_path: str, args: str):
        self.lib = _load(so_path)
        self.state = self.lib.plugin_create(args.encode())
        self._socks = []           # handle -> Sock (None = retired)
        self._free_handles = []    # retired handle indices for reuse
        self._handle_of = {}       # id(Sock) -> handle (stable: HostOS
        #   returns one object per connection incarnation)
        self._os = None
        # keep callback objects alive for the instance lifetime
        self._cbs = self._make_api()

    # --- C -> HostOS trampolines ---
    def _make_api(self):
        def now(_):
            return self._os.now()

        def rnd(_):
            return self._os.random()

        def _new_handle(sock) -> int:
            if self._free_handles:
                h = self._free_handles.pop()
                self._socks[h] = sock
            else:
                self._socks.append(sock)
                h = len(self._socks) - 1
            self._handle_of[id(sock)] = h
            return h

        self._new_handle = _new_handle

        def udp_open(_, port):
            return _new_handle(self._os.udp_open(port))

        def tcp_connect(_, dst, port, tag):
            return _new_handle(self._os.tcp_connect(dst, port, tag))

        def tcp_listen(_, port):
            return _new_handle(self._os.tcp_listen(port))

        def _live(h):
            """Handle -> Sock, or None for retired/invalid handles
            (double close or use-after-close from the plugin is treated
            as a no-op, like writes on a closed fd returning EBADF)."""
            if 0 <= h < len(self._socks):
                return self._socks[h]
            return None

        def send_to(_, h, dst, port, nbytes, aux):
            sock = _live(h)
            if sock is not None:
                self._os.sendto(sock, dst, port, nbytes, aux)

        def write_sk(_, h, nbytes):
            sock = _live(h)
            if sock is not None:
                self._os.write(sock, nbytes)

        def close_sk(_, h):
            sock = _live(h)
            if sock is None:
                return
            self._os.close(sock)
            # retire the handle: bounded by open sockets, not by
            # connections ever opened
            self._handle_of.pop(id(sock), None)
            self._socks[h] = None
            self._free_handles.append(h)

        def timer(_, delay_ns, tag):
            self._os.timer(delay_ns, tag)

        def resolve(_, name):
            return self._os.resolve(name.decode())

        fns = dict(now=now, rnd=rnd, udp_open=udp_open,
                   tcp_connect=tcp_connect, tcp_listen=tcp_listen,
                   send_to=send_to, write_sk=write_sk, close_sk=close_sk,
                   timer=timer, resolve=resolve)
        cbs = {k: t(fns[k]) for k, t in _API_FIELDS}
        self._api = _OsApi(**cbs)
        return cbs

    def _handle_of_slot(self, sock) -> int:
        # HostOS hands back ONE Sock object per connection incarnation
        # (keyed by slot+generation), so object identity is the stable
        # mapping — recycled slots and late post-close wakes both
        # resolve to the right handle.
        h = self._handle_of.get(id(sock))
        if h is None:
            h = self._new_handle(sock)
        return h

    def _wake(self, os, reason, a=0, b=0, c=0):
        self._os = os
        self.lib.plugin_on_wake(self.state, None,
                                ctypes.byref(self._api),
                                reason, a, b, c)

    # --- HostedApp surface ---
    def on_start(self, os):
        self._wake(os, 0)

    def on_timer(self, os, tag):
        self._wake(os, 1, a=tag)

    def on_dgram(self, os, sock, src, sport, nbytes, aux):
        self._wake(os, 2, a=self._handle_of_slot(sock), b=src,
                   c=(aux << 32) | (nbytes & 0xFFFFFFFF))

    def on_connected(self, os, sock, **_identity):
        self._wake(os, 3, a=self._handle_of_slot(sock))

    def on_eof(self, os, sock):
        self._wake(os, 4, a=self._handle_of_slot(sock))

    def on_accept(self, os, sock, tag, dport=0, peer=(0, 0)):
        self._wake(os, 5, a=self._handle_of_slot(sock), b=tag)

    def on_sent(self, os, sock):
        self._wake(os, 6, a=self._handle_of_slot(sock))


def register_c_plugin(name: str, c_or_so_path: str):
    """Register a native plugin under ``hosted:<name>``."""
    path = c_or_so_path
    if path.endswith((".c", ".cpp", ".cc")):
        path = build_plugin(path)
    register(name, lambda args: CPluginApp(path, args))
