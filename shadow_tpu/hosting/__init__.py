"""App hosting: real application code on CPU, transport on device.

The TPU-native replacement for the reference's plugin machinery
(LD_PRELOAD interposition + elf-loader namespaces + rpth green threads,
SURVEY §2.4/2.5): hosted apps implement :class:`HostedApp` callbacks
against a :class:`HostOS` syscall surface; the engine delivers wakes
and applies syscall batches at lookahead-window boundaries
(hosting.bridge / hosting.runtime).
"""

from .api import HostOS, HostedApp, Sock, register, lookup

__all__ = ["HostOS", "HostedApp", "Sock", "register", "lookup"]
