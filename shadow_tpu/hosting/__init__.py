"""hosting subpackage."""
