"""CPU half of the hosting bridge: drain wakes, run app code, batch ops.

Drives hosted apps between lookahead windows. The dispatch order is
deterministic: wake records sort by (time, host, ring index) before
delivery, and per-host RNG streams are seeded from the scenario seed —
the same guarantees the reference's deterministic scheduler provides to
plugins (SURVEY §4 determinism tests).
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..engine.defs import (WAKE_START, WAKE_TIMER, WAKE_SOCKET,
                           WAKE_CONNECTED, WAKE_EOF, WAKE_ACCEPT, WAKE_SENT)
from ..net import packet as P
from ..obs import digest as _DG
from .api import HostOS
from .bridge import OP_WORDS, apply_ops_jit

# first link of the hosted op-stream digest chain (see _op_chain)
OPS_CHAIN_SEED = hashlib.blake2b(
    b"shadow_tpu.hosted.ops.v1", digest_size=8).hexdigest()


class HostingRuntime:
    """Owns the hosted app instances and the window-boundary exchange."""

    def __init__(self, apps: dict, names: dict, dns, seed: int,
                 batch_cap: int = 256, procs: dict = None,
                 factories: dict = None):
        # apps: host_id -> HostedApp; names: host_id -> hostname;
        # procs: host_id -> the hosted process's slot on its host
        # (0 when the hosted app is the only process — the op replay
        # stamps it so sockets wake the hosted slot, not process 0);
        # factories: host_id -> zero-arg callable producing a FRESH
        # app instance (fault-injection restarts respawn through it)
        self.apps = apps
        self.procs = procs or {}
        self.factories = factories or {}
        self.names = names
        self.batch_cap = batch_cap
        self._dns = dns
        self._now = 0
        self._journal_on = False    # enable_journal(): checkpoint runs
        #   journal each child's protocol stream for resume replay
        # hosted-channel op-stream digest (obs.digest): a rolling
        # CHAIN hash over every applied op batch — with the per-app
        # shim request digests it attributes a determinism divergence
        # to the hosted tier. A chain (hash of previous hex + batch)
        # rather than one long hash object so checkpoints can carry it
        # (hashlib midstates do not pickle). Updated only while a
        # digest recorder is installed.
        self._op_chain = OPS_CHAIN_SEED
        self._dead = set()      # generic apps killed by a fault (shim
        #   apps self-guard; these need their wakes suppressed here)
        self._exit_log = {}     # host_id -> exit record of the LAST
        #   death (a restarted-and-surviving child leaves no record)
        # one per-simulation payload broker (api.PayloadBroker): hosted
        # apps that move REAL bytes (the LD_PRELOAD shim) share it so
        # hosted<->hosted TCP connections deliver actual payloads
        from .api import PayloadBroker
        self.payloads = PayloadBroker()
        for app in apps.values():
            attach = getattr(app, "attach_payload_broker", None)
            if attach is not None:
                attach(self.payloads)
        self.os = {
            hid: HostOS(hid, names.get(hid, f"host{hid}"),
                        np.random.default_rng((seed, hid)), dns,
                        lambda: self._now)
            for hid in apps
        }

    def shutdown(self):
        """End-of-run teardown: release apps holding OS resources
        (e.g. the LD_PRELOAD shim's child process) — a stop_time
        truncation otherwise leaks them."""
        for app in self.apps.values():
            terminate = getattr(app, "terminate", None)
            if terminate is not None:
                terminate()

    def has_hosts(self) -> bool:
        return bool(self.apps)

    # --- supervision / fault-injection surface (engine.faults) ---
    def kill_host(self, hid: int, cause: str, sim_ns: int):
        """host_down: SIGKILL a shim child (ShimApp.fault_kill records
        the cause); a pure-Python hosted app just stops receiving
        wakes. The injector scrubs the device state itself."""
        app = self.apps.get(hid)
        if app is None:
            return
        fk = getattr(app, "fault_kill", None)
        if fk is not None:
            fk(cause, sim_ns)
        else:
            self._dead.add(hid)
            self._exit_log[hid] = {"exit_status": None, "cause": cause,
                                   "sim_ns": sim_ns, "clean": False,
                                   "violations": []}

    def restart_host(self, hid: int):
        """host_up: archive the dead instance's exit record and swap
        in a FRESH app from its factory (a shim app respawns its child
        on the WAKE_START the injector re-arms). The HostOS — and with
        it the per-host RNG stream — carries over: the restarted
        process continues the host's deterministic entropy sequence."""
        old = self.apps.get(hid)
        if old is not None:
            # a host_up with no preceding host_down replaces a LIVE
            # instance: reap its child/channel first or the orphan
            # process outlives the simulation (end-of-run shutdown
            # only walks the current apps)
            fk = getattr(old, "fault_kill", None)
            if fk is not None:
                fk("fault: host_up replaced the live instance", None)
            info = getattr(old, "exit_info", None)
            rec = info() if info is not None else None
            if rec is not None:
                self._exit_log[hid] = rec
        factory = self.factories.get(hid)
        if factory is None:
            self._dead.discard(hid)
            return
        app = factory()
        attach = getattr(app, "attach_payload_broker", None)
        if attach is not None:
            attach(self.payloads)
        if self._journal_on:
            ej = getattr(app, "enable_journal", None)
            if ej is not None:
                ej()
        self.apps[hid] = app
        self._dead.discard(hid)

    # --- checkpoint/resume (engine.checkpoint hosted sidecar) ---
    def enable_journal(self):
        """Checkpointed runs journal each shim child's protocol
        stream so a resume can fast-forward a respawned child by
        deterministic replay (docs/durability.md). Must be enabled
        before children spawn (engine.sim does, before the run loop).
        The journal grows with the child's syscall traffic for the
        whole run — the documented price of hosted resumability."""
        self._journal_on = True
        for app in self.apps.values():
            ej = getattr(app, "enable_journal", None)
            if ej is not None:
                ej()

    def snapshot(self) -> bytes:
        """Pickle the hosted tier for one checkpoint: app instances
        (ShimApp excludes its live process/channel and keeps the
        journal), per-host OS state (PRNG + live socket handles — ONE
        pickle, so Sock identity shared between HostOS and app state
        survives), the payload broker, and the op-stream chain.
        Runs at a window boundary: every pending op batch has been
        flushed and every live child is parked in a blocked call."""
        import pickle
        for os_ in self.os.values():
            assert not os_._ops, \
                "hosted snapshot mid-batch (ops not flushed)"
        state = {
            "version": 1,
            "op_chain": self._op_chain,
            "dead": set(self._dead),
            "exit_log": dict(self._exit_log),
            "payload_streams": self.payloads._streams,
            "payload_subs": self.payloads._subs,
            "apps": dict(self.apps),
            "os": {hid: {"rng": o._rng, "socks": o._socks}
                   for hid, o in self.os.items()},
        }
        try:
            return pickle.dumps(state,
                                protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            raise RuntimeError(
                "hosted tier is not snapshotable: a hosted app holds "
                f"unpicklable state ({type(e).__name__}: {e}); give "
                "it __getstate__/__setstate__ like hosting.shim."
                "ShimApp") from e

    def restore(self, blob: bytes):
        """Rebuild the hosted tier from a checkpoint sidecar, then
        fast-forward each shim child by replaying its journaled
        protocol stream (ShimApp.resume_replay): the respawned binary
        re-executes deterministically (time, entropy and I/O are
        virtualized), re-issues the same requests, and receives the
        journaled responses — byte divergence is diagnosed loudly in
        SimReport.hosted and the child is killed, never desynced."""
        import pickle
        state = pickle.loads(blob)
        self._op_chain = state["op_chain"]
        self._dead = state["dead"]
        self._exit_log = state["exit_log"]
        self.payloads._streams = state["payload_streams"]
        self.payloads._subs = state["payload_subs"]
        self.apps = state["apps"]
        self.os = {}
        for hid, osd in state["os"].items():
            o = HostOS(hid, self.names.get(hid, f"host{hid}"),
                       osd["rng"], self._dns, lambda: self._now)
            o._socks = osd["socks"]
            self.os[hid] = o
        for hid, app in sorted(self.apps.items()):
            attach = getattr(app, "attach_payload_broker", None)
            if attach is not None:
                attach(self.payloads)
            if self._journal_on:
                ej = getattr(app, "enable_journal", None)
                if ej is not None:
                    ej()
        # replay AFTER the whole tier is rewired (a replaying child's
        # payload pops must see the restored broker)
        for hid, app in sorted(self.apps.items()):
            rr = getattr(app, "resume_replay", None)
            if rr is not None:
                rr(self.os[hid])
        if not self._journal_on:
            # this run takes no further snapshots, so the restored
            # journals have no consumer left — drop them instead of
            # buffering the rest of the run's traffic
            for app in self.apps.values():
                dj = getattr(app, "disable_journal", None)
                if dj is not None:
                    dj()

    def exit_info(self) -> dict:
        """Per-host exit report, keyed by hostname (SimReport.hosted):
        the latest death of each hosted process, including children
        reaped at end-of-run shutdown."""
        out = {}
        for hid, app in sorted(self.apps.items()):
            rec = None
            info = getattr(app, "exit_info", None)
            if info is not None:
                rec = info()
            if rec is None:
                rec = self._exit_log.get(hid)
            if rec is not None:
                out[self.names.get(hid, f"host{hid}")] = rec
        return out

    def digest_state(self) -> dict:
        """Hosted-tier digests for one obs.digest record: the running
        op-batch stream hash plus each shim app's protocol-request
        stream hash (hostname-keyed — stable across runs)."""
        out = {"ops": self._op_chain}
        shim = {}
        for hid, app in sorted(self.apps.items()):
            f = getattr(app, "op_stream_digest", None)
            if f is not None:
                shim[self.names.get(hid, f"host{hid}")] = f()
        if shim:
            out["shim"] = shim
        return out

    def child_rss(self) -> dict:
        """host_id -> resident-set bytes of live hosted children (the
        [ram] tracker heartbeat column; obs.tracker)."""
        out = {}
        for hid, app in self.apps.items():
            rss = getattr(app, "rss_bytes", None)
            if rss is not None:
                v = rss()
                if v is not None:
                    out[hid] = v
        return out

    def step(self, hosts, hp, sh, now_ns: int):
        """Drain wake rings, dispatch app callbacks, apply the op batch.
        Returns updated hosts."""
        hw_cnt = np.asarray(hosts.hw_cnt)
        if not hw_cnt.any():
            return hosts
        hw_time = np.asarray(hosts.hw_time)
        hw_pkt = np.asarray(hosts.hw_pkt)

        # deterministic delivery order: (time, host, ring index)
        recs = []
        for hid in np.flatnonzero(hw_cnt):
            for i in range(int(hw_cnt[hid])):
                recs.append((int(hw_time[hid, i]), int(hid), i))
        recs.sort()

        for t, hid, i in recs:
            app = self.apps.get(hid)
            if app is None or hid in self._dead:
                continue
            os = self.os[hid]
            self._now = t
            wake = hw_pkt[hid, i]
            reason = int(wake[P.ACK])
            slot = int(wake[P.SEQ])
            gen = int(wake[P.WND]) & 0x7FFF
            sock = os.sock_for(slot, gen) if slot >= 0 else None
            if reason == WAKE_START:
                app.on_start(os)
            elif reason == WAKE_TIMER:
                app.on_timer(os, int(wake[P.AUX]))
            elif reason == WAKE_CONNECTED:
                # the connected wake rides the SYN|ACK: SRC/SPORT are
                # the server's identity, DPORT our local ephemeral port
                app.on_connected(os, sock,
                                 lport=int(wake[P.DPORT]),
                                 peer=(int(wake[P.SRC]),
                                       int(wake[P.SPORT])))
            elif reason == WAKE_ACCEPT:
                # the accept wake rides the SYN packet: SRC/SPORT are
                # the connecting client's identity, DPORT the listener
                app.on_accept(os, sock, int(wake[P.APP]),
                              dport=int(wake[P.DPORT]),
                              peer=(int(wake[P.SRC]),
                                    int(wake[P.SPORT])))
            elif reason == WAKE_EOF:
                app.on_eof(os, sock)
            elif reason == WAKE_SENT:
                app.on_sent(os, sock)
            elif reason == WAKE_SOCKET:
                app.on_dgram(os, sock, int(wake[P.SRC]), int(wake[P.SPORT]),
                             int(wake[P.LEN]), int(wake[P.AUX]))

        self._now = now_ns
        return self._flush(hosts, hp, sh, now_ns)

    def _flush(self, hosts, hp, sh, now_ns: int):
        """Apply all pending ops as one batch and bind returned socket
        slots to their Sock handles. Operands that are still-unresolved
        Socks from this batch are encoded as result references
        (-(k+2) for op k), decoded on device — create-before-use holds
        because each host's ops keep insertion order."""
        import jax.numpy as jnp
        from .api import Sock

        pending = []  # (hid, os, op) in deterministic host order
        for hid in sorted(self.os):
            os = self.os[hid]
            for op in os._ops:
                pending.append((hid, os, op))
            os._ops = []

        if not pending:
            # nothing to apply: just clear the drained wake rings
            return hosts.replace(hw_cnt=jnp.zeros_like(hosts.hw_cnt))

        # one batch, padded up to a multiple of 64 (a handful of
        # distinct batch shapes keeps recompiles rare)
        K = -(-len(pending) // 64) * 64
        ops = np.zeros((K, OP_WORDS), dtype=np.int64)
        ref_of = {}  # Sock object -> creating op index
        for k, (hid, os, op) in enumerate(pending):
            if op.out is not None and not isinstance(op.out, tuple):
                ref_of[id(op.out)] = k   # pipe pairs (tuples) cannot
                # be same-batch referenced: one result names two socks

            def enc(x):
                if isinstance(x, Sock):
                    j = ref_of.get(id(x))
                    if j is None:
                        raise RuntimeError(
                            "Sock used before any op created it")
                    return -(j + 2)
                return int(x)

            ops[k] = (hid, op.code, enc(op.a), enc(op.b), enc(op.c),
                      enc(op.d), op.t, self.procs.get(hid, 0))
        if _DG.ENABLED:
            # the un-padded batch IS the hosted-channel op stream the
            # device replays — chain-hash it in flush order
            self._op_chain = hashlib.blake2b(
                bytes.fromhex(self._op_chain) +
                ops[:len(pending)].tobytes(),
                digest_size=8).hexdigest()
        hosts, results = apply_ops_jit(hosts, hp, sh, jnp.asarray(ops))
        res = np.asarray(results)
        for k, (hid, os, op) in enumerate(pending):
            if isinstance(op.out, tuple):
                os._bind_pipe(op.out[0], op.out[1], int(res[k]))
            elif op.out is not None:
                os._bind(op.out, int(res[k]))
        return hosts
