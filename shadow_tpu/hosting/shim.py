"""Unmodified-binary hosting: the LD_PRELOAD shim bridge.

The reference's defining trick is running real, unmodified binaries by
interposing 262 libc symbols (/root/reference/src/preload/
shd-interposer.c:211-222, shd-preload-defs.h) and re-entering blocked
app code with green threads (shd-process.c:1076-1263). This module is
the TPU build's minimal realization of that capability for epoll-style
network clients:

- the REAL binary runs as a separate OS process with
  ``libshadow_shim.so`` LD_PRELOADed (hosting/shim_preload.c);
- the shim interposes the socket/epoll/clock libc surface and forwards
  each call over an inherited socketpair to :class:`ShimApp`, a hosted
  app (hosting.api) inside the simulator;
- blocking semantics replace rpth: the binary only ever blocks inside
  a forwarded ``epoll_wait``; the simulator answers it when a device
  wake (connection established, bytes delivered, EOF) maps to a
  registered epoll interest — so simulated time never advances while
  app code runs, exactly the reference's cooperative model;
- TCP payload bytes are MATERIALIZED host-side (round 4): the engine
  models byte counts and timing, while the real bytes ride the control
  channel into a per-connection FIFO (api.PayloadBroker) keyed by the
  TCP 4-tuple both endpoints derive from their establishment wakes.
  Delivered counts are in-order stream advances bounded by what was
  sent, so popping the FIFO reproduces exactly the bytes a real
  network would deliver — payload-parsing binaries (HTTP-style
  request/response) run unmodified when both endpoints are hosted.
  A hosted endpoint talking to a MODELED app still sees zero-fill
  (modeled apps have no real bytes), and UDP datagram payloads are
  not materialized.

Scenario usage: plugin="hosted:shim" with arguments
``[out=<stdout file>] cmd=<binary> [child args...]`` — cmd paths
resolve like any exec (absolute, or relative to the process CWD). The
preload library builds on demand with cc into SHADOW_SHIM_BUILD or the
temp dir (hosting.shim.build_shim).

Protocol (one request, one response, in lockstep — the child is
single-threaded between epoll_waits):
  request  = <iiqq64s>  op, a, b, c, name  (88 bytes)
  response = <qqq>      r0, r1, r2         (24 bytes)
  OP_EPOLL_WAIT responses with r0 = n > 0 carry n trailing <qq>
  (fd, events) pairs — multi-event waits honoring maxevents.
  OP_SEND requests on STREAM sockets carry b trailing payload bytes
  (the app's real buffer; both ends key the same per-vfd dgram
  table); successful OP_RECV responses with r1 == 1 carry r0 trailing
  payload bytes (real stream contents — r1 == 0 means no live stream
  covers the read and the C side zero-fills locally). Datagram
  OP_SEND, OP_SENDTO and OP_RECVFROM never carry payload.

Round 3: the full SERVER path (bind/listen/accept) and UDP
(sendto/recvfrom) — an unmodified epoll server binary accepts
simulated clients, mirroring the reference's server-side process_emu
surface (shd-process.c:1993-2605).

Round 4: BLOCKING semantics — per-vfd O_NONBLOCK tracking (fcntl,
SOCK_NONBLOCK, ioctl FIONBIO) with blocking connect/recv/recvfrom/
accept parking until their wake, which is what lets stock
blocking-socket binaries (e.g. the CPython interpreter running a
plain socket script, tests/test_shim.py) run unmodified. Known gap:
poll()/select() are not interposed, so clients that wait with those
(e.g. CPython sockets with a TIMEOUT set, which go nonblocking and
poll internally) need the epoll or plain-blocking style instead.
"""

from __future__ import annotations

import os as _os
import struct
import subprocess

from .api import HostedApp, register

REQ = struct.Struct("<iiqq64s")
RSP = struct.Struct("<qqq")
EVPAIR = struct.Struct("<qq")

OP_SOCKET = 1
OP_CONNECT = 2
OP_SEND = 3
OP_RECV = 4
OP_CLOSE = 5
OP_SHUTDOWN = 6
OP_EPOLL_CREATE = 7
OP_EPOLL_CTL = 8
OP_EPOLL_WAIT = 9
OP_CLOCK = 10
OP_RESOLVE = 11
OP_BIND = 12
OP_LISTEN = 13
OP_ACCEPT = 14
OP_SENDTO = 15
OP_RECVFROM = 16

EPOLLIN = 0x001
EPOLLOUT = 0x004
EPOLLRDHUP = 0x2000
EPOLLHUP = 0x010
EINPROGRESS = 115
ENOTCONN = 107
EAGAIN = 11
ECONNREFUSED = 111

EPOLL_CTL_ADD = 1
EPOLL_CTL_DEL = 2
EPOLL_CTL_MOD = 3

_SRC = _os.path.dirname(_os.path.abspath(__file__))
SHIM_C = _os.path.join(_SRC, "shim_preload.c")


def build_shim(out_dir: str = None) -> str:
    """Compile the preload library (cached). -> .so path

    Builds into SHADOW_SHIM_BUILD or a per-user 0700 cache directory —
    never next to the target binary (may be read-only) and never at a
    predictable path in the world-writable system temp dir (another
    local user could pre-plant a .so there that would then be
    LD_PRELOADed into our child processes). A cached .so is reused
    only if we own it and it is not group/other-writable."""
    if out_dir is None:
        out_dir = _os.environ.get("SHADOW_SHIM_BUILD")
    if out_dir is None:
        base = _os.environ.get("XDG_CACHE_HOME",
                               _os.path.join(_os.path.expanduser("~"),
                                             ".cache"))
        out_dir = _os.path.join(base, "shadow_tpu")
    _os.makedirs(out_dir, mode=0o700, exist_ok=True)
    so = _os.path.join(out_dir, "libshadow_shim.so")
    if (_os.path.exists(so) and
            _os.path.getmtime(so) >= _os.path.getmtime(SHIM_C)):
        st = _os.stat(so)
        if st.st_uid == _os.getuid() and not (st.st_mode & 0o022):
            return so
    subprocess.run(["cc", "-shared", "-fPIC", "-O2", "-o", so, SHIM_C,
                    "-ldl"], check=True)
    _os.chmod(so, 0o755)
    return so


class _VSock:
    """Shim-side view of one virtual socket fd."""

    __slots__ = ("sock", "avail", "eof", "connected", "closed", "key",
                 "kind", "bound_port", "accept_q", "dgrams", "dgram_dst",
                 "conn", "is_client", "pending_tx")

    def __init__(self, kind="tcp"):
        self.sock = None        # hosting.api.Sock once connect issued
        self.avail = 0          # delivered-but-unread byte count
        self.eof = False
        self.connected = False
        self.closed = False
        self.key = None         # (slot, gen) once resolved
        self.kind = kind        # "tcp" | "udp" | "listen"
        self.bound_port = 0
        self.accept_q = []      # listener: (child Sock, src, sport, conn)
        self.dgrams = []        # udp: (src_host, sport, nbytes)
        self.dgram_dst = None   # udp: connect()ed default destination
        # TCP payload stream identity (api.PayloadBroker): the
        # canonical (cli_host, cli_port, srv_host, srv_port) both
        # endpoints derive, or None until the connection resolves
        self.conn = None
        self.is_client = False
        self.pending_tx = []    # payloads written before conn resolved


class ShimApp(HostedApp):
    """Hosts one real binary behind the LD_PRELOAD shim (module doc)."""

    def __init__(self, args: str):
        parts = args.split()
        i = next((j for j, p in enumerate(parts)
                  if p.startswith("cmd=")), None)
        if i is None:
            raise ValueError("hosted:shim needs cmd=<binary> argument")
        # shim options (out=...) precede cmd=; everything AFTER cmd= is
        # the child's argv verbatim
        kv = dict(p.split("=", 1) for p in parts[:i + 1] if "=" in p)
        self.argv = [kv["cmd"]] + parts[i + 1:]
        self.out_path = kv.get("out")   # child stdout -> file (tests)
        self.proc = None
        self.chan = None          # our end of the socketpair
        self.vfds = {}            # vfd -> _VSock
        self.by_sock = {}         # id(Sock) -> vfd (pre-resolution)
        self.by_key = {}          # (slot, gen) -> vfd: wakes arriving
        # after os.close() carry a FRESH Sock object for the same
        # incarnation (HostOS retires closed handles), so identity
        # lookup alone would drop e.g. the post-shutdown EOF
        self.epolls = {}          # vepfd -> {vfd: events}
        self.next_fd = 1 << 20
        # the child's one blocked call (it is single-threaded): None,
        # ("epoll", epfd, maxev), ("connect", vfd), ("recv", vfd, n),
        # ("recvd", vfd, n) [blocking recv() on udp],
        # ("recvfrom", vfd, n), or ("accept", vfd). Blocking calls park
        # here until a wake satisfies them (_maybe_unpark) — the
        # shim's replacement for the reference's rpth block/reenter
        # (shd-process.c:1076-1263)
        self.parked = None
        self.park_seq = 0         # increments per park: stale-timeout guard
        self.exited = False
        self._payloads = None     # api.PayloadBroker (runtime attaches)
        self._opened = set()      # broker keys this app opened
        self._mysubs = set()      # the subset I subscribed (I read)
        self._vfd_dgram = {}      # vfd -> created SOCK_DGRAM (never
        #   pruned: mirrors the C side's dg table so send-payload
        #   framing agrees even for fds the app already closed)

    def attach_payload_broker(self, broker):
        """HostingRuntime wires the per-simulation PayloadBroker in:
        hosted<->hosted TCP connections then carry REAL bytes (counts
        still modeled by the engine; hosted<->modeled stays zero-fill)."""
        self._payloads = broker

    # --- payload streams (api.PayloadBroker) ---
    def _open_streams(self, vs):
        """Open both directions at establishment (writer-side open
        included: the accept wake precedes the connected wake in sim
        time, so a server's first push must not find a missing stream)
        and SUBSCRIBE the inbound one — subscription marks the stream
        as having a real reader, which exempts it from the reader-less
        cap and preserves it across the writer's close. Then flush
        sends issued before the identity resolved."""
        if self._payloads is None or vs.conn is None:
            return
        for d in (0, 1):
            key = vs.conn + (d,)
            self._payloads.open(key)
            self._opened.add(key)
        inkey = vs.conn + (1 if vs.is_client else 0,)
        self._payloads.subscribe(inkey)
        self._mysubs.add(inkey)
        if vs.pending_tx:
            out = vs.conn + (0 if vs.is_client else 1,)
            for data in vs.pending_tx:
                self._payloads.push(out, data)
            vs.pending_tx = []

    def _tx_payload(self, vs, data):
        if (self._payloads is None or vs.kind != "tcp" or not data):
            return
        if vs.conn is None:            # optimistic pre-connect write
            vs.pending_tx.append(data)
            return
        self._payloads.push(vs.conn + (0 if vs.is_client else 1,), data)

    def _rx_payload(self, vs, k):
        """Exactly k real stream bytes for a recv answer, or None when
        no live stream backs the connection (peer modeled) — the C side
        then zero-fills locally instead of moving k zeros over the
        channel."""
        if (self._payloads is None or vs is None or vs.conn is None
                or vs.kind != "tcp"):
            return None
        return self._payloads.pop(vs.conn + (1 if vs.is_client else 0,),
                                  int(k))

    # --- child lifecycle ---
    def _spawn(self):
        import socket as pysock
        ours, theirs = pysock.socketpair()
        env = dict(_os.environ)
        env["LD_PRELOAD"] = build_shim()
        env["SHADOW_SHIM_FD"] = str(theirs.fileno())
        stdout = (open(self.out_path, "w") if self.out_path else None)
        self.proc = subprocess.Popen(self.argv, env=env,
                                     pass_fds=(theirs.fileno(),),
                                     stdout=stdout)
        if stdout is not None:
            stdout.close()
        theirs.close()
        self.chan = ours

    def _read_req(self):
        buf = b""
        while len(buf) < REQ.size:
            chunk = self.chan.recv(REQ.size - len(buf))
            if not chunk:
                return None
            buf += chunk
        return REQ.unpack(buf)

    def _read_n(self, n):
        """n trailing payload bytes of an OP_SEND/OP_SENDTO request."""
        buf = bytearray()
        n = int(n)
        while len(buf) < n:
            chunk = self.chan.recv(min(n - len(buf), 1 << 20))
            if not chunk:
                return None
            buf += chunk
        return bytes(buf)

    def _rsp(self, r0=0, r1=0, r2=0):
        self.chan.sendall(RSP.pack(int(r0), int(r1), int(r2)))

    def _rsp_data(self, k, data=None):
        """OP_RECV answer: header then, when `data` is real stream
        bytes (r1 = 1), EXACTLY k trailing payload bytes. data=None
        means no live stream backs the connection — r1 = 0, no
        trailing bytes, and the C side zero-fills locally (keeps the
        hosted<->modeled hot path free of per-byte channel traffic)."""
        k = max(int(k), 0)
        if data is None:
            self.chan.sendall(RSP.pack(k, 0, 0))
            return
        out = data[:k] + b"\0" * (k - len(data))
        self.chan.sendall(RSP.pack(k, 1, 0) + out)

    # --- epoll readiness ---
    def _events_of(self, vfd):
        vs = self.vfds.get(vfd)
        if vs is None:
            return 0
        ev = 0
        if vs.kind == "listen":
            if vs.accept_q:
                ev |= EPOLLIN
            return ev
        if vs.kind == "udp":
            if vs.dgrams:
                ev |= EPOLLIN
            ev |= EPOLLOUT          # modeled datagrams never block
            return ev
        if vs.avail > 0 or vs.eof:
            ev |= EPOLLIN | (EPOLLRDHUP if vs.eof else 0)
        if vs.connected:
            ev |= EPOLLOUT
        return ev

    def _ready(self, vepfd, maxevents=1):
        hits = []
        for vfd, interest in self.epolls.get(vepfd, {}).items():
            ev = self._events_of(vfd) & (interest | EPOLLRDHUP | EPOLLHUP)
            if ev:
                hits.append((vfd, ev))
                if len(hits) >= maxevents:
                    break
        return hits

    def _rsp_events(self, hits):
        """Multi-event epoll_wait answer: header with the count, then
        one (fd, events) pair per event (shim_preload.c evpair)."""
        out = RSP.pack(len(hits), 0, 0)
        for vfd, ev in hits:
            out += EVPAIR.pack(vfd, ev)
        self.chan.sendall(out)

    def _alloc_vfd(self):
        """Next virtual fd. Fails LOUD at the preload library's
        per-vfd flag-table bound (shim_preload.c NB_CAP): past it the
        C side could no longer track O_NONBLOCK and a nonblocking
        call would silently park — wedging the child — instead of
        returning EAGAIN."""
        if self.next_fd - (1 << 20) >= (1 << 16):
            raise RuntimeError(
                "hosted binary exhausted the shim's vfd space "
                "(65536 sockets/epolls over the process lifetime)")
        vfd = self.next_fd
        self.next_fd += 1
        return vfd

    def _rsp_accept(self, vs):
        """Pop one pending child off a listener and answer the accept
        call (shared by the immediate and parked paths)."""
        child, src, sport, conn = vs.accept_q.pop(0)
        cfd = self._alloc_vfd()
        cvs = _VSock(kind="tcp")
        cvs.sock = child
        cvs.connected = True
        cvs.conn = conn
        cvs.is_client = False
        self._open_streams(cvs)
        self.vfds[cfd] = cvs
        self.by_sock[id(child)] = cfd
        if child.slot is not None:
            self.by_key[(child.slot, child.gen)] = cfd
            cvs.key = (child.slot, child.gen)
        # peer identity: (virtual host id, port) off the handshake —
        # servers keying state by accept() address see distinct
        # simulated clients
        self._rsp(cfd, src, sport)

    def _maybe_unpark(self):
        """Answer the child's parked blocking call if a wake has made
        it ready. One parked call at most (single-threaded child)."""
        if self.parked is None:
            return False
        kind = self.parked[0]
        if kind == "epoll":
            _, epfd, maxev = self.parked
            hits = self._ready(epfd, maxev)
            if not hits:
                return False
            self.parked = None
            self._rsp_events(hits)
            return True
        if kind == "connect":
            vfd = self.parked[1]
            vs = self.vfds.get(vfd)
            if vs is None or vs.eof:
                self.parked = None
                self._rsp(-1, ECONNREFUSED)
                return True
            if vs.connected:
                self.parked = None
                self._rsp(0)
                return True
            return False
        if kind == "recv":
            _, vfd, n = self.parked
            vs = self.vfds.get(vfd)
            if vs is None:
                self.parked = None
                self._rsp_data(0)
                return True
            if vs.avail > 0 or vs.eof:
                k = min(vs.avail, n)
                vs.avail -= k
                self.parked = None
                self._rsp_data(k, self._rx_payload(vs, k))  # 0 = EOF
                return True
            return False
        if kind in ("recvd", "recvfrom"):
            _, vfd, n = self.parked
            vs = self.vfds.get(vfd)
            if vs is None or not vs.dgrams:
                return False
            src, sport, nbytes = vs.dgrams.pop(0)
            self.parked = None
            if kind == "recvfrom":
                # OP_RECVFROM answers never carry payload (r1/r2 are
                # the datagram's source identity; the C side zero-fills)
                self._rsp(min(n, nbytes), src, sport)
            else:
                self._rsp_data(min(n, nbytes))
            return True
        if kind == "accept":
            vfd = self.parked[1]
            vs = self.vfds.get(vfd)
            if vs is None or not vs.accept_q:
                return False
            self.parked = None
            self._rsp_accept(vs)
            return True
        return False

    def _sweep_streams(self):
        """Runs when the child is gone (exit or terminate). Drops the
        streams I READ (my subscriptions — nothing will pop them
        again, and a hosted peer pushing into a dead subscriber would
        grow one unbounded, since subscribed streams are exempt from
        the reader-less cap) and reader-less streams I wrote. Streams
        the PEER subscribed stay: it may still be draining bytes I
        sent before exiting (a server that serves, closes and exits
        while the client reads); the peer drops them at its own
        close/exit."""
        if self._payloads is None:
            return
        for key in list(self._opened):
            if key in self._mysubs or not self._payloads.subscribed(key):
                self._payloads.drop(key)
                self._opened.discard(key)
        self._mysubs.clear()

    # --- the service loop: run the child until it blocks ---
    def _service(self, os):
        if self.exited:
            return
        self._maybe_unpark()
        while self.parked is None and not self.exited:
            req = self._read_req()
            if req is None:
                self.exited = True
                if self.proc is not None:
                    self.proc.wait()
                break
            self._handle(os, *req)
        if self.exited:
            self._sweep_streams()

    def _handle(self, os, op, a, b, c, name):
        if op == OP_SEND and not self._vfd_dgram.get(a, False):
            # a stream-socket send carries the app's REAL payload bytes
            # (b = n); consume them before anything else so the channel
            # stays framed even on error answers. Datagram sends and
            # OP_SENDTO never carry payload (UDP contents are not
            # materialized) — the C side keys the same per-vfd
            # dgram table, so both ends agree on the framing even for
            # closed/unknown vfds
            payload = self._read_n(b)
            if payload is None:
                self.exited = True
                return
        else:
            payload = b""
        if op == OP_SOCKET:
            vfd = self._alloc_vfd()
            self.vfds[vfd] = _VSock(kind="udp" if a else "tcp")
            self._vfd_dgram[vfd] = bool(a)
            self._rsp(vfd)
        elif op == OP_BIND:
            vs = self.vfds[a]
            vs.bound_port = int(b)
            if vs.kind == "udp":
                vs.sock = os.udp_open(port=int(b))
                self.by_sock[id(vs.sock)] = a
            self._rsp(0)
        elif op == OP_LISTEN:
            vs = self.vfds[a]
            vs.kind = "listen"
            vs.sock = os.tcp_listen(vs.bound_port)
            self.by_sock[id(vs.sock)] = a
            self._rsp(0)
        elif op == OP_ACCEPT:
            vs = self.vfds[a]
            if vs.accept_q:
                self._rsp_accept(vs)
            elif int(b) & 1:             # blocking listener: park
                self.parked = ("accept", a)
            else:
                self._rsp(-1, EAGAIN)
        elif op == OP_SENDTO:
            vs = self.vfds[a]
            if vs.sock is None:        # unbound UDP: ephemeral port
                vs.sock = os.udp_open(port=0)
                self.by_sock[id(vs.sock)] = a
            dst = int(c) >> 16
            port = int(c) & 0xFFFF
            os.sendto(vs.sock, dst, port, int(b))
            self._rsp(b)
        elif op == OP_RECVFROM:
            vs = self.vfds[a]
            if vs.dgrams:
                src, sport, nbytes = vs.dgrams.pop(0)
                self._rsp(min(int(b), nbytes), src, sport)
            elif int(c) & 1:             # blocking: park until a dgram
                self.parked = ("recvfrom", a, int(b))
            else:
                self._rsp(-1, EAGAIN)
        elif op == OP_CONNECT:
            vs = self.vfds[a]
            blk = (int(c) >> 16) & 1
            c = int(c) & 0xFFFF
            if vs.kind == "udp":
                # connected-UDP: record the default destination; no
                # handshake, succeeds immediately
                vs.bound_port = -1       # marker unused for udp
                vs.dgram_dst = (int(b), int(c))
                if vs.sock is None:
                    vs.sock = os.udp_open(port=0)
                    self.by_sock[id(vs.sock)] = a
                self._rsp(0)
            else:
                vs.sock = os.tcp_connect(int(b), int(c))
                self.by_sock[id(vs.sock)] = a
                if blk:                  # blocking connect: park until
                    self.parked = ("connect", a)   # established
                else:
                    self._rsp(-1, EINPROGRESS)  # completes via EPOLLOUT
        elif op == OP_SEND:
            vs = self.vfds[a]
            if vs.kind == "udp":
                if vs.dgram_dst is None:
                    self._rsp(-1, ENOTCONN)
                else:
                    dst, port = vs.dgram_dst
                    if vs.sock is None:
                        vs.sock = os.udp_open(port=0)
                        self.by_sock[id(vs.sock)] = a
                    os.sendto(vs.sock, dst, port, int(b))
                    self._rsp(b)
            else:
                self._tx_payload(vs, payload)
                os.write(vs.sock, int(b))
                self._rsp(b)
        elif op == OP_RECV:
            vs = self.vfds[a]
            blk = int(c) & 1
            if vs.kind == "udp":         # recv() on a datagram socket
                if vs.dgrams:
                    _src, _sp, nbytes = vs.dgrams.pop(0)
                    self._rsp_data(min(int(b), nbytes))
                elif blk:
                    self.parked = ("recvd", a, int(b))
                else:
                    self._rsp(-1, EAGAIN)
            else:
                n = min(vs.avail, int(b))
                vs.avail -= n
                if n == 0 and not vs.eof:
                    if blk:              # blocking read: park until
                        self.parked = ("recv", a, int(b))  # data/EOF
                    else:
                        self._rsp(-1, EAGAIN)
                else:
                    self._rsp_data(n, self._rx_payload(vs, n))  # 0 = EOF
        elif op in (OP_CLOSE, OP_SHUTDOWN):
            vs = self.vfds.get(a)
            if vs is not None and vs.sock is not None and not vs.closed:
                os.close(vs.sock)
                vs.closed = True
            if op == OP_CLOSE:
                gone = self.vfds.pop(a, None)
                if gone is not None and gone.key is not None:
                    self.by_key.pop(gone.key, None)
                if gone is not None:
                    self.by_sock.pop(id(gone.sock), None)
                    if (gone.conn is not None and
                            self._payloads is not None):
                        # I was the reader of my in-direction; the peer
                        # drops the other one at its own close
                        key = gone.conn + (1 if gone.is_client else 0,)
                        self._payloads.drop(key)
                        self._opened.discard(key)
                        self._mysubs.discard(key)
                        # my OUT-direction: no subscribed reader means
                        # the peer process is modeled and nothing will
                        # ever drain it — drop now, not at end-of-run
                        # (a many-connection run would accumulate one
                        # capped stream per connection). A subscribed
                        # stream survives until ITS reader closes.
                        out = gone.conn + (0 if gone.is_client else 1,)
                        if not self._payloads.subscribed(out):
                            self._payloads.drop(out)
                            self._opened.discard(out)
                for watch in self.epolls.values():
                    watch.pop(a, None)
            self._rsp(0)
        elif op == OP_EPOLL_CREATE:
            vfd = self._alloc_vfd()
            self.epolls[vfd] = {}
            self._rsp(vfd)
        elif op == OP_EPOLL_CTL:
            ctl = int(b) & 0xFFFFFFFF
            events = int(b) >> 32
            watch = self.epolls.setdefault(a, {})
            if ctl == EPOLL_CTL_DEL:
                watch.pop(int(c), None)
            else:
                watch[int(c)] = events
            self._rsp(0)
        elif op == OP_EPOLL_WAIT:
            maxev = max(int(c), 1)
            hits = self._ready(a, maxev)
            if hits:
                self._rsp_events(hits)
            elif b == 0:
                self._rsp(0)             # pure poll: never parks
            else:
                # block until a wake readies it
                self.parked = ("epoll", a, maxev)
                self.park_seq += 1
                if b > 0:                # bounded wait: sim-time timer,
                    # tagged with this park's sequence so a stale timer
                    # from an earlier (already answered) wait cannot
                    # cut a later one short. The tag rides an i32
                    # packet word, so the seq is masked to 7 bits
                    # (sign bit must stay clear); a false match needs
                    # a stale timer exactly 128 timed parks old AND
                    # the same epfd AND the child parked — acceptable
                    # odds vs. the wedge an unmatched timeout causes
                    os.timer(int(b) * 1_000_000,
                             tag=((self.park_seq & 0x7F) << 24) |
                                 (a & 0xFFFFFF))
        elif op == OP_CLOCK:
            self._rsp(os.now())
        elif op == OP_RESOLVE:
            try:
                hid = os.resolve(name.rstrip(b"\0").decode())
            except Exception:
                hid = -1
            self._rsp(hid)
        else:
            self._rsp(-1)

    # --- hosted-app callbacks: map device wakes to epoll readiness ---
    def on_start(self, os):
        self._spawn()
        self._service(os)

    def _vs_of(self, sock):
        vfd = self.by_sock.get(id(sock))
        if vfd is None and sock is not None and sock.slot is not None:
            vfd = self.by_key.get((sock.slot, sock.gen))
        if vfd is None:
            return None, None
        vs = self.vfds.get(vfd)
        if (sock.slot is not None and vs is not None):
            self.by_key[(sock.slot, sock.gen)] = vfd
            vs.key = (sock.slot, sock.gen)
        return vfd, vs

    def on_connected(self, os, sock, lport=0, peer=(0, 0)):
        _, vs = self._vs_of(sock)
        if vs is not None:
            vs.connected = True
            if vs.conn is None and lport:
                # payload stream identity off the SYN|ACK: we are the
                # client side of (cli_host, cli_port, srv_host, srv_port)
                vs.conn = (os.host_id, int(lport),
                           int(peer[0]), int(peer[1]))
                vs.is_client = True
                self._open_streams(vs)
        self._service(os)

    def on_accept(self, os, sock, tag, dport=0, peer=(0, 0)):
        # queue the accepted child on its listener (matched by bound
        # port; fall back to the only listener when ports are unset)
        target = None
        for vs in self.vfds.values():
            if vs.kind == "listen":
                if vs.bound_port == dport or target is None:
                    target = vs
                    if vs.bound_port == dport:
                        break
        if target is not None:
            conn = (int(peer[0]), int(peer[1]), os.host_id,
                    int(dport) or target.bound_port)
            target.accept_q.append((sock, peer[0], peer[1], conn))
            # subscribe our inbound direction NOW, at the wake — not
            # at the app's accept() call, which it may make arbitrarily
            # later: the client's first pushes land between this wake
            # and that call, and an unsubscribed stream would cap and
            # die under them (api.PayloadBroker.push)
            if self._payloads is not None:
                for d in (0, 1):
                    self._payloads.open(conn + (d,))
                    self._opened.add(conn + (d,))
                self._payloads.subscribe(conn + (0,))
                self._mysubs.add(conn + (0,))
        self._service(os)

    def on_dgram(self, os, sock, src, sport, nbytes, aux):
        # WAKE_SOCKET: TCP delivered bytes, or a UDP datagram
        _, vs = self._vs_of(sock)
        if vs is not None:
            if vs.kind == "udp":
                vs.dgrams.append((int(src), int(sport), int(nbytes)))
            else:
                vs.avail += int(nbytes)
        self._service(os)

    def on_eof(self, os, sock):
        _, vs = self._vs_of(sock)
        if vs is not None:
            vs.eof = True
        self._service(os)

    def on_sent(self, os, sock):
        self._service(os)

    def on_timer(self, os, tag):
        # epoll_wait timeout expiry: answer 0 events iff the child is
        # still parked in the SAME wait that armed this timer
        epfd = tag & 0xFFFFFF
        seq = tag >> 24
        if (self.parked is not None and self.parked[0] == "epoll" and
                (self.parked[1] & 0xFFFFFF) == epfd and
                seq == (self.park_seq & 0x7F)):
            self.parked = None
            self._rsp(0)
        self._service(os)

    def terminate(self):
        """End-of-run cleanup: release the child and the channel (a
        stop_time truncation can leave the child parked forever)."""
        if self.chan is not None:
            try:
                self.chan.close()
            except OSError:
                pass
            self.chan = None
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()
        self.exited = True
        self._sweep_streams()


register("shim", ShimApp)
