"""Unmodified-binary hosting: the LD_PRELOAD shim bridge.

The reference's defining trick is running real, unmodified binaries by
interposing 262 libc symbols (/root/reference/src/preload/
shd-interposer.c:211-222, shd-preload-defs.h) and re-entering blocked
app code with green threads (shd-process.c:1076-1263). This module is
the TPU build's realization of that capability:

- the REAL binary runs as a separate OS process with
  ``libshadow_shim.so`` LD_PRELOADed (hosting/shim_preload.c);
- the shim interposes the socket/epoll/poll/select/clock/sleep/entropy
  libc surface and forwards each call over an inherited socketpair to
  :class:`ShimApp`, a hosted app (hosting.api) inside the simulator;
- blocking semantics replace rpth: the binary only ever blocks inside
  a forwarded wait (epoll_wait/poll/select, blocking connect/recv/
  accept, nanosleep); the simulator answers it when a device wake
  (connection established, bytes delivered, EOF, timer) maps to it —
  so simulated time never advances while app code runs, exactly the
  reference's cooperative model;
- ALL clocks read simulated time (clock_gettime, gettimeofday,
  time — reference shd-process.c:4329-4389), sleeps advance sim time
  (process_emu_nanosleep, shd-process.c:3055), and entropy
  (getrandom/getentropy//dev/u?random) comes from the host's
  deterministic PRNG (shd-host.c:574) — a hosted binary that draws
  randomness runs bit-identically across runs (the reference's
  determinism dual-run, shd-test-determinism.c:15-60, realized in
  tests/test_shim_libc.py);
- TCP payload bytes are MATERIALIZED host-side (round 4): the engine
  models byte counts and timing, while the real bytes ride the control
  channel into a per-connection FIFO (api.PayloadBroker) keyed by the
  TCP 4-tuple both endpoints derive from their establishment wakes.
  A hosted endpoint talking to a MODELED app sees zero-fill, and UDP
  datagram payloads are not materialized.

Virtual fd numbering (round 5): the C side reserves a real kernel fd
(an open /dev/null placeholder) per virtual fd and the simulator keys
its state by that number — vfds stay small (select()'s fd_set caps
fds at 1024), never collide with the process's real fds, and close()
retires both together. Creating ops carry the reserved number.

Scenario usage: plugin="hosted:shim" with arguments
``[out=<stdout file>] cmd=<binary> [child args...]`` — cmd paths
resolve like any exec (absolute, or relative to the process CWD). The
preload library builds on demand with cc into SHADOW_SHIM_BUILD or the
temp dir (hosting.shim.build_shim).

Protocol (one request, one response, in lockstep — the child is
single-threaded between waits):
  request  = <iiqq64s>  op, a, b, c, name  (88 bytes)
  response = <qqq>      r0, r1, r2         (24 bytes)
  OP_EPOLL_WAIT / OP_POLL responses with r0 = n > 0 carry n trailing
  <qq> (fd, events) pairs. OP_POLL requests carry b trailing payload
  bytes (the virtual pollfd set as <qq> pairs). OP_SEND requests with
  c == 1 carry b trailing payload bytes (stream sends; datagram sends
  set c = 0 and attach nothing). Successful OP_RECV / OP_RANDOM
  responses with r1 == 1 carry r0 trailing payload bytes (real stream
  contents / PRNG bytes — r1 == 0 on OP_RECV means no live stream
  covers the read and the C side zero-fills locally). OP_RECVFROM
  responses never carry payload.
"""

from __future__ import annotations

import hashlib as _hashlib
import os as _os
import struct
import subprocess
import time as _time

from .api import HostedApp, register
from ..obs import digest as _DG
from ..obs import metrics as _MT

REQ = struct.Struct("<iiqq64s")
RSP = struct.Struct("<qqq")
EVPAIR = struct.Struct("<qq")

OP_SOCKET = 1
OP_CONNECT = 2
OP_SEND = 3
OP_RECV = 4
OP_CLOSE = 5
OP_SHUTDOWN = 6
OP_EPOLL_CREATE = 7
OP_EPOLL_CTL = 8
OP_EPOLL_WAIT = 9
OP_CLOCK = 10
OP_RESOLVE = 11
OP_BIND = 12
OP_LISTEN = 13
OP_ACCEPT = 14
OP_SENDTO = 15
OP_RECVFROM = 16
OP_SLEEP = 17
OP_POLL = 18
OP_RANDOM = 19
OP_GETNAME = 20
OP_VIOLATION = 21   # child attempted a refused operation (fork/exec):
#                     name carries what; diagnostic only, answer is 0

# op code -> metric name (obs.metrics shim.op.* counters and
# shim.op_us.* latency histograms, recorded per served request)
OP_NAMES = {
    OP_SOCKET: "socket", OP_CONNECT: "connect", OP_SEND: "send",
    OP_RECV: "recv", OP_CLOSE: "close", OP_SHUTDOWN: "shutdown",
    OP_EPOLL_CREATE: "epoll_create", OP_EPOLL_CTL: "epoll_ctl",
    OP_EPOLL_WAIT: "epoll_wait", OP_CLOCK: "clock",
    OP_RESOLVE: "resolve", OP_BIND: "bind", OP_LISTEN: "listen",
    OP_ACCEPT: "accept", OP_SENDTO: "sendto", OP_RECVFROM: "recvfrom",
    OP_SLEEP: "sleep", OP_POLL: "poll", OP_RANDOM: "random",
    OP_GETNAME: "getname", OP_VIOLATION: "violation",
}

EPOLLIN = 0x001
EPOLLOUT = 0x004
EPOLLRDHUP = 0x2000
EPOLLHUP = 0x010
EINPROGRESS = 115
ENOTCONN = 107
EAGAIN = 11
ECONNREFUSED = 111

EPOLL_CTL_ADD = 1
EPOLL_CTL_DEL = 2
EPOLL_CTL_MOD = 3

# sim-time timer tags (ride an i32 packet word; sign bit must stay
# clear): bits 0-19 = fd/id operand, bits 20-22 = kind, bits 24-30 =
# park sequence. A stale timer can only false-match the CURRENT park if
# kind, operand AND a 128-window sequence all line up — acceptable odds
# vs. the wedge an unmatched timeout causes.
TK_EPOLL = 0    # epoll_wait timeout (operand = epfd)
TK_SLEEP = 1    # nanosleep/usleep/sleep deadline
TK_POLL = 2     # poll/select timeout
TK_GRACE = 3    # deferred payload-stream drop (operand = grace id)


def _tag(kind, operand, seq):
    return ((seq & 0x7F) << 24) | ((kind & 0x7) << 20) | (operand & 0xFFFFF)


_SRC = _os.path.dirname(_os.path.abspath(__file__))
SHIM_C = _os.path.join(_SRC, "shim_preload.c")

# sim-time grace before an unsubscribed out-direction payload stream is
# dropped at close: long enough for the peer's establishment wake (one
# path latency) to arrive and subscribe — a hosted server that writes
# and closes within its accept window (banner-then-close) must not lose
# its bytes (round-4 advisor, shim OP_CLOSE)
GRACE_NS = 30 * 10**9

# hung-child watchdog: WALL-clock ceiling on one channel read. The
# protocol is lockstep, so between our reads the child is either
# computing (bounded by its own work) or about to issue its next
# request; a child stuck in a busy loop or wedged in real libc makes
# no RPC progress and would otherwise freeze the whole simulator
# inside _read_req. SHADOW_SHIM_WATCHDOG_S overrides; 0 disables.
WATCHDOG_S_DEFAULT = 30.0


# first link of the per-child protocol-stream digest chain
SHIM_CHAIN_SEED = _hashlib.blake2b(
    b"shadow_tpu.shim.ops.v1", digest_size=8).hexdigest()


class ShimHang(Exception):
    """Watchdog: the child made no RPC progress within the deadline."""


class ShimProtocolError(Exception):
    """The channel carried something the protocol forbids (short read
    mid-frame, oversized trailing payload, ...) — unrecoverable
    framing; the supervisor kills the channel, not the simulator."""


def _status_cause(status):
    """OS exit status -> (cause string, clean?) for the exit report."""
    if status is not None and status < 0:
        import signal as _signal
        try:
            signame = _signal.Signals(-status).name
        except ValueError:
            signame = f"signal {-status}"
        return f"killed by {signame}", False
    return f"exited status={status}", status == 0


def build_shim(out_dir: str = None) -> str:
    """Compile the preload library (cached). -> .so path

    Builds into SHADOW_SHIM_BUILD or a per-user 0700 cache directory —
    never next to the target binary (may be read-only) and never at a
    predictable path in the world-writable system temp dir (another
    local user could pre-plant a .so there that would then be
    LD_PRELOADed into our child processes). A cached .so is reused
    only if we own it and it is not group/other-writable."""
    if out_dir is None:
        out_dir = _os.environ.get("SHADOW_SHIM_BUILD")
    if out_dir is None:
        base = _os.environ.get("XDG_CACHE_HOME",
                               _os.path.join(_os.path.expanduser("~"),
                                             ".cache"))
        out_dir = _os.path.join(base, "shadow_tpu")
    _os.makedirs(out_dir, mode=0o700, exist_ok=True)
    so = _os.path.join(out_dir, "libshadow_shim.so")
    if (_os.path.exists(so) and
            _os.path.getmtime(so) >= _os.path.getmtime(SHIM_C)):
        st = _os.stat(so)
        if st.st_uid == _os.getuid() and not (st.st_mode & 0o022):
            return so
    subprocess.run(["cc", "-shared", "-fPIC", "-O2", "-o", so, SHIM_C,
                    "-ldl", "-lpthread"], check=True)
    _os.chmod(so, 0o755)
    return so


class _VSock:
    """Shim-side view of one virtual socket fd."""

    __slots__ = ("sock", "avail", "eof", "connected", "closed", "key",
                 "kind", "bound_port", "accept_q", "dgrams", "dgram_dst",
                 "conn", "is_client", "pending_tx")

    def __init__(self, kind="tcp"):
        self.sock = None        # hosting.api.Sock once connect issued
        self.avail = 0          # delivered-but-unread byte count
        self.eof = False
        self.connected = False
        self.closed = False
        self.key = None         # (slot, gen) once resolved
        self.kind = kind        # "tcp" | "udp" | "listen"
        self.bound_port = 0
        self.accept_q = []      # listener: (child Sock, src, sport, conn)
        self.dgrams = []        # udp: (src_host, sport, nbytes)
        self.dgram_dst = None   # udp: connect()ed default destination
        # TCP payload stream identity (api.PayloadBroker): the
        # canonical (cli_host, cli_port, srv_host, srv_port) both
        # endpoints derive, or None until the connection resolves
        self.conn = None
        self.is_client = False
        self.pending_tx = []    # payloads written before conn resolved


class ShimApp(HostedApp):
    """Hosts one real binary behind the LD_PRELOAD shim (module doc)."""

    def __init__(self, args: str):
        parts = args.split()
        i = next((j for j, p in enumerate(parts)
                  if p.startswith("cmd=")), None)
        if i is None:
            raise ValueError("hosted:shim needs cmd=<binary> argument")
        # shim options (out=...) precede cmd=; everything AFTER cmd= is
        # the child's argv verbatim
        kv = dict(p.split("=", 1) for p in parts[:i + 1] if "=" in p)
        self.argv = [kv["cmd"]] + parts[i + 1:]
        self.out_path = kv.get("out")   # child stdout -> file (tests)
        self.proc = None
        self.chan = None          # our end of the socketpair
        self.vfds = {}            # vfd -> _VSock (vfd = C-reserved fd)
        self.by_sock = {}         # id(Sock) -> vfd (pre-resolution)
        self.by_key = {}          # (slot, gen) -> vfd: wakes arriving
        # after os.close() carry a FRESH Sock object for the same
        # incarnation (HostOS retires closed handles), so identity
        # lookup alone would drop e.g. the post-shutdown EOF
        self.epolls = {}          # vepfd -> {vfd: events}
        # the child's one blocked call (it is single-threaded): None,
        # ("epoll", epfd, maxev), ("connect", vfd), ("recv", vfd, n),
        # ("recvd", vfd, n) [blocking recv() on udp],
        # ("recvfrom", vfd, n), ("accept", vfd, cfd), ("sleep",), or
        # ("poll", interest). Blocking calls park here until a wake
        # satisfies them (_maybe_unpark) — the shim's replacement for
        # the reference's rpth block/reenter (shd-process.c:1076-1263)
        self.parked = None
        self.park_seq = 0         # increments per park: stale-timeout guard
        self.exited = False
        self._started = False     # a child was spawned at least once
        # --- checkpoint/resume (docs/durability.md) ---
        # protocol-stream journal: ordered ("rx"/"tx", bytes) records
        # of everything that crossed the channel since THIS child
        # spawned. None = disabled; enable_journal() (checkpointed
        # runs) arms it. resume_replay() respawns the child and pumps
        # the journal back: the shim virtualizes time, entropy and
        # I/O, so a deterministic binary re-issues byte-identical
        # requests and lands parked in the same blocked call.
        self._journal = None
        self._replaying = False
        # --- supervision (per-host exit report; SimReport.hosted) ---
        self.exit_status = None   # OS exit status (negative = -signal)
        self.exit_cause = None    # human diagnosis ("hung: ...", ...)
        self.exit_sim_ns = None   # sim time the death was observed
        self.exit_clean = False   # True: status-0 exit / end-of-run
        self.violations = []      # refused ops the child attempted
        self.watchdog_s = float(
            _os.environ.get("SHADOW_SHIM_WATCHDOG_S",
                            str(WATCHDOG_S_DEFAULT)) or 0)
        # protocol-request stream digest (obs.digest): every frame the
        # child issued, in service order — pins a determinism
        # divergence to "the child behaved differently" vs "the engine
        # diverged". A rolling chain (not one hash object) so
        # checkpoints can carry it — hashlib midstates do not pickle.
        # Updated only while a digest recorder is installed.
        self._op_chain = SHIM_CHAIN_SEED
        self._payloads = None     # api.PayloadBroker (runtime attaches)
        self._opened = set()      # broker keys this app opened
        self._mysubs = set()      # the subset I subscribed (I read)
        self._grace = {}          # grace id -> stream key pending drop
        self._next_grace = 0

    def attach_payload_broker(self, broker):
        """HostingRuntime wires the per-simulation PayloadBroker in:
        hosted<->hosted TCP connections then carry REAL bytes (counts
        still modeled by the engine; hosted<->modeled stays zero-fill)."""
        self._payloads = broker

    # --- payload streams (api.PayloadBroker) ---
    def _open_streams(self, vs):
        """Open both directions at establishment (writer-side open
        included: the accept wake precedes the connected wake in sim
        time, so a server's first push must not find a missing stream)
        and SUBSCRIBE the inbound one — subscription marks the stream
        as having a real reader, which exempts it from the reader-less
        cap and preserves it across the writer's close. Then flush
        sends issued before the identity resolved."""
        if self._payloads is None or vs.conn is None:
            return
        for d in (0, 1):
            key = vs.conn + (d,)
            self._payloads.open(key)
            self._opened.add(key)
        inkey = vs.conn + (1 if vs.is_client else 0,)
        self._payloads.subscribe(inkey)
        self._mysubs.add(inkey)
        if vs.pending_tx:
            out = vs.conn + (0 if vs.is_client else 1,)
            for data in vs.pending_tx:
                self._payloads.push(out, data)
            vs.pending_tx = []

    def _tx_payload(self, vs, data):
        if (self._payloads is None or vs.kind != "tcp" or not data):
            return
        if vs.conn is None:            # optimistic pre-connect write
            vs.pending_tx.append(data)
            return
        self._payloads.push(vs.conn + (0 if vs.is_client else 1,), data)

    def _rx_payload(self, vs, k):
        """Exactly k real stream bytes for a recv answer, or None when
        no live stream backs the connection (peer modeled) — the C side
        then zero-fills locally instead of moving k zeros over the
        channel."""
        if (self._payloads is None or vs is None or vs.conn is None
                or vs.kind != "tcp"):
            return None
        return self._payloads.pop(vs.conn + (1 if vs.is_client else 0,),
                                  int(k))

    # --- child lifecycle ---
    def _spawn(self):
        import socket as pysock
        ours, theirs = pysock.socketpair()
        env = dict(_os.environ)
        env["LD_PRELOAD"] = build_shim()
        env["SHADOW_SHIM_FD"] = str(theirs.fileno())
        stdout = (open(self.out_path, "w") if self.out_path else None)
        self.proc = subprocess.Popen(self.argv, env=env,
                                     pass_fds=(theirs.fileno(),),
                                     stdout=stdout)
        if stdout is not None:
            stdout.close()
        theirs.close()
        self.chan = ours
        self._started = True
        # wall-clock RPC deadline (module doc above WATCHDOG_S_DEFAULT):
        # applies to every channel read AND write, so a child that
        # stops draining its end cannot wedge _rsp either
        if self.watchdog_s > 0:
            self.chan.settimeout(self.watchdog_s)

    def _jrec(self, d: str, data: bytes):
        """Journal one channel transfer (adjacent same-direction
        records coalesce, so the journal is bounded by traffic, not
        read granularity). Replay traffic is never re-journaled — the
        restored journal already holds those bytes."""
        if self._journal is None or self._replaying or not data:
            return
        if self._journal and self._journal[-1][0] == d:
            self._journal[-1][1] += data
        else:
            self._journal.append([d, bytearray(data)])

    def _recv(self, n):
        """One watchdog-supervised channel read."""
        import socket as pysock
        try:
            chunk = self.chan.recv(n)
        except pysock.timeout:
            raise ShimHang(
                f"no RPC progress within {self.watchdog_s:g}s wall"
                f" (pid {self.proc.pid if self.proc else '?'})")
        self._jrec("rx", chunk)
        return chunk

    def _send(self, data: bytes):
        """One journaled channel write (every response goes through
        here so resume replay can reproduce the exact byte stream)."""
        self.chan.sendall(data)
        self._jrec("tx", data)

    def _read_req(self):
        buf = b""
        while len(buf) < REQ.size:
            chunk = self._recv(REQ.size - len(buf))
            if not chunk:
                if buf:
                    # EOF inside a frame: the child died (or wrote
                    # garbage) mid-request — diagnose, don't desync
                    raise ShimProtocolError(
                        f"channel EOF mid-request "
                        f"({len(buf)}/{REQ.size} header bytes)")
                return None
            buf += chunk
        return REQ.unpack(buf)

    def _read_n(self, n):
        """n trailing payload bytes of an OP_SEND/OP_POLL request."""
        buf = bytearray()
        n = int(n)
        if n < 0 or n > (64 << 20):
            raise ShimProtocolError(
                f"request claims {n} trailing payload bytes")
        while len(buf) < n:
            chunk = self._recv(min(n - len(buf), 1 << 20))
            if not chunk:
                raise ShimProtocolError(
                    f"channel EOF mid-payload ({len(buf)}/{n} bytes)")
            buf += chunk
        return bytes(buf)

    def _rsp(self, r0=0, r1=0, r2=0):
        self._send(RSP.pack(int(r0), int(r1), int(r2)))

    def _rsp_data(self, k, data=None):
        """OP_RECV/OP_RANDOM answer: header then, when `data` is real
        bytes (r1 = 1), EXACTLY k trailing payload bytes. data=None
        means no live stream backs the connection — r1 = 0, no
        trailing bytes, and the C side zero-fills locally (keeps the
        hosted<->modeled hot path free of per-byte channel traffic)."""
        k = max(int(k), 0)
        if data is None:
            self._send(RSP.pack(k, 0, 0))
            return
        out = data[:k] + b"\0" * (k - len(data))
        self._send(RSP.pack(k, 1, 0) + out)

    # --- epoll/poll readiness ---
    def _events_of(self, vfd):
        vs = self.vfds.get(vfd)
        if vs is None:
            return 0
        ev = 0
        if vs.kind == "listen":
            if vs.accept_q:
                ev |= EPOLLIN
            return ev
        if vs.kind == "udp":
            if vs.dgrams:
                ev |= EPOLLIN
            ev |= EPOLLOUT          # modeled datagrams never block
            return ev
        if vs.avail > 0 or vs.eof:
            ev |= EPOLLIN | (EPOLLRDHUP if vs.eof else 0)
        if vs.connected:
            ev |= EPOLLOUT
        return ev

    def _ready(self, vepfd, maxevents=1):
        hits = []
        for vfd, interest in self.epolls.get(vepfd, {}).items():
            ev = self._events_of(vfd) & (interest | EPOLLRDHUP | EPOLLHUP)
            if ev:
                hits.append((vfd, ev))
                if len(hits) >= maxevents:
                    break
        return hits

    def _poll_ready(self, interest):
        """poll() readiness over an explicit {vfd: events} interest
        set (POLLIN/POLLOUT share EPOLL bit values)."""
        hits = []
        for vfd, events in interest.items():
            ev = self._events_of(vfd) & (events | EPOLLRDHUP | EPOLLHUP)
            if ev:
                hits.append((vfd, ev))
        return hits

    def _rsp_events(self, hits):
        """Multi-event epoll_wait/poll answer: header with the count,
        then one (fd, events) pair per event (shim_preload.c evpair)."""
        out = RSP.pack(len(hits), 0, 0)
        for vfd, ev in hits:
            out += EVPAIR.pack(vfd, ev)
        self._send(out)

    def _take_vfd(self, vfd):
        """Adopt the C-side reserved fd number as a vfd id. The number
        is a live kernel fd in the child, so it cannot collide with
        another LIVE vfd — a collision means close-tracking desynced,
        which must fail loud, not corrupt state."""
        vfd = int(vfd)
        if vfd in self.vfds or vfd in self.epolls:
            raise ShimProtocolError(
                f"vfd {vfd} re-reserved while live (close-tracking "
                "desync)")
        return vfd

    def _rsp_accept(self, vs, cfd):
        """Pop one pending child off a listener and answer the accept
        call with the C-reserved child fd (shared by the immediate and
        parked paths)."""
        child, src, sport, conn = vs.accept_q.pop(0)
        cfd = self._take_vfd(cfd)
        cvs = _VSock(kind="tcp")
        cvs.sock = child
        cvs.connected = True
        cvs.conn = conn
        cvs.is_client = False
        self._open_streams(cvs)
        self.vfds[cfd] = cvs
        self.by_sock[id(child)] = cfd
        if child.slot is not None:
            self.by_key[(child.slot, child.gen)] = cfd
            cvs.key = (child.slot, child.gen)
        # peer identity: (virtual host id, port) off the handshake —
        # servers keying state by accept() address see distinct
        # simulated clients
        self._rsp(cfd, src, sport)

    def _maybe_unpark(self):
        """Answer the child's parked blocking call if a wake has made
        it ready. One parked call at most (single-threaded child)."""
        if self.parked is None:
            return False
        kind = self.parked[0]
        if kind == "epoll":
            _, epfd, maxev = self.parked
            hits = self._ready(epfd, maxev)
            if not hits:
                return False
            self.parked = None
            self._rsp_events(hits)
            return True
        if kind == "poll":
            interest = self.parked[1]
            hits = self._poll_ready(interest)
            if not hits:
                return False
            self.parked = None
            self._rsp_events(hits)
            return True
        if kind == "connect":
            vfd = self.parked[1]
            vs = self.vfds.get(vfd)
            if vs is None or vs.eof:
                self.parked = None
                self._rsp(-1, ECONNREFUSED)
                return True
            if vs.connected:
                self.parked = None
                self._rsp(0)
                return True
            return False
        if kind == "recv":
            _, vfd, n = self.parked
            vs = self.vfds.get(vfd)
            if vs is None:
                self.parked = None
                self._rsp_data(0)
                return True
            if vs.avail > 0 or vs.eof:
                k = min(vs.avail, n)
                vs.avail -= k
                self.parked = None
                self._rsp_data(k, self._rx_payload(vs, k))  # 0 = EOF
                return True
            return False
        if kind in ("recvd", "recvfrom"):
            _, vfd, n = self.parked
            vs = self.vfds.get(vfd)
            if vs is None or not vs.dgrams:
                return False
            src, sport, nbytes = vs.dgrams.pop(0)
            self.parked = None
            if kind == "recvfrom":
                # OP_RECVFROM answers never carry payload (r1/r2 are
                # the datagram's source identity; the C side zero-fills)
                self._rsp(min(n, nbytes), src, sport)
            else:
                self._rsp_data(min(n, nbytes))
            return True
        if kind == "accept":
            _, vfd, cfd = self.parked
            vs = self.vfds.get(vfd)
            if vs is None or not vs.accept_q:
                return False
            self.parked = None
            self._rsp_accept(vs, cfd)
            return True
        # "sleep" parks resolve only via their timer (on_timer)
        return False

    def _sweep_streams(self):
        """Runs when the child is gone (exit or terminate). Drops the
        streams I READ (my subscriptions — nothing will pop them
        again, and a hosted peer pushing into a dead subscriber would
        grow one unbounded, since subscribed streams are exempt from
        the reader-less cap) and reader-less streams I wrote. Streams
        the PEER subscribed stay: it may still be draining bytes I
        sent before exiting (a server that serves, closes and exits
        while the client reads); the peer drops them at its own
        close/exit. Streams under a close-time GRACE deferral also
        stay — dropping them here would defeat the grace window for a
        child that writes, closes and exits before the peer's
        establishment wake subscribes (the banner-then-close case;
        round-5 advisor): their pending TK_GRACE timers keep firing
        after child exit (on_timer runs without the child) and perform
        the deferred reader-less check then."""
        if self._payloads is None:
            return
        deferred = set(self._grace.values())
        for key in list(self._opened):
            if key in deferred:
                continue
            if key in self._mysubs or not self._payloads.subscribed(key):
                self._payloads.drop(key)
                self._opened.discard(key)
        self._mysubs.clear()

    # --- the service loop: run the child until it blocks ---
    def _service(self, os):
        """Run the child until it blocks — SUPERVISED: a hung child
        (watchdog), a malformed frame (protocol validation) or a
        channel failure becomes a diagnosed child death and the
        simulation continues; only the hosted process dies."""
        if self.exited:
            return
        try:
            self._maybe_unpark()
            while self.parked is None and not self.exited:
                req = self._read_req()
                if req is None:
                    self._child_gone(os)       # clean channel EOF
                    break
                if _DG.ENABLED:
                    self._op_chain = _hashlib.blake2b(
                        bytes.fromhex(self._op_chain) + REQ.pack(*req),
                        digest_size=8).hexdigest()
                # per-op protocol metrics: count + HANDLER latency (a
                # call that parks is counted when it arrives; the
                # sim-time it stays parked is not wall cost)
                # simlint: ok DET101 -- op-handler latency metric (wall-side)
                _t0 = _time.perf_counter_ns() if _MT.ENABLED else None
                self._handle(os, *req)
                if _t0 is not None:
                    _MT.shim_op(
                        OP_NAMES.get(req[0], str(req[0])),
                        # simlint: ok DET101 -- op latency metric (wall)
                        _time.perf_counter_ns() - _t0)
        except ShimHang as e:
            self._supervise_kill(os, f"hung: {e}")
        except ShimProtocolError as e:
            self._supervise_kill(os, f"protocol error: {e}")
        except (KeyError, IndexError, struct.error) as e:
            # a malformed opcode/operand must not surface as a
            # traceback that takes the simulator down (tentpole
            # contract): diagnose and kill the channel instead
            self._supervise_kill(
                os, f"protocol error: malformed request "
                    f"({type(e).__name__}: {e})")
        except OSError as e:
            self._supervise_kill(os, f"channel failure: {e}")
        if self.exited:
            self._sweep_streams()

    def _supervise_kill(self, os, cause):
        """Supervisor verdict: the child is unusable — SIGKILL it,
        record the diagnosis, tear its sockets down abortively."""
        import sys as _sys
        _sys.stderr.write(
            f"shadow_tpu: shim[{_os.path.basename(self.argv[0])}]: "
            f"{cause} — killing hosted child; simulation continues\n")
        if _MT.ENABLED:
            _MT.REGISTRY.counter("shim.supervisor_kills").inc()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
        self._child_gone(os, cause=cause)

    def _child_gone(self, os, cause=None):
        """The child is dead (clean exit, crash, or supervisor kill):
        record per-host exit status + cause, release the channel, and
        convert the sockets it left open into RST/EOF toward peers —
        the simulation keeps running (tentpole contract; the reference
        analogue is process teardown, shd-process.c:3195-3234)."""
        self.exited = True
        if self.proc is not None:
            try:
                self.proc.wait(timeout=5)
            except Exception:
                pass
            self.exit_status = self.proc.returncode
        if self.exit_cause is None:
            if cause is not None:
                self.exit_cause = cause
                self.exit_clean = False
            else:
                self.exit_cause, self.exit_clean = _status_cause(
                    self.exit_status)
        if os is not None and self.exit_sim_ns is None:
            self.exit_sim_ns = os.now()
        if _MT.ENABLED:
            _MT.REGISTRY.counter("shim.child_exits").inc()
        if self.chan is not None:
            try:
                self.chan.close()
            except OSError:
                pass
            self.chan = None
        self.parked = None
        if os is None:
            return
        # leftover socket teardown, deterministic vfd order. A clean
        # exit closes gracefully (the kernel FINs a closed fd) except
        # where delivered-but-unread bytes sit (a real stack RSTs
        # then); any diagnosed death resets everything.
        graceful = self.exit_clean
        for vfd in sorted(self.vfds):
            vs = self.vfds[vfd]
            for child, _, _, _ in vs.accept_q:
                os.abort(child)        # never-accepted server children
            vs.accept_q = []
            if vs.sock is not None and not vs.closed:
                if graceful and vs.avail == 0 and vs.kind != "listen":
                    os.close(vs.sock)
                else:
                    os.abort(vs.sock)
                vs.closed = True

    def op_stream_digest(self) -> str:
        """Running chain hash of every protocol request served so far
        (hosting.runtime.digest_state -> obs.digest records)."""
        return self._op_chain

    # --- checkpoint/resume (hosting.runtime snapshot/restore) ---
    def enable_journal(self):
        """Arm protocol-stream journaling (idempotent: a restored app
        keeps the journal it was pickled with)."""
        if self._journal is None:
            self._journal = []

    def disable_journal(self):
        """Drop the journal: a run that will never snapshot again
        (resume without --checkpoint) must not keep buffering the
        child's protocol traffic in RAM."""
        self._journal = None

    def __getstate__(self):
        """Checkpoint pickling: everything but the live OS process,
        its channel, the shared payload broker (runtime re-attaches)
        and the id()-keyed socket index (rebuilt on restore)."""
        d = dict(self.__dict__)
        for k in ("proc", "chan", "_payloads", "by_sock"):
            d.pop(k, None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.proc = None
        self.chan = None
        self._payloads = None
        self._replaying = False
        self.by_sock = {}
        for vfd, vs in self.vfds.items():
            if vs.sock is not None:
                self.by_sock[id(vs.sock)] = vfd

    def resume_replay(self, os):
        """Fast-forward a respawned child to the snapshot point: spawn
        the binary fresh and pump the journaled protocol stream — read
        back each request the original child issued (byte-compared:
        the shim virtualizes time, entropy and I/O, so a deterministic
        binary MUST reproduce it exactly) and answer with the recorded
        response bytes. No ops are re-issued and no simulator state is
        touched: the device arrays already hold the post-checkpoint
        truth; only the real OS process needs to catch up. Afterwards
        the child sits parked in the same blocked call the snapshot
        recorded. A byte divergence (non-deterministic child: wall
        clock, unvirtualized I/O, ...) is a diagnosed supervisor kill
        — loud in SimReport.hosted — never a desynced channel."""
        if self.exited or not self._started:
            return          # dead before the snapshot, or never ran
        if self._journal is None:
            self._supervise_kill(os, "resume: snapshot carries no "
                                     "protocol journal; cannot "
                                     "fast-forward the child")
            return
        self._replaying = True
        try:
            self._spawn()
            for dirn, data in self._journal:
                data = bytes(data)
                if dirn == "tx":
                    self.chan.sendall(data)
                    continue
                got = bytearray()
                while len(got) < len(data):
                    chunk = self._recv(len(data) - len(got))
                    if not chunk:
                        raise ShimProtocolError(
                            f"channel EOF {len(got)}/{len(data)} "
                            "bytes into a journaled request (child "
                            "died during replay)")
                    got += chunk
                if bytes(got) != data:
                    off = next(i for i, (x, y)
                               in enumerate(zip(got, data)) if x != y)
                    raise ShimProtocolError(
                        f"request stream diverged at byte {off} of a "
                        f"{len(data)}-byte journaled read")
        except (ShimHang, ShimProtocolError, OSError) as e:
            self._supervise_kill(
                os, "resume: journal replay diverged — the respawned "
                    f"child did not reproduce its recorded protocol "
                    f"stream ({e})")
        finally:
            self._replaying = False

    def exit_info(self) -> dict:
        """Per-host exit record for SimReport.hosted (None while the
        child is alive and unsupervised)."""
        if not self.exited and self.exit_cause is None:
            return None
        return {"exit_status": self.exit_status,
                "cause": self.exit_cause,
                "sim_ns": self.exit_sim_ns,
                "clean": bool(self.exit_clean),
                "violations": list(self.violations)}

    def rss_bytes(self):
        """Hosted child resident set (bytes) off /proc statm — the
        [ram] tracker column (reference shd-tracker.c:266 reports real
        process RSS; modeled hosts have none). None once dead."""
        if self.proc is None or self.proc.poll() is not None:
            return None
        try:
            with open(f"/proc/{self.proc.pid}/statm") as f:
                pages = int(f.read().split()[1])
            return pages * (_os.sysconf("SC_PAGE_SIZE"))
        except (OSError, ValueError, IndexError):
            return None

    def fault_kill(self, cause, sim_ns):
        """engine.faults host_down: SIGKILL the child and record the
        cause. No socket ops are issued — the injector scrubs the dead
        host's device state itself and radiates the RSTs."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            try:
                self.proc.wait(timeout=5)
            except Exception:
                pass
        if not self.exited:
            self.exit_status = (self.proc.returncode
                                if self.proc is not None else None)
            self.exit_cause = cause
            self.exit_sim_ns = sim_ns
            self.exit_clean = False
        self.exited = True
        self.parked = None
        if self.chan is not None:
            try:
                self.chan.close()
            except OSError:
                pass
            self.chan = None
        # the host is GONE: pending grace timers died with its event
        # queue, so perform their deferred reader-less drops now
        if self._payloads is not None:
            for key in self._grace.values():
                if not self._payloads.subscribed(key):
                    self._payloads.drop(key)
                    self._opened.discard(key)
        self._grace = {}
        self._sweep_streams()

    def _park_timer(self, os, ns, kind, operand=0):
        """Arm a sim-time timer tagged to the CURRENT park (park_seq
        must already be bumped). See the tag layout above."""
        os.timer(int(ns), tag=_tag(kind, operand, self.park_seq))

    def _handle(self, os, op, a, b, c, name):
        if op == OP_SEND and int(c) == 1:
            # a stream-socket send carries the app's REAL payload bytes
            # (b = n); consume them before anything else so the channel
            # stays framed even on error answers. Datagram sends set
            # c = 0 and OP_SENDTO never carries payload (UDP contents
            # are not materialized) — the C side stamps the flag from
            # its own per-fd state, so framing never depends on
            # mirrored tables
            payload = self._read_n(b)   # raises ShimProtocolError on
            #                             EOF mid-frame (supervised)
        else:
            payload = b""
        if op == OP_POLL:
            raw = self._read_n(b)
            interest = {}
            for i in range(int(a)):
                fd, events = EVPAIR.unpack_from(raw, i * EVPAIR.size)
                interest[int(fd)] = interest.get(int(fd), 0) | int(events)
            hits = self._poll_ready(interest)
            timeout_ms = int(c)
            if hits:
                self._rsp_events(hits)
            elif timeout_ms == 0:
                self._rsp_events([])
            else:
                self.parked = ("poll", interest)
                self.park_seq += 1
                if timeout_ms > 0:
                    self._park_timer(os, timeout_ms * 1_000_000, TK_POLL)
            return
        if op == OP_SOCKET:
            vfd = self._take_vfd(b)
            self.vfds[vfd] = _VSock(kind="udp" if a else "tcp")
            self._rsp(vfd)
        elif op == OP_BIND:
            vs = self.vfds[a]
            vs.bound_port = int(b)
            if vs.kind == "udp":
                vs.sock = os.udp_open(port=int(b))
                self.by_sock[id(vs.sock)] = a
            self._rsp(0)
        elif op == OP_LISTEN:
            vs = self.vfds[a]
            vs.kind = "listen"
            vs.sock = os.tcp_listen(vs.bound_port)
            self.by_sock[id(vs.sock)] = a
            self._rsp(0)
        elif op == OP_ACCEPT:
            vs = self.vfds[a]
            if vs.accept_q:
                self._rsp_accept(vs, int(c))
            elif int(b) & 1:             # blocking listener: park
                self.parked = ("accept", a, int(c))
                self.park_seq += 1
            else:
                self._rsp(-1, EAGAIN)
        elif op == OP_SENDTO:
            vs = self.vfds[a]
            if vs.sock is None:        # unbound UDP: ephemeral port
                vs.sock = os.udp_open(port=0)
                self.by_sock[id(vs.sock)] = a
            dst = int(c) >> 16
            port = int(c) & 0xFFFF
            os.sendto(vs.sock, dst, port, int(b))
            self._rsp(b)
        elif op == OP_RECVFROM:
            vs = self.vfds[a]
            if vs.dgrams:
                src, sport, nbytes = vs.dgrams.pop(0)
                self._rsp(min(int(b), nbytes), src, sport)
            elif int(c) & 1:             # blocking: park until a dgram
                self.parked = ("recvfrom", a, int(b))
                self.park_seq += 1
            else:
                self._rsp(-1, EAGAIN)
        elif op == OP_CONNECT:
            vs = self.vfds[a]
            blk = (int(c) >> 16) & 1
            c = int(c) & 0xFFFF
            if vs.kind == "udp":
                # connected-UDP: record the default destination; no
                # handshake, succeeds immediately
                vs.bound_port = -1       # marker unused for udp
                vs.dgram_dst = (int(b), int(c))
                if vs.sock is None:
                    vs.sock = os.udp_open(port=0)
                    self.by_sock[id(vs.sock)] = a
                self._rsp(0)
            else:
                vs.sock = os.tcp_connect(int(b), int(c))
                self.by_sock[id(vs.sock)] = a
                if blk:                  # blocking connect: park until
                    self.parked = ("connect", a)   # established
                    self.park_seq += 1
                else:
                    self._rsp(-1, EINPROGRESS)  # completes via EPOLLOUT
        elif op == OP_SEND:
            vs = self.vfds[a]
            if vs.kind == "udp":
                if vs.dgram_dst is None:
                    self._rsp(-1, ENOTCONN)
                else:
                    dst, port = vs.dgram_dst
                    if vs.sock is None:
                        vs.sock = os.udp_open(port=0)
                        self.by_sock[id(vs.sock)] = a
                    os.sendto(vs.sock, dst, port, int(b))
                    self._rsp(b)
            else:
                self._tx_payload(vs, payload)
                os.write(vs.sock, int(b))
                self._rsp(b)
        elif op == OP_RECV:
            vs = self.vfds[a]
            blk = int(c) & 1
            if vs.kind == "udp":         # recv() on a datagram socket
                if vs.dgrams:
                    _src, _sp, nbytes = vs.dgrams.pop(0)
                    self._rsp_data(min(int(b), nbytes))
                elif blk:
                    self.parked = ("recvd", a, int(b))
                    self.park_seq += 1
                else:
                    self._rsp(-1, EAGAIN)
            else:
                n = min(vs.avail, int(b))
                vs.avail -= n
                if n == 0 and not vs.eof:
                    if blk:              # blocking read: park until
                        self.parked = ("recv", a, int(b))  # data/EOF
                        self.park_seq += 1
                    else:
                        self._rsp(-1, EAGAIN)
                else:
                    self._rsp_data(n, self._rx_payload(vs, n))  # 0 = EOF
        elif op in (OP_CLOSE, OP_SHUTDOWN):
            if op == OP_CLOSE and a in self.epolls:
                # closing an epoll instance: forget its interest set
                # (with C-reserved fd numbers the number WILL be
                # reused; stale state would collide in _take_vfd)
                del self.epolls[a]
                self._rsp(0)
                return
            vs = self.vfds.get(a)
            if vs is not None and vs.sock is not None and not vs.closed:
                os.close(vs.sock)
                vs.closed = True
            if op == OP_CLOSE:
                gone = self.vfds.pop(a, None)
                if gone is not None and gone.key is not None:
                    self.by_key.pop(gone.key, None)
                if gone is not None:
                    self.by_sock.pop(id(gone.sock), None)
                    if (gone.conn is not None and
                            self._payloads is not None):
                        # I was the reader of my in-direction; the peer
                        # drops the other one at its own close
                        key = gone.conn + (1 if gone.is_client else 0,)
                        self._payloads.drop(key)
                        self._opened.discard(key)
                        self._mysubs.discard(key)
                        # my OUT-direction: if no reader subscribed YET,
                        # the peer is either modeled (nothing will ever
                        # drain it) or a hosted process whose
                        # establishment wake hasn't arrived (a server
                        # that writes and closes within its accept
                        # window — banner-then-close). Don't drop now:
                        # give the peer a sim-time GRACE window to
                        # subscribe, then drop if still reader-less
                        # (round-4 advisor: the immediate drop silently
                        # discarded such a server's bytes)
                        out = gone.conn + (0 if gone.is_client else 1,)
                        if not self._payloads.subscribed(out):
                            gid = self._next_grace & 0xFFFFF
                            self._next_grace += 1
                            self._grace[gid] = out
                            os.timer(GRACE_NS, tag=_tag(TK_GRACE, gid, 0))
                for watch in self.epolls.values():
                    watch.pop(a, None)
            self._rsp(0)
        elif op == OP_EPOLL_CREATE:
            vfd = self._take_vfd(b)
            self.epolls[vfd] = {}
            self._rsp(vfd)
        elif op == OP_EPOLL_CTL:
            ctl = int(b) & 0xFFFFFFFF
            events = int(b) >> 32
            watch = self.epolls.setdefault(a, {})
            if ctl == EPOLL_CTL_DEL:
                watch.pop(int(c), None)
            else:
                watch[int(c)] = events
            self._rsp(0)
        elif op == OP_EPOLL_WAIT:
            maxev = max(int(c), 1)
            hits = self._ready(a, maxev)
            if hits:
                self._rsp_events(hits)
            elif b == 0:
                self._rsp(0)             # pure poll: never parks
            else:
                # block until a wake readies it
                self.parked = ("epoll", a, maxev)
                self.park_seq += 1
                if b > 0:                # bounded wait: sim-time timer
                    self._park_timer(os, int(b) * 1_000_000, TK_EPOLL, a)
        elif op == OP_SLEEP:
            # sleeping advances SIM time (reference shd-process.c:3055):
            # park until the deadline timer fires
            self.parked = ("sleep",)
            self.park_seq += 1
            self._park_timer(os, int(b), TK_SLEEP)
        elif op == OP_RANDOM:
            # deterministic entropy from the host PRNG (reference
            # shd-host.c:574; determinism shd-test-determinism.c)
            n = max(int(b), 0)
            self._rsp_data(n, os.random_bytes(n))
        elif op == OP_GETNAME:
            vs = self.vfds.get(a)
            if vs is None:
                self._rsp(-1, ENOTCONN)
            else:
                self._rsp(*self._name_of(os, vs, which=int(b)))
        elif op == OP_VIOLATION:
            # the child attempted a refused operation (fork/vfork/
            # exec*: shim_preload.c returned ENOSYS); record the
            # diagnostic so the refusal is visible in the exit report
            # and metrics, not only on the child's stderr
            what = name.rstrip(b"\0").decode(errors="replace") or "?"
            self.violations.append(what)
            import sys as _sys
            _sys.stderr.write(
                f"shadow_tpu: shim[{_os.path.basename(self.argv[0])}]:"
                f" child attempted {what} — refused (ENOSYS)\n")
            if _MT.ENABLED:
                _MT.REGISTRY.counter("shim.violations").inc()
            self._rsp(0)
        elif op == OP_CLOCK:
            self._rsp(os.now())
        elif op == OP_RESOLVE:
            try:
                hid = os.resolve(name.rstrip(b"\0").decode())
            except Exception:
                hid = -1
            self._rsp(hid)
        else:
            # an opcode this side does not speak is framing poison:
            # its (unknown) trailing payload would desync every later
            # frame — diagnosed channel kill, not a guessed answer
            raise ShimProtocolError(f"unknown opcode {int(op)}")

    def _name_of(self, os, vs, which):
        """getsockname (which=0) / getpeername (which=1) answer:
        (0, host, port) from the connection identity, or the bound
        port pre-establishment."""
        if vs.conn is not None:
            cli_host, cli_port, srv_host, srv_port = vs.conn
            if which == 0:
                return (0, os.host_id,
                        cli_port if vs.is_client else srv_port)
            return ((0, srv_host, srv_port) if vs.is_client
                    else (0, cli_host, cli_port))
        if which == 0:
            return (0, os.host_id, max(vs.bound_port, 0))
        if vs.kind == "udp" and vs.dgram_dst is not None:
            return (0, vs.dgram_dst[0], vs.dgram_dst[1])
        return (-1, ENOTCONN, 0)

    # --- hosted-app callbacks: map device wakes to epoll readiness ---
    def on_start(self, os):
        self._spawn()
        self._service(os)

    def _vs_of(self, sock):
        vfd = self.by_sock.get(id(sock))
        if vfd is None and sock is not None and sock.slot is not None:
            vfd = self.by_key.get((sock.slot, sock.gen))
        if vfd is None:
            return None, None
        vs = self.vfds.get(vfd)
        if (sock.slot is not None and vs is not None):
            self.by_key[(sock.slot, sock.gen)] = vfd
            vs.key = (sock.slot, sock.gen)
        return vfd, vs

    def on_connected(self, os, sock, lport=0, peer=(0, 0)):
        _, vs = self._vs_of(sock)
        if vs is not None:
            vs.connected = True
            if vs.conn is None and lport:
                # payload stream identity off the SYN|ACK: we are the
                # client side of (cli_host, cli_port, srv_host, srv_port)
                vs.conn = (os.host_id, int(lport),
                           int(peer[0]), int(peer[1]))
                vs.is_client = True
                self._open_streams(vs)
        self._service(os)

    def on_accept(self, os, sock, tag, dport=0, peer=(0, 0)):
        # queue the accepted child on its listener, matched by bound
        # port (fall back to the only listener when ports are unset)
        target = None
        for vs in self.vfds.values():
            if vs.kind == "listen":
                if vs.bound_port == dport or target is None:
                    target = vs
                    if vs.bound_port == dport:
                        break
        if target is not None:
            matched = (not dport) or target.bound_port == dport
            conn = (int(peer[0]), int(peer[1]), os.host_id,
                    int(dport) or target.bound_port)
            target.accept_q.append((sock, peer[0], peer[1], conn))
            # subscribe our inbound direction NOW, at the wake — not
            # at the app's accept() call, which it may make arbitrarily
            # later: the client's first pushes land between this wake
            # and that call, and an unsubscribed stream would cap and
            # die under them (api.PayloadBroker.push). ONLY when the
            # SYN's port matched the listener — a mismatched fallback
            # connection may never be accepted, and its subscribed
            # (cap-exempt) stream would accumulate forever (round-4
            # advisor); if the app does accept it, _rsp_accept's
            # _open_streams subscribes then, with the cap protecting
            # the interim
            if self._payloads is not None and matched:
                for d in (0, 1):
                    self._payloads.open(conn + (d,))
                    self._opened.add(conn + (d,))
                self._payloads.subscribe(conn + (0,))
                self._mysubs.add(conn + (0,))
        self._service(os)

    def on_dgram(self, os, sock, src, sport, nbytes, aux):
        # WAKE_SOCKET: TCP delivered bytes, or a UDP datagram
        _, vs = self._vs_of(sock)
        if vs is not None:
            if vs.kind == "udp":
                vs.dgrams.append((int(src), int(sport), int(nbytes)))
            else:
                vs.avail += int(nbytes)
        self._service(os)

    def on_eof(self, os, sock):
        _, vs = self._vs_of(sock)
        if vs is not None:
            vs.eof = True
        self._service(os)

    def on_sent(self, os, sock):
        self._service(os)

    def on_timer(self, os, tag):
        kind = (tag >> 20) & 0x7
        seq = (tag >> 24) & 0x7F
        operand = tag & 0xFFFFF
        if kind == TK_GRACE:
            # deferred payload-stream drop (see OP_CLOSE): drop only if
            # still reader-less — a peer that subscribed meanwhile owns
            # the stream until ITS close
            key = self._grace.pop(operand, None)
            if (key is not None and self._payloads is not None and
                    not self._payloads.subscribed(key)):
                self._payloads.drop(key)
                self._opened.discard(key)
        elif (kind == TK_EPOLL and self.parked is not None and
                self.parked[0] == "epoll" and
                (self.parked[1] & 0xFFFFF) == operand and
                seq == (self.park_seq & 0x7F)):
            # epoll_wait timeout expiry: answer 0 events iff the child
            # is still parked in the SAME wait that armed this timer
            self.parked = None
            self._rsp(0)
        elif (kind == TK_POLL and self.parked is not None and
                self.parked[0] == "poll" and
                seq == (self.park_seq & 0x7F)):
            self.parked = None
            self._rsp_events([])
        elif (kind == TK_SLEEP and self.parked is not None and
                self.parked[0] == "sleep" and
                seq == (self.park_seq & 0x7F)):
            self.parked = None
            self._rsp(0)
        self._service(os)

    def terminate(self):
        """End-of-run cleanup: release the child and the channel (a
        stop_time truncation can leave the child parked forever)."""
        if self.chan is not None:
            try:
                self.chan.close()
            except OSError:
                pass
            self.chan = None
        if self.proc is not None:
            was_alive = self.proc.poll() is None
            if was_alive:
                self.proc.terminate()
                try:
                    self.proc.wait(timeout=5)
                except Exception:
                    self.proc.kill()
                    try:
                        self.proc.wait(timeout=5)
                    except Exception:
                        pass
            if self.exit_status is None:
                self.exit_status = self.proc.returncode
            if self.exit_cause is None:
                if was_alive:
                    # truncated by the stop time while healthy — a
                    # normal end for a long-running hosted process
                    self.exit_cause = "terminated at end of run"
                    self.exit_clean = True
                else:
                    # the child had already died on its own but the
                    # death was never serviced (e.g. crashed while
                    # parked): report the REAL status, not a healthy
                    # truncation
                    self.exit_cause, self.exit_clean = _status_cause(
                        self.exit_status)
        self.exited = True
        self._sweep_streams()


register("shim", ShimApp)
