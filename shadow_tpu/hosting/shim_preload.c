/* LD_PRELOAD shim: run an unmodified epoll-based network client under
 * the simulator.
 *
 * The minimal realization of the reference's interposition library
 * (/root/reference/src/preload/shd-interposer.c: 262 PRELOADDEF
 * wrappers dispatching to process_emu_* or the real libc): this shim
 * interposes the socket/epoll/clock surface a typical nonblocking
 * client uses and forwards each call as a fixed-size request over the
 * socketpair inherited in SHADOW_SHIM_FD; the simulator-side peer is
 * shadow_tpu/hosting/shim.py (protocol defined there).
 *
 * Virtualization boundary: only fds >= VFD_BASE (handed out by the
 * simulator) are virtual; everything else falls through to the real
 * libc via dlsym(RTLD_NEXT) — same split as the reference's
 * shadow-fd vs OS-fd descriptor tables (shd-host.c fd mapping).
 *
 * Payload note (round 4): the engine still models byte COUNTS, but
 * real payload bytes now ride the control channel host-side: send()
 * ships the app's buffer to the simulator, which stores it per
 * connection (api.PayloadBroker) and returns the true stream contents
 * with each recv() when BOTH endpoints are hosted processes —
 * payload-parsing binaries (HTTP-style request/response) run
 * unmodified. recv() from a MODELED peer still zero-fills; UDP
 * datagram payloads are not materialized.
 *
 * Blocking semantics (round 4): each vfd tracks O_NONBLOCK (fcntl /
 * SOCK_NONBLOCK at creation). Nonblocking fds keep the historical
 * EINPROGRESS/EAGAIN returns; BLOCKING connect/recv/recvfrom/accept
 * forward a block flag and the simulator parks the call until the
 * matching wake (shim.py _maybe_unpark) — the analogue of the
 * reference's rpth green-thread block/reenter (shd-process.c:
 * 1076-1263), which is what lets stock blocking-socket binaries
 * (e.g. a python interpreter running a plain socket script) run
 * unmodified.
 */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/ioctl.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define VFD_BASE (1 << 20)
#define NB_CAP (1 << 16)

/* per-vfd O_NONBLOCK bits (vfds are handed out sequentially from
 * VFD_BASE by shim.py, so a small dense table suffices) */
static unsigned char nb_flags[NB_CAP];

static int vfd_nb(int fd) {
    int i = fd - VFD_BASE;
    return (i >= 0 && i < NB_CAP) ? nb_flags[i] : 0;
}

static void vfd_set_nb(int fd, int on) {
    int i = fd - VFD_BASE;
    if (i >= 0 && i < NB_CAP) nb_flags[i] = (unsigned char)(on != 0);
}

/* per-vfd SOCK_DGRAM bit: datagram sends never attach payload (UDP
 * contents are not materialized). Never cleared on close — shim.py
 * mirrors this table so both ends agree on framing for any vfd. */
static unsigned char dg_flags[NB_CAP];

static int vfd_dg(int fd) {
    int i = fd - VFD_BASE;
    return (i >= 0 && i < NB_CAP) ? dg_flags[i] : 0;
}

enum {
    OP_SOCKET = 1, OP_CONNECT, OP_SEND, OP_RECV, OP_CLOSE, OP_SHUTDOWN,
    OP_EPOLL_CREATE, OP_EPOLL_CTL, OP_EPOLL_WAIT, OP_CLOCK, OP_RESOLVE,
    OP_BIND, OP_LISTEN, OP_ACCEPT, OP_SENDTO, OP_RECVFROM,
};

struct req { int32_t op; int32_t a; int64_t b; int64_t c; char name[64]; };
struct rsp { int64_t r0; int64_t r1; int64_t r2; };
/* OP_EPOLL_WAIT responses with r0 = n > 0 are followed by n of these
 * (multi-event wait honoring maxevents; see shim.py _rsp_events) */
struct evpair { int64_t fd; int64_t events; };

static int chan_fd = -1;
static ssize_t (*real_send)(int, const void *, size_t, int);
static ssize_t (*real_recv)(int, void *, size_t, int);
static ssize_t (*real_read)(int, void *, size_t);
static ssize_t (*real_write)(int, const void *, size_t);
static int (*real_close)(int);
static int (*real_socket)(int, int, int);
static int (*real_connect)(int, const struct sockaddr *, socklen_t);
static int (*real_shutdown)(int, int);
static int (*real_epoll_create1)(int);
static int (*real_epoll_ctl)(int, int, int, struct epoll_event *);
static int (*real_epoll_wait)(int, struct epoll_event *, int, int);
static int (*real_clock_gettime)(clockid_t, struct timespec *);
static int (*real_getaddrinfo)(const char *, const char *,
                               const struct addrinfo *,
                               struct addrinfo **);

static void shim_init(void) {
    static int done = 0;
    if (done) return;
    done = 1;
    real_send = dlsym(RTLD_NEXT, "send");
    real_recv = dlsym(RTLD_NEXT, "recv");
    real_read = dlsym(RTLD_NEXT, "read");
    real_write = dlsym(RTLD_NEXT, "write");
    real_close = dlsym(RTLD_NEXT, "close");
    real_socket = dlsym(RTLD_NEXT, "socket");
    real_connect = dlsym(RTLD_NEXT, "connect");
    real_shutdown = dlsym(RTLD_NEXT, "shutdown");
    real_epoll_create1 = dlsym(RTLD_NEXT, "epoll_create1");
    real_epoll_ctl = dlsym(RTLD_NEXT, "epoll_ctl");
    real_epoll_wait = dlsym(RTLD_NEXT, "epoll_wait");
    real_clock_gettime = dlsym(RTLD_NEXT, "clock_gettime");
    real_getaddrinfo = dlsym(RTLD_NEXT, "getaddrinfo");
    const char *env = getenv("SHADOW_SHIM_FD");
    if (env) chan_fd = atoi(env);
}

static int active(void) {
    shim_init();
    return chan_fd >= 0;
}

/* one lockstep request/response on the control channel.
 *
 * Payload framing (round 4): OP_SEND requests on STREAM sockets are
 * followed by exactly b payload bytes (the app's REAL buffer — the
 * simulator stores them so hosted<->hosted connections deliver true
 * contents); datagram OP_SEND and OP_SENDTO attach nothing (UDP
 * contents are not materialized). Successful OP_RECV responses with
 * r1 == 1 are followed by exactly r0 payload bytes (real stream
 * contents); r1 == 0 means no live stream covers the read (modeled
 * peer) and the CALLER zero-fills locally — no per-byte channel
 * traffic on that path. OP_RECVFROM responses never carry payload
 * (r1/r2 hold the datagram source). tx/txn attach request payload;
 * rx/rxcap receive response payload. A short read/write kills the
 * channel (EPIPE) rather than desynchronize the framing. */
static struct rsp call2(int32_t op, int32_t a, int64_t b, int64_t c,
                        const char *name, const void *tx, size_t txn,
                        void *rx, size_t rxcap) {
    struct req q;
    struct rsp r = {-1, 0, 0};
    memset(&q, 0, sizeof q);
    q.op = op; q.a = a; q.b = b; q.c = c;
    if (name) strncpy(q.name, name, sizeof q.name - 1);
    size_t off = 0;
    while (off < sizeof q) {
        ssize_t n = real_write(chan_fd, (char *)&q + off, sizeof q - off);
        if (n <= 0) { chan_fd = -1; errno = EPIPE; return r; }
        off += (size_t)n;
    }
    off = 0;
    while (off < txn) {
        ssize_t n = real_write(chan_fd, (const char *)tx + off, txn - off);
        if (n <= 0) { chan_fd = -1; errno = EPIPE; return r; }
        off += (size_t)n;
    }
    off = 0;
    while (off < sizeof r) {
        ssize_t n = real_read(chan_fd, (char *)&r + off, sizeof r - off);
        if (n <= 0) {
            chan_fd = -1; errno = EPIPE;
            struct rsp bad = {-1, 0, 0}; return bad;
        }
        off += (size_t)n;
    }
    if (rx && r.r0 > 0 && r.r1 == 1) {
        if ((size_t)r.r0 > rxcap) {   /* protocol violation: the sim
            * side answered more than we asked — unrecoverable framing */
            chan_fd = -1; errno = EPIPE;
            struct rsp bad = {-1, 0, 0}; return bad;
        }
        off = 0;
        while (off < (size_t)r.r0) {
            ssize_t n = real_read(chan_fd, (char *)rx + off,
                                  (size_t)r.r0 - off);
            if (n <= 0) {
                chan_fd = -1; errno = EPIPE;
                struct rsp bad = {-1, 0, 0}; return bad;
            }
            off += (size_t)n;
        }
    }
    return r;
}

static struct rsp call(int32_t op, int32_t a, int64_t b, int64_t c,
                       const char *name) {
    return call2(op, a, b, c, name, NULL, 0, NULL, 0);
}

static int is_vfd(int fd) { return fd >= VFD_BASE; }

/* --- interposed surface ------------------------------------------------ */

int socket(int domain, int type, int protocol) {
    if (!active() || domain != AF_INET)
        return real_socket(domain, type, protocol);
    int dgram = (type & 0xFF) == SOCK_DGRAM;
    int fd = (int)call(OP_SOCKET, dgram, 0, 0, NULL).r0;
    if (fd >= 0) {
        vfd_set_nb(fd, (type & SOCK_NONBLOCK) != 0);
        int i = fd - VFD_BASE;
        if (i >= 0 && i < NB_CAP) dg_flags[i] = (unsigned char)dgram;
    }
    return fd;
}

int bind(int fd, const struct sockaddr *addr, socklen_t len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_bind)(int, const struct sockaddr *, socklen_t);
        if (!real_bind) real_bind = dlsym(RTLD_NEXT, "bind");
        return real_bind(fd, addr, len);
    }
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    struct rsp r = call(OP_BIND, fd, ntohs(a->sin_port), 0, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return 0;
}

int listen(int fd, int backlog) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_listen)(int, int);
        if (!real_listen) real_listen = dlsym(RTLD_NEXT, "listen");
        return real_listen(fd, backlog);
    }
    struct rsp r = call(OP_LISTEN, fd, backlog, 0, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return 0;
}

int accept4(int fd, struct sockaddr *addr, socklen_t *len, int flags) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_accept4)(int, struct sockaddr *, socklen_t *,
                                   int);
        if (!real_accept4) real_accept4 = dlsym(RTLD_NEXT, "accept4");
        return real_accept4(fd, addr, len, flags);
    }
    struct rsp r = call(OP_ACCEPT, fd, vfd_nb(fd) ? 0 : 1, 0, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    if (flags & SOCK_NONBLOCK) vfd_set_nb((int)r.r0, 1);
    if (addr && len && *len >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in *a = (struct sockaddr_in *)addr;
        memset(a, 0, sizeof *a);
        a->sin_family = AF_INET;
        a->sin_addr.s_addr = (uint32_t)r.r1;  /* virtual peer host id */
        a->sin_port = htons((uint16_t)r.r2);
        *len = sizeof *a;
    }
    return (int)r.r0;
}

int accept(int fd, struct sockaddr *addr, socklen_t *len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_accept)(int, struct sockaddr *, socklen_t *);
        if (!real_accept) real_accept = dlsym(RTLD_NEXT, "accept");
        return real_accept(fd, addr, len);
    }
    return accept4(fd, addr, len, 0);
}

ssize_t sendto(int fd, const void *buf, size_t n, int flags,
               const struct sockaddr *addr, socklen_t alen) {
    if (!active() || !is_vfd(fd)) {
        static ssize_t (*real_sendto)(int, const void *, size_t, int,
                                      const struct sockaddr *, socklen_t);
        if (!real_sendto) real_sendto = dlsym(RTLD_NEXT, "sendto");
        return real_sendto(fd, buf, n, flags, addr, alen);
    }
    if (!addr) return send(fd, buf, n, flags);
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    int64_t packed = ((int64_t)a->sin_addr.s_addr << 16) |
                     (int64_t)ntohs(a->sin_port);
    /* OP_SENDTO never attaches payload: datagram contents are not
     * materialized, so there is nothing for the simulator to keep */
    struct rsp r = call(OP_SENDTO, fd, (int64_t)n, packed, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return (ssize_t)r.r0;
}

ssize_t recvfrom(int fd, void *buf, size_t n, int flags,
                 struct sockaddr *addr, socklen_t *alen) {
    if (!active() || !is_vfd(fd)) {
        static ssize_t (*real_recvfrom)(int, void *, size_t, int,
                                        struct sockaddr *, socklen_t *);
        if (!real_recvfrom) real_recvfrom = dlsym(RTLD_NEXT, "recvfrom");
        return real_recvfrom(fd, buf, n, flags, addr, alen);
    }
    int blk = !vfd_nb(fd) && !(flags & MSG_DONTWAIT);
    struct rsp r = call(OP_RECVFROM, fd, (int64_t)n, blk, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    if ((size_t)r.r0 > n) {  /* protocol violation: never overrun buf */
        chan_fd = -1; errno = EPIPE; return -1;
    }
    memset(buf, 0, (size_t)r.r0);  /* datagram payloads not materialized */
    if (addr && alen && *alen >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in *a = (struct sockaddr_in *)addr;
        memset(a, 0, sizeof *a);
        a->sin_family = AF_INET;
        a->sin_addr.s_addr = (uint32_t)r.r1;  /* virtual src host id */
        a->sin_port = htons((uint16_t)r.r2);
        *alen = sizeof *a;
    }
    return (ssize_t)r.r0;
}

int connect(int fd, const struct sockaddr *addr, socklen_t len) {
    if (!active() || !is_vfd(fd)) return real_connect(fd, addr, len);
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    /* sin_addr carries the virtual host id verbatim (stamped by our
     * getaddrinfo); sin_port is network order. Bit 16 of the port
     * word = blocking call: park until established. */
    int64_t port = ntohs(a->sin_port);
    if (!vfd_nb(fd)) port |= (int64_t)1 << 16;
    struct rsp r = call(OP_CONNECT, fd, (int64_t)a->sin_addr.s_addr,
                        port, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return 0;
}

ssize_t send(int fd, const void *buf, size_t n, int flags) {
    if (!active() || !is_vfd(fd)) return real_send(fd, buf, n, flags);
    /* stream sends carry the REAL payload: hosted<->hosted TCP
     * connections deliver true bytes (api.PayloadBroker). Datagram
     * sends attach nothing — UDP contents are not materialized. */
    if (vfd_dg(fd))
        return (ssize_t)call(OP_SEND, fd, (int64_t)n, 0, NULL).r0;
    return (ssize_t)call2(OP_SEND, fd, (int64_t)n, 0, NULL,
                          buf, n, NULL, 0).r0;
}

ssize_t recv(int fd, void *buf, size_t n, int flags) {
    if (!active() || !is_vfd(fd)) return real_recv(fd, buf, n, flags);
    int blk = !vfd_nb(fd) && !(flags & MSG_DONTWAIT);
    /* r1 == 1: the response carries the true stream contents (hosted
     * peer); r1 == 0: modeled peer, zero-fill locally */
    struct rsp r = call2(OP_RECV, fd, (int64_t)n, blk, NULL,
                         NULL, 0, buf, n);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    if ((size_t)r.r0 > n) {  /* protocol violation: never overrun buf */
        chan_fd = -1; errno = EPIPE; return -1;
    }
    if (r.r1 != 1) memset(buf, 0, (size_t)r.r0);
    return (ssize_t)r.r0;
}

ssize_t write(int fd, const void *buf, size_t n) {
    if (active() && is_vfd(fd)) return send(fd, buf, n, 0);
    shim_init();
    return real_write(fd, buf, n);
}

ssize_t read(int fd, void *buf, size_t n) {
    if (active() && is_vfd(fd)) return recv(fd, buf, n, 0);
    shim_init();
    return real_read(fd, buf, n);
}

int shutdown(int fd, int how) {
    if (!active() || !is_vfd(fd)) return real_shutdown(fd, how);
    return (int)call(OP_SHUTDOWN, fd, how, 0, NULL).r0;
}

int close(int fd) {
    if (!active() || !is_vfd(fd)) { shim_init(); return real_close(fd); }
    return (int)call(OP_CLOSE, fd, 0, 0, NULL).r0;
}

int epoll_create1(int flags) {
    if (!active()) return real_epoll_create1(flags);
    return (int)call(OP_EPOLL_CREATE, 0, 0, 0, NULL).r0;
}

int epoll_create(int size) { (void)size; return epoll_create1(0); }

int epoll_ctl(int epfd, int op, int fd, struct epoll_event *ev) {
    if (!active() || !is_vfd(epfd)) return real_epoll_ctl(epfd, op, fd, ev);
    int64_t packed = (int64_t)op |
        ((int64_t)(ev ? ev->events : 0) << 32);
    return (int)call(OP_EPOLL_CTL, epfd, packed, fd, NULL).r0;
}

int epoll_wait(int epfd, struct epoll_event *evs, int maxevents,
               int timeout) {
    if (!active() || !is_vfd(epfd))
        return real_epoll_wait(epfd, evs, maxevents, timeout);
    if (maxevents < 1) { errno = EINVAL; return -1; }
    struct rsp r = call(OP_EPOLL_WAIT, epfd, timeout, maxevents, NULL);
    if (r.r0 <= 0) return (int)r.r0;
    /* r0 = n ready events; read the n trailing (fd, events) pairs */
    int n = (int)r.r0;
    for (int i = 0; i < n; i++) {
        struct evpair p;
        size_t off = 0;
        while (off < sizeof p) {
            ssize_t m = real_read(chan_fd, (char *)&p + off,
                                  sizeof p - off);
            if (m <= 0) {
                /* short read of a trailing evpair: returning a partial
                 * count would leave unread bytes in the channel and
                 * the next call() would parse them as a rsp header —
                 * a silent protocol desync. Kill the channel and fail
                 * fast instead. */
                chan_fd = -1;
                errno = EPIPE;
                return -1;
            }
            off += (size_t)m;
        }
        evs[i].events = (uint32_t)p.events;
        evs[i].data.fd = (int)p.fd;
    }
    return n;
}

int clock_gettime(clockid_t clk, struct timespec *ts) {
    if (!active()) return real_clock_gettime(clk, ts);
    int64_t ns = call(OP_CLOCK, (int32_t)clk, 0, 0, NULL).r0;
    ts->tv_sec = ns / 1000000000LL;
    ts->tv_nsec = ns % 1000000000LL;
    return 0;
}

int getaddrinfo(const char *node, const char *service,
                const struct addrinfo *hints, struct addrinfo **res) {
    if (!active()) return real_getaddrinfo(node, service, hints, res);
    struct rsp r = call(OP_RESOLVE, 0, 0, 0, node);
    if (r.r0 < 0) return EAI_NONAME;
    struct addrinfo *ai = calloc(1, sizeof *ai);
    struct sockaddr_in *sa = calloc(1, sizeof *sa);
    sa->sin_family = AF_INET;
    sa->sin_addr.s_addr = (uint32_t)r.r0;   /* virtual host id */
    sa->sin_port = service ? htons((uint16_t)atoi(service)) : 0;
    ai->ai_family = AF_INET;
    ai->ai_socktype = hints ? hints->ai_socktype : SOCK_STREAM;
    ai->ai_addrlen = sizeof *sa;
    ai->ai_addr = (struct sockaddr *)sa;
    *res = ai;
    return 0;
}

void freeaddrinfo(struct addrinfo *res) {
    /* frees only what our getaddrinfo allocated; pass through others */
    if (!active()) {
        void (*real_fai)(struct addrinfo *) =
            dlsym(RTLD_NEXT, "freeaddrinfo");
        real_fai(res);
        return;
    }
    if (res) { free(res->ai_addr); free(res); }
}

/* CPython's socket(fileno=fd) — the path accept() takes to wrap an
 * accepted fd — calls getsockname() to detect the address family; an
 * uninterposed call would hit the real kernel with a virtual fd
 * (EBADF) and kill a hosted python SERVER at its first accept. The
 * shim answers AF_INET with a zero address: callers use the family,
 * and peer identity comes from accept4's filled sockaddr instead. */
int getsockname(int fd, struct sockaddr *addr, socklen_t *len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_gsn)(int, struct sockaddr *, socklen_t *);
        if (!real_gsn) real_gsn = dlsym(RTLD_NEXT, "getsockname");
        return real_gsn(fd, addr, len);
    }
    if (addr && len && *len >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in *a = (struct sockaddr_in *)addr;
        memset(a, 0, sizeof *a);
        a->sin_family = AF_INET;
        *len = sizeof *a;
    }
    return 0;
}

int getpeername(int fd, struct sockaddr *addr, socklen_t *len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_gpn)(int, struct sockaddr *, socklen_t *);
        if (!real_gpn) real_gpn = dlsym(RTLD_NEXT, "getpeername");
        return real_gpn(fd, addr, len);
    }
    if (addr && len && *len >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in *a = (struct sockaddr_in *)addr;
        memset(a, 0, sizeof *a);
        a->sin_family = AF_INET;
        *len = sizeof *a;
    }
    return 0;
}

/* harmless accepted no-ops on virtual fds */
int setsockopt(int fd, int level, int optname, const void *optval,
               socklen_t optlen) {
    if (active() && is_vfd(fd)) return 0;
    static int (*real_sso)(int, int, int, const void *, socklen_t);
    if (!real_sso) real_sso = dlsym(RTLD_NEXT, "setsockopt");
    return real_sso(fd, level, optname, optval, optlen);
}

int getsockopt(int fd, int level, int optname, void *optval,
               socklen_t *optlen) {
    if (active() && is_vfd(fd)) {
        /* SO_ERROR after EPOLLOUT: connection is established */
        if (optval && optlen && *optlen >= sizeof(int))
            *(int *)optval = 0;
        return 0;
    }
    static int (*real_gso)(int, int, int, void *, socklen_t *);
    if (!real_gso) real_gso = dlsym(RTLD_NEXT, "getsockopt");
    return real_gso(fd, level, optname, optval, optlen);
}

int ioctl(int fd, unsigned long req, ...) {
    __builtin_va_list ap;
    __builtin_va_start(ap, req);
    void *argp = __builtin_va_arg(ap, void *);
    __builtin_va_end(ap);
    if (active() && is_vfd(fd)) {
        /* FIONBIO is how CPython's internal_setblocking toggles
         * blocking mode on Linux — without this, s.setblocking(False)
         * or any socket timeout in a hosted python script would hit
         * the real kernel with a virtual fd (EBADF) */
        if (req == FIONBIO && argp) {
            vfd_set_nb(fd, *(int *)argp != 0);
            return 0;
        }
        return 0;                       /* FIONREAD etc: accepted */
    }
    static int (*real_ioctl)(int, unsigned long, ...);
    if (!real_ioctl) real_ioctl = dlsym(RTLD_NEXT, "ioctl");
    return real_ioctl(fd, req, argp);
}

int fcntl(int fd, int cmd, ...) {
    __builtin_va_list ap;
    __builtin_va_start(ap, cmd);
    long arg = __builtin_va_arg(ap, long);
    __builtin_va_end(ap);
    if (active() && is_vfd(fd)) {
        if (cmd == F_SETFL) { vfd_set_nb(fd, arg & O_NONBLOCK); return 0; }
        if (cmd == F_GETFL) return vfd_nb(fd) ? O_NONBLOCK : 0;
        return 0;                        /* F_SETFD etc: accepted */
    }
    static int (*real_fcntl)(int, int, ...);
    if (!real_fcntl) real_fcntl = dlsym(RTLD_NEXT, "fcntl");
    return real_fcntl(fd, cmd, arg);
}
