/* LD_PRELOAD shim: run an unmodified network binary under the
 * simulator.
 *
 * The minimal realization of the reference's interposition library
 * (/root/reference/src/preload/shd-interposer.c: 262 PRELOADDEF
 * wrappers dispatching to process_emu_* or the real libc): this shim
 * interposes the socket/epoll/poll/select/clock/sleep/entropy surface
 * a typical network client or server uses and forwards each call as a
 * fixed-size request over the socketpair inherited in SHADOW_SHIM_FD;
 * the simulator-side peer is shadow_tpu/hosting/shim.py (protocol
 * defined there).
 *
 * Virtual fd numbering (round 5): a virtual fd IS a real fd number —
 * each simulated socket/epoll/random-device reserves a kernel fd by
 * opening /dev/null and the simulator keys its state by that number.
 * This keeps vfds small and dense (select()'s fd_set caps fds at
 * FD_SETSIZE=1024, and real apps assume small fds), guarantees no
 * collision with the process's real fds (the kernel can't hand the
 * number out twice), and gives close() ordinary semantics (placeholder
 * and simulator state retire together). The reference solves the same
 * problem with a shadow descriptor table layered over the process fd
 * space (shd-host.c fd mapping).
 *
 * Virtualized beyond sockets (round 5, reference shd-process.c
 * equivalents in parens):
 *  - poll/ppoll/select/pselect on virtual fds (process_emu_poll/
 *    select, shd-process.c:2606-2899);
 *  - gettimeofday/time/clock_gettime all read SIMULATED time
 *    (shd-process.c:4329-4389 — one leaking wallclock call breaks
 *    determinism);
 *  - nanosleep/usleep/sleep advance SIM time, not wall time
 *    (process_emu_nanosleep, shd-process.c:3055);
 *  - getrandom/getentropy and open("/dev/u?random") serve bytes from
 *    the host's deterministic PRNG (shd-host.c:574; determinism test
 *    src/test/determinism/shd-test-determinism.c:15-60);
 *  - getsockname/getpeername answer the real simulated identity;
 *  - pthread_create fails LOUDLY (EAGAIN + stderr): a silently-real
 *    thread would corrupt sim semantics — multi-threaded hosting
 *    (the reference's rpth + pthread emu, shd-process.c:5074-7449)
 *    is not implemented.
 *
 * Payload note (round 4): the engine models byte COUNTS, but real
 * payload bytes ride the control channel host-side: send() ships the
 * app's buffer to the simulator, which stores it per connection
 * (api.PayloadBroker) and returns the true stream contents with each
 * recv() when BOTH endpoints are hosted processes. recv() from a
 * MODELED peer zero-fills; UDP datagram payloads are not materialized.
 *
 * Blocking semantics (round 4): each vfd tracks O_NONBLOCK (fcntl /
 * SOCK_NONBLOCK at creation). Nonblocking fds keep EINPROGRESS/EAGAIN
 * returns; BLOCKING connect/recv/recvfrom/accept/poll/epoll_wait
 * forward a block flag and the simulator parks the call until the
 * matching wake (shim.py _maybe_unpark) — the analogue of the
 * reference's rpth green-thread block/reenter (shd-process.c:
 * 1076-1263).
 */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>
#include <pthread.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/ioctl.h>
#include <sys/random.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

#define FD_CAP (1 << 16)

/* per-fd state bits (vfds are real fd numbers < FD_CAP in practice;
 * an fd past the cap simply cannot become virtual) */
#define VS_VFD 1      /* simulator-managed fd */
#define VS_NB 2       /* O_NONBLOCK */
#define VS_DGRAM 4    /* SOCK_DGRAM: sends never attach payload */
#define VS_RANDOM 8   /* /dev/u?random: reads serve host PRNG bytes */
static unsigned char vstate[FD_CAP];

static int is_vfd(int fd) {
    return fd >= 0 && fd < FD_CAP && (vstate[fd] & VS_VFD);
}

static int vfd_nb(int fd) { return is_vfd(fd) && (vstate[fd] & VS_NB); }

static void vfd_set_nb(int fd, int on) {
    if (fd >= 0 && fd < FD_CAP) {
        if (on) vstate[fd] |= VS_NB; else vstate[fd] &= ~VS_NB;
    }
}

static int vfd_dg(int fd) { return is_vfd(fd) && (vstate[fd] & VS_DGRAM); }

enum {
    OP_SOCKET = 1, OP_CONNECT, OP_SEND, OP_RECV, OP_CLOSE, OP_SHUTDOWN,
    OP_EPOLL_CREATE, OP_EPOLL_CTL, OP_EPOLL_WAIT, OP_CLOCK, OP_RESOLVE,
    OP_BIND, OP_LISTEN, OP_ACCEPT, OP_SENDTO, OP_RECVFROM,
    OP_SLEEP, OP_POLL, OP_RANDOM, OP_GETNAME, OP_VIOLATION,
};

struct req { int32_t op; int32_t a; int64_t b; int64_t c; char name[64]; };
struct rsp { int64_t r0; int64_t r1; int64_t r2; };
/* OP_EPOLL_WAIT / OP_POLL responses with r0 = n > 0 are followed by n
 * of these (fd, events/revents pairs; see shim.py _rsp_events) */
struct evpair { int64_t fd; int64_t events; };

static int chan_fd = -1;
static ssize_t (*real_send)(int, const void *, size_t, int);
static ssize_t (*real_recv)(int, void *, size_t, int);
static ssize_t (*real_read)(int, void *, size_t);
static ssize_t (*real_write)(int, const void *, size_t);
static int (*real_close)(int);
static int (*real_socket)(int, int, int);
static int (*real_connect)(int, const struct sockaddr *, socklen_t);
static int (*real_shutdown)(int, int);
static int (*real_epoll_create1)(int);
static int (*real_epoll_ctl)(int, int, int, struct epoll_event *);
static int (*real_epoll_wait)(int, struct epoll_event *, int, int);
static int (*real_clock_gettime)(clockid_t, struct timespec *);
static int (*real_getaddrinfo)(const char *, const char *,
                               const struct addrinfo *,
                               struct addrinfo **);
static int (*real_poll)(struct pollfd *, nfds_t, int);
static int (*real_open)(const char *, int, ...);

static void shim_init(void) {
    static int done = 0;
    if (done) return;
    done = 1;
    real_send = dlsym(RTLD_NEXT, "send");
    real_recv = dlsym(RTLD_NEXT, "recv");
    real_read = dlsym(RTLD_NEXT, "read");
    real_write = dlsym(RTLD_NEXT, "write");
    real_close = dlsym(RTLD_NEXT, "close");
    real_socket = dlsym(RTLD_NEXT, "socket");
    real_connect = dlsym(RTLD_NEXT, "connect");
    real_shutdown = dlsym(RTLD_NEXT, "shutdown");
    real_epoll_create1 = dlsym(RTLD_NEXT, "epoll_create1");
    real_epoll_ctl = dlsym(RTLD_NEXT, "epoll_ctl");
    real_epoll_wait = dlsym(RTLD_NEXT, "epoll_wait");
    real_clock_gettime = dlsym(RTLD_NEXT, "clock_gettime");
    real_getaddrinfo = dlsym(RTLD_NEXT, "getaddrinfo");
    real_poll = dlsym(RTLD_NEXT, "poll");
    real_open = dlsym(RTLD_NEXT, "open");
    const char *env = getenv("SHADOW_SHIM_FD");
    if (env) chan_fd = atoi(env);
}

static int active(void) {
    shim_init();
    return chan_fd >= 0;
}

/* Reserve a kernel fd number for a new virtual fd. The placeholder
 * (an open /dev/null) pins the number so no real open can collide
 * with it; the simulator keys its state by this number. Returns -1
 * (EMFILE/ENFILE errno from open) on failure. */
static int vfd_reserve(void) {
    int fd = real_open("/dev/null", O_RDWR | O_CLOEXEC);
    if (fd < 0) return -1;
    if (fd >= FD_CAP) {   /* cannot track state past the table */
        real_close(fd);
        errno = EMFILE;
        return -1;
    }
    vstate[fd] = VS_VFD;
    return fd;
}

static void vfd_release(int fd) {
    if (fd >= 0 && fd < FD_CAP) {
        vstate[fd] = 0;
        real_close(fd);
    }
}

/* one lockstep request/response on the control channel.
 *
 * Payload framing: OP_SEND requests on STREAM sockets are followed by
 * exactly b payload bytes (the app's REAL buffer); OP_POLL requests
 * are followed by a * 16 bytes of evpairs (the virtual pollfd set).
 * Datagram OP_SEND and OP_SENDTO attach nothing. Successful OP_RECV /
 * OP_RANDOM responses with r1 == 1 are followed by exactly r0 payload
 * bytes; r1 == 0 means no live stream covers the read (modeled peer)
 * and the CALLER zero-fills locally. OP_RECVFROM responses never carry
 * payload (r1/r2 hold the datagram source). tx/txn attach request
 * payload; rx/rxcap receive response payload. A short read/write kills
 * the channel (EPIPE) rather than desynchronize the framing. */
static struct rsp call2(int32_t op, int32_t a, int64_t b, int64_t c,
                        const char *name, const void *tx, size_t txn,
                        void *rx, size_t rxcap) {
    struct req q;
    struct rsp r = {-1, 0, 0};
    memset(&q, 0, sizeof q);
    q.op = op; q.a = a; q.b = b; q.c = c;
    if (name) strncpy(q.name, name, sizeof q.name - 1);
    size_t off = 0;
    while (off < sizeof q) {
        ssize_t n = real_write(chan_fd, (char *)&q + off, sizeof q - off);
        if (n <= 0) { chan_fd = -1; errno = EPIPE; return r; }
        off += (size_t)n;
    }
    off = 0;
    while (off < txn) {
        ssize_t n = real_write(chan_fd, (const char *)tx + off, txn - off);
        if (n <= 0) { chan_fd = -1; errno = EPIPE; return r; }
        off += (size_t)n;
    }
    off = 0;
    while (off < sizeof r) {
        ssize_t n = real_read(chan_fd, (char *)&r + off, sizeof r - off);
        if (n <= 0) {
            chan_fd = -1; errno = EPIPE;
            struct rsp bad = {-1, 0, 0}; return bad;
        }
        off += (size_t)n;
    }
    if (rx && r.r0 > 0 && r.r1 == 1) {
        if ((size_t)r.r0 > rxcap) {   /* protocol violation: the sim
            * side answered more than we asked — unrecoverable framing */
            chan_fd = -1; errno = EPIPE;
            struct rsp bad = {-1, 0, 0}; return bad;
        }
        off = 0;
        while (off < (size_t)r.r0) {
            ssize_t n = real_read(chan_fd, (char *)rx + off,
                                  (size_t)r.r0 - off);
            if (n <= 0) {
                chan_fd = -1; errno = EPIPE;
                struct rsp bad = {-1, 0, 0}; return bad;
            }
            off += (size_t)n;
        }
    }
    return r;
}

static struct rsp call(int32_t op, int32_t a, int64_t b, int64_t c,
                       const char *name) {
    return call2(op, a, b, c, name, NULL, 0, NULL, 0);
}

/* --- interposed surface ------------------------------------------------ */

int socket(int domain, int type, int protocol) {
    if (!active() || domain != AF_INET)
        return real_socket(domain, type, protocol);
    int dgram = (type & 0xFF) == SOCK_DGRAM;
    int fd = vfd_reserve();
    if (fd < 0) return -1;
    struct rsp r = call(OP_SOCKET, dgram, fd, 0, NULL);
    if (r.r0 < 0) { vfd_release(fd); errno = EMFILE; return -1; }
    if (type & SOCK_NONBLOCK) vstate[fd] |= VS_NB;
    if (dgram) vstate[fd] |= VS_DGRAM;
    return fd;
}

int bind(int fd, const struct sockaddr *addr, socklen_t len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_bind)(int, const struct sockaddr *, socklen_t);
        if (!real_bind) real_bind = dlsym(RTLD_NEXT, "bind");
        return real_bind(fd, addr, len);
    }
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    struct rsp r = call(OP_BIND, fd, ntohs(a->sin_port), 0, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return 0;
}

int listen(int fd, int backlog) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_listen)(int, int);
        if (!real_listen) real_listen = dlsym(RTLD_NEXT, "listen");
        return real_listen(fd, backlog);
    }
    struct rsp r = call(OP_LISTEN, fd, backlog, 0, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return 0;
}

int accept4(int fd, struct sockaddr *addr, socklen_t *len, int flags) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_accept4)(int, struct sockaddr *, socklen_t *,
                                   int);
        if (!real_accept4) real_accept4 = dlsym(RTLD_NEXT, "accept4");
        return real_accept4(fd, addr, len, flags);
    }
    int cfd = vfd_reserve();   /* the child's number, picked up front */
    if (cfd < 0) return -1;
    struct rsp r = call(OP_ACCEPT, fd, vfd_nb(fd) ? 0 : 1, cfd, NULL);
    if (r.r0 < 0) { vfd_release(cfd); errno = (int)r.r1; return -1; }
    if (flags & SOCK_NONBLOCK) vstate[cfd] |= VS_NB;
    if (addr && len && *len >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in *a = (struct sockaddr_in *)addr;
        memset(a, 0, sizeof *a);
        a->sin_family = AF_INET;
        a->sin_addr.s_addr = (uint32_t)r.r1;  /* virtual peer host id */
        a->sin_port = htons((uint16_t)r.r2);
        *len = sizeof *a;
    }
    return cfd;
}

int accept(int fd, struct sockaddr *addr, socklen_t *len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_accept)(int, struct sockaddr *, socklen_t *);
        if (!real_accept) real_accept = dlsym(RTLD_NEXT, "accept");
        return real_accept(fd, addr, len);
    }
    return accept4(fd, addr, len, 0);
}

ssize_t sendto(int fd, const void *buf, size_t n, int flags,
               const struct sockaddr *addr, socklen_t alen) {
    if (!active() || !is_vfd(fd)) {
        static ssize_t (*real_sendto)(int, const void *, size_t, int,
                                      const struct sockaddr *, socklen_t);
        if (!real_sendto) real_sendto = dlsym(RTLD_NEXT, "sendto");
        return real_sendto(fd, buf, n, flags, addr, alen);
    }
    if (!addr) return send(fd, buf, n, flags);
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    int64_t packed = ((int64_t)a->sin_addr.s_addr << 16) |
                     (int64_t)ntohs(a->sin_port);
    /* OP_SENDTO never attaches payload: datagram contents are not
     * materialized, so there is nothing for the simulator to keep */
    struct rsp r = call(OP_SENDTO, fd, (int64_t)n, packed, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return (ssize_t)r.r0;
}

ssize_t recvfrom(int fd, void *buf, size_t n, int flags,
                 struct sockaddr *addr, socklen_t *alen) {
    if (!active() || !is_vfd(fd)) {
        static ssize_t (*real_recvfrom)(int, void *, size_t, int,
                                        struct sockaddr *, socklen_t *);
        if (!real_recvfrom) real_recvfrom = dlsym(RTLD_NEXT, "recvfrom");
        return real_recvfrom(fd, buf, n, flags, addr, alen);
    }
    int blk = !vfd_nb(fd) && !(flags & MSG_DONTWAIT);
    struct rsp r = call(OP_RECVFROM, fd, (int64_t)n, blk, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    if ((size_t)r.r0 > n) {  /* protocol violation: never overrun buf */
        chan_fd = -1; errno = EPIPE; return -1;
    }
    memset(buf, 0, (size_t)r.r0);  /* datagram payloads not materialized */
    if (addr && alen && *alen >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in *a = (struct sockaddr_in *)addr;
        memset(a, 0, sizeof *a);
        a->sin_family = AF_INET;
        a->sin_addr.s_addr = (uint32_t)r.r1;  /* virtual src host id */
        a->sin_port = htons((uint16_t)r.r2);
        *alen = sizeof *a;
    }
    return (ssize_t)r.r0;
}

int connect(int fd, const struct sockaddr *addr, socklen_t len) {
    if (!active() || !is_vfd(fd)) return real_connect(fd, addr, len);
    const struct sockaddr_in *a = (const struct sockaddr_in *)addr;
    /* sin_addr carries the virtual host id verbatim (stamped by our
     * getaddrinfo); sin_port is network order. Bit 16 of the port
     * word = blocking call: park until established. */
    int64_t port = ntohs(a->sin_port);
    if (!vfd_nb(fd)) port |= (int64_t)1 << 16;
    struct rsp r = call(OP_CONNECT, fd, (int64_t)a->sin_addr.s_addr,
                        port, NULL);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    return 0;
}

ssize_t send(int fd, const void *buf, size_t n, int flags) {
    if (!active() || !is_vfd(fd)) return real_send(fd, buf, n, flags);
    if (vstate[fd] & VS_RANDOM) {   /* write() to /dev/u?random (app
        * entropy seeding) — not a socket: forwarding OP_SEND would
        * make shim.py's handler KeyError on an fd it never tracked
        * and kill the whole simulation. Refuse like the recv() guard
        * (round-5 advisor). */
        errno = EBADF;
        return -1;
    }
    /* stream sends carry the REAL payload: hosted<->hosted TCP
     * connections deliver true bytes (api.PayloadBroker). Datagram
     * sends attach nothing — UDP contents are not materialized. */
    if (vfd_dg(fd))
        return (ssize_t)call(OP_SEND, fd, (int64_t)n, 0, NULL).r0;
    return (ssize_t)call2(OP_SEND, fd, (int64_t)n, 1, NULL,
                          buf, n, NULL, 0).r0;
}

ssize_t recv(int fd, void *buf, size_t n, int flags) {
    if (!active() || !is_vfd(fd)) return real_recv(fd, buf, n, flags);
    if (vstate[fd] & VS_RANDOM) {       /* via recv on a random vfd */
        errno = ENOTSOCK; return -1;
    }
    int blk = !vfd_nb(fd) && !(flags & MSG_DONTWAIT);
    /* r1 == 1: the response carries the true stream contents (hosted
     * peer); r1 == 0: modeled peer, zero-fill locally */
    struct rsp r = call2(OP_RECV, fd, (int64_t)n, blk, NULL,
                         NULL, 0, buf, n);
    if (r.r0 < 0) { errno = (int)r.r1; return -1; }
    if ((size_t)r.r0 > n) {  /* protocol violation: never overrun buf */
        chan_fd = -1; errno = EPIPE; return -1;
    }
    if (r.r1 != 1) memset(buf, 0, (size_t)r.r0);
    return (ssize_t)r.r0;
}

/* serve n deterministic PRNG bytes from the simulator (chunked so one
 * huge read cannot wedge the channel) */
static ssize_t random_fill(void *buf, size_t n) {
    size_t got = 0;
    while (got < n) {
        size_t k = n - got;
        if (k > (1 << 16)) k = 1 << 16;
        struct rsp r = call2(OP_RANDOM, 0, (int64_t)k, 0, NULL,
                             NULL, 0, (char *)buf + got, k);
        if (r.r0 <= 0) return got ? (ssize_t)got : -1;
        got += (size_t)r.r0;
    }
    return (ssize_t)got;
}

ssize_t write(int fd, const void *buf, size_t n) {
    if (active() && is_vfd(fd)) return send(fd, buf, n, 0);
    shim_init();
    return real_write(fd, buf, n);
}

ssize_t read(int fd, void *buf, size_t n) {
    if (active() && is_vfd(fd)) {
        if (vstate[fd] & VS_RANDOM) return random_fill(buf, n);
        return recv(fd, buf, n, 0);
    }
    shim_init();
    return real_read(fd, buf, n);
}

int shutdown(int fd, int how) {
    if (!active() || !is_vfd(fd)) return real_shutdown(fd, how);
    return (int)call(OP_SHUTDOWN, fd, how, 0, NULL).r0;
}

int close(int fd) {
    if (!active() || !is_vfd(fd)) { shim_init(); return real_close(fd); }
    int rnd = vstate[fd] & VS_RANDOM;
    int rc = rnd ? 0 : (int)call(OP_CLOSE, fd, 0, 0, NULL).r0;
    vfd_release(fd);        /* free the placeholder + state bits */
    return rc;
}

int epoll_create1(int flags) {
    if (!active()) return real_epoll_create1(flags);
    int fd = vfd_reserve();
    if (fd < 0) return -1;
    struct rsp r = call(OP_EPOLL_CREATE, 0, fd, 0, NULL);
    if (r.r0 < 0) { vfd_release(fd); errno = EMFILE; return -1; }
    return fd;
}

int epoll_create(int size) { (void)size; return epoll_create1(0); }

int epoll_ctl(int epfd, int op, int fd, struct epoll_event *ev) {
    if (!active() || !is_vfd(epfd)) return real_epoll_ctl(epfd, op, fd, ev);
    int64_t packed = (int64_t)op |
        ((int64_t)(ev ? ev->events : 0) << 32);
    return (int)call(OP_EPOLL_CTL, epfd, packed, fd, NULL).r0;
}

/* read n trailing evpairs of a wait/poll response into out[] (cap
 * entries); returns n or -1 on channel failure */
static int read_evpairs(int n, struct evpair *out, int cap) {
    for (int i = 0; i < n; i++) {
        struct evpair p;
        size_t off = 0;
        while (off < sizeof p) {
            ssize_t m = real_read(chan_fd, (char *)&p + off,
                                  sizeof p - off);
            if (m <= 0) {
                /* short read of a trailing evpair: returning a partial
                 * count would leave unread bytes in the channel and
                 * the next call() would parse them as a rsp header —
                 * a silent protocol desync. Kill the channel and fail
                 * fast instead. */
                chan_fd = -1;
                errno = EPIPE;
                return -1;
            }
            off += (size_t)m;
        }
        if (i < cap) out[i] = p;
    }
    return n;
}

int epoll_wait(int epfd, struct epoll_event *evs, int maxevents,
               int timeout) {
    if (!active() || !is_vfd(epfd))
        return real_epoll_wait(epfd, evs, maxevents, timeout);
    if (maxevents < 1) { errno = EINVAL; return -1; }
    struct rsp r = call(OP_EPOLL_WAIT, epfd, timeout, maxevents, NULL);
    if (r.r0 <= 0) return (int)r.r0;
    /* r0 = n <= maxevents ready events (the sim honors maxevents);
     * read the n trailing (fd, events) pairs straight into evs */
    int n = (int)r.r0;
    if (n > maxevents) { chan_fd = -1; errno = EPIPE; return -1; }
    for (int i = 0; i < n; i++) {
        struct evpair p;
        if (read_evpairs(1, &p, 1) < 0) return -1;
        evs[i].events = (uint32_t)p.events;
        evs[i].data.fd = (int)p.fd;
    }
    return n;
}

int epoll_pwait(int epfd, struct epoll_event *evs, int maxevents,
                int timeout, const sigset_t *mask) {
    (void)mask;   /* no signals are delivered to parked hosted code */
    if (!active() || !is_vfd(epfd)) {
        static int (*real_ep)(int, struct epoll_event *, int, int,
                              const sigset_t *);
        if (!real_ep) real_ep = dlsym(RTLD_NEXT, "epoll_pwait");
        return real_ep(epfd, evs, maxevents, timeout, mask);
    }
    return epoll_wait(epfd, evs, maxevents, timeout);
}

/* --- poll / select ----------------------------------------------------- */

static int vsleep_ns(int64_t ns);   /* defined with the sleep surface */

/* Forward the VIRTUAL subset of a pollfd array to the simulator.
 * Mixed sets (virtual + real fds) wait only on the virtual ones —
 * real fds report no events (documented limitation: the simulator
 * cannot wait on kernel fds, and hosted binaries' interesting fds are
 * exactly the virtual ones). Returns the poll() result over fds[]. */
static int vpoll(struct pollfd *fds, nfds_t nfds, int timeout_ms) {
    struct evpair want[256];
    int nv = 0, nreal = 0;
    nfds_t nvirt = 0;
    for (nfds_t i = 0; i < nfds; i++) {
        fds[i].revents = 0;   /* ALL entries, unconditionally: a stale
            * revents on an entry past any cap would report phantom
            * readiness (round-5 advisor) */
        if (fds[i].fd < 0) continue;   /* negative fd = ignore entry */
        if (is_vfd(fds[i].fd)) {
            nvirt++;
            if (nv < 256) {
                want[nv].fd = fds[i].fd;
                want[nv].events = fds[i].events;
                nv++;
            }
        } else {
            nreal++;
        }
    }
    if (nvirt > 256) {   /* fail LOUD instead of silently waiting on a
        * truncated subset (events on the dropped fds would never
        * wake the caller) */
        errno = EINVAL;
        return -1;
    }
    if (nv == 0) {
        /* a poll that waits on NOTHING (empty array, or every entry
         * disabled with fd < 0 — both standard sleep idioms) must
         * advance SIM time: a real poll would burn wallclock while
         * the virtual clock stays frozen, so `while (time() <
         * deadline) poll(0,0,100)` would never terminate (round-5
         * advisor; mirrors nanosleep -> OP_SLEEP). The infinite form
         * (timeout -1, the pause() idiom) must not reach the REAL
         * poll either — it would block the child forever in wallclock
         * and wedge the whole simulator (shim.py waits in _read_req);
         * park it past any stop_time instead (the run's teardown
         * releases the child). */
        if (nreal == 0 && timeout_ms != 0) {
            vsleep_ns(timeout_ms > 0 ? (int64_t)timeout_ms * 1000000LL
                                     : (int64_t)1 << 62);
            return 0;
        }
        return real_poll(fds, nfds, timeout_ms);
    }
    struct rsp r = call2(OP_POLL, nv, (int64_t)nv * sizeof(struct evpair),
                         timeout_ms, NULL, want,
                         (size_t)nv * sizeof(struct evpair), NULL, 0);
    if (r.r0 < 0) { errno = (int)r.r1 ? (int)r.r1 : EPIPE; return -1; }
    int n = (int)r.r0;
    struct evpair pairs[256];
    if (n > 256) { chan_fd = -1; errno = EPIPE; return -1; }
    if (n > 0 && read_evpairs(n, pairs, 256) < 0) return -1;
    int hits = 0;
    for (nfds_t i = 0; i < nfds; i++) {
        for (int j = 0; j < n; j++) {
            if (pairs[j].fd == fds[i].fd) {
                fds[i].revents = (short)pairs[j].events;
                break;
            }
        }
        if (fds[i].revents) hits++;
    }
    return hits;
}

int poll(struct pollfd *fds, nfds_t nfds, int timeout) {
    if (!active()) { shim_init(); return real_poll(fds, nfds, timeout); }
    return vpoll(fds, nfds, timeout);
}

int ppoll(struct pollfd *fds, nfds_t nfds, const struct timespec *ts,
          const sigset_t *mask) {
    (void)mask;
    if (!active()) {
        static int (*real_pp)(struct pollfd *, nfds_t,
                              const struct timespec *, const sigset_t *);
        if (!real_pp) real_pp = dlsym(RTLD_NEXT, "ppoll");
        return real_pp(fds, nfds, ts, mask);
    }
    int ms = ts ? (int)(ts->tv_sec * 1000 +
                        (ts->tv_nsec + 999999) / 1000000) : -1;
    return vpoll(fds, nfds, ms);
}

/* select() rebuilt on vpoll: only meaningful for fds < FD_SETSIZE —
 * which all vfds are, because a vfd IS a small real fd number */
static int vselect(int nfds, fd_set *rs, fd_set *ws, fd_set *es,
                   int timeout_ms) {
    struct pollfd pfds[FD_SETSIZE];
    int np = 0;
    for (int fd = 0; fd < nfds && fd < FD_SETSIZE; fd++) {
        short ev = 0;
        if (rs && FD_ISSET(fd, rs)) ev |= POLLIN;
        if (ws && FD_ISSET(fd, ws)) ev |= POLLOUT;
        if (es && FD_ISSET(fd, es)) ev |= POLLPRI;
        if (ev) { pfds[np].fd = fd; pfds[np].events = ev; np++; }
    }
    int rc = vpoll(pfds, np, timeout_ms);
    if (rc < 0) return -1;
    if (rs) FD_ZERO(rs);
    if (ws) FD_ZERO(ws);
    if (es) FD_ZERO(es);
    int bits = 0;
    for (int i = 0; i < np; i++) {
        short rev = pfds[i].revents;
        if (rs && (rev & (POLLIN | POLLHUP | POLLERR | POLLRDHUP))) {
            FD_SET(pfds[i].fd, rs); bits++;
        }
        if (ws && (rev & (POLLOUT | POLLERR))) {
            FD_SET(pfds[i].fd, ws); bits++;
        }
    }
    return bits;
}

static int fdset_has_vfd(int nfds, fd_set *s) {
    if (!s) return 0;
    for (int fd = 0; fd < nfds && fd < FD_SETSIZE; fd++)
        if (FD_ISSET(fd, s) && is_vfd(fd)) return 1;
    return 0;
}

static int fdset_any(int nfds, fd_set *s) {
    if (!s) return 0;
    for (int fd = 0; fd < nfds && fd < FD_SETSIZE; fd++)
        if (FD_ISSET(fd, s)) return 1;
    return 0;
}

int select(int nfds, fd_set *rs, fd_set *ws, fd_set *es,
           struct timeval *tv) {
    shim_init();
    static int (*real_select)(int, fd_set *, fd_set *, fd_set *,
                              struct timeval *);
    if (!real_select) real_select = dlsym(RTLD_NEXT, "select");
    if (!active() || (!fdset_has_vfd(nfds, rs) &&
                      !fdset_has_vfd(nfds, ws) &&
                      !fdset_has_vfd(nfds, es))) {
        /* empty-set select with a timeout is the classic portable
         * sleep — advance SIM time like poll(NULL,0,ms) above; a NULL
         * tv (block forever) parks past any stop_time rather than
         * wedging the simulator in the real syscall. "Empty" means NO
         * bit set in any of the three sets, whatever nfds claims. */
        if (active() && !fdset_any(nfds, rs) && !fdset_any(nfds, ws) &&
            !fdset_any(nfds, es)) {
            if (!tv) {
                vsleep_ns((int64_t)1 << 62);
                return 0;
            }
            if (tv->tv_sec > 0 || tv->tv_usec > 0) {
                vsleep_ns((int64_t)tv->tv_sec * 1000000000LL +
                          (int64_t)tv->tv_usec * 1000);
                /* Linux select() writes back the remaining time; a
                 * full elapse leaves zero (retry loops depend on it) */
                tv->tv_sec = 0;
                tv->tv_usec = 0;
                return 0;
            }
        }
        return real_select(nfds, rs, ws, es, tv);
    }
    int ms = tv ? (int)(tv->tv_sec * 1000 +
                        (tv->tv_usec + 999) / 1000) : -1;
    return vselect(nfds, rs, ws, es, ms);
}

int pselect(int nfds, fd_set *rs, fd_set *ws, fd_set *es,
            const struct timespec *ts, const sigset_t *mask) {
    (void)mask;
    shim_init();
    static int (*real_ps)(int, fd_set *, fd_set *, fd_set *,
                          const struct timespec *, const sigset_t *);
    if (!real_ps) real_ps = dlsym(RTLD_NEXT, "pselect");
    if (!active() || (!fdset_has_vfd(nfds, rs) &&
                      !fdset_has_vfd(nfds, ws) &&
                      !fdset_has_vfd(nfds, es))) {
        /* pselect's timeout is const (Linux never modifies it) */
        if (active() && !fdset_any(nfds, rs) && !fdset_any(nfds, ws) &&
            !fdset_any(nfds, es)) {
            if (!ts) {
                vsleep_ns((int64_t)1 << 62);
                return 0;
            }
            if (ts->tv_sec > 0 || ts->tv_nsec > 0) {
                vsleep_ns((int64_t)ts->tv_sec * 1000000000LL +
                          (int64_t)ts->tv_nsec);
                return 0;
            }
        }
        return real_ps(nfds, rs, ws, es, ts, mask);
    }
    int ms = ts ? (int)(ts->tv_sec * 1000 +
                        (ts->tv_nsec + 999999) / 1000000) : -1;
    return vselect(nfds, rs, ws, es, ms);
}

/* --- time, sleep, entropy ---------------------------------------------- */

int clock_gettime(clockid_t clk, struct timespec *ts) {
    if (!active()) return real_clock_gettime(clk, ts);
    int64_t ns = call(OP_CLOCK, (int32_t)clk, 0, 0, NULL).r0;
    ts->tv_sec = ns / 1000000000LL;
    ts->tv_nsec = ns % 1000000000LL;
    return 0;
}

int gettimeofday(struct timeval *tv, void *tz) {
    (void)tz;
    if (!active()) {
        static int (*real_gtod)(struct timeval *, void *);
        if (!real_gtod) real_gtod = dlsym(RTLD_NEXT, "gettimeofday");
        return real_gtod(tv, tz);
    }
    if (tv) {
        int64_t ns = call(OP_CLOCK, CLOCK_REALTIME, 0, 0, NULL).r0;
        tv->tv_sec = ns / 1000000000LL;
        tv->tv_usec = (ns % 1000000000LL) / 1000;
    }
    return 0;
}

time_t time(time_t *tloc) {
    if (!active()) {
        static time_t (*real_time)(time_t *);
        if (!real_time) real_time = dlsym(RTLD_NEXT, "time");
        return real_time(tloc);
    }
    time_t t = (time_t)(call(OP_CLOCK, CLOCK_REALTIME, 0, 0, NULL).r0 /
                        1000000000LL);
    if (tloc) *tloc = t;
    return t;
}

/* sleeping advances SIMULATED time: the call parks until a sim-time
 * timer fires (reference process_emu_nanosleep, shd-process.c:3055 —
 * a real sleep would burn wallclock while sim time is frozen) */
static int vsleep_ns(int64_t ns) {
    if (ns <= 0) return 0;
    struct rsp r = call(OP_SLEEP, 0, ns, 0, NULL);
    return (int)r.r0;
}

int nanosleep(const struct timespec *req, struct timespec *rem) {
    if (!active()) {
        static int (*real_ns)(const struct timespec *, struct timespec *);
        if (!real_ns) real_ns = dlsym(RTLD_NEXT, "nanosleep");
        return real_ns(req, rem);
    }
    if (!req || req->tv_sec < 0 || req->tv_nsec < 0 ||
        req->tv_nsec > 999999999L) {
        errno = EINVAL;
        return -1;
    }
    int rc = vsleep_ns(req->tv_sec * 1000000000LL + req->tv_nsec);
    if (rem) { rem->tv_sec = 0; rem->tv_nsec = 0; }
    return rc;
}

int clock_nanosleep(clockid_t clk, int flags, const struct timespec *req,
                    struct timespec *rem) {
    if (!active()) {
        static int (*real_cns)(clockid_t, int, const struct timespec *,
                               struct timespec *);
        if (!real_cns) real_cns = dlsym(RTLD_NEXT, "clock_nanosleep");
        return real_cns(clk, flags, req, rem);
    }
    if (flags & TIMER_ABSTIME) {
        struct timespec now;
        clock_gettime(clk, &now);
        int64_t d = (req->tv_sec - now.tv_sec) * 1000000000LL +
                    (req->tv_nsec - now.tv_nsec);
        vsleep_ns(d);
        return 0;
    }
    return nanosleep(req, rem) ? errno : 0;
}

int usleep(useconds_t us) {
    if (!active()) {
        static int (*real_us)(useconds_t);
        if (!real_us) real_us = dlsym(RTLD_NEXT, "usleep");
        return real_us(us);
    }
    return vsleep_ns((int64_t)us * 1000);
}

unsigned int sleep(unsigned int seconds) {
    if (!active()) {
        static unsigned int (*real_sleep)(unsigned int);
        if (!real_sleep) real_sleep = dlsym(RTLD_NEXT, "sleep");
        return real_sleep(seconds);
    }
    vsleep_ns((int64_t)seconds * 1000000000LL);
    return 0;
}

/* entropy from the host's deterministic PRNG (reference shd-host.c:574
 * random source; determinism dual-run test shd-test-determinism.c) */
ssize_t getrandom(void *buf, size_t n, unsigned int flags) {
    (void)flags;
    if (!active()) {
        static ssize_t (*real_gr)(void *, size_t, unsigned int);
        if (!real_gr) real_gr = dlsym(RTLD_NEXT, "getrandom");
        if (real_gr) return real_gr(buf, n, flags);
        errno = ENOSYS;
        return -1;
    }
    return random_fill(buf, n);
}

int getentropy(void *buf, size_t n) {
    if (!active()) {
        static int (*real_ge)(void *, size_t);
        if (!real_ge) real_ge = dlsym(RTLD_NEXT, "getentropy");
        if (real_ge) return real_ge(buf, n);
        errno = ENOSYS;
        return -1;
    }
    if (n > 256) { errno = EIO; return -1; }
    return random_fill(buf, n) == (ssize_t)n ? 0 : -1;
}

static int is_random_path(const char *path) {
    return path && (!strcmp(path, "/dev/random") ||
                    !strcmp(path, "/dev/urandom") ||
                    !strcmp(path, "/dev/srandom"));
}

int open(const char *path, int flags, ...) {
    va_list ap;
    va_start(ap, flags);
    mode_t mode = va_arg(ap, mode_t);
    va_end(ap);
    shim_init();
    if (active() && is_random_path(path)) {
        int fd = vfd_reserve();
        if (fd >= 0) vstate[fd] |= VS_RANDOM;
        return fd;
    }
    return real_open(path, flags, mode);
}

int open64(const char *path, int flags, ...) {
    va_list ap;
    va_start(ap, flags);
    mode_t mode = va_arg(ap, mode_t);
    va_end(ap);
    shim_init();
    if (active() && is_random_path(path)) {
        int fd = vfd_reserve();
        if (fd >= 0) vstate[fd] |= VS_RANDOM;
        return fd;
    }
    static int (*real_open64)(const char *, int, ...);
    if (!real_open64) real_open64 = dlsym(RTLD_NEXT, "open64");
    return real_open64(path, flags, mode);
}

int openat(int dirfd, const char *path, int flags, ...) {
    va_list ap;
    va_start(ap, flags);
    mode_t mode = va_arg(ap, mode_t);
    va_end(ap);
    shim_init();
    if (active() && is_random_path(path)) {
        int fd = vfd_reserve();
        if (fd >= 0) vstate[fd] |= VS_RANDOM;
        return fd;
    }
    static int (*real_openat)(int, const char *, int, ...);
    if (!real_openat) real_openat = dlsym(RTLD_NEXT, "openat");
    return real_openat(dirfd, path, flags, mode);
}

/* --- fopen entropy (ADVICE r5): glibc's fopen calls an INTERNAL open,
 * so the open/open64/openat interposition above never sees
 * fopen("/dev/urandom") and the stream would read real kernel entropy
 * — breaking the determinism guarantee for stdio-based readers. Back
 * the stream with fopencookie over random_fill instead (the reference
 * interposes fopen/fopen64 for the same reason, shd-interposer.c). */

static ssize_t random_cookie_read(void *cookie, char *buf, size_t n) {
    (void)cookie;
    return random_fill(buf, n);
}

static FILE *random_stream(void) {
    cookie_io_functions_t io = {0};
    io.read = random_cookie_read;
    FILE *f = fopencookie(NULL, "r", io);
    /* unbuffered: stdio readahead would pull KBs per small fread,
     * consuming a different amount of the host PRNG stream than the
     * getrandom/open paths do for the same app behavior */
    if (f) setvbuf(f, NULL, _IONBF, 0);
    return f;
}

FILE *fopen(const char *path, const char *mode) {
    shim_init();
    static FILE *(*real_fopen)(const char *, const char *);
    if (!real_fopen) real_fopen = dlsym(RTLD_NEXT, "fopen");
    if (active() && is_random_path(path)) return random_stream();
    return real_fopen(path, mode);
}

FILE *fopen64(const char *path, const char *mode) {
    shim_init();
    static FILE *(*real_fopen64)(const char *, const char *);
    if (!real_fopen64) real_fopen64 = dlsym(RTLD_NEXT, "fopen64");
    if (active() && is_random_path(path)) return random_stream();
    return real_fopen64(path, mode);
}

/* --- process creation: REFUSED (reference shd-process.c:3195-3234).
 * A forked/exec'd child would share the control channel fd with no
 * protocol identity of its own, make raw libc calls outside the sim,
 * and escape the clock/entropy/network virtualization entirely — the
 * classic sandbox escape. Refuse LOUDLY: errno = ENOSYS, a stderr
 * diagnostic, and an OP_VIOLATION record so the simulator's exit
 * report names the attempt (hosting.shim). Only PLT calls interpose —
 * a static binary or an internal glibc clone bypasses this, like
 * every LD_PRELOAD scheme. */

static int refuse(const char *what) {
    shim_init();
    fprintf(stderr, "shadow-shim: %s refused — hosted processes "
            "cannot fork/exec inside the simulation\n", what);
    if (active()) call(OP_VIOLATION, 0, 0, 0, what);
    errno = ENOSYS;
    return -1;
}

pid_t fork(void) {
    if (!active()) {
        static pid_t (*real_fork)(void);
        if (!real_fork) real_fork = dlsym(RTLD_NEXT, "fork");
        return real_fork();
    }
    return (pid_t)refuse("fork");
}

pid_t vfork(void) {
    if (!active()) {
        static pid_t (*real_vfork)(void);
        if (!real_vfork) real_vfork = dlsym(RTLD_NEXT, "vfork");
        return real_vfork();
    }
    return (pid_t)refuse("vfork");
}

int execve(const char *p, char *const a[], char *const e[]) {
    if (!active()) {
        static int (*real_ev)(const char *, char *const[],
                              char *const[]);
        if (!real_ev) real_ev = dlsym(RTLD_NEXT, "execve");
        return real_ev(p, a, e);
    }
    return refuse("execve");
}

int execv(const char *p, char *const a[]) {
    if (!active()) {
        static int (*real_v)(const char *, char *const[]);
        if (!real_v) real_v = dlsym(RTLD_NEXT, "execv");
        return real_v(p, a);
    }
    return refuse("execv");
}

int execvp(const char *p, char *const a[]) {
    if (!active()) {
        static int (*real_vp)(const char *, char *const[]);
        if (!real_vp) real_vp = dlsym(RTLD_NEXT, "execvp");
        return real_vp(p, a);
    }
    return refuse("execvp");
}

int execvpe(const char *p, char *const a[], char *const e[]) {
    if (!active()) {
        static int (*real_vpe)(const char *, char *const[],
                               char *const[]);
        if (!real_vpe) real_vpe = dlsym(RTLD_NEXT, "execvpe");
        return real_vpe(p, a, e);
    }
    return refuse("execvpe");
}

int fexecve(int fd, char *const a[], char *const e[]) {
    if (!active()) {
        static int (*real_fe)(int, char *const[], char *const[]);
        if (!real_fe) real_fe = dlsym(RTLD_NEXT, "fexecve");
        return real_fe(fd, a, e);
    }
    return refuse("fexecve");
}

/* variadic execl family: a faithful passthrough would need to rebuild
 * the argv — refuse unconditionally under the sim, and rebuild is
 * unnecessary outside it because the shim only loads via the
 * simulator's LD_PRELOAD (active() is the only supported state). */
int execl(const char *p, const char *arg, ...) {
    (void)p; (void)arg;
    return refuse("execl");
}

int execlp(const char *p, const char *arg, ...) {
    (void)p; (void)arg;
    return refuse("execlp");
}

int execle(const char *p, const char *arg, ...) {
    (void)p; (void)arg;
    return refuse("execle");
}

int posix_spawn(pid_t *pid, const char *path, const void *fa,
                const void *attr, char *const argv[],
                char *const envp[]) {
    if (!active()) {
        static int (*real_ps)(pid_t *, const char *, const void *,
                              const void *, char *const[],
                              char *const[]);
        if (!real_ps) real_ps = dlsym(RTLD_NEXT, "posix_spawn");
        return real_ps(pid, path, fa, attr, argv, envp);
    }
    refuse("posix_spawn");
    return ENOSYS;   /* posix_spawn returns the errno, not -1 */
}

int posix_spawnp(pid_t *pid, const char *file, const void *fa,
                 const void *attr, char *const argv[],
                 char *const envp[]) {
    if (!active()) {
        static int (*real_psp)(pid_t *, const char *, const void *,
                               const void *, char *const[],
                               char *const[]);
        if (!real_psp) real_psp = dlsym(RTLD_NEXT, "posix_spawnp");
        return real_psp(pid, file, fa, attr, argv, envp);
    }
    refuse("posix_spawnp");
    return ENOSYS;
}

int system(const char *cmd) {
    if (!active()) {
        static int (*real_system)(const char *);
        if (!real_system) real_system = dlsym(RTLD_NEXT, "system");
        return real_system(cmd);
    }
    if (!cmd) return 0;   /* POSIX: NULL asks "is a shell available" */
    return refuse("system");
}

FILE *popen(const char *cmd, const char *mode) {
    if (!active()) {
        static FILE *(*real_popen)(const char *, const char *);
        if (!real_popen) real_popen = dlsym(RTLD_NEXT, "popen");
        return real_popen(cmd, mode);
    }
    refuse("popen");
    return NULL;
}

/* --- threads: fail LOUDLY until multi-threaded hosting exists ---------- */

int pthread_create(pthread_t *thread, const pthread_attr_t *attr,
                   void *(*start)(void *), void *arg) {
    shim_init();
    if (!active()) {
        static int (*real_pc)(pthread_t *, const pthread_attr_t *,
                              void *(*)(void *), void *);
        if (!real_pc) real_pc = dlsym(RTLD_NEXT, "pthread_create");
        return real_pc(thread, attr, start, arg);
    }
    (void)thread; (void)attr; (void)start; (void)arg;
    /* A silently-real thread would make raw libc calls outside the
     * lockstep channel protocol and corrupt sim semantics — refuse
     * visibly instead (the reference runs threads as rpth green
     * threads, shd-process.c:5074-7449; not implemented here). */
    fprintf(stderr, "shadow-shim: pthread_create refused — "
            "multi-threaded hosted processes are not supported\n");
    return EAGAIN;
}

/* --- name service & identity ------------------------------------------- */

int getaddrinfo(const char *node, const char *service,
                const struct addrinfo *hints, struct addrinfo **res) {
    if (!active()) return real_getaddrinfo(node, service, hints, res);
    struct rsp r = call(OP_RESOLVE, 0, 0, 0, node);
    if (r.r0 < 0) return EAI_NONAME;
    struct addrinfo *ai = calloc(1, sizeof *ai);
    struct sockaddr_in *sa = calloc(1, sizeof *sa);
    sa->sin_family = AF_INET;
    sa->sin_addr.s_addr = (uint32_t)r.r0;   /* virtual host id */
    sa->sin_port = service ? htons((uint16_t)atoi(service)) : 0;
    ai->ai_family = AF_INET;
    ai->ai_socktype = hints ? hints->ai_socktype : SOCK_STREAM;
    ai->ai_addrlen = sizeof *sa;
    ai->ai_addr = (struct sockaddr *)sa;
    *res = ai;
    return 0;
}

void freeaddrinfo(struct addrinfo *res) {
    /* frees only what our getaddrinfo allocated; pass through others */
    if (!active()) {
        void (*real_fai)(struct addrinfo *) =
            dlsym(RTLD_NEXT, "freeaddrinfo");
        real_fai(res);
        return;
    }
    if (res) { free(res->ai_addr); free(res); }
}

/* the real simulated identity (round 5; was fixed zeros): servers
 * that bind port 0 / learn their port via getsockname, and apps that
 * key peers by getpeername, see true virtual addresses */
static int vgetname(int fd, struct sockaddr *addr, socklen_t *len,
                    int which) {
    struct rsp r = call(OP_GETNAME, fd, which, 0, NULL);
    if (r.r0 < 0) { errno = (int)r.r1 ? (int)r.r1 : ENOTCONN; return -1; }
    if (addr && len && *len >= sizeof(struct sockaddr_in)) {
        struct sockaddr_in *a = (struct sockaddr_in *)addr;
        memset(a, 0, sizeof *a);
        a->sin_family = AF_INET;
        a->sin_addr.s_addr = (uint32_t)r.r1;
        a->sin_port = htons((uint16_t)r.r2);
        *len = sizeof *a;
    }
    return 0;
}

int getsockname(int fd, struct sockaddr *addr, socklen_t *len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_gsn)(int, struct sockaddr *, socklen_t *);
        if (!real_gsn) real_gsn = dlsym(RTLD_NEXT, "getsockname");
        return real_gsn(fd, addr, len);
    }
    return vgetname(fd, addr, len, 0);
}

int getpeername(int fd, struct sockaddr *addr, socklen_t *len) {
    if (!active() || !is_vfd(fd)) {
        static int (*real_gpn)(int, struct sockaddr *, socklen_t *);
        if (!real_gpn) real_gpn = dlsym(RTLD_NEXT, "getpeername");
        return real_gpn(fd, addr, len);
    }
    return vgetname(fd, addr, len, 1);
}

/* harmless accepted no-ops on virtual fds */
int setsockopt(int fd, int level, int optname, const void *optval,
               socklen_t optlen) {
    if (active() && is_vfd(fd)) return 0;
    static int (*real_sso)(int, int, int, const void *, socklen_t);
    if (!real_sso) real_sso = dlsym(RTLD_NEXT, "setsockopt");
    return real_sso(fd, level, optname, optval, optlen);
}

int getsockopt(int fd, int level, int optname, void *optval,
               socklen_t *optlen) {
    if (active() && is_vfd(fd)) {
        /* SO_ERROR after EPOLLOUT: connection is established */
        if (optval && optlen && *optlen >= sizeof(int))
            *(int *)optval = 0;
        return 0;
    }
    static int (*real_gso)(int, int, int, void *, socklen_t *);
    if (!real_gso) real_gso = dlsym(RTLD_NEXT, "getsockopt");
    return real_gso(fd, level, optname, optval, optlen);
}

int ioctl(int fd, unsigned long req, ...) {
    __builtin_va_list ap;
    __builtin_va_start(ap, req);
    void *argp = __builtin_va_arg(ap, void *);
    __builtin_va_end(ap);
    if (active() && is_vfd(fd)) {
        /* FIONBIO is how CPython's internal_setblocking toggles
         * blocking mode on Linux — without this, s.setblocking(False)
         * or any socket timeout in a hosted python script would hit
         * the real kernel with a virtual fd's placeholder */
        if (req == FIONBIO && argp) {
            vfd_set_nb(fd, *(int *)argp != 0);
            return 0;
        }
        return 0;                       /* FIONREAD etc: accepted */
    }
    static int (*real_ioctl)(int, unsigned long, ...);
    if (!real_ioctl) real_ioctl = dlsym(RTLD_NEXT, "ioctl");
    return real_ioctl(fd, req, argp);
}

int fcntl(int fd, int cmd, ...) {
    __builtin_va_list ap;
    __builtin_va_start(ap, cmd);
    long arg = __builtin_va_arg(ap, long);
    __builtin_va_end(ap);
    if (active() && is_vfd(fd)) {
        if (cmd == F_SETFL) { vfd_set_nb(fd, arg & O_NONBLOCK); return 0; }
        if (cmd == F_GETFL) return vfd_nb(fd) ? O_NONBLOCK : 0;
        return 0;                        /* F_SETFD etc: accepted */
    }
    static int (*real_fcntl)(int, int, ...);
    if (!real_fcntl) real_fcntl = dlsym(RTLD_NEXT, "fcntl");
    return real_fcntl(fd, cmd, arg);
}
