"""obs subpackage."""
