"""obs subpackage: trace (span timeline), metrics (registry),
perf (phase attribution), ledger (durable perf trajectory),
tracker (heartbeats), pcap (capture), logger (text log) — see
README.md in this directory for roles, usage and overhead notes."""
