"""obs subpackage: trace (span timeline), metrics (registry),
tracker (heartbeats), pcap (capture), logger (text log) — see
README.md in this directory for roles, usage and overhead notes."""
