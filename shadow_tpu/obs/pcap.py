"""Pcap capture: drain device trace rings into standard pcap files.

The reference writes one pcap per network interface when a host sets
``logpcap`` (/root/reference/src/main/host/shd-network-interface.c:
186-223, utility/shd-pcap-writer.c). Here packets are recorded into a
device-side ring at the window exchange (engine.window._trace_append)
and drained per chunk; this module synthesizes Ethernet/IPv4/TCP|UDP
headers around the modeled byte counts (payloads are not materialized —
captured frames declare the true original length with a header-only
snaplen, which wireshark/tcpdump handle as truncated captures).

Limitations vs the reference: loopback traffic is not traced (it never
crosses the exchange), and capture timestamps are wire-entry (tx) and
arrival (rx) times rather than qdisc-internal times.
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..net import packet as P
from . import metrics as _MT

_GLOBAL_HDR = struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 65535, 1)

HEADER_BYTES = {P.PROTO_TCP: 14 + 20 + 20, P.PROTO_UDP: 14 + 20 + 8}


def _mac(hid: int) -> bytes:
    return bytes([0x02, 0, (hid >> 24) & 0xFF, (hid >> 16) & 0xFF,
                  (hid >> 8) & 0xFF, hid & 0xFF])


def _frame(pkt_words, host_ips) -> bytes:
    """Synthesize the packet headers for one trace record."""
    src, dst = int(pkt_words[P.SRC]), int(pkt_words[P.DST])
    proto = int(pkt_words[P.FLAGS]) & P.PROTO_MASK
    ln = int(pkt_words[P.LEN])
    sip = int(host_ips[src]) if 0 <= src < len(host_ips) else 0
    dip = int(host_ips[dst]) if 0 <= dst < len(host_ips) else 0

    eth = _mac(dst) + _mac(src) + b"\x08\x00"
    if proto == P.PROTO_TCP:
        l4len = 20 + ln
        flags = 0x10  # ACK
        w = int(pkt_words[P.FLAGS])
        if w & P.F_SYN:
            flags |= 0x02
        if w & P.F_FIN:
            flags |= 0x01
        if w & P.F_RST:
            flags |= 0x04
        l4 = struct.pack(
            ">HHIIBBHHH",
            int(pkt_words[P.SPORT]) & 0xFFFF,
            int(pkt_words[P.DPORT]) & 0xFFFF,
            int(pkt_words[P.SEQ]) & 0xFFFFFFFF,
            int(pkt_words[P.ACK]) & 0xFFFFFFFF,
            5 << 4, flags,
            int(pkt_words[P.WND]) & 0xFFFF, 0, 0)
        ipproto = 6
    else:
        l4len = 8 + ln
        l4 = struct.pack(">HHHH",
                         int(pkt_words[P.SPORT]) & 0xFFFF,
                         int(pkt_words[P.DPORT]) & 0xFFFF,
                         l4len & 0xFFFF, 0)
        ipproto = 17
    ip = struct.pack(">BBHHHBBHII", 0x45, 0, 20 + l4len, 0, 0, 64,
                     ipproto, 0, sip, dip)
    return eth + ip + l4, 14 + 20 + l4len


class PcapWriter:
    """One capture session: a file per traced host ("<name>-eth0.pcap"),
    fed by drain() after each window chunk."""

    def __init__(self, directory: str, host_names, host_ips,
                 pcap_hosts):
        os.makedirs(directory, exist_ok=True)
        self.host_ips = np.asarray(host_ips, dtype=np.int64)
        self.files = {}
        for hid in pcap_hosts:
            path = os.path.join(directory,
                                f"{host_names[hid]}-eth0.pcap")
            f = open(path, "wb")
            f.write(_GLOBAL_HDR)
            self.files[hid] = f

    def drain(self, tr_time, tr_pkt, tr_cnt):
        """Write each traced host's ring records (chronological)."""
        tr_time = np.asarray(tr_time)
        tr_pkt = np.asarray(tr_pkt)
        tr_cnt = np.asarray(tr_cnt)
        written = 0
        for hid, f in self.files.items():
            n = int(tr_cnt[hid])
            if not n:
                continue
            written += n
            order = np.argsort(tr_time[hid, :n], kind="stable")
            for i in order:
                t = int(tr_time[hid, i])
                frame, orig_len = _frame(tr_pkt[hid, i], self.host_ips)
                f.write(struct.pack("<IIII", t // 10**9,
                                    (t % 10**9) // 1000,
                                    len(frame), orig_len))
                f.write(frame)
        if written and _MT.ENABLED:
            _MT.REGISTRY.counter("pcap.records").inc(written)

    def close(self):
        for f in self.files.values():
            f.close()
        self.files = {}
