"""Metrics registry: counters, gauges and histograms with JSON export.

The reference's quantitative self-reporting is scattered — tracker
heartbeats (shd-tracker.c:405-592), the slave getrusage summary
(shd-slave.c:374-395), the ObjectCounter shutdown report
(shd-slave.c:207-211). Here all of it funnels through ONE registry so
the CLI, the tracker, bench.py and tests read the same numbers:

- counters   monotonically increasing event counts (windows run, shim
  ops served, tracker lines emitted, pcap records written);
- gauges     last-value samples (current sim time, summary figures);
- histograms value distributions with fixed bucket bounds (shim
  per-op latency).

Export surfaces:

- ``Registry.chunk(**fields)`` appends one JSON line per window chunk
  to ``<metrics>.chunks.jsonl`` (streamed, so a crashed run keeps its
  lines) and retains it in memory for tests;
- ``Registry.snapshot()`` is the final ``metrics.json`` document —
  shaped to diff against the BENCH_*.json rounds: the ``sim`` section
  carries SimReport.summary() figures (events/sec, wall per
  sim-second, speedup) published via ``publish("sim", ...)``, and the
  ``shim`` section aggregates per-op counts and latency histograms.

Cheap when disabled: ``ENABLED`` is a module boolean; hot paths guard
with ``if metrics.ENABLED:`` and pay one boolean check (the same
contract as obs.trace). Metric objects expose plain attributes
(`Counter.n`) so the enabled-path cost is one dict lookup + one add.
"""

from __future__ import annotations

import json
from bisect import bisect_left

ENABLED = False
REGISTRY = None

# default histogram bounds: log-ish µs ladder wide enough for both a
# ~2 µs clock op and a multi-second blocking wait
DEFAULT_BOUNDS = (1, 2, 5, 10, 20, 50, 100, 200, 500,
                  1_000, 2_000, 5_000, 10_000, 50_000, 100_000,
                  1_000_000, 10_000_000)


class Counter:
    __slots__ = ("n",)

    def __init__(self):
        self.n = 0

    def inc(self, k: int = 1):
        self.n += k


class Gauge:
    __slots__ = ("v",)

    def __init__(self):
        self.v = 0.0

    def set(self, v):
        self.v = v


class Histogram:
    """Fixed-bound histogram: observe() bisects into len(bounds)+1
    buckets (the last is the overflow bucket)."""

    __slots__ = ("bounds", "buckets", "count", "total", "vmin", "vmax")

    def __init__(self, bounds=DEFAULT_BOUNDS):
        self.bounds = tuple(bounds)
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None

    def observe(self, v):
        self.buckets[bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.total += v
        if self.vmin is None or v < self.vmin:
            self.vmin = v
        if self.vmax is None or v > self.vmax:
            self.vmax = v

    def snapshot(self) -> dict:
        out = {"count": self.count, "sum": self.total,
               "min": self.vmin, "max": self.vmax,
               "mean": (self.total / self.count) if self.count else None,
               "buckets": {}}
        for le, n in zip(self.bounds, self.buckets):
            if n:
                out["buckets"][f"le_{le}"] = n
        if self.buckets[-1]:
            out["buckets"]["overflow"] = self.buckets[-1]
        return out


class Registry:
    """Get-or-create metric store + export. `path` is the final
    snapshot file, `jsonl_path` the per-chunk line stream; either may
    be None (collect only — non-writer processes of a multi-process
    mesh, or in-memory test use)."""

    def __init__(self, path: str = None, jsonl_path: str = None):
        self.path = path
        self.jsonl_path = jsonl_path
        self._jsonl = None           # opened on first chunk line
        self.counters = {}
        self.gauges = {}
        self.histograms = {}
        self.chunks = []             # retained per-chunk lines (tests)
        # outer harnesses timing several runs into one registry (e.g.
        # bench.py's config matrix) set this so interleaved chunk
        # lines stay attributable to their run
        self.label = None

    # --- get-or-create accessors (hot path: one dict hit) ---
    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter()
        return c

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge()
        return g

    def histogram(self, name: str, bounds=DEFAULT_BOUNDS) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(bounds)
        return h

    # --- export ---
    def publish(self, prefix: str, mapping: dict):
        """Expose every numeric value of `mapping` as a gauge named
        ``<prefix>.<key>`` — how SimReport.summary() becomes the
        registry's ``sim`` section (one source of truth for the CLI,
        tracker and bench)."""
        for k, v in mapping.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self.gauge(f"{prefix}.{k}").set(v)

    def chunk(self, **fields):
        """One per-window-chunk JSON line (engine.sim's chunk loop)."""
        if self.label is not None:
            fields = {"run": self.label, **fields}
        self.chunks.append(fields)
        if self.jsonl_path is not None:
            if self._jsonl is None:
                self._jsonl = open(self.jsonl_path, "w")
            self._jsonl.write(json.dumps(fields) + "\n")
            self._jsonl.flush()

    def snapshot(self) -> dict:
        counters = {k: c.n for k, c in sorted(self.counters.items())}
        gauges = {k: g.v for k, g in sorted(self.gauges.items())}
        hists = {k: h.snapshot()
                 for k, h in sorted(self.histograms.items())}
        # convenience views shaped for diffing against BENCH_*.json:
        # the published summary and the shim per-op aggregation
        sim = {k[len("sim."):]: v for k, v in gauges.items()
               if k.startswith("sim.")}
        ops = {k[len("shim.op."):]: v for k, v in counters.items()
               if k.startswith("shim.op.")}
        lat = {k[len("shim.op_us."):]: v for k, v in hists.items()
               if k.startswith("shim.op_us.")}
        # robustness views: the supervision layer's child-exit /
        # violation counters (hosting.shim) and the applied-fault
        # counts per kind (engine.faults) — shaped for diffing like
        # the shim section, present only when nonzero
        superv = {k[len("shim."):]: v for k, v in counters.items()
                  if k in ("shim.child_exits", "shim.supervisor_kills",
                           "shim.violations")}
        faults = {k[len("fault."):]: v for k, v in counters.items()
                  if k.startswith("fault.")}
        # perf views (engine.sim / obs.perf): the per-shard load tables
        # + imbalance gauge of a mesh run, and the per-phase wall
        # attribution of a --perf run — both assembled from their
        # gauge families so metrics.json shows them as sections
        shards = _assemble_indexed(
            {k[len("shard."):]: v for k, v in gauges.items()
             if k.startswith("shard.")})
        perf = {k[len("perf."):]: v for k, v in gauges.items()
                if k.startswith("perf.")}
        # memory view (obs.memscope): device-buffer watermark +
        # per-host state census + the captured XLA cost/memory
        # analysis of the compiled programs — assembled like the perf
        # section, with the per-device peaks folded into a list (the
        # per-shard watermark of a mesh run)
        memory = _assemble_indexed(
            {k[len("mem."):]: v for k, v in gauges.items()
             if k.startswith("mem.")})
        xla_cost = {k[len("cost."):]: v for k, v in gauges.items()
                    if k.startswith("cost.")}
        if xla_cost:
            memory["cost"] = xla_cost
        # network view (obs.netscope): per-kind sample counts, exact
        # percentile read-outs and the non-zero histogram buckets
        # (``<kind>.bucket.<i>`` families fold into per-index lists,
        # missing indices None = empty bucket) — assembled like the
        # perf/memory sections
        net = _assemble_indexed(
            {k[len("net."):]: v for k, v in gauges.items()
             if k.startswith("net.")})
        # occupancy view (obs.passcope): lockstep lane utilization /
        # waste with the per-rung gauge families folded like shards,
        # and the device pass table of a --passcope run — assembled
        # from their occupancy.* / passcope.* gauges
        occupancy = _assemble_indexed(
            {k[len("occupancy."):]: v for k, v in gauges.items()
             if k.startswith("occupancy.")})
        device_phases = {k[len("passcope."):]: v
                         for k, v in gauges.items()
                         if k.startswith("passcope.")}
        # fleet view (shadow_tpu.fleet scheduler): queue depth by
        # state plus lifetime start/retry/preempt/watchdog counters —
        # the sweep-health section of a ``fleet run --metrics`` file
        fleet = {k[len("fleet."):]: v
                 for src in (gauges, counters)
                 for k, v in src.items() if k.startswith("fleet.")}
        out = {"sim": sim,
               "shim": {"ops": ops, "op_latency_us": lat},
               "counters": counters, "gauges": gauges,
               "histograms": hists, "chunks": len(self.chunks)}
        if superv:
            out["shim"]["supervision"] = superv
        if faults:
            out["faults"] = faults
        if shards:
            out["shards"] = shards
        if perf:
            out["perf"] = perf
        if memory:
            out["memory"] = memory
        if net:
            out["net"] = net
        if occupancy:
            out["occupancy"] = occupancy
        if device_phases:
            out["device_phases"] = device_phases
        if fleet:
            out["fleet"] = fleet
        return out

    def close(self):
        """Write the final snapshot (if a path was given) and release
        the chunk stream."""
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self.path is not None:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.snapshot(), f, indent=1)
            import os
            os.replace(tmp, self.path)


def _assemble_indexed(flat: dict) -> dict:
    """Fold ``<name>.<int>`` gauge families into per-index lists:
    ``{"events.0": 5, "events.1": 7, "imbalance": 1.2}`` becomes
    ``{"events": [5, 7], "imbalance": 1.2}`` — how the per-shard
    gauges (engine.sim's mesh-run publishing) become the snapshot's
    ``shards`` section. Missing indices read as None (a shard that
    never reported)."""
    series, scalars = {}, {}
    for k, v in flat.items():
        base, _, idx = k.rpartition(".")
        if base and idx.isdigit():
            series.setdefault(base, {})[int(idx)] = v
        else:
            scalars[k] = v
    out = dict(scalars)
    for base, vals in series.items():
        n = max(vals) + 1
        out[base] = [vals.get(i) for i in range(n)]
    return out


def install(path: str = None, jsonl_path: str = None) -> Registry:
    """Enable metrics process-wide; the installer owns finish()."""
    global ENABLED, REGISTRY
    REGISTRY = Registry(path=path, jsonl_path=jsonl_path)
    ENABLED = True
    return REGISTRY


def finish() -> Registry | None:
    """Disable metrics, write the snapshot, return the registry."""
    global ENABLED, REGISTRY
    reg, REGISTRY, ENABLED = REGISTRY, None, False
    if reg is not None:
        reg.close()
    return reg


# shim protocol helper (hosting.shim._service): one counter + one
# latency histogram per op name, behind the caller's ENABLED guard
def shim_op(op_name: str, dt_ns: int):
    r = REGISTRY
    r.counter("shim.op." + op_name).inc()
    r.histogram("shim.op_us." + op_name).observe(dt_ns / 1000.0)
