"""Durable perf ledger: an append-only JSONL trajectory of measured
throughput, keyed by scenario x config-fingerprint x platform x git
rev.

The round-5 verdict's finding was not that perf regressed — it was
that perf regressed TWO ROUNDS EARLIER and nothing noticed: bench
numbers lived in per-round BENCH_r{N}.json artifacts nobody diffed
mechanically. The ledger is the durable, machine-checkable record:
every bench line, every ``--perf`` run and every A/B variant appends
one line here, and ``tools/perf_regress.py`` compares the newest
entry of each (scenario, platform, fingerprint) group against its
own history with a noise band — so "phold fell 83k -> 34k" becomes
an exit-1 event in the round it happens, not an archaeology finding
two rounds later.

Keying rules (docs/performance.md):

- entries are only ever compared within the same ``platform``
  (``jax.default_backend()``): this repo's dev container is CPU-only
  while the bench box has the accelerator, and a cross-platform
  "regression" is noise by construction (BASELINE.md protocol);
- ``fingerprint`` hashes the engine config + scenario shape, so a
  deliberate config change starts a NEW trajectory instead of
  tripping the gate;
- ``git_rev`` is recorded for audit, never used for grouping.

The file format is one JSON object per line, append-only (the same
crash-tolerant shape as the digest chain and metrics chunk stream: a
torn final line is detectable and skippable). Default location:
``perf/ledger.jsonl`` at the repo root, committed so the trajectory
survives across rounds; ``SHADOW_TPU_LEDGER`` overrides the path
(set it to ``off`` to disable appends entirely).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import time

FORMAT = "shadow_tpu.perf.ledger"
VERSION = 1

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def default_path() -> str | None:
    """The ledger path appends resolve to: SHADOW_TPU_LEDGER if set
    (the literal ``off``/``0``/empty disables appends -> None), else
    ``perf/ledger.jsonl`` at the repo root."""
    env = os.environ.get("SHADOW_TPU_LEDGER")
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "none"):
            return None
        return env
    return os.path.join(_REPO_ROOT, "perf", "ledger.jsonl")


def fingerprint_of(cfg=None, **extra) -> str:
    """Stable 16-hex fingerprint of an EngineConfig (or any dict) plus
    keyword extras (seed, runahead, scenario knobs...) — the ledger's
    "same config" key. Key order never matters; any value change
    changes the fingerprint."""
    d = {}
    if cfg is not None:
        d["cfg"] = (dataclasses.asdict(cfg)
                    if dataclasses.is_dataclass(cfg) else dict(cfg))
    if extra:
        d["extra"] = extra
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def git_rev() -> str | None:
    import subprocess
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5, cwd=_REPO_ROOT)
        rev = out.stdout.strip()
        return rev or None
    except Exception:
        return None


def config_extras(cfg) -> dict | None:
    """The config facts a trajectory reader needs to attribute a rate
    move WITHOUT re-deriving the fingerprint: the drain's hot-column
    count (the level-2 hot/cold split working set for this config),
    the event batch width and the split switch. Recorded verbatim in
    the entry (never part of the fingerprint — the full cfg already
    is), so a ledger delta is attributable to the split rather than
    just the git rev."""
    if cfg is None or not dataclasses.is_dataclass(cfg):
        return None
    try:
        from ..engine.state import hot_fields
        return {"hot_columns": len(hot_fields(cfg)),
                "event_batch": cfg.event_batch,
                "hot_split": cfg.hot_split}
    except Exception:  # pragma: no cover — old/partial cfg shapes
        return None


def make_entry(scenario: str, fingerprint: str, platform: str,
               summary: dict, cost: dict = None, phases: dict = None,
               attributed_frac: float = None, note: str = None,
               rep_rates=None, rep_spread=None, cold_wall=None,
               warm_wall=None, cfg=None) -> dict:
    """One ledger line from a run's summary (SimReport.summary()) and
    cost model (SimReport.cost_model()). `phases` is the per-phase
    wall map from obs.perf (``{phase: wall_s}``); `cfg` (the
    EngineConfig the fingerprint hashed) additionally stamps the
    attribution extras (config_extras)."""
    warm_eps = None
    if warm_wall and summary.get("events"):
        # warm throughput excludes the cold compile — the number the
        # regression gate prefers (compile time varies with cache
        # state; steady-state throughput is the real trajectory)
        warm_eps = round(summary["events"] / warm_wall, 1)
    e = {
        "format": FORMAT, "version": VERSION,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "scenario": scenario,
        "fingerprint": fingerprint,
        "platform": platform,
        "git_rev": git_rev(),
        "events": int(summary.get("events", 0)),
        "sim_seconds": summary.get("sim_seconds"),
        "windows": summary.get("windows"),
        "wall_seconds": round(summary.get("wall_seconds", 0.0), 3),
        "cold_wall": cold_wall,
        "warm_wall": warm_wall,
        "events_per_sec": round(summary.get("events_per_sec", 0.0), 1),
        "warm_events_per_sec": warm_eps,
    }
    # memory observatory fields (obs.memscope, docs/observability.md):
    # the run's device-buffer watermark and per-host state bytes —
    # mem_peak_bytes is what tools/perf_regress.py's memory gate
    # compares against the trajectory's own history (a run whose peak
    # GROWS past the band is a regression like a rate drop is).
    # Present only when the run carried the observatory record, so
    # pre-PR-15 trajectories stay untouched.
    if summary.get("mem_peak_bytes"):
        e["mem_peak_bytes"] = int(summary["mem_peak_bytes"])
        if summary.get("mem_source"):
            e["mem_source"] = summary["mem_source"]
    if summary.get("state_bytes_per_host"):
        e["state_bytes_per_host"] = int(summary["state_bytes_per_host"])
    # network observatory tail fields (obs.netscope): exact p50/p99
    # read-outs from the device histograms — present only on
    # cfg.netscope runs, so perf_regress trajectories can gate tail
    # behavior (not just means) without touching older entries
    if "rtt_p50_us" in summary:
        e["rtt_p50_us"] = int(summary["rtt_p50_us"])
        e["rtt_p99_us"] = int(summary["rtt_p99_us"])
        e["completion_p99_s"] = summary.get("completion_p99_s")
    # occupancy fields (obs.passcope): the lockstep wasted-lane
    # fraction and, on --passcope runs, the top device pass — what
    # tools/perf_regress.py's occupancy gate compares (waste GROWING
    # past the band is a regression like a rate drop is). Present only
    # when the run carried the occupancy record, so pre-passcope
    # trajectories stay untouched.
    if "waste_frac" in summary:
        e["waste_frac"] = summary["waste_frac"]
        if "top_pass" in summary:
            e["top_pass"] = summary["top_pass"]
            e["top_pass_frac"] = summary["top_pass_frac"]
    if rep_rates:
        e["rep_rates"] = list(rep_rates)
    if rep_spread is not None:
        e["rep_spread"] = rep_spread
    if cost:
        e["roofline_frac"] = round(cost.get("roofline_frac", 0.0), 5)
        e["passes_per_window"] = round(
            cost.get("passes_per_window", 0.0), 3)
    if phases:
        e["phases"] = {k: round(v, 4) for k, v in phases.items()}
    if attributed_frac is not None:
        e["attributed_frac"] = attributed_frac
    if note:
        e["note"] = note
    extras = config_extras(cfg)
    if extras:
        if cost and cost.get("hot_columns"):
            # the AS-RUN working set: Simulation fills app_kinds/
            # uses_tcp from the compiled process specs, which can
            # activate more COLD_WHEN gates than the caller's input
            # config shows
            extras["hot_columns"] = int(cost["hot_columns"])
        e["extras"] = extras
    return e


def entry_from_report(scenario: str, fingerprint: str, platform: str,
                      report, attribution: dict = None, **kw) -> dict:
    """One ledger line straight from a SimReport (+ optional obs.perf
    attribution) — the shared construction behind the CLI's ``--perf``
    and ``tools/perf_report.py --ledger``, so the cold/warm split and
    the phase map are derived in exactly one place."""
    warm = report.cost.get("warm_wall")
    phases = attributed = None
    if attribution is not None:
        phases = {p: r["wall_s"]
                  for p, r in attribution["phases"].items()}
        attributed = attribution["attributed_frac"]
    return make_entry(
        scenario=scenario, fingerprint=fingerprint, platform=platform,
        summary=report.summary(), cost=report.cost_model(),
        phases=phases, attributed_frac=attributed,
        cold_wall=round(report.wall_seconds - (warm or 0), 3),
        warm_wall=round(warm, 3) if warm else None, **kw)


def entry_rate(e: dict) -> float | None:
    """The throughput figure the regression gate compares: warm
    events/sec when the entry has a warm wall, else the cold-inclusive
    rate (single-chunk runs have no split)."""
    return e.get("warm_events_per_sec") or e.get("events_per_sec")


def key_of(e: dict) -> tuple:
    """The trajectory-grouping key: same scenario, same platform, same
    config fingerprint — the only entries comparable as a series."""
    return (e.get("scenario"), e.get("platform"), e.get("fingerprint"))


def jsonl_append(path: str, obj: dict, fsync: bool = False,
                 sort_keys: bool = False) -> None:
    """Append one JSON line. A single write+flush of one line is
    already atomic enough for same-process readers; ``fsync=True``
    additionally makes the append DURABLE before returning — the
    contract crash-cause journals and the fleet run queue need (a
    SIGKILL after jsonl_append returns can never lose the record,
    only ever tear a line that was still in flight — which
    jsonl_read skips). This pair is the repo's one implementation of
    the crash-tolerant JSONL pattern (perf ledger, digest chain
    shape, ``<ck>.supervisor.jsonl``, ``fleet/queue.jsonl``)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(obj, sort_keys=sort_keys) + "\n")
        f.flush()
        if fsync:
            os.fsync(f.fileno())


def jsonl_read(path: str, label: str = "jsonl") -> list:
    """All well-formed dict entries, file order. A torn/corrupt line
    (a writer killed mid-append) is skipped with a stderr warning,
    never a crash — readers must keep working on a crashed run's
    file. `label` names the file's role in the warning."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                sys.stderr.write(
                    f"{label}: {path}:{i}: skipping malformed line "
                    "(torn append?)\n")
                continue
            if isinstance(e, dict):
                out.append(e)
    return out


def append(entry: dict, path: str = None) -> str | None:
    """Append one ledger entry. Resolves `path` through
    default_path(); returns the path written, or None when the ledger
    is disabled."""
    if path is None:
        path = default_path()
    if path is None:
        return None
    jsonl_append(path, entry)
    return path


def read(path: str) -> list:
    """All well-formed ledger entries, file order (torn lines skipped
    with a warning — the regression gate must keep working on a
    crashed round's ledger)."""
    return jsonl_read(path, label="ledger")
