"""memscope: the device-memory & XLA-cost observatory.

The reference tracker reports per-host RAM and process RSS every
heartbeat (shd-tracker.c:539-546, shd-slave.c:374-395); this repo
attributed >=90% of *wall time* in PR 6 but memory stayed a blind
spot: the roofline was a hand model with a duplicated 819 GB/s peak,
no XLA ``cost_analysis``/``memory_analysis`` was ever captured, and
nothing could say where the bytes go per field or per pass — while
ROADMAP item 2's 100k->1M-host push names memory-layout refactors as
the blocker. This module is the measure-then-gate counterpart for
bytes (docs/observability.md "Memory observatory"):

- **Static byte census** (:func:`state_census`): per-field
  ``dtype x shape`` bytes of the ``Hosts``/``HostParams``/``Shared``
  pytrees at the run's actual H, rolled up by
  ``engine.state.STATE_SECTIONS`` and split hot/cold per the PR-12
  ``HOT_FIELDS``/``COLD_WHEN`` declaration — the hot-split's HBM
  saving as a number, not a claim. A pure-stdlib dims table
  (:data:`HOSTS_DIMS`/:data:`HP_DIMS`) backs the jax-free consumers
  (``tools/state_matrix.py``'s bytes column); it is pinned exactly
  against ``engine.state.shape_census`` (eval_shape over the real
  ``alloc_hosts``) by tests/test_memscope.py, so the two definitions
  cannot drift.
- **Compiled-program capture** (:func:`observe_executable`, hooked in
  ``core.jitcache.AotJit``): XLA ``cost_analysis()`` (flops, bytes
  accessed) and ``memory_analysis()`` (argument/output/temp/generated
  -code bytes) per compiled entry, kept in :data:`CAPTURED`,
  published as ``cost.*``/``mem.*`` gauges and a ``memscope.analyze``
  span. Backends that refuse either analysis degrade gracefully
  (``available: False`` with the error recorded), never an exception.
- **Live watermarks** (:class:`Watermark`): per-chunk device-buffer
  high-water sampling — real device memory stats where the backend
  provides them (per device, so a mesh run reports per-shard peaks),
  ``resource``/RSS fallback on CPU — wired into the tracker heartbeat
  (``dev=`` column) and the perf ledger (``mem_peak_bytes``, gated by
  ``tools/perf_regress.py``'s memory gate).
- **One HBM-peak definition** (:func:`hbm_peak_gbps`): the roofline
  peak, honoring ``SHADOW_TPU_HBM_GBPS`` — previously duplicated as a
  literal in ``SimReport.cost_model`` and an env default in the run
  loop.

Everything here is host-side and read-only: census, capture and
watermark sampling never touch device state, so a memscope-enabled
run's digest chain is byte-identical to a plain run's (asserted by
tests/test_memscope.py). The module imports nothing heavier than the
stdlib at import time — jax and the engine load lazily inside the
functions that need them — so headless tools (``tools/state_matrix``,
``tools/capacity_plan --help``) can load it by file path.
"""

from __future__ import annotations

import os
import sys

# --- one HBM-peak definition (satellite: un-duplicate 819) -----------------

# v5e-class default; override per box with SHADOW_TPU_HBM_GBPS
DEFAULT_HBM_GBPS = 819.0


def hbm_peak_gbps() -> float:
    """The chip HBM peak the roofline fractions divide by — the ONE
    definition behind SimReport.cost_model and the run loop's cost
    bookkeeping (both previously carried their own copy of 819).
    ``SHADOW_TPU_HBM_GBPS`` overrides; an unparsable value warns and
    falls back rather than crashing a run at report time."""
    env = os.environ.get("SHADOW_TPU_HBM_GBPS")
    if env:
        try:
            return float(env)
        except ValueError:
            sys.stderr.write(
                f"shadow_tpu: memscope: SHADOW_TPU_HBM_GBPS={env!r} is "
                f"not a number; using {DEFAULT_HBM_GBPS}\n")
    return DEFAULT_HBM_GBPS


# --- the stdlib shape table ------------------------------------------------
#
# Per-host trailing dims + dtype of every Hosts/HostParams column, as
# LITERALS: the jax-free consumers (state_matrix's bytes column, the
# capacity planner's headless mode) read these without importing the
# engine. Symbolic dims resolve through dims_of(); the table is pinned
# EXACTLY against engine.state.shape_census (eval_shape over the real
# alloc_hosts) by tests/test_memscope.py::test_census_exactness — an
# alloc_hosts edit that forgets this table fails that test by field
# name.

DTYPE_BYTES = {"i64": 8, "i32": 4, "u32": 4, "f32": 4, "bool": 1,
               "i16": 2, "u16": 2, "i8": 1}
# canonical numpy names, for pinning against real array dtypes
DTYPE_NAMES = {"i64": "int64", "i32": "int32", "u32": "uint32",
               "f32": "float32", "bool": "bool",
               "i16": "int16", "u16": "uint16", "i8": "int8"}

# constant dims mirrored from their owning modules (pinned by the
# exactness test): net.packet.PKT_WORDS, net.sack.K,
# engine.defs.N_STATS, obs.netscope.NS_KINDS/NS_BUCKETS
PKT_WORDS = 13
SACK_K = 4
N_STATS = 24
NS_KINDS = 4
NS_BUCKETS = 32

HOSTS_DIMS = (
    ("eq_time", ("Q",), "i64"),
    ("eq_seq", ("Q",), "i32"),
    ("eq_kind", ("Q",), "i32"),
    ("eq_pkt", ("Q", "PKT"), "i32"),
    ("eq_ctr", (), "i32"),
    ("eq_next", (), "i64"),
    ("rng_ctr", (), "i32"),
    ("cpu_avail", (), "i64"),
    ("nic_busy", (), "i64"),
    ("nic_sched", (), "bool"),
    ("nic_rr", (), "i32"),
    ("nic_rx_until", (), "i64"),
    ("txq_pkt", ("T", "PKT"), "i32"),
    ("txq_head", (), "i32"),
    ("txq_cnt", (), "i32"),
    ("pkt_ctr", (), "i32"),
    ("next_eport", (), "i32"),
    ("sk_used", ("S",), "bool"),
    ("sk_proto", ("S",), "i32"),
    ("sk_state", ("S",), "i32"),
    ("sk_lport", ("S",), "i32"),
    ("sk_rport", ("S",), "i32"),
    ("sk_rhost", ("S",), "i32"),
    ("sk_parent", ("S",), "i32"),
    ("sk_snd_una", ("S",), "i64"),
    ("sk_snd_nxt", ("S",), "i64"),
    ("sk_snd_max", ("S",), "i64"),
    ("sk_snd_end", ("S",), "i64"),
    ("sk_rcv_nxt", ("S",), "i64"),
    ("sk_ooo_s", ("S", "K"), "i64"),
    ("sk_ooo_e", ("S", "K"), "i64"),
    ("sk_sack_s", ("S", "K"), "i64"),
    ("sk_sack_e", ("S", "K"), "i64"),
    ("sk_hole_end", ("S",), "i64"),
    ("sk_rex_nxt", ("S",), "i64"),
    ("sk_peer_fin", ("S",), "i64"),
    ("sk_fin_acked", ("S",), "bool"),
    ("sk_close_after", ("S",), "bool"),
    ("sk_cwnd", ("S",), "f32"),
    ("sk_ssthresh", ("S",), "f32"),
    ("sk_srtt", ("S",), "i64"),
    ("sk_rtt_min", ("S",), "i64"),
    ("sk_rttvar", ("S",), "i64"),
    ("sk_rto", ("S",), "i64"),
    ("sk_rto_deadline", ("S",), "i64"),
    ("sk_timer_on", ("S",), "bool"),
    ("sk_timer_gen", ("S",), "i32"),
    ("sk_dupacks", ("S",), "i32"),
    ("sk_rtt_seq", ("S",), "i64"),
    ("sk_rtt_time", ("S",), "i64"),
    ("sk_ctl", ("S",), "i32"),
    ("sk_peer_rwnd", ("S",), "i64"),
    ("sk_sndbuf", ("S",), "i64"),
    ("sk_rcvbuf", ("S",), "i64"),
    ("sk_hs_time", ("S",), "i64"),
    ("sk_last_tx", ("S",), "i64"),
    ("sk_syn_tag", ("S",), "i32"),
    ("sk_proc", ("S",), "i32"),
    ("sk_app_ref", ("S",), "i32"),
    ("sk_cc_wmax", ("S",), "f32"),
    ("sk_cc_epoch", ("S",), "i64"),
    ("sk_cc_k", ("S",), "f32"),
    ("app_node", ("PP",), "i32"),
    ("app_r", ("PP", 8), "i64"),
    ("app_proc", (), "i32"),
    ("tgen_sync", ("SY",), "i32"),
    ("ob_pkt", ("O", "PKT"), "i32"),
    ("ob_time", ("O",), "i64"),
    ("ob_cnt", (), "i32"),
    ("ob_next", (), "i64"),
    ("hw_time", ("HW",), "i64"),
    ("hw_pkt", ("HW", "PKT"), "i32"),
    ("hw_cnt", (), "i32"),
    ("hw_drop", (), "i32"),
    ("tr_time", ("TC",), "i64"),
    ("tr_pkt", ("TC", "PKT"), "i32"),
    ("tr_dir", ("TC",), "i32"),
    ("tr_cnt", (), "i32"),
    ("tr_drop", (), "i32"),
    ("stats", ("NST",), "i64"),
    ("ns_hist", ("NSK", "NSB"), "i64"),
    ("cap_peaks", (4,), "i32"),
)

# the Shared fields that scale with H (replicated per-host tables —
# engine.state.Shared declares exactly these as [H] rows; everything
# else there is topology-sized or scalar, i.e. fixed cost for the
# capacity model). Pinned against the live tree by
# tests/test_memscope.py.
SHARED_PER_HOST_FIELDS = ("host_vertex", "host_bw_up", "host_bw_down")

HP_DIMS = (
    ("hid", (), "i32"),
    ("rng_stream", (), "u32"),
    ("vertex", (), "i32"),
    ("bw_up", (), "i64"),
    ("bw_down", (), "i64"),
    ("app_kind", ("PP",), "i32"),
    ("app_cfg", ("PP", 8), "i64"),
    ("nic_buf", (), "i64"),
    ("cpu_cost", (), "i64"),
    ("cpu_threshold", (), "i64"),
    ("rcvbuf0", (), "i64"),
    ("sndbuf0", (), "i64"),
    ("pcap_on", (), "bool"),
)


# The shrink campaign's at-rest dtype overlay (docs/performance.md):
# when an EngineConfig allocates the narrow layout (wide_state == 0,
# the default), these Hosts columns live at a narrower dtype than the
# canonical wide one HOSTS_DIMS declares. A LITERAL mirror of
# engine.state.NARROW_SPEC's (field -> narrow dtype) projection,
# pinned against it by tests/test_shrink.py and against live arrays
# by the census exactness pin — a NARROW_SPEC edit that forgets this
# table fails by field name. HOSTS_DIMS itself stays wide-canonical:
# it documents the COMPUTE dtype handlers see, and the digest's
# canonical form.
NARROW_DTYPES = {
    "sk_proto": "i8", "sk_state": "i8", "sk_ctl": "i8",
    "sk_lport": "u16", "sk_rport": "u16",
    "sk_snd_una": "i32", "sk_snd_nxt": "i32", "sk_snd_max": "i32",
    "sk_snd_end": "i32", "sk_rcv_nxt": "i32",
    "sk_ooo_s": "i32", "sk_ooo_e": "i32",
    "sk_sack_s": "i32", "sk_sack_e": "i32",
    "sk_hole_end": "i32", "sk_rex_nxt": "i32", "sk_peer_fin": "i32",
    "sk_rtt_seq": "i32",
    "sk_peer_rwnd": "i32", "sk_sndbuf": "i32", "sk_rcvbuf": "i32",
}


def effective_dtype(field: str, dt: str, cfg=None) -> str:
    """The AT-REST dtype of a Hosts column under this config: the
    NARROW_DTYPES overlay applies unless cfg asks for the wide layout
    (wide_state truthy). None = EngineConfig defaults = narrow."""
    wide = int(getattr(cfg, "wide_state", 0)) if cfg is not None else 0
    return dt if wide else NARROW_DTYPES.get(field, dt)


def dims_of(cfg=None) -> dict:
    """Symbolic-dim sizes from an EngineConfig (duck-typed: anything
    with the cap attributes works, so headless callers can pass a
    plain namespace). None = the EngineConfig defaults — the reference
    point state_matrix's bytes/host column uses."""
    def cap(name, default):
        return int(getattr(cfg, name, default)) if cfg is not None \
            else default

    return {
        "Q": cap("qcap", 32), "S": cap("scap", 16),
        "O": cap("obcap", 32), "T": cap("txqcap", 16),
        "PP": max(cap("procs_per_host", 1), 1),
        "SY": max(cap("synccap", 1), 1),
        "HW": max(cap("hostedcap", 1), 1),
        "TC": max(cap("tracecap", 0), 1),
        "K": SACK_K, "PKT": PKT_WORDS, "NST": N_STATS,
        # netscope's bucket axis is zero-capacity when the knob is off
        # (engine.state.alloc_hosts) — the census must agree
        "NSK": NS_KINDS,
        "NSB": NS_BUCKETS if cap("netscope", 0) else 0,
    }


def row_shape(dims_spec: tuple, dims: dict) -> tuple:
    """Concrete per-host trailing shape for a table row."""
    return tuple(d if isinstance(d, int) else dims[d]
                 for d in dims_spec)


def row_bytes(field: str, cfg=None, table=HOSTS_DIMS) -> int:
    """Per-host bytes of one column at this config (stdlib path).
    Hosts columns honor the at-rest NARROW_DTYPES overlay
    (effective_dtype); HP_DIMS rows have no narrow layout."""
    dims = dims_of(cfg)
    for name, dspec, dt in table:
        if name == field:
            if table is HOSTS_DIMS:
                dt = effective_dtype(name, dt, cfg)
            n = DTYPE_BYTES[dt]
            for d in row_shape(dspec, dims):
                n *= d
            return n
    raise KeyError(f"unknown field {field!r}")


def table_row_bytes(cfg=None, table=HOSTS_DIMS) -> dict:
    """{field: per-host bytes} for a whole dims table (stdlib path —
    what state_matrix's bytes/host column reads), at the layout this
    config actually allocates (NARROW_DTYPES overlay on Hosts)."""
    dims = dims_of(cfg)
    out = {}
    for name, dspec, dt in table:
        if table is HOSTS_DIMS:
            dt = effective_dtype(name, dt, cfg)
        n = DTYPE_BYTES[dt]
        for d in row_shape(dspec, dims):
            n *= d
        out[name] = n
    return out


# --- the census ------------------------------------------------------------

def _tree_field_bytes(tree) -> dict:
    """{field: (bytes, dtype, shape)} from a live chex dataclass of
    arrays (shape/dtype metadata only — no device sync, no transfer)."""
    out = {}
    for f in tree.__dataclass_fields__:
        a = getattr(tree, f)
        n = a.dtype.itemsize
        for d in a.shape:
            n *= int(d)
        out[f] = (n, str(a.dtype), tuple(int(d) for d in a.shape))
    return out


def state_census(cfg, hosts=None, hp=None, sh=None) -> dict:
    """The static byte census: per-field bytes at the run's actual H,
    rolled up by STATE_SECTIONS and split hot/cold per HOT_FIELDS and
    the config-gated hot_fields(cfg) runtime set.

    With only `cfg`, Hosts/HostParams shapes come from
    ``engine.state.shape_census`` (eval_shape — zero allocation) and
    the topology-sized Shared tree is omitted; passing the live trees
    (a built Simulation's hosts/hp/sh) censuses exactly what the run
    holds, Shared included. Either way this imports the engine (jax);
    headless callers use the stdlib table helpers above instead."""
    from ..engine.state import (COLD_FIELDS, HOT_FIELDS, hot_fields,
                                section_of, shape_census)

    H = cfg.num_hosts

    def _nbytes(shape, dtype_name):
        n = {"int64": 8, "int32": 4, "uint32": 4, "float32": 4,
             "bool": 1, "int16": 2, "uint16": 2, "int8": 1}[dtype_name]
        for d in shape:
            n *= int(d)
        return n

    if hosts is not None:
        hosts_fields = _tree_field_bytes(hosts)
    else:
        hosts_fields = {f: (_nbytes(shape, dt), dt, shape)
                        for f, (shape, dt) in shape_census(cfg).items()}
    runtime_hot = set(hot_fields(cfg))

    fields = {}
    sections = {}
    hot_b = cold_b = runtime_b = 0
    for f, (b, dt, shape) in hosts_fields.items():
        sec = section_of(f)
        fields[f] = {"bytes": b, "per_host": b // max(H, 1),
                     "dtype": dt, "shape": list(shape),
                     "section": sec,
                     "hot": f in HOT_FIELDS,
                     "hot_runtime": f in runtime_hot}
        sections[sec] = sections.get(sec, 0) + b
        if f in COLD_FIELDS:
            cold_b += b
        else:
            hot_b += b
        if f in runtime_hot:
            runtime_b += b
    total_h = hot_b + cold_b

    out = {
        "H": H,
        "hosts": {
            "fields": fields,
            "bytes": total_h,
            "per_host": total_h // max(H, 1),
            "sections": sections,
            "hot": {
                # static split (HOT_FIELDS vs COLD_FIELDS)
                "static_bytes": hot_b,
                "static_cold_bytes": cold_b,
                # the AS-CONFIGURED drain working set (COLD_WHEN gates
                # active): the bytes every rung gather/scatter and
                # loop carry actually moves — the split's saving is
                # bytes - runtime_bytes
                "runtime_bytes": runtime_b,
                "runtime_cold_bytes": total_h - runtime_b,
                "runtime_columns": len(runtime_hot),
            },
        },
    }

    if hp is not None:
        hpf = _tree_field_bytes(hp)
    else:
        hpf = {f: (row_bytes(f, cfg, HP_DIMS) * H,
                   DTYPE_NAMES[dt], None)
               for f, _, dt in HP_DIMS}
    hp_total = 0
    hp_fields = {}
    for f, (b, dt, shape) in hpf.items():
        hp_fields[f] = {"bytes": b, "per_host": b // max(H, 1),
                        "dtype": dt}
        hp_total += b
    out["hp"] = {"fields": hp_fields, "bytes": hp_total,
                 "per_host": hp_total // max(H, 1)}

    sh_per_host = sh_fixed = 0
    if sh is not None:
        shf = _tree_field_bytes(sh)
        sh_fields = {}
        for f, (b, dt, shape) in shf.items():
            # per-host replicated tables scale with H; the topology
            # oracle and scalars are fixed cost. Classified by NAME
            # (the declared contract, SHARED_PER_HOST_FIELDS) — a
            # shape[0] == H test would misfile the O(V^2) oracle as
            # linear whenever a topology happens to put one vertex
            # per host, corrupting every ladder extrapolation
            scales = f in SHARED_PER_HOST_FIELDS
            sh_fields[f] = {"bytes": b, "dtype": dt,
                            "scales_with_h": scales}
            if scales:
                sh_per_host += b // max(H, 1)
            else:
                sh_fixed += b
        out["shared"] = {"fields": sh_fields,
                         "bytes": sh_per_host * H + sh_fixed,
                         "per_host": sh_per_host,
                         "fixed_bytes": sh_fixed}

    out["per_host"] = (out["hosts"]["per_host"] + out["hp"]["per_host"]
                       + sh_per_host)
    out["fixed_bytes"] = sh_fixed
    out["bytes"] = (out["hosts"]["bytes"] + out["hp"]["bytes"]
                    + (out["shared"]["bytes"] if sh is not None else 0))
    return out


# --- compiled-program capture ----------------------------------------------

# scope -> the latest analysis dict observed for that compiled program
# (process-wide, kept unconditionally like serving.aotcache.STATS: one
# small dict per compile, never per call)
CAPTURED: dict = {}


def _cost_dict(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def observe_executable(scope: str, compiled, donated=()) -> dict:
    """Record one compiled program's XLA cost/memory analyses.

    Returns (and stores in :data:`CAPTURED` under `scope`) a dict::

        {"scope", "available",           # any analysis succeeded
         "flops", "bytes_accessed",      # cost_analysis (or None)
         "argument_bytes", "output_bytes", "temp_bytes",
         "alias_bytes", "generated_code_bytes",  # memory_analysis
         "errors": {...}}                # per-analysis failure text

    Backends/executables that refuse an analysis (older jax, loaded
    disk-cache entries, TPU variants) record the error and carry None
    for those figures — graceful absence, never an exception (the
    contract tests/test_memscope.py pins). Publishes ``cost.*`` /
    ``mem.xla_*`` gauges when metrics are enabled and a
    ``memscope.analyze`` span when tracing is."""
    out = {"scope": scope, "available": False, "flops": None,
           "bytes_accessed": None, "argument_bytes": None,
           "output_bytes": None, "temp_bytes": None,
           "alias_bytes": None, "generated_code_bytes": None,
           # the DECLARED donation (core.jitcache.AotJit's
           # donate_argnums) — the donation audit compares it against
           # the MEASURED alias_bytes per executable
           "donated": tuple(donated or ()),
           "errors": {}}
    if compiled is None:
        out["errors"]["compiled"] = "no executable"
        CAPTURED[scope] = out
        return out
    from . import trace as TR
    t0 = TR.TRACER.now() if TR.ENABLED else None
    try:
        ca = _cost_dict(compiled)
        flops = ca.get("flops")
        ba = ca.get("bytes accessed")
        out["flops"] = float(flops) if flops is not None else None
        out["bytes_accessed"] = float(ba) if ba is not None else None
        out["available"] = True
    except Exception as e:
        out["errors"]["cost_analysis"] = f"{type(e).__name__}: {e}"
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            raise ValueError("backend returned no memory analysis")
        for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                          ("output_bytes", "output_size_in_bytes"),
                          ("temp_bytes", "temp_size_in_bytes"),
                          ("alias_bytes", "alias_size_in_bytes"),
                          ("generated_code_bytes",
                           "generated_code_size_in_bytes")):
            out[key] = int(getattr(ma, attr))
        out["available"] = True
    except Exception as e:
        out["errors"]["memory_analysis"] = f"{type(e).__name__}: {e}"
    CAPTURED[scope] = out
    if TR.ENABLED:
        TR.TRACER.complete("memscope.analyze", t0,
                           args={"scope": scope,
                                 "available": out["available"]})
    from . import metrics as MT
    if MT.ENABLED:
        reg = MT.REGISTRY
        reg.counter("memscope.programs").inc()
        if out["flops"] is not None:
            reg.gauge("cost.flops").set(out["flops"])
        if out["bytes_accessed"] is not None:
            reg.gauge("cost.bytes_accessed").set(out["bytes_accessed"])
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "generated_code_bytes"):
            if out[key] is not None:
                reg.gauge(f"mem.xla_{key}").set(out[key])
    return out


def program_footprint(analysis: dict) -> int | None:
    """The executable's device footprint in bytes — arguments + temp
    + outputs, minus what aliases the inputs (donation) — or None when
    the backend refused memory_analysis. This is the figure the
    capacity planner validates its census prediction against."""
    if not analysis or analysis.get("argument_bytes") is None:
        return None
    return (analysis["argument_bytes"] + analysis["temp_bytes"]
            + analysis["output_bytes"] - analysis["alias_bytes"])


def donation_audit(captured: dict = None) -> list:
    """Donation/aliasing audit over the captured executables (lever 4
    of the shrink campaign): one row per scope comparing the DECLARED
    donation (AotJit donate_argnums, recorded at build time) against
    the MEASURED ``alias_bytes`` from XLA memory_analysis. Flags:

    - ``ok``          — donation declared and XLA aliased bytes;
    - ``inert``       — donation declared but XLA aliased nothing
      (the backend refused the alias: outputs double-buffer and the
      program peaks ~2x its arguments — worth chasing per backend);
    - ``undonated``   — no donation declared on a program whose
      outputs could alias (output_bytes > 0): the state copy is paid
      every call;
    - ``unmeasured``  — the backend refused memory_analysis.

    Sorted fattest-arguments first, so the top row is the biggest
    lever. Rows are plain dicts (capacity_plan renders them)."""
    rows = []
    for scope, an in (captured if captured is not None
                      else CAPTURED).items():
        arg = an.get("argument_bytes")
        if arg is None:
            rows.append({"scope": scope, "flag": "unmeasured",
                         "declared": list(an.get("donated") or ()),
                         "argument_bytes": None, "alias_bytes": None,
                         "temp_bytes": None, "output_bytes": None,
                         "aliased_frac": None})
            continue
        alias = an.get("alias_bytes") or 0
        declared = list(an.get("donated") or ())
        if declared:
            flag = "ok" if alias > 0 else "inert"
        else:
            flag = "undonated" if (an.get("output_bytes") or 0) > 0 \
                else "ok"
        rows.append({
            "scope": scope, "flag": flag, "declared": declared,
            "argument_bytes": int(arg),
            "alias_bytes": int(alias),
            "temp_bytes": int(an.get("temp_bytes") or 0),
            "output_bytes": int(an.get("output_bytes") or 0),
            "aliased_frac": round(alias / arg, 4) if arg else None,
        })
    rows.sort(key=lambda r: -(r["argument_bytes"] or 0))
    return rows


# --- live watermarks -------------------------------------------------------

def rss_bytes() -> int:
    """This process's LIFETIME peak resident set (ru_maxrss is KiB on
    Linux) — monotone over the whole process, so only an upper bound
    for any single run inside it."""
    import resource
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def current_rss_bytes() -> int:
    """This process's CURRENT resident set (/proc/self/statm) — what
    per-run high-water sampling maxes over. ru_maxrss would be wrong
    here: it is process-lifetime-monotonic, so in a multi-run process
    (bench.py's 4-config matrix) a small scenario benched after a
    large one would record the large one's peak as its own and poison
    the ledger's memory trajectory. Falls back to the lifetime figure
    where /proc is unavailable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, ValueError, IndexError):
        return rss_bytes()


class Watermark:
    """Device-buffer high-water sampling, one sample() per window
    chunk. Uses the backend's per-device ``memory_stats()`` where
    available (TPU/GPU: real HBM in use, per device — under a mesh
    each device is one shard, so ``per_device`` IS the per-shard
    watermark); backends without it (CPU) fall back to process RSS,
    honestly labeled ``source: "rss"``. Sampling is a handful of
    host-side reads per chunk — never a device sync.

    The peak is PER-RUN: the max over this instance's samples of the
    CURRENT usage (``bytes_in_use`` / /proc VmRSS), not the
    allocator's or kernel's lifetime-monotonic peak counters — those
    would contaminate later runs of a multi-run process with earlier
    runs' peaks, exactly the cross-talk the ledger's per-scenario
    memory gate cannot tolerate. The lifetime figures still ride the
    snapshot as ``lifetime_peak_bytes`` for context."""

    def __init__(self, devices=None):
        # devices: the run's device list in shard order
        # (parallel.shard.mesh_local_devices for a mesh; default all
        # local devices). Resolved lazily so constructing a Watermark
        # never imports jax in headless contexts.
        self._devices = devices
        self._probed = False
        self._device_ok = False
        self.source = "rss"
        self.per_device: list = []
        self.peak_bytes = 0
        self.lifetime_peak_bytes = 0
        self.baseline_bytes = 0
        self.samples = 0

    def _probe(self):
        self._probed = True
        if self._devices is None:
            try:
                import jax
                self._devices = jax.local_devices()
            except Exception:
                self._devices = []
        try:
            st = (self._devices[0].memory_stats()
                  if self._devices else None)
        except Exception:
            st = None
        self._device_ok = bool(st) and "bytes_in_use" in st
        self.source = "device" if self._device_ok else "rss"
        self.per_device = [0] * (len(self._devices)
                                 if self._device_ok else 0)
        self.baseline_bytes = (self._device_sample()
                               if self._device_ok
                               else current_rss_bytes())

    def _device_sample(self) -> int:
        total = 0
        for i, d in enumerate(self._devices):
            try:
                st = d.memory_stats() or {}
            except Exception:
                st = {}
            cur = int(st.get("bytes_in_use", 0))
            if cur > self.per_device[i]:
                self.per_device[i] = cur
            total += self.per_device[i]
            life = int(st.get("peak_bytes_in_use", cur))
            if life > self.lifetime_peak_bytes:
                self.lifetime_peak_bytes = life
        return total

    def sample(self) -> int:
        """Take one sample; returns the running per-run peak in
        bytes."""
        if not self._probed:
            self._probe()
        if self._device_ok:
            cur = self._device_sample()
        else:
            cur = current_rss_bytes()
            life = rss_bytes()
            if life > self.lifetime_peak_bytes:
                self.lifetime_peak_bytes = life
        if cur > self.peak_bytes:
            self.peak_bytes = cur
        self.samples += 1
        return self.peak_bytes

    def snapshot(self) -> dict:
        """The watermark record SimReport.memory / the tracker / the
        ledger read. ``peak_bytes`` is this run's high water (max of
        current-usage samples — comparable run to run even inside one
        process); ``delta_bytes`` subtracts the pre-run baseline;
        ``lifetime_peak_bytes`` is the monotone process/allocator
        figure, context only, never gated."""
        if not self._probed:
            self.sample()
        return {
            "source": self.source,
            "peak_bytes": int(self.peak_bytes),
            "baseline_bytes": int(self.baseline_bytes),
            "delta_bytes": int(max(self.peak_bytes
                                   - self.baseline_bytes, 0)),
            # clamped to >= the per-run peak: ru_maxrss and /proc
            # statm disagree by a few pages (kernel accounting
            # granularity), and the documented lifetime >= run
            # invariant should hold for consumers
            "lifetime_peak_bytes": int(max(self.lifetime_peak_bytes,
                                           self.peak_bytes)),
            "per_device": (list(self.per_device)
                           if self._device_ok else None),
            "samples": self.samples,
        }


def publish(registry, watermark: dict = None, census: dict = None,
            xla: dict = None) -> None:
    """Expose a run's memory figures as ``mem.*`` gauges — the
    metrics.json ``memory`` section (obs.metrics assembles it from
    this prefix, like the ``perf`` section)."""
    if watermark:
        registry.gauge("mem.peak_bytes").set(watermark["peak_bytes"])
        registry.gauge("mem.delta_bytes").set(watermark["delta_bytes"])
        if watermark.get("per_device"):
            for i, v in enumerate(watermark["per_device"]):
                registry.gauge(f"mem.device_peak_bytes.{i}").set(v)
    if census:
        registry.gauge("mem.state_bytes").set(census["bytes"])
        registry.gauge("mem.state_bytes_per_host").set(
            census["per_host"])
        registry.gauge("mem.hot_state_bytes").set(
            census["hosts"]["hot"]["runtime_bytes"])
    if xla:
        for key in ("bytes_accessed", "flops"):
            if xla.get(key) is not None:
                registry.gauge(f"cost.{key}").set(xla[key])
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "generated_code_bytes"):
            if xla.get(key) is not None:
                registry.gauge(f"mem.xla_{key}").set(xla[key])
