"""passcope — per-pass device-time & lockstep-occupancy observatory.

The third observatory tier: obs.perf attributes host WALL time to
engine phases, obs.memscope attributes BYTES; this module attributes
the DEVICE time spent inside the compiled window program to the same
named passes the stateflow matrix analyzes (lint/stateflow.py
ENTRIES: drain / exchange / cap_peaks / advance / nic.tx /
nic.rx_admit / tcp.rx / tcp.timer / udp.deliver), and measures how
much of each lockstep pass was wasted on idle lanes — the two numbers
the conservative-lookahead design hides from host-side timing
(tools/xplane_profile.py's docstring: nothing finer than ~10 ms
resolves from outside the jitted program).

Three surfaces, mirroring the obs.perf contract:

- **Wire decoder** (`parse_xspace` / `hlo_scope_map` /
  `device_self_times`): the xplane protobuf decoder, promoted here
  from tools/xplane_profile.py (which is now a thin CLI over this
  module — one wire-format implementation). Beyond the per-op
  duration table the tool always printed, it decodes the serialized
  HloProto the profiler embeds in the ``/host:metadata`` plane and
  maps every HLO instruction to its `jax.named_scope` path, so
  device self-times land on pass labels, not HLO mangles.
- **Attribution** (`attribute`): per-op SELF time (stack walk over
  nested (offset, duration) intervals — a while-loop's span must not
  double-count its body) mapped to the INNERMOST pass label on the
  op's scope path. ≥90% of trace-window device time attributed
  (`MIN_ATTRIBUTED`) or the result flags itself and labels the
  residual — the PR 6 rule, applied to device time.
- **Occupancy** (`occupancy` / `shard_occupancy`): lockstep
  efficiency from data the drain already returns (the per-rung pass
  mix + executed events) — no extra device work, so it is always on.
  A pass over a rung of width W engages W lanes whether or not a
  host has work; `waste_frac` is the fraction of those lane-steps no
  event filled.

Import cost: stdlib only. jax is imported lazily inside `Capture`,
so the headless consumers (tools/xplane_profile.py --self-check, the
CI simlint job with no jax installed) load this file by path and pay
nothing.
"""

from __future__ import annotations

import collections
import glob
import json
import os
import re

# the stateflow entry names (lint/stateflow.py ENTRIES) this module
# attributes device time to — the same labels engine/window.py and
# parallel/shard.py stamp with jax.named_scope
PASS_LABELS = (
    "drain", "exchange", "exchange.sharded", "cap_peaks", "advance",
    "nic.tx", "nic.rx_admit", "tcp.rx", "tcp.timer", "udp.deliver",
)
# drain-rung sublabels (engine.window.pass_labels): w<K> window
# rungs, k<K> per-pass rungs, dense
_RUNG_RE = re.compile(r"^(?:[wk][0-9]+|dense)$")

MIN_ATTRIBUTED = 0.90
RESIDUAL = "unattributed (device glue)"

DEFAULT_TRACE_CHUNKS = 8


# --- minimal protobuf wire decoding ---------------------------------------
# (the single implementation; tools/xplane_profile.py imports these)

def _varint(buf, i):
    x = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        x |= (b & 0x7F) << s
        if not b & 0x80:
            return x, i
        s += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    value: int for varint(0)/fixed(1,5), memoryview for bytes(2)."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 1:
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 5:
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:  # groups unsupported/absent in xplane
            raise ValueError(f"wire type {wt}")
        yield fn, wt, v


def parse_xspace(path):
    """-> [(plane_name, [(line_name, durs, counts)])] — the per-line
    duration aggregate tools/xplane_profile.py has always printed
    (byte-format of its report unchanged)."""
    buf = memoryview(open(path, "rb").read())
    planes = []
    for fn, wt, v in _fields(buf):
        if fn == 1 and wt == 2:             # XSpace.planes
            planes.append(_parse_plane(v))
    return planes


def _plane_raw(buf):
    """-> (name, {metadata_id: metadata_buf}, [line_buf])."""
    name = ""
    emeta = {}
    lines = []
    for fn, wt, v in _fields(buf):
        if fn == 2 and wt == 2:              # XPlane.name
            name = bytes(v).decode("utf-8", "replace")
        elif fn == 3 and wt == 2:            # XPlane.lines
            lines.append(v)
        elif fn == 4 and wt == 2:            # XPlane.event_metadata map
            k, m = None, None
            for fn2, wt2, v2 in _fields(v):
                if fn2 == 1:
                    k = v2
                elif fn2 == 2 and wt2 == 2:
                    m = v2
            if k is not None and m is not None:
                emeta[k] = m
    return name, emeta, lines


def _meta_name(mbuf):
    for fn, wt, v in _fields(mbuf):
        if fn == 2 and wt == 2:              # XEventMetadata.name
            return bytes(v).decode("utf-8", "replace")
    return ""


def _parse_plane(buf):
    name, emeta, lines = _plane_raw(buf)
    meta = {k: _meta_name(m) for k, m in emeta.items()}
    # Aggregate PER LINE: device traces nest container ops (module,
    # while, conditional) on separate lines above the leaf-op line, so
    # a single merged counter double-counts bodies inside containers
    # and conds "cost" their whole branch. Per-line tops let the
    # reader see both views: containers (where the window time sits
    # structurally) and leaves (which HLOs actually burn it).
    per_line = []                            # (line_name, durs, counts)
    for lbuf in lines:
        lname, evs = _line_events(lbuf)
        durs = collections.Counter()
        counts = collections.Counter()
        for _off, dur, mid in evs:
            key = meta.get(mid, f"#{mid}")
            durs[key] += dur
            counts[key] += 1
        if durs:
            per_line.append((lname, dict(durs), dict(counts)))
    return name, per_line


def _line_events(lbuf):
    """-> (line_name, [(offset_ps, duration_ps, metadata_id)])."""
    lname = ""
    evs = []
    for fn, wt, v in _fields(lbuf):
        if fn == 2 and wt == 2:              # XLine.name
            lname = bytes(v).decode("utf-8", "replace")
        # this build writes XLine.events at field 4 (older schema
        # revisions used 6 — accept both)
        elif fn in (4, 6) and wt == 2:       # XLine.events
            mid, off, dur = None, 0, 0
            for fn2, wt2, v2 in _fields(v):
                if fn2 == 1:                 # XEvent.metadata_id
                    mid = v2
                elif fn2 == 2:               # XEvent.offset_ps
                    off = v2
                elif fn2 == 3:               # XEvent.duration_ps
                    dur = v2
            if mid is not None:
                evs.append((off, dur, mid))
    return lname, evs


# --- HLO scope map: instruction name -> named_scope path ------------------

def _walk_hlo_module(mod):
    """HloModuleProto: f3 computations (ALL of them — while bodies and
    cond branches included) -> f2 instructions -> f1 name,
    f7 OpMetadata -> f2 op_name (the full scope path, e.g.
    ``jit(run_windows)/.../drain/w512/gather``)."""
    out = {}
    for fn, wt, v in _fields(mod):
        if fn == 3 and wt == 2:
            for fn2, wt2, v2 in _fields(v):
                if fn2 == 2 and wt2 == 2:
                    nm = op = None
                    for fn3, wt3, v3 in _fields(v2):
                        if fn3 == 1 and wt3 == 2:
                            nm = bytes(v3).decode("utf-8", "replace")
                        elif fn3 == 7 and wt3 == 2:
                            for fn4, wt4, v4 in _fields(v3):
                                if fn4 == 2 and wt4 == 2:
                                    op = bytes(v4).decode(
                                        "utf-8", "replace")
                    if nm:
                        out[nm] = op
    return out


def hlo_scope_map(path):
    """-> {hlo_instruction_name: op_name} over every module the
    profiler recorded (the ``/host:metadata`` plane embeds one
    serialized HloProto per executed jitted module — XEventMetadata
    stats carry it as the bytes value)."""
    buf = memoryview(open(path, "rb").read())
    scopes = {}
    for fn, wt, v in _fields(buf):
        if fn != 1 or wt != 2:
            continue
        name, emeta, _lines = _plane_raw(v)
        if name != "/host:metadata":
            continue
        for m in emeta.values():
            for fn2, wt2, v2 in _fields(m):
                if fn2 == 5 and wt2 == 2:          # XEventMetadata.stats
                    for fn3, wt3, v3 in _fields(v2):
                        if fn3 == 6 and wt3 == 2:  # XStat.bytes_value
                            for fn4, wt4, v4 in _fields(v3):
                                if fn4 == 1 and wt4 == 2:  # HloProto.hlo_module
                                    scopes.update(_walk_hlo_module(v4))
    return scopes


# --- per-op SELF time ------------------------------------------------------

def _self_times(evs):
    """{metadata_id: self_ps} from nested (offset, duration) events on
    one line. Events nest strictly (a while-loop span contains its
    body's spans); sorting by (offset, -duration) makes each parent
    precede its children, and a close-upto stack walk charges every
    span only its own time minus its DIRECT children."""
    out = collections.Counter()
    stack = []                 # [end_ps, dur_ps, child_ps, mid]
    evs = sorted(evs, key=lambda e: (e[0], -e[1]))

    def close(upto):
        while stack and stack[-1][0] <= upto:
            end, dur, child, mid = stack.pop()
            out[mid] += max(dur - child, 0)
            if stack:
                stack[-1][2] += dur
    for off, dur, mid in evs:
        close(off)
        stack.append([off + dur, dur, 0, mid])
    close(float("inf"))
    return out


def device_self_times(path):
    """-> {hlo_name: total_self_ps} over every XLA op line in the
    file. On CPU the per-op events live on the ``/host:CPU`` plane's
    ``tf_XLATfrtCpuClient/*`` line; device backends put them on
    per-device planes' "XLA Ops" lines — both carry "XLA" in the line
    name, which is the filter."""
    buf = memoryview(open(path, "rb").read())
    out = collections.Counter()
    for fn, wt, v in _fields(buf):
        if fn != 1 or wt != 2:
            continue
        pname, emeta, lines = _plane_raw(v)
        if pname == "/host:metadata":
            continue
        names = {k: _meta_name(m) for k, m in emeta.items()}
        for lbuf in lines:
            lname, evs = _line_events(lbuf)
            if "XLA" not in lname:
                continue
            for mid, ps in _self_times(evs).items():
                out[names.get(mid, f"#{mid}")] += ps
    return out


def decode_dir(trace_dir):
    """-> (scopes, self_times) merged over every .xplane.pb under
    trace_dir (one file per profiled host)."""
    scopes = {}
    selfs = collections.Counter()
    paths = sorted(glob.glob(os.path.join(trace_dir, "**",
                                          "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    for p in paths:
        scopes.update(hlo_scope_map(p))
        for k, ps in device_self_times(p).items():
            selfs[k] += ps
    return scopes, selfs


# --- attribution -----------------------------------------------------------

def attribute(selfs, scopes):
    """Map per-op device self-times to pass labels.

    The INNERMOST label on the scope path wins: an op inside
    ``.../drain/w512/nic.rx_admit/tcp.rx/...`` belongs to ``tcp.rx``,
    not to the drain loop that contains it (outer scopes wrap the
    whole while-loop). Rung sublabels (w512/k32/dense) are recorded
    independently in ``rungs`` — the per-rung view keeps handler time,
    so it answers "what does rung X cost" for compaction decisions.

    Runtime scaffolding lines (``ThunkExecutor::Execute (wait for
    completion)`` and friends — thread-pool dispatch and idle waits,
    dominant on small CPU hosts) are NOT compute: they go to a
    separate ``runtime_ms`` bucket excluded from the attribution
    denominator. HLO instruction names never contain ``::`` or
    spaces, which is the filter.

    -> {"phases": {label: {"ms", "frac"}}, "rungs": {...},
        "total_ms", "attributed_ms", "attributed_frac", "ok",
        "runtime_ms", "residual_ms", "residual_frac",
        "residual_label", "residual_top": [{"op", "ms"}]}
    """
    phases = collections.Counter()
    rungs = collections.Counter()
    resid = collections.Counter()
    runtime_ps = 0
    for hlo, ps in selfs.items():
        if "::" in hlo or " " in hlo:
            runtime_ps += ps
            continue
        op = scopes.get(hlo)
        label = rung = None
        if op:
            for part in reversed(op.split("/")):
                if rung is None and _RUNG_RE.match(part):
                    rung = part
                elif label is None and part in PASS_LABELS:
                    label = part
                if label is not None and rung is not None:
                    break
        if rung is not None:
            rungs[rung] += ps
            if label is None:
                label = "drain"       # rung scopes live inside drain
        if label is not None:
            phases[label] += ps
        else:
            resid[hlo] += ps
    total = sum(selfs.values()) - runtime_ps
    attributed = sum(phases.values())
    resid_ps = total - attributed

    def _tbl(ctr):
        return {k: {"ms": round(v / 1e9, 3),
                    "frac": round(v / total, 4) if total else 0.0}
                for k, v in sorted(ctr.items(), key=lambda kv: -kv[1])}
    frac = attributed / total if total else 0.0
    return {
        "phases": _tbl(phases),
        "rungs": _tbl(rungs),
        "total_ms": round(total / 1e9, 3),
        "attributed_ms": round(attributed / 1e9, 3),
        "attributed_frac": round(frac, 4),
        "ok": frac >= MIN_ATTRIBUTED,
        "runtime_ms": round(runtime_ps / 1e9, 3),
        "residual_ms": round(resid_ps / 1e9, 3),
        "residual_frac": round(1.0 - frac, 4) if total else 0.0,
        "residual_label": RESIDUAL,
        "residual_top": [{"op": k, "ms": round(v / 1e9, 3)}
                         for k, v in sorted(resid.items(),
                                            key=lambda kv: -kv[1])[:8]],
    }


def top_pass(dev):
    """-> (label, frac) of the largest attributed pass, or (None, 0)."""
    ph = (dev or {}).get("phases") or {}
    if not ph:
        return None, 0.0
    lbl = max(ph, key=lambda k: ph[k]["ms"])
    return lbl, ph[lbl]["frac"]


# --- lockstep occupancy ----------------------------------------------------

def occupancy(pass_mix, events, batch):
    """Lockstep efficiency from the drain's own pass accounting.

    pass_mix: {label: (width, n_passes)} — SimReport.cost["pass_mix"]
    (engine.window.pass_labels order: w-rungs, k-rungs, dense).
    events: executed events over the same span (chained NIC-TX
    included, so utilization is clamped at 1.0).
    batch: the sparse event batch (engine.window.sparse_batch) — a
    sparse pass runs `batch` event slots per gathered lane; dense
    passes run one.

    A w-rung's counted passes run over its gathered width; inner
    sub-compaction (a k-rung pass inside a w-window) is not counted
    separately, so w-rung lane_steps is a conservative upper bound.

    -> {"lane_steps", "events", "passes", "utilization", "waste_frac",
        "per_rung": {label: {"width", "passes", "batch",
                             "lane_steps", "min_fill"}}}
    """
    per_rung = {}
    lane_steps = 0
    passes = 0
    # selection lower bounds: rung k_i is chosen when the active count
    # lands in (k_{i-1}, k_i], so its fill is at least (k_{i-1}+1)/k_i
    ws = sorted((int(lbl[1:]), lbl) for lbl in pass_mix
                if lbl.startswith("w") and lbl[1:].isdigit())
    ks = sorted((int(lbl[1:]), lbl) for lbl in pass_mix
                if lbl.startswith("k") and lbl[1:].isdigit())

    def _min_fill(lbl, width):
        for sizes in (ws, ks):
            order = [s for s, _ in sizes]
            for j, (s, l) in enumerate(sizes):
                if l == lbl:
                    prev = order[j - 1] if j else 0
                    return (prev + 1) / width if width else 0.0
        if lbl == "dense":
            prev = max([s for s, _ in ws + ks], default=0)
            return (prev + 1) / width if width else 0.0
        return 0.0
    for lbl, (width, n) in pass_mix.items():
        width, n = int(width), int(n)
        b = 1 if lbl == "dense" else max(1, int(batch))
        steps = n * width * b
        lane_steps += steps
        passes += n
        per_rung[lbl] = {
            "width": width, "passes": n, "batch": b,
            "lane_steps": steps,
            "min_fill": round(_min_fill(lbl, width), 4),
        }
    util = min(1.0, events / lane_steps) if lane_steps else 0.0
    return {
        "lane_steps": int(lane_steps),
        "events": int(events),
        "passes": int(passes),
        "utilization": round(util, 4),
        "waste_frac": round(1.0 - util, 4),
        "per_rung": per_rung,
    }


def shard_occupancy(shard_pass_acc, shard_events, labels_sizes, batch):
    """Per-shard waste view, composing with the PR 6 shard.imbalance
    gauges: the same occupancy math per shard row.

    shard_pass_acc: [n_shards][n_rungs] pass counts;
    shard_events: [n_shards] executed events;
    labels_sizes: [(label, width)] in pass-index order.

    -> {"per_shard": [waste_frac...], "utilization": [...],
        "skew": max/mean of per-shard utilization (1.0 = balanced)}
    """
    wastes, utils = [], []
    for row, ev in zip(shard_pass_acc, shard_events):
        mix = {lbl: (size, int(n))
               for (lbl, size), n in zip(labels_sizes, row)}
        o = occupancy(mix, int(ev), batch)
        wastes.append(o["waste_frac"])
        utils.append(o["utilization"])
    mean = sum(utils) / len(utils) if utils else 0.0
    skew = (max(utils) / mean) if mean else 0.0
    return {"per_shard": wastes, "utilization": utils,
            "skew": round(skew, 4)}


# --- capture ---------------------------------------------------------------

class Capture:
    """jax.profiler trace around the first N window chunks of a run.

    The trace arms at the first chunk_done() — i.e. AFTER the first
    chunk, which holds the XLA compilation. Tracing a compile is
    ruinously slow on small hosts and its events would pollute the
    pass table anyway; the HLO metadata plane is emitted at execution
    time, so a post-compile trace still decodes fully. The next
    ``max_chunks`` chunks are traced, then the profiler stops while
    the run continues untraced.

    Profiling is observation only — the compiled program, its inputs
    and the digest chain are untouched (tests/test_passcope.py pins
    passcope-on chains byte-identical to plain runs). Backends that
    refuse the profiler degrade to ``available: False`` with the
    error recorded, never a crash.
    """

    def __init__(self, trace_dir, max_chunks=None):
        self.trace_dir = trace_dir
        self.max_chunks = max_chunks or int(os.environ.get(
            "SHADOW_TPU_PASSCOPE_CHUNKS", str(DEFAULT_TRACE_CHUNKS)))
        self.active = False
        self.stopped = False
        self.error = None
        self.chunks = 0

    def start(self):
        if self.active or self.stopped:
            return
        try:
            import jax
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self.active = True
        except Exception as e:  # refusing backend -> degrade
            self.error = repr(e)
            self.stopped = True

    def chunk_done(self):
        if self.stopped:
            return
        if not self.active:
            # first chunk boundary: compilation is behind us — arm
            self.start()
            return
        self.chunks += 1
        if self.chunks >= self.max_chunks:
            self.stop()

    def stop(self):
        if self.active:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                self.error = self.error or repr(e)
            self.active = False
        self.stopped = True

    def result(self):
        """-> the device_phases dict (attribute() output +
        available/trace_dir/chunks_traced), or available: False."""
        self.stop()
        base = {"trace_dir": self.trace_dir,
                "chunks_traced": self.chunks}
        if self.error:
            return {"available": False, "error": self.error, **base}
        try:
            scopes, selfs = decode_dir(self.trace_dir)
        except Exception as e:
            return {"available": False, "error": repr(e), **base}
        if not selfs:
            return {"available": False,
                    "error": "no XLA device events in trace", **base}
        out = attribute(selfs, scopes)
        out["available"] = True
        out.update(base)
        return out


# --- publishing ------------------------------------------------------------

def publish(registry, occ=None, dev=None, shards=None):
    """passcope.* / occupancy.* gauges — the sections
    obs.metrics.Registry.snapshot() assembles into metrics.json."""
    if occ:
        registry.gauge("occupancy.waste_frac").set(occ["waste_frac"])
        registry.gauge("occupancy.utilization").set(occ["utilization"])
        registry.gauge("occupancy.lane_steps").set(occ["lane_steps"])
        registry.gauge("occupancy.passes").set(occ["passes"])
        for lbl, r in occ["per_rung"].items():
            registry.gauge(
                f"occupancy.rung_passes.{lbl}").set(r["passes"])
            registry.gauge(
                f"occupancy.rung_lane_steps.{lbl}").set(r["lane_steps"])
    if shards:
        registry.gauge("occupancy.shard_skew").set(shards["skew"])
        for i, w in enumerate(shards["per_shard"]):
            registry.gauge(f"occupancy.shard_waste.{i}").set(w)
    if dev and dev.get("available"):
        registry.gauge("passcope.total_ms").set(dev["total_ms"])
        registry.gauge("passcope.attributed_frac").set(
            dev["attributed_frac"])
        registry.gauge("passcope.residual_ms").set(dev["residual_ms"])
        for lbl, ph in dev["phases"].items():
            registry.gauge(f"passcope.phase_ms.{lbl}").set(ph["ms"])


def format_report(dev=None, occ=None):
    """Human-readable pass table + occupancy block (the --passcope
    CLI print and tools/trace_report.py's device section)."""
    lines = []
    if dev is not None:
        if not dev.get("available"):
            lines.append("passcope: device trace unavailable — "
                         f"{dev.get('error')}")
        else:
            lines.append(f"passcope: device pass table "
                         f"({dev['total_ms']:.1f} ms device time, "
                         f"{dev['chunks_traced']} chunks traced)")
            lines.append(f"  {'pass':<18} {'ms':>10} {'share':>7}")
            for lbl, ph in dev["phases"].items():
                lines.append(f"  {lbl:<18} {ph['ms']:>10.2f} "
                             f"{100 * ph['frac']:>6.1f}%")
            lines.append(f"  {dev['residual_label']:<18} "
                         f"{dev['residual_ms']:>10.2f} "
                         f"{100 * dev['residual_frac']:>6.1f}%")
            if dev["rungs"]:
                rung = ", ".join(f"{k}={v['ms']:.1f}ms"
                                 for k, v in dev["rungs"].items())
                lines.append(f"  drain rungs: {rung}")
            if dev.get("runtime_ms"):
                lines.append(f"  (runtime scaffolding excluded: "
                             f"{dev['runtime_ms']:.1f} ms)")
            if not dev["ok"]:
                lines.append(
                    f"  WARNING: only "
                    f"{100 * dev['attributed_frac']:.1f}% attributed "
                    f"(floor {100 * MIN_ATTRIBUTED:.0f}%) — top "
                    "residual ops: " + ", ".join(
                        r["op"] for r in dev["residual_top"][:3]))
    if occ:
        lines.append(
            f"occupancy: waste_frac={occ['waste_frac']:.3f} "
            f"(events={occ['events']} over {occ['lane_steps']} "
            f"lane-steps, {occ['passes']} passes)")
        for lbl, r in occ["per_rung"].items():
            if r["passes"]:
                lines.append(
                    f"  rung {lbl:<8} passes={r['passes']:<8} "
                    f"width={r['width']:<7} batch={r['batch']} "
                    f"min_fill={r['min_fill']:.3f}")
    return "\n".join(lines)


# --- self-check ------------------------------------------------------------

def fixture_path():
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "tests", "data", "passcope_fixture.xplane.pb")


def self_check(path=None):
    """Decode the committed fixture xplane (hand-built varint records,
    tests/helpers/xplane_encode.py) and assert the pass-table schema —
    the CI simlint-job smoke (no jax; run via
    ``python tools/xplane_profile.py --self-check``)."""
    path = path or fixture_path()
    scopes = hlo_scope_map(path)
    selfs = device_self_times(path)
    assert scopes and selfs, f"fixture decoded empty: {path}"
    dev = attribute(selfs, scopes)
    assert set(dev["phases"]) <= set(PASS_LABELS), dev["phases"]
    assert all(_RUNG_RE.match(k) for k in dev["rungs"]), dev["rungs"]
    assert dev["ok"] and dev["attributed_frac"] >= MIN_ATTRIBUTED, dev
    assert dev["residual_label"] == RESIDUAL
    assert abs(sum(p["frac"] for p in dev["phases"].values())
               + dev["residual_frac"] - 1.0) < 0.01, dev
    # the expected fixture content, exactly (self-time math included:
    # the thunk parent's glue is runtime scaffolding, not
    # double-counted; copy.5 is the unscoped-HLO residual)
    assert dev["phases"]["drain"]["ms"] == 40.0, dev
    assert dev["phases"]["exchange"]["ms"] == 30.0, dev
    assert dev["phases"]["tcp.rx"]["ms"] == 20.0, dev
    assert dev["phases"]["advance"]["ms"] == 5.0, dev
    assert dev["residual_ms"] == 3.0, dev
    assert dev["runtime_ms"] == 2.0, dev
    assert dev["total_ms"] == 98.0, dev
    assert dev["attributed_frac"] == round(95 / 98, 4), dev
    assert dev["residual_top"][0]["op"] == "copy.5", dev
    assert dev["rungs"]["w512"]["ms"] == 90.0, dev
    # occupancy arithmetic, exactly
    occ = occupancy({"k32": (32, 10), "dense": (64, 2)},
                    events=200, batch=4)
    assert occ["lane_steps"] == 10 * 32 * 4 + 2 * 64 * 1, occ
    assert occ["passes"] == 12, occ
    assert occ["utilization"] == round(200 / 1408, 4), occ
    assert occ["waste_frac"] == round(1 - 200 / 1408, 4), occ
    assert occ["per_rung"]["k32"]["min_fill"] == round(1 / 32, 4), occ
    assert occ["per_rung"]["dense"]["min_fill"] == round(33 / 64, 4), occ
    sh = shard_occupancy([[10, 2], [2, 0]], [200, 40],
                         [("k32", 32), ("dense", 64)], 4)
    assert len(sh["per_shard"]) == 2 and sh["skew"] >= 1.0, sh
    print("passcope: self-check OK (decoder + attribution + occupancy)")
    return 0


def load_json(path):
    """Read a device_phases JSON a --passcope run wrote into its run
    dir (tools/trace_report.py merges it under the host phase table)."""
    with open(path) as f:
        return json.load(f)
