"""Determinism flight recorder: windowed state digests + run manifest.

Shadow's core contract — kept by this repro (PAPER.md; the fault
injector's "dual same-seed runs bit-identical, hosted children
included") — is deterministic discrete-event execution. Nothing else in
the repo continuously *verifies* that contract, and a broken guarantee
surfaces only as a silently different SimReport. This module turns
"the runs differ" into "window 412, section tcp, host 17": a cheap,
configurable-cadence recorder that hashes the engine's device state at
window-chunk boundaries (and at every fault boundary and at the end of
the run) and appends one JSON line per sample to a *digest chain* —
each record carries a running chain hash over everything before it, so
two chains are comparable record by record and the first divergent
window is pinned by `tools/divergence.py`.

What gets hashed, per record:

- every `engine.state.Hosts` array, pulled once from the device
  (`engine.checkpoint.named_leaves` — the same leaf set checkpoints
  serialize; one device→host transfer per cadence, nothing added to
  the compiled programs), grouped into named *sections* (event_queue,
  tcp, nic,
  outbox, rng, app, stats, ... — `engine.state.STATE_SECTIONS`);
- the hosted-channel op stream: the running hash of every op batch
  `hosting.runtime` applied and of every shim protocol request each
  hosted child issued (`hosting.shim`), so a divergence ATTRIBUTES to
  "the hosted child behaved differently" vs "the engine diverged";
- optionally (host count <= `host_detail`) one short digest per host
  row, so divergence reports name the first divergent host.

Dead-slot canonicalization: freed event-queue slots, outbox tails,
NIC-ring tails and closed socket rows legitimately retain stale bytes
that can differ between semantically identical runs (e.g. the sharded
vs single-chip exchange). `engine.window.canonicalize_state` zeroes
them host-side before hashing, so the digest chain is a statement
about LIVE state — identical across 1-chip and mesh runs, extending
test_parallel's v1≡v2 claim.

A companion ``<path>.manifest.json`` captures seed, scenario
fingerprint, engine config, CLI args, versions, platform and git rev,
so any two chains are comparable (and `tools/divergence.py --bisect`
can replay the runs at cadence 1 to pin the exact window).

Cheap when disabled: the module-level ``ENABLED`` boolean is the whole
cost (the obs.trace/obs.metrics contract); hot paths guard with
``if digest.ENABLED:``. Enabled cost is one state pull + one linear
hash pass per cadence, accounted as a ``digest.record`` span
(obs.trace) and ``digest.*`` metrics when those recorders are on.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

import numpy as np

ENABLED = False
RECORDER = None

# default cadence, in windows: one record per default-sized window
# chunk (EngineConfig.chunk_windows), so recording never forces extra
# host round-trips on a default run
DEFAULT_EVERY = 64

# per-host digests are recorded only up to this host count (the O(H)
# python-loop hashing is real at 100k hosts; section digests still
# localize divergence there). SHADOW_TPU_DIGEST_HOSTS overrides.
DEFAULT_HOST_DETAIL = 1024

_CHAIN_SEED = b"shadow_tpu.digest.v1"


def _hash_arrays(arrs: dict, H: int, host_detail: int):
    """-> (sections hex dict, per-host hex list or None, bytes hashed).

    `arrs` maps field name -> canonicalized [:H] numpy array, in
    engine.state.Hosts field order (insertion order preserved). Each
    section hash covers field name, dtype, shape and raw bytes, so a
    layout change can never alias a value change.
    """
    from ..engine.state import section_of

    sections = {}
    host_hashers = ([hashlib.blake2b(digest_size=4) for _ in range(H)]
                    if 0 < H <= host_detail else None)
    nbytes = 0
    for name, a in arrs.items():
        if a.size == 0:
            # zero-capacity column (a disabled config-gated feature,
            # e.g. netscope off allocates ns_hist with a zero bucket
            # axis): skip it entirely — header included — so chains
            # from disabled runs stay byte-identical to chains
            # recorded before the column existed. No enabled feature
            # allocates at zero (rings use max(cap, 1)), so a real
            # value change can never hide here.
            continue
        sec = sections.get(section_of(name))
        if sec is None:
            sec = sections[section_of(name)] = hashlib.blake2b(
                digest_size=8)
        sec.update(f"{name}:{a.dtype.str}:{a.shape}".encode())
        buf = np.ascontiguousarray(a)
        sec.update(buf)
        nbytes += buf.nbytes
        if host_hashers is not None:
            for i in range(H):
                host_hashers[i].update(buf[i])
    out = {k: h.hexdigest() for k, h in sorted(sections.items())}
    hosts_hex = ([h.hexdigest() for h in host_hashers]
                 if host_hashers is not None else None)
    return out, hosts_hex, nbytes


class DigestRecorder:
    """One digest chain. `path=None` collects in memory only (tests).
    `writer=False` runs the full cadence/chain state machine but never
    touches the filesystem — non-zero processes of a multi-process
    mesh stay in lockstep with process 0 (every process must agree on
    when a record is due, because the state pull is a collective)."""

    def __init__(self, path: str | None, every: int = DEFAULT_EVERY,
                 host_detail: int = None, context: dict = None,
                 writer: bool = True):
        self.path = path
        self.every = max(int(every), 1)
        if host_detail is None:
            host_detail = int(os.environ.get(
                "SHADOW_TPU_DIGEST_HOSTS", str(DEFAULT_HOST_DETAIL)))
        self.host_detail = host_detail
        # CLI context (argv, config path) folded into the manifest by
        # the installer — engine.sim fills the run-derived fields
        self.context = dict(context or {})
        self.records = []
        self.manifest = None
        self.bytes_hashed = 0
        self.writer = bool(writer)
        self._chain = _CHAIN_SEED
        self._file = None
        self._mode = "w"
        self.next_due = self.every

    # --- cadence ---
    def due(self, total_windows: int) -> bool:
        return total_windows >= self.next_due

    def begin_run(self, total_windows: int):
        """Re-arm the cadence for a (re)starting run. One recorder may
        span several runs (an outer harness extending one chain), but
        each run's window counter restarts at 0 — or jumps, on resume —
        so the clock left by a previous run's last record would
        suppress every cadence sample of the next run."""
        self.next_due = int(total_windows) + self.every

    @property
    def chain_hex(self) -> str:
        """Current running chain hash — stamped into checkpoints
        (engine.checkpoint ``__digest_chain__``) so rewind() can
        verify the kept prefix refolds to exactly the snapshot's
        position."""
        return self._chain.hex()

    def rewind(self, n_records: int, chain_hex: str = None):
        """Resume a chain a crashed attempt left behind: reload the
        chain file and keep EXACTLY the first `n_records` records —
        the count the checkpoint stamped at save time — dropping
        everything later (records past the snapshot die with the
        crash and are re-produced live by the resumed run; the
        determinism contract makes the kept prefix identical to what
        this run would have written). The kept prefix is refolded and
        verified against the snapshot's `chain_hex`; the cadence
        re-arms exactly as the uninterrupted run's was (every record
        sets next_due = its window + every). Later records APPEND to
        the truncated file: the final chain is byte-identical to an
        uninterrupted same-seed run's (tests/test_until_complete.py).

        A trailing torn line (the crash landed mid-write) never
        matters — it is past the kept count; a kept record that does
        not refold is a corrupted prefix and fails loud.

        Multi-process meshes: EVERY process runs rewind (all must
        refold the same prefix and re-arm the same cadence — the
        per-record state pull is a collective), reading the chain
        file over the same shared storage the snapshot came from;
        only the writer (process 0) truncates, via an atomic
        os.replace, so a peer reading concurrently sees the kept
        prefix either way. This is what lifted the PR 5
        resume+digest+multi-process gate."""
        n = max(int(n_records), 0)
        kept = []
        if self.path is not None and os.path.exists(self.path):
            assert self._file is None, "rewind() must precede records"
            with open(self.path) as f:
                lines = f.read().splitlines()
            for i, line in enumerate(lines):
                if len(kept) >= n:
                    break
                if not line.strip():
                    continue
                try:
                    kept.append(json.loads(line))
                except json.JSONDecodeError:
                    raise ValueError(
                        f"digest chain {self.path}: line {i + 1} is "
                        "corrupt inside the checkpointed prefix "
                        f"({len(kept)}/{n} records); refusing to "
                        "resume it")
        if len(kept) < n:
            raise ValueError(
                f"digest chain {self.path} holds {len(kept)} records "
                f"but the checkpoint was taken after {n} — the chain "
                "file does not belong to this run")
        chain = _CHAIN_SEED
        for rec in kept:
            body = {k: v for k, v in rec.items() if k != "chain"}
            payload = json.dumps(body, sort_keys=True,
                                 separators=(",", ":")).encode()
            chain = hashlib.blake2b(chain + payload,
                                    digest_size=16).digest()
            if rec.get("chain") != chain.hex():
                raise ValueError(
                    f"digest chain {self.path}: record at window "
                    f"{rec.get('window')} does not refold — the "
                    "prefix is corrupted; delete the chain and record "
                    "fresh")
        if chain_hex and chain.hex() != chain_hex:
            raise ValueError(
                f"digest chain {self.path}: the {n}-record prefix "
                "refolds to a different chain hash than the "
                "checkpoint stamped — chain and snapshot are from "
                "different runs")
        self._chain = chain
        self.records = kept
        if self.path is not None and self.writer:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                for rec in kept:
                    f.write(json.dumps(rec, sort_keys=True,
                                       separators=(",", ":")) + "\n")
            os.replace(tmp, self.path)
        self._mode = "a"
        self.next_due = ((kept[-1]["window"] + self.every) if kept
                         else self.every)

    # --- manifest ---
    def manifest_path(self) -> str | None:
        return self.path + ".manifest.json" if self.path else None

    def write_manifest(self, manifest: dict):
        """Record (and persist) the run manifest; first run wins when
        an outer harness holds the recorder open across runs."""
        if self.manifest is not None:
            return
        self.manifest = manifest
        mp = self.manifest_path()
        if mp is not None and self.writer:
            tmp = mp + ".tmp"
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
            os.replace(tmp, mp)

    # --- recording ---
    def record(self, hosts, H: int, window: int, sim_ns: int, kind: str,
               hosted: dict = None) -> dict:
        """Hash the device state into one chain record.

        `hosts` is the engine's Hosts pytree (its arrays are pulled to
        the host here — the one device→host transfer per cadence);
        `H` the true host count (mesh padding rows are sliced off so
        sharded chains match single-chip ones); `hosted` the
        hosting-runtime op-stream digests, when hosted apps exist.
        """
        from ..engine.checkpoint import named_leaves
        from ..engine.window import canonicalize_state

        arrs = {name: np.asarray(leaf)[:H]
                for name, leaf in named_leaves(hosts)}
        arrs = canonicalize_state(arrs)
        sections, hosts_hex, nbytes = _hash_arrays(arrs, H,
                                                   self.host_detail)
        self.bytes_hashed += nbytes
        rec = {"window": int(window), "sim_ns": int(sim_ns),
               "kind": kind, "sections": sections}
        if hosted is not None:
            rec["hosted"] = hosted
            h = hashlib.blake2b(
                json.dumps(hosted, sort_keys=True).encode(),
                digest_size=8)
            rec["sections"] = dict(sections, hosted=h.hexdigest())
        if hosts_hex is not None:
            rec["hosts"] = hosts_hex
        payload = json.dumps(rec, sort_keys=True,
                             separators=(",", ":")).encode()
        self._chain = hashlib.blake2b(self._chain + payload,
                                      digest_size=16).digest()
        rec["chain"] = self._chain.hex()
        self.records.append(rec)
        if self.path is not None and self.writer:
            if self._file is None:
                self._file = open(self.path, self._mode)
            self._file.write(json.dumps(rec, sort_keys=True,
                                        separators=(",", ":")) + "\n")
            self._file.flush()
        self.next_due = int(window) + self.every
        return rec

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


def build_manifest(scenario, cfg, seed: int, sh, host_names: list,
                   recorder: DigestRecorder, checkpoint_path: str = None,
                   shards: int = 1, pcap: bool = False,
                   faults: bool = False, hosted: bool = False) -> dict:
    """Everything needed to (a) decide two chains are comparable and
    (b) replay the run for bisection (tools/divergence.py)."""
    import platform as _platform
    import sys as _sys

    import jax

    from ..engine.checkpoint import scenario_fingerprint

    cfgd = dataclasses.asdict(cfg)
    if cfgd.get("app_kinds") is not None:
        cfgd["app_kinds"] = list(cfgd["app_kinds"])
    m = {
        "format": "shadow_tpu.digest.manifest", "version": 1,
        "seed": int(seed),
        "fingerprint": scenario_fingerprint(scenario, cfg, seed),
        "config_path": recorder.context.get(
            "config_path", getattr(scenario, "source_path", None)),
        "argv": recorder.context.get("argv"),
        "stop_time_ns": int(scenario.stop_time),
        "min_jump_ns": int(sh.min_jump),
        "tcp": {"cc_kind": int(sh.cc_kind),
                "init_wnd": float(sh.tcp_init_wnd),
                "ssthresh0": float(sh.tcp_ssthresh0)},
        "hosts": len(host_names),
        "host_names": (list(host_names)
                       if len(host_names) <= recorder.host_detail
                       else None),
        "engine_config": cfgd,
        "digest_every": recorder.every,
        "host_detail": recorder.host_detail,
        "shards": int(shards),
        # run modes that legitimately change digested state or gate
        # checkpoint replay: pcap drains the trace rings chunk-wise
        # (a pcap-only pair diverges in trace_ring — the manifest
        # delta says why), faults/hosted block --use-checkpoint
        "pcap": bool(pcap),
        "faults": bool(faults),
        "hosted": bool(hosted),
        "platform": jax.default_backend(),
        "versions": {"python": _sys.version.split()[0],
                     "jax": jax.__version__,
                     "numpy": np.__version__,
                     "os": _platform.platform()},
        "git_rev": _git_rev(),
        "checkpoint_path": checkpoint_path,
    }
    return m


def _git_rev() -> str | None:
    import subprocess
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return out.stdout.strip() or None
    except Exception:
        return None


def install(path: str | None, every: int = DEFAULT_EVERY,
            host_detail: int = None, context: dict = None,
            writer: bool = True) -> DigestRecorder:
    """Enable digest recording process-wide; the installer owns
    finish() (the obs.trace/obs.metrics contract). `writer=False`
    keeps the full recorder state machine but never writes files —
    the non-zero processes of a multi-process mesh."""
    global ENABLED, RECORDER
    RECORDER = DigestRecorder(path, every=every, host_detail=host_detail,
                              context=context, writer=writer)
    ENABLED = True
    return RECORDER


def finish() -> DigestRecorder | None:
    """Disable recording, close the chain file, return the recorder."""
    global ENABLED, RECORDER
    rec, RECORDER, ENABLED = RECORDER, None, False
    if rec is not None:
        rec.close()
    return rec
