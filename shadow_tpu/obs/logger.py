"""Leveled simulation logger.

The reference runs an async buffered logger on a helper pthread with
per-host level overrides (/root/reference/src/main/core/logger/
shd-logger.c:26-152, 100-120). Here log records originate on the host
side only (the device reports through counters, not strings), so the
async machinery reduces to a leveled, optionally host-filtered writer
with the reference's timestamp style:

    wall [shadow-tpu] sim-time [level] [host] message
"""

from __future__ import annotations

import sys
import time

LEVELS = {"error": 0, "critical": 0, "warning": 1, "message": 2,
          "info": 3, "debug": 4}
DEFAULT_LEVEL = "message"


def _fmt_simtime(ns: int) -> str:
    s, rem = divmod(int(ns), 10**9)
    h, rem2 = divmod(s, 3600)
    m, sec = divmod(rem2, 60)
    return f"{h}:{m:02d}:{sec:02d}.{rem:09d}"


class SimLogger:
    def __init__(self, level: str = DEFAULT_LEVEL, stream=None):
        self.level = LEVELS.get(level, 2)
        self.host_levels = {}       # host name -> numeric level
        self.stream = stream or sys.stdout
        self._t0 = time.time()
        self.counts = dict.fromkeys(LEVELS, 0)

    def set_host_level(self, host: str, level: str):
        """Per-host override (reference: <host loglevel=...>)."""
        self.host_levels[host] = LEVELS.get(level, 2)

    def log(self, level: str, sim_ns: int, host: str, msg: str):
        n = LEVELS.get(level, 2)
        self.counts[level] = self.counts.get(level, 0) + 1
        limit = self.host_levels.get(host, self.level)
        if n > limit:
            return
        wall = time.time() - self._t0
        wm, ws = divmod(wall, 60.0)
        self.stream.write(
            f"{int(wm):02d}:{ws:09.6f} [shadow-tpu] "
            f"{_fmt_simtime(sim_ns)} [{level}] [{host}] {msg}\n")

    def error(self, sim_ns, host, msg):
        self.log("error", sim_ns, host, msg)
        raise RuntimeError(f"[{host}] {msg}")

    def warning(self, sim_ns, host, msg):
        self.log("warning", sim_ns, host, msg)

    def message(self, sim_ns, host, msg):
        self.log("message", sim_ns, host, msg)

    def info(self, sim_ns, host, msg):
        self.log("info", sim_ns, host, msg)

    def debug(self, sim_ns, host, msg):
        self.log("debug", sim_ns, host, msg)
