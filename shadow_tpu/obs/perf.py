"""Per-phase wall-cost attribution: every wall-millisecond named.

The reference tracks per-component cost continuously (tracker
heartbeats, shd-tracker.c:266; scheduler barrier self-times,
shd-scheduler.c:250-252) but never answers "what fraction of this
run's wall went to which engine phase". Here the trace recorder
(obs.trace) already spans every phase the host-side loop executes —
setup, the cold XLA compile, each compiled window chunk, hosted-app
steps, pcap drains, tracker heartbeats, checkpoint saves, digest
records, fault applications, report finalization — so attribution is
pure span arithmetic: per-span SELF-time (total minus directly nested
children, the same stack walk tools/trace_report.py uses), mapped
through :data:`PHASE_OF` into a small set of named phases, compared
against the run's measured wall.

The contract the perf tooling builds on (tools/perf_report.py,
docs/performance.md): phases must sum to >= :data:`MIN_ATTRIBUTED`
of the measured wall or the report labels the residual explicitly —
"93% attributed, 7% unattributed (host loop glue)" is an answer;
a silent gap is not.

Everything here is host-side and read-only: attribution never touches
device state, so a ``--perf`` run's digest chain is byte-identical to
a plain run's (asserted by tests/test_perf.py).
"""

from __future__ import annotations

from collections import defaultdict

# span name -> phase name. Spans not listed attribute under their own
# name (visible, never silently dropped); the residual bucket below is
# only for wall time NO span covered.
PHASE_OF = {
    "run.setup": "setup",            # topology/mesh placement, writers
    "compile+first_chunk": "compile",  # cold XLA build (+ 1st chunk)
    "chunk": "window",               # compiled drain+exchange chunks
    "hosting.step": "hosting",       # hosted-app CPU tier per window
    "pcap.drain": "pcap",
    "tracker.heartbeat": "tracker",
    "checkpoint.save": "checkpoint",
    "digest.record": "digest",
    "faults.apply": "faults",
    "report.finalize": "finalize",
    "build": "setup",
    # AOT executable cache (serving.aotcache, PR 13): the real XLA
    # build vs a persistent-cache load. Both nest inside the first
    # chunk's compile+first_chunk span, whose SELF time (first-chunk
    # execution + dispatch glue) stays under "compile" — so a phase
    # map now states mechanically whether "cold" paid a compile
    # (compile-miss > 0) or opened warm from disk (compile-hit only).
    # tools/perf_regress.py's compile-bound exemption reads
    # compile-miss when present: a cache-hit run is gateable.
    "jitcache.compile": "compile-miss",
    "jitcache.load": "compile-hit",
    # memory observatory (obs.memscope, PR 15): the per-executable
    # XLA cost/memory-analysis capture that AotJit runs right after
    # materializing a program. Kept OUT of compile-miss on purpose —
    # analysis wall is observatory overhead, not the XLA build.
    "memscope.analyze": "memscope",
}

RESIDUAL = "unattributed (host loop glue)"

# the attribution-quality floor: below this the report flags itself
MIN_ATTRIBUTED = 0.90


def self_times(events) -> dict:
    """Per span name: [count, total_us, self_us]. Self-time excludes
    directly nested child spans per (pid, tid) track — the standard
    sort-and-stack walk (an enclosing span sorts before the spans it
    contains via (ts, -dur))."""
    agg = {}
    tracks = defaultdict(list)
    for e in events:
        if e.get("ph") != "X":
            continue
        tracks[(e.get("pid", 0), e.get("tid", 0))].append(e)
    for evs in tracks.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []  # [end_ts, child_sum_us, name, dur_us]

        def close(upto):
            while stack and stack[-1][0] <= upto + 1e-9:
                end, child, name, dur = stack.pop()
                a = agg.setdefault(name, [0, 0.0, 0.0])
                a[0] += 1
                a[1] += dur
                a[2] += max(dur - child, 0.0)
                if stack:
                    stack[-1][1] += dur

        for e in evs:
            close(e["ts"])
            stack.append([e["ts"] + e["dur"], 0.0, e["name"], e["dur"]])
        close(float("inf"))
    return agg


def attribute(events, wall_s: float, n_events: int = None) -> dict:
    """Attribute `wall_s` seconds of run wall to named phases from the
    trace `events` (Chrome trace-event dicts, obs.trace format).

    Returns::

        {"wall_s": ..., "events": ...,
         "phases": {phase: {"wall_s", "frac", "count",
                            "us_per_event"?}},   # sorted by wall desc
         "attributed_s": ..., "attributed_frac": ...,
         "residual_s": ..., "residual_frac": ...,
         "residual_label": RESIDUAL,
         "ok": attributed_frac >= MIN_ATTRIBUTED}

    `n_events` (simulated events executed) adds a per-event cost to
    each phase — "what does one simulated event pay this phase".
    """
    agg = self_times(events)
    walls = defaultdict(float)
    counts = defaultdict(int)
    for name, (c, total, self_us) in agg.items():
        phase = PHASE_OF.get(name, name)
        walls[phase] += self_us / 1e6
        counts[phase] += c
    attributed = sum(walls.values())
    # spans can slightly overlap the measured wall (perf_counter noise,
    # spans opened before wall0); clamp so fractions stay sane
    residual = max(wall_s - attributed, 0.0)
    phases = {}
    for phase in sorted(walls, key=lambda p: -walls[p]):
        row = {"wall_s": round(walls[phase], 6),
               "frac": round(walls[phase] / wall_s, 4) if wall_s else 0.0,
               "count": counts[phase]}
        if n_events:
            row["us_per_event"] = round(walls[phase] * 1e6 / n_events, 3)
        phases[phase] = row
    frac = min(attributed / wall_s, 1.0) if wall_s else 0.0
    out = {
        "wall_s": round(wall_s, 6),
        "phases": phases,
        "attributed_s": round(min(attributed, wall_s), 6),
        "attributed_frac": round(frac, 4),
        "residual_s": round(residual, 6),
        "residual_frac": round(residual / wall_s, 4) if wall_s else 0.0,
        "residual_label": RESIDUAL,
        "ok": frac >= MIN_ATTRIBUTED,
    }
    if n_events is not None:
        out["events"] = int(n_events)
    return out


def publish(attribution: dict, registry) -> None:
    """Expose an attribution as ``perf.*`` gauges (obs.metrics): one
    ``perf.phase.<name>_s`` per phase plus the attributed fraction —
    so metrics.json carries the same breakdown the report prints."""
    for phase, row in attribution["phases"].items():
        key = phase.split(" ")[0]  # gauge-safe
        registry.gauge(f"perf.phase.{key}_s").set(row["wall_s"])
    registry.gauge("perf.attributed_frac").set(
        attribution["attributed_frac"])
    registry.gauge("perf.residual_s").set(attribution["residual_s"])


def format_report(attribution: dict) -> str:
    """Human-readable phase table (the --perf CLI output)."""
    lines = [f"== perf: phase attribution "
             f"({attribution['attributed_frac'] * 100:.1f}% of "
             f"{attribution['wall_s']:.3f}s wall attributed) =="]
    lines.append(f"{'phase':<12} {'wall_s':>10} {'frac':>7} "
                 f"{'count':>7} {'us/event':>10}")
    for phase, row in attribution["phases"].items():
        upe = row.get("us_per_event")
        lines.append(
            f"{phase:<12} {row['wall_s']:>10.3f} "
            f"{row['frac'] * 100:>6.1f}% {row['count']:>7} "
            f"{upe if upe is not None else '-':>10}")
    lines.append(
        f"{'residual':<12} {attribution['residual_s']:>10.3f} "
        f"{attribution['residual_frac'] * 100:>6.1f}%    "
        f"<- {attribution['residual_label']}")
    if not attribution["ok"]:
        lines.append(
            f"WARNING: only {attribution['attributed_frac'] * 100:.1f}% "
            f"of the wall is attributed (floor "
            f"{MIN_ATTRIBUTED * 100:.0f}%) — the unattributed "
            "remainder is host-side time between spans")
    return "\n".join(lines)
