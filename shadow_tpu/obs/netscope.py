"""Network observatory: device-side latency histograms, a per-window
network time-series stream, and ensemble percentile curves.

The engine's stats table (``Hosts.stats``, [H, N_STATS] of per-host
sums) can only ever yield means — the reference Shadow's heartbeats
and tgen reports carry full distributions, and cross-seed sweeps need
percentile *curves*, not N separate means. Netscope closes that gap in
three tiers:

1. **Device-side streaming histograms** — ``Hosts.ns_hist``
   ([H, NS_KINDS, NS_BUCKETS] i64) counts samples into fixed
   power-of-two microsecond buckets at the existing measurement sites
   (the ST_RTT_SUM_US update points, app completion paths, NIC queue
   admit, TCP retransmit), inside the jitted passes. O(1) work and
   O(buckets) bytes per host, fully deterministic, and opt-in: with
   ``EngineConfig.netscope`` off the bucket axis is allocated at ZERO
   so shapes, digests and checkpoints of existing runs are untouched
   (``observe`` is a static no-op the compiler never sees).

2. **Per-window time-series** — the :class:`NetScope` recorder samples
   network health (stat totals + deltas, active connections, histogram
   deltas) at every window-chunk boundary into a JSONL stream beside
   the tracker heartbeat. Every value derives from device state and
   sim time only, so same-seed runs produce byte-identical streams.

3. **Ensemble aggregation** — under ``serving/batch.py`` vmapped lanes
   the accumulator is [lanes, H, NS_KINDS, NS_BUCKETS] for free;
   :func:`fold`/:func:`ensemble` reduce any nesting of per-run tables
   into pooled percentiles, per-lane tails and a CDF curve
   (``fleet status --ensemble``, ``tools/netreport.py``).

Bucket scheme: integer power-of-two microsecond ladder. Bucket 0 holds
values < 1 µs, bucket i (1..30) holds [2^(i-1), 2^i) µs, bucket 31 is
the overflow (>= 2^30 µs ≈ 17.9 min). Bucketing is a comparison count
against integer bounds — no logs, no floats — so device and host
agree bit-for-bit on every platform.

Module-level imports are stdlib-only (the memscope convention): tools
and tests may load this file standalone; jax is imported lazily inside
:func:`observe`.
"""

from __future__ import annotations

import json

# kind indices into the ns_hist kind axis (order is the wire format:
# the JSONL `hist` tables and the metrics `net` section use it)
NS_RTT = 0         # round-trip / one-way propagation time (µs)
NS_COMPLETION = 1  # client-observed transfer/fetch completion (µs)
NS_QUEUE = 2       # NIC rx-queue delay at admit (µs)
NS_RETX = 3        # RTO in force at each retransmission (µs)
NS_KINDS = 4
NS_BUCKETS = 32
KIND_NAMES = ("rtt", "completion", "queue", "retx")

# power-of-two µs bucket bounds: value v lands in bucket
# sum(v >= BOUNDS_US) — 31 bounds, 32 buckets, overflow at >= 2^30 µs
BOUNDS_US = tuple(1 << k for k in range(NS_BUCKETS - 1))

FORMAT = "shadow_tpu.netscope.v1"


def observe(row, kind: int, value_us, on=True):
    """Count one sample into ``row.ns_hist[kind]`` inside a jitted
    row handler. ``value_us`` is an integer (or traced i64) number of
    microseconds; ``on`` may be a traced predicate — a False sample
    adds zero (the increment happens either way, keeping the pass
    shape fixed). With the netscope knob off the bucket axis has zero
    capacity and this returns ``row`` untouched — a *static* no-op, so
    disabled runs compile the exact pre-netscope program."""
    if row.ns_hist.shape[-1] == 0:
        return row
    import jax.numpy as jnp
    v = jnp.asarray(value_us, jnp.int64)
    idx = jnp.sum((v >= jnp.asarray(BOUNDS_US, jnp.int64))
                  .astype(jnp.int32))
    inc = jnp.where(on, jnp.int64(1), jnp.int64(0))
    return row.replace(ns_hist=row.ns_hist.at[kind, idx].add(inc))


def bucket_of(value_us: int) -> int:
    """Host-side mirror of the device bucketing (pyengine, tests):
    same integer ladder, same answer for every value."""
    v = int(value_us)
    if v <= 0:
        return 0
    return min(v.bit_length(), NS_BUCKETS - 1)


def bucket_edge_us(i: int) -> int:
    """Upper edge of bucket i in µs (the overflow bucket reports the
    saturated edge 2^31 — consumers treat it as 'off the ladder')."""
    return 1 << min(int(i), NS_BUCKETS - 1)


def _tolist(h):
    return h.tolist() if hasattr(h, "tolist") else h


def _add(a, b):
    if isinstance(a, list):
        return [_add(x, y) for x, y in zip(a, b)]
    return a + b


def fold(hist):
    """Sum any leading axes of a histogram down to one
    [NS_KINDS][NS_BUCKETS] table of ints: accepts [K][B] (already a
    table), [H][K][B] (one run's per-host device state), [N][K][B]
    (per-run tables) or [L][H][K][B] (vmapped lanes) — pure python,
    works on numpy/jax arrays (via tolist) and nested lists alike."""
    h = _tolist(hist)
    if not h or not h[0]:
        return []
    while h[0] and isinstance(h[0][0], list):
        acc = h[0]
        for t in h[1:]:
            acc = _add(acc, t)
        h = acc
    return [[int(c) for c in r] for r in h]


def percentile(counts, q: int) -> int:
    """Exact percentile read-out from one bucket row: the upper edge
    (µs) of the smallest bucket whose cumulative count reaches
    ceil(q/100 · N). Pure integer math — no interpolation, so two
    hosts computing it from the same counts always agree. Returns 0
    for an empty row."""
    counts = [int(c) for c in counts]
    n = sum(counts)
    if n <= 0:
        return 0
    rank = max(1, -((-n * q) // 100))
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= rank:
            return 1 << i
    return 1 << (NS_BUCKETS - 1)


def kind_summary(counts) -> dict:
    """One kind's headline figures + raw buckets."""
    counts = [int(c) for c in counts]
    return {
        "count": sum(counts),
        "p50_us": percentile(counts, 50),
        "p90_us": percentile(counts, 90),
        "p99_us": percentile(counts, 99),
        "buckets": counts,
    }


def report(hist) -> dict:
    """SimReport.network payload from a final histogram (any nesting
    :func:`fold` accepts)."""
    table = fold(hist)
    if not table:
        return {}
    return {
        "bounds_us": list(BOUNDS_US),
        "kinds": {name: kind_summary(table[k])
                  for k, name in enumerate(KIND_NAMES)},
    }


def ensemble(tables) -> dict:
    """Cross-run (or cross-lane) percentile curves: pooled
    distribution + per-run tails per kind. ``tables`` is a list of
    per-run histograms (each any nesting :func:`fold` accepts)."""
    tables = [fold(t) for t in tables]
    tables = [t for t in tables if t]
    if not tables:
        return {}
    pooled = fold(tables)
    out = {"runs": len(tables), "bounds_us": list(BOUNDS_US),
           "kinds": {}}
    for k, name in enumerate(KIND_NAMES):
        tot = sum(pooled[k])
        cum, cdf = 0, []
        for c in pooled[k]:
            cum += c
            cdf.append(round(cum / tot, 6) if tot else 0.0)
        out["kinds"][name] = {
            "count": tot,
            "p50_us": percentile(pooled[k], 50),
            "p90_us": percentile(pooled[k], 90),
            "p99_us": percentile(pooled[k], 99),
            "lane_p50_us": [percentile(t[k], 50) for t in tables],
            "lane_p99_us": [percentile(t[k], 99) for t in tables],
            "cdf": cdf,
            "buckets": pooled[k],
        }
    return out


class NetScope:
    """Per-window network time-series recorder.

    Fed at every window-chunk boundary with the current cumulative
    device state; keeps records in memory (``.records``) and, given a
    path, streams them as JSON lines (compact, sorted keys — the
    dual-run byte-identity contract). The first line is a header
    carrying the format tag, kind names and bucket bounds so the
    stream is self-describing."""

    def __init__(self, path: str | None = None, writer: bool = True):
        from ..engine import defs as _d
        self._stat_cols = (
            ("events", _d.ST_EVENTS),
            ("pkts_sent", _d.ST_PKTS_SENT),
            ("pkts_recv", _d.ST_PKTS_RECV),
            ("bytes_sent", _d.ST_BYTES_SENT),
            ("bytes_recv", _d.ST_BYTES_RECV),
            ("retransmits", _d.ST_RETRANSMIT),
            ("drop_net", _d.ST_PKTS_DROP_NET),
            ("drop_buf", _d.ST_PKTS_DROP_BUF),
            ("xfers_done", _d.ST_XFER_DONE),
        )
        self.path = path if writer else None
        self.records = []
        self._prev_tot = None
        self._prev_hist = None
        self._last_table = None
        self._fh = None
        if self.path:
            self._fh = open(self.path, "w")
            self._write({"format": FORMAT, "kinds": list(KIND_NAMES),
                         "bounds_us": list(BOUNDS_US)})

    def _write(self, obj: dict):
        self._fh.write(json.dumps(obj, sort_keys=True,
                                  separators=(",", ":")) + "\n")
        self._fh.flush()

    def sample(self, window: int, sim_ns: int, hist, stats,
               conns: int | None = None):
        """One chunk-boundary sample. ``hist`` is the cumulative
        device histogram (any :func:`fold` nesting), ``stats`` the
        cumulative [H, N_STATS] table; both arrive as numpy. Every
        emitted value is cumulative-or-delta of device state — no
        wall-clock anywhere, by contract."""
        table = fold(hist)
        tot = {name: int(stats[:, col].sum())
               for name, col in self._stat_cols}
        prev_t = self._prev_tot or {k: 0 for k in tot}
        prev_h = (self._prev_hist or
                  [[0] * len(r) for r in table])
        rec = {
            "window": int(window),
            "sim_ns": int(sim_ns),
            "totals": tot,
            "delta": {k: tot[k] - prev_t[k] for k in tot},
            "hist": table,
            "hist_delta": [[a - b for a, b in zip(ra, rb)]
                           for ra, rb in zip(table, prev_h)],
        }
        if conns is not None:
            rec["conns"] = int(conns)
        self._prev_tot, self._prev_hist = tot, table
        self._last_table = table
        self.records.append(rec)
        if self._fh:
            self._write(rec)

    def summary(self) -> dict:
        """:func:`report` of the latest sampled histogram."""
        return report(self._last_table) if self._last_table else {}

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None


def read_stream(path: str) -> tuple[dict, list]:
    """Parse a netscope JSONL stream -> (header, records). Tolerates a
    missing header (synthesizes one) so partial streams still fold."""
    header = {"format": FORMAT, "kinds": list(KIND_NAMES),
              "bounds_us": list(BOUNDS_US)}
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if "format" in obj:
                header = obj
            else:
                records.append(obj)
    return header, records


def publish(registry, network: dict):
    """Publish a :func:`report` payload as ``net.*`` gauges — the
    metrics.json ``net`` section (obs.metrics assembles the
    ``bucket.<i>`` families back into lists via _assemble_indexed,
    parity with the perf/memory sections)."""
    for name, k in (network or {}).get("kinds", {}).items():
        registry.gauge(f"net.{name}.count").set(int(k["count"]))
        registry.gauge(f"net.{name}.p50_us").set(int(k["p50_us"]))
        registry.gauge(f"net.{name}.p90_us").set(int(k["p90_us"]))
        registry.gauge(f"net.{name}.p99_us").set(int(k["p99_us"]))
        for i, c in enumerate(k.get("buckets", ())):
            if c:
                registry.gauge(f"net.{name}.bucket.{i}").set(int(c))
