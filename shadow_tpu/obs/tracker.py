"""Heartbeat tracker: periodic per-host and global metrics.

The reference's Tracker emits `[shadow-heartbeat] [node|socket|ram]`
CSV-ish lines per host on a configurable interval
(/root/reference/src/main/host/shd-tracker.c:405-592) plus a slave-level
getrusage heartbeat (shd-slave.c:374-395). The TPU engine already keeps
every metric as device-side counters (Hosts.stats); the tracker drains
them at window-chunk boundaries, computes interval deltas, and emits the
same style of lines — no device-side cost beyond the stats the engine
maintains anyway.

Line families (mirroring shd-tracker.c):

- ``[node]``   per-host interval deltas of the engine counters
  (shd-tracker.c:405-447's per-interval counter deltas).
- ``[socket]`` per-host, ``|``-joined per-socket segments
  ``slot,proto,peer:port;inbuflen,inbufsize,outbuflen,outbufsize;``
  ``recv-bytes,send-bytes`` (shd-tracker.c:449-537). Buffer fill maps
  to the offset model: out fill = written-but-unacked bytes
  (snd_end - snd_una), in fill = out-of-order bytes held in the
  receive scoreboard; recv/send byte totals are the stream offsets.
- ``[ram]``    per-host ``alloc,dealloc,total,sockets`` where total is
  the modeled buffered bytes (the engine has no malloc to track —
  shd-tracker.c:539-546's allocated-RAM role is carried by buffer
  occupancy) and alloc/dealloc are the interval's growth/release.
- ``[summary]`` slave-level getrusage roll-up (shd-slave.c:374-395).

Sampling note: stats are only observable at window-chunk boundaries,
so when several intervals elapse within one chunk the tracker emits
ONE heartbeat at the last elapsed boundary covering the whole span
(the interval column carries the true span seconds) instead of one
real delta followed by empty duplicates.
"""

from __future__ import annotations

import resource

import numpy as np

from ..engine import defs
from . import metrics as _MT


HEADER = ("time,host,interval,events,pkts-sent,pkts-recv,bytes-sent,"
          "bytes-recv,retransmits,drop-net,drop-buf,transfers-done")


class Tracker:
    def __init__(self, interval_ns: int, host_names, logger=None,
                 per_host: bool = True):
        self.interval = int(interval_ns)
        self.names = list(host_names)
        self.logger = logger
        self.per_host = per_host
        self.next_ns = self.interval
        self._prev = None
        self._prev_ram = None
        self.lines = []          # retained for tools/tests

    def _emit(self, line: str):
        self.lines.append(line)
        if _MT.ENABLED:
            _MT.REGISTRY.counter("tracker.lines").inc()
        if self.logger is not None:
            self.logger.message(self.next_ns, "tracker", line)

    def due(self, sim_ns: int) -> bool:
        """Will maybe_heartbeat emit anything at this time? Lets the
        caller skip fetching stats (a cross-process all-gather on a
        multi-process mesh) when no interval boundary has passed."""
        return self.interval > 0 and sim_ns >= self.next_ns

    def maybe_heartbeat(self, sim_ns: int, stats: np.ndarray,
                        socks: dict | None = None,
                        hosted_rss: dict | None = None,
                        dev_peak: int | None = None,
                        waste: float | None = None):
        """Called after each window chunk with current cumulative stats;
        emits one heartbeat covering all interval boundaries elapsed
        since the last call (see module docstring on sampling).

        socks: optional dict of per-socket numpy columns (sk_used,
        sk_proto, sk_rhost, sk_rport, sk_snd_una, sk_snd_end,
        sk_sndbuf, sk_rcv_nxt, sk_rcvbuf, ooo_held) enabling the
        [socket] and [ram] line families.

        hosted_rss: optional host_id -> resident-set bytes of the
        host's live hosted child (hosting.runtime.child_rss). Rides
        the [ram] line as a trailing ``rss=`` column — real process
        memory next to the modeled buffer bytes, the reference's
        tracker-RSS role (shd-tracker.c:266).

        dev_peak: optional device-buffer high-water bytes
        (obs.memscope.Watermark — the allocator peak on device
        backends, process RSS on CPU). Rides every [ram] line as a
        trailing ``dev=`` column: the REAL buffer watermark beside
        the modeled per-host bytes. Process/device-global, so the
        value repeats per line by design (the [ram] family is the
        per-host view; consumers take any one).

        waste: optional cumulative wasted-lane fraction of the
        drain's gathered lanes so far (obs.passcope.occupancy).
        Rides the [summary] line as a ``waste=`` column — the
        lockstep-efficiency trend beside the throughput columns.
        """
        if self.interval <= 0 or sim_ns < self.next_ns:
            return
        # collapse all elapsed boundaries into one emission at the last
        elapsed = (sim_ns - self.next_ns) // self.interval + 1
        self.next_ns += (elapsed - 1) * self.interval
        # true covered span in seconds ("%g": sub-second intervals must
        # not truncate to 0 — consumers compute rates as delta/interval)
        span_s = f"{elapsed * self.interval / 1e9:g}"

        cur = stats.astype(np.int64)
        prev = (self._prev if self._prev is not None
                else np.zeros_like(cur))
        d = cur - prev
        self._prev = cur.copy()
        t = self.next_ns // 10**9

        if self.per_host:
            for i, name in enumerate(self.names):
                if d[i, defs.ST_EVENTS] == 0:
                    continue
                # the covered-span column keeps per-host rates
                # computable when several intervals collapse into one
                # chunk-boundary emission (rate = delta / interval)
                self._emit(
                    f"[shadow-heartbeat] [node] {t},{name},{span_s},"
                    f"{d[i, defs.ST_EVENTS]},"
                    f"{d[i, defs.ST_PKTS_SENT]},"
                    f"{d[i, defs.ST_PKTS_RECV]},"
                    f"{d[i, defs.ST_BYTES_SENT]},"
                    f"{d[i, defs.ST_BYTES_RECV]},"
                    f"{d[i, defs.ST_RETRANSMIT]},"
                    f"{d[i, defs.ST_PKTS_DROP_NET]},"
                    f"{d[i, defs.ST_PKTS_DROP_BUF]},"
                    f"{d[i, defs.ST_XFER_DONE]}")
        if socks is not None:
            self._heartbeat_sockets(t, span_s, socks, hosted_rss,
                                    dev_peak)

        ru = resource.getrusage(resource.RUSAGE_SELF)
        tot = d.sum(axis=0)
        # dev-peak-gib: the device-buffer watermark (obs.memscope) on
        # every summary heartbeat — scenarios whose hosts buffer
        # nothing (no [ram] lines) still report the measured high
        # water this way
        dev = (f"dev-peak-gib={dev_peak / (1 << 30):.3f},"
               if dev_peak else "")
        # waste=: cumulative lockstep lane waste (obs.passcope) — the
        # occupancy trend per heartbeat, same optional-column pattern
        # as dev-peak-gib
        wst = f"waste={waste:.4f}," if waste is not None else ""
        self._emit(
            f"[shadow-heartbeat] [summary] {t},"
            f"interval={span_s},"
            f"events={tot[defs.ST_EVENTS]},"
            f"pkts={tot[defs.ST_PKTS_SENT]}/{tot[defs.ST_PKTS_RECV]},"
            f"bytes={tot[defs.ST_BYTES_SENT]}/{tot[defs.ST_BYTES_RECV]},"
            f"{dev}{wst}"
            f"maxrss-gib={ru.ru_maxrss / (1 << 20):.3f},"
            f"utime-min={ru.ru_utime / 60:.3f},"
            f"stime-min={ru.ru_stime / 60:.3f}")
        if _MT.ENABLED:
            # heartbeats surface through the registry too: the metrics
            # snapshot shows how many fired and the interval-delta
            # totals without parsing the text lines
            reg = _MT.REGISTRY
            reg.counter("tracker.heartbeats").inc()
            reg.counter("tracker.events").inc(int(tot[defs.ST_EVENTS]))
            reg.gauge("tracker.last_sim_ns").set(int(self.next_ns))
        self.next_ns += self.interval

    def _heartbeat_sockets(self, t: int, span_s: str, socks: dict,
                           hosted_rss: dict | None = None,
                           dev_peak: int | None = None):
        used = socks["sk_used"]
        proto = socks["sk_proto"]
        is_tcp = proto == 6
        # buffer fill is a TCP notion here: UDP datagrams leave the
        # socket at txq-push (snd_una never advances for UDP, so
        # snd_end - snd_una would read as an ever-growing "leak")
        out_fill = np.where(
            is_tcp,
            np.maximum(socks["sk_snd_end"] - socks["sk_snd_una"], 0), 0)
        in_fill = socks["ooo_held"]
        # cumulative send-bytes: acked stream offset for TCP, datagram
        # bytes handed to the NIC for UDP
        sent_bytes = np.where(is_tcp, socks["sk_snd_una"],
                              socks["sk_snd_end"])
        # modeled RAM per host: all buffered bytes across sockets
        ram_total = (np.where(used, out_fill + in_fill, 0)).sum(axis=1)
        prev_ram = (self._prev_ram if self._prev_ram is not None
                    else np.zeros_like(ram_total))
        ram_delta = ram_total - prev_ram
        self._prev_ram = ram_total.copy()

        for i, name in enumerate(self.names):
            (slots,) = np.nonzero(used[i])
            if slots.size:
                segs = []
                for s in slots:
                    pname = "tcp" if proto[i, s] == 6 else "udp"
                    rh = int(socks["sk_rhost"][i, s])
                    peer = (f"{self.names[rh]}:{int(socks['sk_rport'][i, s])}"
                            if 0 <= rh < len(self.names) else "-:0")
                    segs.append(
                        f"{int(s)},{pname},{peer};"
                        f"{int(in_fill[i, s])},"
                        f"{int(socks['sk_rcvbuf'][i, s])},"
                        f"{int(out_fill[i, s])},"
                        f"{int(socks['sk_sndbuf'][i, s])};"
                        f"{int(socks['sk_rcv_nxt'][i, s])},"
                        f"{int(sent_bytes[i, s])}")
                self._emit(f"[shadow-heartbeat] [socket] {t},{name},"
                           + "|".join(segs))
            rss = (hosted_rss or {}).get(i)
            if ram_total[i] or ram_delta[i] or rss is not None:
                alloc = max(int(ram_delta[i]), 0)
                dealloc = max(-int(ram_delta[i]), 0)
                # trailing rss= column: the hosted child's REAL
                # resident set beside the modeled buffer bytes (only
                # hosts running a live hosted process carry it); dev=
                # is the device-buffer watermark (obs.memscope) — the
                # measured high-water mark beside the modeled bytes
                suffix = f",rss={int(rss)}" if rss is not None else ""
                if dev_peak:
                    suffix += f",dev={int(dev_peak)}"
                self._emit(
                    f"[shadow-heartbeat] [ram] {t},{name},"
                    f"{alloc},{dealloc},{int(ram_total[i])},"
                    f"{int(used[i].sum())}{suffix}")


def socket_columns(hosts) -> dict:
    """Extract the tracker's per-socket columns from device state as
    numpy arrays (one transfer per heartbeat, not per window)."""
    ooo_held = np.maximum(
        np.where(np.asarray(hosts.sk_ooo_s) >= 0,
                 np.asarray(hosts.sk_ooo_e) - np.asarray(hosts.sk_ooo_s),
                 0), 0).sum(axis=-1)
    return {
        "sk_used": np.asarray(hosts.sk_used),
        "sk_proto": np.asarray(hosts.sk_proto),
        "sk_rhost": np.asarray(hosts.sk_rhost),
        "sk_rport": np.asarray(hosts.sk_rport),
        "sk_snd_una": np.asarray(hosts.sk_snd_una),
        "sk_snd_end": np.asarray(hosts.sk_snd_end),
        "sk_sndbuf": np.asarray(hosts.sk_sndbuf),
        "sk_rcv_nxt": np.asarray(hosts.sk_rcv_nxt),
        "sk_rcvbuf": np.asarray(hosts.sk_rcvbuf),
        "ooo_held": ooo_held,
    }
