"""Heartbeat tracker: periodic per-host and global metrics.

The reference's Tracker emits `[shadow-heartbeat] [node|socket|ram]`
CSV-ish lines per host on a configurable interval
(/root/reference/src/main/host/shd-tracker.c:405-592) plus a slave-level
getrusage heartbeat (shd-slave.c:374-395). The TPU engine already keeps
every metric as device-side counters (Hosts.stats); the tracker drains
them at window-chunk boundaries, computes interval deltas, and emits the
same style of lines — no device-side cost beyond the stats the engine
maintains anyway.
"""

from __future__ import annotations

import resource

import numpy as np

from ..engine import defs


HEADER = ("time,host,events,pkts-sent,pkts-recv,bytes-sent,bytes-recv,"
          "retransmits,drop-net,drop-buf,transfers-done")


class Tracker:
    def __init__(self, interval_ns: int, host_names, logger=None,
                 per_host: bool = True):
        self.interval = int(interval_ns)
        self.names = list(host_names)
        self.logger = logger
        self.per_host = per_host
        self.next_ns = self.interval
        self._prev = None
        self.lines = []          # retained for tools/tests

    def _emit(self, line: str):
        self.lines.append(line)
        if self.logger is not None:
            self.logger.message(self.next_ns, "tracker", line)

    def due(self, sim_ns: int) -> bool:
        """Will maybe_heartbeat emit anything at this time? Lets the
        caller skip fetching stats (a cross-process all-gather on a
        multi-process mesh) when no interval boundary has passed."""
        return self.interval > 0 and sim_ns >= self.next_ns

    def maybe_heartbeat(self, sim_ns: int, stats: np.ndarray):
        """Called after each window chunk with current cumulative stats;
        emits one heartbeat per elapsed interval boundary."""
        if self.interval <= 0:
            return
        while sim_ns >= self.next_ns:
            cur = stats.astype(np.int64)
            prev = (self._prev if self._prev is not None
                    else np.zeros_like(cur))
            d = cur - prev
            self._prev = cur.copy()
            t = self.next_ns // 10**9

            if self.per_host:
                for i, name in enumerate(self.names):
                    if d[i, defs.ST_EVENTS] == 0:
                        continue
                    self._emit(
                        f"[shadow-heartbeat] [node] {t},{name},"
                        f"{d[i, defs.ST_EVENTS]},"
                        f"{d[i, defs.ST_PKTS_SENT]},"
                        f"{d[i, defs.ST_PKTS_RECV]},"
                        f"{d[i, defs.ST_BYTES_SENT]},"
                        f"{d[i, defs.ST_BYTES_RECV]},"
                        f"{d[i, defs.ST_RETRANSMIT]},"
                        f"{d[i, defs.ST_PKTS_DROP_NET]},"
                        f"{d[i, defs.ST_PKTS_DROP_BUF]},"
                        f"{d[i, defs.ST_XFER_DONE]}")

            ru = resource.getrusage(resource.RUSAGE_SELF)
            tot = d.sum(axis=0)
            self._emit(
                f"[shadow-heartbeat] [summary] {t},"
                f"events={tot[defs.ST_EVENTS]},"
                f"pkts={tot[defs.ST_PKTS_SENT]}/{tot[defs.ST_PKTS_RECV]},"
                f"bytes={tot[defs.ST_BYTES_SENT]}/{tot[defs.ST_BYTES_RECV]},"
                f"maxrss-gib={ru.ru_maxrss / (1 << 20):.3f},"
                f"utime-min={ru.ru_utime / 60:.3f},"
                f"stime-min={ru.ru_stime / 60:.3f}")
            self.next_ns += self.interval
