"""Span tracing: a low-overhead recorder emitting Chrome trace-event
JSON (viewable in Perfetto / chrome://tracing).

The reference self-times its scheduler barriers and per-host exec
seconds (shd-scheduler.c:250-252, shd-host.c:201-208) but only as
end-of-run aggregates; tools/phase_profile.py and xplane_profile.py
measure phases offline. This module is the ALWAYS-AVAILABLE in-run
counterpart: named wall-clock spans recorded on the host side (the
device reports through counters, not strings), serialized once at the
end of the run as one JSON timeline. Each window-chunk span carries its
sim-time range, windows advanced and events executed in `args`, so
sim-time progress and wall-clock cost correlate in a single view —
"where does the wall time go" answered per chunk, not per run.

Design constraints:

- Cheap when disabled. `ENABLED` is a module-level boolean; hot paths
  (the per-chunk loop in engine.sim) guard every hook with a plain
  ``if trace.ENABLED:`` so a run without ``--trace`` pays one boolean
  check per chunk and allocates nothing. The ``span()`` context
  manager is for cold paths only (setup, teardown, tools) — it
  allocates a generator even when disabled.
- Cheap when enabled. Recording a span is two perf_counter_ns reads
  and one list append of a small dict; serialization happens once, at
  flush. A hard cap (MAX_EVENTS) bounds memory on runaway loops; the
  drop count is recorded in the trace metadata.
- One global tracer. Spans originate from several modules (engine,
  hosting, parallel, obs) on one thread of control; a process-global
  instance keeps the call sites to one import and one boolean.

Timeline format: complete events (``"ph": "X"``) with microsecond
``ts``/``dur`` relative to tracer creation, wrapped as
``{"traceEvents": [...]}`` — both Perfetto and chrome://tracing load
this directly (catapult TraceEvent format).

Usage:

    from shadow_tpu.obs import trace
    trace.install("out.json")
    with trace.span("build"):             # cold path
        ...
    if trace.ENABLED:                     # hot path
        t0 = trace.TRACER.now()
        ...work...
        trace.TRACER.complete("chunk", t0, args={"windows": 8})
    trace.finish()                        # writes out.json
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager

ENABLED = False
TRACER = None

# hard cap on retained events: a pathological span-per-event loop must
# degrade to dropped spans, not to an OOM
MAX_EVENTS = 1_000_000


class Tracer:
    """One trace session. `path=None` collects but discards at flush
    (non-writer processes of a multi-process mesh still time their
    collectives so the collective call pattern stays uniform)."""

    __slots__ = ("path", "events", "dropped", "_pid", "_epoch")

    def __init__(self, path: str | None):
        self.path = path
        self.events = []
        self.dropped = 0
        self._pid = os.getpid()
        self._epoch = time.perf_counter_ns()

    @staticmethod
    def now() -> int:
        """Span start stamp (perf_counter_ns) for complete()."""
        return time.perf_counter_ns()

    def complete(self, name: str, t0_ns: int, args: dict = None,
                 tid: int = 0):
        """Record a complete span [t0_ns, now) named `name`."""
        if len(self.events) >= MAX_EVENTS:
            self.dropped += 1
            return
        t1 = time.perf_counter_ns()
        ev = {"name": name, "ph": "X", "pid": self._pid, "tid": tid,
              "ts": (t0_ns - self._epoch) / 1000.0,
              "dur": (t1 - t0_ns) / 1000.0}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def instant(self, name: str, args: dict = None, tid: int = 0):
        """A zero-duration marker (``"ph": "i"``)."""
        if len(self.events) >= MAX_EVENTS:
            self.dropped += 1
            return
        ev = {"name": name, "ph": "i", "s": "p", "pid": self._pid,
              "tid": tid,
              "ts": (time.perf_counter_ns() - self._epoch) / 1000.0}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def counter(self, name: str, values: dict, tid: int = 0):
        """A counter track sample (``"ph": "C"``): `values` maps
        series name -> number; Perfetto renders them as stacked
        area tracks."""
        if len(self.events) >= MAX_EVENTS:
            self.dropped += 1
            return
        self.events.append(
            {"name": name, "ph": "C", "pid": self._pid, "tid": tid,
             "ts": (time.perf_counter_ns() - self._epoch) / 1000.0,
             "args": values})

    def flush(self):
        """Serialize the timeline. No-op with path=None."""
        if self.path is None:
            return
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": "shadow_tpu"}}]
        doc = {"traceEvents": meta + self.events,
               "displayTimeUnit": "ms"}
        if self.dropped:
            doc["otherData"] = {"dropped_events": self.dropped}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)


def install(path: str | None) -> Tracer:
    """Enable tracing process-wide. Returns the tracer (also at
    module attribute TRACER). Idempotent-hostile by design: the caller
    that installs owns finish()."""
    global ENABLED, TRACER
    TRACER = Tracer(path)
    ENABLED = True
    return TRACER


def finish() -> Tracer | None:
    """Disable tracing and write the timeline (if a path was given).
    Returns the retired tracer so tests can inspect it."""
    global ENABLED, TRACER
    tr, TRACER, ENABLED = TRACER, None, False
    if tr is not None:
        tr.flush()
    return tr


@contextmanager
def span(name: str, **args):
    """Cold-path span context manager. NOT for per-chunk/per-event hot
    loops — the generator allocation is real even when disabled; hot
    paths use the explicit ``if trace.ENABLED:`` + complete() pattern
    (module docstring)."""
    if not ENABLED:
        yield
        return
    tr = TRACER
    t0 = tr.now()
    try:
        yield
    finally:
        tr.complete(name, t0, args or None)
