"""shadow-tpu: a TPU-native discrete-event network simulator.

A ground-up JAX/XLA redesign with the capabilities of the Shadow
simulator (reference surveyed in SURVEY.md): hundreds of thousands of
simulated hosts, each with a virtual TCP/UDP stack, bandwidth-modeled
NIC, CPU model and application behavior, connected by weighted Internet
topologies with latency and packet loss.

Architecture (vs. the reference's callback/event-object design):
- per-host event queues are fixed-capacity struct-of-arrays in device
  memory; the scheduler's pop-min becomes a vectorized reduction;
- the conservative lookahead window barrier (reference master/scheduler
  round loop) becomes a jnp.min / lax.pmin reduction over the mesh;
- cross-host packet sends buffer into per-host outboxes and are
  exchanged at window boundaries (the reference's "bump to barrier"
  causality rule, shd-scheduler-policy-host-single.c:171-175);
- TCP/UDP/NIC/app logic runs as branchless-ish vectorized kernels under
  vmap/shard_map instead of per-connection callbacks.
"""

# Simulation time is int64 nanoseconds; JAX must be in x64 mode before
# any arrays are created.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
