// Native all-pairs routing oracle.
//
// Replaces the reference's igraph dependency (GraphML topology ->
// igraph_get_shortest_paths_dijkstra per source,
// /root/reference/src/main/routing/shd-topology.c:552-905) with a
// self-contained C++ all-pairs pass producing the dense [V,V]
// latency/reliability tables the device engine gathers from.
//
// Semantics mirror shadow_tpu.routing.topology.compute_all_pairs (the
// scipy path), which itself mirrors the reference
// (_topology_computeSourcePathsHelper, shd-topology.c:663-772):
//  - path latency = sum of edge `latency` (ms) along the Dijkstra path;
//  - reliability = (1 - src vloss) * prod(1 - edge loss) * (1 - dst
//    vloss, distinct vertices only), accumulated along the same tree;
//  - same-vertex pairs use the self-loop edge if present else 1 ms;
//  - unreachable pairs report latency 0 / reliability 0;
//  - reachable zero latency clamps up to 1 ms.
//
// Inputs are the deduplicated directed adjacency (min-latency parallel
// edge already chosen, self-loops included).
//
// Build: routing/native/build.py (g++ -O3 -shared); bound via ctypes.

#include <cstddef>
#include <cstdint>
#include <queue>
#include <vector>

using std::size_t;

namespace {

struct HeapItem {
  double dist;
  int vertex;
  bool operator>(const HeapItem& o) const {
    if (dist != o.dist) return dist > o.dist;
    return vertex > o.vertex;  // deterministic tie order: lower id first
  }
};

}  // namespace

extern "C" {

// Single-source Dijkstra with reliability accumulated along the tree.
// Returns 0 on success.
int shadow_sssp(int V, const int32_t* off, const int32_t* nbr,
                const double* wlat, const double* wloss,
                const double* vloss, int s, double* dist, double* rel) {
  std::vector<char> done(V, 0);
  for (int v = 0; v < V; ++v) {
    dist[v] = -1.0;  // -1 = unreached
    rel[v] = 0.0;
  }
  dist[s] = 0.0;
  rel[s] = 1.0 - vloss[s];
  std::priority_queue<HeapItem, std::vector<HeapItem>,
                      std::greater<HeapItem>> pq;
  pq.push({0.0, s});
  while (!pq.empty()) {
    HeapItem it = pq.top();
    pq.pop();
    int u = it.vertex;
    if (done[u]) continue;
    done[u] = 1;
    for (int32_t k = off[u]; k < off[u + 1]; ++k) {
      int v = nbr[k];
      if (v == u) continue;  // self-loops handled by the caller
      double nd = it.dist + wlat[k];
      if (dist[v] < 0.0 || nd < dist[v]) {
        dist[v] = nd;
        rel[v] = rel[u] * (1.0 - wloss[k]);
        pq.push({nd, v});
      }
    }
  }
  return 0;
}

// Dense all-pairs tables with the reference's path semantics.
// esrc/edst/elat/eloss: deduped directed edges (self-loops included).
// out_lat/out_rel: row-major [V, V].
int shadow_apsp(int V, int E, const int32_t* esrc, const int32_t* edst,
                const double* elat, const double* eloss,
                const double* vloss, double* out_lat, double* out_rel) {
  // CSR
  std::vector<int32_t> off(V + 1, 0), nbr(E);
  std::vector<double> wlat(E), wloss(E);
  for (int e = 0; e < E; ++e) off[esrc[e] + 1]++;
  for (int v = 0; v < V; ++v) off[v + 1] += off[v];
  {
    std::vector<int32_t> cur(off.begin(), off.end() - 1);
    for (int e = 0; e < E; ++e) {
      int32_t at = cur[esrc[e]]++;
      nbr[at] = edst[e];
      wlat[at] = elat[e];
      wloss[at] = eloss[e];
    }
  }
  // self-loop lookup
  std::vector<double> self_lat(V, -1.0), self_loss(V, 0.0);
  for (int e = 0; e < E; ++e) {
    if (esrc[e] == edst[e]) {
      self_lat[esrc[e]] = elat[e];
      self_loss[esrc[e]] = eloss[e];
    }
  }

  std::vector<double> dist(V), rel(V);
  for (int s = 0; s < V; ++s) {
    shadow_sssp(V, off.data(), nbr.data(), wlat.data(), wloss.data(),
                vloss, s, dist.data(), rel.data());
    double* L = out_lat + (size_t)s * V;
    double* R = out_rel + (size_t)s * V;
    for (int v = 0; v < V; ++v) {
      if (v == s) continue;
      if (dist[v] < 0.0) {  // unreachable
        L[v] = 0.0;
        R[v] = 0.0;
      } else {
        L[v] = dist[v] > 0.0 ? dist[v] : 1.0;  // 1 ms clamp
        R[v] = rel[v] * (1.0 - vloss[v]);      // dst vertex loss once
      }
    }
    if (self_lat[s] >= 0.0) {
      L[s] = self_lat[s] > 0.0 ? self_lat[s] : 1.0;
      R[s] = (1.0 - vloss[s]) * (1.0 - self_loss[s]);
    } else {
      L[s] = 1.0;  // reference's empty-path fallback
      R[s] = 1.0 - vloss[s];
    }
  }
  return 0;
}

// Count unreachable ordered pairs (strong-connectivity validation,
// reference shd-topology.c:232-474). out_lat as from shadow_apsp.
int64_t shadow_count_unreachable(int V, const double* out_rel) {
  int64_t n = 0;
  for (size_t i = 0; i < (size_t)V * V; ++i)
    if (out_rel[i] <= 0.0) ++n;
  return n;
}

}  // extern "C"
