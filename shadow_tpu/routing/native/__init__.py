"""Native (C++) routing oracle: build-on-first-use + ctypes binding.

The native path replaces the reference's igraph dependency (SURVEY
§2.8). It is used automatically for large graphs and can be forced or
disabled with SHADOW_TPU_NATIVE_ORACLE=1/0.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "oracle.cpp")
_SO = os.path.join(_DIR, "liboracle.so")

_lib = None
_tried = False


def _build() -> bool:
    try:
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-o", _SO, _SRC]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # missing toolchain: fall back to scipy path
        sys.stderr.write(f"shadow_tpu: native oracle build failed ({e}); "
                         "using scipy fallback\n")
        return False


def load():
    """Return the loaded library or None (scipy fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("SHADOW_TPU_NATIVE_ORACLE") == "0":
        return None
    if (not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
        if not _build():
            return None
    lib = ctypes.CDLL(_SO)
    lib.shadow_apsp.restype = ctypes.c_int
    lib.shadow_apsp.argtypes = [
        ctypes.c_int, ctypes.c_int,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
    ]
    lib.shadow_count_unreachable.restype = ctypes.c_int64
    lib.shadow_count_unreachable.argtypes = [
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
    ]
    _lib = lib
    return _lib


def apsp(V: int, src: np.ndarray, dst: np.ndarray, lat: np.ndarray,
         loss: np.ndarray, vloss: np.ndarray):
    """All-pairs (lat_ms[V,V], rel[V,V], unreachable[V,V]) via the
    native oracle. Caller guarantees deduped directed edges."""
    lib = load()
    assert lib is not None
    E = len(src)
    out_lat = np.zeros((V, V), dtype=np.float64)
    out_rel = np.zeros((V, V), dtype=np.float64)
    rc = lib.shadow_apsp(
        V, E,
        np.ascontiguousarray(src, np.int32),
        np.ascontiguousarray(dst, np.int32),
        np.ascontiguousarray(lat, np.float64),
        np.ascontiguousarray(loss, np.float64),
        np.ascontiguousarray(vloss, np.float64),
        out_lat, out_rel)
    if rc != 0:
        raise RuntimeError(f"shadow_apsp failed rc={rc}")
    unreachable = (out_rel <= 0.0) & (out_lat <= 0.0)
    return out_lat, out_rel, unreachable


def available() -> bool:
    return load() is not None
