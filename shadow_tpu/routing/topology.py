"""Topology: the global latency/reliability oracle.

TPU-first redesign of the reference's igraph-backed router
(/root/reference/src/main/routing/shd-topology.c). The reference runs
single-source Dijkstra lazily per source vertex and caches src->dst
``Path{latency, reliability}`` objects (shd-topology.c:552-615,868-905).
Because attached hosts map onto a small set of point-of-interest
vertices (shd-topology.c:1071-1294), the cache is vertex-by-vertex, not
host-by-host — so here we precompute the full dense VxV latency and
reliability tables up front (scipy Dijkstra over a CSR adjacency; a C++
native path exists for very large graphs) and ship them to device HBM,
where per-packet routing is two gathers.

Semantics matched to the reference (verified against
_topology_computeSourcePathsHelper, shd-topology.c:663-772):
- edge weight = ``latency`` attribute, milliseconds;
- path latency = sum of edge latencies along the Dijkstra path;
- same-vertex pairs use the self-loop edge's latency if present, else
  1 ms (the reference's empty-path fallback);
- path reliability = (1 - src vertex loss) * (1 - dst vertex loss,
  distinct vertices only) * prod(1 - edge loss); intermediate vertex
  losses are NOT included;
- zero latency is clamped up to 1 ms;
- jitter is parsed but (like the reference) not used in paths;
- global minimum path latency feeds the conservative lookahead window
  (reference: shd-topology.c:602-614 -> shd-master.c:118-131).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass

import numpy as np
from scipy.sparse import csr_matrix
from scipy.sparse.csgraph import dijkstra

from ..core.simtime import SIMTIME_ONE_MILLISECOND
from .graphml import Graph, parse_graphml


@dataclass
class Topology:
    graph: Graph
    latency_ns: np.ndarray       # [V, V] int64 path latency
    reliability: np.ndarray      # [V, V] float32 path delivery probability
    min_latency_ns: int          # min over all pairs (window lookahead bound)
    v_bw_up_bytes: np.ndarray    # [V] vertex default bandwidths, bytes/s
    v_bw_down_bytes: np.ndarray

    @property
    def num_vertices(self) -> int:
        return self.graph.num_vertices

    def has_edge(self, v1: int, v2: int) -> bool:
        """True when a direct edge joins the two vertices (either
        direction on undirected graphs). Fault injection uses this to
        warn when a link_down/loss/latency fault names a pair that is
        only connected through intermediate hops — the fault still
        applies, but to the precomputed PATH entry [v1, v2], not to
        every path crossing a physical link (the oracle stores paths,
        not edges; see engine.faults)."""
        g = self.graph
        m = (g.e_src == v1) & (g.e_dst == v2)
        if not g.directed:
            m |= (g.e_src == v2) & (g.e_dst == v1)
        return bool(m.any())


def _build_adjacency(g: Graph):
    """Dense-ish CSR of min edge latency between distinct vertices, plus
    per-pair packetloss of the chosen (minimum-latency) edge."""
    V = g.num_vertices
    src, dst = g.e_src, g.e_dst
    lat, loss = g.e_latency_ms, g.e_packetloss
    if not g.directed:
        keep = src != dst
        src = np.concatenate([src, dst[keep]])
        dst = np.concatenate([dst, g.e_src[keep]])
        lat = np.concatenate([lat, lat[keep]])
        loss = np.concatenate([loss, loss[keep]])
    # Keep the minimum-latency edge per (src, dst) pair (parallel edges).
    order = np.lexsort((lat, dst, src))
    src, dst, lat, loss = src[order], dst[order], lat[order], loss[order]
    pair = src * V + dst
    first = np.ones(len(pair), dtype=bool)
    first[1:] = pair[1:] != pair[:-1]
    return src[first], dst[first], lat[first], loss[first]


def compute_all_pairs(g: Graph, native: bool = None):
    """All-pairs (latency_ms, reliability) with reference semantics.

    `native` selects the C++ oracle (routing.native, the igraph
    replacement): None = auto (use it for larger graphs when it
    builds), True = require, False = scipy/numpy path. Both paths
    produce identical tables on graphs without equal-cost multipaths
    (asserted by tests/test_native_oracle.py).
    """
    import os as _os

    V = g.num_vertices
    src, dst, lat, loss = _build_adjacency(g)

    env = _os.environ.get("SHADOW_TPU_NATIVE_ORACLE")
    if native is None:
        if env == "1":
            native = True
        elif env == "0":
            native = False
        else:
            native = V >= 256  # Python reliability loop is O(V^2)
    if native:
        from . import native as native_mod
        if native_mod.available():
            return native_mod.apsp(V, src, dst, lat, loss, g.v_packetloss)
        if env == "1":
            raise RuntimeError("SHADOW_TPU_NATIVE_ORACLE=1 but the "
                               "native oracle failed to build")
    off = src != dst
    adj = csr_matrix((lat[off], (src[off], dst[off])), shape=(V, V))

    # Dijkstra with predecessors so reliability can be accumulated along
    # the same shortest path the latency uses.
    dist, pred = dijkstra(adj, directed=True, return_predecessors=True)

    # Edge loss lookup as dense [V, V] (PoI graphs are small: the bundled
    # topologies have <= a few thousand vertices).
    edge_loss = np.zeros((V, V))
    edge_has = np.zeros((V, V), dtype=bool)
    edge_loss[src, dst] = loss
    edge_has[src, dst] = True

    vloss = g.v_packetloss
    rel = np.ones((V, V))
    # Accumulate reliability along the shortest-path tree of each source:
    # process destinations in order of increasing distance so the
    # predecessor's reliability is already final.
    for s in range(V):
        order = np.argsort(dist[s], kind="stable")
        r = rel[s]
        r[:] = 0.0
        r[s] = 1.0 - vloss[s]
        for v in order:
            p = pred[s, v]
            if v == s or p < 0:
                continue
            r[v] = r[p] * (1.0 - edge_loss[p, v])
        # dst vertex loss applies once for distinct vertices
        r *= np.where(np.arange(V) == s, 1.0, 1.0 - vloss)

    lat_ms = dist.copy()
    # Same-vertex pairs: self-loop edge if present, else the reference's
    # 1 ms empty-path fallback; reliability from src vertex + self-loop.
    for v in range(V):
        if edge_has[v, v]:
            lat_ms[v, v] = lat[(src == v) & (dst == v)][0]
            rel[v, v] = (1.0 - vloss[v]) * (1.0 - edge_loss[v, v])
        else:
            lat_ms[v, v] = 1.0
            rel[v, v] = 1.0 - vloss[v]

    unreachable = ~np.isfinite(lat_ms)
    lat_ms[unreachable] = 0.0
    rel[unreachable] = 0.0
    # Reference clamps zero-latency paths up to 1 ms (shd-topology.c:760-766).
    lat_ms[(lat_ms <= 0.0) & ~unreachable] = 1.0
    return lat_ms, rel, unreachable


def build_topology(source) -> Topology:
    """Build a Topology from GraphML text/path or a parsed Graph."""
    g = source if isinstance(source, Graph) else parse_graphml(source)
    lat_ms, rel, unreachable = compute_all_pairs(g)
    lat_ns = np.round(lat_ms * SIMTIME_ONE_MILLISECOND).astype(np.int64)
    reachable = lat_ns[~unreachable]
    min_lat = int(reachable.min()) if reachable.size else 0
    return Topology(
        graph=g,
        latency_ns=lat_ns,
        reliability=rel.astype(np.float32),
        min_latency_ns=min_lat,
        v_bw_up_bytes=(g.v_bw_up * 1024).astype(np.int64),
        v_bw_down_bytes=(g.v_bw_down * 1024).astype(np.int64),
    )


# --- Host attachment -------------------------------------------------------
#
# Mirrors the reference's hint-driven placement
# (shd-topology.c:1071-1294): each host supplies optional ip / geocode /
# type hints; candidate vertices are scored, ip hints use
# longest-prefix-match, and ties break deterministically via the seeded
# per-host RNG rather than wall-clock randomness.

def _ip_to_int(s: str):
    try:
        return int(ipaddress.IPv4Address(s))
    except Exception:
        return None


def attach_hosts(topo: Topology, hints, seed: int = 1) -> np.ndarray:
    """Assign each host a vertex index.

    ``hints`` is a sequence of (ip_hint, geocode_hint, type_hint) tuples,
    one per host. Returns int32 [num_hosts] vertex indices.
    """
    g = topo.graph
    V = g.num_vertices
    vips = np.array([(_ip_to_int(ip) or -1) for ip in g.v_ip], dtype=np.int64)
    rng = np.random.RandomState(seed ^ 0x5EED)
    out = np.zeros(len(hints), dtype=np.int32)
    for i, (ip_hint, geo_hint, type_hint) in enumerate(hints):
        cand = np.ones(V, dtype=bool)
        if type_hint:
            m = np.array([t == type_hint for t in g.v_type])
            if m.any():
                cand &= m
        if geo_hint:
            m = np.array([c == geo_hint for c in g.v_geocode])
            if (cand & m).any():
                cand &= m
        idxs = np.flatnonzero(cand)
        if ip_hint:
            ip = _ip_to_int(ip_hint)
            if ip is not None:
                # longest common prefix with candidate vertex IPs
                valid = idxs[vips[idxs] >= 0]
                if valid.size:
                    xor = (vips[valid] ^ ip).astype(np.uint64)
                    # fewer leading-one bits in xor = longer shared prefix
                    prefix = 32 - np.ceil(np.log2(xor + 1)).astype(int)
                    best = prefix.max()
                    idxs = valid[prefix == best]
        out[i] = idxs[rng.randint(len(idxs))] if len(idxs) else rng.randint(V)
    return out
