"""Virtual DNS: hostname <-> IP registry.

Mirrors the reference's DNS (/root/reference/src/main/routing/shd-dns.c):
unique IPs are generated from 11.0.0.0 upward, skipping reserved CIDR
blocks (shd-dns.c:65-104), and names are registered at host boot. In the
TPU engine hosts are dense integer ids; DNS is a host-side table built
once at setup, used by config/app parsing to resolve peer names to host
ids, plus [H] device arrays mapping host id -> ip for logging/pcap.
"""

from __future__ import annotations

import ipaddress

import numpy as np

_RESERVED = [
    ipaddress.ip_network(n) for n in (
        "10.0.0.0/8", "100.64.0.0/10", "127.0.0.0/8", "169.254.0.0/16",
        "172.16.0.0/12", "192.0.0.0/24", "192.0.2.0/24", "192.88.99.0/24",
        "192.168.0.0/16", "198.18.0.0/15", "198.51.100.0/24",
        "203.0.113.0/24", "224.0.0.0/4", "240.0.0.0/4", "255.255.255.255/32",
    )
]


def _is_reserved(ip_int: int) -> bool:
    addr = ipaddress.IPv4Address(ip_int)
    return any(addr in net for net in _RESERVED)


class DNS:
    """Name/IP registry. Host ids are dense [0, H)."""

    def __init__(self):
        self._name_to_host = {}
        self._host_to_name = {}
        self._ip_to_host = {}
        self._host_to_ip = {}
        self._next_ip = int(ipaddress.IPv4Address("11.0.0.0"))

    def register(self, host_id: int, name: str, ip_hint: str = None) -> int:
        """Register a host; returns its assigned IPv4 as an int."""
        if name in self._name_to_host:
            raise ValueError(f"duplicate hostname {name!r}")
        ip = None
        if ip_hint:
            try:
                cand = int(ipaddress.IPv4Address(ip_hint))
                if cand not in self._ip_to_host and not _is_reserved(cand):
                    ip = cand
            except ipaddress.AddressValueError:
                ip = None
        if ip is None:
            while _is_reserved(self._next_ip) or self._next_ip in self._ip_to_host:
                self._next_ip += 1
            ip = self._next_ip
            self._next_ip += 1
        self._name_to_host[name] = host_id
        self._host_to_name[host_id] = name
        self._ip_to_host[ip] = host_id
        self._host_to_ip[host_id] = ip
        return ip

    def resolve(self, name: str) -> int:
        """Name -> host id (the virtual getaddrinfo)."""
        if name in self._name_to_host:
            return self._name_to_host[name]
        # dotted-quad literals resolve through the ip table
        try:
            ip = int(ipaddress.IPv4Address(name))
            return self._ip_to_host[ip]
        except (ipaddress.AddressValueError, KeyError):
            raise KeyError(f"unknown hostname {name!r}") from None

    def reverse(self, host_id: int) -> str:
        return self._host_to_name[host_id]

    def ip_of(self, host_id: int) -> int:
        return self._host_to_ip[host_id]

    def ip_str(self, host_id: int) -> str:
        return str(ipaddress.IPv4Address(self._host_to_ip[host_id]))

    def ip_array(self, num_hosts: int) -> np.ndarray:
        """[H] uint32 host id -> ip for device-side use (pcap, tracing)."""
        out = np.zeros(num_hosts, dtype=np.uint32)
        for h, ip in self._host_to_ip.items():
            if h < num_hosts:
                out[h] = ip
        return out
