"""GraphML parsing for network topologies.

Replaces the reference's igraph GraphML import
(/root/reference/src/main/routing/shd-topology.c:95-123) with a small
ElementTree-based parser producing numpy arrays. Supports the attribute
schema used by Shadow topologies: node attrs ``ip, geocode, type, asn,
bandwidthup, bandwidthdown, packetloss``; edge attrs ``latency, jitter,
packetloss``. Handles .xz-compressed files like the bundled resources.
"""

from __future__ import annotations

import lzma
import os
from dataclasses import dataclass, field
from xml.etree import ElementTree

import numpy as np

_NS = "{http://graphml.graphdrawing.org/xmlns}"


@dataclass
class Graph:
    """Parsed topology graph (vertices = points of interest)."""
    vertex_ids: list                 # string ids, index = vertex index
    directed: bool
    # vertex attributes (parallel arrays, len V)
    v_ip: list = field(default_factory=list)          # strings (may be "0.0.0.0")
    v_geocode: list = field(default_factory=list)
    v_type: list = field(default_factory=list)
    v_asn: np.ndarray = None
    v_bw_up: np.ndarray = None       # KiB/s as in the graphml
    v_bw_down: np.ndarray = None
    v_packetloss: np.ndarray = None
    # edges (E rows)
    e_src: np.ndarray = None
    e_dst: np.ndarray = None
    e_latency_ms: np.ndarray = None
    e_jitter_ms: np.ndarray = None
    e_packetloss: np.ndarray = None

    @property
    def num_vertices(self) -> int:
        return len(self.vertex_ids)

    @property
    def num_edges(self) -> int:
        return 0 if self.e_src is None else len(self.e_src)


def _read_text(source: str) -> str:
    if "\n" not in source and os.path.exists(source):
        if source.endswith(".xz"):
            with lzma.open(source, "rt") as f:
                return f.read()
        with open(source) as f:
            return f.read()
    return source


def _strip(tag: str) -> str:
    return tag.split("}")[-1]


def parse_graphml(source: str) -> Graph:
    """Parse GraphML text or a file path (optionally .xz) into a Graph."""
    text = _read_text(source)
    root = ElementTree.fromstring(text)

    # key id -> (domain, attr name, attr type)
    keys = {}
    graph_el = None
    for el in root:
        tag = _strip(el.tag)
        if tag == "key":
            keys[el.attrib["id"]] = (
                el.attrib.get("for", "node"),
                el.attrib.get("attr.name", el.attrib["id"]),
                el.attrib.get("attr.type", "string"),
            )
        elif tag == "graph":
            graph_el = el
    if graph_el is None:
        raise ValueError("graphml contains no <graph> element")
    directed = graph_el.attrib.get("edgedefault", "undirected") == "directed"

    def data_of(el):
        out = {}
        for d in el:
            if _strip(d.tag) == "data":
                _, name, _ = keys.get(d.attrib["key"], (None, d.attrib["key"], "string"))
                out[name] = (d.text or "").strip()
        return out

    vertex_ids, vdata = [], []
    edges = []
    for el in graph_el:
        tag = _strip(el.tag)
        if tag == "node":
            vertex_ids.append(el.attrib["id"])
            vdata.append(data_of(el))
        elif tag == "edge":
            edges.append((el.attrib["source"], el.attrib["target"], data_of(el)))

    vindex = {vid: i for i, vid in enumerate(vertex_ids)}
    V, E = len(vertex_ids), len(edges)

    g = Graph(vertex_ids=vertex_ids, directed=directed)
    g.v_ip = [d.get("ip", "") for d in vdata]
    g.v_geocode = [d.get("geocode", "") for d in vdata]
    g.v_type = [d.get("type", "") for d in vdata]
    g.v_asn = np.array([int(d.get("asn", 0) or 0) for d in vdata], dtype=np.int64)
    g.v_bw_up = np.array([float(d.get("bandwidthup", 0) or 0) for d in vdata])
    g.v_bw_down = np.array([float(d.get("bandwidthdown", 0) or 0) for d in vdata])
    g.v_packetloss = np.array([float(d.get("packetloss", 0) or 0) for d in vdata])

    g.e_src = np.array([vindex[s] for s, _, _ in edges], dtype=np.int64)
    g.e_dst = np.array([vindex[t] for _, t, _ in edges], dtype=np.int64)
    g.e_latency_ms = np.array([float(d.get("latency", 0) or 0) for _, _, d in edges])
    g.e_jitter_ms = np.array([float(d.get("jitter", 0) or 0) for _, _, d in edges])
    g.e_packetloss = np.array([float(d.get("packetloss", 0) or 0) for _, _, d in edges])

    # Validate like the reference (shd-topology.c:232-474): latencies must be
    # positive on every edge.
    if E and (g.e_latency_ms <= 0).any():
        bad = int(np.argmax(g.e_latency_ms <= 0))
        raise ValueError(
            f"invalid latency {g.e_latency_ms[bad]} on edge "
            f"{vertex_ids[g.e_src[bad]]}->{vertex_ids[g.e_dst[bad]]}")
    return g
