"""routing subpackage."""
