"""Command-line entry: ``python -m shadow_tpu [options] config.xml``.

The L7 equivalent of the reference's ``shadow [options] config.xml``
(/root/reference/src/main/core/shd-main.c:724, option groups
shd-options.c:82-140). There is no relaunch/LD_PRELOAD machinery to
bootstrap — the engine selection is ``--engine`` and the device mesh
replaces worker threads (``--workers`` maps to mesh shards).

Observability (shadow_tpu/obs/README.md):

  --trace FILE     record a Chrome trace-event timeline of the run
                   (per-chunk spans with sim-time args; open FILE in
                   https://ui.perfetto.dev or summarize it with
                   ``python tools/trace_report.py FILE``)
  --metrics FILE   write a final metrics snapshot (events/sec, wall
                   per sim-second, shim per-op counts) to FILE and
                   per-chunk JSON lines to FILE.chunks.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys


TEST_TOPOLOGY = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9" />
  <key attr.name="latency" attr.type="double" for="edge" id="d7" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d0" />
  <graph edgedefault="undirected">
    <node id="poi-1"><data key="d0">0.0</data>
      <data key="d3">17038</data><data key="d4">2251</data></node>
    <edge source="poi-1" target="poi-1">
      <data key="d7">50.0</data><data key="d9">0.0</data></edge>
  </graph>
</graphml>"""

# The builtin benchmark scenario, mirroring the reference's --test
# (shd-examples.c:10-41: 1000 clients x 10 small downloads from one
# server pool over a single-PoI topology, 60 s stop).
TEST_SERVER_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="serverport" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start"><data key="d0">80</data></node>
  </graph>
</graphml>"""

TEST_CLIENT_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="count" attr.type="string" for="node" id="d6" />
  <key attr.name="size" attr.type="string" for="node" id="d5" />
  <key attr.name="type" attr.type="string" for="node" id="d4" />
  <key attr.name="time" attr.type="string" for="node" id="d2" />
  <key attr.name="peers" attr.type="string" for="node" id="d0" />
  <graph edgedefault="directed">
    <node id="start"><data key="d0">server:80</data></node>
    <node id="transfer">
      <data key="d4">get</data><data key="d5">18 KiB</data>
    </node>
    <node id="pause"><data key="d2">1</data></node>
    <node id="end"><data key="d6">10</data></node>
    <edge source="start" target="transfer" />
    <edge source="transfer" target="end" />
    <edge source="end" target="pause" />
    <edge source="pause" target="start" />
  </graph>
</graphml>"""


def build_test_scenario(n_clients: int = 1000, stop_s: int = 60):
    from .core.config import HostSpec, ProcessSpec, Scenario
    return Scenario(
        stop_time=stop_s * 10**9,
        topology_graphml=TEST_TOPOLOGY,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=TEST_SERVER_GRAPH)]),
            HostSpec(id="client", quantity=n_clients, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=TEST_CLIENT_GRAPH)]),
        ],
    )


def main(argv=None):
    argv_in = list(argv) if argv is not None else sys.argv[1:]
    if argv_in[:1] == ["fleet"]:
        # the sweep scheduler CLI (fleet submit|run|status) has its
        # own argparse tree — dispatch before the run parser
        from .fleet.cli import main as fleet_main
        return fleet_main(argv_in[1:])
    if argv_in[:1] == ["batch"]:
        # vmapped scenario batching (serving.batch): N same-shape
        # scenarios as one compiled program — its own argparse tree
        from .serving.batch import main as batch_main
        return batch_main(argv_in[1:])
    p = argparse.ArgumentParser(
        prog="shadow_tpu",
        description="TPU-native discrete-event network simulator")
    p.add_argument("config", nargs="?", help="shadow.config.xml path")
    p.add_argument("--test", action="store_true",
                   help="run the builtin benchmark scenario "
                        "(reference --test)")
    p.add_argument("--test-clients", type=int, default=1000)
    p.add_argument("--seed", type=int, default=None,
                   help="override the scenario seed")
    p.add_argument("--stop-time", type=str, default=None,
                   help="override stop time, e.g. 60s / 10m")
    p.add_argument("--workers", type=int, default=0, metavar="N",
                   help="shard hosts over N devices (0 = single chip; "
                        "the reference's worker-thread knob)")
    p.add_argument("--heartbeat-frequency", type=float, default=0,
                   metavar="SEC", help="tracker heartbeat interval")
    p.add_argument("--log-level", default="message",
                   choices=["error", "warning", "message", "info", "debug"])
    p.add_argument("--runahead", type=str, default=None, metavar="TIME",
                   help="override the lookahead window width (e.g. 10ms;"
                        " reference --runahead). Larger than the true "
                        "minimum path latency trades causality slack "
                        "for fewer barriers, like the reference")
    p.add_argument("--tcp-congestion-control", default="cubic",
                   choices=["aimd", "reno", "cubic"])
    p.add_argument("--tcp-windows", type=float, default=10.0,
                   metavar="PKTS",
                   help="initial TCP congestion window in packets "
                        "(reference --tcp-windows, default 10)")
    p.add_argument("--tcp-ssthresh", type=float, default=0,
                   metavar="PKTS",
                   help="initial TCP slow-start threshold in packets "
                        "(0 = discover; reference --tcp-ssthresh)")
    p.add_argument("--socket-recv-buffer", type=int, default=0,
                   metavar="BYTES",
                   help="default socket receive buffer for hosts that "
                        "set none (0 = autotune, the reference default)")
    p.add_argument("--socket-send-buffer", type=int, default=0,
                   metavar="BYTES",
                   help="default socket send buffer (0 = autotune)")
    p.add_argument("--interface-buffer", type=int, default=0,
                   metavar="BYTES",
                   help="default NIC input buffer size for hosts that "
                        "set none (reference --interface-buffer)")
    p.add_argument("--interface-qdisc", default="rr",
                   choices=["fifo", "rr"],
                   help="NIC socket service discipline")
    p.add_argument("--cpu-threshold", type=int, default=None,
                   metavar="US",
                   help="CPU blocked-delay threshold in microseconds "
                        "(negative disables; reference default -1)")
    p.add_argument("--cpu-precision", type=int, default=None,
                   metavar="US",
                   help="round CPU delays to the nearest microseconds "
                        "(default 1; the reference's 200 would round "
                        "the constant modeled event cost to zero)")
    p.add_argument("--pcap-dir", default=None, metavar="DIR",
                   help="write pcap files for hosts with logpcap set")
    p.add_argument("--trace", default=None, metavar="FILE",
                   help="record a Chrome trace-event timeline "
                        "(Perfetto / tools/trace_report.py)")
    p.add_argument("--metrics", default=None, metavar="FILE",
                   help="write a metrics snapshot to FILE and "
                        "per-chunk JSON lines to FILE.chunks.jsonl")
    p.add_argument("--netscope", default=None, metavar="FILE",
                   help="network observatory (obs.netscope): count "
                        "RTT/completion/queue/retransmit latency "
                        "histograms on device and stream a per-chunk "
                        "network time-series to FILE as JSON lines; "
                        "the summary carries exact p50/p99 read-outs "
                        "and --metrics grows a `net` section. "
                        "Deterministic; changes the compiled shape "
                        "(and so the config fingerprint), never the "
                        "simulation results")
    p.add_argument("--passcope", nargs="?", const="", default=None,
                   metavar="DIR",
                   help="pass-time observatory (obs.passcope): "
                        "profile the first few chunks with "
                        "jax.profiler into DIR (default "
                        "passcope_trace; SHADOW_TPU_PASSCOPE also "
                        "enables it), decode the xplane dump into a "
                        "per-pass DEVICE-time table keyed by the "
                        "stateflow entry names, and print it with "
                        "the lockstep-occupancy block after the run. "
                        "Observation only — digest chains are "
                        "byte-identical to a plain run's "
                        "(docs/performance.md)")
    p.add_argument("--perf", nargs="?", const="", default=None,
                   metavar="LEDGER",
                   help="per-phase wall attribution + perf ledger: "
                        "collect spans in memory (no --trace file "
                        "needed), print the phase report after the "
                        "run, and append one entry to the perf "
                        "ledger (default perf/ledger.jsonl; pass a "
                        "path to override; SHADOW_TPU_LEDGER=off "
                        "disables appends). Host-side only — digest "
                        "chains are unchanged (docs/performance.md)")
    p.add_argument("--digest", default=None, metavar="FILE",
                   help="append a determinism digest chain to FILE "
                        "(one JSON line of per-section state hashes "
                        "per cadence, plus FILE.manifest.json; diff "
                        "two chains with tools/divergence.py)")
    p.add_argument("--digest-every", type=int, default=0,
                   metavar="WINDOWS",
                   help="digest cadence in windows (default 64; "
                        "records also land at every fault boundary "
                        "and at the end of the run)")
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="crash-safe checkpoint store base: snapshots "
                        "rotate as PATH.w<windows>.npz (atomic "
                        "tmp+fsync+rename writes, content-hashed, "
                        "last --checkpoint-keep retained) with a "
                        "PATH.latest pointer (docs/durability.md)")
    p.add_argument("--checkpoint-every", type=float, default=0,
                   metavar="SEC")
    p.add_argument("--checkpoint-keep", type=int, default=0,
                   metavar="N",
                   help="snapshots retained in the store (default 3; "
                        "SHADOW_TPU_CHECKPOINT_KEEP also sets it)")
    p.add_argument("--resume", default=None, metavar="PATH|latest",
                   help="restore a snapshot and continue: a concrete "
                        ".npz, a checkpoint store base, or the "
                        "literal 'latest' to resolve the newest valid "
                        "snapshot in the --checkpoint store (corrupt "
                        "heads fall back loudly to the previous "
                        "snapshot; no snapshot yet = start fresh with "
                        "a warning). Resume covers fault schedules "
                        "and hosted apps (journal replay)")
    p.add_argument("--until-complete", action="store_true",
                   help="auto-resume supervision: run the simulation "
                        "in a child process and, if it crashes or is "
                        "preempted, re-exec it with --resume latest "
                        "until it completes (capped retries, "
                        "exponential backoff, crash-cause log at "
                        "<checkpoint>.supervisor.jsonl). Requires "
                        "--checkpoint + --checkpoint-every")
    p.add_argument("--max-retries", type=int, default=5, metavar="N",
                   help="with --until-complete: resume attempts "
                        "before giving up (default 5)")
    p.add_argument("--retry-backoff", type=float, default=1.0,
                   metavar="SEC",
                   help="with --until-complete: initial backoff "
                        "between attempts, doubling to a 60s cap")
    p.add_argument("--fault", action="append", default=None,
                   metavar="K=V,...",
                   help="schedule one fault (repeatable), e.g. "
                        "kind=host_down,at=10s,host=relay or "
                        "kind=link_down,at=5s,until=8s,src=a,dst=b or "
                        "kind=loss,at=5s,until=9s,rate=0.2,src=a,dst=b "
                        "or kind=latency,at=5s,until=9s,extra=30ms,"
                        "src=a,dst=b (engine.faults; deterministic, "
                        "seed-stable)")
    p.add_argument("--aot-cache", default=None, metavar="DIR",
                   help="persistent AOT executable cache: compiled "
                        "window programs are serialized into DIR and "
                        "reloaded by any later process with the same "
                        "config fingerprint / arg signature / jax "
                        "version / platform / source digest — a known "
                        "shape loads in seconds instead of recompiling "
                        "(docs/serving.md; SHADOW_TPU_AOT_CACHE also "
                        "sets it)")
    p.add_argument("--prewarm", action="store_true",
                   help="compile (or disk-load) the scenario's window "
                        "program into the AOT cache and exit WITHOUT "
                        "running — the fleet pre-warm child "
                        "(docs/serving.md)")
    p.add_argument("--shape-fingerprint", action="store_true",
                   help="print the scenario's compiled-shape "
                        "fingerprint (obs.ledger.fingerprint_of of "
                        "the resolved EngineConfig) as one JSON line "
                        "and exit without compiling — the fleet "
                        "scheduler's shape-dedup probe")
    p.add_argument("--engine-caps", default=None, metavar="K=V,...",
                   help="override engine array capacities, e.g. "
                        "qcap=16,scap=2,obcap=16,incap=32,chunk=256 "
                        "(defaults are sized from the scenario)")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--summary-json", action="store_true",
                   help="print the final summary as one JSON line")
    args = p.parse_args(argv)

    if args.checkpoint and not args.checkpoint_every:
        p.error("--checkpoint requires --checkpoint-every SEC")

    if args.until_complete:
        # supervise BEFORE any heavy import/compile: the child
        # processes do the real work (engine.supervisor)
        if not (args.checkpoint and args.checkpoint_every):
            p.error("--until-complete requires --checkpoint PATH and "
                    "--checkpoint-every SEC (resume needs snapshots)")
        from .engine.supervisor import Supervisor, strip_supervisor_args
        from .obs import metrics as MT
        from .obs import trace as TR
        own_tr = own_mt = False
        # the supervisor's own obs stream rides sidecar paths so the
        # child's --trace/--metrics files stay the child's
        if args.trace and not TR.ENABLED:
            TR.install(args.trace + ".supervisor")
            own_tr = True
        if args.metrics and not MT.ENABLED:
            MT.install(args.metrics + ".supervisor")
            own_mt = True
        sup = Supervisor(
            strip_supervisor_args(argv if argv is not None
                                  else sys.argv[1:]),
            args.checkpoint, max_retries=args.max_retries,
            backoff_s=args.retry_backoff)
        try:
            return sup.run()
        finally:
            if own_tr:
                TR.finish()
            if own_mt:
                MT.finish()

    from .core.config import load_xml
    from .core.simtime import parse_time
    from .engine.sim import Simulation
    from .obs.logger import SimLogger

    if args.resume == "latest":
        if not args.checkpoint:
            p.error("--resume latest needs --checkpoint PATH to name "
                    "the store to resolve in")
        from .engine.checkpoint import resolve_latest
        resolved = resolve_latest(args.checkpoint)
        if resolved is None:
            sys.stderr.write(
                "shadow_tpu: no usable snapshot under "
                f"{args.checkpoint!r} yet — starting fresh\n")
            args.resume = None
        else:
            # thread the already-verified snapshot through so load()
            # hashes one file instead of re-resolving the whole store;
            # every supervisor retry re-execs this preflight, so the
            # corrupt-head fallback still runs per attempt
            args.resume = resolved

    if args.test:
        scenario = build_test_scenario(args.test_clients)
    elif args.config:
        scenario = load_xml(args.config)
    else:
        p.error("provide a config.xml or --test")

    if args.stop_time:
        scenario.stop_time = parse_time(args.stop_time, default_unit="s")
    if args.seed is not None:
        scenario.seed = args.seed
    if args.fault:
        from .core.config import FaultSpec
        for spec in args.fault:
            kv = {}
            for part in spec.split(","):
                k, eq, v = part.partition("=")
                if not eq:
                    p.error(f"--fault entry {part!r} is not k=v")
                kv[k.strip()] = v.strip()
            if "kind" not in kv or "at" not in kv:
                p.error("--fault needs at least kind= and at=")
            try:
                scenario.faults.append(FaultSpec(
                    kind=kv["kind"],
                    at=parse_time(kv["at"], default_unit="s"),
                    host=kv.get("host"),
                    src=kv.get("src"),
                    dst=kv.get("dst"),
                    until=(parse_time(kv["until"], default_unit="s")
                           if "until" in kv else None),
                    rate=float(kv.get("rate", 0.0)),
                    extra_ns=(parse_time(kv["extra"], default_unit="ms")
                              if "extra" in kv else 0),
                ))
            except ValueError as e:
                p.error(f"--fault {spec!r}: {e}")
    # None = flag absent (argparse sentinel): only an EXPLICIT flag
    # overrides the scenario — unconditional writes would clobber
    # CPU-model values the XML carries (the to_xml schema extension
    # the fleet's self-contained queue relies on), while an explicit
    # `--cpu-threshold -1` must still win over the XML
    if args.cpu_threshold is not None:
        scenario.cpu_threshold_ns = (args.cpu_threshold * 1000
                                     if args.cpu_threshold >= 0 else -1)
    if args.cpu_precision is not None:
        scenario.cpu_precision_ns = (args.cpu_precision * 1000
                                     if args.cpu_precision >= 0 else 0)
    # CLI buffer defaults apply to hosts whose XML sets none (the
    # reference's CLI-default / XML-override layering, shd-master.c:296-341)
    for h in scenario.hosts:
        if args.socket_recv_buffer and h.socket_recv_buffer is None:
            h.socket_recv_buffer = args.socket_recv_buffer
        if args.socket_send_buffer and h.socket_send_buffer is None:
            h.socket_send_buffer = args.socket_send_buffer
        if args.interface_buffer and h.interface_buffer is None:
            h.interface_buffer = args.interface_buffer

    logger = SimLogger(level=args.log_level)
    logger.message(0, "main", f"shadow_tpu starting: "
                   f"{scenario.total_hosts()} hosts, "
                   f"stop={scenario.stop_time / 1e9:.1f}s")

    engine_cfg = None
    if args.engine_caps or args.netscope:
        # knobs that must be set BEFORE Simulation.__init__ (they
        # change the allocated state shapes): build the auto config
        # ourselves and override it
        from .engine.sim import auto_engine_config
        from .routing.topology import build_topology
        import dataclasses
        topo = build_topology(scenario.topology_graphml or
                              scenario.topology_path)
        engine_cfg = auto_engine_config(scenario, topo)
        if args.netscope:
            engine_cfg = dataclasses.replace(engine_cfg, netscope=True)
        names = {"chunk": "chunk_windows"}
        for kv in (args.engine_caps.split(",")
                   if args.engine_caps else ()):
            k, _, v = kv.partition("=")
            k = names.get(k.strip(), k.strip())
            if k not in {"qcap", "scap", "obcap", "incap", "txqcap",
                         "chunk_windows", "hostedcap", "tracecap"}:
                p.error(f"unknown engine cap {k!r}")
            try:
                val = int(v)
            except ValueError:
                p.error(f"engine cap {k}={v!r} is not an integer")
            engine_cfg = dataclasses.replace(engine_cfg, **{k: val})
        sim = Simulation(scenario, topology=topo, engine_cfg=engine_cfg)
    else:
        sim = Simulation(scenario)
    import jax.numpy as jnp
    cc = {"aimd": 0, "reno": 1, "cubic": 2}[args.tcp_congestion_control]
    if cc != sim.cfg.cc_kind:
        sim.sh = sim.sh.replace(cc_kind=jnp.int32(cc))
    if args.tcp_windows != 10.0:
        sim.sh = sim.sh.replace(tcp_init_wnd=jnp.float32(args.tcp_windows))
    if args.tcp_ssthresh:
        sim.sh = sim.sh.replace(
            tcp_ssthresh0=jnp.float32(args.tcp_ssthresh))
    if args.runahead:
        ra = parse_time(args.runahead, default_unit="ms")
        true_min = int(sim.sh.min_jump)
        if ra > true_min:
            logger.warning(
                0, "main",
                f"runahead {ra}ns exceeds the minimum path latency "
                f"{true_min}ns: cross-host arrivals may execute late "
                "(the reference gives the same warning)")
        sim.sh = sim.sh.replace(min_jump=jnp.int64(max(ra, 1)))
    qd = {"fifo": 0, "rr": 1}[args.interface_qdisc]
    if qd != sim.cfg.qdisc:
        import dataclasses
        sim.cfg = dataclasses.replace(sim.cfg, qdisc=qd)

    mesh = None
    if args.workers:
        from .parallel.shard import make_mesh
        mesh = make_mesh(args.workers)

    if args.aot_cache:
        from .serving import aotcache as AC
        AC.install(args.aot_cache)

    if args.shape_fingerprint or args.prewarm:
        # serving-layer probes (docs/serving.md): both run AFTER every
        # engine-knob override above (qdisc/caps mutate the compiled
        # shape), so the fingerprint/program matches what a real run
        # of this exact command line would build
        from .obs.ledger import fingerprint_of
        from .obs import digest as DG
        if args.shape_fingerprint:
            # the compiled-shape identity is fingerprint AND effective
            # chunk (hosted runs chunk at 1; a digest cadence shrinks
            # it) — two runs sharing a config fingerprint but chunking
            # differently compile DIFFERENT programs, so the prewarm
            # dedup keys on the composite `shape` (serving.prewarm)
            chunk = sim.effective_chunk(
                (args.digest_every or DG.DEFAULT_EVERY)
                if args.digest else 0)
            fp = fingerprint_of(sim.cfg)
            # w<N> folds the mesh dimension in: --workers compiles
            # the SHARDED program (run_windows_sharded), a different
            # executable than the single-chip one — the two must
            # never dedup onto one pre-warm slot
            print(json.dumps(
                {"shape_fingerprint": fp,
                 "shape": f"c{chunk}.w{args.workers or 0}.{fp}",
                 "chunk": chunk,
                 "hosts": scenario.total_hosts(),
                 "workers": args.workers}))
            return 0
        from .serving import aotcache as AC
        info = sim.prewarm(
            mesh=mesh,
            digest_every=((args.digest_every or DG.DEFAULT_EVERY)
                          if args.digest else 0))
        st = AC.STATS
        info["compile_cache"] = ("miss" if st["compiles"] else "hit")
        info["cache_dir"] = args.aot_cache
        print(json.dumps(info))
        logger.message(0, "main",
                       f"prewarm: shape {info['fingerprint']} "
                       f"{info['compile_cache']} "
                       f"(compile {st['compile_wall_s']:.1f}s, "
                       f"load {st['load_wall_s']:.1f}s)")
        return 0

    # --perf: install the span recorder ourselves (in-memory when no
    # --trace path was given) so the phase attribution + ledger append
    # below can read the retired tracer — run() sees it installed and
    # leaves the lifecycle to us (the bench.py outer-harness pattern)
    own_perf_tr = False
    if args.perf is not None:
        from .obs import trace as TR
        if not TR.ENABLED:
            TR.install(args.trace)
            own_perf_tr = True

    # preemption protocol (docs/fleet.md): with a checkpoint store
    # active, SIGTERM means "save a snapshot at the next chunk
    # boundary and exit 75 (resumable)" instead of dying with work
    # lost — the contract the fleet scheduler and any preempting
    # cluster manager rely on. Installed only in the main thread
    # (signal API constraint; embedders call request_preempt
    # themselves).
    if args.checkpoint:
        import signal as _signal
        import threading as _threading
        if _threading.current_thread() is _threading.main_thread():
            from .engine.sim import request_preempt
            _signal.signal(_signal.SIGTERM,
                           lambda s, f: request_preempt())

    # the digest context records the CLI invocation in the manifest —
    # the replay context tools/divergence.py --bisect needs
    dg_ctx = ({"argv": list(argv) if argv is not None else sys.argv[1:],
               "config_path": args.config}
              if args.digest else None)
    from .engine.sim import Preempted
    try:
        report = sim.run(verbose=args.verbose, mesh=mesh,
                         heartbeat_s=args.heartbeat_frequency,
                         logger=logger,
                         checkpoint_path=args.checkpoint,
                         checkpoint_every_s=args.checkpoint_every,
                         checkpoint_keep=args.checkpoint_keep,
                         resume_from=args.resume, pcap_dir=args.pcap_dir,
                         trace=None if own_perf_tr else args.trace,
                         metrics=args.metrics,
                         digest=args.digest,
                         digest_every=args.digest_every,
                         digest_context=dg_ctx,
                         netscope=args.netscope,
                         passcope=args.passcope)
    except Preempted as pe:
        from .engine.supervisor import EXIT_PREEMPTED
        logger.message(pe.sim_ns, "main",
                       f"preempted: {pe} — resume with "
                       "--resume latest")
        return EXIT_PREEMPTED
    s = report.summary()
    if own_perf_tr:
        # phase attribution + ledger append (obs.perf / obs.ledger):
        # the retired tracer's spans name where the wall went; the
        # ledger line extends this scenario's durable trajectory
        # (tools/perf_regress.py gates on it)
        from .obs import ledger as LG
        from .obs import perf as PF
        from .obs import trace as TR
        import jax
        tr = TR.finish()
        att = PF.attribute(tr.events, report.wall_seconds,
                           report.events)
        print(PF.format_report(att))
        if args.resume:
            # a resumed run's events span the WHOLE run (restored
            # stats) but its wall covers only the tail — the rate is
            # inflated and would poison the gated trajectory. The
            # phase table above is still the point of --perf here.
            logger.message(report.sim_time_ns, "main",
                           "perf ledger: skipping append for a "
                           "resumed run (tail-only wall would "
                           "inflate the rate)")
        scen_label = ("test" if args.test else
                      os.path.splitext(
                          os.path.basename(args.config))[0])
        entry = None if args.resume else LG.entry_from_report(
            scen_label,
            LG.fingerprint_of(sim.cfg, seed=scenario.seed,
                              stop_ns=int(scenario.stop_time),
                              runahead=args.runahead or "",
                              workers=args.workers),
            jax.default_backend(), report, att, cfg=sim.cfg)
        lpath = (LG.append(entry, args.perf or None)
                 if entry is not None else None)
        if lpath:
            logger.message(report.sim_time_ns, "main",
                           f"perf ledger += {lpath}")
    if args.passcope is not None or report.device_phases:
        # pass-time observatory read-out (obs.passcope): the decoded
        # per-pass device table + lockstep-occupancy block
        from .obs import passcope as PCOPE
        print(PCOPE.format_report(report.device_phases or None,
                                  report.occupancy or None))
    logger.message(report.sim_time_ns, "main",
                   f"done: {s['events']} events in {s['wall_seconds']:.2f}s "
                   f"wall ({s['events_per_sec']:.0f} ev/s, "
                   f"speedup x{s['speedup']:.2f})")
    if report.network:
        # network observatory read-out: per-kind sample count + exact
        # p50/p99 from the device histograms
        for kind, kk in report.network.get("kinds", {}).items():
            if kk["count"]:
                logger.message(
                    report.sim_time_ns, "main",
                    f"netscope {kind}: n={kk['count']} "
                    f"p50={kk['p50_us']}us p99={kk['p99_us']}us")
    # robustness accounting: applied faults + hosted-process exits
    for rec in report.faults:
        logger.message(report.sim_time_ns, "main",
                       f"fault applied: {rec}")
    for hname, info in sorted(report.hosted.items()):
        line = (f"hosted {hname}: exit_status="
                f"{info.get('exit_status')} cause={info.get('cause')}")
        if info.get("clean", False):
            logger.message(report.sim_time_ns, "main", line)
        else:
            logger.warning(report.sim_time_ns, "main", line)
    # end-of-run capacity accounting (reference ObjectCounter report)
    for row in report.capacity_report():
        line = (f"capacity {row['array']}: peak {row['peak']}"
                f"/{row['capacity']}, overflow {row['overflow']}")
        if row["overflow"]:
            logger.warning(report.sim_time_ns, "main", line)
        else:
            logger.message(report.sim_time_ns, "main", line)
    if args.summary_json:
        print(json.dumps(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
