"""Dotted-name resolution over one module's AST.

Both check families need to answer "what does this call expression
actually refer to?" through import aliases::

    import time as _time          _time.perf_counter() -> time.perf_counter
    import numpy as np            np.random.rand()     -> numpy.random.rand
    from datetime import datetime datetime.now()       -> datetime.datetime.now
    from ..core.jitcache import AotJit   AotJit(f)     -> shadow_tpu.core.jitcache.AotJit

Resolution is purely lexical (no execution): aliases are collected
from EVERY import statement in the file (module or function level —
this codebase imports lazily inside functions a lot), which
over-approximates scoping but is exactly right for lint purposes.
"""

from __future__ import annotations

import ast


def module_name_of(relpath: str) -> str:
    """Repo-relative path -> dotted module name
    (shadow_tpu/engine/window.py -> shadow_tpu.engine.window)."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _resolve_relative(module: str | None, level: int,
                      pkg: str) -> str | None:
    """`from ..core import x` inside package `pkg` -> absolute module."""
    if level == 0:
        return module
    parts = pkg.split(".")
    if level > len(parts):
        return None
    base = parts[: len(parts) - (level - 1)]
    if module:
        base.append(module)
    return ".".join(base)


class AliasMap:
    """local name -> absolute dotted target for one module."""

    def __init__(self, tree: ast.AST, relpath: str):
        self.module = module_name_of(relpath)
        # the package this module's relative imports resolve against
        self.package = (self.module if relpath.endswith("__init__.py")
                        else self.module.rsplit(".", 1)[0]
                        if "." in self.module else self.module)
        self.aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    # `import a.b.c` binds `a`; `import a.b as x` binds
                    # x -> a.b
                    self.aliases[local] = (a.name if a.asname
                                           else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                src = _resolve_relative(node.module, node.level,
                                        self.package)
                if src is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    self.aliases[local] = f"{src}.{a.name}"

    def resolve(self, node: ast.AST) -> str | None:
        """Expression -> absolute dotted name, or None. Handles Name
        and Attribute chains rooted at an imported alias; a bare Name
        that is not an import alias resolves to itself (builtins,
        locals) so callers can match e.g. `hash`."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])


def call_name(alias_map: AliasMap, call: ast.Call) -> str | None:
    return alias_map.resolve(call.func)


def first_arg_names(call: ast.Call):
    """Names referenced anywhere in a call's first positional arg."""
    if not call.args:
        return set()
    return {n.id for n in ast.walk(call.args[0])
            if isinstance(n, ast.Name)}
