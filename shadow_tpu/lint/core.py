"""simlint core: rule registry, violations, suppressions, baseline.

Shared mechanics for the three check families. A violation is keyed by
``(rule, file, snippet)`` — the stripped source line, NOT the line
number — so unrelated edits above a baselined site do not churn the
baseline (tools/simlint/baseline.json), while any edit to the flagged
line itself surfaces the violation again for a fresh look.

Suppression: a violation is silenced by an inline comment on the same
line (or the line directly above)::

    t0 = time.perf_counter()  # simlint: ok DET101 -- wall attribution

The justification after ``--`` (or an em dash, or parentheses) is
REQUIRED: a bare ``simlint: ok`` is itself a violation (LNT001). The
allowlist below covers whole files whose *purpose* is the flagged
behavior (wall-clock observability), so their every line doesn't need
a comment.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

# --- rule registry (docs/static-analysis.md mirrors this catalog) ----

RULES: dict[str, dict] = {}


def rule(rid: str, summary: str, hint: str) -> str:
    RULES[rid] = {"summary": summary, "hint": hint}
    return rid


LNT001 = rule(
    "LNT001", "simlint suppression without a justification",
    "write `# simlint: ok <RULE> -- <why this site is legitimate>`")
LNT002 = rule(
    "LNT002", "stale baseline entry (violation no longer present)",
    "the underlying violation was fixed — remove the entry from "
    "tools/simlint/baseline.json (or run --fix-baseline)")

# Whole-file allowlist: rule -> {repo-relative posix path: why}. These
# files' PURPOSE is the flagged behavior; per-line suppressions would
# be noise. Anything else must suppress inline or baseline.
ALLOW: dict[str, dict[str, str]] = {
    "DET101": {
        "shadow_tpu/obs/trace.py":
            "wall-clock span recorder: perf_counter IS the product",
        "shadow_tpu/obs/metrics.py":
            "wall-clock latency histograms: timing IS the product",
        "shadow_tpu/obs/perf.py":
            "wall-clock phase attribution: timing IS the product",
        "shadow_tpu/obs/tracker.py":
            "heartbeat wall/realtime-ratio reporting",
        "shadow_tpu/obs/logger.py":
            "wall-clock progress log timestamps",
        "shadow_tpu/obs/ledger.py":
            "perf ledger stamps wall times of finished runs",
        # fleet/ (in scope since PR 11): host-side sweep orchestration.
        # Wall time here schedules WORKERS, never simulations — run
        # determinism is carried by the per-run digest chains, which
        # the fleet chaos tests prove byte-identical under arbitrary
        # scheduling (tests/test_fleet.py). The other DET rules still
        # apply: the queue journal fold must stay order-deterministic.
        "shadow_tpu/fleet/queue.py":
            "journal lines stamp wall timestamps; claims use wall "
            "mtimes (durable-queue bookkeeping, not sim state)",
        "shadow_tpu/fleet/scheduler.py":
            "backoff arithmetic, lock takeover and reap timing are "
            "wall-clock scheduling — the scheduler's purpose",
        "shadow_tpu/fleet/worker.py":
            "progress watchdog compares wall mtimes of run artifacts "
            "(hung-run detection IS the product)",
        # serving/ (in scope since PR 13): host-side compile/serve
        # orchestration. Wall time here measures COMPILES and paces
        # child watchdogs, never simulations — cached, pre-warmed and
        # batched runs are proven byte-identical to cold individual
        # runs by digest chains (tests/test_serving.py).
        "shadow_tpu/serving/aotcache.py":
            "compile/disk-load wall tallies (jitcache.* metrics and "
            "the compile-hit/miss phase split ARE the product)",
        "shadow_tpu/serving/prewarm.py":
            "probe/warm child deadlines are wall-clock watchdogs "
            "(the fleet worker contract, one level down)",
        "shadow_tpu/serving/batch.py":
            "batch wall / first-chunk-wall measurement feeding "
            "SimReport and the perf ledger (obs-style reporting)",
    },
}


@dataclasses.dataclass
class Violation:
    rule: str
    file: str          # repo-relative posix path
    line: int
    message: str
    snippet: str = ""  # stripped source line at `line` (baseline key)
    hint: str = ""

    @property
    def key(self):
        return (self.rule, self.file, self.snippet)

    def render(self) -> str:
        hint = self.hint or RULES.get(self.rule, {}).get("hint", "")
        tail = f"  [fix: {hint}]" if hint else ""
        return f"{self.file}:{self.line}: {self.rule} {self.message}{tail}"


def fill_snippets(violations, lines_of):
    """Stamp each violation's snippet from its source line. `lines_of`
    maps repo-relative path -> list of line strings (or None).

    Violations without a source line (line 0 — the SHIM2xx conformance
    family) key by their MESSAGE instead: an empty snippet would
    collapse every such violation in a file to one baseline key, and a
    pinned entry would then silently absorb any later, *different*
    drift of the same rule."""
    for v in violations:
        if v.snippet:
            continue
        lines = lines_of(v.file)
        if lines and 1 <= v.line <= len(lines):
            v.snippet = lines[v.line - 1].strip()[:200]
        else:
            v.snippet = v.message[:200]


# --- inline suppressions ---------------------------------------------

# `# simlint: ok DET101` / `ok DET101,TRC103 -- reason` / `(reason)`
_SUPPRESS_RE = re.compile(
    r"simlint:\s*ok\s+(?P<rules>[A-Z]{3,4}\d{3}(?:\s*,\s*[A-Z]{3,4}\d{3})*)"
    r"\s*(?:(?:--|—|–|[(])\s*(?P<why>[^)]*))?")


def _suppressions_at(lines, lineno):
    """Suppression directives covering `lineno`: the line itself or
    the line directly above. -> (set of rule ids, has_justification)"""
    rules, justified = set(), True
    for ln in (lineno, lineno - 1):
        if not (1 <= ln <= len(lines)):
            continue
        m = _SUPPRESS_RE.search(lines[ln - 1])
        if m:
            rules |= {r.strip() for r in m.group("rules").split(",")}
            if not (m.group("why") or "").strip():
                justified = False
    return rules, justified


def apply_suppressions(violations, lines_of):
    """Filter inline-suppressed violations. Returns (active,
    suppressed_count, extra) where extra holds LNT001 violations for
    suppressions missing a justification."""
    active, extra, suppressed = [], [], 0
    flagged_unjustified = set()
    for v in violations:
        lines = lines_of(v.file)
        if not lines:
            active.append(v)
            continue
        rules, justified = _suppressions_at(lines, v.line)
        if v.rule in rules:
            if justified:
                suppressed += 1
            else:
                sup_key = (v.file, v.line)
                if sup_key not in flagged_unjustified:
                    flagged_unjustified.add(sup_key)
                    extra.append(Violation(
                        "LNT001", v.file, v.line,
                        f"suppression of {v.rule} has no justification",
                        snippet=lines[v.line - 1].strip()[:200]))
                suppressed += 1
        else:
            active.append(v)
    return active, suppressed, extra


def apply_allowlist(violations):
    """Drop violations covered by the whole-file ALLOW map."""
    kept, allowed = [], 0
    for v in violations:
        if v.file in ALLOW.get(v.rule, {}):
            allowed += 1
        else:
            kept.append(v)
    return kept, allowed


# --- baseline --------------------------------------------------------

def load_baseline(path: str) -> dict:
    """baseline.json -> {key: entry}. Missing file = empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out = {}
    for e in data.get("entries", []):
        key = (e["rule"], e["file"], e.get("snippet", ""))
        if key in out:
            out[key]["count"] += int(e.get("count", 1))
        else:
            out[key] = {"count": int(e.get("count", 1)),
                        "justification": e.get("justification", "")}
    return out


def diff_baseline(violations, baseline):
    """Split current violations against the pinned baseline.

    -> (new_violations, baselined_count, stale) where stale is a list
    of LNT002 violations: baseline entries whose violation count
    DROPPED (fixed ones must be removed from the baseline, so the
    pinned debt only ever shrinks deliberately)."""
    by_key: dict[tuple, list] = {}
    for v in violations:
        by_key.setdefault(v.key, []).append(v)
    new, baselined = [], 0
    for key, vs in by_key.items():
        allowed = baseline.get(key, {}).get("count", 0)
        vs_sorted = sorted(vs, key=lambda v: v.line)
        baselined += min(allowed, len(vs))
        new.extend(vs_sorted[allowed:])
    stale = []
    for key, entry in baseline.items():
        have = len(by_key.get(key, ()))
        if have < entry["count"]:
            rid, file, snippet = key
            stale.append(Violation(
                "LNT002", file, 0,
                f"baselined {rid} x{entry['count']} but only {have} "
                f"remain (snippet: {snippet[:60]!r})",
                snippet=snippet))
    return new, baselined, stale


def write_baseline(path: str, violations, old_baseline) -> int:
    """--fix-baseline: pin the CURRENT violation set. Justifications of
    surviving entries are preserved; new entries get a placeholder that
    a reviewer is expected to replace. Returns the entry count.

    LNT meta-violations are never pinned: baselining an LNT001
    (suppression without justification) would permanently defeat the
    justification requirement through the one-command adoption path."""
    by_key: dict[tuple, int] = {}
    for v in violations:
        if v.rule.startswith("LNT"):
            continue
        by_key[v.key] = by_key.get(v.key, 0) + 1
    entries = []
    for (rid, file, snippet), count in sorted(by_key.items()):
        just = old_baseline.get((rid, file, snippet), {}).get(
            "justification") or ("pre-existing violation pinned by "
                                 "--fix-baseline; justify or fix")
        entries.append({"rule": rid, "file": file, "snippet": snippet,
                        "count": count, "justification": just})
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1)
        f.write("\n")
    return len(entries)


# --- source cache ----------------------------------------------------

class SourceCache:
    """Read-once cache of repo files: text, split lines, parsed AST."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._text: dict[str, str | None] = {}
        self._lines: dict[str, list | None] = {}
        self._tree: dict[str, object] = {}

    def rel(self, path: str) -> str:
        return os.path.relpath(os.path.abspath(path),
                               self.root).replace(os.sep, "/")

    def text(self, relpath: str):
        if relpath not in self._text:
            full = os.path.join(self.root, relpath)
            try:
                with open(full, encoding="utf-8",
                          errors="replace") as f:
                    self._text[relpath] = f.read()
            except OSError:
                self._text[relpath] = None
        return self._text[relpath]

    def lines(self, relpath: str):
        if relpath not in self._lines:
            text = self.text(relpath)
            self._lines[relpath] = (None if text is None
                                    else text.splitlines())
        return self._lines[relpath]

    def tree(self, relpath: str):
        """Parsed AST of a Python source, or a SyntaxError instance,
        cached (both check families scan overlapping scopes)."""
        if relpath not in self._tree:
            import ast
            text = self.text(relpath)
            if text is None:
                self._tree[relpath] = None
            else:
                try:
                    self._tree[relpath] = ast.parse(text)
                except SyntaxError as e:
                    self._tree[relpath] = e
        return self._tree[relpath]

    def py_files(self, subdirs) -> list:
        """Repo-relative .py paths under the given subdirectories,
        sorted for deterministic report order."""
        out = []
        for sub in subdirs:
            base = os.path.join(self.root, sub)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(self.rel(os.path.join(dirpath, fn)))
        return out
