"""simlint CLI: run the suite, report, gate against the baseline.

Usage (from the repo root)::

    python -m tools.simlint                  # the tier-1/CI gate
    python -m tools.simlint --list-rules     # rule catalog
    python -m tools.simlint --fix-baseline   # pin current violations
    python -m tools.simlint --json           # machine-readable report

Exit codes: 0 clean (every violation fixed, suppressed with
justification, or baselined), 1 violations (new findings OR stale
baseline entries), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import importlib

# NOT `from . import determinism, ...`: that statement's fromlist
# handling re-imports each submodule through the C-level
# builtins.__import__ with a plain dotted name, which walks to and
# returns the ROOT package — under the standalone tools.simlint
# loader `shadow_tpu` itself is deliberately absent from sys.modules,
# so the walk executes shadow_tpu/__init__.py and imports jax
# (2s of the "sub-second" gate; a hard crash on a jax-free CI box).
# import_module resolves the leaf directly and never touches the
# root. Pinned by tests/test_lint.py::test_gate_runs_without_jax.
determinism = importlib.import_module(f"{__package__}.determinism")
shimproto = importlib.import_module(f"{__package__}.shimproto")
stateflow = importlib.import_module(f"{__package__}.stateflow")
tracing = importlib.import_module(f"{__package__}.tracing")

from .core import (RULES, SourceCache, apply_allowlist,  # noqa: E402
                   apply_suppressions, diff_baseline, fill_snippets,
                   load_baseline, write_baseline)

DEFAULT_BASELINE = "tools/simlint/baseline.json"


def find_root(start: str = None) -> str:
    """Locate the repo root: the nearest ancestor holding
    shadow_tpu/."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(d, "shadow_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            print("simlint: cannot locate the repo root (no "
                  "shadow_tpu/ above the working directory); pass "
                  "--root", file=sys.stderr)
            raise SystemExit(2)
        d = parent


def collect(cache: SourceCache) -> list:
    """All four families, raw (pre-suppression/baseline). The tracing
    module index (~1.5s to build) is shared between the two families
    that need it."""
    project = tracing._Project(cache)
    out = []
    out.extend(determinism.check(cache))
    out.extend(tracing.check(cache, project=project))
    out.extend(shimproto.check(cache))
    out.extend(stateflow.check(cache, project=project))
    return out


def run_lint(root: str, baseline_path: str = None,
             fix_baseline: bool = False) -> dict:
    """Run the full suite. Returns a report dict (see keys below);
    `exit_code` is the gate verdict."""
    cache = SourceCache(root)
    scanned = (cache.py_files(determinism.SCOPE)
               + cache.py_files(tracing.SCOPE))
    if not scanned:
        # an empty scan would pass VACUOUSLY — a wrong --root or a
        # renamed scope must be an error, never a green gate
        print(f"simlint: nothing to scan under {root!r} (no Python "
              "files in the lint scopes); wrong --root?",
              file=sys.stderr)
        raise SystemExit(2)
    raw = collect(cache)
    fill_snippets(raw, cache.lines)
    active, suppressed, unjustified = apply_suppressions(
        raw, cache.lines)
    active, allowed = apply_allowlist(active)
    active.extend(unjustified)

    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    baseline = load_baseline(baseline_path)

    if fix_baseline:
        n = write_baseline(baseline_path, active, baseline)
        return {"exit_code": 0, "fixed_baseline": n,
                "baseline_path": baseline_path, "new": [],
                "stale": [], "baselined": len(active),
                "suppressed": suppressed, "allowed": allowed,
                "total": len(active)}

    new, baselined, stale = diff_baseline(active, baseline)
    new.sort(key=lambda v: (v.file, v.line, v.rule))
    stale.sort(key=lambda v: (v.file, v.snippet))
    return {"exit_code": 1 if (new or stale) else 0,
            "baseline_path": baseline_path,
            "new": new, "stale": stale, "baselined": baselined,
            "suppressed": suppressed, "allowed": allowed,
            "total": len(active)}


def _print_report(report: dict, as_json: bool):
    if as_json:
        out = {k: ([dataclasses_asdict(v) for v in report[k]]
                   if k in ("new", "stale") else report[k])
               for k in report}
        print(json.dumps(out, indent=1))
        return
    if "fixed_baseline" in report:
        print(f"simlint: baseline rewritten with "
              f"{report['fixed_baseline']} entries "
              f"({report['baseline_path']})")
        return
    for v in report["new"]:
        print(v.render())
    for v in report["stale"]:
        print(v.render())
    status = "FAIL" if report["exit_code"] else "clean"
    print(f"simlint: {status} — {len(report['new'])} new, "
          f"{len(report['stale'])} stale baseline entries "
          f"({report['baselined']} baselined, "
          f"{report['suppressed']} suppressed inline, "
          f"{report['allowed']} allowlisted)")


def dataclasses_asdict(v):
    return {"rule": v.rule, "file": v.file, "line": v.line,
            "message": v.message, "snippet": v.snippet}


def _list_rules():
    for rid in sorted(RULES):
        r = RULES[rid]
        print(f"{rid}  {r['summary']}")
        print(f"        fix: {r['hint']}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="simlint",
        description="shadow-tpu determinism & tracing-hazard static "
                    "analysis (docs/static-analysis.md)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect upward)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline path (default: {DEFAULT_BASELINE})")
    p.add_argument("--fix-baseline", action="store_true",
                   help="pin every current violation into the "
                        "baseline and exit 0 (one-command adoption)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2
    if args.list_rules:
        _list_rules()
        return 0
    try:
        root = args.root or find_root()
        report = run_lint(root, baseline_path=args.baseline,
                          fix_baseline=args.fix_baseline)
    except SystemExit:
        raise
    except Exception as e:  # internal error: distinct exit code
        print(f"simlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    _print_report(report, args.json)
    return report["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
