"""State-access & dtype-flow analysis (STF3xx/STF4xx): the machine-
checked contract behind the hot/cold socket-table split.

ROADMAP item 1 wants the ~45-column ``sk_*`` socket table split into
hot rows vs cold columns so the lockstep drain's per-pass gather
touches a hot working set only. That split is only safe — and only
STAYS safe — if something can say which ``Hosts`` columns each jitted
pass actually reads and writes. This module computes exactly that: a
pure-stdlib abstract interpretation over the project AST (reusing the
``tracing`` module index and name resolution) that follows
``Hosts``/``HostParams``/``Shared`` pytree values through attribute
access, ``.replace(...)`` kwargs (including the ``**{f: ... for f in
_FIELDS}`` idiom), ``getattr`` field names, tuple unpacking, closures,
``jax.lax`` combinators (cond/switch/while_loop/fori_loop), ``vmap``/
``functools.partial`` wrappers and helper-function boundaries — and
produces a per-entry pass x field **access matrix** (read / written /
shape-only / untouched), with every access pinned to its source site.

On top of the matrix, two gated rule families:

- **STF3xx access contracts**: every ``Hosts`` field must map to a
  declared ``STATE_SECTIONS`` section (``section_of`` returning
  ``"other"`` silently mis-buckets digests/checkpoints); dead and
  write-only columns are flagged; and the declarative
  ``engine/state.py`` ``COLD_FIELDS`` annotation is enforced — a
  cold-marked column read or written inside the drain-pass subgraph
  fails the build, so a cold column cannot creep back into the
  per-pass working set unnoticed.
- **STF4xx dtype flow**: i32 column values flowing into i64 ns
  arithmetic without explicit widening, f32 congestion-window values
  compared against i64 byte quantities (f32 holds 24 mantissa bits —
  silently lossy past 16 MiB), and SIMTIME_MAX-sentinel comparisons
  against non-i64 operands (the reference's ``guint64`` ns clock is
  the invariant being protected).

The machine-readable matrix is exported by ``tools/state_matrix.py``
(--json/--markdown), so the actual split PR starts from ground truth
and stays gated afterwards. Branches on static config (``cfg.*``) are
all traversed: the matrix is the UNION over engine configurations.
"""

from __future__ import annotations

import ast
import re

from .core import Violation, rule
from .tracing import _Project

STF300 = rule(
    "STF300", "stateflow analysis integrity failure",
    "the analyzer could not build a trustworthy matrix (state.py "
    "unparseable, entry passes renamed, or a vacuous drain scan); fix "
    "the wiring — never baseline this rule")
STF301 = rule(
    "STF301", "Hosts field maps to no STATE_SECTIONS section",
    "add a (prefix, section) entry in engine/state.py STATE_SECTIONS "
    "next to the new field; `other` silently mis-buckets digest and "
    "divergence attribution")
STF302 = rule(
    "STF302", "dead or write-only Hosts column",
    "no analyzed pass reads this field and it is not declared "
    "host-consumed (stateflow.HOST_CONSUMED); delete the column or "
    "declare its host-side consumer")
STF303 = rule(
    "STF303", "cold-marked column touched in the drain-pass subgraph",
    "engine/state.py COLD_FIELDS promises this column stays out of "
    "the lockstep drain's working set; move the access to a window-"
    "boundary phase or un-mark the column (docs/static-analysis.md)")
STF304 = rule(
    "STF304", "COLD_WHEN contract error",
    "a config-gated cold column must name an existing Hosts field "
    "that is in the static HOT_FIELDS set and not in COLD_FIELDS — "
    "the level-2 split only gates columns the drain statically "
    "touches (docs/static-analysis.md)")
STF401 = rule(
    "STF401", "i32 column flows into i64 arithmetic without widening",
    "add .astype(jnp.int64) at the source; implicit promotion hides "
    "intent and an i32 intermediate overflows silently at 2^31")
STF402 = rule(
    "STF402", "f32 congestion value compared against an i64 quantity",
    "widen with .astype(jnp.int64) first (tcp._win_bytes does); an "
    "f32 operand quantizes i64 byte offsets above 2^24")
STF403 = rule(
    "STF403", "SIMTIME_MAX sentinel compared against a non-i64 operand",
    "SIMTIME_MAX is the i64 ns clock's infinity; comparing it against "
    "an i32/f32 value can never be true (or truncates) — widen the "
    "operand")
STF404 = rule(
    "STF404", "narrowed column lacks a machine-checked bound",
    "every NARROW_SPEC entry in engine/state.py must name an existing "
    "Hosts field, carry known wide/narrow dtypes with the narrow one "
    "strictly smaller, a positive bound that fits the narrow dtype's "
    "range, a rel: anchor that is itself an abs-narrowed Hosts column, "
    "and a non-empty invariant note — a shrink without its proof is "
    "how 2^31 overflows land silently (docs/performance.md)")

STATE_PATH = "shadow_tpu/engine/state.py"

# ---------------------------------------------------------------------
# The analyzed entry passes. One matrix column per entry: the
# coarse window phases (drain / exchange / cap-peak sampling) plus the
# individually-testable event-handler passes. Param names map to the
# pytree kind they carry. The `drain` entry's subgraph — everything the
# lockstep pass loop reaches, handlers and TCP/NIC/SACK machinery
# included — is what the STF303 cold-column contract gates.

HOSTS, HP, SH = "hosts", "hp", "sh"

ENTRIES = (
    # (entry, fqn, {param: kind}, in_drain_subgraph)
    ("drain", "shadow_tpu.engine.window.drain_window",
     {"hosts": HOSTS, "hp": HP, "sh": SH}, True),
    ("exchange", "shadow_tpu.engine.window.exchange",
     {"hosts": HOSTS, "hp": HP, "sh": SH}, False),
    ("exchange.sharded", "shadow_tpu.parallel.shard.exchange_sharded",
     {"hosts": HOSTS, "hp": HP, "sh": SH}, False),
    ("cap_peaks", "shadow_tpu.engine.window.update_cap_peaks",
     {"hosts": HOSTS}, False),
    ("advance", "shadow_tpu.engine.window.next_wakeup",
     {"hosts": HOSTS}, False),
    ("nic.tx", "shadow_tpu.net.nic.on_tx",
     {"row": HOSTS, "hp": HP, "sh": SH}, False),
    ("nic.rx_admit", "shadow_tpu.net.nic.rx_admit",
     {"row": HOSTS, "hp": HP}, False),
    ("tcp.rx", "shadow_tpu.net.tcp.tcp_rx",
     {"row": HOSTS, "hp": HP, "sh": SH}, False),
    ("tcp.timer", "shadow_tpu.net.tcp.on_tcp_timer",
     {"row": HOSTS, "hp": HP, "sh": SH}, False),
    ("udp.deliver", "shadow_tpu.net.udp.udp_deliver",
     {"row": HOSTS, "hp": HP, "sh": SH}, False),
    ("channel.write", "shadow_tpu.net.channel.pipe_write",
     {"row": HOSTS}, False),
)

# Hosts columns whose READER is host-side Python, not a jitted pass —
# each with the consumer that justifies it. STF302 treats these as
# read. Everything else written-but-never-read is a dead column.
HOST_CONSUMED = {
    "stats": "SimReport stat table (engine/sim.py summary)",
    "cap_peaks": "end-of-run capacity report (sim.py; ObjectCounter "
                 "analogue)",
    "tr_time": "pcap drain (obs/pcap.py reads the ring per chunk)",
    "tr_pkt": "pcap drain (obs/pcap.py)",
    "tr_dir": "pcap drain (obs/pcap.py)",
    "tr_drop": "trace-ring overflow accounting (sim.py report)",
    "hw_time": "hosted-wake drain (hosting/runtime.py per chunk)",
    "hw_pkt": "hosted-wake drain (hosting/runtime.py)",
    "hw_drop": "hosted-wake overflow accounting (sim.py report)",
}

_DT = {"int8": "i8", "int16": "i16", "int32": "i32", "int64": "i64",
       "uint8": "u8", "uint16": "u16", "uint32": "u32", "uint64": "u64",
       "float16": "f16", "float32": "f32", "float64": "f64",
       "bool_": "bool", "bool": "bool"}

_COMMENT_DT = re.compile(r"\b(i32|i64|u32|f32|f64|bool)\b")


# --- the state model: fields, dtypes, sections, cold set -------------
# Parsed from engine/state.py's AST (never imported: chex pulls jax),
# so the model re-syncs with the source on every run.

class StateModel:
    def __init__(self):
        self.fields = {HOSTS: {}, HP: {}, SH: {}}  # name -> dtype
        self.linenos = {}          # Hosts field -> state.py line
        self.sections = []         # [(prefix, section)]
        self.cold = set()          # COLD_FIELDS
        self.hot = ()              # HOT_FIELDS literal (may be absent
        #                            in fixture repos — see hot_set())
        self.cold_when = []        # [(guard, (fields...))] COLD_WHEN
        self.narrow = []           # NARROW_SPEC entries (STF404)
        self.errors = []           # human-readable parse failures
        self.missing = False       # no state.py at all (fixture repo)

    def hot_set(self) -> tuple:
        """The static hot working set: the declared HOT_FIELDS
        literal, or (fixture repos without one) the complement of
        COLD_FIELDS. This is what a `hot_fields(cfg)` call is modeled
        as returning — the union over configs, which is exactly the
        conservative contract the drain matrix states."""
        if self.hot:
            return self.hot
        return tuple(f for f in self.fields[HOSTS]
                     if f not in self.cold)

    def section_of(self, field: str):
        for prefix, section in self.sections:
            if field.startswith(prefix):
                return section
        return None

    def dtype_of(self, kind: str, field: str) -> str:
        return self.fields.get(kind, {}).get(field, "?")


_CLASS_KINDS = {"Hosts": HOSTS, "HostParams": HP, "Shared": SH}


def _dtype_from_node(node) -> str | None:
    """`jnp.int64` / `np.float32`-style attribute -> short dtype."""
    if isinstance(node, ast.Attribute):
        return _DT.get(node.attr)
    return None


def load_state_model(cache) -> StateModel:
    m = StateModel()
    tree = cache.tree(STATE_PATH)
    lines = cache.lines(STATE_PATH) or []
    if tree is None:
        # no state.py at all: a fixture repo exercising another
        # family — skip, like shimproto's both-sides-missing rule
        # (the real repo's presence is pinned by test_stateflow)
        m.missing = True
        return m
    if isinstance(tree, SyntaxError):
        m.errors.append(f"{STATE_PATH} unparseable: {tree.msg}")
        return m
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name in _CLASS_KINDS:
            kind = _CLASS_KINDS[node.name]
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    name = stmt.target.id
                    # dtype from the same-line annotation comment
                    # (authoritative for HostParams; Hosts/Shared get
                    # overridden from the constructors below)
                    dt = "?"
                    if 1 <= stmt.lineno <= len(lines):
                        _, _, comment = lines[stmt.lineno - 1].partition(
                            "#")
                        hit = _COMMENT_DT.search(comment)
                        if hit:
                            dt = hit.group(1)
                    m.fields[kind][name] = dt
                    if kind == HOSTS:
                        m.linenos[name] = stmt.lineno
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            if tname == "STATE_SECTIONS":
                try:
                    m.sections = [tuple(e) for e in
                                  ast.literal_eval(node.value)]
                except (ValueError, TypeError):
                    m.errors.append("STATE_SECTIONS not a literal "
                                    "tuple of (prefix, section) pairs")
            elif tname == "COLD_FIELDS":
                val = node.value
                if isinstance(val, ast.Call) and val.args:
                    val = val.args[0]    # frozenset({...})
                try:
                    m.cold = set(ast.literal_eval(val))
                except (ValueError, TypeError):
                    m.errors.append("COLD_FIELDS not a literal set "
                                    "of field names")
            elif tname == "HOT_FIELDS":
                try:
                    m.hot = tuple(ast.literal_eval(node.value))
                except (ValueError, TypeError):
                    m.errors.append("HOT_FIELDS not a literal tuple "
                                    "of field names")
            elif tname == "COLD_WHEN":
                try:
                    m.cold_when = [(g, tuple(flds)) for g, flds in
                                   ast.literal_eval(node.value)]
                except (ValueError, TypeError):
                    m.errors.append("COLD_WHEN not a literal tuple of "
                                    "(guard, (fields...)) pairs")
            elif tname == "NARROW_SPEC":
                try:
                    m.narrow = [tuple(e) for e in
                                ast.literal_eval(node.value)]
                except (ValueError, TypeError):
                    m.errors.append(
                        "NARROW_SPEC not a literal tuple of (field, "
                        "wide, narrow, encoding, bound, why) entries")
        elif isinstance(node, ast.FunctionDef) and node.name in (
                "alloc_hosts", "make_shared"):
            kind = HOSTS if node.name == "alloc_hosts" else SH
            _harvest_ctor_dtypes(m, kind, node)
    if not m.fields[HOSTS]:
        m.errors.append("no Hosts fields found in state.py")
    return m


def _harvest_ctor_dtypes(m: StateModel, kind: str, fnode):
    """Authoritative dtypes from the constructor calls:
    alloc_hosts' `full(shape, val, jnp.i64)` kwargs / make_shared's
    `jnp.asarray(x, dtype=jnp.i64)` and `jnp.i64(x)` kwargs."""
    for node in ast.walk(fnode):
        if not (isinstance(node, ast.Return)
                and isinstance(node.value, ast.Call)):
            continue
        for kw in node.value.keywords:
            if kw.arg is None or kw.arg not in m.fields[kind]:
                continue
            v = kw.value
            dt = None
            if isinstance(v, ast.Call):
                if isinstance(v.func, ast.Name):       # full(s, v, dt)
                    if len(v.args) >= 3:
                        dt = _dtype_from_node(v.args[2])
                else:                                   # jnp.xxx(...)
                    dt = _dtype_from_node(v.func)
                    if dt is None:                      # asarray(dtype=)
                        for vkw in v.keywords:
                            if vkw.arg == "dtype":
                                dt = _dtype_from_node(vkw.value)
            if dt:
                m.fields[kind][kw.arg] = dt


# --- abstract values -------------------------------------------------

class Tree:
    __slots__ = ("kind",)

    def __init__(self, kind):
        self.kind = kind


class Arr:
    """An array value: dtype, the state field it derives from (for
    rule messages and the widening requirement), and whether an
    explicit cast has been applied on the path."""
    __slots__ = ("dtype", "origin", "widened")

    def __init__(self, dtype, origin=None, widened=False):
        self.dtype = dtype
        self.origin = origin
        self.widened = widened


class Tup:
    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items


class Func:
    """A project function as a value; `env` snapshots the defining
    scope for nested defs/lambdas (closure capture)."""
    __slots__ = ("fn", "env")

    def __init__(self, fn, env=None):
        self.fn = fn
        self.env = env


class FuncList:
    """One of several functions (lax.switch branch tables, the app
    registry): calls conservatively traverse every member."""
    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items


class Partial:
    __slots__ = ("target", "args", "kwargs")

    def __init__(self, target, args, kwargs):
        self.target = target
        self.args = args
        self.kwargs = kwargs


class Bound:
    """`recv.name` method access pending its call (`.replace`,
    `.astype`, `.at[...]`, reductions)."""
    __slots__ = ("recv", "name")

    def __init__(self, recv, name):
        self.recv = recv
        self.name = name


class StrSet:
    """A comprehension variable ranging over a literal string tuple
    (the `**{f: ... for f in _MERGE_FIELDS}` idiom)."""
    __slots__ = ("values",)

    def __init__(self, values):
        self.values = tuple(values)


class KwDict:
    """A `**kwargs` parameter with its call-site bindings — the
    `_set(row, slot, sk_state=...)` write-helper idiom funnels field
    writes through `kw.items()`, and losing those would blank the
    whole TCP column of the matrix."""
    __slots__ = ("entries",)

    def __init__(self, entries):
        self.entries = dict(entries)


class Sym:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


TOP = None

_INT_RANK = {"bool": 0, "i8": 1, "u8": 1, "i16": 2, "u16": 2,
             "i32": 3, "u32": 3, "i64": 4, "u64": 4}


def _promote(a: str, b: str) -> str:
    if a == b:
        return a
    if a == "?" or b == "?":
        return "?"
    fa, fb = a.startswith("f"), b.startswith("f")
    if fa or fb:
        if fa and fb:
            return a if a >= b else b
        return a if fa else b
    ra, rb = _INT_RANK.get(a, -1), _INT_RANK.get(b, -1)
    if ra < 0 or rb < 0:
        return "?"
    return a if ra >= rb else b


def _merge(a, b):
    """Join of two abstract values (branch results, loop carries)."""
    if a is TOP:
        return b
    if b is TOP:
        return a
    if isinstance(a, Tree) and isinstance(b, Tree) and a.kind == b.kind:
        return a
    if isinstance(a, Tup) and isinstance(b, Tup) \
            and len(a.items) == len(b.items):
        return Tup([_merge(x, y) for x, y in zip(a.items, b.items)])
    if isinstance(a, Arr) and isinstance(b, Arr):
        return Arr(_promote(a.dtype, b.dtype),
                   a.origin if a.origin == b.origin else None,
                   a.widened and b.widened)
    if isinstance(a, FuncList) or isinstance(b, FuncList) \
            or isinstance(a, Func) or isinstance(b, Func):
        items = []
        for v in (a, b):
            items.extend(v.items if isinstance(v, FuncList) else [v])
        return FuncList(items)
    return TOP


# --- per-entry access record -----------------------------------------

class Access:
    def __init__(self):
        # kind -> field -> (file, line) of the first access site
        self.reads = {HOSTS: {}, HP: {}, SH: {}}
        self.writes = {HOSTS: {}, HP: {}, SH: {}}
        self.meta = {HOSTS: {}, HP: {}, SH: {}}   # shape/dtype only
        self.bulk = []   # (tag, file, line): whole-tree ops (tree.map)

    def record(self, table, kind, field, site):
        table[kind].setdefault(field, site)


_META_ATTRS = ("shape", "dtype", "ndim", "size")

_JNP_CASTS = {"int8", "int16", "int32", "int64", "uint8", "uint16",
              "uint32", "uint64", "float16", "float32", "float64",
              "bool_"}
_JNP_PROMOTING = {"where", "minimum", "maximum", "clip", "add",
                  "multiply", "mod", "floor_divide", "power", "abs",
                  "negative", "sign", "cbrt", "sqrt"}
_JNP_BOOL = {"any", "all", "logical_and", "logical_or", "logical_not",
             "isin", "equal", "not_equal"}
_JNP_REDUCE = {"sum", "min", "max", "prod", "cumsum"}
_ROWOPS = {
    "shadow_tpu.core.rowops.rget": 0,
    "shadow_tpu.core.rowops.rset": 0,
    "shadow_tpu.core.rowops.radd": 0,
    "shadow_tpu.core.rowops.rset_where": 0,
}
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv,
              ast.Mod)

_MAX_DEPTH = 60


class _Frame:
    __slots__ = ("info", "fn")

    def __init__(self, info, fn):
        self.info = info   # _ModuleInfo
        self.fn = fn       # _Func or None (module level)

    @property
    def relpath(self):
        return self.info.relpath


class _EntryInterp:
    """Abstract interpretation of one entry pass. Flow-insensitive
    inside a function (both branches of every `if` execute against a
    shared env; loops run once) — an over-approximation that is exact
    for access PRESENCE, which is what the matrix states."""

    def __init__(self, project: _Project, model: StateModel,
                 violations: list, vseen: set):
        self.project = project
        self.model = model
        self.access = Access()
        self.violations = violations   # shared across entries
        self.vseen = vseen             # (rule, file, line) dedup
        self.memo = {}                 # (fqn, bindkey) -> ret abstract
        self.stack = set()
        self.depth = 0

    # --- plumbing ----------------------------------------------------
    def _emit(self, rid, frame, node, message):
        key = (rid, frame.relpath, node.lineno)
        if key not in self.vseen:
            self.vseen.add(key)
            self.violations.append(Violation(
                rid, frame.relpath, node.lineno, message))

    def _site(self, frame, node):
        return (frame.relpath, node.lineno)

    def _read(self, kind, field, frame, node):
        self.access.record(self.access.reads, kind, field,
                           self._site(frame, node))

    def _write(self, kind, field, frame, node):
        self.access.record(self.access.writes, kind, field,
                           self._site(frame, node))

    def _resolve(self, frame, node):
        """Dotted name of an expression, chasing module-level
        `_I64 = jnp.int64`-style aliases one step."""
        dotted = frame.info.aliases.resolve(node)
        if dotted and "." not in dotted and isinstance(node, ast.Name):
            target = _module_alias(frame.info, dotted)
            if target:
                return target
        return dotted

    # --- entry -------------------------------------------------------
    def run_entry(self, fn, binding: dict):
        env = {}
        for pname, kind in binding.items():
            env[pname] = Tree(kind)
        frame = _Frame(self.project.modules[fn.module], fn)
        self._exec_body(fn.node.body, env, frame)

    # --- function calls ----------------------------------------------
    def _call_fn(self, funcabs, args, kwargs, frame, node):
        if isinstance(funcabs, Partial):
            return self._call_fn(funcabs.target,
                                 list(funcabs.args) + list(args),
                                 {**funcabs.kwargs, **kwargs},
                                 frame, node)
        if isinstance(funcabs, FuncList):
            ret = TOP
            for item in funcabs.items:
                ret = _merge(ret, self._call_fn(item, args, kwargs,
                                                frame, node))
            return ret
        if not isinstance(funcabs, Func):
            return TOP
        fn = funcabs.fn
        key = None
        if funcabs.env is None:
            key = (fn.fqn, _bindkey(args, kwargs))
            if key in self.memo:
                return self.memo[key]
        if (fn.fqn in self.stack and funcabs.env is None) \
                or self.depth >= _MAX_DEPTH:
            return TOP
        env = dict(funcabs.env) if funcabs.env else {}
        _bind_params(fn.node, args, kwargs, env)
        callee_frame = _Frame(self.project.modules[fn.module], fn)
        self.stack.add(fn.fqn)
        self.depth += 1
        try:
            if isinstance(fn.node, ast.Lambda):
                ret = self._ev(fn.node.body, env, callee_frame)
            else:
                ret = self._exec_body(fn.node.body, env, callee_frame)
        finally:
            self.depth -= 1
            self.stack.discard(fn.fqn)
        if key is not None:
            self.memo[key] = ret
        return ret

    # --- statements --------------------------------------------------
    def _exec_body(self, body, env, frame):
        returns = TOP
        for stmt in body:
            r = self._exec(stmt, env, frame)
            if r is not _NO_RETURN:
                returns = _merge(returns, r)
        return returns

    def _exec(self, stmt, env, frame):
        if isinstance(stmt, ast.Return):
            return self._ev(stmt.value, env, frame) \
                if stmt.value is not None else TOP
        if isinstance(stmt, ast.Assign):
            val = self._ev(stmt.value, env, frame)
            for t in stmt.targets:
                _assign(t, val, env)
            return _NO_RETURN
        if isinstance(stmt, ast.AnnAssign):
            val = self._ev(stmt.value, env, frame) \
                if stmt.value is not None else TOP
            _assign(stmt.target, val, env)
            return _NO_RETURN
        if isinstance(stmt, ast.AugAssign):
            self._ev(stmt.value, env, frame)
            if isinstance(stmt.target, ast.Name):
                env[stmt.target.id] = TOP
            return _NO_RETURN
        if isinstance(stmt, ast.Expr):
            self._ev(stmt.value, env, frame)
            return _NO_RETURN
        if isinstance(stmt, (ast.If, ast.While)):
            self._ev(stmt.test, env, frame)
            r = self._exec_body(stmt.body, env, frame)
            if stmt.orelse:
                r = _merge(r, self._exec_body(stmt.orelse, env, frame))
            return r
        if isinstance(stmt, ast.For):
            self._ev(stmt.iter, env, frame)
            _assign(stmt.target, TOP, env)
            r = self._exec_body(stmt.body, env, frame)
            if stmt.orelse:
                r = _merge(r, self._exec_body(stmt.orelse, env, frame))
            return r
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = self._nested_fn(frame, stmt.name)
            env[stmt.name] = Func(fn, dict(env)) if fn else TOP
            return _NO_RETURN
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._ev(item.context_expr, env, frame)
            return self._exec_body(stmt.body, env, frame)
        if isinstance(stmt, ast.Try):
            r = self._exec_body(stmt.body, env, frame)
            for h in stmt.handlers:
                r = _merge(r, self._exec_body(h.body, env, frame))
            if stmt.finalbody:
                r = _merge(r, self._exec_body(stmt.finalbody, env,
                                              frame))
            return r
        if isinstance(stmt, (ast.Assert, ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._ev(child, env, frame)
            return _NO_RETURN
        return _NO_RETURN

    def _nested_fn(self, frame, name):
        qual = f"{frame.fn.qual}.{name}" if frame.fn else name
        return frame.info.functions.get(qual)

    # --- expressions -------------------------------------------------
    def _ev(self, node, env, frame):
        if node is None:
            return TOP
        if isinstance(node, ast.Constant):
            return node
        if isinstance(node, ast.Name):
            return self._ev_name(node, env, frame)
        if isinstance(node, ast.Attribute):
            return self._ev_attr(node, env, frame)
        if isinstance(node, ast.Subscript):
            return self._ev_subscript(node, env, frame)
        if isinstance(node, ast.Call):
            return self._ev_call(node, env, frame)
        if isinstance(node, (ast.Tuple, ast.List)):
            items = [self._ev(e, env, frame) for e in node.elts]
            if items and all(isinstance(i, (Func, FuncList))
                             for i in items):
                flat = []
                for i in items:
                    flat.extend(i.items if isinstance(i, FuncList)
                                else [i])
                return FuncList(flat)
            return Tup(items)
        if isinstance(node, ast.BinOp):
            return self._ev_binop(node, env, frame)
        if isinstance(node, ast.Compare):
            return self._ev_compare(node, env, frame)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self._ev(v, env, frame)
            return Arr("bool")
        if isinstance(node, ast.UnaryOp):
            v = self._ev(node.operand, env, frame)
            if isinstance(node.op, ast.Not):
                return Arr("bool")
            return v if isinstance(v, (Arr, ast.Constant)) else TOP
        if isinstance(node, ast.IfExp):
            self._ev(node.test, env, frame)
            return _merge(self._ev(node.body, env, frame),
                          self._ev(node.orelse, env, frame))
        if isinstance(node, ast.Lambda):
            fn = self._nested_fn(frame, f"<lambda@{node.lineno}>")
            return Func(fn, dict(env)) if fn else TOP
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp)):
            with self._comp_env(node, env, frame) as cenv:
                elt = self._ev(node.elt, cenv, frame)
            if isinstance(elt, (Func, FuncList)):
                return elt if isinstance(elt, FuncList) \
                    else FuncList([elt])
            return TOP
        if isinstance(node, ast.DictComp):
            with self._comp_env(node, env, frame) as cenv:
                self._ev(node.key, cenv, frame)
                self._ev(node.value, cenv, frame)
            return TOP
        if isinstance(node, ast.Starred):
            return self._ev(node.value, env, frame)
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self._ev(part, env, frame)
            return TOP
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                self._ev(k, env, frame)
                self._ev(v, env, frame)
            return TOP
        return TOP

    def _comp_env(self, node, env, frame):
        """Context manager yielding the comprehension scope: loop vars
        over literal string tuples become StrSet (the getattr-over-
        field-list idiom); everything else TOP."""
        interp = self

        class _Ctx:
            def __enter__(ctx):
                ctx.env = dict(env)
                for gen in node.generators:
                    vals = interp._str_tuple(gen.iter, env, frame)
                    tgt = gen.target
                    if vals is not None and isinstance(tgt, ast.Name):
                        ctx.env[tgt.id] = StrSet(vals)
                    elif vals is not None and isinstance(
                            tgt, ast.Tuple) and tgt.elts \
                            and isinstance(tgt.elts[0], ast.Name):
                        # `for f, v in kw.items()`
                        ctx.env[tgt.elts[0].id] = StrSet(vals)
                        for t in tgt.elts[1:]:
                            _assign(t, TOP, ctx.env)
                    else:
                        interp._ev(gen.iter, ctx.env, frame)
                        _assign(tgt, TOP, ctx.env)
                return ctx.env

            def __exit__(ctx, *a):
                return False

        return _Ctx()

    def _str_tuple(self, node, env, frame):
        """A literal (or module-constant) tuple/list of strings — or
        the key set of a **kwargs dict (`kw.items()`/`kw.keys()`) —
        or None."""
        if isinstance(node, ast.Name):
            v = env.get(node.id)
            if isinstance(v, StrSet):
                return v.values
            return _module_str_tuple(frame.info, node.id)
        if isinstance(node, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant)
                and isinstance(e.value, str) for e in node.elts):
            return tuple(e.value for e in node.elts)
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in ("items", "keys") \
                and isinstance(node.func.value, ast.Name):
            v = env.get(node.func.value.id)
            if isinstance(v, KwDict):
                return tuple(sorted(v.entries))
        return None

    def _ev_name(self, node, env, frame):
        if node.id in env:
            return env[node.id]
        if node.id == "SIMTIME_MAX":
            return Sym("SIMTIME_MAX")
        fn = self.project._lookup(frame.info, frame.fn, node.id)
        if fn is not None:
            return Func(fn, dict(env) if fn.parent else None)
        return TOP

    def _ev_attr(self, node, env, frame):
        # shape/dtype-only access to a tree field is a META read: it
        # is trace-time static and touches no data
        if node.attr in _META_ATTRS and isinstance(node.value,
                                                   ast.Attribute):
            inner = self._ev(node.value.value, env, frame)
            if isinstance(inner, Tree) and node.value.attr in \
                    self.model.fields[inner.kind]:
                self.access.record(self.access.meta, inner.kind,
                                   node.value.attr,
                                   self._site(frame, node))
                return TOP
        base = self._ev(node.value, env, frame)
        if isinstance(base, Tree):
            if node.attr in self.model.fields[base.kind]:
                self._read(base.kind, node.attr, frame, node)
                return Arr(self.model.dtype_of(base.kind, node.attr),
                           node.attr)
            if node.attr == "replace":
                return Bound(base, "replace")
            return TOP
        if isinstance(base, Arr):
            if node.attr in _META_ATTRS:
                return TOP
            return Bound(base, node.attr)
        if isinstance(base, Bound):
            return Bound(base.recv, node.attr)
        # `equeue.q_push` / `nic.kick`-style module-function refs:
        # the base Name is a module alias, so the base eval is TOP —
        # resolve the whole dotted attribute instead
        dotted = frame.info.aliases.resolve(node)
        if dotted:
            fn = self.project._by_dotted(dotted)
            if fn is not None:
                return Func(fn, None)
        return TOP

    def _ev_subscript(self, node, env, frame):
        base = self._ev(node.value, env, frame)
        self._ev(node.slice, env, frame)
        if isinstance(base, Arr):
            return Arr(base.dtype, base.origin, base.widened)
        if isinstance(base, Bound):       # arr.at[idx] -> still bound
            return base
        if isinstance(base, Tup) and isinstance(node.slice,
                                                ast.Constant) \
                and isinstance(node.slice.value, int) \
                and 0 <= node.slice.value < len(base.items):
            return base.items[node.slice.value]
        if isinstance(base, FuncList):    # registry[idx]: any member
            return base
        return TOP

    # --- calls -------------------------------------------------------
    def _ev_call(self, node, env, frame):
        dotted = self._resolve(frame, node.func)
        handler = self._dotted_call(node, dotted, env, frame)
        if handler is not _UNHANDLED:
            return handler
        funcabs = self._ev(node.func, env, frame)
        args = [self._ev(a, env, frame) for a in node.args]
        kwargs = {}
        for kw in node.keywords:
            if kw.arg:
                kwargs[kw.arg] = self._ev(kw.value, env, frame)
            else:
                self._ev(kw.value, env, frame)
        if isinstance(funcabs, Bound):
            return self._call_bound(funcabs, node, env, frame)
        if isinstance(funcabs, (Func, FuncList, Partial)):
            return self._call_fn(funcabs, args, kwargs, frame, node)
        return TOP

    def _dotted_call(self, node, dotted, env, frame):
        if not dotted:
            return _UNHANDLED
        tail = dotted.rsplit(".", 1)[-1]
        if tail in ("hot_fields", "row_proto") \
                and self.model.fields[HOSTS]:
            # engine.state's hot/cold split helpers: hot_fields(cfg)
            # yields SOME subset of HOT_FIELDS depending on static
            # config — modeled as the full set (the union over
            # configs, which is what the matrix states); row_proto
            # yields a default-valued Hosts row (the drain rebuilds
            # its vmapped rows around it, threading the Hosts kind
            # into the handler subgraph)
            for a in node.args:
                self._ev(a, env, frame)
            if tail == "hot_fields":
                return StrSet(self.model.hot_set())
            return Tree(HOSTS)
        if dotted in _ROWOPS:
            args = [self._ev(a, env, frame) for a in node.args]
            arr = args[0] if args else TOP
            return arr if isinstance(arr, Arr) else TOP
        if dotted == "getattr" and len(node.args) >= 2:
            base = self._ev(node.args[0], env, frame)
            name = self._ev(node.args[1], env, frame)
            if isinstance(base, Tree):
                if isinstance(name, ast.Constant) and isinstance(
                        name.value, str):
                    if name.value in self.model.fields[base.kind]:
                        self._read(base.kind, name.value, frame, node)
                        return Arr(self.model.dtype_of(base.kind,
                                                       name.value),
                                   name.value)
                elif isinstance(name, StrSet):
                    for f in name.values:
                        if f in self.model.fields[base.kind]:
                            self._read(base.kind, f, frame, node)
                else:
                    self.access.bulk.append(("getattr(dynamic)",
                                             *self._site(frame, node)))
            return TOP
        if dotted in ("functools.partial", "partial"):
            target = self._ev(node.args[0], env, frame) \
                if node.args else TOP
            args = [self._ev(a, env, frame) for a in node.args[1:]]
            kwargs = {kw.arg: self._ev(kw.value, env, frame)
                      for kw in node.keywords if kw.arg}
            return Partial(target, args, kwargs)
        if dotted == "jax.vmap":
            return self._ev(node.args[0], env, frame) \
                if node.args else TOP
        if dotted in ("jax.tree.map", "jax.tree_map",
                      "jax.tree_util.tree_map"):
            args = [self._ev(a, env, frame) for a in node.args]
            trees = [a for a in args[1:] if isinstance(a, Tree)]
            if trees:
                self.access.bulk.append((f"tree.map[{trees[0].kind}]",
                                         *self._site(frame, node)))
                return trees[0]
            return TOP
        if dotted == "jax.lax.cond" and len(node.args) >= 3:
            self._ev(node.args[0], env, frame)
            ops = [self._ev(a, env, frame) for a in node.args[3:]]
            ret = TOP
            for br in (node.args[1], node.args[2]):
                f = self._ev(br, env, frame)
                ret = _merge(ret, self._call_fn(f, ops, {}, frame,
                                                node))
            return ret
        if dotted == "jax.lax.switch" and len(node.args) >= 2:
            self._ev(node.args[0], env, frame)
            branches = self._ev(node.args[1], env, frame)
            ops = [self._ev(a, env, frame) for a in node.args[2:]]
            return self._call_fn(branches, ops, {}, frame, node)
        if dotted == "jax.lax.while_loop" and len(node.args) >= 3:
            init = self._ev(node.args[2], env, frame)
            cond = self._ev(node.args[0], env, frame)
            body = self._ev(node.args[1], env, frame)
            self._call_fn(cond, [init], {}, frame, node)
            ret = self._call_fn(body, [init], {}, frame, node)
            return _merge(ret, init)
        if dotted == "jax.lax.fori_loop" and len(node.args) >= 4:
            f = self._ev(node.args[2], env, frame)
            init = self._ev(node.args[3], env, frame)
            ret = self._call_fn(f, [TOP, init], {}, frame, node)
            return _merge(ret, init)
        if dotted == "jax.lax.scan" and len(node.args) >= 2:
            f = self._ev(node.args[0], env, frame)
            init = self._ev(node.args[1], env, frame)
            self._call_fn(f, [init, TOP], {}, frame, node)
            return TOP
        if dotted == "dataclasses.replace" and node.args:
            target = self._ev(node.args[0], env, frame)
            if isinstance(target, Tree):
                self._replace_kwargs(target, node, env, frame)
                return target
            return TOP
        if dotted.startswith("jax.numpy."):
            return self._jnp_call(node, dotted.split(".", 2)[2], env,
                                  frame)
        return _UNHANDLED

    def _jnp_call(self, node, attr, env, frame):
        args = [self._ev(a, env, frame) for a in node.args]
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        if attr in _JNP_CASTS:
            origin = args[0].origin if args and isinstance(args[0],
                                                           Arr) \
                else None
            return Arr(_DT[attr], origin, widened=True)
        if attr in ("asarray", "array", "full", "zeros", "ones",
                    "full_like", "zeros_like", "ones_like", "arange"):
            dt = None
            if "dtype" in kwargs:
                dt = _dtype_from_node(kwargs["dtype"])
            elif attr == "full" and len(node.args) >= 3:
                dt = _dtype_from_node(node.args[2])
            if dt:
                return Arr(dt, None, widened=True)
            if attr in ("asarray", "array") and args \
                    and isinstance(args[0], Arr):
                return args[0]
            return TOP
        if attr in _JNP_BOOL:
            return Arr("bool")
        if attr in _JNP_REDUCE:
            if "dtype" in kwargs:
                dt = _dtype_from_node(kwargs["dtype"])
                if dt:
                    return Arr(dt, None, widened=True)
            arrs = [a for a in args if isinstance(a, Arr)]
            return arrs[0] if arrs else TOP
        if attr in _JNP_PROMOTING:
            arrs = [a for a in args if isinstance(a, Arr)]
            if attr == "where" and len(args) >= 3:
                arrs = [a for a in args[1:3] if isinstance(a, Arr)]
            if not arrs:
                return TOP
            out = arrs[0]
            for a in arrs[1:]:
                out = Arr(_promote(out.dtype, a.dtype),
                          out.origin if out.origin == a.origin
                          else None,
                          out.widened and a.widened)
            return out
        return TOP

    def _call_bound(self, bound, node, env, frame):
        recv, name = bound.recv, bound.name
        if isinstance(recv, Tree) and name == "replace":
            self._replace_kwargs(recv, node, env, frame)
            return recv
        if isinstance(recv, Arr):
            for a in node.args:
                self._ev(a, env, frame)
            if name == "astype" and node.args:
                dt = _dtype_from_node(node.args[0])
                if dt is None and isinstance(node.args[0], ast.Name):
                    dt = _DT.get(_module_alias(
                        frame.info, node.args[0].id,
                        tail=True) or "")
                return Arr(dt or "?", recv.origin, widened=True)
            if name in ("set", "add", "get", "mul", "reshape",
                        "astype"):
                return Arr(recv.dtype, recv.origin, recv.widened)
            if name in _JNP_BOOL:
                return Arr("bool")
            if name in _JNP_REDUCE:
                return Arr(recv.dtype, recv.origin, recv.widened)
        return TOP

    def _replace_kwargs(self, tree, node, env, frame):
        """`.replace(field=..., **{...})` — the ONLY write channel
        into a pytree. Records a write per named field; the dict-comp
        form over a literal field tuple records each member; anything
        dynamic becomes a bulk note (visible in the matrix, never
        silently dropped)."""
        for kw in node.keywords:
            if kw.arg is not None:
                if kw.arg in self.model.fields[tree.kind]:
                    self._write(tree.kind, kw.arg, frame, kw.value)
                self._ev(kw.value, env, frame)
                continue
            # **{...}
            val = kw.value
            if isinstance(val, ast.DictComp):
                keys = None
                for gen in val.generators:
                    vals = self._str_tuple(gen.iter, env, frame)
                    if vals is None or not isinstance(val.key,
                                                      ast.Name):
                        continue
                    tgt = gen.target
                    if isinstance(tgt, ast.Tuple) and tgt.elts:
                        tgt = tgt.elts[0]
                    if isinstance(tgt, ast.Name) \
                            and val.key.id == tgt.id:
                        keys = vals
                if keys:
                    for f in keys:
                        if f in self.model.fields[tree.kind]:
                            self._write(tree.kind, f, frame, val)
                    with self._comp_env(val, env, frame) as cenv:
                        self._ev(val.value, cenv, frame)
                    continue
            if isinstance(val, ast.Dict) and all(
                    isinstance(k, ast.Constant) for k in val.keys):
                for k, v in zip(val.keys, val.values):
                    if k.value in self.model.fields[tree.kind]:
                        self._write(tree.kind, k.value, frame, v)
                    self._ev(v, env, frame)
                continue
            self.access.bulk.append(("replace(**dynamic)",
                                     *self._site(frame, node)))
            self._ev(val, env, frame)

    # --- dtype-flow rules --------------------------------------------
    def _ev_binop(self, node, env, frame):
        l = self._ev(node.left, env, frame)
        r = self._ev(node.right, env, frame)
        if isinstance(node.op, _ARITH_OPS) \
                and isinstance(l, Arr) and isinstance(r, Arr):
            for narrow, wide in ((l, r), (r, l)):
                if (wide.dtype == "i64" and narrow.dtype == "i32"
                        and narrow.origin is not None
                        and not narrow.widened):
                    self._emit(STF401, frame, node,
                               f"i32 `{narrow.origin}` flows into "
                               f"i64 arithmetic"
                               + (f" with `{wide.origin}`"
                                  if wide.origin else "")
                               + " without explicit widening")
            return Arr(_promote(l.dtype, r.dtype), None, True)
        if isinstance(l, FuncList) and isinstance(r, FuncList):
            return FuncList(l.items + r.items)
        if isinstance(l, Arr) and isinstance(r, Arr):
            return Arr(_promote(l.dtype, r.dtype), None, True)
        if isinstance(l, Arr):
            return Arr(l.dtype, l.origin, l.widened)
        if isinstance(r, Arr):
            return Arr(r.dtype, r.origin, r.widened)
        return TOP

    def _ev_compare(self, node, env, frame):
        vals = [self._ev(node.left, env, frame)]
        vals += [self._ev(c, env, frame) for c in node.comparators]
        for a, b in zip(vals, vals[1:]):
            for x, y in ((a, b), (b, a)):
                if isinstance(x, Sym) and x.name == "SIMTIME_MAX" \
                        and isinstance(y, Arr) \
                        and y.dtype not in ("i64", "?"):
                    self._emit(STF403, frame, node,
                               "SIMTIME_MAX compared against "
                               f"{y.dtype} value"
                               + (f" `{y.origin}`" if y.origin
                                  else ""))
                if isinstance(x, Arr) and x.dtype == "f32" \
                        and x.origin is not None \
                        and isinstance(y, Arr) and y.dtype == "i64" \
                        and not x.widened:
                    self._emit(STF402, frame, node,
                               f"f32 `{x.origin}` compared against "
                               "an i64 quantity"
                               + (f" (`{y.origin}`)" if y.origin
                                  else ""))
        return Arr("bool")


_NO_RETURN = object()
_UNHANDLED = object()


def _assign(target, val, env):
    if isinstance(target, ast.Name):
        env[target.id] = val
    elif isinstance(target, (ast.Tuple, ast.List)):
        items = val.items if isinstance(val, Tup) \
            and len(val.items) == len(target.elts) \
            else [TOP] * len(target.elts)
        for t, v in zip(target.elts, items):
            _assign(t, v, env)
    # attribute/subscript targets mutate nothing we track


def _bind_params(fnode, args, kwargs, env):
    a = fnode.args
    params = [p.arg for p in a.posonlyargs + a.args]
    kwonly = {p.arg for p in a.kwonlyargs}
    for name, val in zip(params, args):
        env[name] = val
    leftover = {}
    for name, val in kwargs.items():
        if name in params or name in kwonly:
            env[name] = val
        else:
            leftover[name] = val
    if a.kwarg:
        env[a.kwarg.arg] = KwDict(leftover)


def _sig(v):
    if isinstance(v, Tree):
        return ("T", v.kind)
    if isinstance(v, Arr):
        return ("A", v.dtype, v.origin, v.widened)
    if isinstance(v, Tup):
        return ("t",) + tuple(_sig(i) for i in v.items)
    if isinstance(v, (Func, FuncList, Partial)):
        return ("F",)
    if isinstance(v, KwDict):
        return ("K",) + tuple(sorted(
            (k, _sig(val)) for k, val in v.entries.items()))
    return ("?",)


def _bindkey(args, kwargs):
    return (tuple(_sig(a) for a in args),
            tuple(sorted((k, _sig(v)) for k, v in kwargs.items())))


def _module_alias(info, name, tail=False):
    """Module-level `X = jnp.int64`-style alias: returns the dotted
    target (or with tail=True just its last attribute)."""
    cached = getattr(info, "_stateflow_alias", None)
    if cached is None:
        cached = {}
        for stmt in info.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value,
                                   (ast.Attribute, ast.Name)):
                dotted = info.aliases.resolve(stmt.value)
                if dotted and "." in dotted:
                    cached[stmt.targets[0].id] = dotted
        info._stateflow_alias = cached
    dotted = cached.get(name)
    if dotted and tail:
        return dotted.rsplit(".", 1)[1]
    return dotted


def _module_str_tuple(info, name):
    cached = getattr(info, "_stateflow_strtup", None)
    if cached is None:
        cached = {}
        for stmt in info.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)) \
                    and stmt.value.elts and all(
                        isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                        for e in stmt.value.elts):
                cached[stmt.targets[0].id] = tuple(
                    e.value for e in stmt.value.elts)
        info._stateflow_strtup = cached
    return cached.get(name)


# --- driver ----------------------------------------------------------

def analyze(cache, project: _Project = None):
    """-> (matrix dict, violations). The matrix maps entry name ->
    {kind: {"reads": {...}, "writes": {...}, "meta": {...}},
    "bulk": [...]} with access sites; tools/state_matrix.py renders
    it."""
    model = load_state_model(cache)
    violations: list[Violation] = []
    if model.missing:
        return {}, violations
    if model.errors:
        for err in model.errors:
            violations.append(Violation(STF300, STATE_PATH, 0, err,
                                        snippet=err))
        return {}, violations
    if project is None:
        project = _Project(cache)

    matrix = {}
    vseen: set = set()
    drain_access = None
    resolved = 0
    for entry, fqn, binding, in_drain in ENTRIES:
        mod, _, name = fqn.rpartition(".")
        info = project.modules.get(mod)
        fn = info.functions.get(name) if info else None
        if fn is None:
            # module present but the pass function gone = a RENAMED
            # entry, which must fail loudly (a silently skipped entry
            # shrinks the matrix and the STF302 read census). A
            # missing module is a fixture repo exercising a subset —
            # skipped, like shimproto's both-sides-missing rule.
            if info is not None or entry == "drain":
                violations.append(Violation(
                    STF300, STATE_PATH, 0,
                    f"entry pass `{fqn}` ({entry}) not found — "
                    "renamed? update stateflow.ENTRIES in the same "
                    "change", snippet=fqn))
            continue
        resolved += 1
        interp = _EntryInterp(project, model, violations, vseen)
        interp.run_entry(fn, binding)
        matrix[entry] = _pack_access(interp.access)
        if in_drain:
            drain_access = interp.access
    if resolved == 0:
        violations.append(Violation(
            STF300, STATE_PATH, 0,
            "no stateflow entry passes resolved — wrong root or "
            "renamed engine modules", snippet="entries"))
        return matrix, violations

    # vacuity guard: the drain subgraph reaches the event handlers,
    # TCP machine and NIC — a tiny read set means the interpreter
    # lost the plot, which must fail loudly, not pass green. The
    # threshold scales with the model so fixture repos stay usable.
    floor = min(10, len(model.fields[HOSTS]) // 2)
    if drain_access is not None \
            and len(drain_access.reads[HOSTS]) < floor:
        violations.append(Violation(
            STF300, STATE_PATH, 0,
            f"drain subgraph reads only "
            f"{len(drain_access.reads[HOSTS])} of "
            f"{len(model.fields[HOSTS])} Hosts fields — vacuous "
            "scan", snippet="drain-vacuity"))

    violations.extend(_contract_violations(model, matrix,
                                           drain_access))
    return matrix, violations


def _pack_access(acc: Access):
    out = {}
    for kind in (HOSTS, HP, SH):
        out[kind] = {
            "reads": dict(sorted(acc.reads[kind].items())),
            "writes": dict(sorted(acc.writes[kind].items())),
            "meta": dict(sorted(acc.meta[kind].items())),
        }
    out["bulk"] = sorted(set(acc.bulk))
    return out


def _contract_violations(model: StateModel, matrix, drain_access):
    out = []
    # STF301: every Hosts field sectioned
    for field in model.fields[HOSTS]:
        if model.section_of(field) is None:
            out.append(Violation(
                STF301, STATE_PATH, model.linenos.get(field, 0),
                f"Hosts field `{field}` matches no STATE_SECTIONS "
                "prefix (section_of would return 'other')"))
    # STF302: dead / write-only columns
    read_anywhere, written_anywhere = set(), set()
    for entry in matrix.values():
        read_anywhere |= set(entry[HOSTS]["reads"])
        written_anywhere |= set(entry[HOSTS]["writes"])
    for field in model.fields[HOSTS]:
        if field in read_anywhere or field in HOST_CONSUMED:
            continue
        shape = ("write-only" if field in written_anywhere else "dead")
        out.append(Violation(
            STF302, STATE_PATH, model.linenos.get(field, 0),
            f"Hosts column `{field}` is {shape}: no analyzed pass "
            "reads it and no host-side consumer is declared "
            "(lint/stateflow.HOST_CONSUMED)"))
    # STF303: cold columns out of the drain subgraph
    if drain_access is not None:
        for field in sorted(model.cold):
            for table, verb in ((drain_access.reads[HOSTS], "read"),
                                (drain_access.writes[HOSTS],
                                 "written")):
                if field in table:
                    file, line = table[field]
                    out.append(Violation(
                        STF303, file, line,
                        f"cold column `{field}` is {verb} inside the "
                        "drain-pass subgraph (engine/state.py "
                        "COLD_FIELDS)"))
    # unknown cold names are a contract typo, not a silent no-op
    for field in sorted(model.cold - set(model.fields[HOSTS])):
        out.append(Violation(
            STF300, STATE_PATH, 0,
            f"COLD_FIELDS names `{field}`, which is not a Hosts "
            "field", snippet=f"cold:{field}"))
    # HOT_FIELDS (when declared) must partition the Hosts columns
    # exactly against COLD_FIELDS — the drain's declared working set
    # and the dataclass cannot drift apart
    if model.hot:
        hot = set(model.hot)
        allf = set(model.fields[HOSTS])
        for field in sorted(hot & model.cold):
            out.append(Violation(
                STF300, STATE_PATH, 0,
                f"`{field}` is in both HOT_FIELDS and COLD_FIELDS",
                snippet=f"hotcold:{field}"))
        for field in sorted(allf - hot - model.cold):
            out.append(Violation(
                STF300, STATE_PATH, model.linenos.get(field, 0),
                f"Hosts field `{field}` is in neither HOT_FIELDS nor "
                "COLD_FIELDS — declare it in the hot/cold partition",
                snippet=f"unpartitioned:{field}"))
        for field in sorted(hot - allf):
            out.append(Violation(
                STF300, STATE_PATH, 0,
                f"HOT_FIELDS names `{field}`, which is not a Hosts "
                "field", snippet=f"hot:{field}"))
    # STF304: config-gated cold columns must be real, statically-hot
    # fields (a COLD_WHEN entry that is already in COLD_FIELDS, or
    # unknown, is a contract error)
    hot = set(model.hot_set())
    for guard, fields in model.cold_when:
        for field in fields:
            if field not in model.fields[HOSTS]:
                out.append(Violation(
                    STF304, STATE_PATH, 0,
                    f"COLD_WHEN[{guard}] names `{field}`, which is "
                    "not a Hosts field"))
            elif field in model.cold:
                out.append(Violation(
                    STF304, STATE_PATH, 0,
                    f"COLD_WHEN[{guard}] names `{field}`, which is "
                    "already statically cold (COLD_FIELDS)"))
            elif field not in hot:
                out.append(Violation(
                    STF304, STATE_PATH, 0,
                    f"COLD_WHEN[{guard}] names `{field}`, which is "
                    "not in HOT_FIELDS"))
    # STF404: every narrowed column carries a machine-checked bound
    # annotation (NARROW_SPEC) that actually proves the shrink safe.
    # The narrow layout is opt-out (wide_state=0 is the default), so a
    # malformed entry here is live-state corruption waiting to happen.
    _NARROW_MAX = {"i8": 127, "i16": 32767, "i32": 2147483647,
                   "u8": 255, "u16": 65535, "u32": 4294967295}
    _RANK = {"i8": 1, "u8": 1, "i16": 2, "u16": 2, "i32": 4,
             "u32": 4, "i64": 8, "u64": 8}
    seen_narrow = set()
    abs_anchors = {f for e in model.narrow
                   if len(e) == 6 and e[3] == "abs" for f in (e[0],)}
    for entry in model.narrow:
        if len(entry) != 6:
            out.append(Violation(
                STF404, STATE_PATH, 0,
                f"NARROW_SPEC entry {entry!r} is not a (field, wide, "
                "narrow, encoding, bound, why) 6-tuple"))
            continue
        field, wide, narrow, enc, bound, why = entry
        loc = model.linenos.get(field, 0)
        if field in seen_narrow:
            out.append(Violation(
                STF404, STATE_PATH, loc,
                f"NARROW_SPEC lists `{field}` twice"))
        seen_narrow.add(field)
        if field not in model.fields[HOSTS]:
            out.append(Violation(
                STF404, STATE_PATH, 0,
                f"NARROW_SPEC names `{field}`, which is not a Hosts "
                "field"))
            continue
        mdt = model.dtype_of(HOSTS, field)
        if mdt != "?" and mdt != wide:
            out.append(Violation(
                STF404, STATE_PATH, loc,
                f"NARROW_SPEC declares `{field}` wide dtype {wide} "
                f"but the state model says {mdt} — the annotation "
                "comment (the COMPUTE dtype handlers see) and the "
                "spec must agree"))
        if narrow not in _NARROW_MAX or wide not in _RANK:
            out.append(Violation(
                STF404, STATE_PATH, loc,
                f"NARROW_SPEC `{field}`: unknown dtype pair "
                f"({wide} -> {narrow})"))
            continue
        if _RANK[narrow] >= _RANK.get(wide, 0):
            out.append(Violation(
                STF404, STATE_PATH, loc,
                f"NARROW_SPEC `{field}`: {narrow} is not strictly "
                f"narrower than {wide} — the entry shrinks nothing"))
        if not (isinstance(bound, int) and 0 < bound
                <= _NARROW_MAX[narrow]):
            out.append(Violation(
                STF404, STATE_PATH, loc,
                f"NARROW_SPEC `{field}`: bound {bound!r} does not fit "
                f"{narrow} (max {_NARROW_MAX[narrow]}) — the shrink "
                "is unproven"))
        if not (enc == "abs" or (isinstance(enc, str)
                                 and enc.startswith("rel:"))):
            out.append(Violation(
                STF404, STATE_PATH, loc,
                f"NARROW_SPEC `{field}`: encoding {enc!r} is neither "
                "'abs' nor 'rel:<anchor>'"))
        elif enc != "abs":
            anchor = enc.split(":", 1)[1]
            if anchor not in abs_anchors:
                out.append(Violation(
                    STF404, STATE_PATH, loc,
                    f"NARROW_SPEC `{field}`: rel anchor `{anchor}` is "
                    "not an abs-narrowed NARROW_SPEC column (the "
                    "codec widens anchors first; a non-narrowed or "
                    "rel anchor breaks that ordering)"))
        if not (isinstance(why, str) and why.strip()):
            out.append(Violation(
                STF404, STATE_PATH, loc,
                f"NARROW_SPEC `{field}`: empty invariant note — name "
                "the bound's enforcing mechanism"))
    return out


def check(cache, project: _Project = None) -> list:
    """simlint family entry point. `project` shares the tracing
    module index when the caller already built one (cli.collect) —
    building it is ~1.5s of the gate's wall."""
    _, violations = analyze(cache, project)
    violations.sort(key=lambda v: (v.file, v.line, v.rule))
    return violations
