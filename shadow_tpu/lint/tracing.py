"""JAX tracing-hazard lints (TRC1xx): host Python leaking into traced
code.

The compiled window program is the engine's hot path; a `.item()` or
host-numpy call inside it forces a device sync per call (the
overhead-bound TCP tier's enemy, ROADMAP item 1), a Python `if` on a
traced array fails at trace time, a closure over a mutable module
global silently captures stale state at trace time, and unhashable
static_argnums cause retrace storms.

These hazards only matter in code that actually runs UNDER a trace, so
the family first builds a jit-reachability set:

1. roots: functions wrapped by ``jax.jit`` / ``jax.shard_map`` /
   ``core.jitcache.AotJit`` / ``jax.pmap`` (as decorator or call,
   through ``functools.partial`` and simple local ``body = ...``
   assignments), plus lambdas passed to those wrappers;
2. propagation: any project-defined function REFERENCED by name inside
   a reachable body is reachable (inside traced code, referencing a
   function — as a call, a ``lax.cond`` branch, a ``vmap`` target —
   means it traces), resolved through imports across the scanned
   modules.

Scope: ``engine/``, ``net/``, ``parallel/``, ``core/`` (reachability
is computed over all of ``shadow_tpu/`` so cross-module edges through
``apps/`` etc. still propagate; violations are only REPORTED in
scope).
"""

from __future__ import annotations

import ast

from .core import Violation, rule
from .names import AliasMap, module_name_of

TRC101 = rule(
    "TRC101", ".item()/.tolist() inside jit-reachable code",
    "forces a device->host sync per call; keep the value on device "
    "(jnp ops / lax.cond) or hoist the read out of the traced region")
TRC102 = rule(
    "TRC102", "trace-time int()/float()/bool() on a traced value",
    "concretizes a tracer (TracerConversionError at trace time, or a "
    "silent host sync); use astype/jnp casts or restructure so the "
    "value is static")
TRC103 = rule(
    "TRC103", "host-numpy materialization in jit-reachable code",
    "np.asarray/np.array on a traced value forces transfer, and "
    "numpy scalar constructors are strong-typed (dtype-widening "
    "under x64); use jnp equivalents with an explicit dtype")
TRC104 = rule(
    "TRC104", "Python branch on an array value in traced code",
    "`if jnp.any(...)` needs the concrete value at trace time; use "
    "lax.cond / jnp.where")
TRC105 = rule(
    "TRC105", "jit-reachable closure over a mutable module global",
    "the traced value is captured at FIRST trace and silently never "
    "refreshed (stale capture), and rebinding retraces; pass it as an "
    "argument or freeze it")
TRC106 = rule(
    "TRC106", "static_argnums/static_argnames on an unhashable default",
    "unhashable statics (list/dict/set) fail at call time or retrace "
    "per call; use tuples / hashable config objects")

# report scope (repo-relative); the call graph spans all of shadow_tpu
SCOPE = ("shadow_tpu/engine", "shadow_tpu/net", "shadow_tpu/parallel",
         "shadow_tpu/core", "shadow_tpu/serving")
GRAPH_SCOPE = ("shadow_tpu",)

_JIT_WRAPPERS = {
    "jax.jit", "jax.pmap", "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "shadow_tpu.core.jitcache.AotJit",
}

# parameters conventionally holding STATIC config in this codebase —
# int()/float() on them is trace-time-constant work, not a hazard
_STATIC_PARAMS = {"cfg", "lcfg", "config", "self", "mesh", "cls"}


def _param_names(node) -> set:
    a = node.args
    names = [p.arg for p in
             (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class _Func:
    __slots__ = ("module", "qual", "node", "relpath", "parent")

    def __init__(self, module, qual, node, relpath, parent):
        self.module = module      # dotted module name
        self.qual = qual          # dotted qualname within the module
        self.node = node          # FunctionDef | Lambda
        self.relpath = relpath
        self.parent = parent      # enclosing _Func or None

    @property
    def fqn(self):
        return f"{self.module}.{self.qual}"


class _ModuleInfo:
    def __init__(self, relpath: str, tree: ast.AST):
        self.relpath = relpath
        self.name = module_name_of(relpath)
        self.tree = tree
        self.aliases = AliasMap(tree, relpath)
        self.functions: dict[str, _Func] = {}   # qual -> _Func
        self.mutable_globals: dict[str, int] = {}
        self._scope_cache = None
        self._collect_functions()
        self._collect_mutable_globals()

    def _collect_functions(self):
        mod = self

        class Collector(ast.NodeVisitor):
            def __init__(self):
                self.stack: list[_Func] = []

            def _add(self, name, node):
                parent = self.stack[-1] if self.stack else None
                qual = (f"{parent.qual}.{name}" if parent else name)
                fn = _Func(mod.name, qual, node, mod.relpath, parent)
                mod.functions[qual] = fn
                return fn

            def visit_FunctionDef(self, node):
                fn = self._add(node.name, node)
                self.stack.append(fn)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                fn = self._add(f"<lambda@{node.lineno}>", node)
                self.stack.append(fn)
                self.generic_visit(node)
                self.stack.pop()

        Collector().visit(self.tree)

    def _collect_mutable_globals(self):
        """Module-level names bound to mutable containers (or rebound
        more than once at module level). ALL_CAPS singly-assigned
        immutables are constants, not hazards."""
        counts: dict[str, int] = {}
        for stmt in self.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                targets, value = [stmt.target], stmt.value
            else:
                continue
            for t in targets:
                counts[t.id] = counts.get(t.id, 0) + 1
                if self._is_mutable(value):
                    self.mutable_globals.setdefault(t.id, t.lineno)
        for name, n in counts.items():
            if n > 1:
                self.mutable_globals.setdefault(name, 0)

    def _is_mutable(self, value) -> bool:
        if value is None:
            return False
        if isinstance(value, (ast.Dict, ast.List, ast.Set,
                              ast.DictComp, ast.ListComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            dotted = self.aliases.resolve(value.func)
            return dotted in ("dict", "list", "set", "bytearray",
                              "collections.defaultdict",
                              "collections.deque",
                              "collections.OrderedDict")
        return False


class _Project:
    """All scanned modules + the jit-reachability fixpoint."""

    def __init__(self, cache):
        self.cache = cache
        self.modules: dict[str, _ModuleInfo] = {}
        for rel in cache.py_files(GRAPH_SCOPE):
            tree = cache.tree(rel)
            if tree is None or isinstance(tree, SyntaxError):
                continue
            info = _ModuleInfo(rel, tree)
            self.modules[info.name] = info
        self.reachable: set[_Func] = set()
        self._compute_reachability()

    # --- function resolution -----------------------------------------
    def _lookup(self, module: _ModuleInfo, scope: _Func | None,
                name: str) -> _Func | None:
        """Resolve a bare name referenced inside `scope` to a project
        function: innermost enclosing nested def, then module level,
        then imports."""
        s = scope
        while s is not None:
            cand = module.functions.get(f"{s.qual}.{name}")
            if cand is not None:
                return cand
            s = s.parent
        cand = module.functions.get(name)
        if cand is not None:
            return cand
        dotted = module.aliases.aliases.get(name)
        if dotted:
            return self._by_dotted(dotted)
        return None

    def _by_dotted(self, dotted: str) -> _Func | None:
        mod, _, attr = dotted.rpartition(".")
        info = self.modules.get(mod)
        if info is not None and attr in info.functions:
            return info.functions[attr]
        return None

    def _resolve_wrapped(self, module, scope, node) -> list:
        """The function(s) a jit-wrapper call actually wraps: unwraps
        Lambda, Name (through simple local `name = ...` assignments),
        and functools.partial chains."""
        if isinstance(node, ast.Lambda):
            qual = (f"{scope.qual}.<lambda@{node.lineno}>" if scope
                    else f"<lambda@{node.lineno}>")
            fn = module.functions.get(qual)
            return [fn] if fn else []
        if isinstance(node, ast.Call):
            dotted = module.aliases.resolve(node.func)
            if dotted in ("functools.partial", "partial") and node.args:
                return self._resolve_wrapped(module, scope,
                                             node.args[0])
            return []
        if isinstance(node, (ast.Name, ast.Attribute)):
            if isinstance(node, ast.Name):
                # chase one level of simple local assignment
                # (`body = partial(f, ...)` then `shard_map(body)`)
                assigned = self._local_assignment(scope, node.id)
                if assigned is not None:
                    return self._resolve_wrapped(module, scope,
                                                 assigned)
                fn = self._lookup(module, scope, node.id)
                return [fn] if fn else []
            dotted = module.aliases.resolve(node)
            if dotted:
                fn = self._by_dotted(dotted)
                return [fn] if fn else []
        return []

    @staticmethod
    def _local_assignment(scope: _Func | None, name: str):
        """Last `name = <expr>` statement in the enclosing function
        body (shallow; good enough for the wrapper-arg idiom)."""
        if scope is None or isinstance(scope.node, ast.Lambda):
            return None
        found = None
        for stmt in ast.walk(scope.node):
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == name
                    and not isinstance(stmt.value, ast.Name)):
                found = stmt.value
        return found

    # --- reachability ------------------------------------------------
    def _compute_reachability(self):
        roots: list[_Func] = []
        for info in self.modules.values():
            # decorator roots
            for fn in info.functions.values():
                node = fn.node
                if isinstance(node, ast.Lambda):
                    continue
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    dotted = info.aliases.resolve(d)
                    if dotted in _JIT_WRAPPERS or (
                            isinstance(dec, ast.Call)
                            and info.aliases.resolve(dec.func)
                            in ("functools.partial", "partial")
                            and dec.args
                            and info.aliases.resolve(dec.args[0])
                            in _JIT_WRAPPERS):
                        roots.append(fn)
            # call-wrapper roots: jax.jit(f) / AotJit(f) / shard_map(f)
            scope_of = self._scope_index(info)
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = info.aliases.resolve(node.func)
                if dotted not in _JIT_WRAPPERS or not node.args:
                    continue
                scope = scope_of.get(id(node))
                roots.extend(self._resolve_wrapped(info, scope,
                                                   node.args[0]))
        # fixpoint: references inside reachable bodies
        work = [r for r in roots if r is not None]
        self.reachable = set(work)
        while work:
            fn = work.pop()
            info = self.modules[fn.module]
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    target = self._lookup(info, fn, node.id)
                    if target is not None and target not in \
                            self.reachable:
                        self.reachable.add(target)
                        work.append(target)
                elif isinstance(node, ast.Attribute):
                    dotted = info.aliases.resolve(node)
                    if dotted:
                        target = self._by_dotted(dotted)
                        if target is not None and target not in \
                                self.reachable:
                            self.reachable.add(target)
                            work.append(target)

    def _scope_index(self, info: _ModuleInfo) -> dict:
        """id(ast node) -> innermost enclosing _Func, for locating
        wrapper calls made inside functions (cached per module)."""
        if info._scope_cache is not None:
            return info._scope_cache
        index: dict[int, _Func] = {}

        def mark(fn: _Func):
            for sub in ast.walk(fn.node):
                index.setdefault(id(sub), fn)

        # deeper functions first so setdefault keeps the innermost
        for qual in sorted(info.functions,
                           key=lambda q: -q.count(".")):
            mark(info.functions[qual])
        info._scope_cache = index
        return index


class _HazardVisitor(ast.NodeVisitor):
    """Scan one reachable function body (not descending into nested
    defs/lambdas — they are scanned separately iff reachable)."""

    def __init__(self, project: _Project, fn: _Func):
        self.project = project
        self.fn = fn
        self.info = project.modules[fn.module]
        self.aliases = self.info.aliases
        self.violations: list[Violation] = []
        node = fn.node
        self.params = _param_names(node)
        self.traced_params = self.params - _STATIC_PARAMS
        # locals bound inside the body shadow module globals
        self.locals = set(self.params)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                self.locals.add(sub.id)
            elif isinstance(sub, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                if sub is not node:
                    self.locals.add(sub.name)
        self._root = node

    def _emit(self, rid, node, message):
        self.violations.append(Violation(
            rid, self.fn.relpath, node.lineno,
            f"{message} (in jit-reachable `{self.fn.qual}`)"))

    def _skip_nested(self, node):
        if node is self._root:
            self.generic_visit(node)

    visit_FunctionDef = visit_AsyncFunctionDef = _skip_nested
    visit_Lambda = _skip_nested

    def _mentions_traced(self, node) -> bool:
        return any(isinstance(n, ast.Name) and n.id in
                   self.traced_params for n in ast.walk(node))

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in (
                "item", "tolist") and not node.args:
            self._emit(TRC101, node,
                       f"`.{func.attr}()` syncs device->host")
        dotted = self.aliases.resolve(func)
        if dotted in ("float", "int", "bool") and len(node.args) == 1:
            if self._mentions_traced(node.args[0]):
                self._emit(TRC102, node, f"`{dotted}()` on a value "
                           "derived from a traced argument")
        elif dotted and dotted.startswith("numpy."):
            attr = dotted.split(".", 1)[1]
            if attr in ("asarray", "array", "frombuffer", "copy",
                        "ascontiguousarray"):
                if self._mentions_traced(node):
                    self._emit(TRC103, node, f"`np.{attr}` on a "
                               "traced value transfers to host")
            elif attr in ("float16", "float32", "float64", "int8",
                          "int16", "int32", "int64", "uint8",
                          "uint16", "uint32", "uint64"):
                self._emit(TRC103, node, f"`np.{attr}(...)` builds a "
                           "strong-typed numpy scalar (dtype "
                           "widening under x64)")
        self.generic_visit(node)

    # --- if/while on arrays ------------------------------------------
    def _arrayish_test(self, test) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                dotted = self.aliases.resolve(n.func)
                if dotted and (dotted.startswith("jax.numpy.")
                               or dotted.startswith("jax.lax.")):
                    return True
                if (isinstance(n.func, ast.Attribute)
                        and n.func.attr in ("any", "all", "sum")
                        and self._mentions_traced(n.func.value)):
                    return True
        return False

    def visit_If(self, node: ast.If):
        if self._arrayish_test(node.test):
            self._emit(TRC104, node, "Python `if` on an array-valued "
                       "test")
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        if self._arrayish_test(node.test):
            self._emit(TRC104, node, "Python `while` on an "
                       "array-valued test")
        self.generic_visit(node)

    # --- mutable-global closure --------------------------------------
    def visit_Name(self, node: ast.Name):
        if (isinstance(node.ctx, ast.Load)
                and node.id not in self.locals
                and node.id in self.info.mutable_globals):
            self._emit(TRC105, node, f"reads mutable module global "
                       f"`{node.id}`")
        self.generic_visit(node)


def _static_arg_violations(project: _Project) -> list:
    """TRC106 over every jit-wrapper CALL SITE in scope (the call
    sites live in host-side caller code, outside the reachable set)."""
    out = []
    for info in project.modules.values():
        if not info.relpath.startswith(SCOPE):
            continue
        scope_of = project._scope_index(info)
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = info.aliases.resolve(node.func)
            if dotted not in _JIT_WRAPPERS:
                continue
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            if not ("static_argnums" in kw
                    or "static_argnames" in kw) or not node.args:
                continue
            scope = scope_of.get(id(node))
            for fn in project._resolve_wrapped(info, scope,
                                               node.args[0]):
                if fn is None or isinstance(fn.node, ast.Lambda):
                    continue
                for pname in _unhashable_statics(fn.node, kw):
                    out.append(Violation(
                        TRC106, info.relpath, node.lineno,
                        f"static arg `{pname}` of `{fn.qual}` "
                        "defaults to an unhashable container"))
    return out


def _unhashable_statics(fnode, kw):
    """Parameter names marked static whose default is an unhashable
    container literal."""
    a = fnode.args
    params = a.posonlyargs + a.args
    defaults = [None] * (len(params) - len(a.defaults)) \
        + list(a.defaults)
    marked = []
    sa = kw.get("static_argnums")
    by_index = dict(enumerate(zip(params, defaults)))
    if isinstance(sa, ast.Constant) and isinstance(sa.value, int):
        marked.append(by_index.get(sa.value))
    elif isinstance(sa, (ast.Tuple, ast.List)):
        for el in sa.elts:
            if isinstance(el, ast.Constant):
                marked.append(by_index.get(el.value))
    names = kw.get("static_argnames")
    wanted = set()
    if isinstance(names, (ast.Tuple, ast.List)):
        wanted = {el.value for el in names.elts
                  if isinstance(el, ast.Constant)}
    elif isinstance(names, ast.Constant):
        wanted = {names.value}
    for p, d in zip(params, defaults):
        if p.arg in wanted:
            marked.append((p, d))
    for entry in marked:
        if entry is None:
            continue
        p, d = entry
        if isinstance(d, (ast.List, ast.Dict, ast.Set)):
            yield p.arg


def check(cache, project: "_Project" = None) -> list:
    """Run the tracing family: build the reachability set, then scan
    every reachable function that lives in the report scope.
    `project` reuses an already-built module index (cli.collect
    shares one with the stateflow family)."""
    if project is None:
        project = _Project(cache)
    out = []
    seen = set()
    for fn in project.reachable:
        if not fn.relpath.startswith(SCOPE):
            continue
        hv = _HazardVisitor(project, fn)
        hv.generic_visit(fn.node)
        for v in hv.violations:
            key = (v.rule, v.file, v.line)
            if key not in seen:
                seen.add(key)
                out.append(v)
    out.extend(_static_arg_violations(project))
    out.sort(key=lambda v: (v.file, v.line, v.rule))
    return out
