"""Shim protocol conformance (SHIM2xx): C preload <-> Python bridge.

``hosting/shim_preload.c`` and ``hosting/shim.py`` implement the two
ends of one lockstep wire protocol. Nothing at runtime checks they
agree — a one-sided edit (renumbering an ``OP_*``, adding an opcode to
one table, changing which ops attach trailing payload) silently
corrupts the framing of a hosted run. This checker makes that a BUILD
failure instead, by parsing both sides and cross-checking:

- the ``OP_*`` enum in the C file vs the ``OP_*`` constants in the
  Python file: same names, same values (SHIM201/SHIM202);
- the wire struct layouts: C ``struct req/rsp/evpair`` member types
  vs the Python ``struct.Struct`` format strings REQ/RSP/EVPAIR
  (SHIM210);
- the payload-framing contracts: both sides document, next to their
  protocol code, which opcodes attach trailing request payload,
  trailing response payload, or trailing (fd, events) pairs — the C
  comment block between the enum and ``call2`` and the "Protocol"
  section of the Python module docstring. The claims are extracted
  per-opcode and must agree (SHIM211); any ``<fmt>`` struct token the
  Python docstring cites must be a declared Struct format (SHIM212).

The comment blocks ARE the conformance surface on purpose: the
protocol's framing rules live in prose beside the code that implements
them, and this check makes that prose load-bearing — editing the
behavior without the contract (or one side without the other) fails
the gate.
"""

from __future__ import annotations

import ast
import re

from .core import Violation, rule

SHIM200 = rule(
    "SHIM200", "shim protocol source unparseable",
    "the conformance checker could not locate the enum/constants — "
    "keep the OP_* tables in their canonical form")
SHIM201 = rule(
    "SHIM201", "opcode present on one side only",
    "add the opcode to BOTH hosting/shim_preload.c (enum) and "
    "hosting/shim.py (OP_* constant), same name and value")
SHIM202 = rule(
    "SHIM202", "opcode value mismatch between C and Python",
    "renumbering one side desyncs every hosted run: make the values "
    "identical (and never reuse a retired number)")
SHIM210 = rule(
    "SHIM210", "wire struct layout mismatch",
    "the C struct members and the Python struct.Struct format must "
    "describe the same bytes")
SHIM211 = rule(
    "SHIM211", "payload-framing contract mismatch",
    "the framing comments beside the protocol code disagree on "
    "whether this opcode attaches trailing data; fix the side that "
    "no longer matches the implementation")
SHIM212 = rule(
    "SHIM212", "framing text cites an undeclared struct format",
    "every <fmt> token in the protocol docstring must match a "
    "declared struct.Struct format (REQ/RSP/EVPAIR)")

C_PATH = "shadow_tpu/hosting/shim_preload.c"
PY_PATH = "shadow_tpu/hosting/shim.py"

# C scalar type -> struct format char (little-endian wire)
_CTYPE_FMT = {
    "int8_t": "b", "uint8_t": "B", "int16_t": "h", "uint16_t": "H",
    "int32_t": "i", "uint32_t": "I", "int64_t": "q", "uint64_t": "Q",
    "float": "f", "double": "d", "char": "s",
}

# C struct name -> Python Struct constant name
_STRUCT_MAP = {"req": "REQ", "rsp": "RSP", "evpair": "EVPAIR"}


# --- C side ----------------------------------------------------------

def parse_c_ops(text: str):
    """The OP_* enum -> ({name: value}, {name: lineno}). C enum
    semantics: explicit `= N` sets, bare names increment."""
    m = re.search(r"enum\s*\{(.*?)\};", text, re.S)
    if not m or "OP_" not in m.group(1):
        return None, None
    body = m.group(1)
    # strip comments inside the enum body
    body_clean = re.sub(r"/\*.*?\*/", "", body, flags=re.S)
    ops, linenos = {}, {}
    value = -1
    base = text[: m.start(1)].count("\n") + 1
    for entry in body_clean.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" in entry:
            name, _, rhs = entry.partition("=")
            name = name.strip()
            try:
                value = int(rhs.strip(), 0)
            except ValueError:
                continue
        else:
            name = entry
            value += 1
        if name.startswith("OP_"):
            ops[name] = value
            # line of the name within the original text
            off = body.find(name)
            linenos[name] = (base + body[:off].count("\n")
                             if off >= 0 else base)
    return ops, linenos


def parse_c_structs(text: str):
    """struct req/rsp/evpair member layouts -> {name: (fmt, lineno)}
    with fmt in struct-module notation (no byte-order prefix)."""
    out = {}
    for m in re.finditer(
            r"struct\s+(\w+)\s*\{([^}]*)\}\s*;", text):
        name, body = m.group(1), m.group(2)
        if name not in _STRUCT_MAP:
            continue
        body = re.sub(r"/\*.*?\*/", "", body, flags=re.S)
        fmt = ""
        ok = True
        for decl in body.split(";"):
            decl = decl.strip()
            if not decl:
                continue
            dm = re.match(
                r"(?:unsigned\s+|signed\s+)?(\w+)\s+(\w+)\s*"
                r"(?:\[\s*(\d+)\s*\])?$", decl)
            if not dm:
                ok = False
                break
            ctype, _mname, arr = dm.groups()
            ch = _CTYPE_FMT.get(ctype)
            if ch is None:
                ok = False
                break
            if arr:
                if ch == "s":
                    fmt += f"{arr}s"
                else:
                    fmt += ch * int(arr)
            else:
                fmt += ch
        if ok:
            out[name] = (fmt, text[: m.start()].count("\n") + 1)
    return out


def c_framing_region(text: str) -> str:
    """Comment text of the framing contract: every block comment
    between the OP enum and the call2 definition (covers the evpair
    trailing-pairs note and the 'Payload framing' block)."""
    start = text.find("enum {")
    end = text.find("static struct rsp call2")
    if start < 0 or end < 0 or end <= start:
        return ""
    region = text[start:end]
    chunks = re.findall(r"/\*(.*?)\*/", region, re.S)
    cleaned = []
    for c in chunks:
        c = re.sub(r"^\s*\*", "", c, flags=re.M)
        cleaned.append(" ".join(c.split()))
    return ". ".join(cleaned)


# --- Python side -----------------------------------------------------

def parse_py(text: str):
    """shim.py -> (ops {name: value}, linenos, structs {PYNAME:
    (fmt, lineno)}, docstring, doc_lineno)."""
    tree = ast.parse(text)
    ops, linenos, structs = {}, {}, {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name.startswith("OP_") and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, int):
                ops[name] = node.value.value
                linenos[name] = node.lineno
            elif (name in _STRUCT_MAP.values()
                  and isinstance(node.value, ast.Call)
                  and node.value.args
                  and isinstance(node.value.args[0], ast.Constant)):
                structs[name] = (str(node.value.args[0].value),
                                 node.lineno)
    doc = ast.get_docstring(tree) or ""
    return ops, linenos, structs, doc


def py_framing_region(doc: str) -> str:
    """The docstring's Protocol section (framing contract)."""
    i = doc.find("Protocol")
    return " ".join(doc[i:].split()) if i >= 0 else ""


# --- framing-claim extraction ---------------------------------------

_POSITIVE = re.compile(
    r"followed by|(?<!never )carr(?:y|ies)|attach(?:es)?\s+(?!nothing)")
_NEGATIVE = re.compile(r"never\s+carr|attach(?:es)?\s+nothing")


def framing_claims(region_text: str) -> dict:
    """Extract per-opcode framing claims from contract prose.

    -> {opcode: {"req_payload": bool, "rsp_payload": bool,
    "rsp_pairs": bool}} — an aspect key is present iff the text makes
    a claim about it; conflicting claims (stream sends attach, dgram
    sends attach nothing) resolve to True (CAN attach)."""
    claims: dict[str, dict] = {}
    for sentence in re.split(r"[.;](?:\s|$)", region_text):
        ops = re.findall(r"OP_[A-Z_]+", sentence)
        if not ops:
            continue
        pos = bool(_POSITIVE.search(sentence))
        neg = bool(_NEGATIVE.search(sentence))
        if not pos and not neg:
            continue
        low = sentence.lower()
        # the side is the noun directly following the opcode list
        # ("OP_SEND requests ...", "OP_RECV / OP_RANDOM responses
        # ..."), NOT a sentence-wide keyword — framing sentences often
        # mention the other side's vocabulary in passing
        m = re.search(
            r"op_[a-z_]+(?:\s*(?:/|,|and)\s*op_[a-z_]+)*\s+"
            r"(requests?|responses?)", low)
        side = m.group(1)[:3] if m else "req"
        if side == "res" and ("pair" in low or "evpair" in low):
            aspect = "rsp_pairs"
        elif side == "res":
            aspect = "rsp_payload"
        else:
            # request side, and subject-less claims ("Datagram
            # OP_SEND ... attach nothing") default to it
            aspect = "req_payload"
        for op in ops:
            d = claims.setdefault(op, {})
            d[aspect] = d.get(aspect, False) or pos
    return claims


_FMT_TOKEN = re.compile(r"<([a-zA-Z0-9]+)>")


# --- the cross-check -------------------------------------------------

def check_texts(c_text: str, py_text: str,
                c_path: str = C_PATH, py_path: str = PY_PATH) -> list:
    """Full conformance check over raw file contents (separated from
    path handling so fixtures can feed edited copies)."""
    out = []
    c_ops, c_lines = parse_c_ops(c_text)
    if c_ops is None:
        return [Violation(SHIM200, c_path, 0,
                          "no OP_* enum found in the C shim")]
    try:
        py_ops, py_lines, py_structs, py_doc = parse_py(py_text)
    except SyntaxError as e:
        return [Violation(SHIM200, py_path, e.lineno or 0,
                          f"shim.py unparseable: {e.msg}")]
    if not py_ops:
        return [Violation(SHIM200, py_path, 0,
                          "no OP_* constants found in shim.py")]

    # 1. names + values + count
    for name in sorted(c_ops.keys() - py_ops.keys()):
        out.append(Violation(
            SHIM201, py_path, 0,
            f"{name} (= {c_ops[name]}) exists in the C enum but has "
            "no Python constant"))
    for name in sorted(py_ops.keys() - c_ops.keys()):
        out.append(Violation(
            SHIM201, c_path, 0,
            f"{name} (= {py_ops[name]}) exists in shim.py but not in "
            "the C enum"))
    for name in sorted(c_ops.keys() & py_ops.keys()):
        if c_ops[name] != py_ops[name]:
            out.append(Violation(
                SHIM202, py_path, py_lines.get(name, 0),
                f"{name}: C says {c_ops[name]}, Python says "
                f"{py_ops[name]}"))

    # 2. wire struct layouts
    c_structs = parse_c_structs(c_text)
    for cname, pyname in _STRUCT_MAP.items():
        cs = c_structs.get(cname)
        ps = py_structs.get(pyname)
        if cs is None or ps is None:
            out.append(Violation(
                SHIM210, c_path if cs is None else py_path, 0,
                f"wire struct `{cname}`/`{pyname}` missing on "
                f"{'C' if cs is None else 'Python'} side"))
            continue
        c_fmt, _c_ln = cs
        p_fmt, p_ln = ps
        if p_fmt.lstrip("<=!>@") != c_fmt:
            out.append(Violation(
                SHIM210, py_path, p_ln,
                f"{pyname} format {p_fmt!r} != C struct {cname} "
                f"layout {'<' + c_fmt!r}"))

    # 3. payload-framing agreement
    c_claims = framing_claims(c_framing_region(c_text))
    p_claims = framing_claims(py_framing_region(py_doc))
    aspects = (("req_payload", "trailing request payload"),
               ("rsp_payload", "trailing response payload"),
               ("rsp_pairs", "trailing response (fd, events) pairs"))
    for op in sorted(set(c_claims) | set(p_claims)):
        cc, pc = c_claims.get(op, {}), p_claims.get(op, {})
        for aspect, desc in aspects:
            cv, pv = cc.get(aspect, False), pc.get(aspect, False)
            if cv != pv:
                side_has = "C" if cv else "Python"
                side_not = "Python" if cv else "C"
                out.append(Violation(
                    SHIM211, py_path if cv else c_path, 0,
                    f"{op}: {side_has} framing contract says it "
                    f"attaches {desc}, {side_not} says it does not"))

    # 4. struct format tokens cited in the protocol docstring
    declared = {fmt.lstrip("<=!>@") for fmt, _ in py_structs.values()}
    for tok in sorted(set(_FMT_TOKEN.findall(py_framing_region(py_doc)))):
        if tok not in declared:
            out.append(Violation(
                SHIM212, py_path, 0,
                f"protocol docstring cites <{tok}> which matches no "
                f"declared Struct format ({sorted(declared)})"))
    return out


def check(cache) -> list:
    """Conformance over the repo's canonical shim pair."""
    c_text = cache.text(C_PATH)
    py_text = cache.text(PY_PATH)
    missing = []
    if c_text is None:
        missing.append(Violation(SHIM200, C_PATH, 0,
                                 "C shim source missing"))
    if py_text is None:
        missing.append(Violation(SHIM200, PY_PATH, 0,
                                 "Python shim source missing"))
    if missing:
        # BOTH missing = not a hosting-capable tree (fixture repos);
        # one missing = a real conformance failure
        return [] if len(missing) == 2 else missing
    return check_texts(c_text, py_text)
