"""Determinism lints (DET1xx): host nondeterminism leaking into sim.

Every rule here corresponds to a regression class the repo (or the
reference) has actually hit — see docs/static-analysis.md for the
catalog with examples. Scope: ``engine/``, ``net/``, ``core/``,
``obs/``, ``hosting/``, plus — since PR 11 — ``fleet/`` (its
wall-clock scheduling is legitimate and allowlisted per file; its
QUEUE/journal layer must stay deterministic) and ``lint/`` itself
(a linter whose own report order depends on PYTHONHASHSEED cannot
pin baselines). ``bench.py`` and ``tools/`` stay excluded:
wall-clock reporting is their whole job.
"""

from __future__ import annotations

import ast

from .core import Violation, rule
from .names import AliasMap

DET100 = rule(
    "DET100", "unparseable Python source in a linted scope",
    "fix the syntax error; an unscannable file is an unverified file")
DET101 = rule(
    "DET101", "wallclock read in sim code",
    "sim code must read simulated time (HostOS.now / sim_ns); wall "
    "reads belong in obs/ reporting — suppress with justification if "
    "this is genuinely wall-side")
DET102 = rule(
    "DET102", "unseeded / module-global RNG",
    "draw from the seeded per-host stream (core.rng / "
    "np.random.default_rng(seed)); the module-global RNG is shared "
    "mutable state whose draw order is a determinism hazard")
DET103 = rule(
    "DET103", "OS entropy bypasses the deterministic PRNG",
    "os.urandom/secrets/uuid4/SystemRandom read kernel entropy; use "
    "the seeded PRNG (core.rng, HostOS.random_bytes)")
DET104 = rule(
    "DET104", "builtin hash() feeds state (PYTHONHASHSEED hazard)",
    "hash(str/bytes) differs per process unless PYTHONHASHSEED is "
    "pinned; use hashlib (blake2b) for anything stored, compared or "
    "ordered")
DET105 = rule(
    "DET105", "iteration over an unordered set",
    "set iteration order depends on PYTHONHASHSEED for str elements; "
    "wrap in sorted(...) before anything order-sensitive (event "
    "ordering, digest input, emitted records)")

# scan scope, repo-relative
SCOPE = ("shadow_tpu/engine", "shadow_tpu/net", "shadow_tpu/core",
         "shadow_tpu/obs", "shadow_tpu/hosting", "shadow_tpu/fleet",
         "shadow_tpu/lint", "shadow_tpu/serving")

_WALLCLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.clock_gettime",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# module-global `random.*` draws (anything on the module RNG); the
# class constructors are fine WITH a seed argument
_RANDOM_OK = {"random.Random", "random.getstate", "random.setstate"}
_NP_RANDOM_SEEDED_OK = {"numpy.random.default_rng",
                        "numpy.random.RandomState",
                        "numpy.random.Generator",
                        "numpy.random.SeedSequence",
                        "numpy.random.PCG64", "numpy.random.Philox"}

_ENTROPY = {"os.urandom", "os.getrandom", "random.SystemRandom",
            "uuid.uuid4", "uuid.uuid1"}


def _is_number(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex, bool))
    if isinstance(node, ast.UnaryOp):
        return _is_number(node.operand)
    if isinstance(node, ast.Tuple):
        return all(_is_number(e) for e in node.elts)
    return False


class _DetVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, tree: ast.AST):
        self.relpath = relpath
        self.aliases = AliasMap(tree, relpath)
        self.violations: list[Violation] = []
        # statement-expression hash() calls are hashability PROBES
        # (result discarded, e.g. core/jitcache.py) — not state
        self._discarded: set[int] = {
            id(n.value) for n in ast.walk(tree)
            if isinstance(n, ast.Expr)}
        # per-function names assigned a set expression (DET105)
        self._set_locals: list[set] = [set()]

    def _emit(self, rid, node, message):
        self.violations.append(
            Violation(rid, self.relpath, node.lineno, message))

    # --- calls -------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        dotted = self.aliases.resolve(node.func)
        if dotted:
            self._check_call(node, dotted)
        self.generic_visit(node)

    def _check_call(self, node: ast.Call, dotted: str):
        if dotted in _WALLCLOCK:
            self._emit(DET101, node, f"`{dotted}()` reads the wall "
                       "clock in sim code")
            return
        if dotted in _ENTROPY:
            self._emit(DET103, node, f"`{dotted}` draws OS entropy, "
                       "bypassing the deterministic PRNG")
            return
        if dotted.startswith("random.") and dotted not in _RANDOM_OK:
            if dotted == "random.seed":
                self._emit(DET102, node, "`random.seed` configures the "
                           "process-global RNG; use an owned "
                           "random.Random(seed) instance")
            else:
                self._emit(DET102, node, f"`{dotted}()` draws from the "
                           "module-global RNG")
            return
        if dotted == "random.Random" and not node.args:
            self._emit(DET102, node, "`random.Random()` without a seed")
            return
        if dotted.startswith("numpy.random."):
            if dotted in _NP_RANDOM_SEEDED_OK:
                if not node.args and not node.keywords:
                    self._emit(DET102, node, f"`{dotted}()` without a "
                               "seed draws OS entropy")
            else:
                self._emit(DET102, node, f"`{dotted}()` uses numpy's "
                           "module-global RNG")
            return
        if dotted == "hash" and id(node) not in self._discarded:
            arg = node.args[0] if node.args else None
            if arg is not None and not _is_number(arg):
                self._emit(DET104, node, "builtin `hash()` result is "
                           "used; str/bytes hashes vary per process "
                           "(PYTHONHASHSEED)")

    # --- set iteration (DET105) --------------------------------------
    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            dotted = self.aliases.resolve(node.func)
            if dotted in ("set", "frozenset"):
                return True
        if (isinstance(node, ast.Name)
                and node.id in self._set_locals[-1]):
            return True
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            # set algebra: s1 | s2, s & t, s - t on known sets
            return (self._is_set_expr(node.left)
                    or self._is_set_expr(node.right))
        return False

    def _check_iter(self, iter_node: ast.AST):
        if self._is_set_expr(iter_node):
            self._emit(DET105, iter_node, "iterating an unordered set")

    def visit_For(self, node: ast.For):
        self._check_iter(node.iter)
        self.generic_visit(node)

    def _visit_comp(self, node):
        for gen in node.generators:
            self._check_iter(gen.iter)
        self.generic_visit(node)

    visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp

    def visit_DictComp(self, node):
        self._visit_comp(node)

    # --- local set tracking ------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            name = node.targets[0].id
            if self._is_set_expr(node.value):
                self._set_locals[-1].add(name)
            else:
                self._set_locals[-1].discard(name)
        self.generic_visit(node)

    def _visit_func(self, node):
        self._set_locals.append(set())
        self.generic_visit(node)
        self._set_locals.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _visit_func


def check_source(relpath: str, text: str, tree=None) -> list:
    """Lint one Python source for determinism hazards."""
    if tree is None:
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            tree = e
    if isinstance(tree, SyntaxError):
        return [Violation("DET100", relpath, tree.lineno or 0,
                          f"unparseable source: {tree.msg}")]
    v = _DetVisitor(relpath, tree)
    v.visit(tree)
    return v.violations


def check(cache) -> list:
    """Run the determinism family over its scope. `cache` is a
    core.SourceCache rooted at the repo."""
    out = []
    for rel in cache.py_files(SCOPE):
        tree = cache.tree(rel)
        if tree is not None:
            out.extend(check_source(rel, cache.text(rel), tree))
    return out
