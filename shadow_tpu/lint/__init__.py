"""simlint: determinism & tracing-hazard static analysis.

Shadow's value proposition is bit-deterministic simulation, and every
regression class this repo has actually hit is *statically
detectable*: wallclock leaking into sim code, entropy bypassing the
interposer, the C/Python shim opcode tables drifting apart, and
trace-time Python leaking host state into compiled windows. The
reference enforces these invariants by convention inside one C
codebase; our split Python/JAX + C-preload design enforces them with
this machine-checked gate instead (tests/test_lint.py runs it in
tier-1, .github/workflows/ci.yml on every push).

Four check families (docs/static-analysis.md has the rule catalog):

- ``determinism``  (DET1xx): wallclock, unseeded RNG, os.urandom,
  PYTHONHASHSEED-sensitive ``hash()``, unordered set iteration — over
  ``engine/``, ``net/``, ``core/``, ``obs/``, ``hosting/``,
  ``fleet/`` and ``lint/`` itself.
- ``tracing``      (TRC1xx): JAX tracing hazards inside jit-reachable
  code (``.item()``, trace-time ``int()``/``float()``, host-numpy
  materialization, ``if`` on arrays, closures over mutable module
  globals, unhashable static_argnums) — over ``engine/``, ``net/``,
  ``parallel/``, ``core/``.
- ``shimproto``    (SHIM2xx): C<->Python shim protocol conformance
  (``hosting/shim_preload.c`` vs ``hosting/shim.py``: OP_* names,
  values, struct layouts, payload-framing agreement).
- ``stateflow``    (STF3xx/STF4xx): the per-pass Hosts-field access
  matrix and its contracts — every field sectioned, no dead columns,
  COLD_FIELDS out of the drain subgraph — plus dtype-flow rules
  (unwidened i32 into i64 ns arithmetic, f32 cwnd vs i64 compares,
  SIMTIME_MAX vs non-i64). ``python -m tools.state_matrix`` prints
  the measured matrix.

This package deliberately imports NOTHING outside the stdlib (no jax,
no numpy): ``python -m tools.simlint`` must stay a sub-second gate.
The ``tools.simlint`` wrapper loads it without triggering the
``shadow_tpu`` package __init__ (which imports jax).
"""

from .core import (  # noqa: F401
    RULES, Violation, load_baseline, write_baseline)
from .cli import main, run_lint  # noqa: F401
