"""Pipe/socketpair channels: linked byte-queue halves, no TCP.

The reference backs pipe()/socketpair() with a lightweight Channel
descriptor — two halves linked pairwise, each a byte queue with
readable/writable status (/root/reference/src/main/host/descriptor/
shd-channel.c:134-172) — NOT with loopback TCP self-connections. This
module is that object for the TPU build: a pair of PROTO_PIPE socket
rows on ONE host, partner-linked through sk_parent.

Semantics (matching the cooperative modeled-app world):
- a write moves up to the free capacity (PIPE_BUFFER_SIZE, the
  reference's channel buffer) into the partner's readable stream and
  wakes the partner one nanosecond later (the epoll-notify delay every
  descriptor status change pays, shd-epoll.c:326-370);
- byte counts flow, payloads are not materialized (as everywhere in
  the engine);
- close wakes the partner with EOF and frees the half; the partner
  half stays usable for draining until it closes itself.

No handshake, no ACK clocking, no congestion state, no retransmission
— a pipe-heavy workload pays two events per transfer leg (the write
wake and the EOF) instead of the TCP machine's dozens (see
tests/test_loopback.py's event-count comparison).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rget, rset
from ..engine import equeue
from ..engine.defs import EV_APP, WAKE_SOCKET, WAKE_EOF, ST_BYTES_RECV
from . import packet as P
from .socket import sock_alloc, sock_free

_I32 = jnp.int32
_I64 = jnp.int64

PROTO_PIPE = 1                 # sk_proto value (6 = tcp, 17 = udp)
PIPE_BUFFER_SIZE = 65536       # reference CONFIG_PIPE_BUFFER_SIZE


def _wake_partner(row, now, reason, partner, ln=0):
    """+1ns wake of the partner half's owning process (the same
    descriptor-status notify path net.tcp._wake models)."""
    w = jnp.zeros((P.PKT_WORDS,), _I32)
    w = rset(w, P.ACK, _I32(reason))
    w = rset(w, P.SEQ, partner.astype(_I32))
    w = rset(w, P.LEN, _I32(ln))
    # 7-bit generation: must match the pipe open's packed-pair gens
    # (hosting.api._bind_pipe), which only have 7 bits per half
    w = rset(w, P.WND, rget(row.sk_timer_gen, partner) & 0x7F)
    return equeue.q_push(row, now + 1, EV_APP, w)


def pipe_open(row):
    """Allocate a linked pair of pipe halves. Returns
    (row, slot_a, slot_b, ok)."""
    row, a, ok1 = sock_alloc(row, PROTO_PIPE)
    row, b, ok2 = sock_alloc(row, PROTO_PIPE)
    ok = ok1 & ok2

    def link(r):
        return r.replace(
            sk_parent=rset(rset(r.sk_parent, a, b.astype(_I32)),
                           b, a.astype(_I32)))

    def undo(r):
        # partial alloc (only a landed): release it
        return jax.lax.cond(ok1 & ~ok2,
                            lambda r2: sock_free(r2, a),
                            lambda r2: r2, r)

    row = jax.lax.cond(ok, link, undo, row)
    return row, a, b, ok


def pipe_write(row, now, slot, nbytes):
    """Move the full byte count to the reader and wake it. Delivery is
    immediate (cooperative apps consume on the wake), so a standing
    buffer fill never exists and PIPE_BUFFER_SIZE backpressure is NOT
    modeled — clamping each write to it would silently truncate large
    writes with no short-write signal (modeled byte accounting would
    corrupt); the capacity constant is kept only as documentation of
    the reference's buffer size."""
    partner = rget(row.sk_parent, slot)
    usable = (rget(row.sk_used, slot) & (partner >= 0) &
              (rget(row.sk_proto, slot) == PROTO_PIPE))
    n_ok = jnp.where(usable,
                     jnp.maximum(jnp.asarray(nbytes, _I64), 0), 0)

    def do(r):
        r = r.replace(
            sk_snd_end=rset(r.sk_snd_end, slot,
                            rget(r.sk_snd_end, slot) + n_ok),
            # the reader's stream cursor advances at delivery
            sk_rcv_nxt=rset(r.sk_rcv_nxt, partner,
                            rget(r.sk_rcv_nxt, partner) + n_ok),
            stats=radd(r.stats, ST_BYTES_RECV, n_ok))
        return _wake_partner(r, now, WAKE_SOCKET, partner,
                             ln=n_ok.astype(_I32))

    return jax.lax.cond(n_ok > 0, do, lambda r: r, row)


def pipe_close(row, now, slot):
    """Close this half: EOF to the (still-open) partner, free the
    slot."""
    partner = rget(row.sk_parent, slot)
    live = (rget(row.sk_used, slot) &
            (rget(row.sk_proto, slot) == PROTO_PIPE))
    peer_open = (partner >= 0) & rget(row.sk_used, partner)

    def do(r):
        r = jax.lax.cond(
            peer_open,
            lambda r2: _wake_partner(
                # unlink the partner so a recycled slot cannot alias
                r2.replace(sk_parent=rset(r2.sk_parent, partner,
                                          _I32(-1))),
                now, WAKE_EOF, partner),
            lambda r2: r2, r)
        return sock_free(r, slot)

    return jax.lax.cond(live, do, lambda r: r, row)
