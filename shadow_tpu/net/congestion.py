"""Pluggable TCP congestion control, vectorized.

Reimplements the behavior of the reference's CC vtable family
(/root/reference/src/main/host/descriptor/shd-tcp-congestion.h:31-41,
shd-tcp-aimd.c, shd-tcp-reno.c, shd-tcp-cubic.c) as branchless masked
arithmetic selected by a runtime kind scalar (Shared.cc_kind), default
cubic like the reference (shd-options.c:133).

Window semantics follow the reference: the congestion window is counted
in *packets* (segments), the initial window is 10 packets
(shd-options.c:72), and a zero slow-start threshold means "not yet
discovered" — multiplicative increase continues until the first loss
sets it (shd-tcp-aimd.c:20-27,46-49).

State per socket (columns of Hosts):
  sk_cwnd      f32  congestion window, packets
  sk_ssthresh  f32  slow-start threshold, packets (0 = unset)
  sk_cc_wmax   f32  cubic: window before last loss (lastMaxWindow)
  sk_cc_epoch  i64  cubic: epoch start time ns (-1 = unset)
  sk_cc_k      f32  cubic: K, seconds until plateau
"""

from __future__ import annotations

import jax.numpy as jnp

CC_AIMD = 0
CC_RENO = 1
CC_CUBIC = 2

# Reference cubic constants (shd-tcp-cubic.c — NOT the Linux-kernel
# values): cubic_new sets beta=819 against BETA_SCALE=1024 (Linux uses
# 717), so the loss decrease is W*819/1024 ~ 0.8W and fast convergence
# is W*(1024+819)/2048 ~ 0.9W (cubic_packetLoss, shd-tcp-cubic.c:
# 224-236). The growth constant: _cubic_update computes
# originDelta = (rttScale * offset_ms^3) >> 40 with rttScale =
# scalingFactor*10 = 410 and time in MILLISECONDS (shd-tcp-cubic.c:
# 112-160), i.e. C = 410e9/2^40 ~ 0.3729 pkt/s^3 (the ms time base
# makes this differ from Linux's 0.4, which scales jiffies<<10).
_CUBIC_BETA = 819.0 / 1024.0
_CUBIC_C = 410.0 * 1e9 / float(1 << 40)

_NS = 1e-9  # ns -> seconds


def on_ack(kind, cwnd, ssthresh, wmax, epoch, k, npkts, now, srtt_ns):
    """Congestion avoidance on new-data ACK.

    Args are per-socket scalars (or broadcastable arrays); `kind` is the
    runtime cc selector, `npkts` the number of full segments this ACK
    newly covered, `now` sim time ns, `srtt_ns` the socket's delayMin
    (minimum RTT sample; callers fall back to srtt before the first
    min) — <=0 falls back to the reference's 100ms default
    (shd-tcp-cubic.c:72-74).
    Returns (cwnd', epoch', k').
    """
    npkts_f = npkts.astype(jnp.float32)
    in_ss = (ssthresh == 0.0) | (cwnd < ssthresh)

    # --- slow start (all kinds): window += packets acked ---
    ss_cwnd = cwnd + npkts_f

    # --- aimd/reno additive increase: ceil/frac of n^2/window ---
    add_cwnd = cwnd + (npkts_f * npkts_f) / jnp.maximum(cwnd, 1.0)

    # --- cubic: W(t) = C*(t-K)^3 + wmax, one epoch per loss-free run ---
    fresh = epoch < 0
    epoch2 = jnp.where(fresh, now, epoch)
    k_calc = jnp.cbrt(jnp.maximum(wmax - cwnd, 0.0) / _CUBIC_C)
    k2 = jnp.where(fresh, k_calc, k)
    t = (now - epoch2).astype(jnp.float32) * _NS
    # the curve's origin is FIXED for the epoch (the reference's
    # originPoint, shd-tcp-cubic.c:137-144): wmax when a loss has been
    # seen (post-loss wmax >= cwnd always holds: decrease is 0.8x,
    # fast convergence keeps >= 0.9x), else the pre-loss probe grows
    # from the current window. A moving origin (max(wmax, cwnd)) made
    # the target self-referential past the plateau — growth then
    # saturated at the rate cap instead of following the cubic.
    origin = jnp.where(wmax > 0.0, wmax, cwnd)
    target = _CUBIC_C * (t - k2) ** 3 + origin
    # Growth-rate cap, the reference's minCount floor
    # (shd-tcp-cubic.c:168-173): count never drops below
    # W*1000*8/(10*16*delayMin), and count halves, so the per-ack
    # increment is bounded by delayMin_ms/(25*W) — i.e. at most
    # 0.04*RTT_ms packets per RTT once past the plateau. Without this
    # the target's cubic ramp lets the chase step saturate at one
    # packet per ack = doubling every RTT, unbounded (caught by the
    # golden trajectory test).
    srtt_ms = jnp.where(srtt_ns > 0,
                        srtt_ns.astype(jnp.float32) * 1e-6,
                        jnp.float32(100.0))
    rate_cap = npkts_f * srtt_ms / (25.0 * jnp.maximum(cwnd, 1.0))
    cubic_step = jnp.where(target > cwnd,
                           jnp.minimum((target - cwnd) /
                                       jnp.maximum(cwnd, 1.0), rate_cap),
                           0.01 / jnp.maximum(cwnd, 1.0))
    cubic_cwnd = cwnd + jnp.minimum(cubic_step, npkts_f)

    avoid_cwnd = jnp.where(kind == CC_CUBIC, cubic_cwnd, add_cwnd)
    cwnd2 = jnp.where(in_ss, ss_cwnd, avoid_cwnd)
    # epoch/k only meaningful for cubic avoidance; harmless otherwise
    epoch2 = jnp.where(in_ss, epoch, epoch2)
    k2 = jnp.where(in_ss, k, k2)
    return cwnd2, epoch2, k2


def on_loss(kind, cwnd, ssthresh, wmax):
    """Multiplicative decrease on a loss event (fast retransmit or RTO).

    Mirrors the reference's packetLoss vtable calls and the caller's
    `threshold = packetLoss(); window = threshold` contract
    (shd-tcp.c:1063-1064).
    Returns (cwnd', ssthresh', wmax', epoch'=-1).
    """
    # aimd/reno: halve (shd-tcp-aimd.c:44-60)
    half = jnp.maximum(jnp.ceil(cwnd / 2.0), 1.0)
    # cubic: fast convergence on wmax, beta decrease (shd-tcp-cubic.c:224-236)
    wmax2 = jnp.where(cwnd < wmax, cwnd * (1.0 + _CUBIC_BETA) / 2.0, cwnd)
    cub = jnp.maximum(cwnd * _CUBIC_BETA, 2.0)

    new_wnd = jnp.where(kind == CC_CUBIC, cub, half)
    return (new_wnd, new_wnd,
            jnp.where(kind == CC_CUBIC, wmax2, wmax),
            jnp.int64(-1))
