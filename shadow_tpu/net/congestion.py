"""Pluggable TCP congestion control, vectorized.

Reimplements the behavior of the reference's CC vtable family
(/root/reference/src/main/host/descriptor/shd-tcp-congestion.h:31-41,
shd-tcp-aimd.c, shd-tcp-reno.c, shd-tcp-cubic.c) as branchless masked
arithmetic selected by a runtime kind scalar (Shared.cc_kind), default
cubic like the reference (shd-options.c:133).

Window semantics follow the reference: the congestion window is counted
in *packets* (segments), the initial window is 10 packets
(shd-options.c:72), and a zero slow-start threshold means "not yet
discovered" — multiplicative increase continues until the first loss
sets it (shd-tcp-aimd.c:20-27,46-49).

State per socket (columns of Hosts):
  sk_cwnd      f32  congestion window, packets
  sk_ssthresh  f32  slow-start threshold, packets (0 = unset)
  sk_cc_wmax   f32  cubic: window before last loss (lastMaxWindow)
  sk_cc_epoch  i64  cubic: epoch start time ns (-1 = unset)
  sk_cc_k      f32  cubic: K, seconds until plateau
"""

from __future__ import annotations

import jax.numpy as jnp

CC_AIMD = 0
CC_RENO = 1
CC_CUBIC = 2

# Linux/reference cubic constants: beta = 717/1024, C = 0.4 pkt/s^3
# (shd-tcp-cubic.c uses the same fixed-point beta via BETA_SCALE=1024).
_CUBIC_BETA = 717.0 / 1024.0
_CUBIC_C = 0.4

_NS = 1e-9  # ns -> seconds


def on_ack(kind, cwnd, ssthresh, wmax, epoch, k, npkts, now):
    """Congestion avoidance on new-data ACK.

    Args are per-socket scalars (or broadcastable arrays); `kind` is the
    runtime cc selector, `npkts` the number of full segments this ACK
    newly covered, `now` sim time ns.
    Returns (cwnd', epoch', k').
    """
    npkts_f = npkts.astype(jnp.float32)
    in_ss = (ssthresh == 0.0) | (cwnd < ssthresh)

    # --- slow start (all kinds): window += packets acked ---
    ss_cwnd = cwnd + npkts_f

    # --- aimd/reno additive increase: ceil/frac of n^2/window ---
    add_cwnd = cwnd + (npkts_f * npkts_f) / jnp.maximum(cwnd, 1.0)

    # --- cubic: W(t) = C*(t-K)^3 + wmax, one epoch per loss-free run ---
    fresh = epoch < 0
    epoch2 = jnp.where(fresh, now, epoch)
    k_calc = jnp.cbrt(jnp.maximum(wmax - cwnd, 0.0) / _CUBIC_C)
    k2 = jnp.where(fresh, k_calc, k)
    t = (now - epoch2).astype(jnp.float32) * _NS
    target = _CUBIC_C * (t - k2) ** 3 + jnp.maximum(wmax, cwnd)
    cubic_step = jnp.where(target > cwnd,
                           (target - cwnd) / jnp.maximum(cwnd, 1.0),
                           0.01 / jnp.maximum(cwnd, 1.0))
    cubic_cwnd = cwnd + jnp.minimum(cubic_step, npkts_f)

    avoid_cwnd = jnp.where(kind == CC_CUBIC, cubic_cwnd, add_cwnd)
    cwnd2 = jnp.where(in_ss, ss_cwnd, avoid_cwnd)
    # epoch/k only meaningful for cubic avoidance; harmless otherwise
    epoch2 = jnp.where(in_ss, epoch, epoch2)
    k2 = jnp.where(in_ss, k, k2)
    return cwnd2, epoch2, k2


def on_loss(kind, cwnd, ssthresh, wmax):
    """Multiplicative decrease on a loss event (fast retransmit or RTO).

    Mirrors the reference's packetLoss vtable calls and the caller's
    `threshold = packetLoss(); window = threshold` contract
    (shd-tcp.c:1063-1064).
    Returns (cwnd', ssthresh', wmax', epoch'=-1).
    """
    # aimd/reno: halve (shd-tcp-aimd.c:44-60)
    half = jnp.maximum(jnp.ceil(cwnd / 2.0), 1.0)
    # cubic: fast convergence on wmax, beta decrease (shd-tcp-cubic.c:224-236)
    wmax2 = jnp.where(cwnd < wmax, cwnd * (1.0 + _CUBIC_BETA) / 2.0, cwnd)
    cub = jnp.maximum(cwnd * _CUBIC_BETA, 2.0)

    new_wnd = jnp.where(kind == CC_CUBIC, cub, half)
    return (new_wnd, new_wnd,
            jnp.where(kind == CC_CUBIC, wmax2, wmax),
            jnp.int64(-1))
