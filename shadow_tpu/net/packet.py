"""Packet representation: a fixed-width vector of int32 words.

The reference's Packet is a refcounted heap object with a protocol
header union and a delivery-status trail
(/root/reference/src/main/host/shd-packet.c:11-66, shd-packet.h:15-51).
On TPU a packet is a row of PKT_WORDS int32s living in event queues,
outboxes and exchange buffers — no allocation, no refcounts; lifecycle
status becomes per-host counters (see obs.tracker).

Word layout (all int32):
  0 SRC    source host id
  1 DST    destination host id
  2 SPORT  source port
  3 DPORT  destination port
  4 FLAGS  bits 0-7 protocol (6=TCP, 17=UDP); bits 8+ TCP control flags
  5 SEQ    TCP: first data byte offset of this segment (see note)
  6 ACK    TCP: cumulative ack — next expected data byte offset
  7 WND    TCP: advertised receive window (bytes, clamped to int32)
  8 LEN    payload bytes in this segment
  9 AUX    TCP: timestamp echo / listener child hint; apps: opaque tag
 10 UID    per-source packet counter stamped at emit; (SRC, UID) is the
           globally unique packet id keying the loss roll (rng.DOMAIN_DROP)
 11 APP    application tag: connection metadata on TCP SYNs (e.g. a tgen
           GET request size rides the handshake), opaque app payload tag
           on datagrams. The modeled-app analogue of payload content.

Note on sequence numbers: stream offsets are plain byte counts starting
at 0 (SYN/FIN are modeled as control flags with their own state-machine
retransmission, not as sequence-space occupants — unlike wire TCP but
equivalent for a byte-accounting simulator). int32 offsets cap a single
connection at 2 GiB transferred, matching real TCP's 32-bit sequence
space scale; connections are per-transfer in the bundled apps.
"""

import jax.numpy as jnp

PKT_WORDS = 13

(SRC, DST, SPORT, DPORT, FLAGS, SEQ, ACK, WND, LEN, AUX, UID,
 APP, STATUS) = range(13)

# --- STATUS word: the delivery-status trail -------------------------------
# The reference stamps 18 lifecycle flags on every packet as it moves
# through the stack (shd-packet.h:15-36), logged per transition; here
# the trail is a bitmask accumulated in the packet itself, visible in
# trace-ring records (obs.pcap) and app wakes. Aggregate transition
# counts live in the per-host stats.
DS_CREATED = 1 << 0       # built by the transport (tcp_pull / sendto)
DS_RETRANS = 1 << 1       # this transmission is a re-send
DS_TXQ = 1 << 2           # queued on the NIC transmit ring
DS_NIC_SENT = 1 << 3      # NIC handed it to the wire
DS_LOOPBACK = 1 << 4      # took the local-delivery path
DS_INET = 1 << 5          # entered the cross-host exchange
DS_RX_BUFFERED = 1 << 6   # admitted by the receiver NIC input buffer

_DS_NAMES = [
    (DS_CREATED, "created"), (DS_RETRANS, "retransmit"),
    (DS_TXQ, "tx-queued"), (DS_NIC_SENT, "nic-sent"),
    (DS_LOOPBACK, "loopback"), (DS_INET, "inet"),
    (DS_RX_BUFFERED, "rx-buffered"),
]


def status_names(bits: int) -> list:
    """Decode a STATUS word into the trail's stage names."""
    return [name for bit, name in _DS_NAMES if bits & bit]

# FLAGS word
PROTO_MASK = 0xFF
PROTO_TCP = 6
PROTO_UDP = 17

F_SYN = 1 << 8
F_ACK = 1 << 9
F_FIN = 1 << 10
F_RST = 1 << 11

# Header sizes on the (virtual) wire — used for NIC bandwidth accounting,
# matching reference CONFIG_HEADER_SIZE_{TCP,UDP}IPETH.
from ..core.constants import HEADER_SIZE_TCPIPETH, HEADER_SIZE_UDPIPETH  # noqa: E402


def make(src, dst, sport, dport, flags, seq=0, ack=0, wnd=0, length=0,
         aux=0, app=0, status=0):
    """Assemble a packet word vector (traced or concrete int32s).
    UID is stamped later, at NIC emit time."""
    return jnp.stack([
        jnp.int32(src), jnp.int32(dst), jnp.int32(sport), jnp.int32(dport),
        jnp.int32(flags), jnp.int32(seq), jnp.int32(ack), jnp.int32(wnd),
        jnp.int32(length), jnp.int32(aux), jnp.int32(0), jnp.int32(app),
        jnp.int32(status),
    ])


def wire_bytes(pkt):
    """Total on-wire size for bandwidth accounting. Widened to i64 at
    the source: every consumer is i64 byte/ns arithmetic (NIC busy
    horizons, buffer backlogs), and the packet words are i32
    (simlint STF401)."""
    proto = pkt[FLAGS] & PROTO_MASK
    hdr = jnp.where(proto == PROTO_TCP, HEADER_SIZE_TCPIPETH, HEADER_SIZE_UDPIPETH)
    return (pkt[LEN] + hdr).astype(jnp.int64)
