"""net subpackage."""
