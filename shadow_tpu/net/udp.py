"""UDP: connectionless datagram sockets over the shared socket table.

Mirrors the reference's UDP (/root/reference/src/main/host/descriptor/
shd-udp.c): stateless send/receive through the socket buffers with NIC
bandwidth applied. Payload contents are not materialized — apps are
modeled, so a datagram is its byte count plus a 32-bit app tag
(packet AUX), which is how the bundled apps carry timestamps.

Row-level functions (one host under vmap).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.constants import UDP_MAX_PAYLOAD
from ..core.rowops import radd, rget, rset, rset_where
from ..engine import equeue
from ..engine.defs import EV_APP, WAKE_SOCKET, ST_BYTES_RECV
from . import nic
from . import packet as P
from .socket import sock_alloc, alloc_eport


def udp_open(row, port=None):
    """Create a UDP socket; bind to `port` or an ephemeral one.
    Returns (row, slot, ok)."""
    row, slot, ok = sock_alloc(row, P.PROTO_UDP)
    if port is None:
        row, p = alloc_eport(row)
    else:
        p = jnp.int32(port)
    row = row.replace(sk_lport=rset_where(row.sk_lport, slot, ok, p))
    return row, slot, ok


def udp_sendto(row, hp, now, slot, dst_host, dst_port, nbytes, aux=0):
    """Send one datagram of `nbytes` payload to (dst_host, dst_port).

    The packet is fully formed here and enqueued on the host's NIC
    transmit ring (the socket-output-buffer -> qdisc flow of the
    reference), so concurrent sendto calls to different destinations
    never interfere. The socket stays unconnected for demux, like a
    real sendto. Payload is clamped to one MTU-sized datagram
    (modeled apps send message-sized datagrams).
    """
    length = jnp.minimum(jnp.int64(nbytes), UDP_MAX_PAYLOAD).astype(jnp.int32)
    pkt = P.make(src=hp.hid, dst=dst_host, sport=rget(row.sk_lport, slot),
                 dport=dst_port, flags=P.PROTO_UDP, length=length, aux=aux,
                 status=P.DS_CREATED)
    row = row.replace(sk_snd_end=radd(row.sk_snd_end, slot,
                                      jnp.int64(length)))
    row = nic.txq_push(row, pkt)
    return nic.kick(row, now)


def udp_deliver(row, hp, sh, now, slot, pkt):
    """Inbound datagram for socket `slot`: account bytes, wake the app.

    The app wake carries the datagram's source/ports/len/tag with the
    target socket in SEQ and the reason in ACK (see engine.defs) — the
    vectorized analogue of the reference's epoll-notify ->
    process_continue reentry chain (shd-epoll.c:597-658)."""
    length = jnp.int64(pkt[P.LEN])
    row = row.replace(
        sk_rcv_nxt=radd(row.sk_rcv_nxt, slot, length),
        stats=radd(row.stats, ST_BYTES_RECV, length),
    )
    wake = rset(rset(pkt, P.SEQ, jnp.int32(slot)), P.ACK, WAKE_SOCKET)
    # socket generation for the hosting tier (see tcp._wake)
    wake = rset(wake, P.WND, rget(row.sk_timer_gen, slot))
    return equeue.q_push(row, now + 1, EV_APP, wake)
