"""TCP: vectorized state machine over the socket table.

Re-implements the behavior of the reference's TCP
(/root/reference/src/main/host/descriptor/shd-tcp.c, 2254 LoC): the
11-state machine, server multiplexing into child sockets, sliding
windows, RFC6298 retransmission timers, fast retransmit, and pluggable
congestion control — as branch-masked vectorized kernels instead of
per-connection callbacks.

This module currently carries the interface stubs wired into the NIC;
the full state machine lands with the TCP milestone.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import packet as P


def tcp_want_tx(row):
    """[S] bool: TCP sockets owing the wire a data segment."""
    return jnp.zeros_like(row.sk_used)


def tcp_pull(row, hp, sh, now, slot):
    """NIC pull for a TCP socket. Returns (row, pkt, has_pkt)."""
    return row, jnp.zeros((P.PKT_WORDS,), jnp.int32), jnp.bool_(False)


def tcp_rx(row, hp, sh, now, slot, pkt):
    """Inbound TCP segment dispatch for socket `slot`."""
    return row


def on_tcp_timer(row, hp, sh, now, pkt):
    """EV_TCP_TIMER handler (retransmission timeout)."""
    return row


def on_tcp_close(row, hp, sh, now, pkt):
    """EV_TCP_CLOSE handler (TIME_WAIT / close teardown)."""
    return row
