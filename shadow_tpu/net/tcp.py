"""TCP: vectorized connection state machine over the socket table.

Re-implements the behavior of the reference's TCP
(/root/reference/src/main/host/descriptor/shd-tcp.c, 2254 LoC) as
branch-masked row-level kernels instead of per-connection callbacks:

- the 11-state machine (shd-tcp.c:10-15) lives in sk_state;
- server multiplexing into child sockets keyed by peer
  (shd-tcp.c:56-78,198-264) becomes child-row allocation on SYN plus the
  exact-4-tuple demux preference in socket.sock_demux;
- sliding windows (shd-tcp.c:88-132) are stream-offset arithmetic on
  sk_snd_una/nxt/max/end and sk_rcv_nxt (SYN/FIN are control flags with
  their own retransmission, not sequence-space occupants — see
  net.packet for the offset model);
- the retransmit queue + RFC6298 RTO timer chain (shd-tcp.c:729-843,
  1068-1128) becomes go-back-N from snd_una driven by one outstanding
  EV_TCP_TIMER per socket with a desired-deadline re-check, mirroring
  the reference's desiredTimerExpiration pattern (shd-tcp.c:1091-1100);
- loss recovery carries the SACK scoreboard (net.sack, mirroring
  shd-tcp-scoreboard.c): the receiver buffers out-of-order runs into a
  K-range scoreboard advertised on every ACK, and dupack-triggered fast
  retransmit resends only bytes inferably lost below the highest sacked
  run; scoreboard overflow degrades (counted) to go-back-N at RTO;
- congestion control is the pluggable aimd/reno/cubic family
  (net.congestion), entered via the same avoidance/packetLoss seams as
  the reference (shd-tcp.c:1809,1063-1064);
- the close handshake (FIN/ACK, TIME_WAIT with the 60s close timer,
  shd-tcp.c:439-523) runs on EV_TCP_CLOSE events.

All functions are row-level (one host under vmap). App-facing calls:
tcp_listen, tcp_connect, tcp_write, tcp_close_call.

Hot/cold row contract (engine.state HOT_FIELDS/COLD_WHEN): every
``sk_*`` column this machine touches is part of the drain's hot
working set on TCP tiers, and on ``uses_tcp=False`` tiers the 38
TCP-only columns are config-gated cold — the rows this module sees
there come from the default row prototype, which is exact because the
only reachable writes are the sock_alloc/sock_free default resets
(see the COLD_WHEN invariant note in engine/state.py). A new column
access here lands in the stateflow matrix and the CI snapshot diff;
an access to a COLD_FIELDS column fails simlint STF303 by name.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.constants import (TCP_MSS, TCP_RTO_MIN, TCP_RTO_MAX,
                              TCP_CLOSE_TIMER_DELAY,
                              SEND_BUFFER_MIN_SIZE, RECV_BUFFER_MIN_SIZE)
from ..core.rowops import radd, rget, rset
from ..engine import equeue
from ..engine.defs import (EV_APP, EV_TCP_TIMER, EV_TCP_CLOSE,
                           WAKE_CONNECTED, WAKE_ACCEPT, WAKE_SOCKET,
                           WAKE_EOF, WAKE_SENT,
                           ST_BYTES_RECV, ST_BYTES_SENT, ST_RETRANSMIT,
                           ST_SOCK_FAIL, ST_SACK_RENEGE)
from ..obs import netscope
from . import congestion as CC
from . import nic
from . import packet as P
from . import sack
from .socket import (TCPS_CLOSED, TCPS_LISTEN, TCPS_SYN_SENT,
                     TCPS_SYN_RECEIVED, TCPS_ESTABLISHED, TCPS_FIN_WAIT_1,
                     TCPS_FIN_WAIT_2, TCPS_CLOSE_WAIT, TCPS_CLOSING,
                     TCPS_LAST_ACK, TCPS_TIME_WAIT,
                     CTL_SYN, CTL_SYNACK, CTL_ACKNOW, CTL_FIN, CTL_RST,
                     sock_alloc, sock_free, alloc_eport)

_I32 = jnp.int32
_I64 = jnp.int64

# AUX bit on ACK-bearing segments: "your FIN is fully received" — the
# offset model's stand-in for acking the FIN's sequence slot.
AUX_FINACK = 1


def _set(row, slot, **kw):
    """Set row.<field>[slot] = value for each kwarg (one-hot writes:
    scatters here shattered the window program into unfusable kernels,
    see core.rowops)."""
    return row.replace(
        **{f: rset(getattr(row, f), slot, v) for f, v in kw.items()})


def _wake(row, now, reason, slot, pkt=None, ln=0, aux=0):
    """Schedule an EV_APP notification — the vectorized analogue of the
    epoll-notify -> process_continue reentry (shd-epoll.c:597-658)."""
    w = jnp.zeros((P.PKT_WORDS,), _I32) if pkt is None else pkt
    w = rset(w, P.ACK, _I32(reason))
    w = rset(w, P.SEQ, _I32(slot))
    w = rset(w, P.LEN, _I32(ln))
    w = rset(w, P.AUX, _I32(aux))
    # socket GENERATION rides the (otherwise unused in wakes) WND word
    # so the hosting tier can tell a recycled slot from the connection
    # a late wake belongs to (device slots are reused after close)
    w = rset(w, P.WND, rget(row.sk_timer_gen, slot))
    return equeue.q_push(row, now + 1, EV_APP, w)


def _arm_timer(row, slot, now):
    """Ensure the retransmission timer will fire at now + rto.

    Keeps at most one EV_TCP_TIMER outstanding per socket: if one is in
    flight we only move the desired deadline and the handler re-chains
    (the reference's desiredTimerExpiration check, shd-tcp.c:1091-1100).
    """
    deadline = now + rget(row.sk_rto, slot)
    need_event = ~rget(row.sk_timer_on, slot)

    def push(r):
        ok = equeue.q_has_free(r)
        ev = rset(rset(jnp.zeros((P.PKT_WORDS,), _I32), P.SEQ,
                       _I32(slot)), P.ACK, rget(r.sk_timer_gen, slot))
        r = equeue.q_push(r, deadline, EV_TCP_TIMER, ev)
        # only mark armed if the push landed (full queue = lost wakeup)
        return _set(r, slot, sk_timer_on=ok)

    row = _set(row, slot, sk_rto_deadline=deadline)
    return jax.lax.cond(need_event, push, lambda r: r, row)


def _stop_timer(row, slot):
    return _set(row, slot, sk_rto_deadline=_I64(0))


# --- App-facing calls ------------------------------------------------------

def tcp_listen(row, port):
    """Create a listening socket on `port`. Returns (row, slot, ok)."""
    row, slot, ok = sock_alloc(row, P.PROTO_TCP)
    row = _set(row, slot,
               sk_state=jnp.where(ok, TCPS_LISTEN, rget(row.sk_state, slot)),
               sk_lport=jnp.where(ok, _I32(port), rget(row.sk_lport, slot)))
    return row, slot, ok


def tcp_connect(row, hp, sh, now, dst_host, dst_port, tag=0):
    """Active open to (dst_host, dst_port). Returns (row, slot, ok).
    Sends SYN via the NIC; app is woken WAKE_CONNECTED on completion.
    `tag` is app connection metadata carried in the SYN's APP word and
    delivered to the acceptor (e.g. a tgen GET request size)."""
    row, slot, ok = sock_alloc(row, P.PROTO_TCP)
    row, lport = alloc_eport(row)

    def setup(r):
        r = _set(r, slot,
                 sk_state=_I32(TCPS_SYN_SENT),
                 sk_lport=lport.astype(_I32),
                 sk_rport=_I32(dst_port),
                 sk_rhost=_I32(dst_host),
                 sk_ctl=_I32(CTL_SYN),
                 sk_cwnd=sh.tcp_init_wnd,
                 sk_ssthresh=sh.tcp_ssthresh0,
                 sk_hs_time=_I64(now),
                 sk_syn_tag=_I32(tag))
        r = _arm_timer(r, slot, now)
        return nic.kick(r, now)

    row = jax.lax.cond(ok, setup,
                       lambda r: r.replace(
                           stats=radd(r.stats, ST_SOCK_FAIL, 1)), row)
    return row, slot, ok


def tcp_write(row, now, slot, nbytes):
    """App writes `nbytes` to the stream (payload is not materialized;
    only byte counts flow, as with all modeled apps).

    Stream-offset bound (engine.state.NARROW_SPEC): sk_snd_end and
    every other per-connection stream offset must stay < 2^31 — they
    ride the wire's int32 SEQ/ACK/LEN packet words (net.packet), so an
    offset past that is already a wire-encoding overflow, not a new
    narrow-layout limit. Per-connection cumulative bytes are bounded
    by the apps' declared transfer sizes (socks fetches cap at ~2 MiB
    by the CONNECT tag, tgen/bulk open a fresh connection per
    transfer); rcvbuf advertisement already truncates at 2^31 - 1
    (_recv_window)."""
    row = _set(row, slot,
               sk_snd_end=rget(row.sk_snd_end, slot) + _I64(nbytes))
    return nic.kick(row, now)


def tcp_close_call(row, now, slot):
    """App close: FIN after in-flight data drains (close_after), or
    immediate teardown for listeners/unconnected sockets."""
    state = rget(row.sk_state, slot)
    instant = ((state == TCPS_LISTEN) | (state == TCPS_CLOSED) |
               (state == TCPS_SYN_SENT) | (state == TCPS_SYN_RECEIVED))

    def now_free(r):
        return sock_free(r, slot)

    def deferred(r):
        r = _set(r, slot, sk_close_after=jnp.bool_(True))
        return nic.kick(r, now)

    return jax.lax.cond(instant, now_free, deferred, row)


def tcp_abort_call(row, now, slot):
    """Abortive close (the SO_LINGER-0 shape): RST toward an
    established peer instead of the FIN drain, immediate free
    otherwise. The supervisor path for a crashed/killed hosted process
    (hosting.shim child death) — the peer must observe a reset, not a
    clean shutdown, mirroring what the kernel does to a SIGKILLed
    process's connections (reference: process teardown closes
    descriptors abortively, shd-process.c:3195-3234 vicinity)."""
    used = rget(row.sk_used, slot)
    state = rget(row.sk_state, slot)
    connected = (used & (rget(row.sk_proto, slot) == P.PROTO_TCP) &
                 (state >= TCPS_ESTABLISHED) & (state != TCPS_TIME_WAIT) &
                 (rget(row.sk_rhost, slot) >= 0))

    def rst(r):
        # CTL_RST outranks everything in tcp_pull; the emit frees the
        # socket (RST teardown after emit). Clear close_after so a
        # pending graceful FIN cannot race the reset.
        r = _set(r, slot, sk_ctl=rget(r.sk_ctl, slot) | CTL_RST,
                 sk_close_after=jnp.bool_(False))
        return nic.kick(r, now)

    def free(r):
        return jax.lax.cond(used, lambda rr: sock_free(rr, slot),
                            lambda rr: rr, r)

    return jax.lax.cond(connected, rst, free, row)


# --- Transmit path (NIC pull) ----------------------------------------------

def _win_bytes(row, slot):
    """Effective send window: min(cwnd, peer advertised window)."""
    cw = (rget(row.sk_cwnd, slot).astype(_I64)) * TCP_MSS
    return jnp.minimum(cw, jnp.maximum(rget(row.sk_peer_rwnd, slot), 1))


def _fin_wait_states(state):
    return ((state == TCPS_FIN_WAIT_1) | (state == TCPS_CLOSING) |
            (state == TCPS_LAST_ACK))


def _data_tx_states(state):
    """States in which (re)transmission of stream data is permitted:
    the open states plus the FIN-sent states — after an RTO rewinds
    snd_nxt, data below the FIN must still be deliverable or the
    connection deadlocks in FIN_WAIT_1."""
    return ((state == TCPS_ESTABLISHED) | (state == TCPS_CLOSE_WAIT) |
            (state == TCPS_FIN_WAIT_1) | (state == TCPS_CLOSING) |
            (state == TCPS_LAST_ACK))


def tcp_want_tx(row):
    """[S] bool: sockets owing the wire a data segment, a pending
    fast-retransmission, or a first FIN. (Control-flag work is covered
    by sk_ctl != 0 in nic.tx_want.)"""
    open_tx = ((row.sk_state == TCPS_ESTABLISHED) |
               (row.sk_state == TCPS_CLOSE_WAIT))
    data_tx = _data_tx_states(row.sk_state)
    cw = row.sk_cwnd.astype(_I64) * TCP_MSS
    win = jnp.minimum(cw, jnp.maximum(row.sk_peer_rwnd, 1))
    # recovery cursor skipped over peer-sacked runs, bounded by the
    # loss rule — same sack primitives as tcp_pull ([S, K] batched)
    rex_tgt = sack.skip(row.sk_rex_nxt, row.sk_sack_s, row.sk_sack_e)
    lost_end = sack.lost_bound(row.sk_sack_s, row.sk_sack_e,
                               row.sk_snd_una, row.sk_hole_end)
    rex_ok = data_tx & (row.sk_hole_end > 0) & (rex_tgt < lost_end)
    data_ok = (data_tx & (row.sk_snd_nxt < row.sk_snd_end) &
               (row.sk_snd_nxt < row.sk_snd_una + win))
    fin_due = (open_tx & row.sk_close_after &
               (row.sk_snd_nxt == row.sk_snd_end))
    return (row.sk_proto == P.PROTO_TCP) & (rex_ok | data_ok | fin_due)


def _finack_aux(row, slot):
    """-> (aux_word, app_word): FINACK flag + the two most urgent SACK
    blocks from the receive scoreboard (net.sack wire encoding)."""
    pf = rget(row.sk_peer_fin, slot)
    got_fin = (pf >= 0) & (rget(row.sk_rcv_nxt, slot) >= pf)
    aux = jnp.where(got_fin, AUX_FINACK, 0).astype(_I32)
    b1, b2 = sack.encode2(rget(row.sk_ooo_s, slot),
                          rget(row.sk_ooo_e, slot),
                          rget(row.sk_rcv_nxt, slot))
    return aux | b1, b2


def tcp_pull(row, hp, sh, now, slot):
    """NIC pull: produce this socket's next packet (one per TX event).
    Priority: RST > SYN > SYNACK > data > FIN > pure ACK.
    Returns (row, pkt, has_pkt)."""
    state = rget(row.sk_state, slot)
    ctl = rget(row.sk_ctl, slot)
    open_tx = (state == TCPS_ESTABLISHED) | (state == TCPS_CLOSE_WAIT)

    snd_nxt = rget(row.sk_snd_nxt, slot)
    snd_end = rget(row.sk_snd_end, slot)
    limit = rget(row.sk_snd_una, slot) + _win_bytes(row, slot)
    # fast retransmission runs on its own cursor (the reference's
    # scoreboard next-retransmit selection, shd-tcp-scoreboard.c:271):
    # snd_nxt is NOT rewound; recovery resends only un-sacked holes,
    # jumping the cursor over peer-sacked runs
    data_tx = _data_tx_states(state)
    hole_end = rget(row.sk_hole_end, slot)
    sck_s = rget(row.sk_sack_s, slot)
    sck_e = rget(row.sk_sack_e, slot)
    rex_nxt = sack.skip(rget(row.sk_rex_nxt, slot), sck_s, sck_e)
    lost_end = sack.lost_bound(sck_s, sck_e, rget(row.sk_snd_una, slot),
                               hole_end)
    rex_pending = data_tx & (hole_end > 0) & (rex_nxt < lost_end)
    can_new = data_tx & (snd_nxt < snd_end) & (snd_nxt < limit)
    can_data = rex_pending | can_new

    fin_first = (open_tx & rget(row.sk_close_after, slot) & (snd_nxt == snd_end))
    fin_rexmit = ((ctl & CTL_FIN) != 0) & _fin_wait_states(state)

    p_rst = (ctl & CTL_RST) != 0
    p_syn = (ctl & CTL_SYN) != 0
    p_synack = (ctl & CTL_SYNACK) != 0
    p_fin = (fin_first | fin_rexmit) & ~can_data
    p_ack = (ctl & CTL_ACKNOW) != 0

    sel = jnp.where(p_rst, 0,
          jnp.where(p_syn, 1,
          jnp.where(p_synack, 2,
          jnp.where(can_data, 3,
          jnp.where(p_fin, 4,
          jnp.where(p_ack, 5, -1))))))
    has = sel >= 0

    # common header
    base_flags = _I32(P.PROTO_TCP)
    ack_no = rget(row.sk_rcv_nxt, slot).astype(_I32)
    wnd = jnp.minimum(rget(row.sk_rcvbuf, slot), _I64(2**31 - 1)).astype(_I32)
    aux, sack2 = _finack_aux(row, slot)
    # handshake segments carry this end's bandwidths in AUX (KiB/s,
    # 16 bits each) — the peer autotunes its buffers from the wire
    # instead of indexing a replicated [H] table, which under vmap
    # broadcasts to [H, H] (20 GB at 50k hosts). SYN/SYNACK never
    # carry SACK blocks (scoreboards are empty at handshake).
    bw_stamp = ((jnp.minimum(hp.bw_up >> 10, 0xFFFF).astype(_I32) << 16) |
                jnp.minimum(hp.bw_down >> 10, 0xFFFF).astype(_I32))
    aux = jnp.where((sel == 1) | (sel == 2), bw_stamp, aux)

    # a recovery send stops at the next sacked run (no overlap with
    # bytes the peer already holds) and at the loss boundary
    rex_cap = jnp.minimum(lost_end,
                          sack.next_start_after(rex_nxt, sck_s, sck_e))
    ln = jnp.where(sel == 3,
                   jnp.where(rex_pending,
                             jnp.minimum(_I64(TCP_MSS),
                                         rex_cap - rex_nxt),
                             jnp.minimum(_I64(TCP_MSS),
                                         jnp.minimum(snd_end, limit) -
                                         snd_nxt)),
                   _I64(0)).astype(_I32)
    seq = jnp.where(sel == 3, jnp.where(rex_pending, rex_nxt, snd_nxt),
          jnp.where(sel == 4, snd_end, _I64(0))).astype(_I32)
    flags = base_flags
    flags = flags | jnp.where((sel == 1) | (sel == 2), P.F_SYN, 0)
    flags = flags | jnp.where(sel == 0, P.F_RST, 0)
    flags = flags | jnp.where(sel == 4, P.F_FIN, 0)
    flags = flags | jnp.where((sel == 2) | (sel >= 3), P.F_ACK, 0)

    is_resend = (sel == 3) & (rex_pending |
                              (snd_nxt < rget(row.sk_snd_max, slot)))
    pkt = P.make(src=hp.hid, dst=rget(row.sk_rhost, slot),
                 sport=rget(row.sk_lport, slot), dport=rget(row.sk_rport, slot),
                 flags=flags, seq=seq, ack=ack_no, wnd=wnd, length=ln,
                 aux=aux,
                 app=jnp.where(sel == 1, rget(row.sk_syn_tag, slot),
                               sack2),
                 status=P.DS_CREATED |
                 jnp.where(is_resend, P.DS_RETRANS, 0))

    # --- state updates per selection ---
    # clear the control bit we served; any ACK-bearing send satisfies ACKNOW
    clr = jnp.where(sel == 0, CTL_RST,
          jnp.where(sel == 1, CTL_SYN,
          jnp.where(sel == 2, CTL_SYNACK,
          jnp.where(sel == 4, CTL_FIN, 0))))
    acked_too = (sel == 2) | (sel >= 3)
    clr = clr | jnp.where(acked_too, CTL_ACKNOW, 0)
    row = _set(row, slot, sk_ctl=ctl & ~clr,
               sk_last_tx=_I64(now))  # fifo qdisc service stamp

    # data accounting: fresh transmission vs retransmission, RTT timing
    is_data = sel == 3
    is_rex = is_data & rex_pending
    snd_max = rget(row.sk_snd_max, slot)
    new_nxt = snd_nxt + ln.astype(_I64)
    advance = is_data & ~is_rex & (new_nxt > snd_max)
    # go-back-N after RTO also resends through snd_nxt < snd_max
    gbn = is_data & ~is_rex & (snd_nxt < snd_max)
    fresh_bytes = jnp.where(advance, new_nxt - jnp.maximum(snd_max, snd_nxt),
                            0)
    row = row.replace(stats=radd(radd(row.stats, ST_BYTES_SENT,
                                      fresh_bytes), ST_RETRANSMIT,
                                 jnp.where(is_rex | gbn, 1, 0)))
    # retransmit-interval distribution: the RTO in force at each
    # retransmission (netscope; a non-retransmit send adds zero)
    row = netscope.observe(row, netscope.NS_RETX,
                           rget(row.sk_rto, slot) // 1000,
                           on=is_rex | gbn)
    time_it = is_data & ~is_rex & ~gbn & (rget(row.sk_rtt_seq, slot) < 0)
    row = _set(row, slot,
               sk_snd_nxt=jnp.where(is_data & ~is_rex, new_nxt, snd_nxt),
               sk_rex_nxt=jnp.where(is_rex, rex_nxt + ln.astype(_I64),
                                    rex_nxt),
               sk_snd_max=jnp.where(advance, new_nxt, snd_max),
               sk_rtt_seq=jnp.where(time_it, new_nxt,
                                    rget(row.sk_rtt_seq, slot)),
               sk_rtt_time=jnp.where(time_it, now,
                                     rget(row.sk_rtt_time, slot)))

    # FIN send transitions: EST -> FIN_WAIT_1, CLOSE_WAIT -> LAST_ACK
    is_fin = sel == 4
    st2 = jnp.where(is_fin & (state == TCPS_ESTABLISHED), TCPS_FIN_WAIT_1,
          jnp.where(is_fin & (state == TCPS_CLOSE_WAIT), TCPS_LAST_ACK,
                    state)).astype(_I32)
    row = _set(row, slot, sk_state=st2)

    # RST teardown after emit
    row = jax.lax.cond(sel == 0, lambda r: sock_free(r, slot),
                       lambda r: r, row)

    # arm the retransmission timer for anything that expects an answer
    needs_timer = (sel == 1) | (sel == 2) | is_data | is_fin
    row = jax.lax.cond(needs_timer, lambda r: _arm_timer(r, slot, now),
                       lambda r: r, row)
    return row, pkt, has


# --- Receive path ----------------------------------------------------------

def _rfc6298(srtt, rttvar, sample):
    """RFC6298 smoothed-RTT update (reference shd-tcp.c:844-874).
    Returns (srtt', rttvar', rto')."""
    first = srtt < 0
    srtt1 = jnp.where(first, sample, (7 * srtt + sample) // 8)
    rttvar1 = jnp.where(first, sample // 2,
                        (3 * rttvar + jnp.abs(srtt - sample)) // 4)
    rto = jnp.clip(srtt1 + jnp.maximum(4 * rttvar1, 1),
                   TCP_RTO_MIN, TCP_RTO_MAX)
    return srtt1, rttvar1, rto


def _autotune(row, hp, slot, pkt, apply):
    """Buffer autotuning from a handshake segment (shd-tcp.c:340-433):
    size the buffers to 1.25x the delay-bandwidth product over the
    true path (bottleneck of the two ends), min-bounded; loopback
    pairs get the reference's 16 MiB. Explicit per-host buffer sizes
    (hp.rcvbuf0/sndbuf0 >= 0) disable autotuning, like the reference's
    user-set socket buffer options.

    Inputs ride the packet: the peer's up/down bandwidths in AUX
    (KiB/s halves, stamped by tcp_pull on SYN/SYNACK) and the one-way
    path latency in SEQ (microseconds, stamped by the exchange —
    topologies are undirected so RTT = 2x one-way). Table-free by
    design: per-row dynamic indexing of replicated [H] or [V,V]
    tables broadcasts them per host under vmap (tens of GB at 50k
    hosts)."""
    peer = pkt[P.SRC]
    rtt_us = 2 * jnp.maximum(pkt[P.SEQ].astype(_I64), 0)
    peer_up = ((pkt[P.AUX] >> 16) & 0xFFFF).astype(_I64) << 10
    peer_dn = (pkt[P.AUX] & 0xFFFF).astype(_I64) << 10
    bw_cap = jnp.int64(1) << 38
    snd_bw = jnp.minimum(jnp.minimum(hp.bw_up, peer_dn), bw_cap)
    rcv_bw = jnp.minimum(jnp.minimum(hp.bw_down, peer_up), bw_cap)
    buf_cap = jnp.int64(1) << 30
    sndbuf_auto = jnp.clip((snd_bw * rtt_us // 1_000_000) * 5 // 4,
                           SEND_BUFFER_MIN_SIZE, buf_cap)
    rcvbuf_auto = jnp.clip((rcv_bw * rtt_us // 1_000_000) * 5 // 4,
                           RECV_BUFFER_MIN_SIZE, buf_cap)
    is_loop = peer == hp.hid
    sndbuf_auto = jnp.where(is_loop, 16 * 1024 * 1024, sndbuf_auto)
    rcvbuf_auto = jnp.where(is_loop, 16 * 1024 * 1024, rcvbuf_auto)
    sndbuf1 = jnp.where(hp.sndbuf0 >= 0, hp.sndbuf0, sndbuf_auto)
    rcvbuf1 = jnp.where(hp.rcvbuf0 >= 0, hp.rcvbuf0, rcvbuf_auto)
    return _set(row, slot,
                sk_sndbuf=jnp.where(apply, sndbuf1,
                                    rget(row.sk_sndbuf, slot)),
                sk_rcvbuf=jnp.where(apply, rcvbuf1,
                                    rget(row.sk_rcvbuf, slot)))


def _accept_syn(row, hp, sh, now, lslot, pkt):
    """Listener got a SYN: allocate a child connection row in
    SYN_RECEIVED owing a SYN|ACK — the reference's multiplexed-children
    pattern (shd-tcp.c:198-264)."""
    row, child, ok = sock_alloc(row, P.PROTO_TCP)

    def setup(r):
        r = _set(r, child,
                 sk_state=_I32(TCPS_SYN_RECEIVED),
                 sk_lport=pkt[P.DPORT],
                 sk_rport=pkt[P.SPORT],
                 sk_rhost=pkt[P.SRC],
                 sk_parent=_I32(lslot),
                 # children inherit the LISTENER's owning process:
                 # allocation happens during packet handling, outside
                 # any app dispatch context (app_proc would read 0)
                 sk_proc=rget(r.sk_proc, lslot),
                 sk_ctl=_I32(CTL_SYNACK),
                 sk_cwnd=sh.tcp_init_wnd,
                 sk_ssthresh=sh.tcp_ssthresh0,
                 sk_peer_rwnd=jnp.maximum(pkt[P.WND].astype(_I64), 1),
                 sk_hs_time=_I64(now),
                 sk_syn_tag=pkt[P.APP])
        # passive-side autotuning straight from the SYN's stamps
        r = _autotune(r, hp, child, pkt, jnp.bool_(True))
        return _arm_timer(r, child, now)

    return jax.lax.cond(ok, setup,
                        lambda r: r.replace(
                            stats=radd(r.stats, ST_SOCK_FAIL, 1)), row)


def _rx_conn(row, hp, sh, now, slot, pkt):
    """Segment processing for a non-listening socket — the analogue of
    tcp_processPacket's state dispatch + _tcp_dataProcessing /
    _tcp_ackProcessing (shd-tcp.c:1402-1552)."""
    flags = pkt[P.FLAGS]
    syn = (flags & P.F_SYN) != 0
    ackf = (flags & P.F_ACK) != 0
    fin = (flags & P.F_FIN) != 0
    seq = pkt[P.SEQ].astype(_I64)
    ackno = pkt[P.ACK].astype(_I64)
    ln = pkt[P.LEN].astype(_I64)
    # AUX carries the peer's bandwidth stamps on handshake segments
    # (see _autotune), so the FINACK bit is only meaningful on ~syn
    # segments — without the guard, any peer whose bw_down>>10 is odd
    # would spuriously set fin-acked on the SYN|ACK.
    finack = ~syn & ((pkt[P.AUX] & AUX_FINACK) != 0)

    state0 = rget(row.sk_state, slot)

    # --- A. establishment ---
    estA = (state0 == TCPS_SYN_SENT) & syn & ackf       # our SYN answered
    estB = (state0 == TCPS_SYN_RECEIVED) & ackf & ~syn  # our SYN|ACK acked
    resyn = (state0 == TCPS_SYN_RECEIVED) & syn & ~ackf  # dup SYN: re-answer
    # dup SYN|ACK after we established (our handshake ACK was lost and
    # the peer's SYN|ACK retransmitted): answer with an ACK or the peer
    # stays in SYN_RECEIVED forever (standard TCP: duplicate segments
    # elicit an ACK; the reference reaches the same via ackd-state
    # responses in its packet processing)
    resynack = (state0 >= TCPS_ESTABLISHED) & syn & ackf
    state1 = jnp.where(estA | estB, TCPS_ESTABLISHED, state0).astype(_I32)

    hs_rtt = now - rget(row.sk_hs_time, slot)
    hs_srtt, hs_rttvar, hs_rto = _rfc6298(rget(row.sk_srtt, slot),
                                          rget(row.sk_rttvar, slot), hs_rtt)
    est = estA | estB
    row = _set(row, slot,
               sk_state=state1,
               sk_ctl=rget(row.sk_ctl, slot)
               | jnp.where(estA, CTL_ACKNOW, 0)
               | jnp.where(resyn, CTL_SYNACK, 0)
               | jnp.where(resynack, CTL_ACKNOW, 0),
               sk_srtt=jnp.where(est, hs_srtt, rget(row.sk_srtt, slot)),
               # delayMin: min RTT sample (reference cubic's delayMin)
               sk_rtt_min=jnp.where(
                   est,
                   jnp.where(rget(row.sk_rtt_min, slot) > 0,
                             jnp.minimum(rget(row.sk_rtt_min, slot),
                                         hs_rtt), hs_rtt),
                   rget(row.sk_rtt_min, slot)),
               sk_rttvar=jnp.where(est, hs_rttvar, rget(row.sk_rttvar, slot)),
               sk_rto=jnp.where(est, hs_rto, rget(row.sk_rto, slot)),
               sk_rto_deadline=jnp.where(est, _I64(0),
                                         rget(row.sk_rto_deadline, slot)))
    row = jax.lax.cond(
        est,
        lambda r: _wake(r, now,
                        jnp.where(estA, WAKE_CONNECTED, WAKE_ACCEPT), slot,
                        pkt=pkt),
        lambda r: r, row)

    # --- A2. buffer autotuning: the active opener tunes on the
    # SYN|ACK (estA); the passive side tuned at child creation
    # (_accept_syn) from the SYN — both read the peer's stamped
    # bandwidths and the path latency off the handshake packet itself
    # (see _autotune and the tcp_pull/exchange stamps).
    row = _autotune(row, hp, slot, pkt, estA)

    # --- B. ACK processing ---
    conn = state1 >= TCPS_ESTABLISHED
    valid_ack = ackf & conn
    snd_una0 = rget(row.sk_snd_una, slot)
    snd_end = rget(row.sk_snd_end, slot)
    new_ack = valid_ack & (ackno > snd_una0)
    acked_bytes = jnp.maximum(ackno - snd_una0, 0)
    npkts = (acked_bytes + TCP_MSS - 1) // TCP_MSS
    snd_una1 = jnp.where(new_ack, ackno, snd_una0)

    # accumulate the peer's SACK blocks into the sender scoreboard
    # (the reference's scoreboard_update, shd-tcp-scoreboard.c:187);
    # prune everything the cumulative ack now covers
    snd_max0 = rget(row.sk_snd_max, slot)
    upd = valid_ack & ~syn
    b1s, b1e = sack.decode(pkt[P.AUX], ackno, snd_max0)
    b2s, b2e = sack.decode(pkt[P.APP], ackno, snd_max0)
    sb_s0 = rget(row.sk_sack_s, slot)
    sb_e0 = rget(row.sk_sack_e, slot)
    sb_s1, sb_e1 = sack.insert(sb_s0, sb_e0, jnp.where(upd, b1s, -1),
                               jnp.where(upd, b1e, -2))
    sb_s1, sb_e1 = sack.insert(sb_s1, sb_e1, jnp.where(upd, b2s, -1),
                               jnp.where(upd, b2e, -2))
    sb_s1, sb_e1 = sack.drop_below(sb_s1, sb_e1, snd_una1)
    row = _set(row, slot, sk_sack_s=sb_s1, sk_sack_e=sb_e1)

    # RTT sample (Karn: only the timed offset, cleared on retransmit)
    rtt_seq = rget(row.sk_rtt_seq, slot)
    sample_ok = new_ack & (rtt_seq >= 0) & (ackno >= rtt_seq)
    rtt_sample = jnp.maximum(now - rget(row.sk_rtt_time, slot), 1)
    srtt1, rttvar1, rto1 = _rfc6298(rget(row.sk_srtt, slot),
                                    rget(row.sk_rttvar, slot), rtt_sample)
    rtt_min0 = rget(row.sk_rtt_min, slot)
    rtt_min1 = jnp.where(rtt_min0 > 0,
                         jnp.minimum(rtt_min0, rtt_sample), rtt_sample)
    # congestion: avoidance on new acks, loss on the 3rd dupack
    dup = (valid_ack & (ackno == snd_una0) & (ln == 0) & ~syn & ~fin &
           (rget(row.sk_snd_nxt, slot) > snd_una0))
    dupacks1 = jnp.where(new_ack, 0,
                         rget(row.sk_dupacks, slot) + jnp.where(dup, 1, 0))
    fast_rx = dup & (dupacks1 == 3)

    cw0, ss0 = rget(row.sk_cwnd, slot), rget(row.sk_ssthresh, slot)
    wm0, ep0, k0 = (rget(row.sk_cc_wmax, slot), rget(row.sk_cc_epoch, slot),
                    rget(row.sk_cc_k, slot))
    # the cubic rate cap uses delayMin (min RTT), the reference's
    # choice (shd-tcp-cubic.c:121-126) — srtt inflates under standing
    # queues, which would loosen the cap exactly when congestion builds
    delay_ns = jnp.where(rget(row.sk_rtt_min, slot) > 0,
                         rget(row.sk_rtt_min, slot),
                         rget(row.sk_srtt, slot))
    cw_a, ep_a, k_a = CC.on_ack(sh.cc_kind, cw0, ss0, wm0, ep0, k0,
                                npkts, now, delay_ns)
    cw_l, ss_l, wm_l, ep_l = CC.on_loss(sh.cc_kind, cw0, ss0, wm0)

    row = _set(
        row, slot,
        sk_snd_una=snd_una1,
        sk_dupacks=dupacks1.astype(_I32),
        sk_peer_rwnd=jnp.where(valid_ack,
                               jnp.maximum(pkt[P.WND].astype(_I64), 1),
                               rget(row.sk_peer_rwnd, slot)),
        sk_srtt=jnp.where(sample_ok, srtt1, rget(row.sk_srtt, slot)),
        sk_rtt_min=jnp.where(sample_ok, rtt_min1, rtt_min0),
        sk_rttvar=jnp.where(sample_ok, rttvar1, rget(row.sk_rttvar, slot)),
        sk_rto=jnp.where(sample_ok, rto1, rget(row.sk_rto, slot)),
        sk_rtt_seq=jnp.where(sample_ok, _I64(-1), rtt_seq),
        sk_cwnd=jnp.where(fast_rx, cw_l, jnp.where(new_ack, cw_a, cw0)),
        sk_ssthresh=jnp.where(fast_rx, ss_l, ss0),
        sk_cc_wmax=jnp.where(fast_rx, wm_l, wm0),
        sk_cc_epoch=jnp.where(fast_rx, ep_l,
                              jnp.where(new_ack, ep_a, ep0)),
        sk_cc_k=jnp.where(new_ack & ~fast_rx, k_a, k0),
        # Recovery: retransmit every un-sacked hole below the recovery
        # point on a separate cursor — snd_nxt is NOT rewound (the
        # reference's scoreboard-driven recovery, shd-tcp.c:1044-1066 +
        # shd-tcp-scoreboard.c). The recovery point is everything
        # outstanding at loss detection; the cursor jumps sacked runs
        # (tcp_pull); the episode ends when the cumulative ack covers
        # the recovery point; a partial ack advances the cursor.
        sk_hole_end=jnp.where(
            fast_rx, snd_max0,
            jnp.where(new_ack & (ackno >= rget(row.sk_hole_end, slot)),
                      _I64(0), rget(row.sk_hole_end, slot))),
        sk_rex_nxt=jnp.where(fast_rx, ackno,
                             jnp.where(new_ack,
                                       jnp.maximum(rget(row.sk_rex_nxt, slot),
                                                   ackno),
                                       rget(row.sk_rex_nxt, slot))),
    )

    # our FIN acked?
    fin_done = valid_ack & finack & (ackno >= snd_end)
    fin_acked1 = rget(row.sk_fin_acked, slot) | fin_done
    state2 = jnp.where(fin_acked1 & (state1 == TCPS_FIN_WAIT_1),
                       TCPS_FIN_WAIT_2,
              jnp.where(fin_acked1 & (state1 == TCPS_CLOSING),
                        TCPS_TIME_WAIT,
              jnp.where(fin_acked1 & (state1 == TCPS_LAST_ACK),
                        TCPS_CLOSED, state1))).astype(_I32)
    row = _set(row, slot, sk_fin_acked=fin_acked1, sk_state=state2)

    # restart/stop the retransmission timer on forward progress
    flight = ((rget(row.sk_snd_nxt, slot) > snd_una1) |
              (_fin_wait_states(state2) & ~fin_acked1))
    row = _set(row, slot, sk_rto_deadline=jnp.where(
        valid_ack, jnp.where(flight, now + rget(row.sk_rto, slot), _I64(0)),
        rget(row.sk_rto_deadline, slot)))

    # all-written-bytes-acked notification
    sent_all = new_ack & (ackno >= snd_end) & (snd_end > 0)
    row = jax.lax.cond(sent_all,
                       lambda r: _wake(r, now, WAKE_SENT, slot, pkt=pkt),
                       lambda r: r, row)

    # --- C. data ---
    # Out-of-order segments are held in the K-range receive scoreboard
    # (net.sack). An in-order arrival that reaches a held run delivers
    # the whole buffered chain at once; more than K disjoint runs
    # discards the highest (its bytes are retransmitted eventually).
    can_rx = ((state2 == TCPS_ESTABLISHED) | (state2 == TCPS_FIN_WAIT_1) |
              (state2 == TCPS_FIN_WAIT_2))
    has_data = (ln > 0) & can_rx
    rcv0 = rget(row.sk_rcv_nxt, slot)
    oos0 = rget(row.sk_ooo_s, slot)
    ooe0 = rget(row.sk_ooo_e, slot)
    seg_end = seq + ln

    in_order = has_data & (seq <= rcv0) & (seg_end > rcv0)
    adv = jnp.where(in_order, seg_end, rcv0)
    oos1, ooe1, rcv1 = sack.consume(oos0, ooe0, adv)

    is_ooo = has_data & (seq > rcv1)
    oos2, ooe2, reneged = sack.insert_counted(
        oos1, ooe1,
        jnp.where(is_ooo, seq, -1),
        jnp.where(is_ooo, seg_end, -2))

    delivered = rcv1 - rcv0
    row = _set(row, slot,
               sk_rcv_nxt=rcv1,
               sk_ooo_s=oos2,
               sk_ooo_e=ooe2,
               sk_ctl=rget(row.sk_ctl, slot) |
               jnp.where((ln > 0) | fin, CTL_ACKNOW, 0))
    row = row.replace(stats=radd(
        radd(row.stats, ST_BYTES_RECV, delivered),
        ST_SACK_RENEGE, reneged.astype(jnp.int64)))
    row = jax.lax.cond(
        delivered > 0,
        lambda r: _wake(r, now, WAKE_SOCKET, slot, pkt=pkt,
                        ln=delivered.astype(_I32), aux=pkt[P.AUX]),
        lambda r: r, row)

    # --- D. peer FIN ---
    # The FIN may arrive while a data hole is still open; record its
    # offset once and re-evaluate completion on EVERY segment, so the
    # retransmission that fills the hole also delivers the EOF (state
    # transitions make the wake fire exactly once).
    fin_valid = fin & (state2 >= TCPS_ESTABLISHED)
    peer_fin1 = jnp.where(fin_valid & (rget(row.sk_peer_fin, slot) < 0), seq,
                          rget(row.sk_peer_fin, slot))
    fin_complete = (peer_fin1 >= 0) & (rcv1 >= peer_fin1)
    eof_now = fin_complete & ((state2 == TCPS_ESTABLISHED) |
                              (state2 == TCPS_FIN_WAIT_1) |
                              (state2 == TCPS_FIN_WAIT_2))
    state3 = jnp.where(eof_now & (state2 == TCPS_ESTABLISHED),
                       TCPS_CLOSE_WAIT,
              jnp.where(eof_now & (state2 == TCPS_FIN_WAIT_1),
                        jnp.where(fin_acked1, TCPS_TIME_WAIT, TCPS_CLOSING),
              jnp.where(eof_now & (state2 == TCPS_FIN_WAIT_2),
                        TCPS_TIME_WAIT, state2))).astype(_I32)
    row = _set(row, slot, sk_peer_fin=peer_fin1, sk_state=state3)
    row = jax.lax.cond(eof_now,
                       lambda r: _wake(r, now, WAKE_EOF, slot, pkt=pkt),
                       lambda r: r, row)

    # --- E. terminal bookkeeping ---
    to_time_wait = (state3 == TCPS_TIME_WAIT) & (state0 != TCPS_TIME_WAIT)

    def sched_close(r):
        ev = rset(rset(jnp.zeros((P.PKT_WORDS,), _I32), P.SEQ,
                       _I32(slot)), P.ACK, rget(r.sk_timer_gen, slot))
        r = equeue.q_push(r, now + TCP_CLOSE_TIMER_DELAY, EV_TCP_CLOSE, ev)
        return _stop_timer(r, slot)

    row = jax.lax.cond(to_time_wait, sched_close, lambda r: r, row)
    row = jax.lax.cond(state3 == TCPS_CLOSED,
                       lambda r: sock_free(r, slot), lambda r: r, row)
    return row


def tcp_rx(row, hp, sh, now, slot, pkt):
    """Inbound TCP segment dispatch for socket `slot` (from the NIC
    demux). Listener SYNs spawn children; everything else runs the
    connection machine; any state change may unblock the NIC."""
    flags = pkt[P.FLAGS]
    syn = (flags & P.F_SYN) != 0
    ackf = (flags & P.F_ACK) != 0
    rst = (flags & P.F_RST) != 0
    state = rget(row.sk_state, slot)

    def on_rst(r):
        r = jax.lax.cond(state >= TCPS_ESTABLISHED,
                         lambda rr: _wake(rr, now, WAKE_EOF, slot, pkt=pkt),
                         lambda rr: rr, r)
        return sock_free(r, slot)

    def dispatch(r):
        is_listen_syn = (state == TCPS_LISTEN) & syn & ~ackf
        return jax.lax.cond(
            is_listen_syn,
            lambda rr: _accept_syn(rr, hp, sh, now, slot, pkt),
            lambda rr: _rx_conn(rr, hp, sh, now, slot, pkt), r)

    row = jax.lax.cond(rst, on_rst, dispatch, row)
    return nic.kick(row, now)


# --- Timers ----------------------------------------------------------------

def on_tcp_timer(row, hp, sh, now, wend, ev):
    """EV_TCP_TIMER: RFC6298 retransmission timeout with deadline
    re-chaining (one outstanding event per socket)."""
    slot = ev[P.SEQ]
    gen = ev[P.ACK]
    valid = (rget(row.sk_used, slot) & (gen == rget(row.sk_timer_gen, slot)) &
             (rget(row.sk_proto, slot) == P.PROTO_TCP))

    def live(r):
        deadline = rget(r.sk_rto_deadline, slot)

        def off(rr):
            return _set(rr, slot, sk_timer_on=jnp.bool_(False))

        def rechain(rr):
            ev2 = rset(rset(jnp.zeros((P.PKT_WORDS,), _I32), P.SEQ,
                            slot), P.ACK, gen)
            return equeue.q_push(rr, deadline, EV_TCP_TIMER, ev2)

        def expired(rr):
            state = rget(rr.sk_state, slot)
            # exponential backoff (rfc6298 5.5, shd-tcp.c:1104-1106)
            rto2 = jnp.minimum(rget(rr.sk_rto, slot) * 2, TCP_RTO_MAX)
            # handshake control resends
            ctl2 = (rget(rr.sk_ctl, slot)
                    | jnp.where(state == TCPS_SYN_SENT, CTL_SYN, 0)
                    | jnp.where(state == TCPS_SYN_RECEIVED, CTL_SYNACK, 0)
                    | jnp.where(_fin_wait_states(state) &
                                ~rget(rr.sk_fin_acked, slot), CTL_FIN, 0))
            # go-back-N: rewind to the oldest unacked offset
            had_flight = rget(rr.sk_snd_nxt, slot) > rget(rr.sk_snd_una, slot)
            cw_l, ss_l, wm_l, ep_l = CC.on_loss(
                sh.cc_kind, rget(rr.sk_cwnd, slot), rget(rr.sk_ssthresh, slot),
                rget(rr.sk_cc_wmax, slot))
            rr = _set(
                rr, slot,
                sk_rto=rto2,
                sk_ctl=ctl2.astype(_I32),
                sk_snd_nxt=jnp.where(had_flight, rget(rr.sk_snd_una, slot),
                                     rget(rr.sk_snd_nxt, slot)),
                sk_cwnd=jnp.where(had_flight, cw_l, rget(rr.sk_cwnd, slot)),
                sk_ssthresh=jnp.where(had_flight, ss_l,
                                      rget(rr.sk_ssthresh, slot)),
                sk_cc_wmax=jnp.where(had_flight, wm_l,
                                     rget(rr.sk_cc_wmax, slot)),
                sk_cc_epoch=jnp.where(had_flight, ep_l,
                                      rget(rr.sk_cc_epoch, slot)),
                sk_hole_end=_I64(0),  # RTO: full go-back-N, no skip
                # clear the sender scoreboard: after a timeout the
                # peer may have reneged; trust nothing (RFC 2018 §8)
                sk_sack_s=sack.empty()[0],
                sk_sack_e=sack.empty()[1],
                sk_rtt_seq=_I64(-1),  # Karn
                sk_timer_on=jnp.bool_(False),
            )
            rr = _arm_timer(rr, slot, now)
            return nic.kick(rr, now)

        return jax.lax.cond(
            deadline == 0, off,
            lambda rr: jax.lax.cond(now < deadline, rechain, expired, rr),
            r)

    return jax.lax.cond(valid, live, lambda r: r, row)


def on_tcp_close(row, hp, sh, now, wend, ev):
    """EV_TCP_CLOSE: TIME_WAIT expiration frees the socket row
    (the reference's 60s close timer, shd-tcp.c:439-523)."""
    slot = ev[P.SEQ]
    gen = ev[P.ACK]
    valid = (rget(row.sk_used, slot) & (gen == rget(row.sk_timer_gen, slot)) &
             (rget(row.sk_state, slot) == TCPS_TIME_WAIT))
    return jax.lax.cond(valid, lambda r: sock_free(r, slot),
                        lambda r: r, row)
