"""Socket table operations (row-level, under vmap).

The reference's descriptor hierarchy (Descriptor -> Transport -> Socket
-> TCP/UDP, /root/reference/src/main/host/descriptor/shd-socket.h:18-60)
becomes a fixed socket table of SoA columns per host; "allocation" is
claiming a free row, and the NIC's (protocol, port) -> socket demux
(shd-network-interface.c:164-184) is a vectorized match over the table.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.constants import MIN_RANDOM_PORT, MAX_PORT, SEND_BUFFER_SIZE, RECV_BUFFER_SIZE, TCP_RTO_INIT
from ..core.rowops import radd, rset, rset_where
from .packet import PROTO_TCP, PROTO_UDP

# TCP states — same machine as the reference's 11 states (shd-tcp.c:10-15).
TCPS_CLOSED = 0
TCPS_LISTEN = 1
TCPS_SYN_SENT = 2
TCPS_SYN_RECEIVED = 3
TCPS_ESTABLISHED = 4
TCPS_FIN_WAIT_1 = 5
TCPS_FIN_WAIT_2 = 6
TCPS_CLOSE_WAIT = 7
TCPS_CLOSING = 8
TCPS_LAST_ACK = 9
TCPS_TIME_WAIT = 10

# Pending control-transmission bits (sk_ctl): which header-only packets
# this socket owes the wire. Pulled by the NIC ahead of data.
CTL_SYN = 1
CTL_SYNACK = 2
CTL_ACKNOW = 4
CTL_FIN = 8
CTL_RST = 16


def sock_alloc(row, proto):
    """Claim a free socket row. Returns (row, slot, ok).

    Under table pressure, recycles the TIME_WAIT socket with the most
    progress toward its close-timer expiry (real stacks' tw_reuse; the
    reference's per-peer child hash table has no fixed capacity, so
    eviction is what keeps a fixed-width table equivalent). Safe: by
    TIME_WAIT both FINs are exchanged, and the stale close event is
    filtered by the slot generation."""
    free = ~row.sk_used
    tw = row.sk_used & (row.sk_state == TCPS_TIME_WAIT)
    any_free = jnp.any(free)
    ok = any_free | jnp.any(tw)
    # TIME_WAIT eviction: longest-resident first (earliest service
    # stamp) so a recycled connection's 2MSL protection degrades
    # gracefully; non-tw rows rank last
    tw_rank = jnp.where(tw, row.sk_last_tx, jnp.iinfo(jnp.int64).max)
    slot = jnp.where(any_free, jnp.argmax(free), jnp.argmin(tw_rank))

    def setf(arr, val, dt):
        return rset_where(arr, slot, ok, jnp.asarray(val, dt))

    row = row.replace(
        sk_used=setf(row.sk_used, True, jnp.bool_),
        sk_proto=setf(row.sk_proto, proto, jnp.int32),
        sk_state=setf(row.sk_state, TCPS_CLOSED, jnp.int32),
        sk_lport=setf(row.sk_lport, 0, jnp.int32),
        sk_rport=setf(row.sk_rport, 0, jnp.int32),
        sk_rhost=setf(row.sk_rhost, -1, jnp.int32),
        sk_parent=setf(row.sk_parent, -1, jnp.int32),
        sk_snd_una=setf(row.sk_snd_una, 0, jnp.int64),
        sk_snd_nxt=setf(row.sk_snd_nxt, 0, jnp.int64),
        sk_snd_max=setf(row.sk_snd_max, 0, jnp.int64),
        sk_snd_end=setf(row.sk_snd_end, 0, jnp.int64),
        sk_rcv_nxt=setf(row.sk_rcv_nxt, 0, jnp.int64),
        sk_ooo_s=setf(row.sk_ooo_s, -1, jnp.int64),
        sk_ooo_e=setf(row.sk_ooo_e, -1, jnp.int64),
        sk_sack_s=setf(row.sk_sack_s, -1, jnp.int64),
        sk_sack_e=setf(row.sk_sack_e, -1, jnp.int64),
        sk_hole_end=setf(row.sk_hole_end, 0, jnp.int64),
        sk_rex_nxt=setf(row.sk_rex_nxt, 0, jnp.int64),
        sk_peer_fin=setf(row.sk_peer_fin, -1, jnp.int64),
        sk_fin_acked=setf(row.sk_fin_acked, False, jnp.bool_),
        sk_close_after=setf(row.sk_close_after, False, jnp.bool_),
        sk_cwnd=setf(row.sk_cwnd, 0.0, jnp.float32),
        sk_ssthresh=setf(row.sk_ssthresh, 0.0, jnp.float32),
        sk_srtt=setf(row.sk_srtt, -1, jnp.int64),
        sk_rtt_min=setf(row.sk_rtt_min, -1, jnp.int64),
        sk_rttvar=setf(row.sk_rttvar, 0, jnp.int64),
        sk_rto=setf(row.sk_rto, TCP_RTO_INIT, jnp.int64),
        sk_rto_deadline=setf(row.sk_rto_deadline, 0, jnp.int64),
        sk_timer_on=setf(row.sk_timer_on, False, jnp.bool_),
        sk_timer_gen=radd(row.sk_timer_gen, slot, jnp.where(ok, 1, 0)),
        sk_dupacks=setf(row.sk_dupacks, 0, jnp.int32),
        sk_rtt_seq=setf(row.sk_rtt_seq, -1, jnp.int64),
        sk_rtt_time=setf(row.sk_rtt_time, 0, jnp.int64),
        sk_ctl=setf(row.sk_ctl, 0, jnp.int32),
        sk_peer_rwnd=setf(row.sk_peer_rwnd, RECV_BUFFER_SIZE, jnp.int64),
        sk_sndbuf=setf(row.sk_sndbuf, SEND_BUFFER_SIZE, jnp.int64),
        sk_rcvbuf=setf(row.sk_rcvbuf, RECV_BUFFER_SIZE, jnp.int64),
        sk_hs_time=setf(row.sk_hs_time, 0, jnp.int64),
        sk_last_tx=setf(row.sk_last_tx, 0, jnp.int64),
        sk_syn_tag=setf(row.sk_syn_tag, 0, jnp.int32),
        # the allocating process owns the socket: its wakes route back
        # to that process's app (engine.window._on_app). app_proc is
        # the live dispatch context (0 outside multi-process configs).
        sk_proc=setf(row.sk_proc, row.app_proc, jnp.int32),
        sk_app_ref=setf(row.sk_app_ref, -1, jnp.int32),
        sk_cc_wmax=setf(row.sk_cc_wmax, 0.0, jnp.float32),
        sk_cc_epoch=setf(row.sk_cc_epoch, -1, jnp.int64),
        sk_cc_k=setf(row.sk_cc_k, 0.0, jnp.float32),
    )
    return row, slot, ok


def sock_free(row, slot):
    """Release a socket row (descriptor close)."""
    return row.replace(
        sk_used=rset(row.sk_used, slot, False),
        sk_proto=rset(row.sk_proto, slot, 0),
        sk_state=rset(row.sk_state, slot, TCPS_CLOSED),
        sk_ctl=rset(row.sk_ctl, slot, 0),
        sk_rto_deadline=rset(row.sk_rto_deadline, slot, 0),
        sk_timer_on=rset(row.sk_timer_on, slot, False),
        sk_timer_gen=radd(row.sk_timer_gen, slot, 1),
        sk_app_ref=rset(row.sk_app_ref, slot, -1),
    )


def alloc_eport(row):
    """Allocate an ephemeral local port.

    The reference picks random unused ports >= MIN_RANDOM_PORT
    (shd-host.c:967-1049); we use a deterministic per-host cursor with a
    short probe against the table, which preserves uniqueness with the
    same port range.
    """
    span = MAX_PORT - MIN_RANDOM_PORT

    def used(p):
        return jnp.any(row.sk_used & (row.sk_lport == p))

    p0 = row.next_eport
    p = p0
    # unrolled linear probe (collisions need S simultaneous hits; 4 is ample)
    for _ in range(4):
        p = jnp.where(used(p), MIN_RANDOM_PORT + (p + 1 - MIN_RANDOM_PORT) % span, p)
    row = row.replace(
        next_eport=MIN_RANDOM_PORT + (p + 1 - MIN_RANDOM_PORT) % span)
    return row, p


def sock_demux(row, src_host, sport, dport, proto):
    """Find the socket owning an inbound packet.

    Preference order matches a real stack: exact 4-tuple connection
    match, then a bound-but-unconnected (UDP) or listening (TCP) socket
    on the destination port. Returns slot (or -1).
    """
    usable = row.sk_used & (row.sk_proto == proto)
    port_ok = usable & (row.sk_lport == dport)
    exact = port_ok & (row.sk_rhost == src_host) & (row.sk_rport == sport)
    if proto == PROTO_TCP:
        fallback = port_ok & (row.sk_state == TCPS_LISTEN)
    else:
        fallback = port_ok & (row.sk_rhost == -1)
    any_exact = jnp.any(exact)
    any_fb = jnp.any(fallback)
    slot = jnp.where(any_exact, jnp.argmax(exact),
                     jnp.where(any_fb, jnp.argmax(fallback), -1))
    return slot


