"""NIC model: transmit scheduling, bandwidth accounting, packet emission.

Redesigns the reference NetworkInterface
(/root/reference/src/main/host/shd-network-interface.c): its
time-per-byte uplink accounting with scheduled "next send" callbacks
(:229-286,386-454) becomes an ``nic_busy`` horizon plus one EV_NIC_TX
event in flight per host; its qdisc socket selection (:335-379) becomes
a round-robin scan over the socket table; local-vs-remote delivery
split (:414-425) becomes loopback queue push vs. outbox append; and the
bounded input buffer with drop-on-overflow (:288-311) plus the 10ms
batched receive become the rx-horizon admission test in `rx_admit`.

All functions are row-level (one host under vmap).

Shrink-campaign note (engine.state.NARROW_SPEC): the NIC columns stay
at their wide dtypes deliberately. txq_pkt/ob_pkt are already int32
wire words; nic_busy, txq/outbox timestamps and every other i64 here
is a nanosecond simtime, and sim horizons (hours) times 10^9 clear
int32 by orders of magnitude — narrowing any time column is a
correctness bug, not a saving. The NIC's bytes/host lever is capacity,
not dtype: txqcap/obcap come from apps.compile.auto_caps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rget, rset, rset_where
from ..core.simtime import SIMTIME_ONE_SECOND
from ..engine import equeue
from ..obs import netscope
from ..engine.defs import (EV_NIC_TX, EV_PKT, ST_PKTS_SENT, ST_PKTS_DROP_BUF,
                           ST_OUTBOX_DROP, ST_TXQ_DROP)
from . import packet as P

LOOPBACK_DELAY = 1  # ns, reference's local-delivery task delay (:414-421)


def tx_duration(nbytes, bw_bytes_per_sec):
    """Nanoseconds the uplink is busy transmitting nbytes."""
    return (jnp.int64(nbytes) * SIMTIME_ONE_SECOND) // jnp.maximum(bw_bytes_per_sec, 1)


def tx_want(row):
    """[S] bool: TCP sockets owing the wire a packet (control or data).
    (UDP work lives in the transmit ring, checked separately.)"""
    from .tcp import tcp_want_tx  # late import; tcp depends on nic
    return (row.sk_used & ((row.sk_ctl != 0) | tcp_want_tx(row)))


def has_work(row):
    return (row.txq_cnt > 0) | jnp.any(tx_want(row))


def txq_push(row, pkt):
    """Enqueue a fully-formed packet on the NIC transmit ring."""
    T = row.txq_pkt.shape[0]
    ok = row.txq_cnt < T
    slot = (row.txq_head + row.txq_cnt) % T
    pkt = rset(pkt, P.STATUS, pkt[P.STATUS] | P.DS_TXQ)
    return row.replace(
        txq_pkt=rset_where(row.txq_pkt, slot, ok, pkt),
        txq_cnt=row.txq_cnt + jnp.where(ok, 1, 0),
        stats=radd(row.stats, ST_TXQ_DROP, jnp.where(ok, 0, 1)),
    )


def emit(row, hp, now, pkt):
    """Hand a packet to the wire: loopback to own queue, or outbox for
    the window-boundary exchange. Stamps the per-source UID that keys
    the topology loss roll."""
    pkt = rset(pkt, P.UID, row.pkt_ctr)
    pkt = rset(pkt, P.STATUS, pkt[P.STATUS] | P.DS_NIC_SENT)
    is_loop = pkt[P.DST] == hp.hid

    def local(r):
        lp = rset(pkt, P.STATUS, pkt[P.STATUS] | P.DS_LOOPBACK)
        return equeue.q_push(r, now + LOOPBACK_DELAY, EV_PKT, lp)

    def remote(r):
        rp = rset(pkt, P.STATUS, pkt[P.STATUS] | P.DS_INET)
        cnt = r.ob_cnt
        ok = cnt < r.ob_time.shape[0]
        slot = jnp.minimum(cnt, r.ob_time.shape[0] - 1)
        return r.replace(
            ob_pkt=rset_where(r.ob_pkt, slot, ok, rp),
            ob_time=rset_where(r.ob_time, slot, ok, now),
            ob_cnt=cnt + jnp.where(ok, 1, 0),
            stats=radd(r.stats, ST_OUTBOX_DROP, jnp.where(ok, 0, 1)),
        )

    row = jax.lax.cond(is_loop, local, remote, row)
    return row.replace(stats=radd(row.stats, ST_PKTS_SENT, 1),
                       pkt_ctr=row.pkt_ctr + 1)


def kick(row, now):
    """Ensure an EV_NIC_TX event is pending if the NIC has work.
    Called whenever a socket gains something to send."""
    need = has_work(row) & ~row.nic_sched

    def sched(r):
        ok = equeue.q_has_free(r)
        t = jnp.maximum(now, r.nic_busy)
        r = equeue.q_push(r, t, EV_NIC_TX, jnp.zeros((P.PKT_WORDS,), jnp.int32))
        # only mark scheduled if the push landed — a full queue must
        # leave the NIC kickable or it freezes forever (lost wakeup)
        return r.replace(nic_sched=ok)

    return jax.lax.cond(need, sched, lambda r: r, row)


QDISC_FIFO = 0   # least-recently-served socket first — a non-starving
#                  approximation of the reference's FIFO-by-packet-
#                  priority qdisc (shd-network-interface.c:335-379):
#                  oldest waiting work wins, no static priorities
QDISC_RR = 1     # round-robin over wanting sockets


def on_tx(row, hp, sh, now, wend, pkt, qdisc=QDISC_RR):
    """EV_NIC_TX handler: pull one packet — transmit ring first (UDP and
    queued control), else the qdisc-selected TCP socket — emit it,
    account bandwidth, reschedule while work remains.

    When the outbox (this window's emit budget) is full, transmission is
    deferred to the window boundary instead of dropping: the exchange
    drains the outbox between windows, so an EV_NIC_TX at `wend` resumes
    with a fresh budget. Deterministic overflow-to-next-window."""
    row = row.replace(nic_sched=jnp.bool_(False))

    no_room = row.ob_cnt >= row.ob_time.shape[0]

    def defer(r):
        ok = equeue.q_has_free(r)
        r = equeue.q_push(r, jnp.maximum(wend, now + 1), EV_NIC_TX,
                          jnp.zeros((P.PKT_WORDS,), jnp.int32))
        return r.replace(nic_sched=ok)

    return jax.lax.cond(no_room, defer,
                        lambda r: _tx_pull(r, hp, sh, now, qdisc), row)


def _tx_pull(row, hp, sh, now, qdisc=QDISC_RR):
    from .tcp import tcp_pull
    want = tx_want(row)
    S = want.shape[0]
    if qdisc == QDISC_RR:
        # round-robin pick: the wanting socket with the smallest
        # rotated priority (elementwise + argmin; no gathers)
        prio = (jnp.arange(S) - row.nic_rr) % S
        sock = jnp.argmin(jnp.where(want, prio, S))
    else:
        # FIFO: least recently served first (index as tie-break)
        key = row.sk_last_tx * S + jnp.arange(S)
        sock = jnp.argmin(jnp.where(want, key,
                                    jnp.iinfo(jnp.int64).max))
    ring_has = row.txq_cnt > 0

    def pull_ring(r):
        T = r.txq_pkt.shape[0]
        out = rget(r.txq_pkt, r.txq_head)
        r = r.replace(txq_head=(r.txq_head + 1) % T, txq_cnt=r.txq_cnt - 1)
        return r, out, jnp.bool_(True)

    def pull_tcp(r):
        def go(rr):
            rr, out, has = tcp_pull(rr, hp, sh, now, sock)
            rr = rr.replace(nic_rr=jnp.where(
                has, (sock + 1) % S, rr.nic_rr).astype(jnp.int32))
            return rr, out, has

        def nothing(rr):
            return rr, jnp.zeros((P.PKT_WORDS,), jnp.int32), jnp.bool_(False)

        return jax.lax.cond(jnp.any(want), go, nothing, r)

    row, out_pkt, has_pkt = jax.lax.cond(ring_has, pull_ring, pull_tcp, row)

    wire = P.wire_bytes(out_pkt)
    busy_end = now + jnp.where(has_pkt, jnp.maximum(
        tx_duration(wire, hp.bw_up), 1), 0)
    row = jax.lax.cond(has_pkt, lambda r: emit(r, hp, now, out_pkt),
                       lambda r: r, row)
    row = row.replace(nic_busy=busy_end)

    # Keep draining while the ring or sockets still owe packets — but
    # only if this invocation actually made progress (pulled a packet);
    # otherwise rescheduling at busy_end == now would spin the window
    # loop on the same timestamp forever. A want-but-unpullable socket
    # rearms through kick() when its state changes.
    more = has_work(row) & has_pkt

    def resched(r):
        ok = equeue.q_has_free(r)
        r = equeue.q_push(r, busy_end, EV_NIC_TX,
                          jnp.zeros((P.PKT_WORDS,), jnp.int32))
        return r.replace(nic_sched=ok)

    return jax.lax.cond(more, resched, lambda r: r, row)


def rx_admit(row, hp, now, pkt):
    """Downlink admission: models the reference's bounded NIC input
    buffer (drop on overflow) + receive bandwidth. Returns (row, keep).

    The rx engine drains at bw_down; the backlog at `now` in bytes is
    (rx_until - now) * bw_down. A packet is dropped iff backlog + its
    wire size exceeds the configured buffer."""
    wire = P.wire_bytes(pkt)
    bw = jnp.maximum(hp.bw_down, 1)
    backlog_ns = jnp.maximum(row.nic_rx_until - now, 0)
    backlog_bytes = (backlog_ns * bw) // SIMTIME_ONE_SECOND
    keep = (backlog_bytes + wire) <= hp.nic_buf
    new_until = jnp.maximum(row.nic_rx_until, now) + tx_duration(wire, bw)
    row = row.replace(
        nic_rx_until=jnp.where(keep, new_until, row.nic_rx_until),
        stats=radd(row.stats, ST_PKTS_DROP_BUF, jnp.where(keep, 0, 1)),
    )
    # queue-delay distribution: the rx backlog each ADMITTED packet
    # waits behind (netscope; dropped packets add zero)
    row = netscope.observe(row, netscope.NS_QUEUE, backlog_ns // 1000,
                           on=keep)
    return row, keep
