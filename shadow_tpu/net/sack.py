"""SACK scoreboard: fixed-capacity disjoint byte-range sets.

The vectorized redesign of the reference's scoreboard
(/root/reference/src/main/host/descriptor/shd-tcp-scoreboard.c, 351
LoC of linked-list block bookkeeping): both sides of SACK state are a
sorted set of at most K disjoint, non-adjacent [start, end) stream
ranges stored as two [K] int64 vectors (-1 start = empty slot, empties
sorted last):

- receiver: the out-of-order byte runs held above rcv_nxt;
- sender: the peer-reported sacked runs above snd_una (accumulated
  across acks, exactly like the reference scoreboard accumulates SACK
  blocks per packet).

Every operation is a branch-free pass over the K lanes (K is small and
static), so the whole scoreboard fuses into the surrounding TCP kernel
— no lists, no loops over blocks.

The four `[H, S, K]` i64 scoreboard columns (sk_ooo_*/sk_sack_*) are
the largest per-host socket state after the packet buffers — on
`uses_tcp=False` tiers they are config-gated COLD (engine.state
COLD_WHEN "no_tcp") and leave every drain gather; on TCP tiers they
are pinned hot by the rx/pull accesses the stateflow matrix records
(tests/test_stateflow.py::test_sack_scoreboard_update_invariants).

Wire encoding (the two most-urgent blocks ride each ACK, AUX word +
APP word — real TCP carries 2-4 blocks per segment): 15-bit MSS-unit
(offset, length) pairs, SHRUNK to segment alignment — the advertised
range is always a subset of what the receiver truly holds, so the
sender can never skip bytes the peer does not have (an over-claim
would stall recovery until the RTO). Misaligned edges simply lose up
to MSS-1 bytes of advertisement.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.constants import TCP_MSS

# scoreboard capacity: concurrent tracked holes beyond this degrade to
# go-back-N via the RTO, never to wrong data
K = 4

# At-rest layout note (engine.state.NARROW_SPEC): the four scoreboard
# tables (sk_ooo_s/e absolute receive offsets, sk_sack_s/e absolute
# send-side offsets) are stored DELTA-ENCODED as int32 offsets from
# their window anchor (sk_rcv_nxt / sk_snd_una) with -1 kept as the
# empty sentinel. Live ranges always sit within the receive/send
# window of their anchor, and windows are bounded by buf_cap = 2^30
# (tcp._apply_buffer_sizes), so the deltas fit i32 with 2x margin.
# This module never sees the encoded form: the drain and the op-replay
# bridge decode to absolute i64 at entry (engine.state.widen_state)
# and re-encode at exit, so every function below stays in absolute
# offsets — including the -1/_INF sentinel arithmetic.

_I64 = jnp.int64
# plain Python int: a module-level jnp constant would initialize the
# XLA backend at import time (breaking jax.distributed.initialize and
# this build's AOT dispatch — see .claude/skills/verify notes)
_INF = 2**62


def empty():
    """-> (starts, ends) with no ranges."""
    return jnp.full((K,), -1, _I64), jnp.full((K,), -1, _I64)


def _sorted_pack(s, e):
    """Sort ranges ascending by start with empty slots (-1) last."""
    key = jnp.where(s < 0, _INF, s)
    order = jnp.argsort(key)
    return s[order], e[order]


def insert(s, e, ns, ne):
    """Add range [ns, ne) to the set, merging any overlapping or
    touching ranges. On overflow (more than K disjoint ranges) the
    HIGHEST range is discarded — the least urgent for recovery; its
    bytes are simply no longer advertised/recorded and will be
    retransmitted if lost. Returns (s, e)."""
    return insert_counted(s, e, ns, ne)[:2]


def insert_counted(s, e, ns, ne):
    """:func:`insert` that also reports the overflow: returns
    (s, e, dropped) with dropped = 1 when a valid range was discarded
    by the K-truncation. On the receiver side a dropped range may
    already have been advertised to the peer (a SACK renege) — the
    resulting stall is a silent RTO wait, so callers count it
    (ST_SACK_RENEGE) to make it diagnosable."""
    valid = s >= 0
    new_ok = ne > ns
    ov = valid & new_ok & (ns <= e) & (ne >= s)
    ms = jnp.minimum(ns, jnp.min(jnp.where(ov, s, _INF)))
    me = jnp.maximum(ne, jnp.max(jnp.where(ov, e, -1)))
    keep = valid & ~ov
    # K+1 candidates: survivors + the merged range; keep the K lowest
    cs = jnp.concatenate([jnp.where(keep, s, -1),
                          jnp.where(new_ok, ms, -1)[None]])
    ce = jnp.concatenate([jnp.where(keep, e, -1),
                          jnp.where(new_ok, me, -1)[None]])
    key = jnp.where(cs < 0, _INF, cs)
    order = jnp.argsort(key)
    cs, ce = cs[order], ce[order]
    dropped = (cs[K] >= 0).astype(jnp.int32)
    return cs[:K], ce[:K], dropped


def consume(s, e, rcv):
    """Advance the in-order cursor `rcv` through any ranges it reaches,
    absorbing them. Returns (s, e, rcv'). (A single arrival can bridge
    several ranges, hence the K passes.)"""
    for _ in range(K):
        hit = (s >= 0) & (s <= rcv)
        rcv = jnp.maximum(rcv, jnp.max(jnp.where(hit, e, -1)))
        s = jnp.where(hit, -1, s)
        e = jnp.where(hit, -1, e)
    return (*_sorted_pack(s, e), rcv)


def drop_below(s, e, lo):
    """Remove ranges fully below `lo` and clip partial overlap (the
    cumulative ack advanced past them)."""
    valid = s >= 0
    gone = valid & (e <= lo)
    s = jnp.where(gone, -1, jnp.where(valid, jnp.maximum(s, lo), s))
    e = jnp.where(gone, -1, e)
    return _sorted_pack(s, e)


def skip(x, s, e):
    """First offset >= x not inside any range (the retransmit cursor
    jumping over sacked runs). Single pass suffices: ranges are
    disjoint and non-adjacent, so landing exactly on the next range is
    impossible. Batched: x [...] with s/e [..., K]."""
    xk = jnp.asarray(x)[..., None]
    inside = (s >= 0) & (xk >= s) & (xk < e)
    return jnp.maximum(x, jnp.max(jnp.where(inside, e, -1), axis=-1))


def next_start_after(x, s, e):
    """Smallest range start > x (bounds a retransmission so it does not
    overrun into already-sacked bytes); _INF if none. Batched like
    :func:`skip`."""
    xk = jnp.asarray(x)[..., None]
    cand = jnp.where((s >= 0) & (s > xk), s, _INF)
    return jnp.min(cand, axis=-1)


def any_range(s):
    return jnp.any(s >= 0)


def max_end(s, e):
    """Highest sacked offset (-1 when the set is empty), over the last
    axis. Bytes BELOW this with no sacked cover are inferably lost
    (the scoreboard's loss rule: something sent later already
    arrived); bytes above it are merely in flight and must not be
    retransmitted."""
    return jnp.max(jnp.where(s >= 0, e, -1), axis=-1)


def lost_bound(s, e, una, hole_end):
    """Upper bound of inferably-lost bytes for fast recovery: the
    highest sacked run (loss rule above), or one segment past the
    cumulative ack when no sack information exists (classic fast
    retransmit), clipped to the recovery point. ONE implementation for
    both the per-socket eligibility scan (tcp_want_tx) and the pull
    path (tcp_pull), so they cannot disagree."""
    me = max_end(s, e)
    return jnp.minimum(hole_end, jnp.where(me > 0, me, una + TCP_MSS))


# --- wire encoding ----------------------------------------------------------
# 15-bit (offset, length) in MSS units, relative to the carried ack.
# Alignment-safe: offset rounds UP, length rounds DOWN, so the
# advertised range is contained in the true one.

def _encode_one(s_i, e_i, ack):
    has = s_i >= 0
    rel_raw = (s_i - ack + TCP_MSS - 1) // TCP_MSS
    rel = jnp.clip(rel_raw, 0, 0x7FFF)
    a_s = ack + rel * TCP_MSS
    ln = jnp.clip((e_i - a_s) // TCP_MSS, 0, 0x7FFF)
    # a range starting beyond the 15-bit offset field cannot be
    # represented; emit no block rather than a clipped start that
    # would claim bytes below the true range (subset invariant)
    ok = has & (ln > 0) & (rel_raw <= 0x7FFF)
    word = (rel.astype(jnp.int32) << 1) | (ln.astype(jnp.int32) << 16)
    return jnp.where(ok, word, 0)


def encode2(s, e, ack):
    """The two lowest (most recovery-urgent) ranges as packed words for
    the AUX and APP header fields; 0 = no block. Bit 0 of the first
    word is left clear for the FINACK flag."""
    return _encode_one(s[0], e[0], ack), _encode_one(s[1], e[1], ack)


def decode(word, ack, hi):
    """Packed word -> (start, end) clipped to [ack, hi); (-1, -1) when
    absent."""
    rel = ((word >> 1) & 0x7FFF).astype(_I64)
    ln = ((word >> 16) & 0x7FFF).astype(_I64)
    s = ack + rel * TCP_MSS
    e = jnp.minimum(s + ln * TCP_MSS, hi)
    ok = (ln > 0) & (e > s)
    return jnp.where(ok, s, -1), jnp.where(ok, e, -1)
