"""AOT-compiled jit wrapper.

This build's jax/XLA dispatch fast path mis-executes large programs
when several variants of structurally-similar functions are compiled
interleaved with execution: a later call runs against the wrong
executable and dies with "Execution supplied N buffers but compiled
program expected M buffers" (reproduced on both the cpu and TPU
backends; see tests/conftest.py notes). The ahead-of-time path —
``jit(f).lower(*args).compile()`` then calling the Compiled object —
does not go through that dispatch cache and is immune.

:class:`AotJit` wraps a function in exactly that: one Compiled object
per argument-signature (shapes/dtypes/weak-types/shardings), cached.
It costs a small per-call key computation over the arg pytree.

Since PR 13 the memoization has a DISK tier (serving.aotcache): an
AotJit constructed with a stable ``cache_scope`` string resolves a
signature miss by first trying the persistent executable cache (when
one is active — ``--aot-cache DIR`` / ``SHADOW_TPU_AOT_CACHE``), so a
fresh process loads a known program in seconds instead of recompiling
it in minutes. Programs without a stable identity (no cache_scope)
keep the memory-only behavior.
"""

from __future__ import annotations

import jax


class AotJit:
    def __init__(self, fn, cache_scope: str = None, **jit_kwargs):
        self._jit = jax.jit(fn, **jit_kwargs)
        self._fn = fn
        self._jit_kwargs = dict(jit_kwargs)
        self._compiled = {}
        # stable program identity for the persistent cache: must
        # change whenever the traced Python would trace differently
        # (closed-over config, chunk size...) — by convention it
        # carries obs.ledger.fingerprint_of(cfg). None = memory only.
        self.cache_scope = cache_scope
        # latest obs.memscope analysis of an executable built through
        # this wrapper (flops / bytes accessed / arg+temp bytes), or
        # None before the first build
        self.analysis = None

    def undonated_jit(self):
        """The donation-free twin of this program, or None when there
        is nothing to strip. The disk tier executes cached programs
        through this: a serialize/deserialize round trip of a DONATED
        executable is unsound on the XLA:CPU client (the loaded
        executable's outputs alias the donated input buffers, whose
        memory the runtime frees — a use-after-free that corrupts
        results after later allocations; see serving.aotcache).
        Undonated execution computes identical values — donation is
        memory management, never math — at a transient 2x peak for
        the donated operands during the call."""
        if not (self._jit_kwargs.get("donate_argnums")
                or self._jit_kwargs.get("donate_argnames")):
            return None
        kw = {k: v for k, v in self._jit_kwargs.items()
              if k not in ("donate_argnums", "donate_argnames")}
        return jax.jit(self._fn, **kw)

    @staticmethod
    def _sharding_key(sh):
        """The signature's sharding component. Hashable shardings key
        as themselves. An UNHASHABLE sharding must still yield a
        distinct, stable key: the old ``sh = None`` degradation
        aliased two different-sharding signatures onto one executable
        — exactly the wrong-buffers failure mode this class exists to
        prevent. Derive a structural key instead: type, the sorted
        device ids it spans, its string form (NamedSharding spells
        mesh + PartitionSpec there) and the memory kind."""
        if sh is None:
            return None
        try:
            hash(sh)
            return sh
        except TypeError:
            pass
        try:
            devs = tuple(sorted(d.id for d in sh.device_set))
        except Exception:
            devs = None
        return (type(sh).__name__, devs, str(sh),
                getattr(sh, "memory_kind", None))

    @classmethod
    def _sig(cls, args):
        leaves, treedef = jax.tree.flatten(args)

        def leaf_sig(x):
            aval = jax.api_util.shaped_abstractify(x)
            # the input SHARDING is part of the executable contract
            # too: an AOT program compiled for replicated arrays must
            # not run against mesh-sharded ones (hosted + mesh runs
            # call the same op-replay program in both placements)
            sh = cls._sharding_key(getattr(x, "sharding", None))
            return (aval.shape, str(aval.dtype),
                    getattr(aval, "weak_type", False), sh)

        return treedef, tuple(leaf_sig(x) for x in leaves)

    def __call__(self, *args):
        return self.warm(*args)(*args)

    def warm(self, *args):
        """Materialize the executable for this argument signature —
        disk-load or compile — WITHOUT executing it: the fleet
        pre-warm entry point (serving.prewarm). Donated buffers are
        untouched (donation happens at execution, not compilation),
        so a warmed Simulation still runs."""
        key = self._sig(args)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(key, args)
            self._compiled[key] = fn
        return fn

    def _build(self, key, args):
        from ..serving import aotcache
        fn = aotcache.load_or_compile(self._jit, self.cache_scope,
                                      key, args,
                                      undonated=self.undonated_jit)
        # memory observatory hook (obs.memscope): record the XLA
        # cost_analysis (flops, bytes accessed) and memory_analysis
        # (argument/output/temp/generated-code bytes) of every
        # executable this wrapper materializes — compile or disk-load.
        # Graceful on executables that refuse either analysis (loaded
        # disk entries, exotic backends): `available: False` with the
        # error recorded, never a failed build. The latest analysis is
        # also kept on the instance so callers holding the AotJit
        # (engine.sim's cost model) read it without knowing the scope.
        # The DECLARED donation rides along so memscope's donation
        # audit (shrink-campaign lever 4) can compare it against the
        # measured alias_bytes per executable without reaching back
        # into this wrapper.
        from ..obs import memscope
        self.analysis = memscope.observe_executable(
            self.cache_scope or getattr(self._fn, "__name__", "?"), fn,
            donated=self._jit_kwargs.get("donate_argnums", ()))
        return fn


def aot_jit(fn=None, **jit_kwargs):
    """Decorator/factory: like jax.jit but always executes through the
    AOT Compiled path. Static arguments are not supported — close over
    them and cache one AotJit per static configuration instead."""
    if fn is None:
        return lambda f: AotJit(f, **jit_kwargs)
    return AotJit(fn, **jit_kwargs)
