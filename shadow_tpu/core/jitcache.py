"""AOT-compiled jit wrapper.

This build's jax/XLA dispatch fast path mis-executes large programs
when several variants of structurally-similar functions are compiled
interleaved with execution: a later call runs against the wrong
executable and dies with "Execution supplied N buffers but compiled
program expected M buffers" (reproduced on both the cpu and TPU
backends; see tests/conftest.py notes). The ahead-of-time path —
``jit(f).lower(*args).compile()`` then calling the Compiled object —
does not go through that dispatch cache and is immune.

:class:`AotJit` wraps a function in exactly that: one Compiled object
per argument-signature (shapes/dtypes/weak-types), cached. It costs a
small per-call key computation over the arg pytree.
"""

from __future__ import annotations

import jax


class AotJit:
    def __init__(self, fn, **jit_kwargs):
        self._jit = jax.jit(fn, **jit_kwargs)
        self._compiled = {}

    @staticmethod
    def _sig(args):
        leaves, treedef = jax.tree.flatten(args)

        def leaf_sig(x):
            aval = jax.api_util.shaped_abstractify(x)
            # the input SHARDING is part of the executable contract
            # too: an AOT program compiled for replicated arrays must
            # not run against mesh-sharded ones (hosted + mesh runs
            # call the same op-replay program in both placements)
            sh = getattr(x, "sharding", None)
            try:
                hash(sh)
            except TypeError:
                sh = None
            return (aval.shape, str(aval.dtype),
                    getattr(aval, "weak_type", False), sh)

        return treedef, tuple(leaf_sig(x) for x in leaves)

    def __call__(self, *args):
        key = self._sig(args)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._jit.lower(*args).compile()
            self._compiled[key] = fn
        return fn(*args)


def aot_jit(fn=None, **jit_kwargs):
    """Decorator/factory: like jax.jit but always executes through the
    AOT Compiled path. Static arguments are not supported — close over
    them and cache one AotJit per static configuration instead."""
    if fn is None:
        return lambda f: AotJit(f, **jit_kwargs)
    return AotJit(fn, **jit_kwargs)
