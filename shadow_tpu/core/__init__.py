"""core subpackage."""
