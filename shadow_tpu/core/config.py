"""Scenario configuration.

Two front ends, one typed model:

- :func:`load_xml` parses the reference's ``shadow.config.xml`` schema
  (elements and attributes per
  /root/reference/src/main/core/support/shd-configuration.h:36-95 /
  shd-configuration.c): ``<shadow stoptime bootstraptime preload>``,
  ``<topology path=... | CDATA>``, ``<plugin id path>``,
  ``<host id quantity iphint geocodehint typehint bandwidthup
  bandwidthdown cpufrequency loglevel ...>`` containing
  ``<process plugin starttime stoptime arguments>``.
- Plain Python construction of the same dataclasses (the native API).

Bandwidth attributes are KiB/s in the XML (reference semantics); we store
bytes/sec internally.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional
from xml.etree import ElementTree

from .simtime import parse_time


@dataclass
class ProcessSpec:
    """One virtual process on a host (reference ConfigurationProcessElement)."""
    plugin: str                  # plugin/app id, e.g. "tgen", "ping", "phold"
    start_time: int = 0          # ns
    stop_time: int = 0           # ns; 0 = run to simulation end
    arguments: str = ""          # app-specific argument string


@dataclass
class HostSpec:
    """One host template, expanded ``quantity`` times
    (reference ConfigurationHostElement)."""
    id: str
    quantity: int = 1
    processes: list = field(default_factory=list)
    ip_hint: Optional[str] = None
    geocode_hint: Optional[str] = None
    type_hint: Optional[str] = None
    bandwidth_down: Optional[int] = None   # bytes/sec; None = from topology vertex
    bandwidth_up: Optional[int] = None     # bytes/sec
    cpu_frequency: Optional[int] = None    # kHz, reference semantics
    log_level: Optional[str] = None
    pcap: bool = False
    pcap_dir: Optional[str] = None
    socket_recv_buffer: Optional[int] = None
    socket_send_buffer: Optional[int] = None
    interface_buffer: Optional[int] = None
    autotune_recv_buffer: bool = True
    autotune_send_buffer: bool = True


@dataclass
class PluginSpec:
    id: str
    path: str = ""


@dataclass
class FaultSpec:
    """One scheduled fault (engine.faults): a deterministic, config-
    driven robustness event executed at an exact simulated time, so
    dual same-seed runs are bit-identical.

    Kinds:
      host_down   kill `host` at `at` (hosted child killed, modeled
                  state cleared, open TCP connections RST toward peers)
      host_up     restart `host` at `at` (process start events re-armed;
                  a hosted process respawns fresh)
      link_down   zero the path reliability between the attachment
                  vertices of `src` and `dst` (both directions)
      link_up     restore it
      loss        multiply path reliability between `src` and `dst` by
                  (1 - rate) for [at, until)
      latency     add extra_ns to the path latency between `src` and
                  `dst` for [at, until)

    `host`/`src`/`dst` name hosts by their expanded scenario name
    (e.g. ``relay`` or ``client3``) or a raw attachment vertex as
    ``vertex:N``. `until`, when set on link_down/loss/latency, expands
    to the matching restore event — an episode instead of two entries.
    """
    kind: str
    at: int                      # ns
    host: Optional[str] = None   # host_down / host_up
    src: Optional[str] = None    # link/loss/latency endpoints
    dst: Optional[str] = None
    until: Optional[int] = None  # ns; episode end for link/loss/latency
    rate: float = 0.0            # loss probability (kind == "loss")
    extra_ns: int = 0            # added latency (kind == "latency")


@dataclass
class Scenario:
    stop_time: int                      # ns
    topology_graphml: Optional[str] = None   # inline graphml text
    topology_path: Optional[str] = None      # or a file path (.graphml[.xz])
    hosts: list = field(default_factory=list)
    plugins: list = field(default_factory=list)
    faults: list = field(default_factory=list)   # FaultSpec schedule
    bootstrap_end: int = 0
    seed: int = 1
    # CPU delay model (reference shd-cpu.c; engaged per host by the
    # <host cpufrequency=...> attribute). Costs are modeled per event.
    cpu_raw_frequency_khz: int = 3_000_000   # the "physical" CPU
    cpu_event_cost_ns: int = 10_000          # base cost per event
    # Precision default diverges from the reference's 200us: their
    # rounding applies to VARIABLE measured wallclock deltas, ours to a
    # constant modeled base cost — at 200us every realistic frequency
    # would round the cost to exactly 0 and silently disable the model.
    cpu_precision_ns: int = 1_000
    cpu_threshold_ns: int = -1               # reference default: no block
    source_path: Optional[str] = None        # the XML file this scenario
    #   was loaded from (load_xml) — recorded in digest-run manifests
    #   so tools/divergence.py --bisect can rebuild the run

    def total_hosts(self) -> int:
        return sum(h.quantity for h in self.hosts)

    def to_xml(self) -> str:
        """Serialize back to the shadow.config.xml schema load_xml
        parses — ``load_xml(s.to_xml())`` rebuilds an equivalent
        scenario (tests/test_fleet.py round-trips it). This is how
        programmatic scenario builders (tools/baseline_configs.py)
        become submittable fleet runs: the fleet queue stores
        self-contained XML files, not Python closures. Times are
        emitted in exact nanoseconds; the seed is NOT part of the
        schema (pass ``--seed`` on the run's CLI args)."""
        root = ElementTree.Element(
            "shadow", {"stoptime": f"{int(self.stop_time)}ns"})
        if self.bootstrap_end:
            root.set("bootstraptime", f"{int(self.bootstrap_end)}ns")
        # scenario-level CPU-model overrides (a schema extension like
        # <fault>): emitted only when non-default so reference-style
        # files stay reference-style, parsed back by load_xml — a
        # builder's custom CPU model must round-trip into the fleet's
        # XML copy, not silently revert to defaults
        for attr, field_name in _CPU_XML_ATTRS:
            v = getattr(self, field_name)
            if v != Scenario.__dataclass_fields__[field_name].default:
                root.set(attr, str(int(v)))
        topo = ElementTree.SubElement(root, "topology")
        if self.topology_path:
            topo.set("path", self.topology_path)
        elif self.topology_graphml:
            topo.text = self.topology_graphml
        for pl in self.plugins:
            ElementTree.SubElement(root, "plugin",
                                   {"id": pl.id, "path": pl.path})
        for fs in self.faults:
            a = {"kind": fs.kind, "at": f"{int(fs.at)}ns"}
            if fs.host:
                a["host"] = fs.host
            if fs.src:
                a["src"] = fs.src
            if fs.dst:
                a["dst"] = fs.dst
            if fs.until is not None:
                a["until"] = f"{int(fs.until)}ns"
            if fs.rate:
                a["rate"] = repr(fs.rate)
            if fs.extra_ns:
                a["extra"] = f"{int(fs.extra_ns)}ns"
            ElementTree.SubElement(root, "fault", a)
        for h in self.hosts:
            a = {"id": h.id}
            if h.quantity != 1:
                a["quantity"] = str(h.quantity)
            if h.ip_hint:
                a["iphint"] = h.ip_hint
            if h.geocode_hint:
                a["geocodehint"] = h.geocode_hint
            if h.type_hint:
                a["typehint"] = h.type_hint
            if h.bandwidth_down is not None:
                a["bandwidthdown"] = _to_kib(h.bandwidth_down,
                                             "bandwidth_down", h.id)
            if h.bandwidth_up is not None:
                a["bandwidthup"] = _to_kib(h.bandwidth_up,
                                           "bandwidth_up", h.id)
            if h.cpu_frequency is not None:
                a["cpufrequency"] = str(h.cpu_frequency)
            if h.log_level:
                a["loglevel"] = h.log_level
            if h.pcap:
                a["logpcap"] = "true"
            if h.pcap_dir:
                a["pcapdir"] = h.pcap_dir
            if h.socket_recv_buffer is not None:
                a["socketrecvbuffer"] = str(h.socket_recv_buffer)
            if h.socket_send_buffer is not None:
                a["socketsendbuffer"] = str(h.socket_send_buffer)
            if h.interface_buffer is not None:
                a["interfacebuffer"] = str(h.interface_buffer)
            he = ElementTree.SubElement(root, "host", a)
            for pr in h.processes:
                pa = {"plugin": pr.plugin,
                      "starttime": f"{int(pr.start_time)}ns"}
                if pr.stop_time:
                    pa["stoptime"] = f"{int(pr.stop_time)}ns"
                if pr.arguments:
                    pa["arguments"] = pr.arguments
                ElementTree.SubElement(he, "process", pa)
        return ElementTree.tostring(root, encoding="unicode")

    def expand_hosts(self):
        """Yield (flat_host_index, unique_name, HostSpec) with quantity
        expansion. Names follow the reference's hostname scheme: a host
        with quantity>1 gets a 1-based suffix (``web1``, ``web2``, ...;
        reference shd-master.c host registration)."""
        idx = 0
        for spec in self.hosts:
            for q in range(spec.quantity):
                name = spec.id if spec.quantity == 1 else f"{spec.id}{q + 1}"
                yield idx, name, spec
                idx += 1


_BOOL_TRUE = {"1", "true", "yes", "on"}

# scenario-level CPU-model fields carried through the XML (to_xml
# emits when non-default, load_xml parses when present)
_CPU_XML_ATTRS = (
    ("cpurawfrequencykhz", "cpu_raw_frequency_khz"),
    ("cpueventcostns", "cpu_event_cost_ns"),
    ("cpuprecisionns", "cpu_precision_ns"),
    ("cputhresholdns", "cpu_threshold_ns"),
)


def _to_kib(v: int, what: str, host_id: str) -> str:
    """The XML schema stores bandwidths in whole KiB/s. A value that
    cannot round-trip exactly must fail LOUD at serialization time:
    silently flooring would make the fleet's XML copy of a scenario
    simulate different bandwidths than the in-process original (and
    sub-KiB values would emit \"0\", which loads as 'use the topology
    default')."""
    if v <= 0 or v % 1024:
        raise ValueError(
            f"host {host_id!r}: {what}={v} bytes/s is not expressible "
            "in the XML schema's whole-KiB granularity — round it to "
            "a positive multiple of 1024 before to_xml()")
    return str(v // 1024)


def _get_time(attrs, key, default=0):
    if key in attrs:
        return parse_time(attrs[key], default_unit="s")
    return default


def _kib_to_bytes(v) -> int:
    return int(v) * 1024


def load_xml(source: str) -> Scenario:
    """Parse a shadow.config.xml string or file path into a Scenario."""
    src_path = None
    if os.path.exists(source):
        src_path = source
        with open(source) as f:
            text = f.read()
    else:
        text = source
    root = ElementTree.fromstring(text)
    if root.tag != "shadow":
        raise ValueError(f"expected <shadow> root element, got <{root.tag}>")

    scen = Scenario(stop_time=_get_time(root.attrib, "stoptime"),
                    source_path=src_path)
    scen.bootstrap_end = _get_time(root.attrib, "bootstraptime")
    for attr, field_name in _CPU_XML_ATTRS:
        if attr in root.attrib:
            setattr(scen, field_name, int(root.attrib[attr]))

    for el in root:
        if el.tag == "topology":
            if "path" in el.attrib:
                scen.topology_path = el.attrib["path"]
            elif el.text and el.text.strip():
                scen.topology_graphml = el.text
        elif el.tag == "plugin":
            scen.plugins.append(
                PluginSpec(id=el.attrib["id"], path=el.attrib.get("path", "")))
        elif el.tag == "fault":
            a = el.attrib
            if "kind" not in a or "at" not in a:
                raise ValueError("<fault> requires kind= and at= attributes")
            scen.faults.append(FaultSpec(
                kind=a["kind"],
                at=parse_time(a["at"], default_unit="s"),
                host=a.get("host"),
                src=a.get("src"),
                dst=a.get("dst"),
                until=(parse_time(a["until"], default_unit="s")
                       if "until" in a else None),
                rate=float(a.get("rate", 0.0)),
                extra_ns=(parse_time(a["extra"], default_unit="ms")
                          if "extra" in a else 0),
            ))
        elif el.tag == "host" or el.tag == "node":
            a = el.attrib
            host = HostSpec(
                id=a["id"],
                quantity=int(a.get("quantity", 1) or 1),
                ip_hint=a.get("iphint"),
                geocode_hint=a.get("geocodehint"),
                type_hint=a.get("typehint"),
                bandwidth_down=_kib_to_bytes(a["bandwidthdown"]) if "bandwidthdown" in a else None,
                bandwidth_up=_kib_to_bytes(a["bandwidthup"]) if "bandwidthup" in a else None,
                cpu_frequency=int(a["cpufrequency"]) if "cpufrequency" in a else None,
                log_level=a.get("loglevel"),
                pcap=a.get("logpcap", "").lower() in _BOOL_TRUE,
                pcap_dir=a.get("pcapdir"),
                socket_recv_buffer=int(a["socketrecvbuffer"]) if "socketrecvbuffer" in a else None,
                socket_send_buffer=int(a["socketsendbuffer"]) if "socketsendbuffer" in a else None,
                interface_buffer=int(a["interfacebuffer"]) if "interfacebuffer" in a else None,
            )
            host.autotune_recv_buffer = host.socket_recv_buffer is None
            host.autotune_send_buffer = host.socket_send_buffer is None
            for pel in el:
                if pel.tag in ("process", "application"):
                    pa = pel.attrib
                    host.processes.append(ProcessSpec(
                        plugin=pa["plugin"],
                        start_time=_get_time(pa, "starttime"),
                        stop_time=_get_time(pa, "stoptime"),
                        arguments=pa.get("arguments", ""),
                    ))
            scen.hosts.append(host)
    if scen.stop_time <= 0:
        raise ValueError("scenario requires a positive stoptime")
    return scen
