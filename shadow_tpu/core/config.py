"""Scenario configuration.

Two front ends, one typed model:

- :func:`load_xml` parses the reference's ``shadow.config.xml`` schema
  (elements and attributes per
  /root/reference/src/main/core/support/shd-configuration.h:36-95 /
  shd-configuration.c): ``<shadow stoptime bootstraptime preload>``,
  ``<topology path=... | CDATA>``, ``<plugin id path>``,
  ``<host id quantity iphint geocodehint typehint bandwidthup
  bandwidthdown cpufrequency loglevel ...>`` containing
  ``<process plugin starttime stoptime arguments>``.
- Plain Python construction of the same dataclasses (the native API).

Bandwidth attributes are KiB/s in the XML (reference semantics); we store
bytes/sec internally.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional
from xml.etree import ElementTree

from .simtime import parse_time


@dataclass
class ProcessSpec:
    """One virtual process on a host (reference ConfigurationProcessElement)."""
    plugin: str                  # plugin/app id, e.g. "tgen", "ping", "phold"
    start_time: int = 0          # ns
    stop_time: int = 0           # ns; 0 = run to simulation end
    arguments: str = ""          # app-specific argument string


@dataclass
class HostSpec:
    """One host template, expanded ``quantity`` times
    (reference ConfigurationHostElement)."""
    id: str
    quantity: int = 1
    processes: list = field(default_factory=list)
    ip_hint: Optional[str] = None
    geocode_hint: Optional[str] = None
    type_hint: Optional[str] = None
    bandwidth_down: Optional[int] = None   # bytes/sec; None = from topology vertex
    bandwidth_up: Optional[int] = None     # bytes/sec
    cpu_frequency: Optional[int] = None    # kHz, reference semantics
    log_level: Optional[str] = None
    pcap: bool = False
    pcap_dir: Optional[str] = None
    socket_recv_buffer: Optional[int] = None
    socket_send_buffer: Optional[int] = None
    interface_buffer: Optional[int] = None
    autotune_recv_buffer: bool = True
    autotune_send_buffer: bool = True


@dataclass
class PluginSpec:
    id: str
    path: str = ""


@dataclass
class FaultSpec:
    """One scheduled fault (engine.faults): a deterministic, config-
    driven robustness event executed at an exact simulated time, so
    dual same-seed runs are bit-identical.

    Kinds:
      host_down   kill `host` at `at` (hosted child killed, modeled
                  state cleared, open TCP connections RST toward peers)
      host_up     restart `host` at `at` (process start events re-armed;
                  a hosted process respawns fresh)
      link_down   zero the path reliability between the attachment
                  vertices of `src` and `dst` (both directions)
      link_up     restore it
      loss        multiply path reliability between `src` and `dst` by
                  (1 - rate) for [at, until)
      latency     add extra_ns to the path latency between `src` and
                  `dst` for [at, until)

    `host`/`src`/`dst` name hosts by their expanded scenario name
    (e.g. ``relay`` or ``client3``) or a raw attachment vertex as
    ``vertex:N``. `until`, when set on link_down/loss/latency, expands
    to the matching restore event — an episode instead of two entries.
    """
    kind: str
    at: int                      # ns
    host: Optional[str] = None   # host_down / host_up
    src: Optional[str] = None    # link/loss/latency endpoints
    dst: Optional[str] = None
    until: Optional[int] = None  # ns; episode end for link/loss/latency
    rate: float = 0.0            # loss probability (kind == "loss")
    extra_ns: int = 0            # added latency (kind == "latency")


@dataclass
class Scenario:
    stop_time: int                      # ns
    topology_graphml: Optional[str] = None   # inline graphml text
    topology_path: Optional[str] = None      # or a file path (.graphml[.xz])
    hosts: list = field(default_factory=list)
    plugins: list = field(default_factory=list)
    faults: list = field(default_factory=list)   # FaultSpec schedule
    bootstrap_end: int = 0
    seed: int = 1
    # CPU delay model (reference shd-cpu.c; engaged per host by the
    # <host cpufrequency=...> attribute). Costs are modeled per event.
    cpu_raw_frequency_khz: int = 3_000_000   # the "physical" CPU
    cpu_event_cost_ns: int = 10_000          # base cost per event
    # Precision default diverges from the reference's 200us: their
    # rounding applies to VARIABLE measured wallclock deltas, ours to a
    # constant modeled base cost — at 200us every realistic frequency
    # would round the cost to exactly 0 and silently disable the model.
    cpu_precision_ns: int = 1_000
    cpu_threshold_ns: int = -1               # reference default: no block
    source_path: Optional[str] = None        # the XML file this scenario
    #   was loaded from (load_xml) — recorded in digest-run manifests
    #   so tools/divergence.py --bisect can rebuild the run

    def total_hosts(self) -> int:
        return sum(h.quantity for h in self.hosts)

    def expand_hosts(self):
        """Yield (flat_host_index, unique_name, HostSpec) with quantity
        expansion. Names follow the reference's hostname scheme: a host
        with quantity>1 gets a 1-based suffix (``web1``, ``web2``, ...;
        reference shd-master.c host registration)."""
        idx = 0
        for spec in self.hosts:
            for q in range(spec.quantity):
                name = spec.id if spec.quantity == 1 else f"{spec.id}{q + 1}"
                yield idx, name, spec
                idx += 1


_BOOL_TRUE = {"1", "true", "yes", "on"}


def _get_time(attrs, key, default=0):
    if key in attrs:
        return parse_time(attrs[key], default_unit="s")
    return default


def _kib_to_bytes(v) -> int:
    return int(v) * 1024


def load_xml(source: str) -> Scenario:
    """Parse a shadow.config.xml string or file path into a Scenario."""
    src_path = None
    if os.path.exists(source):
        src_path = source
        with open(source) as f:
            text = f.read()
    else:
        text = source
    root = ElementTree.fromstring(text)
    if root.tag != "shadow":
        raise ValueError(f"expected <shadow> root element, got <{root.tag}>")

    scen = Scenario(stop_time=_get_time(root.attrib, "stoptime"),
                    source_path=src_path)
    scen.bootstrap_end = _get_time(root.attrib, "bootstraptime")

    for el in root:
        if el.tag == "topology":
            if "path" in el.attrib:
                scen.topology_path = el.attrib["path"]
            elif el.text and el.text.strip():
                scen.topology_graphml = el.text
        elif el.tag == "plugin":
            scen.plugins.append(
                PluginSpec(id=el.attrib["id"], path=el.attrib.get("path", "")))
        elif el.tag == "fault":
            a = el.attrib
            if "kind" not in a or "at" not in a:
                raise ValueError("<fault> requires kind= and at= attributes")
            scen.faults.append(FaultSpec(
                kind=a["kind"],
                at=parse_time(a["at"], default_unit="s"),
                host=a.get("host"),
                src=a.get("src"),
                dst=a.get("dst"),
                until=(parse_time(a["until"], default_unit="s")
                       if "until" in a else None),
                rate=float(a.get("rate", 0.0)),
                extra_ns=(parse_time(a["extra"], default_unit="ms")
                          if "extra" in a else 0),
            ))
        elif el.tag == "host" or el.tag == "node":
            a = el.attrib
            host = HostSpec(
                id=a["id"],
                quantity=int(a.get("quantity", 1) or 1),
                ip_hint=a.get("iphint"),
                geocode_hint=a.get("geocodehint"),
                type_hint=a.get("typehint"),
                bandwidth_down=_kib_to_bytes(a["bandwidthdown"]) if "bandwidthdown" in a else None,
                bandwidth_up=_kib_to_bytes(a["bandwidthup"]) if "bandwidthup" in a else None,
                cpu_frequency=int(a["cpufrequency"]) if "cpufrequency" in a else None,
                log_level=a.get("loglevel"),
                pcap=a.get("logpcap", "").lower() in _BOOL_TRUE,
                pcap_dir=a.get("pcapdir"),
                socket_recv_buffer=int(a["socketrecvbuffer"]) if "socketrecvbuffer" in a else None,
                socket_send_buffer=int(a["socketsendbuffer"]) if "socketsendbuffer" in a else None,
                interface_buffer=int(a["interfacebuffer"]) if "interfacebuffer" in a else None,
            )
            host.autotune_recv_buffer = host.socket_recv_buffer is None
            host.autotune_send_buffer = host.socket_send_buffer is None
            for pel in el:
                if pel.tag in ("process", "application"):
                    pa = pel.attrib
                    host.processes.append(ProcessSpec(
                        plugin=pa["plugin"],
                        start_time=_get_time(pa, "starttime"),
                        stop_time=_get_time(pa, "stoptime"),
                        arguments=pa.get("arguments", ""),
                    ))
            scen.hosts.append(host)
    if scen.stop_time <= 0:
        raise ValueError("scenario requires a positive stoptime")
    return scen
