"""Deterministic seed tree.

The reference derives all randomness from one CLI seed through a chain of
seeded rand_r generators (master -> slave -> scheduler/host,
/root/reference/src/main/utility/shd-random.c plus shd-master.c:80,
shd-slave.c:153, shd-host.c:272). We keep the same *shape* — one root
seed deterministically fanning out to every consumer — but use JAX's
counter-based threefry keys so randomness is order-independent and
reproducible under any parallel schedule:

    root = seed
    host_key(h)         = fold_in(fold_in(root, DOMAIN_HOST), h)
    per-use key         = fold_in(host_key, monotonic per-host counter)
    packet drop key     = fold_in(fold_in(root, DOMAIN_DROP), packet uid)

Everything that consumes randomness on-device uses these helpers, so two
runs with the same seed produce bit-identical simulations regardless of
sharding — a stronger guarantee than the reference, whose determinism
holds only for a fixed worker count.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Domain separators for the fold_in tree.
DOMAIN_HOST = 1
DOMAIN_DROP = 2
DOMAIN_APP = 3
DOMAIN_TOPOLOGY = 4
DOMAIN_JITTER = 5
DOMAIN_PORT = 6


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def domain_key(root: jax.Array, domain: int) -> jax.Array:
    return jax.random.fold_in(root, domain)


def host_key(root: jax.Array, host_id) -> jax.Array:
    """Per-host key; host_id may be a traced int32."""
    return jax.random.fold_in(domain_key(root, DOMAIN_HOST), host_id)


def counter_key(base: jax.Array, counter) -> jax.Array:
    """Derive a fresh single-use key from a monotonic counter."""
    return jax.random.fold_in(base, counter)


def uniform_from(key: jax.Array) -> jax.Array:
    """One uniform float32 in [0, 1)."""
    return jax.random.uniform(key)


def drop_decision(root: jax.Array, src_host, packet_uid, reliability) -> jax.Array:
    """Bernoulli drop matching worker_sendPacket's reliability test
    (/root/reference/src/main/core/shd-worker.c:238-244): the packet is
    DELIVERED iff uniform() <= reliability. Keyed by the globally unique
    (src_host, per-source packet counter) pair stamped at NIC emit —
    engine.window.exchange uses the identical key derivation."""
    k = counter_key(counter_key(domain_key(root, DOMAIN_DROP), src_host),
                    packet_uid)
    return jax.random.uniform(k) > reliability  # True = drop


def bounded_int(key: jax.Array, lo: int, hi):
    """Uniform integer in [lo, hi) — used for ephemeral port picks and
    app-level random choices."""
    return jax.random.randint(key, (), lo, hi, dtype=jnp.int32)
