"""Deterministic seed tree.

The reference derives all randomness from one CLI seed through a chain of
seeded rand_r generators (master -> slave -> scheduler/host,
/root/reference/src/main/utility/shd-random.c plus shd-master.c:80,
shd-slave.c:153, shd-host.c:272). We keep the same *shape* — one root
seed deterministically fanning out to every consumer — but use JAX's
counter-based threefry keys so randomness is order-independent and
reproducible under any parallel schedule:

    root = seed
    host_key(h)         = fold_in(fold_in(root, DOMAIN_HOST), h)
    per-use key         = fold_in(host_key, monotonic per-host counter)
    packet drop key     = fold_in(fold_in(root, DOMAIN_DROP), packet uid)

Everything that consumes randomness on-device uses these helpers, so two
runs with the same seed produce bit-identical simulations regardless of
sharding — a stronger guarantee than the reference, whose determinism
holds only for a fixed worker count.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Domain separators for the fold_in tree.
DOMAIN_HOST = 1
DOMAIN_DROP = 2
DOMAIN_APP = 3
DOMAIN_TOPOLOGY = 4
DOMAIN_JITTER = 5
DOMAIN_PORT = 6


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def domain_key(root: jax.Array, domain: int) -> jax.Array:
    return jax.random.fold_in(root, domain)


def host_key(root: jax.Array, host_id) -> jax.Array:
    """Per-host key; host_id may be a traced int32."""
    return jax.random.fold_in(domain_key(root, DOMAIN_HOST), host_id)


def counter_key(base: jax.Array, counter) -> jax.Array:
    """Derive a fresh single-use key from a monotonic counter."""
    return jax.random.fold_in(base, counter)


def uniform_from(key: jax.Array) -> jax.Array:
    """One uniform float32 in [0, 1)."""
    return jax.random.uniform(key)


def drop_decision(root: jax.Array, src_host, packet_uid, reliability) -> jax.Array:
    """Bernoulli drop matching worker_sendPacket's reliability test
    (/root/reference/src/main/core/shd-worker.c:238-244): the packet is
    DELIVERED iff uniform() <= reliability. Keyed by the globally unique
    (src_host, per-source packet counter) pair stamped at NIC emit —
    engine.window.exchange uses the identical key derivation."""
    k = counter_key(counter_key(domain_key(root, DOMAIN_DROP), src_host),
                    packet_uid)
    return jax.random.uniform(k) > reliability  # True = drop


def bounded_int(key: jax.Array, lo: int, hi):
    """Uniform integer in [lo, hi) — used for ephemeral port picks and
    app-level random choices."""
    return jax.random.randint(key, (), lo, hi, dtype=jnp.int32)


# --- Cheap counter PRNG for the per-event hot path --------------------------
#
# Profiling showed threefry dominating the window program: every
# jax.random fold_in/uniform chain is multiple 20-round threefry
# passes, executed for ALL hosts on EVERY lockstep iteration (masked
# vmap). Simulation randomness needs determinism and decent statistics,
# not cryptographic strength — the reference itself uses rand_r
# (shd-random.c). This is a splitmix/murmur3-style avalanche over a
# (stream, counter) pair: ~8 native u32 ALU ops total.
#
# Same tree shape as the threefry path: stream = f(seed, domain, id),
# value = mix(stream, counter). Mirrored exactly (numpy uint32) by
# engine.pyengine for the differential tests.

_GOLDEN = 0x9E3779B9


def _mix32(x):
    """murmur3 finalizer (u32 avalanche)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def stream_of(seed, domain, ident):
    """u32 stream id for (seed, domain, per-entity id)."""
    s = (jnp.uint32(seed) * jnp.uint32(_GOLDEN)
         ^ jnp.uint32(domain) * jnp.uint32(0x85EBCA6B)
         ^ jnp.asarray(ident).astype(jnp.uint32) * jnp.uint32(0xC2B2AE35))
    return _mix32(s)


def cheap_bits(stream, counter):
    """u32 random bits for (stream, counter)."""
    return _mix32(jnp.asarray(stream).astype(jnp.uint32) ^
                  (jnp.asarray(counter).astype(jnp.uint32) +
                   jnp.uint32(_GOLDEN)))


def cheap_uniform(stream, counter):
    """f32 uniform in [0, 1) from 24 high bits."""
    return (cheap_bits(stream, counter) >> jnp.uint32(8)).astype(
        jnp.float32) * jnp.float32(1.0 / (1 << 24))
