"""Fusable row-slot access: one-hot selects instead of scatter/gather.

Under ``vmap``, ``arr.at[slot].set(v)`` and ``arr[slot]`` (per-row
dynamic index) lower to XLA scatter/gather ops, which cannot fuse with
neighboring elementwise work on TPU — profiling showed the window
program shattered into ~2000 ~10us kernels per lockstep iteration,
making kernel overhead (not math) the entire cost of the engine.

These helpers express the same operations as masked elementwise ops
over the (small, static) slot dimension: they do W x more ALU work and
zero extra kernels — everything fuses into the surrounding computation.
Exact: the mask selects exactly one slot, so masked-sum gathers are
bit-identical to indexing for every dtype used here (ints, bool, f32
values stored per slot).

All functions operate on one host's row slices (shapes [N] or
[N, W]) with a scalar ``idx``; use under vmap.
"""

from __future__ import annotations

import jax.numpy as jnp


def mask_of(arr, idx):
    """[N] bool one-hot (False everywhere if idx out of range)."""
    return jnp.arange(arr.shape[0]) == idx


def rget(arr, idx):
    """arr[idx] for scalar idx without a gather. Works for [N] and
    [N, W] arrays; out-of-range idx returns zeros."""
    m = mask_of(arr, idx)
    if arr.ndim == 1:
        if arr.dtype == jnp.bool_:
            return jnp.any(m & arr)
        return jnp.sum(jnp.where(m, arr, 0), dtype=arr.dtype)
    return jnp.sum(jnp.where(m[:, None], arr, 0), axis=0, dtype=arr.dtype)


def rset(arr, idx, val):
    """arr.at[idx].set(val) without a scatter ([N] or [N, W])."""
    m = mask_of(arr, idx)
    if arr.ndim == 1:
        return jnp.where(m, jnp.asarray(val, arr.dtype), arr)
    return jnp.where(m[:, None], jnp.asarray(val, arr.dtype), arr)


def radd(arr, idx, val):
    """arr.at[idx].add(val) without a scatter ([N] only)."""
    m = mask_of(arr, idx)
    return arr + jnp.where(m, jnp.asarray(val, arr.dtype), 0)


def rset_where(arr, idx, cond, val):
    """arr.at[idx].set(where(cond, val, arr[idx])) — conditional slot
    write with no gather/scatter."""
    m = mask_of(arr, idx) & cond
    if arr.ndim == 1:
        return jnp.where(m, jnp.asarray(val, arr.dtype), arr)
    return jnp.where(m[:, None], jnp.asarray(val, arr.dtype), arr)
