"""Simulation time: int64 nanoseconds since simulation start.

Mirrors the reference's SimulationTime (guint64 ns counter,
/root/reference/src/main/core/support/shd-definitions.h:13) and
EmulatedTime (offset from Jan 1 2000, shd-definitions.h:73), redesigned
as plain int64 constants usable inside jitted JAX code.
"""

from __future__ import annotations

import re

# One nanosecond is the base unit.
SIMTIME_ONE_NANOSECOND = 1
SIMTIME_ONE_MICROSECOND = 1_000
SIMTIME_ONE_MILLISECOND = 1_000_000
SIMTIME_ONE_SECOND = 1_000_000_000
SIMTIME_ONE_MINUTE = 60 * SIMTIME_ONE_SECOND
SIMTIME_ONE_HOUR = 60 * SIMTIME_ONE_MINUTE

# Sentinel for "no event" / "never": int64 max. The reference uses
# SIMTIME_INVALID/SIMTIME_MAX (shd-definitions.h:24-40).
SIMTIME_MAX = (1 << 63) - 1
SIMTIME_INVALID = SIMTIME_MAX

# Offset of simulation time 0 from the emulated Unix epoch clock
# (Jan 1 2000 00:00:00 UTC, matching shd-definitions.h:73's
# EMULATED_TIME_OFFSET so apps see a plausible wall clock).
EMULATED_TIME_OFFSET = 946_684_800 * SIMTIME_ONE_SECOND

_TIME_UNITS = {
    "ns": SIMTIME_ONE_NANOSECOND,
    "nanosecond": SIMTIME_ONE_NANOSECOND,
    "us": SIMTIME_ONE_MICROSECOND,
    "microsecond": SIMTIME_ONE_MICROSECOND,
    "ms": SIMTIME_ONE_MILLISECOND,
    "millisecond": SIMTIME_ONE_MILLISECOND,
    "s": SIMTIME_ONE_SECOND,
    "sec": SIMTIME_ONE_SECOND,
    "second": SIMTIME_ONE_SECOND,
    "m": SIMTIME_ONE_MINUTE,
    "min": SIMTIME_ONE_MINUTE,
    "minute": SIMTIME_ONE_MINUTE,
    "h": SIMTIME_ONE_HOUR,
    "hour": SIMTIME_ONE_HOUR,
}


def parse_time(value, default_unit: str = "s") -> int:
    """Parse a time value into int64 nanoseconds.

    Accepts ints/floats (interpreted in ``default_unit``, seconds by
    default — matching the reference's XML stoptime/starttime semantics)
    or strings like "10 ms", "1.5s", "250us".
    """
    if isinstance(value, (int, float)):
        return int(round(value * _TIME_UNITS[default_unit]))
    text = str(value).strip().lower()
    m = re.fullmatch(r"([0-9]*\.?[0-9]+)\s*([a-z]*)", text)
    if not m:
        raise ValueError(f"unparseable time value: {value!r}")
    num = float(m.group(1))
    unit = m.group(2) or default_unit
    # strip trailing plural
    if unit.endswith("s") and unit not in _TIME_UNITS:
        unit = unit[:-1]
    if unit not in _TIME_UNITS:
        raise ValueError(f"unknown time unit in {value!r}")
    return int(round(num * _TIME_UNITS[unit]))


def format_time(ns: int) -> str:
    """Human-readable rendering for logs: h:mm:ss.nnnnnnnnn."""
    ns = int(ns)
    if ns >= SIMTIME_MAX:
        return "never"
    secs, frac = divmod(ns, SIMTIME_ONE_SECOND)
    h, rem = divmod(secs, 3600)
    mm, ss = divmod(rem, 60)
    return f"{h:02d}:{mm:02d}:{ss:02d}.{frac:09d}"
