"""Protocol and buffer constants.

Values deliberately match the reference's CONFIG_* constants
(/root/reference/src/main/core/support/shd-definitions.h:150-230) so that
differential tests against Shadow-like behavior line up, but they are
plain Python ints consumed by JAX kernels as static values.
"""

from .simtime import SIMTIME_ONE_MILLISECOND, SIMTIME_ONE_SECOND

# --- Link layer / packet sizes ---
MTU = 1500
HEADER_SIZE_UDPIPETH = 42   # Ethernet + IP + UDP header bytes
HEADER_SIZE_TCPIPETH = 66   # Ethernet + IP + TCP header bytes (with options)
TCP_MSS = MTU - HEADER_SIZE_TCPIPETH    # 1434 payload bytes per full segment
UDP_MAX_PAYLOAD = MTU - HEADER_SIZE_UDPIPETH
DATAGRAM_MAX_SIZE = 65507

# --- Socket buffers (bytes) ---
SEND_BUFFER_SIZE = 131072
RECV_BUFFER_SIZE = 174760
SEND_BUFFER_MIN_SIZE = 16384
RECV_BUFFER_MIN_SIZE = 87380
TCP_WMEM_MAX = 4194304
TCP_RMEM_MAX = 6291456
PIPE_BUFFER_SIZE = 65536

# --- TCP timers (reference values are in milliseconds) ---
TCP_RTO_INIT = 1000 * SIMTIME_ONE_MILLISECOND
TCP_RTO_MIN = 200 * SIMTIME_ONE_MILLISECOND
TCP_RTO_MAX = 1_200_000 * SIMTIME_ONE_MILLISECOND
TCP_CLOSE_TIMER_DELAY = 60 * SIMTIME_ONE_SECOND

# --- NIC model ---
# Received packets are drained from the NIC in batches covering this much
# simulated time (reference CONFIG_RECEIVE_BATCH_TIME, shd-definitions.h:201).
RECEIVE_BATCH_TIME = 10 * SIMTIME_ONE_MILLISECOND
# Default NIC buffer size in bytes (reference --interface-buffer option
# default, shd-options.c).
INTERFACE_BUFFER_SIZE = 1024000

# --- Port allocation (reference shd-definitions.h MIN_RANDOM_PORT) ---
MIN_RANDOM_PORT = 10000
MAX_PORT = 65535

# Default window for the conservative lookahead barrier when the topology
# provides no minimum latency (reference shd-master.c:123 falls back to 10ms).
DEFAULT_MIN_TIME_JUMP = 10 * SIMTIME_ONE_MILLISECOND
