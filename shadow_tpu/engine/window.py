"""The conservative-lookahead window loop.

This is the TPU redesign of the reference's round machinery: the
master/slave round loop (shd-slave.c:397-449, shd-master.c:410-440),
the scheduler barriers (shd-scheduler.c:602-635), the worker event loop
(shd-worker.c:123-190) and cross-host packet delivery
(shd-worker.c:216-271) — collapsed into three pure array programs:

1. `step_all_hosts`: every host pops and executes its earliest event if
   it falls inside the window — one lockstep iteration of the inner
   `lax.while_loop`, which runs until no host has a ready event. This
   replaces N worker threads walking per-host priority queues.
2. `exchange`: all packets emitted into per-host outboxes this window
   are routed (two [V,V] table gathers), loss-rolled (counter-based
   PRNG), grouped by destination and scattered into destination event
   queues. Cross-host arrivals always land at or after the window end
   because path latency >= the lookahead bound — the same causality
   argument as the reference's bump-to-barrier rule
   (shd-scheduler-policy-host-single.c:171-175).
3. `advance`: the global min next-event time (a jnp.min today, a
   lax.pmin over the mesh when sharded) opens the next window
   [t_min, t_min + min_jump) — exactly master_slaveFinishedCurrentRound.

`run_windows` stitches these into a device-resident multi-window loop so
one jit call executes many windows without host round-trips.
"""

from __future__ import annotations

from functools import partial

import chex
import jax
import jax.numpy as jnp

from ..core import rng as R
from ..core.rowops import radd, rget, rset
from ..core.simtime import SIMTIME_MAX
from ..net import nic
from ..net import packet as P
from ..net.socket import sock_demux
from ..net.tcp import on_tcp_timer, on_tcp_close, tcp_rx
from ..net.udp import udp_deliver
from ..apps.base import dispatch as app_dispatch
from . import equeue
from .defs import (EV_NULL, EV_APP, EV_PKT, EV_NIC_TX, EV_TCP_TIMER,
                   EV_TCP_CLOSE, ST_EVENTS, ST_PKTS_RECV, ST_PKTS_DROP_NET,
                   ST_PKTS_DROP_Q, ST_DEFER_FANIN)
from .state import (NARROW_ABS, NARROW_REL, EngineConfig, hot_fields,
                    narrow_state, row_proto, widen_state)


# --- Event handlers (row-level) -------------------------------------------
# Signature: handler(row, hp, sh, now, wend, pkt). `wend` is the current
# window bound so the NIC can defer work past the next exchange when its
# per-window emit budget is spent (overflow-to-next-window, never drop).

def _on_null(row, hp, sh, now, wend, pkt):
    return row


def _scoped(label, fn):
    """Stamp a handler with its stateflow entry name
    (lint/stateflow.py ENTRIES) via jax.named_scope, so the passcope
    observatory (obs/passcope.py) can attribute decoded HLO self-times
    back to the pass. Trace-time naming only — the compiled math, the
    shapes and the digest chain are untouched."""
    def h(*args):
        with jax.named_scope(label):
            return fn(*args)
    return h


def _make_handlers(cfg: EngineConfig):
    """Build the event-kind switch for this scenario. Static pruning:
    app kinds not present and (when uses_tcp is False) the whole TCP
    machine compile to nothing."""

    def _on_app(row, hp, sh, now, wend, pkt):
        # Multi-process routing (reference: process list per host,
        # shd-configuration.h:36-95): a wake belongs to the process
        # that owns its socket (sk_proc), or to the process stamped in
        # the SRC word for slotless timer/start wakes. The app then
        # runs against a single-process VIEW of the [P]-shaped app
        # state, so app code is process-count agnostic.
        PP = row.app_node.shape[0]
        if PP == 1:
            vrow = row.replace(app_node=row.app_node[0],
                               app_r=row.app_r[0])
            vhp = hp.replace(app_kind=hp.app_kind[0],
                             app_cfg=hp.app_cfg[0])
            vrow = app_dispatch(vrow, vhp, sh, now, pkt,
                                app_kinds=cfg.app_kinds)
            return vrow.replace(app_node=row.app_node.at[0].set(
                                    vrow.app_node),
                                app_r=row.app_r.at[0].set(vrow.app_r))
        slot = pkt[P.SEQ]
        proc = jnp.clip(jnp.where(slot >= 0, rget(row.sk_proc, slot),
                                  pkt[P.SRC]), 0, PP - 1)
        vrow = row.replace(app_node=rget(row.app_node, proc),
                           app_r=rget(row.app_r, proc),
                           app_proc=proc.astype(jnp.int32))
        vhp = hp.replace(app_kind=rget(hp.app_kind, proc),
                         app_cfg=rget(hp.app_cfg, proc))
        vrow = app_dispatch(vrow, vhp, sh, now, pkt,
                            app_kinds=cfg.app_kinds)
        return vrow.replace(
            app_node=rset(row.app_node, proc, vrow.app_node),
            app_r=rset(row.app_r, proc, vrow.app_r),
            app_proc=jnp.int32(0))

    def _on_pkt(row, hp, sh, now, wend, pkt):
        """Packet arrival at the NIC: admission, demux, protocol
        dispatch."""
        with jax.named_scope("nic.rx_admit"):
            row, keep = nic.rx_admit(row, hp, now, pkt)

        def deliver(r):
            r = r.replace(stats=r.stats.at[ST_PKTS_RECV].add(1))
            # delivery-status trail: admitted by the input buffer
            pkt_in = pkt.at[P.STATUS].set(pkt[P.STATUS] |
                                          P.DS_RX_BUFFERED)
            proto = pkt[P.FLAGS] & P.PROTO_MASK

            def tcp_path(rr):
                with jax.named_scope("tcp.rx"):
                    slot = sock_demux(rr, pkt[P.SRC], pkt[P.SPORT],
                                      pkt[P.DPORT], P.PROTO_TCP)
                    return jax.lax.cond(
                        slot >= 0,
                        lambda r2: tcp_rx(r2, hp, sh, now, slot, pkt_in),
                        lambda r2: r2, rr)

            def udp_path(rr):
                with jax.named_scope("udp.deliver"):
                    slot = sock_demux(rr, pkt[P.SRC], pkt[P.SPORT],
                                      pkt[P.DPORT], P.PROTO_UDP)
                    return jax.lax.cond(
                        slot >= 0,
                        lambda r2: udp_deliver(r2, hp, sh, now, slot,
                                               pkt_in),
                        lambda r2: r2, rr)

            if not cfg.uses_tcp:
                return udp_path(r)
            return jax.lax.cond(proto == P.PROTO_TCP, tcp_path, udp_path, r)

        return jax.lax.cond(keep, deliver, lambda r: r, row)

    def _on_tx(row, hp, sh, now, wend, pkt):
        with jax.named_scope("nic.tx"):
            return nic.on_tx(row, hp, sh, now, wend, pkt,
                             qdisc=cfg.qdisc)

    if cfg.uses_tcp:
        return [_on_null, _on_app, _on_pkt, _on_tx,
                _scoped("tcp.timer", on_tcp_timer), on_tcp_close]
    return [_on_null, _on_app, _on_pkt, _on_tx, _on_null, _on_null]


def step_one_host(row, hp, sh, wend, cfg: EngineConfig):
    """Pop and execute this host's earliest event if inside the window."""
    # At-rest narrow layout (state.NARROW_SPEC, cfg.wide_state == 0):
    # this is the drain's single codec insertion point — the row is
    # decoded to the canonical wide compute form here, every handler
    # below sees exactly the pre-shrink dtypes, and the single return
    # path re-encodes. `was_narrow` is a Python bool from static
    # dtypes, so a wide-state run compiles zero conversion code.
    row, was_narrow = widen_state(row)
    slot, t = equeue.q_min(row)
    ready = t < wend
    kind = jnp.where(ready, rget(row.eq_kind, slot), EV_NULL)
    pkt = rget(row.eq_pkt, slot)

    if cfg.cpu_model:
        # Reference CPU model (shd-cpu.c:55-107 + the blocked-I/O
        # check in event_execute, shd-event.c:52-81): when the CPU's
        # built-up delay exceeds the threshold, the event is pushed
        # forward to when the CPU drains instead of executing now.
        blocked = (ready & (hp.cpu_threshold >= 0) &
                   (row.cpu_avail - t > hp.cpu_threshold))
        retry_at = jnp.maximum(row.cpu_avail, t + 1)
        row = jax.lax.cond(
            blocked,
            lambda r: equeue.q_push(equeue.q_clear_slot(r, slot),
                                    retry_at, kind, pkt),
            lambda r: r, row)
        ready = ready & ~blocked
        kind = jnp.where(blocked, EV_NULL, kind)

    row = jax.lax.cond(ready, lambda r: equeue.q_clear_slot(r, slot),
                       lambda r: r, row)
    row = jax.lax.switch(kind, _make_handlers(cfg), row, hp, sh, t, wend, pkt)

    # Chain a due-now NIC-TX into the same lockstep pass: an app send
    # kicks an EV_NIC_TX at the current time when the NIC is idle, and
    # waiting a whole all-hosts pass to serve it doubles the pass count
    # of every send-heavy window. Executing the queue head early is
    # semantically identity (it would be the first pop of the next
    # pass) and the Python differential engine drains per-host queues
    # in exactly this order, so stats stay bit-identical. Disabled
    # under the CPU model: there every pop re-checks the blocked-CPU
    # threshold, which the chain would bypass.
    due = jnp.zeros((), jnp.bool_)
    if not cfg.cpu_model:
        slot2, t2 = equeue.q_min(row)
        due = ready & (t2 == t) & (rget(row.eq_kind, slot2) == EV_NIC_TX)
        with jax.named_scope("nic.tx"):
            row = jax.lax.cond(
                due,
                lambda r: nic.on_tx(equeue.q_clear_slot(r, slot2), hp,
                                    sh, t, wend, pkt, qdisc=cfg.qdisc),
                lambda r: r, row)

    if cfg.cpu_model:
        # charge this event's modeled CPU cost to the busy horizon
        row = row.replace(cpu_avail=jnp.where(
            ready,
            jnp.maximum(row.cpu_avail, t) + hp.cpu_cost,
            row.cpu_avail))

    row = row.replace(
        stats=radd(row.stats, ST_EVENTS,
                   jnp.where(ready, 1, 0) + jnp.where(due, 1, 0)))
    return narrow_state(row) if was_narrow else row


def step_all_hosts(hosts, hp, sh, wend, cfg: EngineConfig):
    # cfg is Python-static; close over it (vmap axes cover arrays only)
    def f(row, hprow):
        return step_one_host(row, hprow, sh, wend, cfg)

    return jax.vmap(f)(hosts, hp)


# --- Hot/cold state split (engine.state HOT_FIELDS / COLD_WHEN) -----------
#
# The drain below never moves the full Hosts pytree: drain_window
# splits it ONCE into the config's hot working set (a dict of hot
# columns) and leaves everything else untouched at full width, then
# rejoins at the window boundary. All gathers, scatters and while-loop
# carries inside operate on the hot dict only — previously every
# window-rung gather and every per-pass sub-compaction hauled all 81
# columns (cold SACK bookkeeping, trace rings, stats sampling
# included) through HBM once per pass. The vmapped row is rebuilt
# around the static row prototype (row_proto): cold columns ride as
# their config-invariant defaults and are dropped on return, so XLA
# dead-code-eliminates them from the compiled pass.

def _split_hosts(hosts, names):
    """Hosts -> {field: array} for the hot working set."""
    return {f: getattr(hosts, f) for f in names}


def _join_hosts(hosts, hot, names):
    """Rejoin the drained hot columns into the full pytree (cold
    columns pass through untouched — byte-identical by contract)."""
    return hosts.replace(**{f: hot[f] for f in names})


def _step_hot(hot, proto, hp, sh, wend, cfg: EngineConfig, names):
    """step_all_hosts over the hot working set only."""
    def f(hrow, hprow):
        row = proto.replace(**{f2: hrow[f2] for f2 in names})
        row = step_one_host(row, hprow, sh, wend, cfg)
        return {f2: getattr(row, f2) for f2 in names}

    return jax.vmap(f)(hot, hp)


def ladder_of(cfg: EngineConfig, H: int = None):
    """Active-set compaction rung sizes for this config (ascending),
    WITHOUT the implicit dense fallback rung.

    - active_block > 0: one explicit rung (the round-3 hand-tuned
      knob, kept for A/B tests and overrides).
    - active_block == 0: compaction off — always dense (the round-3
      default, kept so dense-vs-sparse equality tests stay meaningful).
    - active_block == -1 (default): AUTO — a small ladder of rungs
      sized to the host count; each pass picks the smallest rung that
      fits its ready count, so the hand-tuned per-config constant the
      round-3 verdict flagged is gone (the reference's host-steal load
      balancing needed no tuning either,
      shd-scheduler-policy-host-steal.c:266-299). Rungs must satisfy
      4*K <= H: gathering more than a quarter of the rows costs close
      to a dense pass (round-3 block-size sweep, git 9b878c3).
    """
    if H is None:
        H = cfg.num_hosts
    if cfg.active_block > 0:
        return [min(cfg.active_block, H)]
    if cfg.active_block == 0:
        return []
    return [k for k in (32, 512) if 4 * k <= H]


def sparse_batch(cfg: EngineConfig) -> int:
    """Events executed per gathered host per sparse pass (the inner
    bounded drain). 1 under the CPU model (every pop must re-check the
    blocked-CPU threshold against the busy horizon accumulated by the
    PREVIOUS pop — batching would reorder those checks) and with
    hosted apps (the wake-ring pause margin in run_windows assumes at
    most ~1 wake per host per pass)."""
    if cfg.cpu_model or cfg.hostedcap > 1:
        return 1
    return cfg.event_batch


def window_ladder(cfg: EngineConfig, H: int = None):
    """Window-level active-set rung sizes (ascending), without the
    dense fallback. The set of hosts that can execute ANY event inside
    a window is fixed at window open: hosts interact only at window
    boundaries, and a host's own handlers can only schedule events for
    itself (loopback included), so a host whose earliest event lies at
    or past wend stays idle for the WHOLE window. That makes a single
    gather-at-window-open / scatter-at-window-close exact — the inner
    drain loop then runs every pass on [K] rows with no per-pass
    gather, scatter, or full-state switch carry (measured ~37 ms of
    every socks10k window, tools/xplane_profile.py round 4).

    Disabled (empty) with hosted apps: the mid-window wake-ring pause
    check needs the full host set.
    """
    if H is None:
        H = cfg.num_hosts
    if cfg.hostedcap > 1 or cfg.active_block == 0:
        return []
    if cfg.active_block > 0:
        return [min(cfg.active_block, H)]
    # ONE auto rung: the largest candidate with 4K <= H — the same
    # quarter rule as the per-pass ladder (ladder_of): gathering more
    # than a quarter of the rows costs close to a dense pass. The
    # round-4..8 rule here was the looser 2K <= H, which at H=4096
    # picked a [2048] rung — HALF the state gathered per window — and
    # is the measured phold-4096 regression suspect: the round-9
    # paired A/B (tools/perf_ab.py, BASELINE.md round-9 table;
    # platform cpu) has active_block=512 beating the 2048-rung AUTO
    # 1.21-1.25x in EVERY paired rep at identical pass counts, so the
    # quarter rule now picks 512 there (same pass mix as the winning
    # variant). At the at-scale shapes nothing changes: H >= 8192
    # still selects the 2048 rung socks10k/tor50k were measured with.
    # Only one rung either way: a window rung pays its gather once
    # for the whole window and the inner drain re-compacts per pass
    # (drain_window), so finer window rungs buy almost nothing —
    # while every extra rung compiles another full copy of the
    # event-handler machine (measured: the 3-rung nested build took
    # ~29 min of XLA compile; program size, not run time, is the
    # binding cost of extra rungs)
    for k in (2048, 512):
        if 4 * k <= H:
            return [k]
    return []


def drain_window(hosts, hp, sh, wend, cfg: EngineConfig, pc):
    """Execute every event below `wend` (one whole window's pass
    loop), window-level active-set compaction applied when the active
    count fits a rung. Returns (hosts, pc) with pass counters
    accumulated per rung (window rungs first, then the per-pass rungs
    of the dense fallback, then dense — see pass_labels).

    Hot/cold split (state.HOT_FIELDS/COLD_WHEN): the full pytree is
    split here ONCE per window; everything inside — the rung gathers,
    per-pass sub-compaction and both while-loop carries — moves the
    hot working set only, and the cold columns rejoin untouched at
    the return. cfg.hot_split=0 restores the full-tree carry."""
    names = hot_fields(cfg)
    proto = row_proto(cfg)
    hot = _split_hosts(hosts, names)
    hot, pc = _drain_hot(hot, proto, hp, sh, wend, cfg, pc, names)
    return _join_hosts(hosts, hot, names), pc


def _drain_hot(hot, proto, hp, sh, wend, cfg: EngineConfig, pc, names):
    H = hot["eq_ctr"].shape[0]
    wks = window_ladder(cfg, H)
    nw = len(wks)

    def fallback(h, pc2):
        # full-set drain. With a window rung present this branch only
        # runs population-wave windows (most hosts active), where the
        # dense step is the right tool anyway — so it compiles the
        # plain dense loop, not another rung-ladder copy of the
        # handler machine. Without window rungs (small/mid H, hosted
        # apps, explicit active_block) it IS the engine, and the
        # per-pass ladder applies as before (_pass_hot handles the
        # ladderless active_block=0 case as plain dense).
        use_ladder = not wks

        def ev_cond(carry2):
            h2, _ = carry2
            go = jnp.min(h2["eq_next"]) < wend
            if cfg.hostedcap > 1:
                # pause before a hosted wake ring can overflow so the
                # CPU tier drains mid-window (the window re-opens on
                # the next call). The threshold floor keeps tiny
                # manual hostedcap values from wedging the loop.
                # (hw_* are pinned hot whenever hostedcap > 1 —
                # COLD_WHEN "no_hosted".)
                cap = h2["hw_time"].shape[1]
                go = go & (jnp.max(h2["hw_cnt"]) < max(cap - 4, 1))
            return go

        def ev_body(carry2):
            h2, pc3 = carry2
            if use_ladder:
                h2, rung = _pass_hot(h2, proto, hp, sh, wend, cfg,
                                     names)
            else:
                with jax.named_scope("dense"):
                    h2 = _step_hot(h2, proto, hp, sh, wend, cfg,
                                   names)
                rung = len(ladder_of(cfg, H))  # the dense slot
            return h2, pc3.at[nw + rung].add(1)

        return jax.lax.while_loop(ev_cond, ev_body, (h, pc2))

    if not wks:
        return fallback(hot, pc)

    active = hot["eq_next"] < wend                    # [H]
    nact = jnp.sum(active, dtype=jnp.int32)

    def make_win(K, slot):
        def f(h, pc2):
            rank = jnp.cumsum(active) - 1
            take = active & (rank < K)
            tgt = jnp.where(take, rank, K).astype(jnp.int32)
            hid = jnp.arange(H, dtype=jnp.int32)
            dummy = jnp.argmin(active).astype(jnp.int32)
            idx = jnp.full((K,), dummy, jnp.int32).at[tgt].set(
                hid, mode="drop")
            sub = {f2: h[f2][idx] for f2 in names}
            shp = jax.tree.map(lambda a: a[idx], hp)

            def c(carry2):
                s, _ = carry2
                return jnp.min(s["eq_next"]) < wend

            def b(carry2):
                # per-pass sub-compaction INSIDE the gathered set:
                # early passes run dense over [K], but once the easy
                # hosts drain, the remaining passes (the busiest
                # host's long tail) gather [32]-row subsets of the
                # sub — without this, every tail pass pays the full
                # [K]-row switch (measured: a flat [2048]-wide drain
                # was SLOWER than the per-pass ladder it replaced)
                s, n = carry2
                s, _rung = _pass_hot(s, proto, shp, sh, wend, cfg,
                                     names)
                return s, n + 1

            sub, n = jax.lax.while_loop(c, b, (sub, jnp.int64(0)))
            h = {f2: h[f2].at[idx].set(sub[f2]) for f2 in names}
            return h, pc2.at[slot].add(n)
        return _scoped(f"w{K}", f)

    branches = [make_win(K, i) for i, K in enumerate(wks)] + [fallback]
    rung = jnp.searchsorted(jnp.asarray(wks, jnp.int32), nact,
                            side="left").astype(jnp.int32)
    # arrival-only windows (every queue event at/past wend; the window
    # opened on a carried ob_next arrival) execute nothing — route
    # them to the fallback, whose loop exits without the K-row
    # gather/scatter a window rung would pay for zero passes
    rung = jnp.where(nact == 0, jnp.int32(len(wks)), rung)
    return jax.lax.switch(rung, branches, hot, pc)


def pass_labels(cfg: EngineConfig, H: int = None):
    """Cost-model labels/sizes for drain_window's pass counters:
    window rungs, then the dense-fallback's per-pass rungs, then
    dense."""
    if H is None:
        H = cfg.num_hosts
    wks = window_ladder(cfg, H)
    ks = ladder_of(cfg, H)
    return ([(f"w{k}", k) for k in wks] +
            [(f"k{k}", k) for k in ks] + [("dense", H)])


def step_window_pass(hosts, hp, sh, wend, cfg: EngineConfig):
    """One lockstep pass with active-set compaction.

    The dense pass pays O(H x row-state) per iteration even when one
    busy host is the only one with events left in the window — the
    lockstep-skew cost that made at-scale TCP runs follow the busiest
    relay (the round-2 diagnosis; the reference solves the same skew by
    migrating hosts between threads, shd-scheduler-policy-host-steal.c:
    163-191,266-299). Here: count the ready hosts, pick the smallest
    ladder rung K >= nready, gather exactly those rows, drain up to
    sparse_batch(cfg) consecutive due events per gathered host, scatter
    back — O(K x row-state) amortized over up to B events — else fall
    back to the dense all-hosts step (which executes one event on EVERY
    ready host, so it is strictly better when most hosts are busy).

    Exactness: hosts interact only at window boundaries (loopback
    delivery is host-local), so any per-pass subset schedule that
    steps each host's own events in (time, seq) order produces
    bit-identical state — including draining SEVERAL consecutive due
    events for one host in a single pass (that is exactly the order
    the per-host queue would pop them over consecutive passes, and the
    order the pyengine oracle drains them in). A not-ready row's step
    is the identity (every handler is gated on `ready`; pinned by
    tests/test_compaction.py::test_idle_step_identity), which makes
    dummy gather slots (duplicates of one not-ready host) harmless:
    every duplicate scatter-back writes identical bytes.

    Returns (hosts, rung) where rung indexes ladder_of(cfg) with
    len(ladder) == the dense fallback (pass-mix accounting for the
    SimReport cost model).

    Public full-tree wrapper (tests, tools/phase_profile.py); the
    drain itself calls the hot-working-set core `_pass_hot` directly.
    """
    names = hot_fields(cfg)
    hot = _split_hosts(hosts, names)
    hot, rung = _pass_hot(hot, row_proto(cfg), hp, sh, wend, cfg,
                          names)
    return _join_hosts(hosts, hot, names), rung


def _pass_hot(hot, proto, hp, sh, wend, cfg: EngineConfig, names):
    H = hot["eq_ctr"].shape[0]
    ks = ladder_of(cfg, H)
    ready = hot["eq_next"] < wend                     # [H]
    nready = jnp.sum(ready, dtype=jnp.int32)
    B = sparse_batch(cfg)

    def dense(h):
        with jax.named_scope("dense"):
            return _step_hot(h, proto, hp, sh, wend, cfg, names)

    def make_sparse(K):
        def sparse(h):
            rank = jnp.cumsum(ready) - 1
            take = ready & (rank < K)
            tgt = jnp.where(take, rank, K).astype(jnp.int32)
            hid = jnp.arange(H, dtype=jnp.int32)
            # dummy slots point at the first NOT-ready host: whenever a
            # dummy is needed (nready < K), one exists (nready < H), and
            # its step is the identity (see docstring)
            dummy = jnp.argmin(ready).astype(jnp.int32)
            idx = jnp.full((K,), dummy, jnp.int32).at[tgt].set(
                hid, mode="drop")
            sub = {f: h[f][idx] for f in names}
            shp = jax.tree.map(lambda a: a[idx], hp)
            if B > 1:
                sub = jax.lax.fori_loop(
                    0, B,
                    lambda _, s: _step_hot(s, proto, shp, sh, wend,
                                           cfg, names),
                    sub)
            else:
                sub = _step_hot(sub, proto, shp, sh, wend, cfg, names)
            return {f: h[f].at[idx].set(sub[f]) for f in names}
        return _scoped(f"k{K}", sparse)

    if not ks:
        return dense(hot), jnp.int32(0)

    # smallest rung that fits the ready count; len(ks) = dense
    rung = jnp.searchsorted(jnp.asarray(ks, jnp.int32), nready,
                            side="left").astype(jnp.int32)
    branches = [make_sparse(K) for K in ks] + [dense]
    return jax.lax.switch(rung, branches, hot), rung


# --- Window-boundary packet exchange --------------------------------------

def exsort_cap(cfg: EngineConfig) -> int:
    """Exchange sort-compaction cap (state.EngineConfig.exsortcap).
    Auto: the smallest power of two >= num_hosts (>= 2048) — big
    enough that a whole-population wave of one packet per host (the
    connect-wave worst case) still takes the compact path; multi-
    packet-per-host bursts beyond it fall back to the full sort."""
    N = cfg.num_hosts * cfg.obcap
    if cfg.exsortcap:
        return min(cfg.exsortcap, N)
    c = 2048
    while c < cfg.num_hosts and c < N:
        c *= 2
    return min(c, N)


def dst_cap(cfg: EngineConfig) -> int:
    """Destination-compaction cap for the arrival merge
    (state.EngineConfig.dstcap): when at most this many hosts received
    arrivals this window, only their rows are gathered/merged/
    scattered (merge_arrivals_at); more receivers fall back to the
    full-width merge. MUST be <= num_hosts: dummy slots duplicate a
    no-arrival destination, which is guaranteed to exist only while
    the receiving set is smaller than the host count."""
    if cfg.dstcap:
        return min(cfg.dstcap, cfg.num_hosts)
    return min(cfg.num_hosts, 4096)


def _intake_take(nfree, count_of, IN, cfg: EngineConfig):
    """THE per-destination intake policy — the single definition both
    exchange paths share (and the pyengine oracle mirrors,
    engine.pyengine._exchange): take = min(count, IN, headroom) where
    headroom = free queue slots less a reserve for protocol-internal
    pushes, floored at one arrival while at least TWO slots remain
    free (forward progress without starving internal pushes into
    ST_EQ_FULL_LOCAL — advisor round 3). With nfree <= 1 the arrival
    defers at the source; run_windows' anti-livelock advance drains
    the destination meanwhile."""
    reserve = min(8, cfg.qcap // 4)
    floor = jnp.where(nfree >= 2, 1, 0)
    allow = jnp.minimum(IN, jnp.maximum(nfree - reserve, floor))
    return jnp.minimum(count_of, allow)


def _trace_append(row, pkts, times, valid, dirv, on):
    """Append up to len(times) records to this host's trace ring
    (obs.pcap). Row-level under vmap; compiled only when tracing."""
    TC = row.tr_time.shape[0]
    take = valid & on
    k = jnp.sum(take).astype(jnp.int32)
    rank = jnp.cumsum(take) - 1
    pos = row.tr_cnt + rank.astype(jnp.int32)
    ok = take & (pos < TC)
    tgt = jnp.where(ok, pos, TC)
    return row.replace(
        tr_time=row.tr_time.at[tgt].set(times, mode="drop"),
        tr_pkt=row.tr_pkt.at[tgt].set(pkts, mode="drop"),
        tr_dir=row.tr_dir.at[tgt].set(jnp.int32(dirv), mode="drop"),
        tr_cnt=jnp.minimum(row.tr_cnt + k, TC),
        tr_drop=row.tr_drop + jnp.maximum(row.tr_cnt + k - TC, 0),
    )


def exchange(hosts, hp, sh, cfg: EngineConfig):
    """Route, loss-roll and deliver all outbox packets into destination
    event queues. Pure array program; runs once per window.

    Round-3 deferral semantics: a packet whose destination cannot take
    it this window (per-window intake budget or queue headroom spent)
    STAYS in the source outbox and re-exchanges next window with its
    send time — and therefore its arrival time — unchanged. Never a
    drop: the only modeled drop points are the topology reliability
    roll here and the NIC input buffer
    (shd-network-interface.c:288-311). Engine-capacity pressure shows
    up as ST_DEFER_FANIN, not as lost packets.

    Causal caveat (advisor round 3): the carry preserves arrival
    STAMPS, not execution order. By the window in which a deferred
    packet finally merges, its destination may already have executed
    events with later timestamps (e.g. an RTO that fired before the
    'earlier' ACK was processed), so the arrival's handler runs with a
    stale `now` against newer state. This matches the reference's
    behavior under resource pressure only loosely (the reference
    blocks the sender instead); both engines (this one and the
    pyengine oracle) implement the SAME rule, so differential tests
    stay exact, and TCP handlers are timestamp-robust (stale ACKs/
    segments are filtered by sequence state, not wall order)."""
    H, O, IN = cfg.num_hosts, cfg.obcap, cfg.incap
    N = H * O

    pkts = hosts.ob_pkt.reshape(N, P.PKT_WORDS)
    stimes = hosts.ob_time.reshape(N)
    valid = (jnp.arange(O)[None, :] < hosts.ob_cnt[:, None]).reshape(N)

    src = jnp.clip(pkts[:, P.SRC], 0, H - 1)
    dst = jnp.clip(pkts[:, P.DST], 0, H - 1)
    sv = sh.host_vertex[src]
    dv = sh.host_vertex[dst]
    lat = sh.lat_ns[sv, dv]
    rel = sh.rel[sv, dv]
    arrival = stimes + lat
    # handshake segments carry the one-way path latency (us) in SEQ:
    # the receiver's buffer autotuning reads RTT off the packet
    # instead of a per-row [V,V] table lookup (net.tcp._autotune)
    is_syn = (pkts[:, P.FLAGS] & P.F_SYN) != 0
    pkts = pkts.at[:, P.SEQ].set(
        jnp.where(is_syn, (lat // 1000).astype(jnp.int32),
                  pkts[:, P.SEQ]))

    # Deterministic per-packet drop roll keyed by the globally unique
    # (src, uid) stamped at NIC emit — the counter-based analogue of
    # worker_sendPacket's reliability test (shd-worker.c:238-244).
    # A carried packet re-rolls with the SAME (src, uid) key, so the
    # roll is stable across deferrals.
    u = R.cheap_uniform(R.stream_of(sh.seed32, R.DOMAIN_DROP, src),
                        pkts[:, P.UID])

    reachable = rel > 0
    deliver = valid & reachable & (u <= rel)
    net_dropped = valid & ~deliver

    # group-by-destination: stable sort once, then build the dense
    # [H, IN] inbound buffers entirely with GATHERS — the sorted order
    # makes every per-destination run contiguous, so cell (d, r) is
    # simply sorted position first_of[d] + r. (The previous
    # scatter-based construction dominated the whole window cost:
    # TPU scatters serialize.)
    #
    # Sort compaction (round 4): the argsort over all N = H x obcap
    # slots was itself the dominant window cost at scale (measured
    # ~110 ms/window at socks10k via tools/phase_profile.py — TPU
    # sorts are bitonic). Most windows ship a tiny fraction of N, so
    # when the survivor count fits cfg.exsortcap the valid entries are
    # first compacted (stable: compact rank is monotone in the
    # original index) and only the cap-sized list is sorted; a stable
    # sort of that subsequence equals the full stable sort filtered to
    # the survivors, so delivery order — and every downstream bit —
    # is unchanged. Oversized bursts fall back to the full sort.
    sortkey = jnp.where(deliver, dst, H)
    C = exsort_cap(cfg)
    if C < N and not cfg.tracecap:
        # At-scale path: sort compaction + destination-compacted merge
        # (both exact; see exsort_cap / merge_arrivals_at). The merge
        # runs INSIDE the branches so the dest-compacted variant can
        # touch [D] host rows instead of [H]. pcap tracing needs the
        # full [H, IN] inbound buffers, so traced runs take the static
        # path below instead.
        D = dst_cap(cfg)
        nval = jnp.sum(deliver, dtype=jnp.int32)
        merge_late = False

        def compact_tail(h):
            rank = jnp.cumsum(deliver) - 1
            tgt = jnp.where(deliver, rank, C).astype(jnp.int32)
            idx = jnp.full((C,), N, jnp.int32).at[tgt].set(
                jnp.arange(N, dtype=jnp.int32), mode="drop")
            live = idx < N
            idxc = jnp.minimum(idx, N - 1)
            key_c = jnp.where(live, sortkey[idxc], H)
            order_c = jnp.argsort(key_c, stable=True)
            sdst_c = key_c[order_c]
            # pre-gather ONCE into the sorted domain: the per-dest
            # window reads below then index a [C]-array, not [N]
            pkt_s = pkts[idxc][order_c]
            arr_s = arrival[idxc][order_c]
            dsts = jnp.arange(H, dtype=sdst_c.dtype)
            first_of = jnp.searchsorted(sdst_c, dsts, side="left")
            count_of = (jnp.searchsorted(sdst_c, dsts, side="right")
                        - first_of)
            has = count_of > 0
            ndst = jnp.sum(has, dtype=jnp.int32)

            def dst_compact(h):
                rankD = jnp.cumsum(has) - 1
                tgtD = jnp.where(has, rankD, D).astype(jnp.int32)
                # dummy rows: a destination with NO arrivals — its
                # merge is the identity (k = 0), so duplicates are
                # harmless (merge_arrivals_at docstring)
                dummy = jnp.argmin(has).astype(jnp.int32)
                idxD = jnp.full((D,), dummy, jnp.int32).at[tgtD].set(
                    jnp.arange(H, dtype=jnp.int32), mode="drop")
                nfreeD = jnp.sum(h.eq_time[idxD] == SIMTIME_MAX,
                                 axis=1, dtype=jnp.int32)
                take_ofD = _intake_take(nfreeD, count_of[idxD], IN, cfg)
                r = jnp.arange(IN)
                jD = jnp.clip(first_of[idxD][:, None] + r[None, :],
                              0, C - 1)
                cellD = r[None, :] < take_ofD[:, None]
                in_timeD = jnp.where(cellD, arr_s[jD], SIMTIME_MAX)
                in_pktD = jnp.where(cellD[:, :, None], pkt_s[jD],
                                    jnp.int32(0))
                take_full = jnp.zeros((H,), jnp.int32).at[idxD].set(
                    take_ofD, mode="drop")
                # accepted flags in the sorted domain
                dbc = jnp.clip(sdst_c, 0, H - 1)
                rank_s = jnp.arange(C) - first_of[dbc]
                kept_sorted = ((sdst_c < H) &
                               (rank_s < take_full[dbc]))
                h = merge_arrivals_at(h, cfg, in_pktD, in_timeD, idxD)
                return h, kept_sorted

            def dst_full(h):
                nfree = jnp.sum(h.eq_time == SIMTIME_MAX, axis=1,
                                dtype=jnp.int32)
                in_pkt, in_time, kept_sorted = _deliver_dense(
                    nfree, order_c, sdst_c, pkts[idxc], arrival[idxc],
                    IN, cfg)
                h = merge_arrivals(h, hp, cfg, in_pkt, in_time)
                return h, kept_sorted

            h, kept_sorted = jax.lax.cond(ndst <= D, dst_compact,
                                          dst_full, h)
            kept_c = jnp.zeros((C,), jnp.bool_).at[order_c].set(
                kept_sorted)
            kept = jnp.zeros((N,), jnp.bool_).at[idx].set(
                kept_c, mode="drop")
            return h, kept

        def full_tail(h):
            order = jnp.argsort(sortkey, stable=True)
            sdst = sortkey[order]
            nfree = jnp.sum(h.eq_time == SIMTIME_MAX, axis=1,
                            dtype=jnp.int32)
            in_pkt, in_time, kept_sorted = _deliver_dense(
                nfree, order, sdst, pkts, arrival, IN, cfg)
            h = merge_arrivals(h, hp, cfg, in_pkt, in_time)
            kept = jnp.zeros((N,), jnp.bool_).at[order].set(kept_sorted)
            return h, kept

        hosts, kept = jax.lax.cond(nval <= C, compact_tail, full_tail,
                                   hosts)
    else:
        # static path (small scale, or pcap tracing): full-width sort
        # and delivery; the merge runs LAST (below) so the trace ring
        # keeps its historical tx-before-rx record order — the rx
        # records are appended by merge_arrivals
        order = jnp.argsort(sortkey, stable=True)
        sdst = sortkey[order]
        nfree = jnp.sum(hosts.eq_time == SIMTIME_MAX, axis=1,
                        dtype=jnp.int32)
        in_pkt, in_time, kept_sorted = _deliver_dense(
            nfree, order, sdst, pkts, arrival, IN, cfg)
        kept = jnp.zeros((N,), jnp.bool_).at[order].set(kept_sorted)
        merge_late = True

    # tx trace records cover only packets that actually depart this
    # window (a carried packet is traced in the window it ships).
    # In the at-scale branches above the arrival merge has already
    # run; that is order-equivalent because tracing is off there
    # (tracecap == 0) and everything below touches disjoint state or
    # commuting stat columns.
    hosts = _trace_tx(hosts, hp, cfg, pkts, stimes,
                      (kept | net_dropped).reshape(H, O))
    stay = deliver & ~kept
    net_per_src = jnp.sum(net_dropped.reshape(H, O), axis=1,
                          dtype=jnp.int64)
    hosts = hosts.replace(stats=hosts.stats
                          .at[:, ST_PKTS_DROP_NET].add(net_per_src)
                          .at[:, ST_DEFER_FANIN].add(
        jnp.sum(stay.reshape(H, O), axis=1, dtype=jnp.int64)))
    hosts = _carry_outbox(hosts, pkts, stimes, arrival, stay, O)
    if merge_late:
        hosts = merge_arrivals(hosts, hp, cfg, in_pkt, in_time)
    return hosts


def _deliver_dense(nfree, order, sdst, pkts, arrival,
                   IN, cfg: EngineConfig, lo=0):
    """Shared gather-based delivery construction for both exchanges.
    `order`/`sdst` sort the (possibly gathered) global packet list by
    destination; builds this block's [Hl, IN] inbound buffers for hosts
    [lo, lo+Hl) (reshape-sums, no scatters). `nfree` is the caller's
    per-host free-queue-slot count [Hl].

    Takes and returns ONLY the small delivery arrays — not the Hosts
    pytree — so the compact-vs-full sort branches in the exchange
    carry ~the inbound buffers through lax.cond instead of the whole
    simulation state (conditional branch boundaries materialize their
    operands; at 10k hosts the state is ~0.5 GB per copy).

    Per-destination intake = min(IN, queue headroom): the IN window
    budget, bounded by the free event-queue slots less the reserve for
    protocol-internal pushes — floored at one arrival while at least
    TWO slots are free, so a jammed destination still makes progress
    without the floor consuming the last slot internal pushes need
    (no livelock either way: with nfree <= 1 the arrival defers at
    the source and run_windows' anti-livelock advance drains the
    destination). Returns kept_sorted, the accepted mask over the
    sorted list (False for entries destined outside this block), which
    the caller turns into source-side carries."""
    N = sdst.shape[0]
    Hl = nfree.shape[0]
    dsts = lo + jnp.arange(Hl, dtype=sdst.dtype)
    first_of = jnp.searchsorted(sdst, dsts, side="left")
    count_of = jnp.searchsorted(sdst, dsts, side="right") - first_of

    take_of = _intake_take(nfree, count_of, IN, cfg)

    r = jnp.arange(IN)
    j = jnp.clip(first_of[:, None] + r[None, :], 0, N - 1)  # [Hl, IN]
    oj = order[j]
    cell_ok = r[None, :] < take_of[:, None]
    in_time = jnp.where(cell_ok, arrival[oj], SIMTIME_MAX)
    in_pkt = jnp.where(cell_ok[:, :, None], pkts[oj], jnp.int32(0))

    # accepted flags in the sorted domain: rank within my dest block
    # < that dest's intake
    db = sdst - lo
    inblock = (db >= 0) & (db < Hl)
    dbc = jnp.clip(db, 0, Hl - 1)
    rank = jnp.arange(N) - first_of[dbc]
    kept_sorted = inblock & (rank < take_of[dbc])
    return in_pkt, in_time, kept_sorted


def _carry_outbox(hosts, pkts, stimes, arrival, stay, O):
    """Compact the packets in `stay` (original-order mask [Hl*O]) to
    the front of each source outbox; everything else departed. Records
    the earliest carried arrival in ob_next (window-advance bound).
    Callers count the carries into the appropriate defer stat."""
    Hl = hosts.stats.shape[0]
    stay2 = stay.reshape(Hl, O)
    ordr = jnp.argsort(~stay2, axis=1, stable=True)  # stayers first,
    #   original order preserved (stable sort of booleans)
    ob_pkt = jnp.take_along_axis(pkts.reshape(Hl, O, -1),
                                 ordr[:, :, None], axis=1)
    ob_time = jnp.take_along_axis(stimes.reshape(Hl, O), ordr, axis=1)
    cnt = jnp.sum(stay2, axis=1, dtype=jnp.int32)
    ob_next = jnp.min(jnp.where(stay2, arrival.reshape(Hl, O),
                                SIMTIME_MAX), axis=1)
    return hosts.replace(ob_pkt=ob_pkt, ob_time=ob_time, ob_cnt=cnt,
                         ob_next=ob_next)


def _trace_tx(hosts, hp, cfg: EngineConfig, pkts, stimes, departed):
    """Optional tx pcap records for the packets leaving the outbox
    this window (`departed` [Hl, O] mask; carried packets are traced
    in the window they finally ship). Loopback delivery bypasses the
    exchange and is not traced."""
    if not cfg.tracecap:
        return hosts
    Hl = hosts.stats.shape[0]
    O = departed.shape[1]
    return jax.vmap(_trace_append, in_axes=(0, 0, 0, 0, None, 0))(
        hosts, pkts.reshape(Hl, O, -1), stimes.reshape(Hl, O),
        departed, 1, hp.pcap_on)


def _merge_row(row, ipkt, itime, IN):
    """Merge one host's inbound arrivals into its queue free slots.
    Row-level under vmap; `row` may be a full Hosts row or the
    _MergeView slice of one (destination-compacted path)."""
    k = jnp.sum(itime != SIMTIME_MAX).astype(jnp.int32)
    free = row.eq_time == SIMTIME_MAX
    nfree = jnp.sum(free).astype(jnp.int32)
    k2 = jnp.minimum(k, nfree)
    frank = jnp.cumsum(free) - 1
    take = free & (frank < k2)
    j = jnp.clip(frank, 0, IN - 1)
    overflow = k - k2
    eq_time = jnp.where(take, itime[j], row.eq_time)
    return row.replace(
        eq_time=eq_time,
        eq_kind=jnp.where(take, EV_PKT, row.eq_kind),
        eq_seq=jnp.where(take, row.eq_ctr + frank.astype(jnp.int32),
                         row.eq_seq),
        eq_pkt=jnp.where(take[:, None], ipkt[j], row.eq_pkt),
        eq_ctr=row.eq_ctr + k2,
        eq_next=jnp.min(eq_time),  # cache invariant (state.eq_next)
        stats=radd(row.stats, ST_PKTS_DROP_Q, jnp.int64(overflow)),
    )


def merge_arrivals(hosts, hp, cfg: EngineConfig, in_pkt, in_time):
    """Shared tail of both exchanges (single-chip and sharded — ONE
    implementation so the bit-equality contract between them cannot
    drift): optional rx trace records, then the inbound merge into
    per-host queue free slots. The delivery construction already
    bounded each destination's intake by its queue headroom
    (_deliver_dense), so every arrival fits; the clamp here is a
    belt-and-braces guard — a nonzero ST_PKTS_DROP_Q is an engine
    bug, not a modeled drop."""
    IN = in_time.shape[1]

    if cfg.tracecap:
        hosts = jax.vmap(_trace_append, in_axes=(0, 0, 0, 0, None, 0))(
            hosts, in_pkt, in_time, in_time != SIMTIME_MAX, 0, hp.pcap_on)

    return jax.vmap(partial(_merge_row, IN=IN))(hosts, in_pkt, in_time)


@chex.dataclass
class _MergeView:
    """The subset of Hosts the arrival merge touches — gathered for
    just the destination rows in the compacted merge path, so the
    merge's per-row queue rewrites and data-dependent gathers scale
    with the number of RECEIVING hosts, not the host count (the
    xplane trace showed those gathers were ~45 ms of every socks10k
    window at [H, Q] width)."""
    eq_time: jnp.ndarray
    eq_kind: jnp.ndarray
    eq_seq: jnp.ndarray
    eq_pkt: jnp.ndarray
    eq_ctr: jnp.ndarray
    eq_next: jnp.ndarray
    stats: jnp.ndarray


_MERGE_FIELDS = ("eq_time", "eq_kind", "eq_seq", "eq_pkt", "eq_ctr",
                 "eq_next", "stats")


def merge_arrivals_at(hosts, cfg: EngineConfig, in_pkt, in_time, idxD):
    """Destination-compacted arrival merge: `in_pkt`/`in_time` are
    [D, IN] inbound buffers for the host rows named by idxD [D]
    (duplicates allowed ONLY for rows with zero arrivals — their merge
    is the identity, so duplicate scatters write identical bytes, the
    same argument as step_window_pass's dummy slots). Gathers only the
    merge-touched columns (_MergeView), merges, scatters back."""
    IN = in_time.shape[1]
    view = _MergeView(**{f: getattr(hosts, f)[idxD]
                         for f in _MERGE_FIELDS})
    merged = jax.vmap(partial(_merge_row, IN=IN))(view, in_pkt, in_time)
    return hosts.replace(**{
        f: getattr(hosts, f).at[idxD].set(getattr(merged, f))
        for f in _MERGE_FIELDS})


def update_cap_peaks(hosts):
    """Track peak occupancy of the fixed-capacity per-host arrays (one
    fused elementwise pass per window). Backs the end-of-run capacity
    report — the TPU analogue of the reference's ObjectCounter
    new/free accounting (shd-object-counter.c, reported at
    shd-slave.c:207-211): with no heap objects the failure mode is not
    a leak but an undersized array, so we report headroom instead.

    Sampled at window boundaries (after the drain for outbox/tx, after
    the merge for the queue), so short intra-window spikes can exceed
    the recorded peak — the overflow column of the report is the exact
    loss signal; peaks are a sizing hint."""
    eq_fill = jnp.sum(hosts.eq_time != SIMTIME_MAX, axis=1,
                      dtype=jnp.int32)
    sk_fill = jnp.sum(hosts.sk_used, axis=1, dtype=jnp.int32)
    cur = jnp.stack([eq_fill, sk_fill, hosts.ob_cnt, hosts.txq_cnt],
                    axis=1)
    return hosts.replace(cap_peaks=jnp.maximum(hosts.cap_peaks, cur))


# --- Multi-window driver ---------------------------------------------------

def next_event_time(hosts):
    """Global minimum pending EXECUTABLE event time (the pmin
    reduction). Drives the intra-window pass loop. Reads the cached
    per-host minima (state.eq_next), an [H] reduction, instead of
    scanning the full [H, Q] queue table every pass."""
    return jnp.min(hosts.eq_next)


def next_wakeup(hosts):
    """Window-advance bound: the earliest pending event OR the earliest
    arrival among source-carried packets (ob_next) — a deferred
    delivery must reopen the window even when no queue holds an event
    yet."""
    return jnp.minimum(jnp.min(hosts.eq_next), jnp.min(hosts.ob_next))


# One AOT-compiled instance per (cfg, max_windows): this build's jit
# dispatch fast path runs the wrong executable when multiple big
# variants exist in one process ("supplied 87 buffers but expected 90");
# the ahead-of-time Compiled path sidesteps it (core.jitcache).
_RW_INSTANCES = {}


def run_windows_aot(cfg: EngineConfig, max_windows: int):
    """The AotJit wrapping the (cfg, max_windows) chunk program —
    shared by run_windows and the serving layer's pre-warm path
    (Simulation.prewarm compiles it without executing). The
    cache_scope carries the config fingerprint, so the persistent
    executable cache (serving.aotcache) keys this program stably
    across processes."""
    from ..core.jitcache import AotJit

    key = (cfg, max_windows)
    fn = _RW_INSTANCES.get(key)
    if fn is None:
        def impl(hosts, hp, sh, wstart, wend):
            return _run_windows_impl(hosts, hp, sh, wstart, wend, cfg,
                                     max_windows)

        impl.__name__ = f"run_windows_v{len(_RW_INSTANCES)}"
        impl.__qualname__ = impl.__name__
        from ..obs.ledger import fingerprint_of
        fn = AotJit(impl, donate_argnums=(0,),
                    cache_scope=(f"run_windows.c{max_windows}"
                                 f".{fingerprint_of(cfg)}"))
        _RW_INSTANCES[key] = fn
    return fn


_RWB_INSTANCES = {}


def run_windows_batch_aot(cfg: EngineConfig, max_windows: int,
                          batch: int):
    """The vmapped chunk program of the serving layer's scenario
    batching (serving.batch): `batch` same-shape scenarios stacked on
    a leading axis run the SAME (cfg, max_windows) program as
    run_windows, one compile for all of them. jax's while_loop
    batching rule freezes a finished lane's carry (select against the
    old value), so each lane's window trajectory is exactly its
    individual run's — byte-identical per digest chain
    (tests/test_serving.py)."""
    from ..core.jitcache import AotJit

    key = (cfg, max_windows, batch)
    fn = _RWB_INSTANCES.get(key)
    if fn is None:
        def impl(hosts, hp, sh, wstart, wend):
            return jax.vmap(
                lambda h, p, s, a, b: _run_windows_impl(
                    h, p, s, a, b, cfg, max_windows))(
                hosts, hp, sh, wstart, wend)

        impl.__name__ = f"run_windows_batch_v{len(_RWB_INSTANCES)}"
        impl.__qualname__ = impl.__name__
        from ..obs.ledger import fingerprint_of
        fn = AotJit(impl, donate_argnums=(0,),
                    cache_scope=(f"run_windows_batch.c{max_windows}"
                                 f".b{batch}.{fingerprint_of(cfg)}"))
        _RWB_INSTANCES[key] = fn
    return fn


def run_windows(hosts, hp, sh, wstart, wend, cfg: EngineConfig,
                max_windows: int):
    """Execute up to `max_windows` lookahead windows on device.

    Returns (hosts, wstart', wend', windows_run, pass_counts). The
    caller loops until wstart' >= stop_time or wstart' == SIMTIME_MAX
    (no events left). pass_counts is an i64 vector of lockstep passes
    executed per compaction rung — one entry per ladder_of(cfg) rung
    plus the trailing dense fallback — feeding the SimReport cost
    model (the TPU analogue of the reference's self-reported scheduler
    idle/barrier seconds, shd-scheduler.c:250-252).
    """
    return run_windows_aot(cfg, max_windows)(hosts, hp, sh, wstart,
                                             wend)


def _run_windows_impl(hosts, hp, sh, wstart, wend, cfg: EngineConfig,
                      max_windows: int):
    NR = len(pass_labels(cfg))  # pass-mix counters (SimReport cost)

    def win_cond(carry):
        _, ws, _, i, _ = carry
        return (i < max_windows) & (ws < sh.stop_time) & (ws < SIMTIME_MAX)

    def win_body(carry):
        hosts, ws, we, i, pc = carry
        # never execute past the simulation end (the reference clamps the
        # execution window to endTime, shd-master.c:410-440)
        we_eff = jnp.minimum(we, sh.stop_time)
        ran = next_event_time(hosts) < we_eff  # >=1 event will execute

        # named_scope stamps carry the stateflow entry names into the
        # compiled HLO metadata so the passcope observatory
        # (obs/passcope.py) attributes decoded device self-times back
        # to these passes — naming only, never math
        with jax.named_scope("drain"):
            hosts, pc = drain_window(hosts, hp, sh, we_eff, cfg, pc)
        with jax.named_scope("cap_peaks"):
            hosts = update_cap_peaks(hosts)
        ob0 = jnp.sum(hosts.ob_cnt)
        # an empty exchange is the identity: skip its sort/gather work
        # for windows that emitted nothing (common in sparse phases).
        # Single-chip only — the sharded body's collectives must run
        # uniformly on every shard.
        with jax.named_scope("exchange"):
            hosts = jax.lax.cond(
                jnp.any(hosts.ob_cnt > 0),
                lambda h: exchange(h, hp, sh, cfg),
                lambda h: h, hosts)
        # second sample catches the queue right after arrivals merged
        with jax.named_scope("cap_peaks"):
            hosts = update_cap_peaks(hosts)
        # Anti-livelock: a window that executed nothing AND shipped
        # nothing (every carried packet's destination still jammed)
        # must not re-open at the same carried arrival forever —
        # advance to the earliest queue event instead so the jammed
        # destination drains (its events execute, freeing intake).
        with jax.named_scope("advance"):
            progressed = ran | (jnp.sum(hosts.ob_cnt) < ob0)
            nt = jnp.where(progressed, next_wakeup(hosts),
                           next_event_time(hosts))
            we2 = jnp.where(nt == SIMTIME_MAX, SIMTIME_MAX,
                            nt + sh.min_jump)
        return hosts, nt, we2, i + 1, pc

    return jax.lax.while_loop(
        win_cond, win_body,
        (hosts, wstart, wend, jnp.int32(0), jnp.zeros((NR,), jnp.int64)))


# --- Determinism-digest canonicalization (obs.digest) ---------------------
# Host-side, numpy-only: this module owns the slot conventions (free
# event-queue slots, outbox compaction tails, NIC/trace/wake ring
# bounds), so the rules zeroing DEAD slots before hashing live here —
# next to the device code whose conventions they restate.

def canonicalize_state(arrs: dict) -> dict:
    """Zero dead slots in a host-side copy of the Hosts arrays so
    semantically identical states hash identically.

    Dead slots legitimately retain stale bytes that may differ between
    equal runs (the sharded exchange compacts outboxes differently
    than the single-chip one; q_clear_slot frees a slot without
    scrubbing its payload; closed socket rows keep their last values).
    The digest chain is a statement about LIVE state only:

    - event queue: slots with eq_time == SIMTIME_MAX are free — their
      seq/kind/payload words are scrubbed (equeue.q_clear_slot only
      resets time and kind);
    - outbox: slots at index >= ob_cnt are exchange-compaction tail;
    - NIC tx ring: positions outside [txq_head, txq_head + txq_cnt);
    - hosted-wake / packet-trace rings: slots >= hw_cnt / tr_cnt
      (append-with-drop, never wrapped — _trace_append, bridge.py);
    - socket table: rows with sk_used False are scrubbed wholesale.

    `arrs` maps Hosts field name -> numpy array (leading dim H); a new
    dict of (copied where modified) arrays is returned. Device state
    is never touched.
    """
    import numpy as np

    a = dict(arrs)

    # Narrow at-rest layout (state.NARROW_SPEC, cfg.wide_state == 0):
    # decode every narrowed column back to its canonical wide dtype —
    # and the delta-encoded scoreboards back to absolute stream
    # offsets — BEFORE any hashing or scrubbing. The digest hashes
    # dtype+shape headers per column, so without this a narrowed run
    # could never chain byte-identically to a --wide-state one; and
    # the socket scrub below must see the scoreboards' dead-slot
    # sentinel in ONE encoding (a freed slot's stale relative values
    # decode to garbage absolutes, which the sk_used scrub then zeroes
    # exactly like the wide run's stale absolutes). Order matters: the
    # abs columns first, so the rel anchors (sk_rcv_nxt/sk_snd_una)
    # are wide when the scoreboards decode against them.
    for f, (wdt, _ndt) in NARROW_ABS.items():
        if f in a and a[f].dtype != np.dtype(wdt):
            a[f] = a[f].astype(wdt)
    for f, (wdt, _ndt, anchor) in NARROW_REL.items():
        if f in a and a[f].dtype != np.dtype(wdt):
            rel = a[f]
            anc = a[anchor]
            a[f] = np.where(rel >= 0, rel.astype(wdt) + anc[..., None],
                            np.array(-1, wdt))

    def scrub(key, dead):
        v = a[key]
        m = dead
        while m.ndim < v.ndim:
            m = m[..., None]
        a[key] = np.where(m, np.zeros((), v.dtype), v)

    free = a["eq_time"] == SIMTIME_MAX
    for k in ("eq_seq", "eq_kind", "eq_pkt"):
        scrub(k, free)

    O = a["ob_time"].shape[1]
    dead_ob = np.arange(O)[None, :] >= a["ob_cnt"][:, None]
    for k in ("ob_time", "ob_pkt"):
        scrub(k, dead_ob)

    T = a["txq_pkt"].shape[1]
    pos = (np.arange(T)[None, :] - a["txq_head"][:, None]) % T
    scrub("txq_pkt", pos >= a["txq_cnt"][:, None])

    HW = a["hw_time"].shape[1]
    dead_hw = np.arange(HW)[None, :] >= a["hw_cnt"][:, None]
    for k in ("hw_time", "hw_pkt"):
        scrub(k, dead_hw)

    TC = a["tr_time"].shape[1]
    dead_tr = np.arange(TC)[None, :] >= a["tr_cnt"][:, None]
    for k in ("tr_time", "tr_pkt", "tr_dir"):
        scrub(k, dead_tr)

    unused = ~a["sk_used"]
    for k in arrs:
        if k.startswith("sk_") and k != "sk_used":
            scrub(k, unused)
    return a
