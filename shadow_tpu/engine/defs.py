"""Engine-wide enums: event kinds, app notification reasons, stat slots.

The reference models work as heap-allocated Event/Task closures
(/root/reference/src/main/core/work/shd-event.c, shd-task.c); a closure
cannot be traced by XLA, so here every schedulable behavior is one of a
fixed set of event kinds dispatched through lax.switch.
"""

# --- Event kinds (eq_kind) ---
EV_NULL = 0        # empty queue slot
EV_APP = 1         # app wake: payload AUX = reason, SEQ = socket (or -1)
EV_PKT = 2         # packet arrival at this host's NIC; payload = packet
EV_NIC_TX = 3      # NIC transmit becomes free; pull next packet
EV_TCP_TIMER = 4   # TCP retransmission timer; payload SEQ=socket, ACK=generation
EV_TCP_CLOSE = 5   # TCP close/TIME_WAIT teardown timer; payload SEQ=socket
N_EVENT_KINDS = 6

# --- App wake reasons (in EV_APP payload AUX word) ---
WAKE_START = 0       # process start (reference: _process_runStartTask)
WAKE_TIMER = 1       # app-requested timer
WAKE_SOCKET = 2      # socket readable/writable/established/closed
WAKE_CONNECTED = 3   # connection established (TCP handshake done)
WAKE_EOF = 4         # peer FIN: stream finished
WAKE_ACCEPT = 5      # listener accepted a new child connection
WAKE_SENT = 6        # all written bytes acked (send complete)

# --- Per-host stat slots (stats[H, N_STATS] int64) ---
ST_EVENTS = 0          # events executed
ST_PKTS_SENT = 1       # packets handed to the wire (incl. retransmits)
ST_PKTS_RECV = 2       # packets arriving at NIC
ST_PKTS_DROP_NET = 3   # dropped by topology reliability roll
ST_PKTS_DROP_BUF = 4   # dropped: receiver NIC input buffer full
ST_PKTS_DROP_Q = 5     # dropped: destination event queue overflow
#                        (exchange belt-and-braces only since round 3 —
#                        arrivals that cannot merge DEFER at the source
#                        instead; a nonzero value here is an engine bug)
ST_BYTES_SENT = 6      # payload bytes sent (first transmission)
ST_BYTES_RECV = 7      # payload bytes received in order (delivered to app)
ST_RETRANSMIT = 8      # TCP segments retransmitted
ST_OUTBOX_DROP = 9     # dropped: outbox overflow (window emit budget)
ST_EQ_FULL_LOCAL = 10  # dropped local pushes: own queue full
ST_SOCK_FAIL = 11      # socket allocation failures
ST_APP_DONE = 12       # app reached terminal state (end node)
ST_XFER_DONE = 13      # app-level transfers completed
ST_RTT_SUM_US = 14     # accumulated app RTT measurements (microseconds)
ST_RTT_COUNT = 15      # number of RTT samples
ST_TXQ_DROP = 16       # dropped: NIC transmit ring full (sndbuf overflow)
ST_TGEN_DROP = 17      # tgen walk forks lost to cursor-stack overflow
ST_CHAIN_SHORT = 18    # socks circuits shortened: relay had no pool to
#                        extend a hops>0 CONNECT (config mismatch)
ST_SACK_RENEGE = 19    # receiver OOO scoreboard overflow discarded a
#                        range possibly already advertised (stall ends
#                        at the RTO; see net/sack.py insert_counted)
ST_TGEN_ABORT = 20     # tgen transfers aborted by timeout/stallout
#                        (shd-tgen-transfer.c:918-961 semantics)
ST_DEFER_FANIN = 21    # packets deferred to the next window at the
#                        SOURCE because the destination's per-window
#                        intake (incap or queue headroom) was spent —
#                        exact carry, arrival times unchanged; counted
#                        per window deferred (a packet carried 3
#                        windows counts 3). The engine-artifact
#                        replacement for what used to be a drop: the
#                        only modeled-semantics drop point is the NIC
#                        input buffer (ST_PKTS_DROP_BUF,
#                        shd-network-interface.c:288-311)
ST_DEFER_A2A = 22      # packets deferred at the source because the
#                        sharded exchange's per-(src,dst)-shard bucket
#                        was full (parallel.shard; raise a2acap if this
#                        grows — deferral is exact but delays delivery
#                        processing by a window)
ST_FAULTS = 23         # injected fault events applied to this host
#                        (engine.faults: host kill/restart count at the
#                        faulted host; the RSTs a kill sends toward
#                        peers ride the normal EV_PKT path and are NOT
#                        separately counted here)
N_STATS = 24
