"""Simulation state: struct-of-arrays pytrees.

The reference keeps per-host state in heap objects (Host,
NetworkInterface, Socket/TCP, descriptor tables —
/root/reference/src/main/host/shd-host.c:64-130) and events as allocated
closures in per-host priority queues. On TPU the whole simulation is
three pytrees:

- :class:`Hosts` — every mutable per-host array, leading dim H. This is
  what the engine transforms (and donates between jit steps). Under
  ``vmap`` a "row" of it is one simulated host.
- :class:`HostParams` — read-only per-host configuration (topology
  vertex, bandwidths, app wiring).
- :class:`Shared` — replicated tables and scalars: the vertex-by-vertex
  latency/reliability oracle, RNG root, stop time, lookahead window.

Sizing knobs live in :class:`EngineConfig`; they are Python static so
XLA sees fixed shapes.
"""

from __future__ import annotations

from dataclasses import dataclass

import chex
import jax.numpy as jnp
import numpy as np

from ..core.simtime import SIMTIME_MAX
from ..core import constants as C
from ..net.packet import PKT_WORDS
from ..net.sack import K as SACK_K
from ..obs.netscope import NS_BUCKETS, NS_KINDS
from .defs import N_STATS


@dataclass(frozen=True)
class EngineConfig:
    """Static engine shape/size configuration."""
    num_hosts: int
    qcap: int = 32          # event-queue slots per host
    scap: int = 16          # socket table rows per host
    obcap: int = 32         # outbox (per-window emit budget) per host
    incap: int = 32         # per-window inbound packet budget per host
    txqcap: int = 16        # NIC transmit-ring slots per host
    chunk_windows: int = 64  # windows executed per jit invocation
    #   (larger chunks amortize dispatch + host sync; measured ~1.6x
    #   on-chip at 128 vs 32 — heartbeat/pcap/checkpoint granularity
    #   is per chunk, so not unbounded)
    cc_kind: int = 2        # 0=aimd 1=reno 2=cubic (reference default cubic)
    hostedcap: int = 1      # hosted-app wake-ring slots per host (hosting/)
    # Dead-branch pruning: which app kinds exist in this scenario, and
    # whether any host can open a TCP socket. The Simulation fills these
    # from the compiled process specs; the window program only traces
    # branches that can run — at 1 app kind the compile is a fraction
    # of the all-apps program (no behavioral effect: a pruned branch is
    # unreachable by construction).
    app_kinds: tuple = None  # e.g. (0, 3) — must include 0 (APP_NULL)
    uses_tcp: bool = True
    qdisc: int = 1          # NIC socket service: 0=fifo, 1=round-robin
    #   (reference --interface-qdisc, default fifo; rr kept as our
    #   default for fairness under many concurrent flows)
    cpu_model: bool = False  # host CPU delay model (net effect only
    #   when a scenario sets cpu costs; static so the default engine
    #   compiles none of it — the reference's is also off by default
    #   (--cpu-threshold [-1], shd-options.c:76)
    tracecap: int = 0       # packet-trace ring slots per host (obs.pcap;
    #   0 disables tracing entirely — the exchange compiles no trace code)
    synccap: int = 1        # tgen synchronize-barrier counters per host
    #   (sized by the Simulation to the compiled graphs' sync-node count)
    procs_per_host: int = 1  # process slots per host (the reference
    #   runs a process LIST per host, shd-configuration.h:36-95,
    #   slave_addNewVirtualProcess shd-slave.c:293 — e.g. tor + tgen
    #   on one machine). Each process has its own app kind/cfg/
    #   registers ([H, P] rows); sockets remember their owning process
    #   (sk_proc) so wakes route back to it. Sized by the Simulation
    #   to the scenario's max process count.
    exchange_a2a: bool = True  # sharded exchange protocol: bucketed
    #   ragged all-to-all (v2, per-shard wire bytes ~flat in shard
    #   count) vs the v1 all_gather (O(shards x outbox); set False to
    #   fall back). Single-chip runs ignore this.
    a2acap: int = 0         # all-to-all bucket slots per (src shard ->
    #   dst shard) pair; 0 = auto (4x the uniform-traffic share,
    #   clamped to the shard outbox). Bucket overflow DEFERS the tail
    #   at the source (counted in ST_DEFER_A2A; see
    #   parallel.shard.exchange_sharded).
    active_block: int = -1  # active-set compaction: a lockstep pass
    #   with few ready hosts gathers just those rows, steps them, and
    #   scatters back instead of paying a full all-hosts pass — the
    #   TPU-native analogue of the reference's host-steal load
    #   balancing (shd-scheduler-policy-host-steal.c:163-191): a
    #   single busy relay no longer charges every idle host one pass
    #   per event. -1 (default) = AUTO: a small rung ladder sized from
    #   num_hosts, each pass picking the smallest rung that fits its
    #   ready count (engine.window.ladder_of — replaces the round-3
    #   hand-tuned per-config constant), plus ONE window-level rung
    #   under the quarter rule 4K <= H (engine.window.window_ladder;
    #   round 9 tightened it from 2K <= H after the paired phold-4096
    #   A/B showed the half-state [2048] window rung losing 1.2x to
    #   [512] — BASELINE.md round-9 table, tools/perf_ab.py). > 0 =
    #   one explicit rung of that size. 0 = off (always dense).
    #   Bit-identical in every mode: hosts only interact at window
    #   boundaries, so per-host (time, seq) execution order is
    #   unchanged.
    exsortcap: int = 0      # exchange sort-compaction cap: the window
    #   exchange's group-by-destination argsort ran over ALL H x obcap
    #   outbox slots (240k at socks10k — measured ~110 ms/window on
    #   chip, ~40% of the socks10k wall; TPU sorts are bitonic and
    #   expensive). When the window's surviving packet count fits this
    #   cap, the exchange first compacts the valid entries (stable,
    #   original order) and sorts only the cap-sized list; larger
    #   bursts fall back to the full sort. 0 = auto
    #   (engine.window.exsort_cap); bit-identical either way (a stable
    #   sort of the compacted subsequence equals the filtered stable
    #   sort of the full list).
    dstcap: int = 0         # destination-compaction cap for the
    #   arrival merge (engine.window.dst_cap): windows whose receiving
    #   host set fits the cap merge only those rows ([D] gathers
    #   instead of [H]-wide queue rewrites — the xplane trace showed
    #   the full-width merge's data-dependent gathers were ~45 ms of
    #   every socks10k window). 0 = auto (min(H, 4096) —
    #   engine.window.dst_cap); bit-identical either way (a no-arrival
    #   row's merge is the identity).
    event_batch: int = 16   # max consecutive due events drained per
    #   gathered host within ONE sparse compaction pass (engine.window.
    #   sparse_batch; forced to 1 under the CPU model and with hosted
    #   apps). Amortizes the rung gather/scatter over up to this many
    #   events — pass COUNT, not just pass cost, is the other factor
    #   of the lockstep-skew product (round-3 verdict item 2). Dense
    #   passes always drain exactly one event per ready host. Default
    #   widened 8 -> 16 by the paired event-batch A/B (BASELINE.md
    #   round-12 table; 32 was not significantly better and doubles
    #   the per-rung program size).
    hot_split: int = 1      # hot/cold state split in the lockstep
    #   drain: 1 (default) = the drain's gathers, scatters and
    #   while-loop carries move only hot_fields(cfg) — COLD_FIELDS
    #   plus the config-gated COLD_WHEN columns stay full-width at the
    #   window boundary and rejoin after the drain. 0 = carry the full
    #   pytree (the pre-split engine; kept for paired A/Bs and the
    #   split-equivalence tests). Bit-identical either way: the drain
    #   provably never touches cold columns (simlint STF303 statically;
    #   COLD_WHEN columns hold their alloc defaults in the gating
    #   configs, so the row prototype reads are exact — see row_proto).
    netscope: bool = False  # network observatory (obs.netscope):
    #   allocate the per-host latency histograms (ns_hist —
    #   [H, NS_KINDS, NS_BUCKETS] i64) and count RTT / completion /
    #   queue-delay / retransmit samples into them inside the jitted
    #   passes. Off (default) allocates the bucket axis at ZERO, so
    #   shapes, digests and checkpoints of existing runs are
    #   untouched and every observe() call is a static no-op.
    wide_state: int = 0     # at-rest state width (the shrink
    #   campaign, ROADMAP item 2): 0 (default) = the socket-table
    #   columns in NARROW_SPEC live at their narrow dtypes (i32/i16/
    #   i8/u16) with the SACK/OOO scoreboards delta-encoded relative
    #   to their stream anchors; every handler still computes at the
    #   canonical wide dtypes — widen_state/narrow_state convert at
    #   the drain's single row entry/exit (engine.window.step_one_host)
    #   and at the hosted op-replay boundary (hosting.bridge.apply_ops).
    #   1 = allocate full-width (the pre-shrink layout; the --wide-state
    #   A/B escape hatch, same pattern as hot_split=0). Bit-identical
    #   either way: NARROW_SPEC carries a machine-checked bound per
    #   column (simlint STF404) proving round-trip exactness, and
    #   digest chains canonicalize narrowed columns back to wide form
    #   before hashing (engine.window.canonicalize_state).


# Digest sections (obs.digest): Hosts field prefix -> the named state
# section a divergence report attributes to. Declared next to the
# arrays so a new field group gets a section in the same edit; fields
# matching no prefix digest under "other" (visible, never silently
# skipped).
STATE_SECTIONS = (
    ("eq_", "event_queue"),
    ("rng_ctr", "rng"),
    ("cpu_avail", "cpu"),
    ("nic_", "nic"),
    ("txq_", "nic"),
    ("pkt_ctr", "nic"),
    ("next_eport", "nic"),
    ("sk_", "tcp"),
    ("app_", "app"),
    ("tgen_sync", "app"),
    ("ob_", "outbox"),
    ("hw_", "hosted_wakes"),
    ("tr_", "trace_ring"),
    ("stats", "stats"),
    ("ns_", "netscope"),
    ("cap_peaks", "stats"),
)


def section_of(field: str, *, strict: bool = False) -> str:
    """Digest section for a Hosts field. With strict=True an unmapped
    field raises instead of landing in the "other" bucket — digest and
    checkpoint attribution silently degrade there, so simlint STF301
    (and tests/test_stateflow.py) require every field to be sectioned;
    the default stays lenient for forward-compat readers of old
    digest chains."""
    for prefix, section in STATE_SECTIONS:
        if field.startswith(prefix):
            return section
    if strict:
        raise KeyError(
            f"Hosts field {field!r} matches no STATE_SECTIONS prefix; "
            "add a (prefix, section) entry next to the field")
    return "other"


# Hot/cold column contract for the ROADMAP item-1 socket-table split
# (engine.window.drain_window actually enforces it at runtime: the
# drain's gathers, scatters and while-loop carries move hot columns
# only). Two levels:
#
# - COLD_FIELDS (static): a column NO drain-pass code touches in ANY
#   config — only read/written at window boundaries (exchange,
#   cap-peak sampling, window advance) or by host-side consumers
#   (pcap drain, reports). The stateflow analyzer (lint/stateflow.py,
#   STF303) verifies this against the drain-pass subgraph on every
#   simlint run, so a cold column cannot creep back into the working
#   set unnoticed; tools/state_matrix.py prints the measured matrix
#   this set was derived from.
# - COLD_WHEN (config-gated): columns whose drain accesses are
#   statically pruned under a named config predicate (cpu model off,
#   no hosted apps, no tgen, no TCP) — the socket table's SACK
#   bookkeeping, RTT/congestion state and per-connection config all
#   leave the working set on the UDP/phold tiers. See the invariant
#   note at COLD_WHEN below and docs/static-analysis.md.
COLD_FIELDS = frozenset({
    "ob_next",      # written by the exchange carry, read by advance
    "tr_time", "tr_pkt", "tr_dir", "tr_cnt", "tr_drop",  # pcap ring:
    #   exchange-side appends, host-side drain
    "cap_peaks",    # window-boundary sampling only
})

# The drain's STATIC hot working set: every Hosts column that is not
# in COLD_FIELDS, in declaration order. A LITERAL tuple on purpose —
# the stateflow analyzer reads it from the AST (never importing this
# module) and treats `hot_fields(cfg)` calls as exactly this set, so
# the drain's declared working set and the machine-checked one cannot
# drift. Import-time assert below pins HOT_FIELDS | COLD_FIELDS ==
# fields(Hosts) with no overlap; simlint STF300 re-checks it statically.
HOT_FIELDS = (
    "eq_time", "eq_seq", "eq_kind", "eq_pkt", "eq_ctr", "eq_next",
    "rng_ctr", "cpu_avail",
    "nic_busy", "nic_sched", "nic_rr", "nic_rx_until",
    "txq_pkt", "txq_head", "txq_cnt", "pkt_ctr", "next_eport",
    "sk_used", "sk_proto", "sk_state", "sk_lport", "sk_rport",
    "sk_rhost", "sk_parent", "sk_snd_una", "sk_snd_nxt", "sk_snd_max",
    "sk_snd_end", "sk_rcv_nxt", "sk_ooo_s", "sk_ooo_e", "sk_sack_s",
    "sk_sack_e", "sk_hole_end", "sk_rex_nxt", "sk_peer_fin",
    "sk_fin_acked", "sk_close_after", "sk_cwnd", "sk_ssthresh",
    "sk_srtt", "sk_rtt_min", "sk_rttvar", "sk_rto", "sk_rto_deadline",
    "sk_timer_on", "sk_timer_gen", "sk_dupacks", "sk_rtt_seq",
    "sk_rtt_time", "sk_ctl", "sk_peer_rwnd", "sk_sndbuf", "sk_rcvbuf",
    "sk_hs_time", "sk_last_tx", "sk_syn_tag", "sk_proc", "sk_app_ref",
    "sk_cc_wmax", "sk_cc_epoch", "sk_cc_k",
    "app_node", "app_r", "app_proc", "tgen_sync",
    "ob_pkt", "ob_time", "ob_cnt",
    "hw_time", "hw_pkt", "hw_cnt", "hw_drop",
    "stats", "ns_hist",
)

# Config-gated cold columns (the level-2 split): (guard, fields) —
# each field leaves the drain's RUNTIME working set when its guard
# holds for the engine config, because the static pruning already
# compiles no access to it (the Python `if cfg.*` branches and the
# app_kinds switch table). Exactness invariant (pinned by
# tests/test_compaction.py::test_hot_split_gating_bit_identical and
# the dual-run digest suite): under the guard, the column holds its
# alloc_hosts default on every row at every instant — the only
# reachable writes are the sock_alloc/sock_free resets, which write
# that same default — so the drain's compiled reads of it (e.g.
# tcp_want_tx scanning a TCP-less socket table) see the true value
# through the row prototype (row_proto), and discarding its writes is
# the identity. The stateflow gate cannot see static config, so a new
# access to one of these columns OUTSIDE its guard must be caught by
# the equivalence tests; grow this table only with the paired
# all-hot-vs-gated proof (docs/performance.md "hot/cold split").
COLD_WHEN = (
    # host CPU delay model off: cpu_avail is only touched inside
    # `if cfg.cpu_model:` blocks (engine.window.step_one_host)
    ("cpu_model_off", ("cpu_avail",)),
    # no hosted apps: the wake ring is appended only by
    # hosting.bridge (APP_HOSTED switch branch) and the mid-window
    # pause check compiles only when hostedcap > 1
    ("no_hosted", ("hw_time", "hw_pkt", "hw_cnt", "hw_drop")),
    # no tgen processes: the synchronize-barrier counters are touched
    # only by apps.tgen (APP_TGEN switch branch)
    ("no_tgen", ("tgen_sync",)),
    # no TCP sockets can exist (uses_tcp False prunes the rx TCP path
    # and the timer/close handlers; no TCP-capable app kind is
    # compiled): every column below is written only by the TCP
    # machine or reset-to-default by sock_alloc/sock_free, and every
    # residual compiled read (tcp_want_tx via nic.kick, the sock_alloc
    # TIME_WAIT eviction rank, the fifo qdisc key) sees the default —
    # the exact value the column invariantly holds. The UDP-touched
    # columns (sk_used/proto/lport/snd_end/rcv_nxt/timer_gen) and
    # sk_proc stay hot.
    ("no_tcp", (
        "sk_state", "sk_rport", "sk_rhost", "sk_parent", "sk_snd_una",
        "sk_snd_nxt", "sk_snd_max", "sk_ooo_s", "sk_ooo_e",
        "sk_sack_s", "sk_sack_e", "sk_hole_end", "sk_rex_nxt",
        "sk_peer_fin", "sk_fin_acked", "sk_close_after", "sk_cwnd",
        "sk_ssthresh", "sk_srtt", "sk_rtt_min", "sk_rttvar", "sk_rto",
        "sk_rto_deadline", "sk_timer_on", "sk_dupacks", "sk_rtt_seq",
        "sk_rtt_time", "sk_ctl", "sk_peer_rwnd", "sk_sndbuf",
        "sk_rcvbuf", "sk_hs_time", "sk_last_tx", "sk_syn_tag",
        "sk_app_ref", "sk_cc_wmax", "sk_cc_epoch", "sk_cc_k",
    )),
    # multi-process wake routing reads sk_proc (window._on_app, PP>1
    # branch); single-process no-TCP configs only ever write the
    # default 0 (sock_alloc stamps app_proc, which is 0 there)
    ("no_tcp_single_proc", ("sk_proc",)),
    # network observatory off: ns_hist is written only by
    # obs.netscope.observe, which is a static no-op when the bucket
    # axis is allocated at zero (cfg.netscope False) — the column is
    # then zero-size anyway, but gating it keeps the hot-column count
    # honest for the ledger's config_extras
    ("netscope_off", ("ns_hist",)),
)


def _guard_holds(guard: str, cfg: "EngineConfig") -> bool:
    def has_app(kind):
        # unknown app set (None = Simulation has not filled it) is
        # treated as "present": gating must be conservative
        return cfg.app_kinds is None or kind in cfg.app_kinds

    from ..apps.base import APP_HOSTED, APP_TGEN  # no import cycle:
    #   apps.base pulls engine.equeue/defs only

    no_hosted = cfg.hostedcap <= 1 and not has_app(APP_HOSTED)
    if guard == "cpu_model_off":
        return not cfg.cpu_model
    if guard == "no_hosted":
        return no_hosted
    if guard == "no_tgen":
        return not has_app(APP_TGEN)
    if guard == "no_tcp":
        return not cfg.uses_tcp and no_hosted
    if guard == "no_tcp_single_proc":
        return (not cfg.uses_tcp and no_hosted
                and cfg.procs_per_host <= 1)
    if guard == "netscope_off":
        return not cfg.netscope
    raise KeyError(f"unknown COLD_WHEN guard {guard!r}")


def hot_fields(cfg: "EngineConfig") -> tuple:
    """The drain's runtime hot working set for this config, in Hosts
    declaration order: HOT_FIELDS minus every COLD_WHEN column whose
    guard holds. With cfg.hot_split == 0 the full pytree (static cold
    columns included) is returned — the pre-split engine, for paired
    A/Bs and equivalence tests."""
    if not cfg.hot_split:
        return tuple(Hosts.__dataclass_fields__)
    off = set()
    for guard, fields in COLD_WHEN:
        if _guard_holds(guard, cfg):
            off.update(fields)
    return tuple(f for f in HOT_FIELDS if f not in off)


# At-rest narrow layout for provably-bounded socket columns (the
# shrink campaign, ROADMAP item 2). Each entry:
#
#   (field, wide, narrow, encoding, bound, why)
#
# - `wide` is the canonical COMPUTE dtype every handler sees (the
#   dtype the Hosts annotation comments declare and digest chains
#   canonicalize to);
# - `narrow` is the AT-REST dtype alloc_hosts uses when
#   cfg.wide_state == 0;
# - `encoding` is "abs" (plain cast — the value itself fits the
#   narrow dtype) or "rel:<anchor>" (stored as offset from the named
#   Hosts column; the free-slot sentinel -1 is preserved verbatim);
# - `bound` is the machine-checked maximum magnitude a live value can
#   take (plain int literal — the stateflow analyzer reads this table
#   from the AST and ast.literal_eval cannot fold shifts), and `why`
#   names the invariant that enforces it.
#
# Simlint STF404 verifies every entry (known dtypes, bound fits the
# narrow dtype, rel anchors are abs-narrowed i64 columns, non-empty
# why) and tests/test_shrink.py asserts the bounds against the
# documented max scenario parameters, failing by field name.
#
# Stream offsets are bounded by the TCP wire format: every SEQ/ACK is
# cast to i32 on the wire (net/tcp.py mk_segment), so an absolute
# stream offset past 2^31-1 would already corrupt the protocol — the
# sender's flow control (sndbuf/rwnd <= buf_cap = 2^30) never reaches
# it within any supported scenario envelope (max transfer ~2 GiB per
# connection; UDP's cumulative sk_rcv_nxt byte counter shares the
# same documented envelope). Scoreboard runs are additionally bounded
# by the receive/send buffer (< 2^30) so offsets relative to
# sk_rcv_nxt/sk_snd_una always fit i32 with room.
NARROW_SPEC = (
    # -- delta-encoded scoreboards (lever 1): [H, S, K] i64 -> i32 --
    ("sk_ooo_s", "i64", "i32", "rel:sk_rcv_nxt", 1073741824,
     "receiver OOO runs lie in (rcv_nxt, rcv_nxt + rcvbuf]; "
     "rcvbuf <= buf_cap = 2^30 (net/tcp.py _autotune)"),
    ("sk_ooo_e", "i64", "i32", "rel:sk_rcv_nxt", 1073741824,
     "run ends share the OOO window bound (end - rcv_nxt <= rcvbuf)"),
    ("sk_sack_s", "i64", "i32", "rel:sk_snd_una", 1073741824,
     "sender SACK runs lie in [snd_una, snd_una + sndbuf + rwnd); "
     "both <= buf_cap = 2^30 and runs are dropped below una on every "
     "ACK (net/tcp.py on_tcp_rx drop_below BEFORE the una write)"),
    ("sk_sack_e", "i64", "i32", "rel:sk_snd_una", 1073741824,
     "run ends share the SACK window bound"),
    # -- absolute stream offsets (lever 2): i64 -> i32 ----------------
    ("sk_snd_una", "i64", "i32", "abs", 2147483647,
     "wire i32 SEQ/ACK cast (net/tcp.py mk_segment) bounds every "
     "absolute stream offset below 2^31"),
    ("sk_snd_nxt", "i64", "i32", "abs", 2147483647, "wire i32 SEQ"),
    ("sk_snd_max", "i64", "i32", "abs", 2147483647, "wire i32 SEQ"),
    ("sk_snd_end", "i64", "i32", "abs", 2147483647,
     "app write cursor; flow control caps it at snd_una + sndbuf"),
    ("sk_rcv_nxt", "i64", "i32", "abs", 2147483647,
     "wire i32 ACK; UDP reuses it as a delivered-bytes counter under "
     "the same documented scenario envelope"),
    ("sk_hole_end", "i64", "i32", "abs", 2147483647,
     "recovery point: a snapshot of snd_max (wire-bounded)"),
    ("sk_rex_nxt", "i64", "i32", "abs", 2147483647,
     "retransmit cursor within [snd_una, snd_max]"),
    ("sk_peer_fin", "i64", "i32", "abs", 2147483647,
     "peer FIN stream offset (wire-bounded; -1 sentinel when unset)"),
    ("sk_rtt_seq", "i64", "i32", "abs", 2147483647,
     "RTT-sampled SEQ (wire-bounded; -1 sentinel between samples)"),
    # -- buffer/window sizes (lever 2): i64 -> i32 --------------------
    ("sk_peer_rwnd", "i64", "i32", "abs", 1073741824,
     "peer-advertised window, clamped to buf_cap = 2^30 on receive"),
    ("sk_sndbuf", "i64", "i32", "abs", 1073741824,
     "send buffer, autotuned within [min, buf_cap = 2^30]"),
    ("sk_rcvbuf", "i64", "i32", "abs", 1073741824,
     "receive buffer, autotuned within [min, buf_cap = 2^30]"),
    # -- small enums / flags / ports (lever 2) ------------------------
    ("sk_proto", "i32", "i8", "abs", 17,
     "IPPROTO id: 0 free, 1 hosted pipe, 6 tcp, 17 udp"),
    ("sk_state", "i32", "i8", "abs", 10,
     "TCPS_* enum, max TCPS_TIME_WAIT = 10 (net/socket.py)"),
    ("sk_ctl", "i32", "i8", "abs", 31,
     "pending-control bitmask SYN|SYNACK|ACKNOW|FIN|RST = 0x1f"),
    ("sk_lport", "i32", "u16", "abs", 65535,
     "port numbers <= MAX_PORT = 65535 (core/constants.py)"),
    ("sk_rport", "i32", "u16", "abs", 65535,
     "port numbers <= MAX_PORT = 65535"),
)

_DTYPES = {"i8": "int8", "i16": "int16", "u16": "uint16",
           "i32": "int32", "i64": "int64"}


def _narrow_maps():
    """(abs, rel) field maps parsed once from NARROW_SPEC: abs is
    {field: (wide_dt, narrow_dt)}, rel is {field: (wide_dt, narrow_dt,
    anchor)} with anchors resolvable through abs."""
    abs_f, rel_f = {}, {}
    for field, wide, narrow, enc, _bound, _why in NARROW_SPEC:
        wdt, ndt = _DTYPES[wide], _DTYPES[narrow]
        if enc == "abs":
            abs_f[field] = (wdt, ndt)
        else:
            rel_f[field] = (wdt, ndt, enc.split(":", 1)[1])
    return abs_f, rel_f


NARROW_ABS, NARROW_REL = _narrow_maps()
# the dtype probe the codec keys on: wide alloc gives int64 here
_PROBE_FIELD = "sk_snd_una"


def narrow_dtypes(cfg: "EngineConfig") -> dict:
    """{field: jnp dtype} for the at-rest layout this config allocates
    — empty when cfg.wide_state (the A/B escape hatch) asks for the
    full-width layout."""
    if getattr(cfg, "wide_state", 0):
        return {}
    out = {f: jnp.dtype(ndt) for f, (_w, ndt) in NARROW_ABS.items()}
    out.update({f: jnp.dtype(ndt)
                for f, (_w, ndt, _a) in NARROW_REL.items()})
    return out


def widen_state(t):
    """Decode a narrow at-rest Hosts tree (or a single vmapped row) to
    the canonical wide compute form -> (tree, was_narrow). Identity on
    wide state; `was_narrow` is a PYTHON bool read from static dtypes
    at trace time, so the wide path compiles zero conversion code.
    Rank-agnostic: scoreboard anchors broadcast over the trailing K
    axis via [..., None], so the same codec serves step_one_host's
    rows and apply_ops' full [H, S, K] tables."""
    probe = getattr(t, _PROBE_FIELD)
    if str(probe.dtype) == NARROW_ABS[_PROBE_FIELD][0]:
        return t, False
    reps = {}
    for f, (wdt, _ndt) in NARROW_ABS.items():
        reps[f] = getattr(t, f).astype(wdt)
    for f, (wdt, _ndt, anchor) in NARROW_REL.items():
        rel = getattr(t, f)
        anc = reps[anchor]  # anchors are abs-narrowed -> already wide
        reps[f] = jnp.where(rel >= 0,
                            rel.astype(wdt) + anc[..., None],
                            jnp.array(-1, wdt))
    return t.replace(**reps), True


def narrow_state(t):
    """Re-encode a wide Hosts tree (or row) to the narrow at-rest
    layout — the inverse of :func:`widen_state` (exact for every value
    within its NARROW_SPEC bound; free-slot -1 sentinels round-trip
    verbatim). Identity when the tree is already narrow."""
    probe = getattr(t, _PROBE_FIELD)
    if str(probe.dtype) != NARROW_ABS[_PROBE_FIELD][0]:
        return t
    reps = {}
    for f, (_wdt, ndt, anchor) in NARROW_REL.items():
        s = getattr(t, f)
        anc = getattr(t, anchor)  # still wide in t
        reps[f] = jnp.where(s >= 0, s - anc[..., None],
                            jnp.array(-1, s.dtype)).astype(ndt)
    for f, (_wdt, ndt) in NARROW_ABS.items():
        reps[f] = getattr(t, f).astype(ndt)
    return t.replace(**reps)


def shape_census(cfg: "EngineConfig") -> dict:
    """{field: (shape, dtype_name)} of every Hosts column at this
    config, via ``jax.eval_shape`` over the real :func:`alloc_hosts` —
    zero allocation, exact by construction. This is the ground truth
    the memory observatory's stdlib dims table
    (obs.memscope.HOSTS_DIMS — the jax-free byte census behind
    tools/state_matrix's bytes column and the capacity planner) is
    pinned against in tests/test_memscope.py: an alloc_hosts edit that
    forgets the table fails that pin by field name."""
    import jax

    sd = jax.eval_shape(lambda: alloc_hosts(cfg))
    return {f: (tuple(int(d) for d in getattr(sd, f).shape),
                str(getattr(sd, f).dtype))
            for f in sd.__dataclass_fields__}


def row_proto(cfg: "EngineConfig") -> "Hosts":
    """One host ROW of alloc_hosts defaults (no leading H axis) — the
    prototype the drain rebuilds its vmapped rows around: hot columns
    are replaced by the gathered data; cold columns ride as these
    defaults and are dropped on return (XLA dead-code-eliminates
    them), which is exact because a config-gated cold column's live
    value IS its default under the gating config (COLD_WHEN), and a
    static COLD_FIELDS column is never read by any handler (STF303)."""
    import dataclasses as _dc

    import jax

    h1 = alloc_hosts(_dc.replace(cfg, num_hosts=1))
    return jax.tree.map(lambda a: jnp.squeeze(a, 0), h1)


@chex.dataclass
class Hosts:
    """All mutable per-host state. Every leaf has leading dim H."""
    # --- event queue (the per-host scheduler) ---
    eq_time: jnp.ndarray   # [H, Q] i64, SIMTIME_MAX = free slot
    eq_seq: jnp.ndarray    # [H, Q] i32 tie-break (reference event_compare order)
    eq_kind: jnp.ndarray   # [H, Q] i32
    eq_pkt: jnp.ndarray    # [H, Q, PKT_WORDS] i32 payload
    eq_ctr: jnp.ndarray    # [H] i32 next sequence number
    eq_next: jnp.ndarray   # [H] i64 CACHED min(eq_time, axis=1) —
    #   maintained by every queue mutation (equeue.q_push/q_clear_slot,
    #   window.merge_arrivals) so the window loop's ready mask and
    #   min-next-event reductions read [H] instead of scanning the full
    #   [H, Q] table every lockstep pass (at 10k hosts x 192 slots that
    #   scan alone was ~15 MB of HBM traffic per pass, twice per pass)
    # --- per-host RNG use counter (key = fold_in(host_key, rng_ctr)) ---
    rng_ctr: jnp.ndarray   # [H] i32
    # --- CPU model (reference shd-cpu.c): busy horizon per host ---
    cpu_avail: jnp.ndarray  # [H] i64 time the CPU becomes available
    # --- NIC (reference shd-network-interface.c bandwidth accounting) ---
    nic_busy: jnp.ndarray      # [H] i64: tx free at this time
    nic_sched: jnp.ndarray     # [H] bool: an EV_NIC_TX event is in flight
    nic_rr: jnp.ndarray        # [H] i32: round-robin pointer over sockets
    nic_rx_until: jnp.ndarray  # [H] i64: rx engine busy horizon
    # NIC transmit ring: fully-formed packets awaiting bandwidth (the
    # analogue of socket output buffers + qdisc FIFO). UDP datagrams are
    # enqueued here at sendto time; TCP regenerates segments on pull.
    txq_pkt: jnp.ndarray       # [H, T, PKT_WORDS] i32
    txq_head: jnp.ndarray      # [H] i32 ring head
    txq_cnt: jnp.ndarray       # [H] i32 entries queued
    pkt_ctr: jnp.ndarray       # [H] i32: packets originated (drop RNG uid)
    next_eport: jnp.ndarray    # [H] i32: ephemeral port allocator cursor
    # --- socket table [H, S] ---
    sk_used: jnp.ndarray     # bool
    sk_proto: jnp.ndarray    # i32: 0 free, 6 tcp, 17 udp
    sk_state: jnp.ndarray    # i32 TCP state (net.tcp)
    sk_lport: jnp.ndarray    # i32 local port
    sk_rport: jnp.ndarray    # i32 remote port (0 = unconnected)
    sk_rhost: jnp.ndarray    # i32 remote host id (-1 = unconnected)
    sk_parent: jnp.ndarray   # i32 listener slot for accepted children (-1)
    sk_snd_una: jnp.ndarray  # i64 oldest unacked stream offset
    sk_snd_nxt: jnp.ndarray  # i64 next offset to transmit
    sk_snd_max: jnp.ndarray  # i64 highest offset ever transmitted
    sk_snd_end: jnp.ndarray  # i64 total bytes app has written
    sk_rcv_nxt: jnp.ndarray  # i64 next in-order offset expected
    # SACK scoreboard (the reference's shd-tcp-scoreboard.c as fixed
    # range sets, net.sack): K disjoint [start, end) ranges per socket
    sk_ooo_s: jnp.ndarray    # [H, S, K] i64 receiver out-of-order runs
    sk_ooo_e: jnp.ndarray    # [H, S, K] i64 (-1 = empty slot)
    sk_sack_s: jnp.ndarray   # [H, S, K] i64 sender: peer-sacked runs
    sk_sack_e: jnp.ndarray   # [H, S, K] i64 (accumulated from acks)
    sk_hole_end: jnp.ndarray  # i64 sender: recovery point — fast
    #   retransmission covers un-sacked bytes in [rex_nxt, hole_end)
    sk_rex_nxt: jnp.ndarray   # i64 sender: recovery cursor (skips
    #   sacked runs via the scoreboard)
    sk_peer_fin: jnp.ndarray  # i64 peer's FIN stream offset (-1 = none seen)
    sk_fin_acked: jnp.ndarray  # bool our FIN was acked
    sk_close_after: jnp.ndarray  # bool app closed: FIN after snd_end drains
    sk_cwnd: jnp.ndarray     # f32 congestion window (bytes)
    sk_ssthresh: jnp.ndarray  # f32
    sk_srtt: jnp.ndarray     # i64 (-1 until first sample; RFC6298)
    sk_rtt_min: jnp.ndarray  # i64 minimum RTT sample seen (-1 none) —
    #   the reference cubic's delayMin (shd-tcp-cubic.c:121-126),
    #   which bounds the growth-rate cap in net.congestion.on_ack
    sk_rttvar: jnp.ndarray   # i64
    sk_rto: jnp.ndarray      # i64 current retransmission timeout
    sk_rto_deadline: jnp.ndarray  # i64 desired timer expiration (0 = off)
    sk_timer_on: jnp.ndarray   # bool an EV_TCP_TIMER event is outstanding
    sk_timer_gen: jnp.ndarray  # i32 timer generation (stale-event filter)
    sk_dupacks: jnp.ndarray  # i32 duplicate-ack counter
    sk_rtt_seq: jnp.ndarray  # i64 offset being RTT-timed (-1 none; Karn)
    sk_rtt_time: jnp.ndarray  # i64 send time of the timed offset
    sk_ctl: jnp.ndarray      # i32 pending control bitmask (net.tcp CTL_*)
    sk_peer_rwnd: jnp.ndarray  # i64 peer advertised window
    sk_sndbuf: jnp.ndarray   # i64
    sk_rcvbuf: jnp.ndarray   # i64
    sk_hs_time: jnp.ndarray  # i64 handshake start (connect timeout/rtt)
    sk_last_tx: jnp.ndarray  # i64 last NIC service time (fifo qdisc key)
    sk_syn_tag: jnp.ndarray  # i32 connection-metadata tag carried on SYN
    sk_proc: jnp.ndarray     # i32 owning process slot (socket wakes
    #   route to this process's app — the analogue of the reference's
    #   descriptor-to-process ownership)
    sk_app_ref: jnp.ndarray  # i32 app-owner reference for client sockets
    #   (tgen: the behavior node whose transfer rides this socket; -1
    #   for server children and non-app sockets)
    # cubic congestion-control per-socket vars (net.congestion)
    sk_cc_wmax: jnp.ndarray   # f32 window before last loss
    sk_cc_epoch: jnp.ndarray  # i64 start of current cubic epoch (-1)
    sk_cc_k: jnp.ndarray      # f32 cubic K (seconds to plateau)
    # --- app layer (vectorized behavior machines; one row per
    # process slot) ---
    app_node: jnp.ndarray  # [H, PP] i32 behavior-graph node / phase
    app_r: jnp.ndarray     # [H, PP, 8] i64 app registers
    app_proc: jnp.ndarray  # [H] i32 process context during an EV_APP
    #   dispatch: pushes made by the running app (timers, socket
    #   allocations) are stamped with it so their wakes return to the
    #   same process; 0 between dispatches
    tgen_sync: jnp.ndarray  # [H, SY] i32 synchronize-barrier arrival counts
    # --- outbox: packets emitted this window awaiting exchange.
    # Packets the destination could not take this window (per-window
    # intake or queue headroom spent) STAY here and re-exchange next
    # window with unchanged send times — exact deferral, see
    # window.exchange ---
    ob_pkt: jnp.ndarray    # [H, O, PKT_WORDS] i32
    ob_time: jnp.ndarray   # [H, O] i64 send (wire-entry) time
    ob_cnt: jnp.ndarray    # [H] i32
    ob_next: jnp.ndarray   # [H] i64 earliest ARRIVAL time among carried
    #   packets (SIMTIME_MAX when none) — folded into the window-advance
    #   minimum so a deferred delivery reopens the window
    # --- hosted-app wake ring (hosting.bridge; drained per window) ---
    hw_time: jnp.ndarray   # [H, HW] i64 wake event times
    hw_pkt: jnp.ndarray    # [H, HW, PKT_WORDS] i32 wake payloads
    hw_cnt: jnp.ndarray    # [H] i32
    hw_drop: jnp.ndarray   # [H] i32 wakes lost to ring overflow
    # --- packet-trace ring (obs.pcap; drained per chunk) ---
    tr_time: jnp.ndarray   # [H, TC] i64
    tr_pkt: jnp.ndarray    # [H, TC, PKT_WORDS] i32
    tr_dir: jnp.ndarray    # [H, TC] i32: 0 rx, 1 tx
    tr_cnt: jnp.ndarray    # [H] i32
    tr_drop: jnp.ndarray   # [H] i32 records lost to ring overflow
    # --- observability ---
    stats: jnp.ndarray     # [H, N_STATS] i64
    ns_hist: jnp.ndarray   # [H, NSK, NSB] i64 network-observatory
    #   latency histograms (obs.netscope): per kind (RTT, completion,
    #   queue delay, retransmit interval), power-of-two µs buckets.
    #   NSB is NS_BUCKETS with cfg.netscope on, else ZERO — disabled
    #   runs keep their pre-netscope shapes and digests bit-for-bit.
    cap_peaks: jnp.ndarray  # [H, 4] i32 peak occupancy of the fixed
    #   capacity arrays (0=event queue, 1=socket table, 2=outbox,
    #   3=NIC tx ring) — the TPU analogue of the reference's
    #   ObjectCounter end-of-run report (shd-object-counter.c; there
    #   leaks are the hazard, here capacity headroom is)


@chex.dataclass
class HostParams:
    """Read-only per-host configuration, leading dim H."""
    hid: jnp.ndarray        # [H] i32 own host id (global, shard-invariant)
    rng_stream: jnp.ndarray  # [H] u32 per-host PRNG stream (core.rng)
    vertex: jnp.ndarray     # [H] i32 topology attachment
    bw_up: jnp.ndarray      # [H] i64 bytes/sec uplink
    bw_down: jnp.ndarray    # [H] i64 bytes/sec downlink
    app_kind: jnp.ndarray   # [H, PP] i32 app per process slot (apps
    #   registry; APP_NULL = empty slot)
    app_cfg: jnp.ndarray    # [H, PP, 8] i64 app static params
    nic_buf: jnp.ndarray    # [H] i64 NIC input buffer bytes
    cpu_cost: jnp.ndarray   # [H] i64 modeled CPU ns per executed event
    #   (= base event cost x frequencyRatio, precision-rounded at
    #   build; the modeled-app stand-in for shd-cpu.c's measured
    #   wallclock x ratio). 0 = free.
    cpu_threshold: jnp.ndarray  # [H] i64 blocked-CPU threshold (-1 off)
    rcvbuf0: jnp.ndarray    # [H] i64 explicit socket recv buffer, or -1
    #   = autotune from the delay-bandwidth product at establishment
    #   (reference <host socketrecvbuffer>, shd-tcp.c:340-433)
    sndbuf0: jnp.ndarray    # [H] i64 explicit send buffer, or -1
    pcap_on: jnp.ndarray    # [H] bool: record this host's packets
    #   (reference <host logpcap=...>, shd-network-interface.c:186-223)


@chex.dataclass
class Shared:
    """Replicated loop-invariant tables and scalars. The live window
    bounds [wstart, wend) are loop-carried scalars in engine.window, not
    stored here."""
    lat_ns: jnp.ndarray    # [V, V] i64 path latency
    rel: jnp.ndarray       # [V, V] f32 path reliability
    host_vertex: jnp.ndarray  # [H] i32 host -> topology vertex (replicated
    #   copy of HostParams.vertex: routing needs the vertex of REMOTE
    #   destination hosts, which a host-sharded table cannot provide)
    host_bw_up: jnp.ndarray    # [H] i64 replicated peer-bandwidth tables
    host_bw_down: jnp.ndarray  # [H] i64 (TCP buffer autotuning needs the
    #   REMOTE end's bandwidths, shd-tcp.c:386-404)
    rng_root: jnp.ndarray  # PRNG key (host-side / setup uses)
    seed32: jnp.ndarray    # u32 scalar: root of the cheap counter PRNG
    stop_time: jnp.ndarray  # i64 scalar
    min_jump: jnp.ndarray   # i64 scalar: lookahead window width
    # TCP tuning scalars (reference --tcp-congestion-control /
    # --tcp-windows / --tcp-ssthresh options, shd-options.c:132-133)
    cc_kind: jnp.ndarray       # i32: 0=aimd 1=reno 2=cubic
    tcp_init_wnd: jnp.ndarray  # f32 initial cwnd, packets (default 10)
    tcp_ssthresh0: jnp.ndarray  # f32 initial ssthresh (0 = discover)
    # tgen behavior-graph tables (apps.tgen; 1-row dummies when unused)
    tgen_nodes: jnp.ndarray    # [N, 10] i64 node table
    tgen_peers: jnp.ndarray    # [M, 2] i32 (host, port) pool
    tgen_pool: jnp.ndarray     # [K] i64 pause-choice pool (ns)
    tgen_edges: jnp.ndarray    # [E] i32 successor-node pool (multi-edge
    #   parallel walks: each node points at edges[eoff:eoff+ecnt])


def alloc_hosts(cfg: EngineConfig) -> Hosts:
    H, Q, S, O = cfg.num_hosts, cfg.qcap, cfg.scap, cfg.obcap
    T = cfg.txqcap

    def full(shape, val, dt):
        return jnp.full(shape, val, dtype=dt)

    # at-rest dtype per column: NARROW_SPEC's narrow dtype when the
    # shrink layout is on (cfg.wide_state == 0), else the wide dtype
    # named by the field's annotation comment. The stateflow model
    # intentionally keeps the WIDE dtype for these fields (handlers
    # only ever see widened rows — engine.window.step_one_host).
    _nd = narrow_dtypes(cfg)

    def ndt(name, wide):
        return _nd.get(name, wide)

    return Hosts(
        eq_time=full((H, Q), SIMTIME_MAX, jnp.int64),
        eq_seq=full((H, Q), 0, jnp.int32),
        eq_kind=full((H, Q), 0, jnp.int32),
        eq_pkt=full((H, Q, PKT_WORDS), 0, jnp.int32),
        eq_ctr=full((H,), 0, jnp.int32),
        eq_next=full((H,), SIMTIME_MAX, jnp.int64),
        rng_ctr=full((H,), 0, jnp.int32),
        cpu_avail=full((H,), 0, jnp.int64),
        nic_busy=full((H,), 0, jnp.int64),
        nic_sched=full((H,), False, jnp.bool_),
        nic_rr=full((H,), 0, jnp.int32),
        nic_rx_until=full((H,), 0, jnp.int64),
        txq_pkt=full((H, T, PKT_WORDS), 0, jnp.int32),
        txq_head=full((H,), 0, jnp.int32),
        txq_cnt=full((H,), 0, jnp.int32),
        pkt_ctr=full((H,), 0, jnp.int32),
        next_eport=full((H,), C.MIN_RANDOM_PORT, jnp.int32),
        sk_used=full((H, S), False, jnp.bool_),
        sk_proto=full((H, S), 0, ndt("sk_proto", jnp.int32)),
        sk_state=full((H, S), 0, ndt("sk_state", jnp.int32)),
        sk_lport=full((H, S), 0, ndt("sk_lport", jnp.int32)),
        sk_rport=full((H, S), 0, ndt("sk_rport", jnp.int32)),
        sk_rhost=full((H, S), -1, jnp.int32),
        sk_parent=full((H, S), -1, jnp.int32),
        sk_snd_una=full((H, S), 0, ndt("sk_snd_una", jnp.int64)),
        sk_snd_nxt=full((H, S), 0, ndt("sk_snd_nxt", jnp.int64)),
        sk_snd_max=full((H, S), 0, ndt("sk_snd_max", jnp.int64)),
        sk_snd_end=full((H, S), 0, ndt("sk_snd_end", jnp.int64)),
        sk_rcv_nxt=full((H, S), 0, ndt("sk_rcv_nxt", jnp.int64)),
        sk_ooo_s=full((H, S, SACK_K), -1, ndt("sk_ooo_s", jnp.int64)),
        sk_ooo_e=full((H, S, SACK_K), -1, ndt("sk_ooo_e", jnp.int64)),
        sk_sack_s=full((H, S, SACK_K), -1, ndt("sk_sack_s", jnp.int64)),
        sk_sack_e=full((H, S, SACK_K), -1, ndt("sk_sack_e", jnp.int64)),
        sk_hole_end=full((H, S), 0, ndt("sk_hole_end", jnp.int64)),
        sk_rex_nxt=full((H, S), 0, ndt("sk_rex_nxt", jnp.int64)),
        sk_peer_fin=full((H, S), -1, ndt("sk_peer_fin", jnp.int64)),
        sk_fin_acked=full((H, S), False, jnp.bool_),
        sk_close_after=full((H, S), False, jnp.bool_),
        sk_cwnd=full((H, S), 0.0, jnp.float32),
        sk_ssthresh=full((H, S), 0.0, jnp.float32),
        sk_srtt=full((H, S), -1, jnp.int64),
        sk_rtt_min=full((H, S), -1, jnp.int64),
        sk_rttvar=full((H, S), 0, jnp.int64),
        sk_rto=full((H, S), C.TCP_RTO_INIT, jnp.int64),
        sk_rto_deadline=full((H, S), 0, jnp.int64),
        sk_timer_on=full((H, S), False, jnp.bool_),
        sk_timer_gen=full((H, S), 0, jnp.int32),
        sk_dupacks=full((H, S), 0, jnp.int32),
        sk_rtt_seq=full((H, S), -1, ndt("sk_rtt_seq", jnp.int64)),
        sk_rtt_time=full((H, S), 0, jnp.int64),
        sk_ctl=full((H, S), 0, ndt("sk_ctl", jnp.int32)),
        sk_peer_rwnd=full((H, S), C.RECV_BUFFER_SIZE, ndt("sk_peer_rwnd", jnp.int64)),
        sk_sndbuf=full((H, S), C.SEND_BUFFER_SIZE, ndt("sk_sndbuf", jnp.int64)),
        sk_rcvbuf=full((H, S), C.RECV_BUFFER_SIZE, ndt("sk_rcvbuf", jnp.int64)),
        sk_hs_time=full((H, S), 0, jnp.int64),
        sk_last_tx=full((H, S), 0, jnp.int64),
        sk_syn_tag=full((H, S), 0, jnp.int32),
        sk_proc=full((H, S), 0, jnp.int32),
        sk_app_ref=full((H, S), -1, jnp.int32),
        sk_cc_wmax=full((H, S), 0.0, jnp.float32),
        sk_cc_epoch=full((H, S), -1, jnp.int64),
        sk_cc_k=full((H, S), 0.0, jnp.float32),
        app_node=full((H, max(cfg.procs_per_host, 1)), 0, jnp.int32),
        app_r=full((H, max(cfg.procs_per_host, 1), 8), 0, jnp.int64),
        app_proc=full((H,), 0, jnp.int32),
        tgen_sync=full((H, max(cfg.synccap, 1)), 0, jnp.int32),
        ob_pkt=full((H, O, PKT_WORDS), 0, jnp.int32),
        ob_time=full((H, O), 0, jnp.int64),
        ob_cnt=full((H,), 0, jnp.int32),
        ob_next=full((H,), SIMTIME_MAX, jnp.int64),
        hw_time=full((H, max(cfg.hostedcap, 1)), 0, jnp.int64),
        hw_pkt=full((H, max(cfg.hostedcap, 1), PKT_WORDS), 0, jnp.int32),
        hw_cnt=full((H,), 0, jnp.int32),
        hw_drop=full((H,), 0, jnp.int32),
        tr_time=full((H, max(cfg.tracecap, 1)), 0, jnp.int64),
        tr_pkt=full((H, max(cfg.tracecap, 1), PKT_WORDS), 0, jnp.int32),
        tr_dir=full((H, max(cfg.tracecap, 1)), 0, jnp.int32),
        tr_cnt=full((H,), 0, jnp.int32),
        tr_drop=full((H,), 0, jnp.int32),
        stats=full((H, N_STATS), 0, jnp.int64),
        ns_hist=full((H, NS_KINDS,
                      NS_BUCKETS if cfg.netscope else 0), 0,
                     jnp.int64),
        cap_peaks=full((H, 4), 0, jnp.int32),
    )


# Partition integrity: the declared hot/cold split covers every Hosts
# column exactly once, and every config-gated cold column is a member
# of the static hot set (it only LEAVES it under its guard). simlint
# STF300/STF304 re-check both statically on every lint run.
assert set(HOT_FIELDS).isdisjoint(COLD_FIELDS), \
    sorted(set(HOT_FIELDS) & COLD_FIELDS)
assert set(HOT_FIELDS) | COLD_FIELDS == set(Hosts.__dataclass_fields__), \
    sorted(set(Hosts.__dataclass_fields__)
           ^ (set(HOT_FIELDS) | COLD_FIELDS))
assert all(f in HOT_FIELDS for _, flds in COLD_WHEN for f in flds), \
    [f for _, flds in COLD_WHEN for f in flds if f not in HOT_FIELDS]


def make_shared(topo_lat_ns: np.ndarray, topo_rel: np.ndarray, rng_root,
                stop_time: int, min_jump: int, seed: int = 1,
                cc_kind: int = 2,
                tcp_init_wnd: float = 10.0,
                tcp_ssthresh0: float = 0.0,
                tgen_nodes: np.ndarray = None,
                tgen_peers: np.ndarray = None,
                tgen_pool: np.ndarray = None,
                tgen_edges: np.ndarray = None,
                host_vertex: np.ndarray = None,
                host_bw_up: np.ndarray = None,
                host_bw_down: np.ndarray = None) -> Shared:
    if host_vertex is None:
        host_vertex = np.zeros((1,), np.int32)
    if host_bw_up is None:
        host_bw_up = np.ones((1,), np.int64)
    if host_bw_down is None:
        host_bw_down = np.ones((1,), np.int64)
    if tgen_nodes is None:
        tgen_nodes = np.zeros((1, 10), np.int64)
    if tgen_peers is None:
        tgen_peers = np.zeros((1, 2), np.int32)
    if tgen_pool is None:
        tgen_pool = np.zeros((1,), np.int64)
    if tgen_edges is None:
        tgen_edges = np.full((1,), -1, np.int32)
    return Shared(
        lat_ns=jnp.asarray(topo_lat_ns, dtype=jnp.int64),
        rel=jnp.asarray(topo_rel, dtype=jnp.float32),
        host_vertex=jnp.asarray(host_vertex, dtype=jnp.int32),
        host_bw_up=jnp.asarray(host_bw_up, dtype=jnp.int64),
        host_bw_down=jnp.asarray(host_bw_down, dtype=jnp.int64),
        rng_root=rng_root,
        seed32=jnp.uint32(seed & 0xFFFFFFFF),
        stop_time=jnp.int64(stop_time),
        min_jump=jnp.int64(min_jump),
        cc_kind=jnp.int32(cc_kind),
        tcp_init_wnd=jnp.float32(tcp_init_wnd),
        tcp_ssthresh0=jnp.float32(tcp_ssthresh0),
        tgen_nodes=jnp.asarray(tgen_nodes, dtype=jnp.int64),
        tgen_peers=jnp.asarray(tgen_peers, dtype=jnp.int32),
        tgen_pool=jnp.asarray(tgen_pool, dtype=jnp.int64),
        tgen_edges=jnp.asarray(tgen_edges, dtype=jnp.int32),
    )
