"""Simulation state: struct-of-arrays pytrees.

The reference keeps per-host state in heap objects (Host,
NetworkInterface, Socket/TCP, descriptor tables —
/root/reference/src/main/host/shd-host.c:64-130) and events as allocated
closures in per-host priority queues. On TPU the whole simulation is
three pytrees:

- :class:`Hosts` — every mutable per-host array, leading dim H. This is
  what the engine transforms (and donates between jit steps). Under
  ``vmap`` a "row" of it is one simulated host.
- :class:`HostParams` — read-only per-host configuration (topology
  vertex, bandwidths, app wiring).
- :class:`Shared` — replicated tables and scalars: the vertex-by-vertex
  latency/reliability oracle, RNG root, stop time, lookahead window.

Sizing knobs live in :class:`EngineConfig`; they are Python static so
XLA sees fixed shapes.
"""

from __future__ import annotations

from dataclasses import dataclass

import chex
import jax.numpy as jnp
import numpy as np

from ..core.simtime import SIMTIME_MAX
from ..core import constants as C
from ..net.packet import PKT_WORDS
from .defs import N_STATS


@dataclass(frozen=True)
class EngineConfig:
    """Static engine shape/size configuration."""
    num_hosts: int
    qcap: int = 32          # event-queue slots per host
    scap: int = 16          # socket table rows per host
    obcap: int = 32         # outbox (per-window emit budget) per host
    incap: int = 32         # per-window inbound packet budget per host
    txqcap: int = 16        # NIC transmit-ring slots per host
    chunk_windows: int = 16  # windows executed per jit invocation
    cc_kind: int = 2        # 0=aimd 1=reno 2=cubic (reference default cubic)


@chex.dataclass
class Hosts:
    """All mutable per-host state. Every leaf has leading dim H."""
    # --- event queue (the per-host scheduler) ---
    eq_time: jnp.ndarray   # [H, Q] i64, SIMTIME_MAX = free slot
    eq_seq: jnp.ndarray    # [H, Q] i32 tie-break (reference event_compare order)
    eq_kind: jnp.ndarray   # [H, Q] i32
    eq_pkt: jnp.ndarray    # [H, Q, PKT_WORDS] i32 payload
    eq_ctr: jnp.ndarray    # [H] i32 next sequence number
    # --- per-host RNG use counter (key = fold_in(host_key, rng_ctr)) ---
    rng_ctr: jnp.ndarray   # [H] i32
    # --- NIC (reference shd-network-interface.c bandwidth accounting) ---
    nic_busy: jnp.ndarray      # [H] i64: tx free at this time
    nic_sched: jnp.ndarray     # [H] bool: an EV_NIC_TX event is in flight
    nic_rr: jnp.ndarray        # [H] i32: round-robin pointer over sockets
    nic_rx_until: jnp.ndarray  # [H] i64: rx engine busy horizon
    # NIC transmit ring: fully-formed packets awaiting bandwidth (the
    # analogue of socket output buffers + qdisc FIFO). UDP datagrams are
    # enqueued here at sendto time; TCP regenerates segments on pull.
    txq_pkt: jnp.ndarray       # [H, T, PKT_WORDS] i32
    txq_head: jnp.ndarray      # [H] i32 ring head
    txq_cnt: jnp.ndarray       # [H] i32 entries queued
    pkt_ctr: jnp.ndarray       # [H] i32: packets originated (drop RNG uid)
    next_eport: jnp.ndarray    # [H] i32: ephemeral port allocator cursor
    # --- socket table [H, S] ---
    sk_used: jnp.ndarray     # bool
    sk_proto: jnp.ndarray    # i32: 0 free, 6 tcp, 17 udp
    sk_state: jnp.ndarray    # i32 TCP state (net.tcp)
    sk_lport: jnp.ndarray    # i32 local port
    sk_rport: jnp.ndarray    # i32 remote port (0 = unconnected)
    sk_rhost: jnp.ndarray    # i32 remote host id (-1 = unconnected)
    sk_parent: jnp.ndarray   # i32 listener slot for accepted children (-1)
    sk_snd_una: jnp.ndarray  # i64 oldest unacked stream offset
    sk_snd_nxt: jnp.ndarray  # i64 next offset to transmit
    sk_snd_max: jnp.ndarray  # i64 highest offset ever transmitted
    sk_snd_end: jnp.ndarray  # i64 total bytes app has written
    sk_rcv_nxt: jnp.ndarray  # i64 next in-order offset expected
    sk_peer_fin: jnp.ndarray  # i64 peer's FIN stream offset (-1 = none seen)
    sk_fin_acked: jnp.ndarray  # bool our FIN was acked
    sk_close_after: jnp.ndarray  # bool app closed: FIN after snd_end drains
    sk_cwnd: jnp.ndarray     # f32 congestion window (bytes)
    sk_ssthresh: jnp.ndarray  # f32
    sk_srtt: jnp.ndarray     # i64 (-1 until first sample; RFC6298)
    sk_rttvar: jnp.ndarray   # i64
    sk_rto: jnp.ndarray      # i64 current retransmission timeout
    sk_rto_deadline: jnp.ndarray  # i64 desired timer expiration (0 = off)
    sk_timer_on: jnp.ndarray   # bool an EV_TCP_TIMER event is outstanding
    sk_timer_gen: jnp.ndarray  # i32 timer generation (stale-event filter)
    sk_dupacks: jnp.ndarray  # i32 duplicate-ack counter
    sk_rtt_seq: jnp.ndarray  # i64 offset being RTT-timed (-1 none; Karn)
    sk_rtt_time: jnp.ndarray  # i64 send time of the timed offset
    sk_ctl: jnp.ndarray      # i32 pending control bitmask (net.tcp CTL_*)
    sk_peer_rwnd: jnp.ndarray  # i64 peer advertised window
    sk_sndbuf: jnp.ndarray   # i64
    sk_rcvbuf: jnp.ndarray   # i64
    sk_hs_time: jnp.ndarray  # i64 handshake start (connect timeout/rtt)
    sk_syn_tag: jnp.ndarray  # i32 connection-metadata tag carried on SYN
    # cubic congestion-control per-socket vars (net.congestion)
    sk_cc_wmax: jnp.ndarray   # f32 window before last loss
    sk_cc_epoch: jnp.ndarray  # i64 start of current cubic epoch (-1)
    sk_cc_k: jnp.ndarray      # f32 cubic K (seconds to plateau)
    # --- app layer (vectorized behavior machines) ---
    app_node: jnp.ndarray  # [H] i32 current behavior-graph node / phase
    app_r: jnp.ndarray     # [H, 8] i64 app registers
    # --- outbox: packets emitted this window awaiting exchange ---
    ob_pkt: jnp.ndarray    # [H, O, PKT_WORDS] i32
    ob_time: jnp.ndarray   # [H, O] i64 send (wire-entry) time
    ob_cnt: jnp.ndarray    # [H] i32
    # --- observability ---
    stats: jnp.ndarray     # [H, N_STATS] i64


@chex.dataclass
class HostParams:
    """Read-only per-host configuration, leading dim H."""
    hid: jnp.ndarray        # [H] i32 own host id (global, shard-invariant)
    vertex: jnp.ndarray     # [H] i32 topology attachment
    bw_up: jnp.ndarray      # [H] i64 bytes/sec uplink
    bw_down: jnp.ndarray    # [H] i64 bytes/sec downlink
    app_kind: jnp.ndarray   # [H] i32 which app runs here (apps registry)
    app_cfg: jnp.ndarray    # [H, 8] i64 app static params
    nic_buf: jnp.ndarray    # [H] i64 NIC input buffer bytes


@chex.dataclass
class Shared:
    """Replicated loop-invariant tables and scalars. The live window
    bounds [wstart, wend) are loop-carried scalars in engine.window, not
    stored here."""
    lat_ns: jnp.ndarray    # [V, V] i64 path latency
    rel: jnp.ndarray       # [V, V] f32 path reliability
    rng_root: jnp.ndarray  # PRNG key
    stop_time: jnp.ndarray  # i64 scalar
    min_jump: jnp.ndarray   # i64 scalar: lookahead window width
    # TCP tuning scalars (reference --tcp-congestion-control /
    # --tcp-windows / --tcp-ssthresh options, shd-options.c:132-133)
    cc_kind: jnp.ndarray       # i32: 0=aimd 1=reno 2=cubic
    tcp_init_wnd: jnp.ndarray  # f32 initial cwnd, packets (default 10)
    tcp_ssthresh0: jnp.ndarray  # f32 initial ssthresh (0 = discover)


def alloc_hosts(cfg: EngineConfig) -> Hosts:
    H, Q, S, O = cfg.num_hosts, cfg.qcap, cfg.scap, cfg.obcap
    T = cfg.txqcap

    def full(shape, val, dt):
        return jnp.full(shape, val, dtype=dt)

    return Hosts(
        eq_time=full((H, Q), SIMTIME_MAX, jnp.int64),
        eq_seq=full((H, Q), 0, jnp.int32),
        eq_kind=full((H, Q), 0, jnp.int32),
        eq_pkt=full((H, Q, PKT_WORDS), 0, jnp.int32),
        eq_ctr=full((H,), 0, jnp.int32),
        rng_ctr=full((H,), 0, jnp.int32),
        nic_busy=full((H,), 0, jnp.int64),
        nic_sched=full((H,), False, jnp.bool_),
        nic_rr=full((H,), 0, jnp.int32),
        nic_rx_until=full((H,), 0, jnp.int64),
        txq_pkt=full((H, T, PKT_WORDS), 0, jnp.int32),
        txq_head=full((H,), 0, jnp.int32),
        txq_cnt=full((H,), 0, jnp.int32),
        pkt_ctr=full((H,), 0, jnp.int32),
        next_eport=full((H,), C.MIN_RANDOM_PORT, jnp.int32),
        sk_used=full((H, S), False, jnp.bool_),
        sk_proto=full((H, S), 0, jnp.int32),
        sk_state=full((H, S), 0, jnp.int32),
        sk_lport=full((H, S), 0, jnp.int32),
        sk_rport=full((H, S), 0, jnp.int32),
        sk_rhost=full((H, S), -1, jnp.int32),
        sk_parent=full((H, S), -1, jnp.int32),
        sk_snd_una=full((H, S), 0, jnp.int64),
        sk_snd_nxt=full((H, S), 0, jnp.int64),
        sk_snd_max=full((H, S), 0, jnp.int64),
        sk_snd_end=full((H, S), 0, jnp.int64),
        sk_rcv_nxt=full((H, S), 0, jnp.int64),
        sk_peer_fin=full((H, S), -1, jnp.int64),
        sk_fin_acked=full((H, S), False, jnp.bool_),
        sk_close_after=full((H, S), False, jnp.bool_),
        sk_cwnd=full((H, S), 0.0, jnp.float32),
        sk_ssthresh=full((H, S), 0.0, jnp.float32),
        sk_srtt=full((H, S), -1, jnp.int64),
        sk_rttvar=full((H, S), 0, jnp.int64),
        sk_rto=full((H, S), C.TCP_RTO_INIT, jnp.int64),
        sk_rto_deadline=full((H, S), 0, jnp.int64),
        sk_timer_on=full((H, S), False, jnp.bool_),
        sk_timer_gen=full((H, S), 0, jnp.int32),
        sk_dupacks=full((H, S), 0, jnp.int32),
        sk_rtt_seq=full((H, S), -1, jnp.int64),
        sk_rtt_time=full((H, S), 0, jnp.int64),
        sk_ctl=full((H, S), 0, jnp.int32),
        sk_peer_rwnd=full((H, S), C.RECV_BUFFER_SIZE, jnp.int64),
        sk_sndbuf=full((H, S), C.SEND_BUFFER_SIZE, jnp.int64),
        sk_rcvbuf=full((H, S), C.RECV_BUFFER_SIZE, jnp.int64),
        sk_hs_time=full((H, S), 0, jnp.int64),
        sk_syn_tag=full((H, S), 0, jnp.int32),
        sk_cc_wmax=full((H, S), 0.0, jnp.float32),
        sk_cc_epoch=full((H, S), -1, jnp.int64),
        sk_cc_k=full((H, S), 0.0, jnp.float32),
        app_node=full((H,), 0, jnp.int32),
        app_r=full((H, 8), 0, jnp.int64),
        ob_pkt=full((H, O, PKT_WORDS), 0, jnp.int32),
        ob_time=full((H, O), 0, jnp.int64),
        ob_cnt=full((H,), 0, jnp.int32),
        stats=full((H, N_STATS), 0, jnp.int64),
    )


def make_shared(topo_lat_ns: np.ndarray, topo_rel: np.ndarray, rng_root,
                stop_time: int, min_jump: int, cc_kind: int = 2,
                tcp_init_wnd: float = 10.0,
                tcp_ssthresh0: float = 0.0) -> Shared:
    return Shared(
        lat_ns=jnp.asarray(topo_lat_ns, dtype=jnp.int64),
        rel=jnp.asarray(topo_rel, dtype=jnp.float32),
        rng_root=rng_root,
        stop_time=jnp.int64(stop_time),
        min_jump=jnp.int64(min_jump),
        cc_kind=jnp.int32(cc_kind),
        tcp_init_wnd=jnp.float32(tcp_init_wnd),
        tcp_ssthresh0=jnp.float32(tcp_ssthresh0),
    )
