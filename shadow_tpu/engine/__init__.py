"""engine subpackage."""
