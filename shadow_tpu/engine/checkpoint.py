"""Checkpoint/resume: snapshot the simulation state arrays.

The reference has no checkpointing (SURVEY §5 calls it out as absent);
on TPU the whole simulation is a pytree of dense arrays, so a snapshot
is one device->host copy + npz write, and resume is exact: the restored
run produces the same results as an uninterrupted one (asserted by
tests/test_checkpoint.py).
"""

from __future__ import annotations

import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np


def named_leaves(hosts) -> list:
    """[(field_name, leaf array)] in declaration order — the leaf
    enumeration the digest recorder (obs.digest) hashes. save() below
    serializes via jax.tree.flatten, whose order DIFFERS (chex does
    not flatten in declaration order) but whose leaf set is identical
    — asserted in save(), so a field the digest hashes can never be
    silently absent from checkpoints or vice versa. Each consumer is
    internally order-consistent; nothing exchanges ordered leaves."""
    import dataclasses
    return [(f.name, getattr(hosts, f.name))
            for f in dataclasses.fields(hosts)]


def scenario_fingerprint(scenario, cfg, seed: int) -> str:
    """Stable hash binding a checkpoint to its scenario + engine shape."""
    text = json.dumps({
        "scenario": repr(scenario),
        "cfg": repr(cfg),
        "seed": seed,
    }, sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def save(path: str, hosts, wstart, wend, windows: int, fingerprint: str):
    leaves, treedef = jax.tree.flatten(hosts)
    # checkpoints and digests must cover the same leaf SET (orders
    # legitimately differ — see named_leaves): a pytree leaf that is
    # not a dataclass field would be digested but not checkpointed,
    # or vice versa
    named = named_leaves(hosts)
    assert (len(named) == len(leaves)
            and {id(a) for _, a in named} == {id(b) for b in leaves})
    np.savez_compressed(
        path,
        __fingerprint__=np.frombuffer(
            fingerprint.encode(), dtype=np.uint8),
        __wstart__=np.int64(int(wstart)),
        __wend__=np.int64(int(wend)),
        __windows__=np.int64(windows),
        **{f"leaf{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )


def load(path: str, hosts_template, fingerprint: str,
         strict: bool = True):
    """-> (hosts, wstart, wend, windows). `hosts_template` supplies the
    pytree structure (a freshly built Hosts). `strict=False` downgrades
    a fingerprint mismatch to a stderr warning (the shape check below
    still applies) — for tooling that deliberately resumes under a
    changed stop time or chunk size, e.g. tools/divergence.py --bisect
    replaying from the nearest checkpoint at digest cadence 1."""
    z = np.load(path)
    got = bytes(z["__fingerprint__"]).decode()
    if got != fingerprint:
        if strict:
            raise ValueError(
                f"checkpoint fingerprint {got} does not match scenario "
                f"{fingerprint}: refusing to resume into a different "
                "simulation")
        import sys
        sys.stderr.write(
            f"shadow_tpu: warning: resuming past a checkpoint "
            f"fingerprint mismatch ({got} vs {fingerprint}) — caller "
            "vouches the scenario only differs in run parameters\n")
    leaves, treedef = jax.tree.flatten(hosts_template)
    n = len(leaves)
    new_leaves = [jnp.asarray(z[f"leaf{i}"]) for i in range(n)]
    for tpl, new in zip(leaves, new_leaves):
        if tpl.shape != new.shape or tpl.dtype != new.dtype:
            raise ValueError("checkpoint layout mismatch "
                             f"({new.shape}/{new.dtype} vs "
                             f"{tpl.shape}/{tpl.dtype})")
    hosts = jax.tree.unflatten(treedef, new_leaves)
    return (hosts, int(z["__wstart__"]), int(z["__wend__"]),
            int(z["__windows__"]))
