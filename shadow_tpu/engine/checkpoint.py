"""Checkpoint/resume: snapshot the simulation state arrays.

The reference has no checkpointing (SURVEY §5 calls it out as absent);
on TPU the whole simulation is a pytree of dense arrays, so a snapshot
is one device->host copy + npz write, and resume is exact: the restored
run produces the same results as an uninterrupted one (asserted by
tests/test_checkpoint.py).
"""

from __future__ import annotations

import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np


def scenario_fingerprint(scenario, cfg, seed: int) -> str:
    """Stable hash binding a checkpoint to its scenario + engine shape."""
    text = json.dumps({
        "scenario": repr(scenario),
        "cfg": repr(cfg),
        "seed": seed,
    }, sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def save(path: str, hosts, wstart, wend, windows: int, fingerprint: str):
    leaves, treedef = jax.tree.flatten(hosts)
    np.savez_compressed(
        path,
        __fingerprint__=np.frombuffer(
            fingerprint.encode(), dtype=np.uint8),
        __wstart__=np.int64(int(wstart)),
        __wend__=np.int64(int(wend)),
        __windows__=np.int64(windows),
        **{f"leaf{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )


def load(path: str, hosts_template, fingerprint: str):
    """-> (hosts, wstart, wend, windows). `hosts_template` supplies the
    pytree structure (a freshly built Hosts)."""
    z = np.load(path)
    got = bytes(z["__fingerprint__"]).decode()
    if got != fingerprint:
        raise ValueError(
            f"checkpoint fingerprint {got} does not match scenario "
            f"{fingerprint}: refusing to resume into a different "
            "simulation")
    leaves, treedef = jax.tree.flatten(hosts_template)
    n = len(leaves)
    new_leaves = [jnp.asarray(z[f"leaf{i}"]) for i in range(n)]
    for tpl, new in zip(leaves, new_leaves):
        if tpl.shape != new.shape or tpl.dtype != new.dtype:
            raise ValueError("checkpoint layout mismatch "
                             f"({new.shape}/{new.dtype} vs "
                             f"{tpl.shape}/{tpl.dtype})")
    hosts = jax.tree.unflatten(treedef, new_leaves)
    return (hosts, int(z["__wstart__"]), int(z["__wend__"]),
            int(z["__windows__"]))
